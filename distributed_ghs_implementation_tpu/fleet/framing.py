"""Length-prefixed JSON framing for the router <-> worker channels.

The single-process service speaks newline-delimited JSON (one request per
line, ``serve/service.py``); the fleet cannot: a worker's channel carries
*interleaved* responses written by concurrent request threads, and a torn
line would silently merge two frames. Each frame is therefore::

    <payload-byte-length>\\n<payload>\\n                  # legacy (v1)
    <payload-byte-length> <crc32-hex>\\n<payload>\\n      # checksummed

— the reader knows exactly how many bytes belong to the frame before it
parses a single one, a short read is detected (not mis-parsed), and the
trailing newline keeps frames greppable in a captured channel dump. The
same framing runs over OS pipes (the single-host fleet) and TCP sockets
(``fleet/transport.py``) — a frame is a frame on either medium.

**Payload checksums** (round 19): the optional second header token is the
crc32 of the payload bytes. Length-prefixing alone detects *truncation*
but not *mutation* — a bit-flipped byte inside the payload either breaks
the JSON (caught late, after buffering) or, worse, survives as valid JSON
with a different value. With the checksum, every flipped payload is
rejected at the frame boundary as a typed :class:`FrameError`. Readers
accept both forms unconditionally; writers emit checksums only toward
peers that advertised the ``crc`` capability in their hello (or whose own
frames carried checksums) — the version gate that keeps a mixed-build
fleet compatible (``fleet/transport.py``, ``docs/FLEET.md``).

Error surface: :func:`read_frame` returns ``None`` only on a *clean* EOF
at a frame boundary (the peer closed in between frames — drain, or death)
and raises :class:`FrameError` on everything garbled: a non-numeric or
over-long length prefix, a length past ``max_bytes`` (a corrupt prefix
must not become a multi-gigabyte allocation — the reader sizes its buffer
from attacker/garbage-controlled bytes), a payload the stream could not
complete, a payload failing its declared checksum, or bytes that are not
one JSON object. ``FrameError`` subclasses ``ValueError``, so callers
that treated every framing problem as peer-death (the router's reader
catches ``(OSError, ValueError)``) keep doing so unchanged — the typed
error exists for callers that want to *distinguish* a corrupt peer from a
closed one (tests, the drills, the dial-in hello validation). Writes must
be serialized by the caller (the transports hold a per-connection write
lock).
"""

from __future__ import annotations

import json
import zlib
from typing import IO, Optional

#: A frame larger than this is a protocol violation (a runaway edges_out
#: response, or garbage on the channel) — refuse to buffer it. Callers with
#: tighter expectations (the hello exchange is a few hundred bytes) pass
#: their own ``max_bytes``.
MAX_FRAME_BYTES = 256 * 1024 * 1024

#: The longest legal header is 9 length digits + space + 8 crc hex digits
#: + newline (19 bytes); anything longer is garbage, and an unbounded
#: ``readline`` on a corrupt stream would buffer until memory runs out.
_MAX_HEADER_BYTES = 20


class FrameError(ValueError):
    """A garbled frame: corrupt length prefix, oversize declaration,
    truncated payload, checksum mismatch, or non-JSON bytes. The channel
    can no longer be trusted to be frame-aligned — the only safe response
    is to drop it."""


def encode_frame(obj: dict, *, crc: bool = False) -> bytes:
    """``obj`` as one wire-ready frame; ``crc=True`` emits the checksummed
    header form (send it only to peers known to parse it)."""
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if crc:
        return (
            b"%d %08x\n" % (len(payload), zlib.crc32(payload))
            + payload + b"\n"
        )
    return b"%d\n" % len(payload) + payload + b"\n"


def write_frame(stream: IO[bytes], obj: dict, *, crc: bool = False) -> None:
    """Serialize ``obj`` as one length-prefixed frame and flush."""
    stream.write(encode_frame(obj, crc=crc))
    stream.flush()


def read_frame(
    stream: IO[bytes],
    *,
    max_bytes: int = MAX_FRAME_BYTES,
    meta: Optional[dict] = None,
) -> Optional[dict]:
    """Read one frame; ``None`` on clean EOF, :class:`FrameError` on
    anything garbled (see module docstring for the contract). ``meta``
    (when given) reports ``{"crc": bool}`` — whether the frame carried a
    checksum, which is how a transport learns its peer speaks the
    checksummed form."""
    header = stream.readline(_MAX_HEADER_BYTES)
    if not header:
        return None
    if not header.endswith(b"\n"):
        raise FrameError(
            f"frame header not newline-terminated within "
            f"{_MAX_HEADER_BYTES} bytes: {header[:32]!r}"
        )
    parts = header.split()
    if not parts or len(parts) > 2:
        raise FrameError(f"malformed frame header: {header!r}")
    try:
        n = int(parts[0])
    except ValueError:
        raise FrameError(f"non-numeric frame length prefix: {header!r}") from None
    want_crc: Optional[int] = None
    if len(parts) == 2:
        try:
            want_crc = int(parts[1], 16)
        except ValueError:
            raise FrameError(
                f"non-hex frame checksum token: {header!r}"
            ) from None
    if n < 0 or n > max_bytes:
        raise FrameError(
            f"declared frame length {n} outside [0, {max_bytes}]"
        )
    payload = stream.read(n)
    if payload is None or len(payload) != n:
        raise FrameError(
            f"truncated frame: header promised {n} bytes, "
            f"got {0 if payload is None else len(payload)}"
        )
    stream.read(1)  # the trailing newline (EOF here still parsed a frame)
    if want_crc is not None and zlib.crc32(payload) != want_crc:
        raise FrameError(
            f"frame payload checksum mismatch: declared {want_crc:08x}, "
            f"computed {zlib.crc32(payload):08x} over {n} bytes"
        )
    if meta is not None:
        meta["crc"] = want_crc is not None
    try:
        obj = json.loads(payload)
    except ValueError:
        raise FrameError(
            f"frame payload is not valid JSON ({n} bytes)"
        ) from None
    if not isinstance(obj, dict):
        raise FrameError(f"frame payload is {type(obj).__name__}, not object")
    return obj
