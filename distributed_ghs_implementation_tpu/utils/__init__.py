"""Utilities: verification oracles, visualization, reporting, metrics."""

from distributed_ghs_implementation_tpu.utils.verify import (
    networkx_mst_weight,
    scipy_mst_weight,
    verify_result,
)

__all__ = ["networkx_mst_weight", "scipy_mst_weight", "verify_result"]
