"""Reusable append-only JSONL write-ahead-log core.

Factored out of ``stream/log.py`` (which hardened these idioms over five
review rounds) so the router's accepted-work journal (``fleet/journal.py``)
can share the exact same durability discipline instead of re-deriving it:

* **Durable appends** — one JSON object per line, flushed + fsynced,
  serialized across processes by the advisory per-path flock
  (``utils/locking.py``). Before writing, a *torn tail* left by a crash
  mid-append (a partial line with no trailing newline) is sealed with a
  newline, so the new — durably committed — record can never fuse onto
  garbage and become unparsable itself.
* **Tolerant reads** — :meth:`JsonlWal.read` skips a torn tail and any
  unparsable mid-log line (each counted on the owner's taxonomy), then
  hands the surviving entries to the caller, whose *chain validation*
  (digest chain for streams, sequence contiguity for the router journal)
  decides how much of the suffix is still trustworthy.
* **Tail scan** — :meth:`JsonlWal.tail` finds the last parsable entry by
  a backwards chunked scan, so per-append validation stays O(tail) even
  when compaction has been failing and the log has grown.
* **Compaction** — :meth:`JsonlWal.rewrite` replaces the log atomically
  (tmp + fsync + rename); a crash anywhere leaves either the old or the
  new generation, never a mix.
* **Record checksums** (round 19) — every line carries a ``crc`` field
  (crc32 over the record's canonical JSON), validated on read: a bit flip
  that keeps the line parsable — the corruption schema checks cannot see
  — is counted (``.crc_mismatch``) and skipped instead of replayed.
  Pre-crc lines (no field) stay accepted, so existing logs upgrade in
  place.

The core knows nothing about what a record *means*: callers provide the
``schema`` stamped into (and checked out of) every line, an optional
``validate`` hook for field coercion, and the counter prefix their
taxonomy lives under (``stream.log`` / ``fleet.router.journal``).
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Callable, List, Optional, Tuple

from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.utils.locking import flocked, fsync_dir


def _canonical(obj: dict) -> str:
    """The one byte-deterministic JSON form records are checksummed over
    (sorted keys, tight separators, ASCII escapes) — ``json.loads`` then
    ``_canonical`` round-trips to the identical string, so readers can
    re-derive the writer's checksum input from the parsed record."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def _stamp_crc(record: dict) -> str:
    """One record -> its log line: canonical JSON with a ``crc`` field
    (crc32 of the canonical form WITHOUT the field). A bit flip inside a
    value that stays valid JSON — the corruption the schema check cannot
    see — then fails the checksum on read instead of replaying garbage."""
    crc = zlib.crc32(_canonical(record).encode("utf-8"))
    return _canonical({**record, "crc": crc})


class JsonlWal:
    """One append-only JSONL log file with the durability discipline above.

    ``validate(record) -> dict`` turns one parsed, schema-checked JSON
    object into the caller's entry shape; raising ``ValueError`` /
    ``KeyError`` / ``TypeError`` marks the line unparsable (skipped and
    counted like any other corruption). ``counter_prefix`` namespaces the
    ``.sealed_torn`` / ``.torn_skipped`` / ``.corrupt_line`` / ``.append``
    / ``.rewrite`` counters.
    """

    def __init__(
        self,
        path: str,
        *,
        schema: str,
        counter_prefix: str,
        validate: Optional[Callable[[dict], dict]] = None,
    ):
        self.path = path
        self.schema = schema
        self.counter_prefix = counter_prefix
        self._validate = validate

    def _count(self, name: str, n: int = 1) -> None:
        BUS.count(f"{self.counter_prefix}.{name}", n)

    def lock(self):
        """The advisory cross-process write lock for this log. Callers
        that must validate-then-append atomically hold it around both
        (``append(..., locked=True)`` skips re-taking it)."""
        return flocked(
            self.path, counter=f"{self.counter_prefix}.lock_timeout"
        )

    # -- writing -------------------------------------------------------
    def append(self, record: dict, *, locked: bool = False) -> None:
        """Durably append one record (schema stamped in), sealing any torn
        tail first so a crashed predecessor cannot corrupt this line."""
        if locked:
            self._append_locked(record)
        else:
            with self.lock():
                self._append_locked(record)

    def _append_locked(self, record: dict) -> None:
        parent = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(parent, exist_ok=True)
        line = _stamp_crc({"schema": self.schema, **record})
        seal = b""
        created = True
        try:
            with open(self.path, "rb") as rf:
                created = False
                rf.seek(-1, os.SEEK_END)
                if rf.read(1) != b"\n":
                    seal = b"\n"
                    self._count("sealed_torn")
        except FileNotFoundError:
            pass  # missing: the append below creates it
        except OSError:
            created = False  # exists but empty: nothing to seal
        with open(self.path, "ab") as f:
            f.write(seal + (line + "\n").encode())
            f.flush()
            os.fsync(f.fileno())
        if created:
            # A first append CREATES the log: without a directory fsync
            # the entry is only eventually durable, and "durable before
            # the caller proceeds" is this class's whole contract (the
            # same host-crash hole atomic_write_npz closes).
            fsync_dir(parent)
        self._count("append")

    def rewrite(self, entries: List[dict], *, locked: bool = False) -> None:
        """Atomically replace the log with ``entries`` (compaction /
        chain-truncation repair). tmp + fsync + rename: a crash leaves
        either generation whole, never a blend."""
        if locked:
            self._rewrite_locked(entries)
        else:
            with self.lock():
                self._rewrite_locked(entries)

    def _rewrite_locked(self, entries: List[dict]) -> None:
        os.makedirs(
            os.path.dirname(os.path.abspath(self.path)) or ".", exist_ok=True
        )
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for e in entries:
                f.write(_stamp_crc({"schema": self.schema, **e}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        fsync_dir(os.path.dirname(os.path.abspath(self.path)) or ".")
        self._count("rewrite")

    # -- reading -------------------------------------------------------
    def parse_line(self, line: str) -> Optional[dict]:
        """One log line -> entry dict, or ``None`` for anything torn,
        unparsable, or schema-mismatched."""
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("record is not an object")
            crc = rec.pop("crc", None)
            if crc is not None and zlib.crc32(
                _canonical(rec).encode("utf-8")
            ) != crc:
                # Parsable-but-wrong bytes: a value-level bit flip the
                # schema check cannot see. Counted separately (then
                # skipped like any corrupt line); records from pre-crc
                # builds simply have no crc field and stay accepted.
                self._count("crc_mismatch")
                raise ValueError("record checksum mismatch")
            if rec.get("schema") != self.schema:
                raise ValueError(f"bad schema {rec.get('schema')!r}")
            rec.pop("schema", None)
            if self._validate is not None:
                rec = self._validate(rec)
            return rec
        except (ValueError, KeyError, TypeError):
            return None

    def read(self, *, count: bool = True) -> Tuple[List[dict], int]:
        """Parse the whole log; returns ``(entries, torn_skipped)``.

        A partial final line (torn append) is skipped; an unparsable line
        anywhere else is also skipped (a sealed torn record from a retried
        append sits mid-file) — whether the log is usable past it is the
        caller's chain validation to decide.
        """
        if not os.path.exists(self.path):
            return [], 0
        # errors="replace", like tail(): a non-UTF-8 corruption byte must
        # become an unparsable (skipped, chain-breaking) line, not an
        # uncaught UnicodeDecodeError that makes the whole log — valid
        # prefix included — unrecoverable.
        with open(self.path, encoding="utf-8", errors="replace") as f:
            raw = f.read()
        entries: List[dict] = []
        torn = 0
        lines = raw.split("\n")
        complete = lines[:-1]  # text after the final newline is a torn tail
        if lines[-1]:
            torn += 1
        for i, line in enumerate(complete):
            if not line.strip():
                continue
            entry = self.parse_line(line)
            if entry is None:
                if i == len(complete) - 1:
                    torn += 1  # torn mid-record on the last complete line
                elif count:
                    self._count("corrupt_line")
                continue
            entries.append(entry)
        if torn and count:
            self._count("torn_skipped", torn)
        return entries, torn

    def tail(self) -> Optional[dict]:
        """Last complete, parsable entry, by a backwards chunked scan of
        the file tail — per-append validation must not become O(total log)
        when compaction keeps failing and the file grows."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return None
        buf = b""
        with open(self.path, "rb") as f:
            pos = size
            while pos > 0:
                step = min(65536, pos)
                pos -= step
                f.seek(pos)
                buf = f.read(step) + buf
                lines = buf.decode("utf-8", errors="replace").split("\n")
                # lines[-1] is a torn tail (or empty past the final
                # newline); lines[0] may be a mid-line fragment unless
                # the scan reached the start of the file.
                first = 0 if pos == 0 else 1
                for line in reversed(lines[first:-1]):
                    if not line.strip():
                        continue
                    entry = self.parse_line(line)
                    if entry is not None:
                        return entry
        return None
