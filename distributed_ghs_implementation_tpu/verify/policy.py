"""Serving verification policy: ``off | sample | full`` per SLO class.

The certificate checker (``verify/certify.py``) costs roughly a solve's
host-prep, which is the wrong price for every interactive cache hit and
the right price for everything whose blast radius is large. The policy
maps each request's SLO class to a mode:

* ``full`` — certify inline, before the response leaves the service. A
  failed certificate triggers the **correction path**: the poisoned entry
  is evicted from the store (disk generations quarantined), any device
  residency for the digest dropped, the graph re-solved fresh, the fresh
  result certified, and the corrected answer served — the client never
  sees the bad result (``verify.failed`` / ``verify.corrected``).
* ``sample`` — every ``sample_every``-th request of the class is certified
  on a background audit thread (``verify.audit.*``): the response ships at
  full speed, and a failed audit evicts the entry so the *next* request
  re-solves (you cannot retract a served answer; you can stop serving it).
  Sampling is count-based, not random, so drill counters gate exactly.
* ``off`` — trust the path (the pre-round-19 behavior).

Spec strings (the ``--verify`` CLI flag / ``MSTService(verify=...)``)::

    "full"                          # every class, inline
    "sample"                        # every class, sampled audit
    "bulk=full,interactive=sample,default=off"
    "sample:4"                      # sampled, every 4th request

Class names run through ``obs.slo.sanitize_class`` — the same
normalization the SLO join uses, so a policy class always matches the
class the telemetry reports.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Callable, Dict, Optional

from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.obs.slo import sanitize_class
from distributed_ghs_implementation_tpu.verify.certify import (
    Certificate,
    certify_result,
)

MODES = ("off", "sample", "full")
_DEFAULT_SAMPLE_EVERY = 8


class VerifyPolicy:
    """Per-class verification modes with a default, parsed from a spec."""

    def __init__(
        self,
        default: str = "off",
        *,
        classes: Optional[Dict[str, str]] = None,
        sample_every: int = _DEFAULT_SAMPLE_EVERY,
        engine: str = "auto",
    ):
        if default not in MODES:
            raise ValueError(
                f"verify mode {default!r}; expected off|sample|full"
            )
        self.default = default
        self.classes = {}
        for cls, mode in (classes or {}).items():
            if mode not in MODES:
                raise ValueError(
                    f"verify mode {mode!r} for class {cls!r}; "
                    f"expected off|sample|full"
                )
            self.classes[sanitize_class(cls) or cls] = mode
        if sample_every < 1:
            raise ValueError(f"sample_every must be >= 1, got {sample_every}")
        self.sample_every = int(sample_every)
        self.engine = engine
        self._counts: Dict[Optional[str], int] = collections.defaultdict(int)
        self._lock = threading.Lock()

    @staticmethod
    def parse(spec, **kwargs) -> "VerifyPolicy":
        """``VerifyPolicy`` from a spec string (see module docstring);
        passes through an existing policy, maps ``None``/"" to all-off."""
        if isinstance(spec, VerifyPolicy):
            return spec
        if not spec:
            return VerifyPolicy("off", **kwargs)
        spec = str(spec).strip()
        sample_every = kwargs.pop("sample_every", _DEFAULT_SAMPLE_EVERY)
        default = "off"
        classes: Dict[str, str] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" in part:
                cls, mode = part.split("=", 1)
            else:
                cls, mode = "default", part
            mode = mode.strip()
            if ":" in mode:  # "sample:4" — per-mode sampling cadence
                mode, every = mode.split(":", 1)
                sample_every = int(every)
            if cls.strip() == "default":
                default = mode
            else:
                classes[cls.strip()] = mode
        return VerifyPolicy(
            default, classes=classes, sample_every=sample_every, **kwargs
        )

    @property
    def enabled(self) -> bool:
        return self.default != "off" or any(
            m != "off" for m in self.classes.values()
        )

    def mode_for(self, cls: Optional[str]) -> str:
        return self.classes.get(cls, self.default)

    def should_sample(self, cls: Optional[str]) -> bool:
        """Deterministic count-based sampling: the 1st, then every
        ``sample_every``-th request of the class is audited (counting from
        the first, so a single-request class is still covered)."""
        with self._lock:
            count = self._counts[cls]
            self._counts[cls] = count + 1
        return count % self.sample_every == 0

    def describe(self) -> dict:
        return {
            "default": self.default,
            "classes": dict(self.classes),
            "sample_every": self.sample_every,
            "engine": self.engine,
        }


class AsyncAuditor:
    """Background certification: a bounded queue drained by one daemon
    thread. Enqueue never blocks the serving path — a full queue drops the
    audit and counts it (``verify.audit.dropped``): sampled verification
    is an alarm, not a guarantee, and an alarm that can stall serving
    would be worse than the silent failure it hunts."""

    def __init__(
        self,
        *,
        engine: str = "auto",
        capacity: int = 64,
        on_failure: Optional[Callable] = None,
    ):
        self.engine = engine
        self.on_failure = on_failure
        self._q: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._idle = threading.Event()
        self._idle.set()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._drain, name="verify-audit", daemon=True
                )
                self._thread.start()

    def submit(
        self,
        result,
        *,
        cls: Optional[str] = None,
        key=None,
        certify: Optional[Callable] = None,
    ) -> bool:
        """Queue one result for audit; ``False`` when dropped (full).
        ``certify`` overrides the default MST certificate — the analytics
        kinds audit with their own adapters (``certify(result, engine) ->
        Certificate``)."""
        self._ensure_thread()
        try:
            self._q.put_nowait((result, cls, key, certify))
        except queue.Full:
            BUS.count("verify.audit.dropped")
            return False
        self._idle.clear()
        BUS.count("verify.audit.queued")
        return True

    def _drain(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                self._idle.set()
                continue
            result, cls, key, certify = item
            try:
                cert = (certify or certify_result)(
                    result, engine=self.engine
                )
                if cert.ok:
                    BUS.count("verify.audit.ok")
                else:
                    BUS.count("verify.audit.failed")
                    BUS.instant(
                        "verify.audit.failure", cat="verify",
                        reason=cert.reason, cls=cls,
                        digest=result.graph.digest()[:16],
                    )
                    if self.on_failure is not None:
                        self.on_failure(result, cert, cls, key)
            except Exception:  # noqa: BLE001 — audit must never kill serving
                BUS.count("verify.audit.errors")
            finally:
                if self._q.empty():
                    self._idle.set()
                self._q.task_done()

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Drills/tests: wait until the queue drains. ``True`` on drained."""
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._q.empty() and self._idle.is_set():
                return True
            time.sleep(0.005)
        return self._q.empty()


class ResultVerifier:
    """The serve-side glue: policy + inline correction + async audit.

    ``resolve(graph, backend)`` is injected by the service — it must
    bypass whatever produced the bad result (the service passes a
    store-invalidating fresh solve); ``invalidate(key, digest)`` evicts
    the poisoned entry from cache + residency. Both are called ONLY on a
    failed certificate, so the hot path stays allocation-light.
    """

    def __init__(
        self,
        policy: VerifyPolicy,
        *,
        invalidate: Optional[Callable] = None,
        resolve: Optional[Callable] = None,
    ):
        self.policy = policy
        self.invalidate = invalidate
        self.resolve = resolve
        # Audits run on the NumPy engine unconditionally: the daemon
        # thread must not contend with serving for the device, and a
        # jitted XLA computation living in a daemon thread aborts XLA's
        # thread-pool teardown at interpreter exit ("terminate called
        # without an active exception"). Inline full-mode checks keep the
        # policy's engine (XLA by default where jax is present).
        self.auditor = AsyncAuditor(
            engine="np", on_failure=self._audit_failed
        )

    def _audit_failed(self, result, cert: Certificate, cls, key) -> None:
        # Too late to retract the served response; stop serving the entry.
        if self.invalidate is not None:
            self.invalidate(key, result.graph.digest())

    def audit(
        self,
        result,
        *,
        cls: Optional[str],
        key,
        certify: Optional[Callable] = None,
    ) -> Optional[str]:
        """Async-only verification for paths where inline correction has
        no safe shape (incremental update sessions, stream commits — the
        response is gone before an audit could retract it). ``full``
        classes audit every result, ``sample`` classes on cadence; a
        failure evicts the entry so the next solve re-derives it.
        ``certify`` selects a non-MST adapter (see :meth:`check`)."""
        mode = self.policy.mode_for(cls)
        if mode == "off":
            return None
        if mode == "full" or self.policy.should_sample(cls):
            self.auditor.submit(result, cls=cls, key=key, certify=certify)
            return "audit"
        return None

    def check(
        self,
        result,
        *,
        cls: Optional[str],
        key,
        backend: str,
        certify: Optional[Callable] = None,
        rederive: Optional[Callable] = None,
    ):
        """Verify ``result`` per policy; returns ``(result, verified)``
        where ``verified`` is ``"full"`` / ``"audit"`` / ``None`` and the
        returned result is the CORRECTED one when inline certification
        failed. Raises ``VerificationError`` only when even the fresh
        re-solve fails its certificate (systemic — a broken checker or a
        broken solver; serving either blind would be worse than erroring).

        The analytics kinds pass their own adapters: ``certify(result,
        engine) -> Certificate`` replaces the MST certificate, and
        ``rederive() -> result`` replaces the injected ``resolve`` for the
        correction path (a kind answer is re-derived by its own solver
        wrapper, not by re-solving an MST).
        """
        mode = self.policy.mode_for(cls)
        if mode == "off":
            return result, None
        if mode == "sample":
            if self.policy.should_sample(cls):
                self.auditor.submit(
                    result, cls=cls, key=key, certify=certify
                )
                return result, "audit"
            return result, None
        # mode == "full": inline, with transparent correction.
        check_fn = certify or certify_result
        cert = check_fn(result, engine=self.policy.engine)
        if cert.ok:
            BUS.count("verify.pass")
            return result, "full"
        BUS.count("verify.failed")
        BUS.instant(
            "verify.failure", cat="verify", reason=cert.reason, cls=cls,
            digest=result.graph.digest()[:16],
        )
        if self.invalidate is not None:
            self.invalidate(key, result.graph.digest())
        if rederive is None and self.resolve is None:
            raise VerificationError(
                f"certificate failed ({cert.reason}: {cert.detail}) and no "
                f"re-solve path is attached"
            )
        if rederive is not None:
            corrected = rederive()
        else:
            corrected = self.resolve(result.graph, backend)
        recheck = check_fn(corrected, engine=self.policy.engine)
        if not recheck.ok:
            BUS.count("verify.unrecoverable")
            raise VerificationError(
                f"certificate failed even after a fresh re-solve "
                f"({recheck.reason}: {recheck.detail}) — refusing to serve"
            )
        BUS.count("verify.corrected")
        return corrected, "full"


class VerificationError(RuntimeError):
    """A result failed its certificate and could not be corrected."""
