"""Lane stacking: K same-bucket graphs through one compiled solve.

``models/boruvka.py`` already pads every graph to power-of-two ``(n_pad,
m_pad)`` buckets so same-bucket graphs share a compiled kernel — but the
sharing is only ever *serial*: one dispatch per graph, and on small graphs
the chip idles between dispatches. This module stacks K same-bucket graphs
into lanes and solves all of them in ONE dispatch, two ways:

* ``"fused"`` (default) — block-diagonal: lane ``i``'s vertices shift by
  ``i * n_pad`` and its ranks by ``i * m_pad``, turning the batch into one
  disjoint-union graph the existing flat kernel (``_solve_from_iota``)
  solves unchanged. Fragments never cross lanes, and the rank shift is
  order-preserving within a lane, so the MSF of the union is exactly the
  per-lane MSFs. Measured ~4x graphs/sec over serial dispatch on
  128-vertex graphs (CPU; the win is amortized per-op/dispatch overhead).
* ``"vmap"`` — ``jax.vmap`` of the same iota solve over a leading lane
  axis. The batched ``while_loop`` runs every lane to the slowest lane's
  level count with per-carry selects, which on small graphs eats the
  dispatch savings — kept as the straightforward formulation and for
  accelerators where the selects are free, not as the default.

Compiles are bounded by construction: the solver cache keys on
``(n_pad, m_pad, lanes, mode)``, so traffic drawn from B shape buckets
costs at most B compilations no matter how many batches run
(``batch.compile.hit`` / ``batch.compile.miss`` count the cache traffic).
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, List, Sequence, Tuple

import jax
import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.models.boruvka import (
    _next_pow2,
    _solve_from_iota,
)
from distributed_ghs_implementation_tpu.obs.events import BUS

_INT32_MAX = np.iinfo(np.int32).max

BucketKey = Tuple[int, int]  # (n_pad, m_pad)


def bucket_key(graph: Graph) -> BucketKey:
    """The compiled-shape bucket a graph pads into: ``(n_pad, m_pad)``.

    This is the SAME padding ``prepare_device_arrays`` applies (vertices to
    the next power of two, undirected ranks to the next power of two — edge
    slots are always ``2 * m_pad``), so two graphs with equal keys stack
    into interchangeable lanes. Empty dimensions bucket at 1.
    """
    return (_next_pow2(max(1, graph.num_nodes)), _next_pow2(max(1, graph.num_edges)))


# ----------------------------------------------------------------------
# Compile cache: (n_pad, m_pad, lanes, mode) -> solver callable
# ----------------------------------------------------------------------
_SOLVER_CACHE: Dict[Tuple[int, int, int, str], object] = {}
_CACHE_LOCK = threading.Lock()


def lane_compile_stats() -> dict:
    """Counters mirror onto the bus; this is the direct view for drills."""
    return {
        "entries": len(_SOLVER_CACHE),
        "keys": sorted(_SOLVER_CACHE),
    }


def _get_solver(n_pad: int, m_pad: int, lanes: int, mode: str):
    key = (n_pad, m_pad, lanes, mode)
    with _CACHE_LOCK:
        fn = _SOLVER_CACHE.get(key)
        if fn is not None:
            BUS.count("batch.compile.hit")
            return fn
        BUS.count("batch.compile.miss")
        if mode == "fused":
            fn = functools.partial(_solve_from_iota, num_nodes=lanes * n_pad)
        elif mode == "vmap":
            fn = jax.jit(
                jax.vmap(functools.partial(_solve_from_iota, num_nodes=n_pad))
            )
        else:
            raise ValueError(f"unknown lane mode {mode!r}; expected fused|vmap")
        _SOLVER_CACHE[key] = fn
        return fn


# ----------------------------------------------------------------------
# Stacking
# ----------------------------------------------------------------------
def _stack_fused(graphs: Sequence[Graph], n_pad: int, m_pad: int, lanes: int):
    """Block-diagonal layout: one flat disjoint-union graph.

    Pads are kept inert exactly as in the single-graph layout, just shifted
    into their lane's block: slot pads are lane-local self-edges, rank pads
    stay at the INT32_MAX sentinel (NOT shifted — shifting would overflow
    and, worse, make a pad comparable), endpoint pads are the lane's vertex
    0 (never chosen). Unfilled lanes are all-pad: zero real edges, n_pad
    isolated vertices that cost one union-find no-op per level.
    """
    e_pad = 2 * m_pad
    src = np.empty(lanes * e_pad, np.int32)
    dst = np.empty(lanes * e_pad, np.int32)
    rank = np.full(lanes * e_pad, _INT32_MAX, np.int32)
    ra = np.empty(lanes * m_pad, np.int32)
    rb = np.empty(lanes * m_pad, np.int32)
    for i in range(lanes):
        voff = i * n_pad
        es, ee = i * e_pad, (i + 1) * e_pad
        rs, re = i * m_pad, (i + 1) * m_pad
        if i < len(graphs):
            s, d, r, a, b = graphs[i].rank_arrays(
                pad_edges_to=e_pad, pad_ranks_to=m_pad
            )
            src[es:ee] = s + voff
            dst[es:ee] = d + voff
            rank[es:ee] = np.where(r == _INT32_MAX, _INT32_MAX, r + i * m_pad)
            ra[rs:re] = a + voff
            rb[rs:re] = b + voff
        else:
            src[es:ee] = voff
            dst[es:ee] = voff
            ra[rs:re] = voff
            rb[rs:re] = voff
    return src, dst, rank, ra, rb


def _stack_vmap(graphs: Sequence[Graph], n_pad: int, m_pad: int, lanes: int):
    """Leading-lane-axis layout ``(lanes, ...)`` for the vmapped solver."""
    e_pad = 2 * m_pad
    src = np.zeros((lanes, e_pad), np.int32)
    dst = np.zeros((lanes, e_pad), np.int32)
    rank = np.full((lanes, e_pad), _INT32_MAX, np.int32)
    ra = np.zeros((lanes, m_pad), np.int32)
    rb = np.zeros((lanes, m_pad), np.int32)
    for i, g in enumerate(graphs):
        s, d, r, a, b = g.rank_arrays(pad_edges_to=e_pad, pad_ranks_to=m_pad)
        src[i], dst[i], rank[i], ra[i], rb[i] = s, d, r, a, b
    return src, dst, rank, ra, rb


# ----------------------------------------------------------------------
# The batch solve
# ----------------------------------------------------------------------
def solve_lanes(
    graphs: Sequence[Graph],
    *,
    lanes: int | None = None,
    mode: str = "fused",
) -> List[Tuple[np.ndarray, np.ndarray, int]]:
    """Solve K same-bucket graphs in one dispatch.

    Returns one ``(edge_ids, fragment, levels)`` per input graph, in order
    — the exact contract of ``models.boruvka.solve_graph`` (edge ids index
    ``graph.u/v/w``, sorted; fragment trimmed to ``num_nodes``). ``lanes``
    (default ``len(graphs)``) fixes the stacked lane count; extra lanes are
    inert padding, so a policy can pin ``lanes = max_lanes`` and keep ONE
    compiled shape per bucket regardless of fill. In ``"fused"`` mode
    ``levels`` is the shared batch level count (the slowest lane's); in
    ``"vmap"`` mode it is per-lane.
    """
    if not graphs:
        return []
    lanes = len(graphs) if lanes is None else int(lanes)
    if lanes < len(graphs):
        raise ValueError(f"lanes={lanes} < {len(graphs)} graphs")
    n_pad, m_pad = bucket_key(graphs[0])
    for g in graphs[1:]:
        if bucket_key(g) != (n_pad, m_pad):
            raise ValueError(
                f"mixed buckets in one lane stack: {bucket_key(g)} vs "
                f"{(n_pad, m_pad)} (the policy must group by bucket)"
            )
    if lanes * n_pad >= _INT32_MAX or lanes * m_pad >= _INT32_MAX:
        raise ValueError(
            f"bucket ({n_pad}, {m_pad}) x {lanes} lanes exceeds int32 id "
            "space; the policy should bypass graphs this large"
        )
    solver = _get_solver(n_pad, m_pad, lanes, mode)
    if mode == "fused":
        arrays = _stack_fused(graphs, n_pad, m_pad, lanes)
    else:
        arrays = _stack_vmap(graphs, n_pad, m_pad, lanes)
    mst_ranks, fragment, levels = jax.device_get(solver(*arrays))

    out: List[Tuple[np.ndarray, np.ndarray, int]] = []
    if mode == "fused":
        lane_ranks = np.asarray(mst_ranks).reshape(lanes, m_pad)
        lane_frag = np.asarray(fragment).reshape(lanes, n_pad)
        for i, g in enumerate(graphs):
            ranks = np.nonzero(lane_ranks[i])[0]
            edge_ids = np.sort(g.edge_id_of_rank(ranks))
            frag = lane_frag[i, : g.num_nodes] - i * n_pad
            out.append((edge_ids, frag.astype(np.int32), int(levels)))
    else:
        for i, g in enumerate(graphs):
            ranks = np.nonzero(np.asarray(mst_ranks[i]))[0]
            edge_ids = np.sort(g.edge_id_of_rank(ranks))
            frag = np.asarray(fragment[i])[: g.num_nodes]
            out.append((edge_ids, frag, int(np.asarray(levels)[i])))
    return out
