"""Run the GHS protocol over a graph and harvest the MST.

The backend-facing wrapper (the role ``GHSAlgorithm.run`` plays for threads at
``/root/reference/ghs_implementation.py:442-490``): builds one
:class:`GHSNode` per vertex with rank-valued edges, wakes all nodes, drains
the event queue to quiescence, and harvests BRANCH edges as the MST.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.protocol.messages import EdgeState
from distributed_ghs_implementation_tpu.protocol.node import GHSNode
from distributed_ghs_implementation_tpu.protocol.transport import SimTransport


def run_protocol(
    graph: Graph, *, transport: Optional[SimTransport] = None
) -> Tuple[Dict[int, GHSNode], SimTransport]:
    """Execute the protocol to quiescence; returns the node map + transport."""
    transport = transport or SimTransport()
    m = graph.num_edges
    order = graph.edge_id_of_rank(np.arange(m))
    rank_of_edge = np.empty(m, dtype=np.int64)
    rank_of_edge[order] = np.arange(m)

    adjacency: Dict[int, Dict[int, int]] = {v: {} for v in range(graph.num_nodes)}
    for eid, (a, b) in enumerate(zip(graph.u, graph.v)):
        r = int(rank_of_edge[eid])
        adjacency[int(a)][int(b)] = r
        adjacency[int(b)][int(a)] = r

    nodes: Dict[int, GHSNode] = {}
    for v in range(graph.num_nodes):
        nodes[v] = GHSNode(
            v,
            adjacency[v],
            send=lambda dst, msg, _src=v: transport.send(_src, dst, msg),
        )
    for v in range(graph.num_nodes):
        nodes[v].wakeup()
    transport.run(nodes)
    return nodes, transport


def solve_graph_protocol(
    graph: Graph, *, transport: Optional[SimTransport] = None
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Backend entry matching ``models.boruvka.solve_graph``'s contract.

    ``transport`` lets callers run the protocol over a misbehaving channel
    (``protocol.faults``) — the chaos drill's entry point.
    """
    if graph.num_nodes == 0 or graph.num_edges == 0:
        return (
            np.zeros(0, dtype=np.int64),
            np.arange(graph.num_nodes, dtype=np.int32),
            0,
        )
    nodes, _ = run_protocol(graph, transport=transport)

    # Harvest BRANCH edges (each appears as BRANCH on both endpoints).
    branch_pairs = set()
    for v, node in nodes.items():
        for e in node.edges.values():
            if e.state == EdgeState.BRANCH:
                branch_pairs.add((min(v, e.neighbor), max(v, e.neighbor)))
    pair_to_eid = {
        (int(a), int(b)): eid for eid, (a, b) in enumerate(zip(graph.u, graph.v))
    }
    edge_ids = np.sort([pair_to_eid[p] for p in branch_pairs]).astype(np.int64)

    # Component labels from the harvested tree (host union-find), matching the
    # kernel's fragment contract (labels are root ids).
    parent = np.arange(graph.num_nodes, dtype=np.int32)

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = int(parent[x])
        return x

    for a, b in branch_pairs:
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
    fragment = np.fromiter(
        (find(v) for v in range(graph.num_nodes)), dtype=np.int32, count=graph.num_nodes
    )
    levels = max((n.level for n in nodes.values()), default=0)
    return edge_ids, fragment, int(levels)
