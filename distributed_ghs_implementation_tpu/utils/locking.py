"""Advisory per-path write locking shared by every durable layer.

One helper, three users: the shared on-disk result store
(``serve/store.py``), the stream snapshot+WAL log (``stream/log.py``), and
the router's accepted-work journal (``fleet/journal.py``). It used to live
as ``serve.store._flocked``; the router journal must stay importable
without the serve stack (echo-worker fleets never pay the jax import), so
the lock moved here and ``serve.store`` re-exports it unchanged.

The lock serializes *writers only* — every caller keeps its read path
lock-free (atomic rename + content re-validation) so lookups never block
on a slow writer. ``flock`` is fd-scoped: a holding process that dies
releases it automatically, which is exactly the failure semantics a
crash-recovery layer needs from its own serialization primitive.
"""

from __future__ import annotations

import contextlib
import os
import time

try:  # advisory write locking (processes sharing one directory)
    import fcntl
except ImportError:  # pragma: no cover — non-POSIX: single-writer only
    fcntl = None

from distributed_ghs_implementation_tpu.obs.events import BUS

#: How long a writer waits for a contended per-path lock before giving up
#: (callers treat a timeout as a skipped write, never a failed request).
LOCK_TIMEOUT_S = 2.0
_LOCK_POLL_S = 0.005


def fsync_dir(d: str) -> None:
    """Make a rename/creation durable: fsync the directory holding it.
    Filesystems without directory fds (or sandboxes refusing them) get
    best-effort — the write stays atomic, just back to eventually-
    durable. Shared by ``atomic_write_npz`` and the WAL core."""
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def flocked(
    path: str,
    timeout_s: float = LOCK_TIMEOUT_S,
    *,
    counter: str = "serve.store.lock_timeout",
):
    """Advisory per-path write lock (``<path>.lock``, ``fcntl.flock``).

    Processes sharing one directory (fleet workers on a ``disk_dir`` or
    ``stream_dir``, a restarted router on its journal) must not interleave
    the ``.bak`` rotation inside ``atomic_write_npz`` (rotate, rotate,
    rename, rename) or fuse two half-written WAL appends. Raises
    ``TimeoutError`` past ``timeout_s`` (counted on ``counter`` — the
    default keeps the historical ``serve.store.lock_timeout`` name);
    holders that die release the lock automatically (flock is fd-scoped,
    the kernel drops it on process exit).
    """
    if fcntl is None:
        yield
        return
    # The lock file precedes the payload (writers beneath us create their
    # directory lazily — the lock must not fail on a fresh directory).
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    lock_path = path + ".lock"
    deadline = time.monotonic() + timeout_s
    while True:
        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                if time.monotonic() >= deadline:
                    BUS.count(counter)
                    raise TimeoutError(
                        f"write lock busy > {timeout_s}s: {path}"
                    ) from None
                time.sleep(_LOCK_POLL_S)
                continue
            # Re-validate after acquiring: a cleanup sweep may have
            # unlinked this lock file between our open and our flock, in
            # which case we hold a lock on an anonymous inode while a
            # newer writer holds one on the recreated file — retry on the
            # current file.
            try:
                current_ino = os.stat(lock_path).st_ino
            except FileNotFoundError:
                current_ino = -1
            if os.fstat(fd).st_ino != current_ino:
                continue  # stale inode: reopen and re-acquire
            yield
            return
        finally:
            os.close(fd)  # closing the fd releases the flock
