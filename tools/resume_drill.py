"""On-chip interrupt+resume drill at the capacity regime (VERDICT r4 item 4).

Usage:
  python tools/resume_drill.py run    <ckpt.npz>   # gen RMAT-25, checkpointed
                                                   # solve (saves per chunk)
  python tools/resume_drill.py resume <ckpt.npz>   # same command, fresh
                                                   # process: resumes + verifies

The driver (a shell around this) watches for the checkpoint file to appear,
SIGKILLs the `run` process mid-solve, then invokes `resume` in a fresh
process — exactly the operator flow (re-run the same command after a
preemption). RMAT-25 is the regime ADVICE r3 flagged: on resume the chunked
endpoint rebuild must not re-materialize full-width arrays next to the
4.3 GB resident ra/rb (utils/checkpoint.py chunked-rebuild path) — a
failure only the real 16 GB chip can produce. Oracle weight (scale 25,
ef 16, seed 24): 1,008,877,972 (docs/BASELINE_RUNS.jsonl).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ORACLE_WEIGHT = 1_008_877_972
SCALE = 25


def main() -> int:
    mode, path = sys.argv[1], sys.argv[2]

    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        load_checkpoint,
        solve_graph_checkpointed,
    )

    t0 = time.perf_counter()
    g = rmat_graph(SCALE, 16, seed=24)
    print(f"gen: {time.perf_counter()-t0:.1f}s  m={g.num_edges:,}", flush=True)

    if mode == "resume":
        state = load_checkpoint(path)
        print(f"resuming from saved level={state[2]}", flush=True)

    t0 = time.perf_counter()
    edge_ids, fragment, levels = solve_graph_checkpointed(
        g, path, strategy="rank"
    )
    wall = time.perf_counter() - t0
    w = int(g.w[edge_ids].sum())
    ok = w == ORACLE_WEIGHT
    print(
        f"{mode.upper()} {'OK' if ok else 'WEIGHT MISMATCH'}: weight={w} "
        f"(oracle {ORACLE_WEIGHT}) wall_s={wall:.1f} (prep-inclusive) "
        f"levels={levels}", flush=True,
    )
    if mode == "run":
        print("solve completed uninterrupted (kill came too late)", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
