"""Self-healing supervisor, fault registry, checkpoint recovery, chaos drill.

Covers the acceptance contract end to end: induced solver faults walk the
retry/degrade ladder and still return the oracle MST with every attempt in
the incident log; torn checkpoint writes recover from the retained
generation, then from scratch. Deterministic throughout — injected faults
and a virtual clock, no sleeps.
"""

import os

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.generators import erdos_renyi_graph
from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
from distributed_ghs_implementation_tpu.utils.resilience import (
    FAULTS,
    FaultRegistry,
    InjectedFault,
    Supervisor,
    SupervisorConfig,
    SupervisorExhausted,
    TransientDeviceError,
    WatchdogTimeout,
    is_transient,
)

G = erdos_renyi_graph(80, 0.08, seed=5)
REF_IDS = solve_graph(G)[0]

# No-sleep, zero-backoff policy used throughout (tier-1 must not wait).
FAST = SupervisorConfig(retries_per_rung=1, backoff_base_s=0.0)


def _sup(config=FAST, **kwargs):
    kwargs.setdefault("sleep", lambda s: None)
    return Supervisor(config, **kwargs)


@pytest.fixture(autouse=True)
def _clean_registry():
    FAULTS.reset()
    yield
    FAULTS.reset()


# ----------------------------------------------------------------------
# Fault registry
# ----------------------------------------------------------------------
def test_registry_arm_pop_counts():
    reg = FaultRegistry()
    reg.arm("a.site", times=2)
    assert reg.pop("a.site") is not None
    assert reg.pop("a.site") is not None
    assert reg.pop("a.site") is None  # exhausted and forgotten


def test_registry_fire_raises_only_when_armed():
    reg = FaultRegistry()
    reg.fire("quiet.site")  # unarmed: no-op
    reg.arm("loud.site")
    with pytest.raises(InjectedFault, match="loud.site"):
        reg.fire("loud.site")
    reg.fire("loud.site")  # single-shot: now disarmed


def test_registry_context_manager_disarms():
    reg = FaultRegistry()
    with reg.inject("tmp.site", times=99):
        assert reg.pop("tmp.site") is not None
    assert reg.pop("tmp.site") is None


def test_registry_rejects_bad_input():
    reg = FaultRegistry()
    with pytest.raises(ValueError, match="kind"):
        reg.arm("x", kind="explode")
    with pytest.raises(ValueError, match="'_'"):
        reg.arm("under_scored")


def test_registry_env_parsing(monkeypatch):
    monkeypatch.setenv("GHS_FAULT_RESILIENCE_ATTEMPT_DEVICE", "2")
    monkeypatch.setenv("GHS_FAULT_RESILIENCE_SLOW_STEPPED", "1:slow:3600")
    reg = FaultRegistry()
    reg.reload_env()
    armed = reg.pop("resilience.attempt.device")
    assert armed is not None and armed.kind == "raise"
    assert reg.pop("resilience.attempt.device") is not None
    slow = reg.pop("resilience.slow.stepped")
    assert slow is not None and slow.kind == "slow" and slow.value == 3600.0


def test_registry_env_bad_value(monkeypatch):
    monkeypatch.setenv("GHS_FAULT_BROKEN", "lots")
    reg = FaultRegistry()
    with pytest.raises(ValueError, match="GHS_FAULT_BROKEN"):
        reg.reload_env()


def test_transient_classification():
    assert is_transient(InjectedFault("x"))
    assert is_transient(TransientDeviceError("x"))
    assert is_transient(WatchdogTimeout("x"))
    assert is_transient(OSError("io"))
    assert not is_transient(ValueError("bad input"))
    assert not is_transient(RuntimeError("livelock guard"))

    class XlaRuntimeError(RuntimeError):  # jaxlib's name, matched by name
        pass

    assert is_transient(XlaRuntimeError("device halted"))


# ----------------------------------------------------------------------
# Supervisor
# ----------------------------------------------------------------------
def test_supervised_happy_path_parity():
    ids, frag, _lv, log = _sup().solve(G, entry="device")
    assert np.array_equal(ids, REF_IDS)
    assert [(r.rung, r.outcome) for r in log.records] == [("device", "ok")]
    assert log.final_rung == "device"


def test_supervised_retries_then_succeeds():
    slept = []
    cfg = SupervisorConfig(retries_per_rung=1, backoff_base_s=2.0)
    sup = Supervisor(cfg, sleep=slept.append)
    with FAULTS.inject("resilience.attempt.device", times=1):
        ids, _, _, log = sup.solve(G, entry="device")
    assert np.array_equal(ids, REF_IDS)
    assert [(r.rung, r.outcome) for r in log.records] == [
        ("device", "transient"),
        ("device", "ok"),
    ]
    assert slept == [2.0]  # backoff honored, via the injected sleeper
    assert log.records[0].backoff_s == 2.0


def test_supervised_backoff_doubles_and_caps():
    slept = []
    cfg = SupervisorConfig(
        retries_per_rung=3, backoff_base_s=2.0, backoff_cap_s=5.0, ladder=("device",)
    )
    with FAULTS.inject("resilience.attempt.device", times=3):
        ids, _, _, log = Supervisor(cfg, sleep=slept.append).solve(G)
    assert np.array_equal(ids, REF_IDS)
    assert slept == [2.0, 4.0, 5.0]  # 2, 4, then capped at 5


def test_supervised_degrades_down_the_ladder():
    """The acceptance scenario: persistent device faults ride the ladder to
    the stepped rung; the incident log names every attempt and fallback."""
    with FAULTS.inject("resilience.attempt.device", times=2):
        ids, frag, _lv, log = _sup().solve(G, entry="device")
    assert np.array_equal(ids, REF_IDS)
    assert [(r.rung, r.outcome) for r in log.records] == [
        ("device", "transient"),
        ("device", "transient"),
        ("stepped", "ok"),
    ]
    assert log.final_rung == "stepped"
    assert "InjectedFault" in log.records[0].error
    assert "stepped#1 ok" in log.summary()


def test_supervised_watchdog_timeout_virtual_clock():
    """An armed slow-chunk site advances virtual time past the deadline: the
    attempt dies with WatchdogTimeout at a chunk boundary (no sleeps) and
    the clean retry succeeds."""
    cfg = SupervisorConfig(retries_per_rung=1, backoff_base_s=0.0, deadline_s=100.0)
    sup = _sup(cfg, clock=lambda: 0.0)  # frozen real clock: only skew advances
    with FAULTS.inject("resilience.slow.device", times=1, kind="slow", value=1e6):
        ids, _, _, log = sup.solve(G, entry="device")
    assert np.array_equal(ids, REF_IDS)
    assert [(r.rung, r.outcome) for r in log.records] == [
        ("device", "timeout"),
        ("device", "ok"),
    ]
    assert log.records[0].elapsed_s >= 1e6


def test_supervised_fatal_error_propagates():
    """Non-transient errors are logged and re-raised, never retried."""
    import distributed_ghs_implementation_tpu.models.rank_solver as rs

    real = rs.make_production_solver
    calls = []

    def broken(graph):
        calls.append(1)
        raise ValueError("malformed input")

    rs.make_production_solver = broken
    try:
        with pytest.raises(ValueError, match="malformed input"):
            _sup().solve(G, entry="device")
    finally:
        rs.make_production_solver = real
    assert calls == [1]  # exactly one attempt: no retry on fatal


def test_supervised_exhausted_carries_log():
    cfg = SupervisorConfig(retries_per_rung=0, backoff_base_s=0.0, ladder=("device",))
    with FAULTS.inject("resilience.attempt.device", times=5):
        with pytest.raises(SupervisorExhausted) as ei:
            _sup(cfg).solve(G, entry="device")
    log = ei.value.incidents
    assert [(r.rung, r.outcome) for r in log.records] == [("device", "transient")]


def test_supervised_empty_graph():
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph

    g = Graph.from_edges(3, [])
    ids, frag, lv, log = _sup().solve(g)
    assert ids.size == 0 and frag.tolist() == [0, 1, 2] and len(log) == 0


def test_api_supervised_surface():
    """`minimum_spanning_forest(supervised=True)` labels the backend with the
    rung that actually ran and attaches the incident log."""
    with FAULTS.inject("resilience.attempt.device", times=2):
        r = minimum_spanning_forest(
            G,
            supervised=True,
            supervisor=_sup(),
        )
    assert np.array_equal(r.edge_ids, REF_IDS)
    assert r.backend == "supervised/stepped"
    assert len(r.incidents) == 3
    assert r.incidents.to_json()  # serializes


def test_supervised_attempts_emit_structured_events():
    """Satellite contract: the attempt log is mirrored onto the event bus as
    structured ``resilience.attempt`` events (attempt index, fault site,
    rung = degradation tier, outcome) — no string parsing required."""
    from distributed_ghs_implementation_tpu.obs.events import BUS

    BUS.enable()
    mark = BUS.mark()
    with FAULTS.inject("resilience.attempt.device", times=2):
        _sup().solve(G, entry="device")
    attempts = [
        rec[6] for rec in BUS.events_since(mark) if rec[1] == "resilience.attempt"
    ]
    assert [(a["rung"], a["attempt"], a["outcome"]) for a in attempts] == [
        ("device", 1, "transient"),
        ("device", 2, "transient"),
        ("stepped", 1, "ok"),
    ]
    assert attempts[0]["site"] == "resilience.attempt.device"
    assert "InjectedFault" in attempts[0]["error"]
    assert attempts[2]["site"] is None  # success implicates no fault site
    degrades = [
        rec[6] for rec in BUS.events_since(mark) if rec[1] == "resilience.degrade"
    ]
    assert degrades == [{"from_rung": "device", "to_rung": "stepped"}]
    solves = [
        rec[6] for rec in BUS.events_since(mark) if rec[1] == "resilience.solve"
    ]
    assert solves[0]["entry"] == "device"
    assert solves[0]["final_rung"] == "stepped" and solves[0]["attempts"] == 3


def test_watchdog_timeout_incident_names_slow_site():
    """Timeouts are attributed to the slow site, not the attempt site, in
    both the Incident record and its bus event."""
    from distributed_ghs_implementation_tpu.obs.events import BUS

    BUS.enable()
    mark = BUS.mark()
    cfg = SupervisorConfig(retries_per_rung=1, backoff_base_s=0.0, deadline_s=100.0)
    sup = _sup(cfg, clock=lambda: 0.0)
    with FAULTS.inject("resilience.slow.device", times=1, kind="slow", value=1e6):
        _ids, _, _, log = sup.solve(G, entry="device")
    assert log.records[0].site == "resilience.slow.device"
    attempts = [
        rec[6] for rec in BUS.events_since(mark) if rec[1] == "resilience.attempt"
    ]
    assert attempts[0]["outcome"] == "timeout"
    assert attempts[0]["site"] == "resilience.slow.device"


def test_api_supervised_env_knob(monkeypatch):
    monkeypatch.setenv("GHS_FAULT_RESILIENCE_ATTEMPT_DEVICE", "1")
    FAULTS.reload_env()
    r = minimum_spanning_forest(G, supervised=True, supervisor=_sup())
    assert np.array_equal(r.edge_ids, REF_IDS)
    assert [(i.rung, i.outcome) for i in r.incidents.records] == [
        ("device", "transient"),
        ("device", "ok"),
    ]


# ----------------------------------------------------------------------
# Checkpoint generations + recovery
# ----------------------------------------------------------------------
def test_checkpoint_retains_previous_generation(tmp_path):
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        load_checkpoint,
        save_checkpoint,
    )

    p = str(tmp_path / "gen.npz")
    save_checkpoint(p, np.arange(4, dtype=np.int32), np.zeros(8, bool), 1)
    save_checkpoint(p, np.arange(4, dtype=np.int32), np.ones(8, bool), 2)
    assert os.path.exists(p + ".bak")
    _, _, lv_cur = load_checkpoint(p)
    _, _, lv_bak = load_checkpoint(p + ".bak")
    assert (lv_cur, lv_bak) == (2, 1)


def test_torn_write_recovers_from_bak(tmp_path):
    """The acceptance scenario: a save torn mid-write costs one generation,
    not the run — resume falls back to .bak and matches the oracle."""
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        graph_fingerprint,
        load_checkpoint,
        load_checkpoint_resilient,
        save_checkpoint,
        solve_graph_checkpointed,
    )

    g = erdos_renyi_graph(120, 0.06, seed=31)
    ref_ids = solve_graph(g)[0]
    fp = graph_fingerprint(g)
    p = str(tmp_path / "torn.npz")
    solve_graph_checkpointed(g, p, every=1)
    frag, mst, lv = load_checkpoint(p, expect_fingerprint=fp)

    with FAULTS.inject("checkpoint.save", times=1, kind="torn"):
        with pytest.raises(InjectedFault, match="torn"):
            save_checkpoint(p, frag, mst, lv, fingerprint=fp)

    # The primary generation is now a truncated npz; .bak still loads.
    with pytest.raises(Exception):
        load_checkpoint(p)
    state, source, notes = load_checkpoint_resilient(p, expect_fingerprint=fp)
    assert state is not None and source == p + ".bak"
    assert notes and notes[0][0] == p  # the torn file is named in the trail

    ids, _, _ = solve_graph_checkpointed(g, p, resume=True)
    assert np.array_equal(ids, ref_ids)


def test_double_corruption_falls_back_to_fresh_solve(tmp_path):
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        load_checkpoint_resilient,
        solve_graph_checkpointed,
    )

    g = erdos_renyi_graph(120, 0.06, seed=32)
    ref_ids = solve_graph(g)[0]
    p = str(tmp_path / "dead.npz")
    solve_graph_checkpointed(g, p, every=1)
    for victim in (p, p + ".bak"):
        with open(victim, "wb") as f:
            f.write(b"\x00not-a-zip")
    state, source, notes = load_checkpoint_resilient(p)
    assert state is None and source is None and len(notes) == 2
    ids, _, _ = solve_graph_checkpointed(g, p, resume=True)
    assert np.array_equal(ids, ref_ids)


def test_wrong_graph_checkpoint_still_refused(tmp_path):
    """Recovery must not weaken the fingerprint guard: wrong-graph resume
    raises CheckpointMismatch (a ValueError) instead of falling back."""
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        CheckpointMismatch,
        solve_graph_checkpointed,
    )

    g1 = erdos_renyi_graph(100, 0.1, seed=16)
    g2 = erdos_renyi_graph(100, 0.1, seed=17)
    p = str(tmp_path / "fp.npz")
    solve_graph_checkpointed(g1, p)
    with pytest.raises(CheckpointMismatch, match="different graph"):
        solve_graph_checkpointed(g2, p, resume=True)


def test_plain_injected_save_failure_keeps_generations_loadable(tmp_path):
    """kind="raise" at checkpoint.save models a crash before the rename: the
    primary path is gone but .bak still resumes."""
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        load_checkpoint_resilient,
        save_checkpoint,
    )

    p = str(tmp_path / "crash.npz")
    save_checkpoint(p, np.arange(4, dtype=np.int32), np.zeros(8, bool), 1)
    with FAULTS.inject("checkpoint.save", times=1):
        with pytest.raises(InjectedFault):
            save_checkpoint(p, np.arange(4, dtype=np.int32), np.ones(8, bool), 2)
    state, source, _ = load_checkpoint_resilient(p)
    assert state is not None and source == p + ".bak" and state[2] == 1


# ----------------------------------------------------------------------
# Chaos drill (the tier-1 fast subset of tools/chaos_drill.py)
# ----------------------------------------------------------------------
def test_chaos_drill_fast_subset(tmp_path):
    from distributed_ghs_implementation_tpu.utils.chaos import run_chaos_drill

    report = run_chaos_drill(fast=True, workdir=str(tmp_path))
    failed = [c for c in report["cases"] if not c["ok"]]
    assert report["ok"], f"chaos cases failed: {failed}"
    kinds = {c["kind"] for c in report["cases"]}
    assert kinds == {"protocol", "solver", "checkpoint"}
    # Every protocol case must have genuinely exercised its fault schedule.
    for c in report["cases"]:
        if c["kind"] == "protocol" and c["spec"]["drop"] > 0:
            assert c["stats"]["dropped"] > 0


def test_supervisor_kwarg_implies_supervised():
    """Passing a configured supervisor must not be silently ignored."""
    r = minimum_spanning_forest(G, supervisor=_sup())
    assert r.backend == "supervised/device"
    assert r.incidents is not None and r.incidents.final_rung == "device"


def test_result_json_carries_incident_log(tmp_path):
    """Persisted artifacts of a supervised run keep the attempt trail."""
    from distributed_ghs_implementation_tpu.utils.reporting import result_to_dict

    with FAULTS.inject("resilience.attempt.device", times=1):
        r = minimum_spanning_forest(G, supervisor=_sup())
    d = result_to_dict(r)
    assert [i["outcome"] for i in d["incidents"]] == ["transient", "ok"]
    plain = minimum_spanning_forest(G)
    assert "incidents" not in result_to_dict(plain)


def test_degraded_resume_warns(tmp_path):
    """Falling back past a corrupt generation is loud (RuntimeWarning naming
    the rejected file), not silent."""
    import warnings

    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        solve_graph_checkpointed,
    )

    g = erdos_renyi_graph(100, 0.08, seed=41)
    p = str(tmp_path / "warn.npz")
    solve_graph_checkpointed(g, p, every=1)
    with open(p, "wb") as f:
        f.write(b"\x00torn")
    with pytest.warns(RuntimeWarning, match="previous generation"):
        ids, _, _ = solve_graph_checkpointed(g, p, resume=True)
    assert np.array_equal(ids, solve_graph(g)[0])
    # A clean resume stays silent.
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        solve_graph_checkpointed(g, p, resume=True)


def test_chaos_drill_crashed_case_reported(monkeypatch):
    """A solver case whose supervisor crashes becomes ok:false in the
    report, not a drill traceback."""
    from distributed_ghs_implementation_tpu.utils import chaos
    from distributed_ghs_implementation_tpu.utils import resilience

    class Boom(resilience.Supervisor):
        def solve(self, graph, *, entry="device"):
            raise SupervisorExhausted("boom", resilience.IncidentLog())

    monkeypatch.setattr(
        "distributed_ghs_implementation_tpu.utils.resilience.Supervisor", Boom
    )
    cases = chaos._solver_cases(fast=True)
    assert cases and all(c["ok"] is False for c in cases)
    assert all("SupervisorExhausted" in c["error"] for c in cases)


def test_slow_site_consumed_without_deadline():
    """An armed slow site must be consumed by the guarded attempt even when
    no deadline is set — it must not leak into a later solve."""
    FAULTS.arm("resilience.slow.device", kind="slow", value=1e6)
    ids, _, _, log = _sup().solve(G, entry="device")
    assert np.array_equal(ids, REF_IDS)
    assert [(r.rung, r.outcome) for r in log.records] == [("device", "ok")]
    assert not FAULTS.armed("resilience.slow.device")


def test_cli_supervised_deadline_watchdog(tmp_path, monkeypatch, capsys):
    """`run --supervised --deadline-s` arms the watchdog end to end: an
    env-injected slow chunk times the first attempt out, the retry lands."""
    from distributed_ghs_implementation_tpu.cli import main as cli_main
    from distributed_ghs_implementation_tpu.graphs import io as gio

    gdir = str(tmp_path / "g")
    gio.write_partition_dir(erdos_renyi_graph(30, 0.2, seed=6), gdir)
    monkeypatch.setenv("GHS_FAULT_RESILIENCE_SLOW_DEVICE", "1:slow:1000000")
    FAULTS.reload_env()
    rc = cli_main(
        ["run", "--graph-dir", gdir, "--backend", "device",
         "--supervised", "--deadline-s", "600", "--verify"]
    )
    assert rc == 0
    err = capsys.readouterr().err
    assert "timeout" in err and "device#2 ok" in err


def test_save_after_torn_recovery_keeps_good_generation(tmp_path):
    """Rotating a torn primary over the good .bak would reopen the
    zero-generation window; the torn file is dropped instead."""
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        load_checkpoint,
        save_checkpoint,
    )

    p = str(tmp_path / "rot.npz")
    save_checkpoint(p, np.arange(4, dtype=np.int32), np.zeros(8, bool), 1)
    with FAULTS.inject("checkpoint.save", times=1, kind="torn"):
        with pytest.raises(InjectedFault):
            save_checkpoint(p, np.arange(4, dtype=np.int32), np.ones(8, bool), 2)
    # p is torn, .bak holds level 1. The next save must not rotate the torn
    # primary over it: afterwards BOTH generations load.
    save_checkpoint(p, np.arange(4, dtype=np.int32), np.ones(8, bool), 3)
    assert load_checkpoint(p)[2] == 3
    assert load_checkpoint(p + ".bak")[2] == 1
