"""Offline kernel autotuner: measured per-bucket kernel selection.

The selector's ``auto`` tier used to be a pure heuristic — "Pallas on TPU
when the probe passes, XLA everywhere else" — with the Pallas block
shapes themselves hardcoded guesses. This package replaces the guess with
a measurement, the same empirical bar the reference paper holds itself
to (correctness by external oracle, timing by measurement):

* :mod:`tune.space` — enumerate the valid candidates per solver bucket
  (kernel x :class:`~..ops.pallas_kernels.KernelGeometry` knobs, with the
  trace-time shape/VMEM guards as hard validity filters);
* :mod:`tune.measure` — the seeded offline search: interpret-mode parity
  check before any candidate is trusted, warm-then-median timing with
  the bench conventions, bad candidates scored dead instead of crashing
  the search; on non-TPU hosts winners deterministically pin ``xla``
  (Pallas off-TPU is interpret mode — a parity tool, not a throughput
  path), which is what makes the whole subsystem CI-testable;
* :mod:`tune.record` — the persisted ``ghs-tuning-v1`` TuningRecord,
  keyed by the machine fingerprint of ``utils/compile_cache`` and
  protected by the round-19 integrity pattern (atomic writes + sha256
  sidecars); staleness guards invalidate it when the jax version,
  backend, or capability probe changes.

Installing a record (``record.install_record``) makes it load-bearing:
``pallas_kernels.kernel_choice``'s ``auto`` tier consults the measured
winner for the bucket being resolved (``kernel.selected.measured`` on
the obs bus), falling back to the probe heuristic for unknown buckets.
``cli tune`` is the front end; docs/KERNELS.md "Autotuning" is the
operator story.
"""

from distributed_ghs_implementation_tpu.tune.measure import search
from distributed_ghs_implementation_tpu.tune.record import (
    RECORD_SCHEMA,
    default_record_path,
    install_record,
    load_and_install,
    load_record,
    save_record,
)
from distributed_ghs_implementation_tpu.tune.space import (
    Candidate,
    enumerate_candidates,
    raw_space_size,
)

__all__ = [
    "Candidate",
    "RECORD_SCHEMA",
    "default_record_path",
    "enumerate_candidates",
    "install_record",
    "load_and_install",
    "load_record",
    "raw_space_size",
    "save_record",
    "search",
]
