"""Real multi-process coverage for the DCN path.

Spawns two OS processes that bring up ``jax.distributed`` on CPU (2 virtual
devices each -> a 4-device mesh spanning both), solve the same graph through
``solve_graph_sharded``, and agree on the oracle weight. This executes the
code the SLURM/TPU-pod launchers drive (``parallel/multihost.py``,
``launcher/``) — the role of the reference's ``mpiexec -n N`` localhost runs
(``/root/reference/README_MPI.md:78-81``).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

_CHILD = os.path.join(os.path.dirname(__file__), "_multihost_child.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_solve(tmp_path):
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # The children configure their own JAX env (CPU, 2 virtual devices).
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, _CHILD, coordinator, "2", str(i), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost child timed out")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"child failed:\nstdout={out}\nstderr={err}"

    records = []
    for i in range(2):
        with open(tmp_path / f"proc{i}.json") as f:
            records.append(json.load(f))
    for r in records:
        assert r["process_count"] == 2
        assert r["local_devices"] == 2
        assert r["global_devices"] == 4
        assert r["mst_weight"] == r["expected_weight"]
        assert r["mst_edges"] == 119  # n-1: connected by construction
    assert [r["is_primary"] for r in sorted(records, key=lambda r: r["process_id"])] == [
        True,
        False,
    ]
    # Both processes harvested the identical MST (replicated outputs).
    assert records[0]["mst_weight"] == records[1]["mst_weight"]

    # Rank-space fast path (VERDICT r3 item 1): byte-identical to the
    # single-device solve on every process, plain and filter-Kruskal.
    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.graphs.generators import (
        erdos_renyi_graph,
    )

    g = erdos_renyi_graph(120, 0.08, seed=33)
    expected = [int(x) for x in minimum_spanning_forest(g, backend="device").edge_ids]
    for r in records:
        assert r["rank_edge_ids"] == expected
        assert r["filtered_edge_ids"] == expected
        # Split-key rank64 program, two real processes (VERDICT r4 item 6).
        assert r["rank64_edge_ids"] == expected
        # Checkpointed sharded solve + broadcast-agreed resume.
        assert r["ckpt_edge_ids"] == expected
        assert r["ckpt_resume_edge_ids"] == expected
    # Primary-only artifact rule: exactly process 0 wrote its checkpoint.
    by_id = sorted(records, key=lambda r: r["process_id"])
    assert [r["ckpt_file_exists"] for r in by_id] == [True, False]
