"""BASELINE config 4: RMAT-24 (16.7M nodes, ~260M undirected edges), single chip.

The BASELINE.json metric is MST edges/sec on RMAT-24 with weight parity.
The north-star target is the v5e-8 sharded solve; this tool records the
single-chip number (the 8-chip path is validated functionally on a virtual
mesh — real multi-chip hardware is not attached to this host).

Prints per-stage timings and a final JSON line.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))



def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    import jax

    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.models import rank_solver as rs
    from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight

    scale = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    expect = int(sys.argv[2]) if len(sys.argv) > 2 else None
    cache = f"/tmp/rmat{scale}_s24.npz"
    t0 = time.perf_counter()
    if os.path.exists(cache):
        from distributed_ghs_implementation_tpu.graphs.io import read_npz

        g = read_npz(cache)
        log(f"loaded {cache} in {time.perf_counter()-t0:.1f}s")
    else:
        g = rmat_graph(scale, 16, seed=24)
        log(f"gen RMAT-{scale}: {g.num_nodes:,} nodes {g.num_edges:,} edges "
            f"in {time.perf_counter()-t0:.1f}s")
        from distributed_ghs_implementation_tpu.graphs.io import write_npz

        write_npz(g, cache)

    t0 = time.perf_counter()
    vmin0, ra, rb, parent1 = rs.prepare_rank_arrays_full(g)
    jax.block_until_ready((vmin0, ra, rb, parent1))
    t_prep = time.perf_counter() - t0
    log(f"host prep + staging: {t_prep:.1f}s (m_pad={ra.shape[0]:,})")

    times = []
    lv = 0
    for i in range(3):
        t0 = time.perf_counter()
        mst, frag, lv = rs.solve_rank_auto(vmin0, ra, rb, family="dense", parent1=parent1)
        jax.block_until_ready((mst, frag))
        times.append(time.perf_counter() - t0)
        log(f"solve {i}: {times[-1]:.2f}s levels={lv}")
    best = min(times)

    ids = rs.fetch_mst_edge_ids(g, mst)
    weight = int(g.w[ids].sum())
    t_oracle = 0.0
    if expect is None:  # pass the known weight as argv[2] to skip the oracle
        t0 = time.perf_counter()
        expect = int(scipy_mst_weight(g))
        t_oracle = time.perf_counter() - t0
    ok = weight == expect
    out = {
        "config": f"RMAT-{scale}",
        "nodes": g.num_nodes,
        "edges": g.num_edges,
        "solve_best_s": round(best, 3),
        "edges_per_s": round(g.num_edges / best, 0),
        "levels": int(lv),
        "prep_s": round(t_prep, 1),
        "oracle_s": round(t_oracle, 1),
        "weight": weight,
        "verified": ok,
    }
    print(json.dumps(out), flush=True)
    assert ok, (weight, expect)


if __name__ == "__main__":
    main()
