"""Chaos drill: the full fault matrix, checked against the oracle.

One command answers "does the system actually degrade gracefully, or do we
merely hope so": sweep lossy-channel specs (drop x duplicate x reorder) over
fuzz graphs through the reliable protocol layer, induce solver faults and
slow chunks under the supervisor, tear a checkpoint mid-write and resume —
and assert oracle-parity MST weight on every single case. Everything is
seeded and event-driven (no sleeps, no wall-clock dependence), so a failing
case replays bit-identically.

``fast=True`` is the tier-1 subset (runs in the unit suite);
``tools/chaos_drill.py`` and ``python -m distributed_ghs_implementation_tpu
chaos`` run it standalone and emit the JSON report.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
from typing import List, Optional

import numpy as np


def _oracle_weight(graph) -> float:
    from distributed_ghs_implementation_tpu.utils.verify import networkx_mst_weight

    return float(networkx_mst_weight(graph))


def _fuzz_graphs(fast: bool) -> list:
    from distributed_ghs_implementation_tpu.graphs.generators import (
        erdos_renyi_graph,
        line_graph,
        simple_test_graph,
    )

    graphs = [
        ("simple", simple_test_graph()),
        ("line24", line_graph(24)),
        ("er40-a", erdos_renyi_graph(40, 0.12, seed=101)),
        ("er40-b", erdos_renyi_graph(40, 0.12, seed=102)),
    ]
    if not fast:
        graphs += [
            ("line80", line_graph(80)),
            ("er60-sparse", erdos_renyi_graph(60, 0.06, seed=103)),
            ("er60-dense", erdos_renyi_graph(60, 0.25, seed=104)),
            ("er90", erdos_renyi_graph(90, 0.08, seed=105)),
        ]
    return graphs


def _fault_specs(fast: bool) -> list:
    from distributed_ghs_implementation_tpu.protocol.faults import FaultSpec

    if fast:
        return [
            FaultSpec(drop=0.2, duplicate=0.1, reorder=0.3, seed=7),
            FaultSpec(drop=0.2, seed=11),
            FaultSpec(duplicate=0.1, reorder=0.3, seed=13),
        ]
    specs = []
    seed = 1000
    for drop in (0.0, 0.05, 0.1, 0.2):
        for dup in (0.0, 0.1):
            for reorder in (0.0, 0.3):
                seed += 1
                specs.append(
                    FaultSpec(drop=drop, duplicate=dup, reorder=reorder, seed=seed)
                )
    return specs


def _protocol_cases(fast: bool) -> List[dict]:
    """Reliable protocol layer vs the lossy-channel matrix."""
    from distributed_ghs_implementation_tpu.protocol.faults import ReliableTransport
    from distributed_ghs_implementation_tpu.protocol.runner import solve_graph_protocol

    cases = []
    for gname, graph in _fuzz_graphs(fast):
        expected = _oracle_weight(graph)
        for spec in _fault_specs(fast):
            transport = ReliableTransport(spec)
            edge_ids, fragment, _levels = solve_graph_protocol(
                graph, transport=transport
            )
            weight = float(graph.w[edge_ids].sum())
            components = int(np.unique(fragment).size)
            ok = (
                abs(weight - expected) < 1e-9
                and edge_ids.shape[0] == graph.num_nodes - components
            )
            cases.append(
                {
                    "kind": "protocol",
                    "graph": gname,
                    "spec": {
                        "drop": spec.drop,
                        "duplicate": spec.duplicate,
                        "reorder": spec.reorder,
                        "seed": spec.seed,
                    },
                    "weight": weight,
                    "expected_weight": expected,
                    "stats": transport.stats,
                    "ok": ok,
                }
            )
    return cases


def _solver_cases(fast: bool) -> List[dict]:
    """Induced solver faults + slow chunks under the supervisor."""
    from distributed_ghs_implementation_tpu.graphs.generators import erdos_renyi_graph
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
    from distributed_ghs_implementation_tpu.utils.resilience import (
        FAULTS,
        Supervisor,
        SupervisorConfig,
    )

    graph = erdos_renyi_graph(80, 0.08, seed=200)
    ref_ids, _, _ = solve_graph(graph)
    cfg = SupervisorConfig(retries_per_rung=1, backoff_base_s=0.0)

    def drill(name, sites, expect_outcomes, config=cfg):
        sup = Supervisor(config, sleep=lambda s: None)
        for site_kwargs in sites:
            FAULTS.arm(**site_kwargs)
        try:
            edge_ids, _frag, _lv, log = sup.solve(graph, entry="device")
        except Exception as e:  # a crashed case is a failed case, not a
            return {  # crashed report
                "kind": "solver",
                "case": name,
                "error": repr(e),
                "ok": False,
            }
        finally:
            for site_kwargs in sites:
                FAULTS.disarm(site_kwargs["site"])
        outcomes = [(r.rung, r.outcome) for r in log.records]
        ok = bool(np.array_equal(edge_ids, ref_ids)) and outcomes == expect_outcomes
        return {
            "kind": "solver",
            "case": name,
            "incidents": log.to_dicts(),
            "ok": ok,
        }

    cases = [
        # One transient device error: retried on the same rung.
        drill(
            "retry-after-transient",
            [dict(site="resilience.attempt.device", times=1)],
            [("device", "transient"), ("device", "ok")],
        ),
        # Persistent device errors: retries exhausted, degrade to stepped.
        drill(
            "degrade-to-stepped",
            [dict(site="resilience.attempt.device", times=2)],
            [("device", "transient"), ("device", "transient"), ("stepped", "ok")],
        ),
        # A slow chunk trips the watchdog deadline; the retry is clean. The
        # injected 1e6 s of virtual skew dwarfs any real scheduler jitter.
        drill(
            "watchdog-timeout-then-retry",
            [dict(site="resilience.slow.device", times=1, kind="slow", value=1e6)],
            [("device", "timeout"), ("device", "ok")],
            SupervisorConfig(
                retries_per_rung=1, backoff_base_s=0.0, deadline_s=1e5
            ),
        ),
    ]
    if not fast:
        # Every device-path attempt fails: ride the full ladder down to the
        # host Kruskal rung (gated on the native toolchain being present).
        from distributed_ghs_implementation_tpu.graphs import native

        if native.native_available():
            cases.append(
                drill(
                    "degrade-to-host",
                    [
                        dict(site="resilience.attempt.device", times=2),
                        dict(site="resilience.attempt.stepped", times=2),
                    ],
                    [
                        ("device", "transient"),
                        ("device", "transient"),
                        ("stepped", "transient"),
                        ("stepped", "transient"),
                        ("host", "ok"),
                    ],
                    SupervisorConfig(
                        retries_per_rung=1,
                        backoff_base_s=0.0,
                        ladder=("device", "stepped", "host"),
                    ),
                )
            )
    return cases


def _checkpoint_cases(fast: bool, workdir: Optional[str] = None) -> List[dict]:
    """Torn checkpoint writes: recovery from .bak, then from scratch."""
    import os

    from distributed_ghs_implementation_tpu.graphs.generators import erdos_renyi_graph
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        graph_fingerprint,
        load_checkpoint,
        load_checkpoint_resilient,
        save_checkpoint,
        solve_graph_checkpointed,
    )
    from distributed_ghs_implementation_tpu.utils.resilience import FAULTS, InjectedFault

    graph = erdos_renyi_graph(120, 0.06, seed=201)
    ref_ids, _, _ = solve_graph(graph)
    fp = graph_fingerprint(graph)
    cases = []
    with tempfile.TemporaryDirectory(dir=workdir) as d:
        path = os.path.join(d, "chaos.npz")

        # Populate both generations, then tear a save mid-write.
        solve_graph_checkpointed(graph, path, every=1)
        frag, mst, level = load_checkpoint(path, expect_fingerprint=fp)
        torn_raised = False
        try:
            with FAULTS.inject("checkpoint.save", times=1, kind="torn"):
                save_checkpoint(path, frag, mst, level, fingerprint=fp)
        except InjectedFault:
            torn_raised = True
        state, source, notes = load_checkpoint_resilient(path, expect_fingerprint=fp)
        ids_bak, _, _ = solve_graph_checkpointed(graph, path, resume=True)
        cases.append(
            {
                "kind": "checkpoint",
                "case": "torn-write-recovers-from-bak",
                "recovered_from": source,
                "notes": notes,
                "ok": bool(
                    torn_raised
                    and state is not None
                    and source == path + ".bak"
                    and np.array_equal(ids_bak, ref_ids)
                ),
            }
        )

        # Both generations corrupt: resume falls through to a fresh solve.
        with open(path, "wb") as f:
            f.write(b"\x00torn")
        with open(path + ".bak", "wb") as f:
            f.write(b"\x00torn")
        state2, source2, notes2 = load_checkpoint_resilient(
            path, expect_fingerprint=fp
        )
        ids_fresh, _, _ = solve_graph_checkpointed(graph, path, resume=True)
        cases.append(
            {
                "kind": "checkpoint",
                "case": "double-corruption-solves-fresh",
                "notes": notes2,
                "ok": bool(
                    state2 is None
                    and source2 is None
                    and np.array_equal(ids_fresh, ref_ids)
                ),
            }
        )
    return cases


def _channel_totals(cases: List[dict]) -> dict:
    """Aggregate the reliable sublayer's counters across protocol cases:
    what the lossy channel did (drops/duplicates/reorders injected) and
    what reliability cost (retransmits, acks, duplicates suppressed, worst
    ack latency) — the one-glance health line of the drill."""
    totals = {
        "messages_sent": 0,
        "dropped": 0,
        "duplicated": 0,
        "jittered": 0,
        "retransmits": 0,
        "acks_sent": 0,
        "dup_suppressed": 0,
        "ack_latency_max_ticks": 0,
    }
    for case in cases:
        stats = case.get("stats")
        if not stats:
            continue
        for key in (
            "messages_sent", "dropped", "duplicated", "jittered",
            "retransmits", "acks_sent", "dup_suppressed",
        ):
            totals[key] += stats.get(key, 0)
        latency = stats.get("ack_latency_ticks") or {}
        totals["ack_latency_max_ticks"] = max(
            totals["ack_latency_max_ticks"], latency.get("max", 0)
        )
    return totals


def run_chaos_drill(
    fast: bool = True, include_solver: bool = True, workdir: Optional[str] = None
) -> dict:
    """Run the drill; returns the report dict (``report["ok"]`` is the verdict)."""
    cases = _protocol_cases(fast)
    if include_solver:
        cases += _solver_cases(fast)
        cases += _checkpoint_cases(fast, workdir=workdir)
    return {
        "schema": "ghs-chaos-report-v1",
        "fast": fast,
        "num_cases": len(cases),
        "num_failed": sum(not c["ok"] for c in cases),
        "channel_totals": _channel_totals(cases),
        "cases": cases,
        "ok": all(c["ok"] for c in cases),
    }


def emit_report(report: dict, output: Optional[str] = None) -> int:
    """Print/write the report + a failure summary; returns the exit code."""
    blob = json.dumps(report, indent=2)
    if output:
        with open(output, "w") as f:
            f.write(blob + "\n")
        print(output)
    else:
        print(blob)
    failed = [c for c in report["cases"] if not c["ok"]]
    for c in failed:
        print(f"FAILED: {c['kind']}/{c.get('case', c.get('graph'))}", file=sys.stderr)
    print(
        f"chaos drill: {report['num_cases'] - len(failed)}/{report['num_cases']} ok",
        file=sys.stderr,
    )
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="chaos_drill", description="fault-injection drill vs the MST oracle"
    )
    parser.add_argument(
        "--full", action="store_true", help="full matrix (default: fast subset)"
    )
    parser.add_argument(
        "--no-solver",
        action="store_true",
        help="protocol/lossy-channel cases only",
    )
    parser.add_argument("--output", help="write the JSON report here")
    args = parser.parse_args(argv)
    report = run_chaos_drill(
        fast=not args.full, include_solver=not args.no_solver
    )
    return emit_report(report, args.output)
