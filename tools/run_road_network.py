"""BASELINE config 5 on NON-grid road topology (VERDICT r3 item 6).

``random_road_network`` at USA-road size: 4864x4912 lattice cells with
holes -> ~22M intersections, ~2.4 incident average, irregular degrees,
distance-derived weights. Confirms the ``_pick_family`` sparse tuning
holds off the grid family it was tuned on, oracle-verified. Prints a
JSON receipt for docs/BASELINE_RUNS.jsonl.

Usage: python tools/run_road_network.py [rows] [cols] [seed]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    import jax

    from distributed_ghs_implementation_tpu.graphs.generators import (
        random_road_network,
    )
    from distributed_ghs_implementation_tpu.models import rank_solver as rs
    from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight

    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 4864
    cols = int(sys.argv[2]) if len(sys.argv) > 2 else 4912
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 5
    t0 = time.perf_counter()
    g = random_road_network(rows, cols, seed=seed)
    t_gen = time.perf_counter() - t0
    deg = np.bincount(g.u, minlength=g.num_nodes) + np.bincount(
        g.v, minlength=g.num_nodes
    )
    hist = (np.bincount(deg, minlength=9)[:9] / g.num_nodes).round(4)
    family = rs._pick_family(g)
    log(f"gen {t_gen:.1f}s: n={g.num_nodes:,} m={g.num_edges:,} "
        f"avg_deg={2*g.num_edges/g.num_nodes:.2f} family={family}")
    log(f"degree histogram 0..8: {hist.tolist()}")

    t0 = time.perf_counter()
    vmin0, ra, rb, parent1 = rs.prepare_rank_arrays_full(g)
    jax.block_until_ready((vmin0, ra, rb, parent1))
    t_prep = time.perf_counter() - t0
    log(f"prep+staging {t_prep:.1f}s")

    times = []
    lv = 0
    for i in range(3):
        t0 = time.perf_counter()
        mst, frag, lv = rs.solve_rank_auto(
            vmin0, ra, rb, family=family, parent1=parent1
        )
        jax.block_until_ready((mst, frag))
        # Force a real sync (block_until_ready alone returns early on the
        # axon tunnel backend — see tools/probe_head.py).
        np.asarray(mst[:1])
        times.append(time.perf_counter() - t0)
        log(f"solve {i}: {times[-1]:.2f}s levels={lv}")
    best = min(times)

    ids = rs.fetch_mst_edge_ids(g, mst)
    weight = float(g.w[ids].sum())
    frag_np = np.asarray(frag)[: g.num_nodes]
    components = int(np.unique(frag_np).size)
    t0 = time.perf_counter()
    expect = scipy_mst_weight(g)
    t_oracle = time.perf_counter() - t0
    ok = abs(weight - expect) < 1e-6
    out = {
        "round": 4,
        "config": "5 (non-grid): random_road_network at USA-road size",
        "nodes": g.num_nodes, "edges": g.num_edges,
        "avg_degree": round(2 * g.num_edges / g.num_nodes, 3),
        "degree_hist_0_8": hist.tolist(),
        "family": family,
        "gen_s": round(t_gen, 1), "prep_s": round(t_prep, 1),
        "solve_best_s": round(best, 3),
        "edges_per_s": round(g.num_edges / best, 0),
        "levels": int(lv), "mst_edges": int(len(ids)),
        "components": components,
        "structural_identity": bool(len(ids) == g.num_nodes - components),
        "weight": weight, "oracle_s": round(t_oracle, 1),
        "verified": bool(ok),
    }
    print(json.dumps(out), flush=True)
    assert ok, (weight, expect)


if __name__ == "__main__":
    main()
