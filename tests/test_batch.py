"""batch/ — bucketed multi-graph lane execution (round 9).

Gates: lane solves are edge-for-edge identical to per-graph sequential
solves (both lane modes), compiles stay bounded by shape-bucket count,
the policy forms/bypasses correctly, the engine isolates lane failures,
concurrent scheduler misses coalesce into device batches, and in-batch
duplicate digests share one flight.
"""

import threading

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.api import (
    minimum_spanning_forest,
    minimum_spanning_forest_batch,
)
from distributed_ghs_implementation_tpu.batch.engine import BatchEngine
from distributed_ghs_implementation_tpu.batch.lanes import (
    bucket_key,
    solve_lanes,
)
from distributed_ghs_implementation_tpu.batch.policy import BatchPolicy
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import (
    gnm_random_graph,
    line_graph,
)
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.utils.resilience import (
    FAULTS,
    SupervisorConfig,
)


@pytest.fixture(autouse=True)
def _clean_global_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.enable()
    BUS.clear()


def _fast_config():
    return SupervisorConfig(retries_per_rung=1, backoff_base_s=0.0)


# ----------------------------------------------------------------------
# Lanes
# ----------------------------------------------------------------------
def test_bucket_key_matches_device_padding():
    g = gnm_random_graph(100, 300, seed=1)
    assert bucket_key(g) == (128, 512)
    assert bucket_key(Graph.from_edges(0, [])) == (1, 1)
    assert bucket_key(line_graph(9)) == (16, 8)


@pytest.mark.parametrize("mode", ["fused", "vmap"])
def test_solve_lanes_parity_same_bucket(mode):
    graphs = [gnm_random_graph(100, 300, seed=s) for s in range(6)]
    outs = solve_lanes(graphs, mode=mode)
    for g, (edge_ids, fragment, levels) in zip(graphs, outs):
        seq = minimum_spanning_forest(g)
        assert np.array_equal(edge_ids, seq.edge_ids)
        assert fragment.shape == (g.num_nodes,)
        # One root per component, in this graph's own vertex id space.
        assert np.unique(fragment).size == seq.num_components
        assert fragment.min() >= 0 and fragment.max() < g.num_nodes
        assert levels >= 1


@pytest.mark.parametrize("mode", ["fused", "vmap"])
def test_solve_lanes_padded_lanes_are_inert(mode):
    graphs = [gnm_random_graph(60, 150, seed=s) for s in range(3)]
    padded = solve_lanes(graphs, lanes=8, mode=mode)
    tight = solve_lanes(graphs, mode=mode)
    for (a, _, _), (b, _, _) in zip(padded, tight):
        assert np.array_equal(a, b)


def test_solve_lanes_rejects_mixed_buckets_and_bad_lane_count():
    a = gnm_random_graph(60, 150, seed=1)
    b = gnm_random_graph(600, 1500, seed=2)
    with pytest.raises(ValueError, match="mixed buckets"):
        solve_lanes([a, b])
    with pytest.raises(ValueError, match="lanes"):
        solve_lanes([a, a], lanes=1)


def test_compile_cache_bounded_by_bucket_count():
    """>= 64 mixed graphs across B buckets cost at most B compilations —
    the ISSUE 4 acceptance bound, measured on the compile-cache counter."""
    rng = np.random.default_rng(5)
    graphs = []
    for i in range(64):
        nodes = int(rng.choice([48, 96, 200, 400]))
        graphs.append(
            gnm_random_graph(nodes, int(rng.integers(nodes, 3 * nodes)),
                             seed=1000 + i)
        )
    buckets = {bucket_key(g) for g in graphs}
    mark_miss = BUS.counters().get("batch.compile.miss", 0)
    engine = BatchEngine(policy=BatchPolicy(max_lanes=8))
    results = engine.solve_many(graphs)
    compiles = BUS.counters().get("batch.compile.miss", 0) - mark_miss
    assert compiles <= len(buckets)
    for g, r in zip(graphs, results):
        assert np.array_equal(
            r.edge_ids, minimum_spanning_forest(g).edge_ids
        )


# ----------------------------------------------------------------------
# Parity property: mixed sizes, duplicates, forests, degenerates
# ----------------------------------------------------------------------
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_batch_vs_sequential_parity_property(seed):
    rng = np.random.default_rng(seed)
    graphs = []
    for i in range(12):
        n = int(rng.integers(8, 300))
        m = int(rng.integers(0, max(1, min(2 * n, n * (n - 1) // 2))))
        graphs.append(
            gnm_random_graph(
                n, m, seed=seed * 100 + i,
                ensure_connected=bool(rng.integers(0, 2)),
            )
        )
    graphs.append(graphs[rng.integers(0, len(graphs))])  # duplicate
    graphs.append(Graph.from_edges(4, []))  # empty edge set
    graphs.append(Graph.from_edges(1, []))  # single vertex
    results = minimum_spanning_forest_batch(graphs)
    assert len(results) == len(graphs)
    for g, r in zip(graphs, results):
        seq = minimum_spanning_forest(g)
        assert r.graph is g
        assert np.array_equal(r.edge_ids, seq.edge_ids)
        assert r.num_components == seq.num_components
        assert r.total_weight == seq.total_weight


def test_batch_api_non_device_backend_falls_back_sequential():
    graphs = [gnm_random_graph(30, 90, seed=s) for s in range(2)]
    results = minimum_spanning_forest_batch(graphs, backend="host")
    for g, r in zip(graphs, results):
        seq = minimum_spanning_forest(g)
        assert np.array_equal(r.edge_ids, seq.edge_ids)
        assert BUS.counters().get("batch.batches.formed", 0) == 0


# ----------------------------------------------------------------------
# Policy
# ----------------------------------------------------------------------
def test_policy_forms_by_bucket_and_chunks_at_max_lanes():
    policy = BatchPolicy(max_lanes=4)
    small = [gnm_random_graph(60, 150, seed=s) for s in range(10)]
    big = [gnm_random_graph(600, 1500, seed=s) for s in range(2)]
    graphs = small[:5] + big + small[5:]
    batches, bypass = policy.form(graphs)
    assert bypass == []
    covered = sorted(i for fb in batches for i in fb.indices)
    assert covered == list(range(len(graphs)))
    assert all(len(fb.indices) <= 4 for fb in batches)
    for fb in batches:
        assert len({bucket_key(graphs[i]) for i in fb.indices}) == 1
    # 10 small (3 chunks of 4/4/2) + 2 big (1 chunk).
    assert len(batches) == 4


def test_policy_oversize_bypass():
    policy = BatchPolicy(max_bucket_nodes=64, max_bucket_edges=256)
    ok = gnm_random_graph(50, 120, seed=1)
    too_many_nodes = gnm_random_graph(100, 120, seed=2)
    too_many_edges = gnm_random_graph(50, 400, seed=3)
    assert policy.admits(ok)
    assert not policy.admits(too_many_nodes)
    assert not policy.admits(too_many_edges)
    batches, bypass = policy.form([ok, too_many_nodes, too_many_edges])
    assert bypass == [1, 2]
    assert [fb.indices for fb in batches] == [(0,)]


def test_policy_validation():
    with pytest.raises(ValueError):
        BatchPolicy(max_lanes=0)
    with pytest.raises(ValueError):
        BatchPolicy(max_wait_s=-1)
    with pytest.raises(ValueError):
        BatchPolicy(mode="turbo")


# ----------------------------------------------------------------------
# Engine
# ----------------------------------------------------------------------
def test_engine_oversize_bypass_counts_and_solves():
    policy = BatchPolicy(max_lanes=4, max_bucket_nodes=64, max_bucket_edges=256)
    engine = BatchEngine(policy=policy, supervisor_config=_fast_config())
    graphs = [gnm_random_graph(50, 120, seed=1),
              gnm_random_graph(300, 900, seed=2)]
    results = engine.solve_many(graphs)
    assert BUS.counters()["batch.bypass"] == 1
    assert results[0].backend == "batch/fused"
    assert results[1].backend.startswith("supervised/")
    for g, r in zip(graphs, results):
        assert np.array_equal(
            r.edge_ids, minimum_spanning_forest(g).edge_ids
        )


def test_engine_retries_transient_batch_fault():
    engine = BatchEngine(
        policy=BatchPolicy(max_lanes=4),
        supervisor_config=_fast_config(),
    )
    graphs = [gnm_random_graph(40, 100, seed=s) for s in range(3)]
    with FAULTS.inject("batch.attempt", times=1):
        results = engine.solve_many(graphs)
    assert BUS.counters()["batch.retry"] == 1
    for g, r in zip(graphs, results):
        assert np.array_equal(
            r.edge_ids, minimum_spanning_forest(g).edge_ids
        )
        # The retried attempt is visible on every lane's incident log.
        assert r.incidents is not None
        assert [rec.outcome for rec in r.incidents.records] == [
            "transient", "ok"
        ]


def test_engine_exhausted_batch_falls_back_per_lane():
    """Per-lane isolation: when every batch attempt fails, each lane solves
    alone under the supervisor — one poisoned batch never fails requests."""
    engine = BatchEngine(
        policy=BatchPolicy(max_lanes=4),
        supervisor_config=_fast_config(),
    )
    graphs = [gnm_random_graph(40, 100, seed=s) for s in range(3)]
    with FAULTS.inject("batch.attempt", times=10):
        results = engine.solve_many(graphs)
    counters = BUS.counters()
    assert counters["batch.lane.fallback"] == 3
    for g, r in zip(graphs, results):
        assert np.array_equal(
            r.edge_ids, minimum_spanning_forest(g).edge_ids
        )
        assert r.backend.startswith("supervised/")


def test_engine_nontransient_error_raises():
    engine = BatchEngine(policy=BatchPolicy(max_lanes=4))
    graphs = [gnm_random_graph(40, 100, seed=1)]

    def boom(*a, **k):
        raise ValueError("programming error")

    import distributed_ghs_implementation_tpu.batch.engine as eng_mod

    orig = eng_mod.execute_stacked
    eng_mod.execute_stacked = boom
    try:
        with pytest.raises(ValueError, match="programming error"):
            engine.solve_many(graphs)
    finally:
        eng_mod.execute_stacked = orig


def test_engine_submit_coalesces_concurrent_misses():
    """A full bucket dispatches immediately: K concurrent submits form ONE
    device batch (deterministic — no timing luck, the forming window only
    closes when the bucket fills or the generous wait expires)."""
    engine = BatchEngine(
        policy=BatchPolicy(max_lanes=4, max_wait_s=30.0),
        supervisor_config=_fast_config(),
    )
    try:
        graphs = [gnm_random_graph(40, 100, seed=s) for s in range(4)]
        pendings = [engine.submit(g) for g in graphs]
        results = [p.wait(timeout=60) for p in pendings]
        counters = BUS.counters()
        assert counters["batch.batches.formed"] == 1
        assert counters["batch.lanes.formed"] == 4
        for g, r in zip(graphs, results):
            assert np.array_equal(
                r.edge_ids, minimum_spanning_forest(g).edge_ids
            )
    finally:
        engine.close()


def test_engine_submit_lone_request_dispatches_after_wait():
    engine = BatchEngine(
        policy=BatchPolicy(max_lanes=8, max_wait_s=0.01),
        supervisor_config=_fast_config(),
    )
    try:
        g = gnm_random_graph(40, 100, seed=9)
        result = engine.submit(g).wait(timeout=60)
        assert np.array_equal(
            result.edge_ids, minimum_spanning_forest(g).edge_ids
        )
        assert BUS.counters()["batch.batches.formed"] == 1
    finally:
        engine.close()


# ----------------------------------------------------------------------
# Scheduler integration
# ----------------------------------------------------------------------
def test_scheduler_batch_engine_miss_path():
    from distributed_ghs_implementation_tpu.serve.scheduler import SolveScheduler

    engine = BatchEngine(
        policy=BatchPolicy(max_lanes=4, max_wait_s=0.005),
        supervisor_config=_fast_config(),
    )
    try:
        sched = SolveScheduler(batch_engine=engine)
        g = gnm_random_graph(50, 150, seed=3)
        result, source = sched.solve(g)
        assert source == "solved"
        assert result.backend == "batch/fused"
        assert sched.solve(g)[1] == "cache"
    finally:
        engine.close()


def test_scheduler_solve_batch_duplicates_share_one_flight():
    """The round-9 satellite: duplicate digests inside one batch resolve
    against a single flight — exactly one solve per distinct digest, even
    when the duplicates are interleaved."""
    from distributed_ghs_implementation_tpu.serve.scheduler import SolveScheduler

    engine = BatchEngine(
        policy=BatchPolicy(max_lanes=4),
        supervisor_config=_fast_config(),
    )
    try:
        sched = SolveScheduler(batch_engine=engine)
        g1 = gnm_random_graph(40, 100, seed=1)
        g1_again = Graph.from_edges(40, list(reversed(g1.edge_triples())))
        g2 = gnm_random_graph(40, 100, seed=2)
        out = sched.solve_batch([g1, g1_again, g2, g1])
        assert [s for _, s in out] == [
            "solved", "coalesced", "solved", "coalesced"
        ]
        assert out[0][0].total_weight == out[1][0].total_weight
        # Exactly one device batch carried both distinct digests.
        assert BUS.counters()["batch.batches.formed"] == 1
        assert BUS.counters()["batch.lanes.formed"] == 2
    finally:
        engine.close()


def test_scheduler_solve_batch_joins_inflight_solve():
    """A batch arriving while another thread already leads a flight for one
    of its digests joins that flight instead of re-solving."""
    import time as _time

    from distributed_ghs_implementation_tpu.serve import scheduler as sched_mod
    from distributed_ghs_implementation_tpu.serve.scheduler import SolveScheduler

    g_shared = gnm_random_graph(40, 100, seed=7)
    g_other = gnm_random_graph(40, 100, seed=8)
    gate = threading.Event()
    entries: list = []
    real = sched_mod.minimum_spanning_forest

    def blocking_solve(graph, **kwargs):
        entries.append(graph)
        assert gate.wait(timeout=30)
        return real(graph, **kwargs)

    sched_mod.minimum_spanning_forest = blocking_solve
    try:
        sched = SolveScheduler()
        solo: list = []
        t = threading.Thread(
            target=lambda: solo.append(sched.solve(g_shared))
        )
        t.start()
        deadline = _time.monotonic() + 30
        while not entries and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert entries  # the solo flight is in blocking_solve, unlanded
        batch_out: list = []
        t2 = threading.Thread(
            target=lambda: batch_out.append(
                sched.solve_batch([g_shared, g_other])
            )
        )
        t2.start()
        # The batch joins the live g_shared flight structurally (its join
        # pass runs before any solving) and leads only g_other — wait for
        # that second solver entry, then release both.
        while len(entries) < 2 and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert len(entries) == 2
        gate.set()
        t.join(timeout=60)
        t2.join(timeout=60)
        assert solo[0][1] == "solved"
        sources = dict(zip(["shared", "other"], [s for _, s in batch_out[0]]))
        assert sources["shared"] == "coalesced"
        assert sources["other"] == "solved"
    finally:
        sched_mod.minimum_spanning_forest = real


def test_service_with_batch_lanes_end_to_end():
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    svc = MSTService(batch_lanes=4)

    def edges(g):
        return [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]

    g = gnm_random_graph(60, 180, seed=11)
    first = svc.handle({"op": "solve", "num_nodes": 60, "edges": edges(g)})
    assert first["ok"] and first["source"] == "solved"
    assert first["backend"] == "batch/fused"
    repeat = svc.handle({"op": "solve", "num_nodes": 60, "edges": edges(g)})
    assert repeat["source"] == "cache"
    assert repeat["total_weight"] == first["total_weight"]
    seq = minimum_spanning_forest(g)
    assert first["total_weight"] == seq.total_weight
    # batch.* counters surface through the stats op.
    stats = svc.handle({"op": "stats"})
    assert stats["counters"]["batch.lanes.formed"] >= 1


def test_scheduler_oversize_miss_keeps_semaphore_path():
    """An engine-attached scheduler must NOT route misses the engine's
    policy would bypass through the unbounded submit() shortcut — oversize
    graphs stay on the semaphore-bounded supervised path."""
    from distributed_ghs_implementation_tpu.serve.scheduler import SolveScheduler

    engine = BatchEngine(
        policy=BatchPolicy(
            max_lanes=4, max_bucket_nodes=32, max_bucket_edges=64
        ),
        supervisor_config=_fast_config(),
    )
    try:
        sched = SolveScheduler(
            batch_engine=engine, supervisor_config=_fast_config()
        )
        big = gnm_random_graph(100, 300, seed=4)
        result, source = sched.solve(big)
        assert source == "solved"
        assert result.backend.startswith("supervised/")
        assert BUS.counters().get("batch.batches.formed", 0) == 0
        assert BUS.counters().get("batch.bypass", 0) == 0  # never submitted
    finally:
        engine.close()


def test_scheduler_solve_batch_lands_flights_when_publish_raises():
    """A raise mid-publish (store.put blowing up on leader 1 of 2) must
    still land every leader's flight — a leaked flight would block all
    future requests for that digest forever."""
    from distributed_ghs_implementation_tpu.serve.scheduler import SolveScheduler

    sched = SolveScheduler()
    g1 = gnm_random_graph(40, 100, seed=21)
    g2 = gnm_random_graph(40, 100, seed=22)
    real_put = sched.store.put
    calls = []

    def failing_put(key, result):
        calls.append(key)
        raise RuntimeError("store exploded")

    sched.store.put = failing_put
    try:
        with pytest.raises(RuntimeError, match="store exploded"):
            sched.solve_batch([g1, g2])
    finally:
        sched.store.put = real_put
    assert len(calls) == 1  # died on the first leader
    assert sched._flights == {}  # nothing leaked
    # The digests are solvable again (fresh flights, no hang).
    out = sched.solve_batch([g1, g2])
    assert [s for _, s in out] == ["solved", "solved"]


def test_fallback_lane_incidents_include_batch_attempts():
    """A degraded lane's incident log starts with the batch-level failures
    that caused the fallback, then its own supervised attempts."""
    engine = BatchEngine(
        policy=BatchPolicy(max_lanes=4),
        supervisor_config=_fast_config(),
    )
    graphs = [gnm_random_graph(40, 100, seed=s) for s in range(2)]
    with FAULTS.inject("batch.attempt", times=10):
        results = engine.solve_many(graphs)
    for r in results:
        assert r.incidents is not None
        rungs = [rec.rung for rec in r.incidents.records]
        assert rungs[:2] == ["batch", "batch"]  # first try + retry
        assert rungs[-1] == "device"
        assert r.incidents.records[-1].outcome == "ok"
        assert r.incidents.final_rung == "device"
