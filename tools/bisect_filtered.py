"""Per-phase bisection of the filtered (filter-Kruskal) solve (VERDICT r3
item 4): where do RMAT-24's ~12.5 s actually go?

The cost model says ~7-9 s (filter = 2 x 260M gathers ~ 4.7 s at the
measured ~9 ns/elem, prefix solve ~1.5 s, plus compactions and ~0.11 s per
dispatch); the residual has never been attributed. This tool wraps every
jitted phase of ``solve_rank_filtered`` with a block-and-record timer (the
host loop already syncs on a stats fetch per chunk, so blocking adds no
real serialization) and prints a per-phase table plus the unattributed
remainder (host work + dispatch round trips + fetches).

Usage: python tools/bisect_filtered.py [scale] [expected_weight]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    import jax

    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    scale = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    expect = int(sys.argv[2]) if len(sys.argv) > 2 else None
    cache = f"/tmp/rmat{scale}_s24.npz"
    t0 = time.perf_counter()
    if os.path.exists(cache):
        from distributed_ghs_implementation_tpu.graphs.io import read_npz

        g = read_npz(cache)
        log(f"loaded {cache} in {time.perf_counter()-t0:.1f}s")
    else:
        g = rmat_graph(scale, 16, seed=24)
        log(f"gen RMAT-{scale}: {g.num_nodes:,} nodes {g.num_edges:,} edges "
            f"in {time.perf_counter()-t0:.1f}s")
        from distributed_ghs_implementation_tpu.graphs.io import write_npz

        write_npz(g, cache)

    t0 = time.perf_counter()
    vmin0, ra, rb, parent1 = rs.prepare_rank_arrays_full(g)
    jax.block_until_ready((vmin0, ra, rb, parent1))
    log(f"prep+staging {time.perf_counter()-t0:.1f}s (m_pad={ra.shape[0]:,})")

    # Warm both code paths (compile + caches), and give the baseline number.
    for i in range(2):
        t0 = time.perf_counter()
        mst, frag, lv = rs.solve_rank_filtered(vmin0, ra, rb, parent1=parent1)
        jax.block_until_ready((mst, frag))
        log(f"baseline solve {i}: {time.perf_counter()-t0:.2f}s levels={lv}")
    baseline = time.perf_counter() - t0

    # Instrument every jitted phase. The wrapper blocks on the outputs, so
    # each record is true device time for that dispatch (the tunnel's
    # dispatch overhead lands in the unattributed remainder).
    record = []

    def timed(name, fn):
        def w(*a, **k):
            t0 = time.perf_counter()
            out = fn(*a, **k)
            jax.block_until_ready(out)
            record.append((name, time.perf_counter() - t0))
            return out
        return w

    names = [
        "_filtered_head", "_compact_and_mark", "_shrink_and_run",
        "_run_levels", "_finish_chunk", "_filter_suffix_ends",
        "_filter_compact", "_filter_suffix_fused",
    ]
    saved = {n: getattr(rs, n) for n in names}
    try:
        for n in names:
            setattr(rs, n, timed(n, saved[n]))
        t0 = time.perf_counter()
        mst, frag, lv = rs.solve_rank_filtered(vmin0, ra, rb, parent1=parent1)
        jax.block_until_ready((mst, frag))
        total = time.perf_counter() - t0
    finally:
        for n in names:
            setattr(rs, n, saved[n])

    by_phase = {}
    for name, dt in record:
        by_phase.setdefault(name, [0.0, 0])
        by_phase[name][0] += dt
        by_phase[name][1] += 1
    log(f"\ninstrumented total: {total:.2f}s ({len(record)} timed dispatches)")
    attributed = 0.0
    for name, (dt, cnt) in sorted(by_phase.items(), key=lambda kv: -kv[1][0]):
        log(f"  {name:22s} {dt:7.2f}s  x{cnt}")
        attributed += dt
    log(f"  {'(unattributed: host+RT)':22s} {total-attributed:7.2f}s")

    ids = rs.fetch_mst_edge_ids(g, mst)
    weight = int(g.w[ids].sum())
    ok = expect is None or weight == expect
    out = {
        "tool": "bisect_filtered",
        "scale": scale,
        "baseline_solve_s": round(baseline, 2),
        "instrumented_total_s": round(total, 2),
        "phases": {k: [round(v[0], 2), v[1]] for k, v in by_phase.items()},
        "unattributed_s": round(total - attributed, 2),
        "weight": weight,
        "verified": ok,
    }
    print(json.dumps(out), flush=True)
    assert ok, (weight, expect)


if __name__ == "__main__":
    main()
