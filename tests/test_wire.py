"""Binary wire plane (round 24, docs/FLEET.md "Binary wire"): B-frame
encode/decode, the zero-copy graph codec, wire-format parity (the same
graph through JSON frames, B-frames, and graph_path must produce one
digest, one solve result, one store key), transport capability
negotiation, the binary serve front door, and the malformed-frame fuzz
contract (every garbled B-frame is a typed FrameError with bounded
allocation — never a crash, never a silent mis-parse)."""

import io
import json
import zlib

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.fleet.framing import (
    SECTIONS_KEY,
    FrameError,
    WireSections,
    encode_bframe,
    encode_frame,
    fold_sections,
    frame_sections,
    read_frame,
)
from distributed_ghs_implementation_tpu.fleet.transport import (
    PipeTransport,
    build_hello,
)
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import (
    gnm_random_graph,
)
from distributed_ghs_implementation_tpu.obs.events import BUS


@pytest.fixture(autouse=True)
def _clean_global_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.enable()
    BUS.clear()


def _edges(g):
    return [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]


def _read_bytes(data: bytes, **kw):
    return read_frame(io.BytesIO(data), **kw)


def _raw_bframe(header: bytes, sections: bytes) -> bytes:
    """A wire-correct B-frame around arbitrary header/section bytes — the
    crc is honest, so only the defect under test trips the reader."""
    crc = zlib.crc32(sections, zlib.crc32(header))
    return (
        b"B%d %d %08x\n" % (len(header), len(sections), crc)
        + header + sections + b"\n"
    )


# ----------------------------------------------------------------------
# B-frame encode/decode round trips
# ----------------------------------------------------------------------
def test_bframe_roundtrip_top_level_sections():
    g = gnm_random_graph(64, 160, seed=7)
    obj = {"op": "solve", **g.to_wire()}
    data = encode_bframe(obj)
    meta: dict = {}
    out = _read_bytes(data, meta=meta)
    assert meta == {"crc": True, "wire": True}
    assert out["op"] == "solve"
    assert out["digest"] == g.digest()
    secs = out[SECTIONS_KEY]
    assert isinstance(secs, WireSections)
    assert secs.names == ("u", "v", "w")
    np.testing.assert_array_equal(secs.array("u"), g.u)
    np.testing.assert_array_equal(secs.array("v"), g.v)
    np.testing.assert_array_equal(secs.array("w"), g.w)


def test_bframe_roundtrip_nested_envelope():
    # The fleet wraps exactly one envelope around a request; the sections
    # must survive one nesting level down.
    g = gnm_random_graph(32, 80, seed=8)
    obj = {"id": 7, "req": {"op": "solve", **g.to_wire()}}
    out = _read_bytes(encode_bframe(obj))
    assert out["id"] == 7
    secs = out["req"][SECTIONS_KEY]
    np.testing.assert_array_equal(secs.array("w"), g.w)


def test_bframe_passthrough_reencode_is_byte_identical():
    # The router's opaque-forwarding contract: a decoded B-frame re-encodes
    # to the same bytes without the section elements ever being touched
    # (decode-side chunks() is the received buffer itself).
    g = gnm_random_graph(48, 120, seed=9)
    data = encode_bframe({"op": "solve", **g.to_wire()})
    decoded = _read_bytes(data)
    assert encode_bframe(decoded) == data
    secs = decoded[SECTIONS_KEY]
    chunks = secs.chunks()
    assert len(chunks) == 1  # ONE spliced buffer, not per-section copies


def test_bframe_empty_sections_and_empty_graph():
    g = Graph.from_edges(5, [])
    out = _read_bytes(encode_bframe({"op": "solve", **g.to_wire()}))
    rebuilt = Graph.from_wire(out)
    assert rebuilt.num_edges == 0 and rebuilt.num_nodes == 5
    assert rebuilt.digest() == g.digest()


def test_plain_json_frames_still_read_with_wire_meta_false():
    meta: dict = {}
    out = _read_bytes(encode_frame({"op": "stats"}, crc=True), meta=meta)
    assert out == {"op": "stats"}
    assert meta == {"crc": True, "wire": False}


# ----------------------------------------------------------------------
# Zero-copy codec + fold parity
# ----------------------------------------------------------------------
def test_from_wire_digest_and_arrays_match_sender():
    g = gnm_random_graph(200, 600, seed=11)
    out = _read_bytes(encode_bframe({"op": "solve", **g.to_wire()}))
    rebuilt = Graph.from_wire(out)
    assert rebuilt.digest() == g.digest()
    np.testing.assert_array_equal(rebuilt.u, g.u)
    np.testing.assert_array_equal(rebuilt.v, g.v)
    np.testing.assert_array_equal(rebuilt.w, g.w)
    # Canonical fast path: the arrays are frombuffer views over the one
    # received frame buffer, not copies.
    assert rebuilt.u.base is not None


def test_from_wire_non_canonical_sender_falls_back_to_canonical_digest():
    g = Graph.from_edges(6, [(0, 1, 3), (1, 2, 5), (0, 2, 4), (3, 4, 1)])
    # A sender shipping unsorted, flipped-endpoint arrays: the receiver
    # must still end at the canonical digest, exactly as the JSON path.
    secs = (
        WireSections()
        .add("u", np.array([2, 1, 4, 1], dtype=np.int64))
        .add("v", np.array([0, 0, 3, 2], dtype=np.int64))
        .add("w", np.array([4, 3, 1, 5], dtype=np.int64))
    )
    payload = {"num_nodes": 6, SECTIONS_KEY: secs}
    roundtripped = _read_bytes(encode_bframe(payload))
    assert Graph.from_wire(roundtripped).digest() == g.digest()


def test_fold_sections_matches_classic_json_request():
    g = gnm_random_graph(40, 100, seed=12)
    folded = fold_sections({"op": "solve", **g.to_wire()})
    assert folded["edges"] == _edges(g)
    assert SECTIONS_KEY not in folded
    assert json.dumps(folded)  # pure JSON again, serializable
    # And the response-shape fold: mst_u/mst_v become mst_edges pairs.
    resp = {
        "ok": True,
        SECTIONS_KEY: WireSections()
        .add("mst_u", g.u[:3])
        .add("mst_v", g.v[:3]),
    }
    assert fold_sections(resp)["mst_edges"] == [
        [int(a), int(b)] for a, b in zip(g.u[:3], g.v[:3])
    ]


def test_from_edges_generator_input_digest_parity():
    # Streamed (generator) construction must hash identically to the
    # materializing list path — int and float weight decks both.
    triples = [(0, 1, 3), (1, 2, 5), (0, 2, 4), (2, 3, 9), (0, 3, 2)]
    assert (
        Graph.from_edges(4, iter(triples)).digest()
        == Graph.from_edges(4, triples).digest()
    )
    ftriples = [(a, b, w + 0.5) for a, b, w in triples]
    assert (
        Graph.from_edges(4, (t for t in ftriples)).digest()
        == Graph.from_edges(4, ftriples).digest()
    )
    # Chunk-boundary crossing: a deck larger than one 65536 block.
    big = [(i, i + 1, i % 97) for i in range(70000)]
    assert (
        Graph.from_edges(70001, iter(big)).digest()
        == Graph.from_edges(70001, big).digest()
    )


# ----------------------------------------------------------------------
# Wire-format parity through the serving stack
# ----------------------------------------------------------------------
def test_solve_parity_json_bframe_graph_path(tmp_path):
    from distributed_ghs_implementation_tpu.graphs import io as gio
    from distributed_ghs_implementation_tpu.serve.service import MSTService
    from distributed_ghs_implementation_tpu.serve.store import (
        solve_cache_key,
    )

    g = gnm_random_graph(80, 240, seed=13)
    path = gio.write_npz(g, str(tmp_path / "g.npz"))
    svc = MSTService()

    json_req = {"op": "solve", "num_nodes": g.num_nodes,
                "edges": _edges(g), "edges_out": True}
    bin_req = _read_bytes(
        encode_bframe({"op": "solve", **g.to_wire(), "edges_out": True})
    )
    path_req = {"op": "solve", "graph_path": path, "edges_out": True}

    r_json = svc.handle(json_req)
    r_bin = svc.handle(bin_req)
    r_path = svc.handle(path_req)
    for r in (r_json, r_bin, r_path):
        assert r["ok"], r
    # One identity: same digest, same store key, same answer.
    assert r_json["digest"] == r_bin["digest"] == r_path["digest"]
    assert (
        solve_cache_key(Graph.from_wire(bin_req))
        == solve_cache_key(Graph.from_edges(g.num_nodes, _edges(g)))
    )
    assert (
        r_json["total_weight"]
        == r_bin["total_weight"]
        == r_path["total_weight"]
    )
    # The JSON solve populated the store; the other two forms must HIT it
    # (byte-identical cache keys, not merely equal answers).
    assert not r_json["cached"]
    assert r_bin["cached"] and r_path["cached"]
    # Binary request -> binary egress; JSON request -> folded pairs; the
    # two egress forms describe the same forest.
    secs = r_bin[SECTIONS_KEY]
    pairs = np.stack(
        [secs.array("mst_u"), secs.array("mst_v")], axis=1
    ).tolist()
    assert pairs == r_json["mst_edges"]


# ----------------------------------------------------------------------
# Transport negotiation (caps.wire, echo-on-receipt, fold-at-boundary)
# ----------------------------------------------------------------------
def test_hello_advertises_wire_cap_and_env_opt_out(monkeypatch):
    assert build_hello(0)["caps"]["wire"] is True
    monkeypatch.setenv("GHS_FLEET_WIRE", "0")
    assert build_hello(0)["caps"]["wire"] is False


def test_encode_for_peer_folds_without_wire_cap():
    t = PipeTransport(io.BytesIO(), io.BytesIO())
    g = gnm_random_graph(24, 60, seed=14)
    payload = {"op": "solve", **g.to_wire()}
    # Legacy peer: section-bearing payload leaves as classic JSON.
    meta: dict = {}
    out = _read_bytes(t.encode_for_peer(dict(payload)), meta=meta)
    assert not meta["wire"]
    assert out["edges"] == _edges(g) and SECTIONS_KEY not in out
    # caps.wire peer: the same payload leaves as a B-frame.
    t.enable_wire()
    meta = {}
    out = _read_bytes(t.encode_for_peer(dict(payload)), meta=meta)
    assert meta["wire"]
    assert isinstance(out[SECTIONS_KEY], WireSections)
    # Sectionless payloads stay plain either way.
    meta = {}
    _read_bytes(t.encode_for_peer({"op": "stats"}), meta=meta)
    assert not meta["wire"]


def test_transport_echo_on_receipt_flips_wire_out():
    g = gnm_random_graph(16, 40, seed=15)
    inbound = io.BytesIO(encode_bframe({"op": "solve", **g.to_wire()}))
    t = PipeTransport(io.BytesIO(), inbound)
    assert not t.wire_out
    frame = t.recv()
    assert isinstance(frame[SECTIONS_KEY], WireSections)
    assert t.wire_out and t.crc_out  # B-frames imply the crc capability


# ----------------------------------------------------------------------
# Binary serve front door (serve --wire binary)
# ----------------------------------------------------------------------
def test_serve_frames_binary_round_trip_and_shutdown():
    from distributed_ghs_implementation_tpu.serve.service import (
        serve_frames,
    )

    g = gnm_random_graph(30, 90, seed=16)
    in_stream = io.BytesIO(
        encode_bframe({"op": "solve", **g.to_wire(), "edges_out": True})
        + encode_frame({"op": "shutdown"}, crc=True)
    )
    out_stream = io.BytesIO()
    assert serve_frames(in_stream, out_stream) == 0
    out_stream.seek(0)
    meta: dict = {}
    resp = read_frame(out_stream, meta=meta)
    assert resp["ok"] and resp["digest"] == g.digest()
    assert meta["wire"]  # binary in -> binary egress
    secs = resp[SECTIONS_KEY]
    assert "mst_u" in secs and "mst_v" in secs
    bye = read_frame(out_stream)
    assert bye["ok"] and bye["op"] == "shutdown"


def test_serve_frames_json_client_never_sees_a_bframe():
    from distributed_ghs_implementation_tpu.serve.service import (
        serve_frames,
    )

    g = gnm_random_graph(30, 90, seed=16)
    in_stream = io.BytesIO(
        encode_frame(
            {"op": "solve", "num_nodes": g.num_nodes, "edges": _edges(g),
             "edges_out": True},
            crc=True,
        )
    )
    out_stream = io.BytesIO()
    assert serve_frames(in_stream, out_stream) == 0  # clean EOF
    out_stream.seek(0)
    meta: dict = {}
    resp = read_frame(out_stream, meta=meta)
    assert resp["ok"] and resp["digest"] == g.digest()
    assert not meta["wire"]  # folded JSON back, per-connection
    assert resp["mst_edges"] and SECTIONS_KEY not in resp


def test_serve_frames_garbled_stream_exits_nonzero():
    from distributed_ghs_implementation_tpu.serve.service import (
        serve_frames,
    )

    out_stream = io.BytesIO()
    rc = serve_frames(io.BytesIO(b"not a frame at all\n"), out_stream)
    assert rc == 1
    out_stream.seek(0)
    err = read_frame(out_stream)
    assert not err["ok"] and "bad frame" in err["error"]


# ----------------------------------------------------------------------
# Fuzz: every malformed B-frame is a typed FrameError, allocation bounded
# ----------------------------------------------------------------------
def _sample_bframe() -> bytes:
    g = gnm_random_graph(20, 50, seed=17)
    return encode_bframe({"op": "solve", **g.to_wire()})


def test_bframe_truncation_at_every_byte_is_typed():
    data = _sample_bframe()
    # Cut everywhere except the trailing newline (EOF there still parsed
    # a complete frame — the newline is cosmetic framing).
    for cut in range(len(data) - 1):
        stream = io.BytesIO(data[:cut])
        if cut == 0:
            assert read_frame(stream) is None  # clean EOF, not an error
        else:
            with pytest.raises(FrameError):
                read_frame(stream)


def test_bframe_bit_flip_at_every_byte_is_typed():
    data = _sample_bframe()
    for pos in range(len(data) - 1):  # trailing newline is unchecked
        flipped = bytearray(data)
        flipped[pos] ^= 0x40
        try:
            out = _read_bytes(bytes(flipped))
        except FrameError:
            continue  # the contract: typed rejection
        except Exception as e:  # noqa: BLE001 — anything else is the bug
            raise AssertionError(
                f"flip at byte {pos} escaped FrameError: {type(e).__name__}: {e}"
            ) from e
        raise AssertionError(
            f"flip at byte {pos} produced a frame: {type(out).__name__}"
        )


def test_bframe_section_table_must_tile_exactly():
    u = np.arange(4, dtype=np.int64)
    header_short = json.dumps(
        {"op": "solve", SECTIONS_KEY: [["u", "<i8", 3]]},
        separators=(",", ":"),
    ).encode()
    header_long = json.dumps(
        {"op": "solve", SECTIONS_KEY: [["u", "<i8", 5]]},
        separators=(",", ":"),
    ).encode()
    for header in (header_short, header_long):
        with pytest.raises(FrameError):
            _read_bytes(_raw_bframe(header, u.tobytes()))


def test_bframe_declared_lengths_bounded_before_allocation():
    # A corrupt/adversarial header must not size an allocation: the
    # declared byte counts are checked against max_bytes FIRST...
    with pytest.raises(FrameError):
        _read_bytes(
            b"B20 999999999999 00000000\n", max_bytes=64 * 1024
        )
    # ...and an honest-but-oversize frame respects a caller's tighter cap
    # (max_bytes extends to the section declarations, not just the header).
    data = _sample_bframe()
    with pytest.raises(FrameError):
        _read_bytes(data, max_bytes=100)
    # Section-table counts are validated against bytes ALREADY read, so a
    # huge count in a tiny frame is a cheap typed error, not an allocation.
    header = json.dumps(
        {"op": "solve", SECTIONS_KEY: [["u", "<i8", 10**12]]},
        separators=(",", ":"),
    ).encode()
    with pytest.raises(FrameError):
        _read_bytes(_raw_bframe(header, b"\x00" * 16))


def test_bframe_rejects_unknown_dtype_and_bad_tables():
    u = np.arange(2, dtype=np.int64)
    bad_headers = [
        # dtype outside the closed whitelist must never size anything.
        {"op": "solve", SECTIONS_KEY: [["u", "<c16", 1]]},
        # malformed entry shapes
        {"op": "solve", SECTIONS_KEY: [["u", "<i8"]]},
        {"op": "solve", SECTIONS_KEY: [[1, "<i8", 2]]},
        {"op": "solve", SECTIONS_KEY: [["u", "<i8", -1]]},
        {"op": "solve", SECTIONS_KEY: [["u", "<i8", True]]},
        # duplicate section names
        {"op": "solve", SECTIONS_KEY: [["u", "<i8", 1], ["u", "<i8", 1]]},
        # a table longer than _MAX_SECTIONS is garbage, not a graph
        {"op": "solve",
         SECTIONS_KEY: [[f"s{i}", "<u1", 0] for i in range(65)]
         + [["u", "<i8", 2]]},
    ]
    for head in bad_headers:
        header = json.dumps(head, separators=(",", ":")).encode()
        with pytest.raises(FrameError):
            _read_bytes(_raw_bframe(header, u.tobytes()))
    # Section bytes with no table to claim them: frame-alignment is gone.
    header = json.dumps({"op": "solve"}, separators=(",", ":")).encode()
    with pytest.raises(FrameError):
        _read_bytes(_raw_bframe(header, u.tobytes()))
    # A header that is not JSON at all.
    with pytest.raises(FrameError):
        _read_bytes(_raw_bframe(b"not json", b""))
