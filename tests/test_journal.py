"""Router journal + shared WAL core: durability, corruption recovery,
compaction, and the replay state machine (all jax-free).

The corruption matrix mirrors the ``stream/log.py`` test patterns the
core was factored from: a torn tail costs at most the torn record, a
corrupt mid-log line stops the chain there (longest valid prefix — never
a splice across the gap), and whatever the prefix says was accepted but
not answered is exactly what a restarted router must re-queue.
"""

import json
import os

import pytest

from distributed_ghs_implementation_tpu.fleet.journal import (
    JOURNAL_SCHEMA,
    RouterJournal,
)
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.utils.wal import JsonlWal


@pytest.fixture(autouse=True)
def _clean_global_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.enable()
    BUS.clear()


# ----------------------------------------------------------------------
# JsonlWal: the factored core
# ----------------------------------------------------------------------
def _wal(tmp_path, name="w.jsonl"):
    return JsonlWal(
        str(tmp_path / name), schema="test-wal-v1", counter_prefix="test.wal"
    )


def test_wal_append_read_round_trip(tmp_path):
    wal = _wal(tmp_path)
    for i in range(5):
        wal.append({"seq": i, "payload": f"p{i}"})
    entries, torn = wal.read()
    assert torn == 0
    assert [e["seq"] for e in entries] == list(range(5))
    assert wal.tail()["payload"] == "p4"


def test_wal_seals_torn_tail_before_next_append(tmp_path):
    wal = _wal(tmp_path)
    wal.append({"seq": 0})
    with open(wal.path, "ab") as f:
        f.write(b'{"schema": "test-wal-v1", "seq": 1, "tru')  # crash mid-append
    wal.append({"seq": 2})
    entries, _torn = wal.read()
    # The torn record is skipped; the sealed append after it parses fine.
    assert [e["seq"] for e in entries] == [0, 2]
    assert BUS.counters().get("test.wal.sealed_torn") == 1


def test_wal_skips_corrupt_midlog_lines_and_counts(tmp_path):
    wal = _wal(tmp_path)
    for i in range(4):
        wal.append({"seq": i})
    lines = open(wal.path).read().splitlines()
    lines[1] = "garbage{{{not json"
    lines[2] = json.dumps({"schema": "some-other-schema", "seq": 2})
    with open(wal.path, "w") as f:
        f.write("\n".join(lines) + "\n")
    entries, torn = wal.read()
    assert [e["seq"] for e in entries] == [0, 3]
    assert BUS.counters().get("test.wal.corrupt_line") == 2
    assert torn == 0


def test_wal_rewrite_is_atomic_replacement(tmp_path):
    wal = _wal(tmp_path)
    for i in range(6):
        wal.append({"seq": i})
    wal.rewrite([{"seq": 9}])
    entries, _ = wal.read()
    assert [e["seq"] for e in entries] == [9]
    assert not os.path.exists(wal.path + ".tmp")


# ----------------------------------------------------------------------
# RouterJournal: the replay state machine
# ----------------------------------------------------------------------
def test_journal_round_trip_rebuilds_router_state(tmp_path):
    j = RouterJournal(str(tmp_path))
    jid1 = j.accept({"op": "solve", "digest": "a"}, key="a", cls="hit")
    jid2 = j.accept({"op": "update", "digest": "b"}, key="b", cls=None,
                    lane=True)
    j.ring("add", 0)
    j.ring("add", 1, addr="127.0.0.1:9")
    j.answer(jid1, ok=True, worker=1, digest="a")
    j.pin("b2", 0, prev="b")
    j.scale({"action": "up", "at": 123.0})

    state = RouterJournal(str(tmp_path)).load()
    assert state.had_state
    assert list(state.unanswered) == [jid2]
    assert state.unanswered[jid2]["req"]["op"] == "update"
    assert state.unanswered[jid2]["lane"] is True
    assert state.pins == {"b2": 0}
    assert state.served == {"a": 1}
    assert state.members[1]["addr"] == "127.0.0.1:9"
    assert state.last_scale["action"] == "up"
    assert state.next_jid == jid2 + 1


def test_journal_ring_remove_drops_dead_workers_pins_and_affinity(tmp_path):
    j = RouterJournal(str(tmp_path))
    a = j.accept({"op": "solve"}, key="a", cls=None)
    j.answer(a, ok=True, worker=0, digest="a")
    j.pin("s", 0)
    j.pin("t", 1)
    j.ring("remove", 0)  # worker 0 died: its warm copies died with it
    state = RouterJournal(str(tmp_path)).load()
    assert state.pins == {"t": 1}
    assert state.served == {}
    assert not state.members[0]["retired"]  # dead, not retired: restartable
    j.ring("retire", 1)
    state = RouterJournal(str(tmp_path)).load()
    assert state.members[1]["retired"]
    assert state.pins == {}


def test_journal_accept_is_durable_before_return(tmp_path):
    # The gating property: once accept() returns, a fresh process sees it.
    j = RouterJournal(str(tmp_path))
    jid = j.accept({"op": "solve", "digest": "q"}, key="q", cls="gold")
    state = RouterJournal(str(tmp_path)).load()
    assert jid in state.unanswered
    assert state.unanswered[jid]["cls"] == "gold"


def test_journal_checkpoint_compacts_but_keeps_unanswered(tmp_path):
    j = RouterJournal(str(tmp_path), checkpoint_every=8)
    keep = j.accept({"op": "solve", "digest": "keep"}, key="keep", cls=None)
    for i in range(12):  # crosses the checkpoint cadence
        jid = j.accept({"op": "solve", "digest": f"d{i}"}, key=f"d{i}",
                       cls=None)
        j.answer(jid, ok=True, worker=0, digest=f"d{i}")
    lines = open(j.path).read().splitlines()
    assert len(lines) < 12  # compacted: answered accepts are gone
    assert json.loads(lines[0])["t"] == "checkpoint"
    state = RouterJournal(str(tmp_path)).load()
    assert keep in state.unanswered  # the orphan rode inside the checkpoint
    assert state.served["d11"] == 0
    assert BUS.counters().get("fleet.router.journal.compact", 0) >= 1


# ----------------------------------------------------------------------
# Satellite: the torn-tail and mid-log corruption matrix
# ----------------------------------------------------------------------
def _journal_with_orphans(tmp_path, n=6):
    """n accepts, even jids answered — so odd ones are the re-queue set."""
    j = RouterJournal(str(tmp_path))
    jids = []
    for i in range(n):
        jid = j.accept({"op": "solve", "digest": f"g{i}"}, key=f"g{i}",
                       cls=None)
        jids.append(jid)
        if i % 2 == 0:
            j.answer(jid, ok=True, worker=i % 3, digest=f"g{i}")
    return j, jids


@pytest.mark.parametrize("cut", [1, 7, 23])
def test_journal_torn_tail_recovers_all_but_the_torn_record(tmp_path, cut):
    j, jids = _journal_with_orphans(tmp_path)
    raw = open(j.path, "rb").read()
    lines = raw.splitlines(keepends=True)
    # Crash mid-append: the last record is cut `cut` bytes in.
    torn = b"".join(lines[:-1]) + lines[-1][: min(cut, len(lines[-1]) - 1)]
    with open(j.path, "wb") as f:
        f.write(torn)
    state = RouterJournal(str(tmp_path)).load()
    # The last record was `answer(jid 5 is odd -> no)`... recompute: the
    # final line is whatever _journal_with_orphans wrote last (an answer
    # for jid 5? jids are 1-based and i=5 is odd: an accept). Torn = that
    # accept never happened; everything before it replays.
    assert state.had_state
    assert state.dropped == 0  # a torn tail is not a chain break
    full = RouterJournal(str(tmp_path))
    # The journal stays appendable after recovery (seal + chain continue).
    full.load()
    jid = full.accept({"op": "solve", "digest": "post"}, key="post", cls=None)
    state2 = RouterJournal(str(tmp_path)).load()
    assert jid in state2.unanswered


def test_journal_midlog_corruption_recovers_longest_valid_prefix(tmp_path):
    j, jids = _journal_with_orphans(tmp_path, n=6)
    lines = open(j.path).read().splitlines()
    # Corrupt the 4th record: everything from there on is untrusted.
    lines[3] = lines[3][: len(lines[3]) // 2] + "#corrupt#"
    with open(j.path, "w") as f:
        f.write("\n".join(lines) + "\n")
    state = RouterJournal(str(tmp_path)).load()
    assert state.had_state
    assert state.dropped > 0
    assert BUS.counters().get("fleet.router.journal.chain_broken") == 1
    # The prefix (records 0-2: accept g0, answer g0, accept g1) replays;
    # the unanswered set from the prefix is exactly the re-queue set.
    assert state.served == {"g0": 0}
    assert jids[1] in state.unanswered
    # Nothing past the break leaked into the state.
    assert all(a["req"]["digest"] != "g5" for a in state.unanswered.values())


def test_journal_non_utf8_corruption_is_a_gap_not_a_crash(tmp_path):
    # Bitrot bytes >= 0x80 must decode as replacement garbage (an
    # unparsable, chain-breaking line), never raise UnicodeDecodeError
    # out of load() — that would make the VALID prefix unrecoverable too.
    j, jids = _journal_with_orphans(tmp_path, n=6)
    raw = open(j.path, "rb").read()
    lines = raw.split(b"\n")
    lines[3] = lines[3][:4] + b"\xff\xfe\x80" + lines[3][7:]
    with open(j.path, "wb") as f:
        f.write(b"\n".join(lines))
    state = RouterJournal(str(tmp_path)).load()
    assert state.had_state and state.dropped > 0
    assert state.served == {"g0": 0}  # the prefix before the rot replays


def test_journal_close_refuses_appends_synchronously(tmp_path):
    # crash() closes the journal: an append after close raises OSError
    # (the router turns it into a retryable router_crashed error) rather
    # than racing a successor that already loaded the file.
    j = RouterJournal(str(tmp_path))
    j.accept({"op": "solve"}, key="a", cls=None)
    j.close()
    with pytest.raises(OSError, match="closed"):
        j.accept({"op": "solve"}, key="b", cls=None)
    state = RouterJournal(str(tmp_path)).load()
    assert len(state.unanswered) == 1  # only the pre-close accept exists


def test_journal_seq_gap_is_a_chain_break(tmp_path):
    j, _jids = _journal_with_orphans(tmp_path, n=6)
    lines = open(j.path).read().splitlines()
    del lines[2]  # a vanished record: the suffix no longer follows
    with open(j.path, "w") as f:
        f.write("\n".join(lines) + "\n")
    state = RouterJournal(str(tmp_path)).load()
    assert state.dropped == len(lines) - 2
    assert BUS.counters().get("fleet.router.journal.chain_broken") == 1


def test_journal_schema_stamp(tmp_path):
    j = RouterJournal(str(tmp_path))
    j.accept({"op": "solve"}, key=None, cls=None)
    rec = json.loads(open(j.path).read().splitlines()[0])
    assert rec["schema"] == JOURNAL_SCHEMA
