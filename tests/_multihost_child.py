"""Child process for the 2-process jax.distributed test (see test_multihost.py).

Usage: python _multihost_child.py <coordinator> <num_processes> <process_id> <outdir>

Initializes the distributed runtime through ``parallel.multihost`` (the env-var
names the SLURM launcher exports), builds a mesh spanning both processes, runs
the sharded solve, and writes what it saw to ``<outdir>/proc<id>.json``.
"""

import json
import os
import sys


def main() -> int:
    coordinator, num_processes, process_id, outdir = sys.argv[1:5]
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()
    # Exercise the launcher env-var path of multihost.initialize().
    os.environ["JAX_COORDINATOR_ADDRESS"] = coordinator
    os.environ["JAX_NUM_PROCESSES"] = num_processes
    os.environ["JAX_PROCESS_ID"] = process_id

    from distributed_ghs_implementation_tpu.parallel import multihost

    multihost.initialize()

    import jax

    jax.config.update("jax_platforms", "cpu")
    assert jax.process_count() == int(num_processes), jax.process_count()

    from distributed_ghs_implementation_tpu.graphs.generators import (
        erdos_renyi_graph,
    )
    from distributed_ghs_implementation_tpu.parallel.mesh import edge_mesh
    from distributed_ghs_implementation_tpu.parallel.sharded import (
        solve_graph_sharded,
    )
    from distributed_ghs_implementation_tpu.utils.verify import networkx_mst_weight

    g = erdos_renyi_graph(120, 0.08, seed=33)
    mesh = edge_mesh()  # spans all 4 devices across both processes
    edge_ids, fragment, levels = solve_graph_sharded(g, mesh=mesh, strategy="ell")
    weight = int(g.w[edge_ids].sum())

    # The rank-space fast path, multi-process: packed all-gather harvest.
    # Both the plain head and the filter-Kruskal split must produce the
    # byte-identical MST on every process.
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )

    rank_ids, _, _ = solve_graph_sharded(g, mesh=mesh, strategy="rank")
    filt_ids, _, _ = solve_graph_rank_sharded(g, mesh=mesh, filtered=True)
    # Split-key rank64 program across two real processes (the 2^31+-rank
    # device program at test width; its two-pmin combine and local-crank
    # finish must agree with the int32 path on every process).
    r64_ids, _, _ = solve_graph_rank_sharded(g, mesh=mesh, rank64=True)

    # Checkpointed sharded solve with PER-PROCESS checkpoint dirs (the
    # non-shared-filesystem shape): only the primary writes; the resume
    # decision + state must come from the primary via broadcast, not from
    # local os.path.exists — a divergent decision would hang the pod.
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        solve_graph_checkpointed_sharded,
    )

    ckdir = os.path.join(outdir, f"ck{process_id}")
    os.makedirs(ckdir, exist_ok=True)
    ck = os.path.join(ckdir, "shard.npz")
    ck_ids, _, _ = solve_graph_checkpointed_sharded(g, ck, mesh=mesh, filtered=True)
    ck_ids2, _, _ = solve_graph_checkpointed_sharded(g, ck, mesh=mesh, filtered=True)
    record = {
        "process_id": int(process_id),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "is_primary": multihost.is_primary(),
        "mst_weight": weight,
        "mst_edges": len(edge_ids),
        "levels": int(levels),
        "expected_weight": float(networkx_mst_weight(g)),
        "rank_edge_ids": [int(x) for x in rank_ids],
        "filtered_edge_ids": [int(x) for x in filt_ids],
        "rank64_edge_ids": [int(x) for x in r64_ids],
        "ckpt_edge_ids": [int(x) for x in ck_ids],
        "ckpt_resume_edge_ids": [int(x) for x in ck_ids2],
        "ckpt_file_exists": os.path.exists(ck),
    }
    with open(os.path.join(outdir, f"proc{process_id}.json"), "w") as f:
        json.dump(record, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
