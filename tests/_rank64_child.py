"""Child process for the rank64 (split-key) validation test.

Runs in its own interpreter so the forced virtual-CPU device count can't
collide with the suite's backend state. Validates, on the virtual 8-device
CPU mesh at forced-small width:

  * the split-key plain sharded path (``rank64=True``) lands byte-identical
    to the int32 sharded path and the single-chip rank solve, on a dense
    RMAT graph, a high-diameter grid, and a thinned (disconnected) grid;
  * the capacity-guard loop under split keys (tiny gather budget);
  * ``first_ranks64`` agrees with ``first_ranks`` under sentinel remap.

The device program is all-int32 (ranks travel as (shard, local) pairs), so
no x64 flag is involved — the same program that runs at 2^31+ ranks runs
here, only with smaller shard ids and offsets. Exits 0 on success.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import numpy as np  # noqa: E402


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from distributed_ghs_implementation_tpu.graphs.generators import (
        rmat_graph,
        road_grid_graph,
    )
    from distributed_ghs_implementation_tpu.models.rank_solver import (
        solve_graph_rank,
    )
    from distributed_ghs_implementation_tpu.parallel import rank_sharded as rsh

    assert len(jax.devices()) == 8, jax.devices()

    for g, name in (
        (rmat_graph(11, 16, seed=9), "rmat11"),
        (road_grid_graph(40, 40, seed=9), "grid40"),
        (road_grid_graph(32, 32, seed=3, keep_prob=0.7), "sparse-forest"),
    ):
        ref, ref_frag, _ = solve_graph_rank(g)
        ids32, _, _ = rsh.solve_graph_rank_sharded(g, rank64=False)
        ids64, frag64, _ = rsh.solve_graph_rank_sharded(g, rank64=True)
        assert np.array_equal(ids64, ref), f"{name}: rank64 != single-chip"
        assert np.array_equal(ids64, ids32), f"{name}: rank64 != rank32"
        assert np.unique(frag64).size == np.unique(ref_frag).size, name

    # Capacity-guard loop under split keys (in-place sharded levels).
    rsh._FINISH_GATHER_MAX_SLOTS = 64
    g = road_grid_graph(40, 40, seed=9)
    ref, _, _ = solve_graph_rank(g)
    ids, _, _ = rsh.solve_graph_rank_sharded(g, rank64=True)
    assert np.array_equal(ids, ref), "rank64 capacity-guard diverged"

    # first_ranks64 == first_ranks with the sentinel remapped (isolated
    # vertices present: num_nodes exceeds the largest endpoint).
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph

    gi = Graph.from_arrays(
        12,
        np.array([0, 1, 5, 3]),
        np.array([1, 2, 6, 5]),
        np.array([4, 1, 9, 2]),
    )
    fr32 = gi.first_ranks.astype(np.int64)
    fr32 = np.where(
        fr32 == np.iinfo(np.int32).max, np.iinfo(np.int64).max, fr32
    )
    assert np.array_equal(gi.first_ranks64, fr32), "first_ranks64 mismatch"

    print("rank64 child ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
