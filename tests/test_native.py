"""Native ingestion library: build, bindings, and NumPy-fallback parity."""


import numpy as np
import pytest

from distributed_ghs_implementation_tpu.graphs import native
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native toolchain unavailable"
)


def test_rmat_canonical_and_deduped():
    u, v, w, n = native.rmat_edges(10, 8, seed=3)
    assert n == 1024
    assert (u < v).all()
    codes = u * n + v
    assert np.unique(codes).size == codes.size
    assert w.min() >= 1 and w.max() <= 255


def test_rmat_deterministic():
    a = native.rmat_edges(9, 8, seed=5)
    b = native.rmat_edges(9, 8, seed=5)
    assert all(np.array_equal(x, y) for x, y in zip(a[:3], b[:3]))
    c = native.rmat_edges(9, 8, seed=6)
    assert not np.array_equal(a[0], c[0])


def test_rmat_graph_native_routing_solves():
    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.utils.verify import verify_result

    g = rmat_graph(16, 4, seed=7, use_native=True)
    assert g.num_nodes == 1 << 16
    r = minimum_spanning_forest(g)
    assert verify_result(r, oracle="scipy").ok


def test_dedup_edges_keeps_min_weight():
    lib = native.get_lib()
    u = np.array([3, 1, 1, 2, 2], dtype=np.int64)
    v = np.array([3, 2, 2, 1, 4], dtype=np.int64)  # (3,3) loop; (1,2) x3
    w = np.array([9, 5, 2, 7, 4], dtype=np.int64)
    kept = int(lib.dedup_edges(5, 5, native._ptr(u), native._ptr(v), native._ptr(w)))
    assert kept == 2
    assert u[:kept].tolist() == [1, 2]
    assert v[:kept].tolist() == [2, 4]
    assert w[:kept].tolist() == [2, 4]  # min weight of the (1,2) triplicate


def test_dimacs_native_matches_python(tmp_path):
    from distributed_ghs_implementation_tpu.graphs.io import read_dimacs

    p = tmp_path / "toy.gr"
    p.write_text(
        "c toy\np sp 4 8\n"
        "a 1 2 5\na 2 1 5\na 2 3 2\na 3 2 2\na 3 4 7\na 4 3 7\na 1 4 1\na 4 1 1\n"
    )
    u, v, w, n = native.read_dimacs_native(str(p))
    assert n == 4 and u.size == 8
    g_native = Graph.from_arrays(n, u, v, w)
    g_py = read_dimacs(str(p))
    assert g_native.edge_triples() == g_py.edge_triples()


def test_csr_native():
    u = np.array([0, 0, 1], dtype=np.int64)
    v = np.array([1, 2, 2], dtype=np.int64)
    w = np.array([5, 6, 7], dtype=np.int64)
    indptr, adj, adjw = native.build_csr_native(3, u, v, w)
    assert indptr.tolist() == [0, 2, 4, 6]
    assert sorted(adj[0:2].tolist()) == [1, 2]
    assert sorted(adjw[4:6].tolist()) == [6, 7]


def test_first_rank_i32_out64_matches_first_ranks64():
    """The rank64 staging's endpoint-reusing native pass must agree with
    the Graph.first_ranks64 property (which re-gathers from u/v)."""
    import numpy as np

    from distributed_ghs_implementation_tpu.graphs import native
    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph

    if not native.native_available():
        import pytest

        pytest.skip("native library unavailable")
    g = rmat_graph(9, 8, seed=6)
    ra, rb = g.rank_endpoints()
    out = native.first_rank_i32_out64_native(g.num_nodes, ra, rb)
    assert np.array_equal(out, g.first_ranks64)


def test_kruskal_native_oracle_parity():
    """The native Kruskal oracle must agree with NetworkX and SciPy on
    connected, disconnected, and negative-weight graphs."""
    import numpy as np

    from distributed_ghs_implementation_tpu.graphs import native
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
    from distributed_ghs_implementation_tpu.graphs.generators import (
        erdos_renyi_graph,
        rmat_graph,
        road_grid_graph,
    )
    from distributed_ghs_implementation_tpu.utils.verify import (
        native_mst_weight,
        networkx_mst_weight,
        scipy_mst_weight,
    )

    if not native.native_available():
        import pytest

        pytest.skip("native library unavailable")
    rng = np.random.default_rng(3)
    neg = Graph.from_arrays(
        40,
        rng.integers(0, 40, 160),
        rng.integers(0, 40, 160),
        rng.integers(-50, 50, 160),
    )
    for g in (
        erdos_renyi_graph(120, 0.06, seed=7),
        rmat_graph(10, 8, seed=5),
        road_grid_graph(20, 20, seed=2, keep_prob=0.6),  # disconnected
        neg,
    ):
        w = native_mst_weight(g)
        assert w is not None
        assert w == networkx_mst_weight(g)
        assert abs(w - scipy_mst_weight(g)) < 1e-6


def test_kruskal_native_rejects_corrupt_order():
    """The native Kruskal oracle validates the order it is handed (it is
    the same order the solver consumes, so trusting it would make the
    check circular): non-permutations and weight-order violations raise,
    and verify's wrapper falls back to SciPy."""
    import numpy as np
    import pytest

    from distributed_ghs_implementation_tpu.graphs import native
    from distributed_ghs_implementation_tpu.graphs.generators import (
        erdos_renyi_graph,
    )
    from distributed_ghs_implementation_tpu.utils.verify import (
        native_mst_weight,
        scipy_mst_weight,
    )

    if not native.native_available():
        pytest.skip("native library unavailable")
    g = erdos_renyi_graph(60, 0.1, seed=4)
    order = g._rank_order.copy()
    # Duplicate an index (not a permutation).
    bad = order.copy()
    bad[1] = bad[0]
    with pytest.raises(ValueError, match="non-decreasing permutation"):
        native.kruskal_msf_native(g.num_nodes, bad, g.u, g.v, g.w)
    # Break the weight order.
    bad2 = order[::-1].copy()
    if not np.all(np.diff(g.w[bad2]) >= 0):  # reversed order is decreasing
        with pytest.raises(ValueError, match="non-decreasing permutation"):
            native.kruskal_msf_native(g.num_nodes, bad2, g.u, g.v, g.w)
    # verify-level fallback: corrupt the cached order on the graph; the
    # wrapper must return the SciPy answer, not garbage.
    g.__dict__["_rank_order"] = bad
    w = native_mst_weight(g)
    assert w is None or abs(w - scipy_mst_weight(g)) < 1e-6


@pytest.mark.parametrize("seed", range(4))
def test_host_backend_byte_identical(seed):
    """backend='host' (native Kruskal solve) must produce the byte-identical
    MSF edge set and component structure as the device backend."""
    import numpy as np

    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.graphs import native
    from distributed_ghs_implementation_tpu.graphs.generators import (
        erdos_renyi_graph,
        rmat_graph,
        road_grid_graph,
    )

    if not native.native_available():
        pytest.skip("native library unavailable")
    graphs = [
        erdos_renyi_graph(150, 0.05, seed=seed),
        rmat_graph(10, 8, seed=seed),
        road_grid_graph(25, 25, seed=seed, keep_prob=0.7),
    ]
    for g in graphs:
        rh = minimum_spanning_forest(g, backend="host")
        rd = minimum_spanning_forest(g, backend="device")
        assert np.array_equal(rh.edge_ids, rd.edge_ids)
        assert rh.num_components == rd.num_components
        assert rh.total_weight == rd.total_weight


def test_fused_endpoint_planes_parity():
    """The fused endpoints+planes pass must emit the identical int32 arrays
    and the identical wire buffer as the two-step gather-then-pack form."""
    import numpy as np

    from distributed_ghs_implementation_tpu.graphs import native
    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.models.rank_solver import (
        _bucket_size,
    )

    if not native.native_available():
        pytest.skip("native library unavailable")
    g = rmat_graph(11, 8, seed=7)
    m_pad = _bucket_size(g.num_edges)
    ra_ref, rb_ref = g.rank_endpoints(pad_to=m_pad)
    ra, rb, planes = native.rank_endpoints_i32_planes_native(
        g._rank_order, g.u, g.v, m_pad
    )
    assert np.array_equal(ra, ra_ref) and np.array_equal(rb, rb_ref)
    ref_planes = np.empty(6 * m_pad, dtype=np.uint8)
    for i, (arr, base) in enumerate(((ra_ref, 0), (rb_ref, 3 * m_pad))):
        b_ = arr.view(np.uint8)
        for k in range(3):
            ref_planes[base + k * m_pad:base + (k + 1) * m_pad] = b_[k::4]
    assert np.array_equal(planes, ref_planes)
