"""Deterministic discrete-event transport for the GHS protocol.

The reference's transports — per-thread ``queue.Queue`` with requeue caps
(``/root/reference/ghs_implementation.py:82-116``) and MPI ``iprobe``/``recv``
with deferred lists (``ghs_implementation_mpi.py:94-115,696-701``) — are both
sources of nondeterminism and the reason its liveness heuristics exist. This
transport is a single priority queue keyed ``(deliver_time, sequence)``:
identical runs deliver identical orders, deferred messages are redelivered at
a strictly later time, and quiescence (empty queue) is *exact* termination
detection — no idle counters, no polling (contrast
``ghs_implementation.py:442-526``).
"""

from __future__ import annotations

import heapq
import itertools
from typing import Dict

from distributed_ghs_implementation_tpu.protocol.messages import Message


class SimTransport:
    """Event-queue message delivery with per-hop latency.

    ``latency`` may be a constant or a ``(src, dst) -> int`` callable, letting
    tests model asymmetric links and delivery races deterministically.
    """

    def __init__(self, latency=1, *, defer_delay: int = 1, max_events: int = 50_000_000):
        self._queue: list = []
        self._seq = itertools.count()
        self._latency = latency if callable(latency) else (lambda s, d: latency)
        self._defer_delay = defer_delay
        self._max_events = max_events
        self.now = 0
        self.messages_sent = 0
        self.messages_deferred = 0

    def send(self, src: int, dst: int, msg: Message) -> None:
        self.messages_sent += 1
        when = self.now + max(1, self._latency(src, dst))
        heapq.heappush(self._queue, (when, next(self._seq), dst, msg))

    def run(self, nodes: Dict[int, "GHSNode"]) -> int:
        """Drain the queue to quiescence; returns events processed."""
        processed = 0
        iterations = 0
        while self._queue:
            iterations += 1  # counts deferrals too, so livelock still trips the guard
            if iterations >= self._max_events:
                raise RuntimeError(
                    f"protocol did not quiesce within {self._max_events} events"
                )
            when, _, dst, msg = heapq.heappop(self._queue)
            self.now = max(self.now, when)
            if nodes[dst].handle(msg):
                processed += 1
            else:
                # Protocol-mandated deferral: redeliver strictly later.
                self.messages_deferred += 1
                heapq.heappush(
                    self._queue,
                    (self.now + self._defer_delay, next(self._seq), dst, msg),
                )
        return processed
