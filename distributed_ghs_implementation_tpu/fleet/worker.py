"""Fleet worker process: one MSTService behind a framed channel.

Spawned by :class:`fleet.router.FleetRouter` as
``python -m distributed_ghs_implementation_tpu.fleet.worker --worker-id K``
— over stdin/stdout pipes (the single-host default), or over TCP
(``fleet/transport.py``): ``--connect HOST:PORT`` dials into the router's
listener and registers with a hello frame; ``--listen [HOST:]PORT`` serves
a socket an off-host router dials (the ``--fleet-workers host:port`` remote
topology). Each worker owns a full serving stack — its own lane engine,
warm-bucket cache, obs bus, and solve scheduler — and shares only the
*persistent* layers with same-host siblings: the on-disk result store
(flock-serialized writes, ``serve/store.py``) and the machine-fingerprinted
XLA compile cache. Across hosts nothing is shared — the router's
cache-miss forwarding hop covers that gap (``docs/FLEET.md``).

Inbound frames (``fleet/framing.py``):

* ``{"id": N, "req": {...}}`` — one service request; the response frame
  ``{"id": N, "resp": {...}, "t": seconds}`` may be written out of order
  (requests run on a small thread pool so the batch engine can coalesce
  lane-mates); ``t`` is the in-worker service time, which lets the router
  compute the pure transport+queueing hop latency per request.
* ``{"ping": S}`` — heartbeat; answered ``{"pong": S}`` inline from the
  read loop, so a worker busy solving still proves its process is alive
  (busy is not dead — only a wedged or exited process misses heartbeats,
  and over TCP that silence is what expires the router-side lease).
* ``{"arm": {"site": ..., "times": T, "kind": ...}}`` — arm the in-process
  :data:`~distributed_ghs_implementation_tpu.utils.resilience.FAULTS`
  registry (kill drills arm ``fleet.worker.crash`` mid-traffic this way).
* ``{"drain": true}`` (or channel EOF in pipe/connect mode, or SIGTERM) —
  graceful drain: stop reading, finish every in-flight request, flush the
  responses, export the obs JSONL (``--obs-jsonl``), and exit 0. In
  ``--listen`` mode a *connection loss without drain* instead returns the
  worker to ``accept()`` with its caches and sessions intact — the router
  re-dials and the worker rejoins warm.

The hello/ready frame (one builder for every medium,
``transport.build_hello``) carries the protocol version and the worker's
capability flags — ``lane`` (owns a mesh-sharded oversize lane),
``stream`` (durable stream log attached), ``kernel`` (level-kernel
choice), ``warmed`` (the elastic fleet's warm-handoff gate) — so the
router learns everything routing needs in one place. ``warmed`` is
truthful *by ordering*: the hello is only built after
:func:`_build_service` returns, which means the service exists, the
persistent compile cache is attached, and any warmup ladder has already
run — a joining worker that advertises ``warmed`` cannot serve a cold
p99. ``GHS_FLEET_COLD_HELLO=1`` is the test hook that advertises cold
anyway, to prove the router's refuse-a-cold-joiner path end to end.

The ``fleet.worker.crash`` fault site is consulted once per request,
*before* it is handled: when the armed shot count reaches zero the process
dies via ``os._exit`` — no response, no flushing, no atexit — which is
exactly the crash the router's zero-lost-query re-queue path must absorb.
``GHS_FAULT_FLEET_WORKER_CRASH=K`` in a worker's environment therefore
means "die in place of answering the K-th request".
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import signal
import socket
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from distributed_ghs_implementation_tpu.fleet.transport import (
    PipeTransport,
    SocketTransport,
    Transport,
    build_hello,
    parse_hostport,
)
from distributed_ghs_implementation_tpu.obs import tracing
from distributed_ghs_implementation_tpu.obs.events import BUS

CRASH_SITE = "fleet.worker.crash"
#: Armed with kind="slow", stalls the worker's next request INSIDE its
#: pool thread for `value` seconds — a deterministic stand-in for a long
#: oversize solve. The read loop keeps answering pings throughout (pongs
#: are out-of-band by construction), which is exactly what the
#: busy-is-not-dead lease test pins: `fleet.lease.expired` must never
#: fire on a healthy-but-busy worker.
SLOW_SITE = "fleet.worker.slow"
CRASH_EXIT_CODE = 17  # distinguishable from drain (0) and tracebacks (1)


class _DrainSignal(Exception):
    """Raised in the read loop by the SIGTERM/SIGINT handlers."""


class EchoService:
    """A jax-free stand-in service for fleet plumbing tests.

    Answers the same ops as :class:`serve.service.MSTService` with canned
    content: solves echo a digest derived from the request payload (and
    remember it, so ``cached_only`` probes answer hit/miss honestly — the
    forwarding drills need that), updates re-key it digest-chained,
    ``sleep_s`` simulates a slow solve. This is what lets
    ``tests/test_fleet.py`` exercise routing, re-queue, shedding,
    heartbeats, forwarding, and drain without compiling a single kernel.
    """

    def __init__(self, worker_id: int):
        self.worker_id = worker_id
        self.handled = 0
        self._served = set()  # digests this worker has "solved" (cached)

    def handle(self, request: dict) -> dict:
        self.handled += 1
        op = request.get("op")
        if request.get("sleep_s"):
            time.sleep(float(request["sleep_s"]))
        if op == "solve":
            digest = request.get("digest") or hashlib.sha256(
                json.dumps(request.get("edges", []), sort_keys=True).encode()
            ).hexdigest()[:32]
            if request.get("cached_only"):
                if digest in self._served:
                    return {"ok": True, "op": "solve", "digest": digest,
                            "source": "cache", "cached": True,
                            "worker": self.worker_id}
                return {"ok": False, "op": "solve", "digest": digest,
                        "cache_miss": True, "worker": self.worker_id,
                        "error": f"cache_miss: {digest} not cached here"}
            self._served.add(digest)
            return {"ok": True, "op": "solve", "digest": digest,
                    "source": "echo", "worker": self.worker_id}
        if op == "update":
            digest = request.get("digest")
            if digest is None:
                return {"ok": False, "op": "update", "error": "no digest"}
            new = hashlib.sha256(
                (digest + json.dumps(request.get("updates", []))).encode()
            ).hexdigest()[:32]
            self._served.add(new)
            return {"ok": True, "op": "update", "digest": new,
                    "prev_digest": digest, "worker": self.worker_id}
        if op == "stats":
            from distributed_ghs_implementation_tpu.obs.events import BUS

            return {"ok": True, "op": "stats",
                    "counters": {"echo.handled": self.handled},
                    "events_dropped": BUS.dropped,
                    "histograms_raw": BUS.histograms_export(),
                    "worker": self.worker_id}
        if op == "shutdown":
            return {"ok": True, "op": "shutdown"}
        return {"ok": False, "op": op, "error": f"unknown op {op!r}"}


def _build_service(args):
    if args.test_echo:
        return EchoService(args.worker_id)
    # Deferred: the echo path must never pay the jax import.
    if args.multihost:
        # A pod-slice worker: bring up the JAX distributed runtime from
        # the standard env (launcher/tpu_pod_worker.sh exports it) BEFORE
        # any other JAX API, so jax.devices() spans the slice and the
        # sharded lane's mesh covers every chip the worker owns.
        from distributed_ghs_implementation_tpu.parallel.multihost import (
            initialize,
        )

        initialize()
    from distributed_ghs_implementation_tpu.batch.warmup import plan_from_flags
    from distributed_ghs_implementation_tpu.serve.service import MSTService
    from distributed_ghs_implementation_tpu.utils.compile_cache import (
        enable_persistent_cache,
    )

    if not args.no_compile_cache:
        # Workers share the persistent XLA cache (machine-fingerprinted):
        # the first worker to compile a bucket pays; its siblings and every
        # restarted incarnation reload the executable.
        enable_persistent_cache(args.compile_cache_dir)
    if args.tune_record:
        # Shared exactly like the compile cache: one offline `cli tune`
        # run's record (machine-fingerprinted, integrity-checked) makes
        # every worker's auto tier measured. Miss/stale installs nothing
        # and the probe heuristic serves — a worker never refuses to boot
        # over a tuning file (tune.record.miss/stale on the bus).
        from distributed_ghs_implementation_tpu.tune.record import (
            load_and_install,
        )

        load_and_install(args.tune_record)
    return MSTService(
        backend=args.backend,
        store_capacity=args.store_capacity,
        disk_dir=args.disk_cache,
        max_concurrent=args.max_concurrent,
        max_sessions=args.max_sessions,
        resolve_threshold=args.resolve_threshold,
        batch_lanes=args.batch_lanes,
        batch_wait_s=args.batch_wait,
        warmup=plan_from_flags(
            buckets=args.warmup_buckets, replay=args.warmup_replay,
            lanes=args.batch_lanes, mesh_buckets=args.warmup_mesh_buckets,
            stream_buckets=args.warmup_stream_buckets,
            tuning=args.tune_record,
        ),
        # -1 = the bare flag: a lane over all of this worker's devices.
        sharded_lane=(True if args.sharded_lane == -1
                      else max(0, args.sharded_lane)),
        stream_dir=args.stream_dir,
        stream_snapshot_every=args.stream_snapshot_every,
        verify=args.verify,
    )


def _hello_for(args, warmup_summary=None) -> dict:
    # The one place capability flags live (routing reads them off the
    # hello; ad-hoc per-feature keys are what this replaces). Called only
    # AFTER _build_service, so "warmed" is a statement of fact: the
    # service — warmup ladder included — already exists.
    caps = {
        "lane": bool(args.sharded_lane),
        "stream": bool(args.stream_dir),
        # Both halves of the fused path: this worker can serve an
        # oversize stream mesh-resident AND rebuild that residency from
        # the shared durable log after a restart (stream/session.py) —
        # what lets the router treat lane workers as interchangeable
        # inheritors for sharded streams.
        "stream_sharded": bool(args.sharded_lane and args.stream_dir),
        "kernel": os.environ.get("GHS_KERNEL", "auto"),
        "verify": args.verify or "off",
    }
    if not args.test_echo:
        # Measured-selection provenance (None = probe heuristic): the
        # stats op shows which workers serve on a TuningRecord and which
        # machine/fingerprint measured it. Echo workers never import jax.
        from distributed_ghs_implementation_tpu.ops.pallas_kernels import (
            tuned_summary,
        )

        caps["tuned"] = tuned_summary()
    if warmup_summary is not None:
        caps["warmup"] = warmup_summary
    return build_hello(
        args.worker_id,
        caps=caps,
        token=args.conn_token,
        warmed=not os.environ.get("GHS_FLEET_COLD_HELLO"),
    )


def _serve_connection(transport: Transport, service, pool) -> str:
    """Drain frames off one channel until drain/EOF; returns ``"drain"``
    (stop the worker) or ``"eof"`` (connection lost; a ``--listen`` worker
    goes back to accept)."""
    from distributed_ghs_implementation_tpu.utils.resilience import FAULTS

    def _serve_one(
        rid: int, request: dict, trace: Optional[dict] = None
    ) -> None:
        shot = FAULTS.pop(CRASH_SITE)
        if shot is not None and shot.remaining == 0:
            os._exit(CRASH_EXIT_CODE)  # a real crash: no response, no flush
        slow = FAULTS.pop(SLOW_SITE)
        if slow is not None and slow.kind == "slow":
            # A long solve, without needing a graph big enough to be one:
            # the stall happens on a pool thread, so the read loop's
            # inline pongs keep flowing — busy, not dead.
            time.sleep(slow.value)
        t0 = time.perf_counter()
        # Re-establish the router's trace context (when the frame carried
        # one) so every span this worker records — serve.*, batch.*,
        # stream.* — shares the router's trace_id; ``fleet.serve`` is the
        # worker-side service-time span the merge subtracts from the
        # router's attempt span to price the transport hop.
        ctx = tracing.from_wire(trace)
        try:
            with tracing.activated(ctx), BUS.span(
                "fleet.serve", cat="fleet", op=request.get("op")
            ):
                response = service.handle(request)
        except Exception as e:  # noqa: BLE001 — the channel must survive
            response = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        try:
            transport.send({
                "id": rid, "resp": response,
                "t": round(time.perf_counter() - t0, 6),
            })
        except OSError:
            pass  # router gone mid-response; it re-queues, we carry on

    try:
        while True:
            frame = transport.recv()
            if frame is None or frame.get("drain"):
                return "drain" if frame else "eof"
            if "ping" in frame:
                try:
                    transport.send({"pong": frame["ping"]})
                except OSError:
                    return "eof"
                continue
            if "arm" in frame:
                arm = frame["arm"]
                FAULTS.arm(
                    arm.get("site", CRASH_SITE),
                    times=int(arm.get("times", 1)),
                    kind=arm.get("kind", "raise"),
                    value=float(arm.get("value", 0.0)),
                )
                continue
            if "req" in frame:
                pool.submit(
                    _serve_one, frame["id"], frame["req"],
                    frame.get("trace"),
                )
    except _DrainSignal:
        return "drain"


def run_worker(args) -> int:
    from distributed_ghs_implementation_tpu.obs.events import BUS

    BUS.enable()
    service = _build_service(args)
    draining = threading.Event()

    def _drain_handler(signum, frame):
        draining.set()
        # Requests run on the pool, so the main (read) thread is always
        # safe to interrupt: stop admitting immediately, then flush.
        raise _DrainSignal()

    try:
        signal.signal(signal.SIGTERM, _drain_handler)
        signal.signal(signal.SIGINT, _drain_handler)
    except ValueError:  # not the main thread (in-process tests)
        pass

    pool = ThreadPoolExecutor(
        max_workers=args.threads, thread_name_prefix=f"worker{args.worker_id}"
    )
    warmup_summary = None
    if not args.test_echo:
        from distributed_ghs_implementation_tpu.batch.warmup import (
            summarize_report,
        )

        warmup_summary = summarize_report(
            getattr(service, "warmup_report", None)
        )
    hello = _hello_for(args, warmup_summary)

    last_transport = None
    try:
        if args.listen:
            host, port = parse_hostport(args.listen, default_host="0.0.0.0")
            server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            server.bind((host, port))
            server.listen(1)
            print(
                f"fleet.worker {args.worker_id}: listening on "
                f"{server.getsockname()[0]}:{server.getsockname()[1]}",
                file=sys.stderr, flush=True,
            )
            # One router connection at a time; a lost connection (router
            # death, network partition) returns to accept with the warm
            # service intact — the re-dialing router gets a warm rejoin,
            # not a cold restart.
            while not draining.is_set():
                try:
                    conn, _addr = server.accept()
                except (OSError, _DrainSignal):
                    break
                transport = last_transport = SocketTransport(conn)
                try:
                    transport.send(hello)
                except OSError:
                    transport.close()
                    continue
                outcome = _serve_connection(transport, service, pool)
                if outcome == "drain":
                    break
                transport.close()
            server.close()
        elif args.connect:
            sock = socket.create_connection(
                parse_hostport(args.connect), timeout=30.0
            )
            sock.settimeout(None)
            transport = last_transport = SocketTransport(sock)
            transport.send(hello)
            _serve_connection(transport, service, pool)
        else:
            transport = last_transport = PipeTransport(
                sys.stdout.buffer, sys.stdin.buffer
            )
            transport.send(hello)
            _serve_connection(transport, service, pool)
    except _DrainSignal:
        pass
    # Drain: everything admitted gets its response flushed before exit 0.
    pool.shutdown(wait=True)
    if last_transport is not None:
        try:
            last_transport.send({"bye": True, "worker": args.worker_id})
        except OSError:
            pass  # router already gone; the drain still completed
        last_transport.close()
    if args.obs_jsonl:
        from distributed_ghs_implementation_tpu.obs.export import (
            write_events_jsonl,
        )

        write_events_jsonl(
            BUS, args.obs_jsonl, label=f"worker{args.worker_id}"
        )
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="fleet.worker", description=__doc__)
    p.add_argument("--worker-id", type=int, required=True)
    p.add_argument("--backend", default="device")
    p.add_argument("--batch-lanes", type=int, default=0)
    p.add_argument("--batch-wait", type=float, default=None)
    p.add_argument("--store-capacity", type=int, default=128)
    p.add_argument("--disk-cache", default=None,
                   help="shared persistent result store directory")
    p.add_argument("--stream-dir", default=None,
                   help="shared durable stream log directory (snapshot + "
                   "WAL per stream; failover replays from here)")
    p.add_argument("--stream-snapshot-every", type=int, default=8,
                   help="windows between stream snapshots")
    p.add_argument("--max-concurrent", type=int, default=2)
    p.add_argument("--max-sessions", type=int, default=32)
    p.add_argument("--resolve-threshold", type=int, default=None)
    p.add_argument("--warmup-replay", default=None)
    p.add_argument("--threads", type=int, default=4,
                   help="request threads (lets the batch engine coalesce)")
    p.add_argument("--warmup-buckets", default=None)
    p.add_argument("--warmup-mesh-buckets", default=None,
                   help="RAW NODESxEDGES oversize workloads to warm on the "
                   "sharded lane before serving")
    p.add_argument("--warmup-stream-buckets", default=None,
                   help="RAW NODESxEDGES subscribed-graph sizes whose "
                   "window kernels warm before serving")
    p.add_argument("--sharded-lane", type=int, nargs="?", const=-1,
                   default=0, metavar="N",
                   help="own a mesh-sharded oversize solve lane over N "
                   "devices (bare flag = all; 0 = off)")
    p.add_argument("--connect", default=None, metavar="HOST:PORT",
                   help="dial into the router's listener over TCP and "
                   "register with a hello frame (spawned network workers)")
    p.add_argument("--listen", default=None, metavar="[HOST:]PORT",
                   help="serve a TCP socket the router dials (remote "
                   "workers addressed via --fleet-workers host:port); a "
                   "lost connection returns to accept with caches warm")
    p.add_argument("--conn-token", default=None,
                   help="dial-in token proving this process belongs to its "
                   "router-assigned slot + incarnation")
    p.add_argument("--multihost", action="store_true",
                   help="initialize the JAX distributed runtime before "
                   "building the service (a pod-slice worker; "
                   "launcher/tpu_pod_worker.sh)")
    p.add_argument("--verify", default=None, metavar="SPEC",
                   help="result verification policy (off|sample|full, or "
                   "per-class 'bulk=full,interactive=sample,default=off' — "
                   "docs/VERIFICATION.md)")
    p.add_argument("--compile-cache-dir", default=None)
    p.add_argument("--no-compile-cache", action="store_true")
    p.add_argument("--tune-record", default=None,
                   help="ghs-tuning-v1 TuningRecord to install (shared "
                        "across workers like the compile cache)")
    p.add_argument("--obs-jsonl", default=None,
                   help="export this worker's bus events here on drain")
    p.add_argument("--test-echo", action="store_true",
                   help="jax-free canned service (fleet plumbing tests)")
    return p


def main(argv=None) -> int:
    return run_worker(build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
