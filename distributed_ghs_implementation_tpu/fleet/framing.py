"""Length-prefixed JSON framing for the router <-> worker channels.

The single-process service speaks newline-delimited JSON (one request per
line, ``serve/service.py``); the fleet cannot: a worker's channel carries
*interleaved* responses written by concurrent request threads, and a torn
line would silently merge two frames. Each frame is therefore::

    <payload-byte-length>\\n<payload>\\n                  # legacy (v1)
    <payload-byte-length> <crc32-hex>\\n<payload>\\n      # checksummed

— the reader knows exactly how many bytes belong to the frame before it
parses a single one, a short read is detected (not mis-parsed), and the
trailing newline keeps frames greppable in a captured channel dump. The
same framing runs over OS pipes (the single-host fleet) and TCP sockets
(``fleet/transport.py``) — a frame is a frame on either medium.

**Payload checksums** (round 19): the optional second header token is the
crc32 of the payload bytes. Length-prefixing alone detects *truncation*
but not *mutation* — a bit-flipped byte inside the payload either breaks
the JSON (caught late, after buffering) or, worse, survives as valid JSON
with a different value. With the checksum, every flipped payload is
rejected at the frame boundary as a typed :class:`FrameError`. Readers
accept both forms unconditionally; writers emit checksums only toward
peers that advertised the ``crc`` capability in their hello (or whose own
frames carried checksums) — the version gate that keeps a mixed-build
fleet compatible (``fleet/transport.py``, ``docs/FLEET.md``).

**Binary frames ("B-frames")**: a third header form carries raw array
sections after a compact JSON header, so a 3000-edge graph crosses the
wire as three contiguous little-endian buffers instead of a Python-list
JSON blob re-parsed on every hop::

    B<header-bytes> <section-bytes> <crc32-hex>\\n<header><sections>\\n

The header is ordinary compact JSON in which the reserved ``_sections``
key (at the top level, or one nesting level down — ``{"id": N, "req":
{...}}`` frames) holds the section table ``[[name, dtype, count], ...]``;
the section bytes follow the header back to back in table order. The
crc32 covers header *and* sections (B-frames are always checksummed —
they only ever go to peers that negotiated ``caps.wire``, which implies
the round-19 checksum support). :func:`read_frame` validates the table
against the declared byte count *before* any allocation beyond the
already-``max_bytes``-bounded payload read, rebuilds a
:class:`WireSections` view over the one received buffer (``np.frombuffer``
— zero copies, zero per-element Python objects), and re-implants it where
the table sat. Writers emit B-frames only toward peers that advertised
the ``wire`` capability in their hello (``fleet/transport.py``); for
legacy peers :func:`fold_sections` lowers the sections back to the
classic JSON fields (``edges`` triples, ``mst_edges`` pairs, plain
lists), so a mixed-build fleet degrades per-connection, transparently.

Error surface: :func:`read_frame` returns ``None`` only on a *clean* EOF
at a frame boundary (the peer closed in between frames — drain, or death)
and raises :class:`FrameError` on everything garbled: a non-numeric or
over-long length prefix, a length past ``max_bytes`` (a corrupt prefix
must not become a multi-gigabyte allocation — the reader sizes its buffer
from attacker/garbage-controlled bytes), a payload the stream could not
complete, a payload failing its declared checksum, bytes that are not
one JSON object, or a section table whose declarations do not tile the
declared section bytes exactly. ``FrameError`` subclasses ``ValueError``,
so callers that treated every framing problem as peer-death (the router's
reader catches ``(OSError, ValueError)``) keep doing so unchanged — the
typed error exists for callers that want to *distinguish* a corrupt peer
from a closed one (tests, the drills, the dial-in hello validation).
Writes must be serialized by the caller (the transports hold a
per-connection write lock).
"""

from __future__ import annotations

import json
import zlib
from typing import IO, Optional

#: A frame larger than this is a protocol violation (a runaway edges_out
#: response, or garbage on the channel) — refuse to buffer it. Callers with
#: tighter expectations (the hello exchange is a few hundred bytes) pass
#: their own ``max_bytes``.
MAX_FRAME_BYTES = 256 * 1024 * 1024

#: The longest legal header is the B-frame form: ``B`` + 9 header digits
#: + space + 9 section digits + space + 8 crc hex digits + newline (29
#: bytes; the legacy forms top out at 19). Anything longer is garbage,
#: and an unbounded ``readline`` on a corrupt stream would buffer until
#: memory runs out.
_MAX_HEADER_BYTES = 32

#: Reserved payload key that carries a :class:`WireSections` (in-memory)
#: or the section table (on the wire). Never a user-facing field name.
SECTIONS_KEY = "_sections"

#: Raw-section element types a B-frame may declare, with byte widths.
#: A closed whitelist: the itemsize must come from this table, never from
#: the wire, or a garbage dtype string sizes an allocation.
_SECTION_DTYPES = {"<i8": 8, "<f8": 8, "<i4": 4, "<f4": 4, "<u1": 1}

#: A section table longer than this is garbage, not a graph.
_MAX_SECTIONS = 64


class FrameError(ValueError):
    """A garbled frame: corrupt length prefix, oversize declaration,
    truncated payload, checksum mismatch, non-JSON bytes, or a binary
    section table that does not tile its declared bytes. The channel
    can no longer be trusted to be frame-aligned — the only safe response
    is to drop it."""


class WireSections:
    """Named contiguous little-endian array sections riding a B-frame.

    Two lives, one class. *Encode side* (:meth:`add`): holds the original
    NumPy arrays and emits their buffers directly onto the wire — no
    intermediate concatenation, no per-element Python objects. *Decode
    side* (:meth:`from_buffer`): holds the ONE buffer ``read_frame``
    received plus the validated ``(dtype, count, offset)`` table;
    :meth:`array` is an ``np.frombuffer`` view into it — zero-copy — and
    :meth:`chunks` returns the raw buffer itself, so a router forwarding
    a B-frame re-emits the section bytes without ever decoding them (the
    opaque-passthrough contract, ``docs/FLEET.md``).
    """

    __slots__ = ("_order", "_specs", "_arrays", "_buf", "_offsets")

    def __init__(self) -> None:
        self._order: list = []  # section names, wire order
        self._specs: dict = {}  # name -> (dtype_str, count)
        self._arrays: dict = {}  # encode side: name -> contiguous ndarray
        self._buf: bytes = b""  # decode side: the received section bytes
        self._offsets: dict = {}  # decode side: name -> byte offset

    # -- encode side ---------------------------------------------------
    def add(self, name: str, arr) -> "WireSections":
        """Attach ``arr`` as section ``name`` (chainable). The array is
        normalized to a C-contiguous little-endian whitelisted dtype; a
        dtype outside the whitelist is a caller bug, not a wire error."""
        import numpy as np

        a = np.ascontiguousarray(arr)
        dt = a.dtype.newbyteorder("<")
        if dt.str not in _SECTION_DTYPES:
            raise ValueError(
                f"section {name!r} dtype {a.dtype.str} not wire-encodable "
                f"(allowed: {sorted(_SECTION_DTYPES)})"
            )
        if a.dtype != dt:
            a = a.astype(dt)
        if a.ndim != 1:
            a = a.reshape(-1)
        if name in self._specs:
            raise ValueError(f"duplicate section {name!r}")
        self._order.append(name)
        self._specs[name] = (dt.str, int(a.shape[0]))
        self._arrays[name] = a
        return self

    # -- decode side ---------------------------------------------------
    @classmethod
    def from_buffer(cls, decl, buf: bytes) -> "WireSections":
        """Rebuild from a wire section table + the received bytes;
        :class:`FrameError` unless the table is well-formed and tiles
        ``buf`` exactly (the bounded-allocation contract: counts are
        checked against bytes already read, never used to size a read)."""
        if not isinstance(decl, list) or len(decl) > _MAX_SECTIONS:
            raise FrameError(
                f"malformed section table: "
                f"{type(decl).__name__} of {len(decl) if isinstance(decl, list) else '?'}"
            )
        self = cls()
        self._buf = buf
        offset = 0
        for entry in decl:
            if not (isinstance(entry, list) and len(entry) == 3):
                raise FrameError(f"malformed section entry: {entry!r}")
            name, dtype, count = entry
            if (
                not isinstance(name, str)
                or not name
                or len(name) > 64
                or name in self._specs
            ):
                raise FrameError(f"bad section name: {name!r}")
            itemsize = _SECTION_DTYPES.get(dtype)
            if itemsize is None:
                raise FrameError(f"section {name!r} dtype {dtype!r} unknown")
            if not isinstance(count, int) or isinstance(count, bool) or count < 0:
                raise FrameError(f"section {name!r} count {count!r} invalid")
            nbytes = count * itemsize
            if offset + nbytes > len(buf):
                raise FrameError(
                    f"section table overruns payload: {name!r} wants "
                    f"[{offset}, {offset + nbytes}) of {len(buf)} bytes"
                )
            self._order.append(name)
            self._specs[name] = (dtype, count)
            self._offsets[name] = offset
            offset += nbytes
        if offset != len(buf):
            raise FrameError(
                f"section table covers {offset} of {len(buf)} payload bytes"
            )
        return self

    # -- shared --------------------------------------------------------
    @property
    def names(self) -> tuple:
        return tuple(self._order)

    def __contains__(self, name: str) -> bool:
        return name in self._specs

    def count(self, name: str) -> int:
        return self._specs[name][1]

    @property
    def nbytes(self) -> int:
        return sum(
            count * _SECTION_DTYPES[dtype]
            for dtype, count in self._specs.values()
        )

    def decl(self) -> list:
        """The wire section table ``[[name, dtype, count], ...]``."""
        return [
            [name, self._specs[name][0], self._specs[name][1]]
            for name in self._order
        ]

    def array(self, name: str):
        """Section ``name`` as a 1-D array — an ``np.frombuffer`` view on
        the decode side (read-only, zero-copy), the original array on the
        encode side."""
        import numpy as np

        a = self._arrays.get(name)
        if a is not None:
            return a
        dtype, count = self._specs[name]
        return np.frombuffer(
            self._buf, dtype=dtype, count=count, offset=self._offsets[name]
        )

    def chunks(self) -> list:
        """Buffer objects whose concatenation is the wire section bytes.
        Decode-side sections return the received buffer itself — the
        forwarding path splices it without touching a single element."""
        if self._arrays:
            return [
                memoryview(self._arrays[name]).cast("B")
                for name in self._order
            ]
        return [self._buf] if self._buf else []


def encode_frame(obj: dict, *, crc: bool = False) -> bytes:
    """``obj`` as one wire-ready frame; ``crc=True`` emits the checksummed
    header form (send it only to peers known to parse it)."""
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if crc:
        return (
            b"%d %08x\n" % (len(payload), zlib.crc32(payload))
            + payload + b"\n"
        )
    return b"%d\n" % len(payload) + payload + b"\n"


def frame_sections(obj: dict):
    """The :class:`WireSections` riding ``obj`` (at the top level or one
    nesting level down), or ``None`` — how a transport decides between the
    B-frame and JSON encodings for one payload."""
    return _locate_sections(obj)[1]


def _locate_sections(obj: dict):
    """``(nest_key_or_None, WireSections_or_None)`` for ``obj``. One
    nesting level is enough by construction: requests/responses carry
    sections directly, and the fleet wraps exactly one envelope around
    them (``{"id": N, "req"/"resp": payload}``)."""
    s = obj.get(SECTIONS_KEY)
    if isinstance(s, WireSections):
        return None, s
    for key, val in obj.items():
        if isinstance(val, dict) and isinstance(
            val.get(SECTIONS_KEY), WireSections
        ):
            return key, val[SECTIONS_KEY]
    return None, None


def encode_bframe(obj: dict) -> bytes:
    """``obj`` (which must carry a :class:`WireSections`) as one binary
    frame. Always checksummed — B-frames only go to ``caps.wire`` peers.
    The section arrays' buffers are spliced into the frame directly; the
    JSON header is everything else plus the section table in place."""
    nest, secs = _locate_sections(obj)
    if secs is None:
        raise ValueError("encode_bframe: payload carries no WireSections")
    if nest is None:
        head_obj = {**obj, SECTIONS_KEY: secs.decl()}
    else:
        head_obj = {**obj, nest: {**obj[nest], SECTIONS_KEY: secs.decl()}}
    header = json.dumps(head_obj, separators=(",", ":")).encode("utf-8")
    crc = zlib.crc32(header)
    chunks = secs.chunks()
    sec_bytes = 0
    for ch in chunks:
        crc = zlib.crc32(ch, crc)
        sec_bytes += len(ch)
    return b"".join(
        [b"B%d %d %08x\n" % (len(header), sec_bytes, crc), header]
        + chunks
        + [b"\n"]
    )


def fold_sections(obj: dict) -> dict:
    """Lower a section-bearing payload to its classic pure-JSON form —
    the per-connection degradation path for peers without ``caps.wire``
    (and the text ``serve_loop``'s JSON egress). Graph-schema sections
    fold to their established field shapes: ``u``/``v``/``w`` become
    ``edges`` triples, ``mst_u``/``mst_v`` become ``mst_edges`` pairs;
    anything else folds to a plain list under its own name. Payloads
    without sections pass through unchanged (same object)."""
    nest, secs = _locate_sections(obj)
    if secs is None:
        return obj
    target = obj if nest is None else obj[nest]
    folded = {k: v for k, v in target.items() if k != SECTIONS_KEY}
    done = set()
    if all(n in secs for n in ("u", "v", "w")):
        done.update(("u", "v", "w"))
        folded["edges"] = [
            list(t)
            for t in zip(
                secs.array("u").tolist(),
                secs.array("v").tolist(),
                secs.array("w").tolist(),
            )
        ]
    if all(n in secs for n in ("mst_u", "mst_v")):
        done.update(("mst_u", "mst_v"))
        folded["mst_edges"] = [
            list(t)
            for t in zip(
                secs.array("mst_u").tolist(), secs.array("mst_v").tolist()
            )
        ]
    for name in secs.names:
        if name not in done:
            folded[name] = secs.array(name).tolist()
    return folded if nest is None else {**obj, nest: folded}


def write_frame(stream: IO[bytes], obj: dict, *, crc: bool = False) -> None:
    """Serialize ``obj`` as one length-prefixed frame and flush."""
    stream.write(encode_frame(obj, crc=crc))
    stream.flush()


def read_frame(
    stream: IO[bytes],
    *,
    max_bytes: int = MAX_FRAME_BYTES,
    meta: Optional[dict] = None,
) -> Optional[dict]:
    """Read one frame; ``None`` on clean EOF, :class:`FrameError` on
    anything garbled (see module docstring for the contract). ``meta``
    (when given) reports ``{"crc": bool, "wire": bool}`` — whether the
    frame carried a checksum / was a binary B-frame, which is how a
    transport learns what forms its peer speaks."""
    header = stream.readline(_MAX_HEADER_BYTES)
    if not header:
        return None
    if not header.endswith(b"\n"):
        raise FrameError(
            f"frame header not newline-terminated within "
            f"{_MAX_HEADER_BYTES} bytes: {header[:32]!r}"
        )
    parts = header.split()
    if parts and parts[0][:1] == b"B":
        return _read_bframe(stream, parts, max_bytes=max_bytes, meta=meta)
    if not parts or len(parts) > 2:
        raise FrameError(f"malformed frame header: {header!r}")
    try:
        n = int(parts[0])
    except ValueError:
        raise FrameError(f"non-numeric frame length prefix: {header!r}") from None
    want_crc: Optional[int] = None
    if len(parts) == 2:
        try:
            want_crc = int(parts[1], 16)
        except ValueError:
            raise FrameError(
                f"non-hex frame checksum token: {header!r}"
            ) from None
    if n < 0 or n > max_bytes:
        raise FrameError(
            f"declared frame length {n} outside [0, {max_bytes}]"
        )
    payload = stream.read(n)
    if payload is None or len(payload) != n:
        raise FrameError(
            f"truncated frame: header promised {n} bytes, "
            f"got {0 if payload is None else len(payload)}"
        )
    stream.read(1)  # the trailing newline (EOF here still parsed a frame)
    if want_crc is not None and zlib.crc32(payload) != want_crc:
        raise FrameError(
            f"frame payload checksum mismatch: declared {want_crc:08x}, "
            f"computed {zlib.crc32(payload):08x} over {n} bytes"
        )
    if meta is not None:
        meta["crc"] = want_crc is not None
        meta["wire"] = False
    try:
        obj = json.loads(payload)
    except ValueError:
        raise FrameError(
            f"frame payload is not valid JSON ({n} bytes)"
        ) from None
    if not isinstance(obj, dict):
        raise FrameError(f"frame payload is {type(obj).__name__}, not object")
    return obj


def _read_bframe(
    stream: IO[bytes],
    parts: list,
    *,
    max_bytes: int,
    meta: Optional[dict],
) -> dict:
    """The B-frame tail of :func:`read_frame`: parts is the split header
    line ``[b"B<hdr>", b"<sec>", b"<crc>"]``. Every length is bounds-
    checked against ``max_bytes`` BEFORE any payload allocation, and the
    section table must tile the section bytes exactly."""
    if len(parts) != 3:
        raise FrameError(f"malformed binary frame header: {parts!r}")
    try:
        hdr_n = int(parts[0][1:])
        sec_n = int(parts[1])
    except ValueError:
        raise FrameError(
            f"non-numeric binary frame length: {parts!r}"
        ) from None
    try:
        want_crc = int(parts[2], 16)
    except ValueError:
        raise FrameError(f"non-hex binary frame checksum: {parts!r}") from None
    if hdr_n < 0 or sec_n < 0 or hdr_n + sec_n > max_bytes:
        raise FrameError(
            f"declared binary frame length {hdr_n}+{sec_n} outside "
            f"[0, {max_bytes}]"
        )
    header = stream.read(hdr_n)
    if header is None or len(header) != hdr_n:
        raise FrameError(
            f"truncated binary frame header: promised {hdr_n} bytes, "
            f"got {0 if header is None else len(header)}"
        )
    sections = stream.read(sec_n)
    if sections is None or len(sections) != sec_n:
        raise FrameError(
            f"truncated binary frame sections: promised {sec_n} bytes, "
            f"got {0 if sections is None else len(sections)}"
        )
    stream.read(1)  # the trailing newline (EOF here still parsed a frame)
    crc = zlib.crc32(sections, zlib.crc32(header))
    if crc != want_crc:
        raise FrameError(
            f"binary frame checksum mismatch: declared {want_crc:08x}, "
            f"computed {crc:08x} over {hdr_n}+{sec_n} bytes"
        )
    try:
        obj = json.loads(header)
    except ValueError:
        raise FrameError(
            f"binary frame header is not valid JSON ({hdr_n} bytes)"
        ) from None
    if not isinstance(obj, dict):
        raise FrameError(
            f"binary frame header is {type(obj).__name__}, not object"
        )
    # Locate the section table where the sections will be re-implanted.
    nest, decl = None, obj.get(SECTIONS_KEY)
    if not isinstance(decl, list):
        decl = None
        for key, val in obj.items():
            if isinstance(val, dict) and isinstance(
                val.get(SECTIONS_KEY), list
            ):
                nest, decl = key, val[SECTIONS_KEY]
                break
    if decl is None:
        if sec_n:
            raise FrameError(
                f"binary frame carries {sec_n} section bytes but the "
                f"header declares no section table"
            )
    else:
        secs = WireSections.from_buffer(decl, sections)
        (obj if nest is None else obj[nest])[SECTIONS_KEY] = secs
    if meta is not None:
        meta["crc"] = True
        meta["wire"] = True
    return obj
