"""Elastic fleet: warm-handoff scale-up, drain-aware scale-down, and the
obs-driven control loop (``fleet/autoscaler.py``, ``docs/FLEET.md``
"Elasticity").

Everything runs against ``--test-echo`` workers — real subprocesses, real
spawns, real drains — so the join/retire machinery is exercised at full
fidelity without a kernel compile in sight.
"""

import threading
import time

import pytest

from distributed_ghs_implementation_tpu.fleet.autoscaler import (
    Autoscaler,
    ElasticPolicy,
    parse_class_budgets,
)
from distributed_ghs_implementation_tpu.fleet.hashing import HashRing
from distributed_ghs_implementation_tpu.fleet.router import (
    FleetConfig,
    FleetRouter,
)
from distributed_ghs_implementation_tpu.obs.events import BUS


@pytest.fixture(autouse=True)
def _clean_global_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.enable()
    BUS.clear()


def _echo_config(workers: int, **kw) -> FleetConfig:
    defaults = dict(
        workers=workers, test_echo=True, heartbeat_interval_s=0.1,
        restart_backoff_base_s=0.02, restart_backoff_cap_s=0.2,
        ready_timeout_s=120.0, request_timeout_s=30.0,
    )
    defaults.update(kw)
    return FleetConfig(**defaults)


# ----------------------------------------------------------------------
# Policy surface
# ----------------------------------------------------------------------
def test_policy_validation_and_class_budgets():
    with pytest.raises(ValueError, match="min_workers"):
        ElasticPolicy(min_workers=0)
    with pytest.raises(ValueError, match="max_workers"):
        ElasticPolicy(min_workers=3, max_workers=2)
    with pytest.raises(ValueError, match="tick_s"):
        ElasticPolicy(tick_s=0.0)
    budgets = parse_class_budgets("interactive=0.05, bulk=2")
    assert budgets == {"interactive": 0.05, "bulk": 2.0}
    with pytest.raises(ValueError, match="CLASS=SECONDS"):
        parse_class_budgets("nope")
    policy = ElasticPolicy(class_budgets_s=budgets, wait_budget_s=0.5)
    assert policy.budget_for("interactive") == 0.05
    assert policy.budget_for("untuned") == 0.5


def test_autoscaler_refuses_remote_topologies():
    cfg = FleetConfig(remote_workers=("127.0.0.1:1",), transport="tcp")
    router = FleetRouter(cfg)  # never started; construction is enough
    with pytest.raises(ValueError, match="remote"):
        Autoscaler(router)


# ----------------------------------------------------------------------
# Router primitives: warm join, drain-aware retire
# ----------------------------------------------------------------------
def test_add_worker_joins_warm_and_owns_its_keyspace():
    cfg = _echo_config(2)
    with FleetRouter(cfg) as r:
        for i in range(6):
            assert r.handle({"op": "solve", "digest": f"w{i}"})["ok"]
        joined = r.add_worker()
        assert joined["worker"] == 2 and joined["warm_s"] > 0
        assert r.pool_size() == 3
        # The joiner owns its ring share immediately — and only entered
        # the ring after its warmed hello was confirmed.
        ring = HashRing(range(3), replicas=cfg.ring_replicas)
        d = next(f"j{i}" for i in range(1000) if ring.assign(f"j{i}") == 2)
        resp = r.handle({"op": "solve", "digest": d})
        assert resp["ok"] and resp["worker"] == 2
        counters = BUS.counters()
        assert counters.get("fleet.scale.up", 0) == 1
        assert BUS.histograms()["fleet.join.warm_s"]["count"] == 1
        stats = r.handle({"op": "stats"})
        assert stats["pool"]["size"] == 3
        assert stats["workers"]["2"]["warmed"] is True
        assert sorted(stats["ring"]) == [0, 1, 2]


def test_add_worker_refuses_cold_hello_join():
    # The warm-handoff gate end to end: a joiner advertising a cold hello
    # (GHS_FLEET_COLD_HELLO test hook) must never enter the ring.
    cfg = _echo_config(
        1, worker_env={1: {"GHS_FLEET_COLD_HELLO": "1"}},
    )
    with FleetRouter(cfg) as r:
        with pytest.raises(RuntimeError, match="warmed"):
            r.add_worker()
        assert r.pool_size() == 1
        assert BUS.counters().get("fleet.join.cold_rejected", 0) == 1
        # The pool is undamaged and still serves.
        assert r.handle({"op": "solve", "digest": "post-cold"})["ok"]
        assert sorted(r.handle({"op": "stats"})["ring"]) == [0]


def test_retire_drains_in_flight_migrates_sessions_and_hands_off():
    cfg = _echo_config(3)
    with FleetRouter(cfg) as r:
        # Pin an update session to some worker via the digest chain.
        seed = r.handle({"op": "solve", "digest": "retire-chain"})
        upd = r.handle({"op": "update", "digest": "retire-chain",
                        "updates": [{"k": 1}]})
        assert upd["ok"] and upd["worker"] == seed["worker"]
        victim = upd["worker"]
        # Slow request in flight inside the victim while it retires.
        results = []
        t = threading.Thread(target=lambda: results.append(r.handle(
            {"op": "solve", "digest": "retire-chain", "sleep_s": 0.4}
        )))
        t.start()
        time.sleep(0.15)
        out = r.retire_worker(victim)
        t.join(timeout=30)
        assert results and results[0]["ok"]  # drained, not dropped
        assert out["exit_code"] == 0
        assert out["sessions_moved"] >= 1  # the pinned chain unpinned
        assert r.pool_size() == 2
        # The session digest now routes to a survivor (the inheritor —
        # with a real service it would replay from the shared WAL here).
        after = r.handle({"op": "update", "digest": upd["digest"],
                          "updates": [{"k": 2}]})
        assert after["ok"] and after["worker"] != victim
        counters = BUS.counters()
        assert counters.get("fleet.scale.down", 0) == 1
        assert counters.get("fleet.worker.dead", 0) == 0  # planned != dead
        stats = r.handle({"op": "stats"})
        assert stats["workers"][str(victim)]["retired"] is True
        assert victim not in stats["ring"]


def test_abandoned_worker_leaves_the_pool_count():
    # A slot that exhausts max_restarts is gone for good — if it kept
    # counting toward pool_size(), the autoscaler would see phantom
    # capacity and refuse to scale up past a crash-looped worker.
    cfg = _echo_config(2, max_restarts=0)
    with FleetRouter(cfg) as r:
        assert r.pool_size() == 2
        r.kill_worker(1)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and r.pool_size() != 1:
            time.sleep(0.05)
        assert r.pool_size() == 1
        assert BUS.counters().get("fleet.worker.abandoned", 0) == 1
        assert r.handle({"op": "solve", "digest": "survivor"})["ok"]


def test_retire_refuses_the_last_live_worker():
    with FleetRouter(_echo_config(1)) as r:
        with pytest.raises(ValueError, match="last live"):
            r.retire_worker(0)
        assert r.handle({"op": "solve", "digest": "still-here"})["ok"]


def test_retire_victim_selection_prefers_lowest_affinity():
    # Unpinned retire picks the worker whose warm cache the fleet would
    # miss least: fewest owner-of-record entries, youngest slot on ties.
    with FleetRouter(_echo_config(2)) as r:
        for i in range(24):  # both originals accumulate affinity
            assert r.handle({"op": "solve", "digest": f"aff-{i}"})["ok"]
        joined = r.add_worker()
        out = r.retire_worker()  # the affinity-free joiner goes first
        assert out["worker"] == joined["worker"]


def test_add_worker_dials_a_remote_standby_and_retires_it():
    # The operator path for remote fleets: a standby `--listen` worker is
    # dialed into the pool by address (same warm gate), then drained back
    # out — it must exit 0 like any planned departure.
    from tests.test_fleet import _spawn_listening_worker

    proc0, addr0 = _spawn_listening_worker(worker_id=0)
    proc1, addr1 = _spawn_listening_worker(worker_id=1)
    try:
        cfg = FleetConfig(
            remote_workers=(addr0,), transport="tcp", test_echo=True,
            heartbeat_interval_s=0.1, ready_timeout_s=30.0,
            request_timeout_s=30.0,
        )
        with FleetRouter(cfg) as r:
            with pytest.raises(ValueError, match="standby"):
                r.add_worker()  # a remote topology cannot spawn
            joined = r.add_worker(addr=addr1)
            assert joined["worker"] == 1 and r.pool_size() == 2
            ring = HashRing(range(2), replicas=cfg.ring_replicas)
            d = next(f"rm-{i}" for i in range(1000)
                     if ring.assign(f"rm-{i}") == 1)
            resp = r.handle({"op": "solve", "digest": d})
            assert resp["ok"] and resp["worker"] == 1
            out = r.retire_worker(1)
            assert out["worker"] == 1 and r.pool_size() == 1
            assert r.handle({"op": "solve", "digest": d})["ok"]
        assert proc1.wait(timeout=30) == 0  # drained out, exit 0
        assert proc0.wait(timeout=30) == 0  # fleet shutdown drains too
    finally:
        for proc in (proc0, proc1):
            if proc.poll() is None:
                proc.kill()


# ----------------------------------------------------------------------
# The control loop
# ----------------------------------------------------------------------
def test_step_decisions_are_deterministic_and_hysteretic():
    # step() driven by hand (no thread): breach -> up, at-max -> hold
    # with a reason, sustained idle -> down, at-min -> hold. The exact
    # sequence the elastic drill's event counts rest on.
    policy = ElasticPolicy(
        min_workers=1, max_workers=2, tick_s=0.05, cooldown_s=0.0,
        wait_budget_s=0.0, idle_ticks=2,
    )
    with FleetRouter(_echo_config(1)) as r:
        a = Autoscaler(r, policy)
        assert r.handle({"op": "solve", "digest": "t1",
                         "slo_class": "hit"})["ok"]
        d1 = a.step()
        assert d1["action"] == "up" and "budget" in d1["reason"]
        assert r.pool_size() == 2
        assert r.handle({"op": "solve", "digest": "t2",
                         "slo_class": "hit"})["ok"]
        d2 = a.step()
        assert d2["action"] == "hold" and "max_workers" in d2["reason"]
        assert a.step()["action"] == "hold"  # idle tick 1 of 2
        d3 = a.step()  # idle tick 2: scale down
        assert d3["action"] == "down" and "idle" in d3["reason"]
        assert r.pool_size() == 1
        assert a.step()["action"] == "hold"  # at min: idle never goes lower
        assert a.step()["action"] == "hold"
        counters = BUS.counters()
        assert counters.get("fleet.scale.up", 0) == 1
        assert counters.get("fleet.scale.down", 0) == 1
        # The stats op explains the current size with the last decision.
        last = r.handle({"op": "stats"})["pool"]["last_scale"]
        assert last["action"] == "down" and "idle" in last["reason"]


def test_queue_depth_watermark_breaches_without_latency():
    # Depth leads latency: a backed-up worker triggers scale-up even when
    # no tagged request has completed yet (nothing on the bus to join).
    policy = ElasticPolicy(
        min_workers=1, max_workers=2, cooldown_s=0.0, queue_high=1,
        wait_budget_s=1e9,  # latency can never breach in this test
    )
    with FleetRouter(_echo_config(1)) as r:
        slow = threading.Thread(target=lambda: r.handle(
            {"op": "solve", "digest": "backlog", "sleep_s": 0.8}
        ))
        slow.start()
        time.sleep(0.2)  # the request occupies the one worker's queue
        a = Autoscaler(r, policy)
        d = a.step()
        assert d["action"] == "up" and "watermark" in d["reason"]
        assert r.pool_size() == 2
        slow.join()


def test_control_loop_scales_up_on_breach_and_down_on_idle():
    # The threaded loop end to end: drive tagged traffic with a
    # zero-second budget until the pool grows, then stop and watch it
    # drain back to min — warm joins, planned retires, no deaths.
    policy = ElasticPolicy(
        min_workers=1, max_workers=2, tick_s=0.1, cooldown_s=0.3,
        wait_budget_s=0.0, idle_ticks=4,
    )
    with FleetRouter(_echo_config(1)) as r:
        with Autoscaler(r, policy):
            deadline = time.monotonic() + 30
            i = 0
            while r.pool_size() < 2 and time.monotonic() < deadline:
                r.handle({"op": "solve", "digest": f"ramp-{i}",
                          "slo_class": "hit"})
                i += 1
                time.sleep(0.05)
            assert r.pool_size() == 2, "never scaled up under breach"
            deadline = time.monotonic() + 30
            while r.pool_size() > 1 and time.monotonic() < deadline:
                time.sleep(0.1)
            assert r.pool_size() == 1, "never drained back to min on idle"
            # Let the retire's accounting land before reading counters.
            deadline = time.monotonic() + 10
            while (BUS.counters().get("fleet.scale.down", 0) < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
        counters = BUS.counters()
        assert counters.get("fleet.scale.up", 0) == 1
        assert counters.get("fleet.scale.down", 0) == 1
        assert counters.get("fleet.worker.dead", 0) == 0
        assert BUS.histograms()["fleet.join.warm_s"]["count"] == 1
        # The fleet still serves at min size.
        assert r.handle({"op": "solve", "digest": "after"})["ok"]
