#!/usr/bin/env python
"""Batch drill: mixed-size batched solving checked for parity + isolation.

Two modes, both exit 0 iff every check passed (``--output`` writes JSON):

* ``--smoke`` — the CI gate for the lane engine's core contract: a mixed
  batch of >= 64 random graphs (several shape buckets, duplicates, a
  disconnected forest, an empty edge set, an oversize bypass) solved via
  ``minimum_spanning_forest_batch`` must be (a) edge-for-edge identical to
  per-graph sequential ``minimum_spanning_forest``, and (b) compiled at
  most once per distinct shape bucket (``batch.compile.miss`` counts it).
  The same traffic then replays through a ``batch_lanes``-enabled
  ``MSTService`` scheduler to prove in-batch duplicate digests coalesce to
  one flight and the cache absorbs the repeat.
* ``--chaos`` — per-lane incident isolation: with the ``batch.attempt``
  fault armed (and a transient device fault for the fallback path), every
  batch attempt fails, the engine degrades to per-lane supervised solves,
  and every result must STILL be oracle-exact with its incidents recorded
  per lane. Armed ``GHS_FAULT_*`` environment variables are honored on
  top of the drill's own arming.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _slo_section(graphs, results, policy, wall_s: float) -> dict:
    """Per-class accounting over a batch run — the shared
    ``ghs-slo-summary-v1`` schema (obs/slo.py) all drills report.

    A bulk solve has no per-request arrival clock, so ``latency_s`` here
    is each result's own solve wall (``MSTResult.wall_time_s``: the device
    dispatch its lane rode, or the single solve for a bypass); classes are
    the admission split the engine actually made (``batch`` vs
    ``oversize``). Queue-wait/overflow context attaches from the bus.
    """
    from distributed_ghs_implementation_tpu.obs import slo
    from distributed_ghs_implementation_tpu.obs.events import BUS

    stats = slo.ClassStats()
    for g, r in zip(graphs, results):
        cls = "batch" if policy.admits(g) else "oversize"
        stats.observe(cls, r.wall_time_s)
    return slo.assemble(
        stats,
        wall_s=wall_s,
        histograms=BUS.histograms(),
        events_dropped=BUS.dropped,
    )


def _mixed_graphs(seed: int, count: int):
    """>= ``count`` graphs over several buckets + structural edge cases."""
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
        line_graph,
    )

    rng = np.random.default_rng(seed)
    graphs = []
    for i in range(count - 4):
        nodes = int(rng.choice([48, 96, 200, 400]))
        edges = int(rng.integers(nodes, 3 * nodes))
        graphs.append(
            gnm_random_graph(
                nodes, edges, seed=seed + i,
                ensure_connected=bool(i % 3),  # disconnected forests too
            )
        )
    graphs.append(graphs[0])  # duplicate graph in the same batch
    graphs.append(Graph.from_edges(6, []))  # empty edge set
    graphs.append(line_graph(9))
    # Oversize: pads beyond the default bucket ceiling -> must bypass.
    graphs.append(gnm_random_graph(70_000, 140_000, seed=seed))
    return graphs


def run_smoke(args) -> dict:
    from distributed_ghs_implementation_tpu.api import (
        minimum_spanning_forest,
        minimum_spanning_forest_batch,
    )
    from distributed_ghs_implementation_tpu.batch.lanes import bucket_key
    from distributed_ghs_implementation_tpu.batch.policy import BatchPolicy
    from distributed_ghs_implementation_tpu.graphs.generators import gnm_random_graph
    from distributed_ghs_implementation_tpu.obs.events import BUS
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    BUS.enable()
    BUS.clear()
    graphs = _mixed_graphs(args.seed, args.graphs)
    policy = BatchPolicy(max_lanes=args.lanes)
    batchable = [g for g in graphs if policy.admits(g)]
    buckets = {bucket_key(g) for g in batchable}

    checks = []
    t_batch = time.perf_counter()
    results = minimum_spanning_forest_batch(graphs, policy=policy)
    batch_wall_s = time.perf_counter() - t_batch
    parity = all(
        np.array_equal(
            r.edge_ids, minimum_spanning_forest(g).edge_ids
        )
        for g, r in zip(graphs, results)
    )
    checks.append(("batch == sequential, edge-for-edge", parity))
    counters = BUS.counters()
    compiles = counters.get("batch.compile.miss", 0)
    checks.append(
        (f"compilations ({compiles}) <= shape buckets ({len(buckets)})",
         compiles <= len(buckets))
    )
    checks.append(
        ("oversize graph bypassed", counters.get("batch.bypass", 0) >= 1)
    )
    checks.append(
        (f"lanes formed == batchable graphs ({len(batchable)})",
         counters.get("batch.lanes.formed", 0) == len(batchable))
    )

    # Scheduler replay: duplicates inside one request list share a flight,
    # and the whole list is answered from cache on repeat.
    svc = MSTService(batch_lanes=args.lanes)
    small = [gnm_random_graph(64, 160, seed=args.seed + i) for i in range(8)]
    request = small + [small[0], small[3]]
    out = svc.scheduler.solve_batch(request)
    sources = [s for _, s in out]
    checks.append(
        ("scheduler: one solve per distinct digest",
         sources.count("solved") == len(small)
         and sources.count("coalesced") == 2)
    )
    again = svc.scheduler.solve_batch(request)
    checks.append(
        ("scheduler: repeat batch is all cache hits",
         {s for _, s in again} <= {"cache", "coalesced"})
    )
    weights_match = all(
        a.total_weight == b.total_weight
        for (a, _), (b, _) in zip(out, again)
    )
    checks.append(("scheduler: repeat weights stable", weights_match))

    slo_summary = _slo_section(graphs, results, policy, batch_wall_s)
    return {
        "mode": "smoke",
        "graphs": len(graphs),
        "buckets": len(buckets),
        "compilations": compiles,
        "slo": slo_summary,
        "events_dropped": slo_summary["events_dropped"],
        "dropped_warning": slo_summary["dropped_warning"],
        "checks": [{"name": n, "ok": bool(ok)} for n, ok in checks],
        "ok": all(ok for _, ok in checks),
    }


def run_chaos(args) -> dict:
    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest_batch
    from distributed_ghs_implementation_tpu.batch.policy import BatchPolicy
    from distributed_ghs_implementation_tpu.obs.events import BUS
    from distributed_ghs_implementation_tpu.utils.resilience import (
        FAULTS,
        SupervisorConfig,
    )
    from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight

    BUS.enable()
    BUS.clear()
    FAULTS.reload_env()  # operator-armed GHS_FAULT_* ride along
    graphs = _mixed_graphs(args.seed, args.graphs)
    policy = BatchPolicy(max_lanes=args.lanes)
    config = SupervisorConfig(retries_per_rung=1, backoff_base_s=0.0)
    # Every batch attempt (first try + retry) fails transiently -> the
    # engine must fall back to per-lane supervised solves; the first few
    # of those hit a transient device fault too (retry inside the lane).
    FAULTS.arm("batch.attempt", times=10_000)
    FAULTS.arm("resilience.attempt.device", times=3)

    from distributed_ghs_implementation_tpu.batch.engine import BatchEngine

    engine = BatchEngine(policy=policy, supervisor_config=config)
    t_batch = time.perf_counter()
    results = minimum_spanning_forest_batch(graphs, engine=engine)
    batch_wall_s = time.perf_counter() - t_batch
    FAULTS.reset()

    checks = []
    exact = all(
        abs(float(r.total_weight) - float(scipy_mst_weight(g))) < 1e-6
        if g.num_edges else r.total_weight == 0
        for g, r in zip(graphs, results)
    )
    checks.append(("all weights oracle-exact under chaos", exact))
    counters = BUS.counters()
    batchable = sum(policy.admits(g) for g in graphs)
    checks.append(
        (f"every lane fell back in isolation ({batchable})",
         counters.get("batch.lane.fallback", 0) == batchable)
    )
    checks.append(
        ("batch retries recorded", counters.get("batch.retry", 0) >= 1)
    )
    # Edge-less graphs short-circuit before the supervisor attempts run,
    # so their (still isolated) fallback carries an empty incident log.
    isolated = all(
        r.incidents is not None and len(r.incidents) >= 1
        for g, r in zip(graphs, results)
        if policy.admits(g) and g.num_edges
    )
    checks.append(("per-lane incidents recorded", isolated))
    device_retries = sum(
        1
        for g, r in zip(graphs, results)
        if r.incidents is not None
        for rec in r.incidents.records
        if rec.rung == "device" and rec.outcome == "transient"
    )
    checks.append(
        ("transient lane faults isolated to their lanes (3 armed)",
         device_retries == 3)
    )
    slo_summary = _slo_section(graphs, results, policy, batch_wall_s)
    return {
        "mode": "chaos",
        "graphs": len(graphs),
        "lane_fallbacks": counters.get("batch.lane.fallback", 0),
        "batch_retries": counters.get("batch.retry", 0),
        "slo": slo_summary,
        "events_dropped": slo_summary["events_dropped"],
        "dropped_warning": slo_summary["dropped_warning"],
        "checks": [{"name": n, "ok": bool(ok)} for n, ok in checks],
        "ok": all(ok for _, ok in checks),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="batch_drill", description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="mixed-batch parity + compile-bound + scheduler dedup")
    p.add_argument("--chaos", action="store_true",
                   help="fault-armed run asserting per-lane isolation")
    p.add_argument("--graphs", type=int, default=68,
                   help="graphs in the mixed batch (>= 64 for the CI gate)")
    p.add_argument("--lanes", type=int, default=16)
    p.add_argument("--seed", type=int, default=19)
    p.add_argument("--output", help="write the JSON report here")
    args = p.parse_args(argv)

    if args.chaos and not args.smoke:
        report = run_chaos(args)
    else:
        report = run_smoke(args)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    print(json.dumps(report, indent=2))
    print(f"batch drill: {'PASS' if report['ok'] else 'FAIL'}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
