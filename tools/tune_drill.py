#!/usr/bin/env python
"""Tune drill: prove a TuningRecord actually steers serving, correctly.

The CI gate for the autotuner's end-to-end contract (``gate-tune-v1``).
Given a record written by ``ghs tune`` (``--record``), the drill asserts:

1. **Integrity** — the record's sha256 sidecar verifies
   (``utils/integrity.check_file`` == ``"ok"``).
2. **CPU pin** — on a non-TPU host every winner is exactly ``xla``
   (interpret-mode Pallas is a parity tool, never a measured winner).
3. **Load-bearing** — after ``install_record``, a seeded ``solve_lanes``
   with ``kernel=None`` resolves through the measured tier:
   ``kernel.selected.measured`` must COUNT (the record is consulted, not
   merely parsed).
4. **Parity** — the tuned selection, the explicit XLA path, and the
   interpret-mode Pallas path produce edge-for-edge identical MSFs on
   the same seeded graphs (the fallback contract, exercised end to end).

Exit 0 iff every check passed; ``--output`` writes the JSON report.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401 — repo-root sys.path setup

import argparse
import json
import sys

import numpy as np


def _fail(report: dict, why: str) -> int:
    report["failed"] = why
    print(json.dumps(report, indent=2, sort_keys=True))
    print(f"TUNE DRILL FAILED: {why}", file=sys.stderr)
    return 1


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--record", required=True,
                   help="ghs-tuning-v1 record path (from `ghs tune`)")
    p.add_argument("--lanes", type=int, default=4,
                   help="lane count for the load-bearing solve")
    p.add_argument("--output", help="write the JSON report here too")
    args = p.parse_args()

    import jax

    from distributed_ghs_implementation_tpu.batch import lanes as lanes_mod
    from distributed_ghs_implementation_tpu.obs.events import BUS
    from distributed_ghs_implementation_tpu.ops import pallas_kernels as pk
    from distributed_ghs_implementation_tpu.tune import load_record
    from distributed_ghs_implementation_tpu.tune import record as record_mod
    from distributed_ghs_implementation_tpu.tune.measure import _bucket_graph
    from distributed_ghs_implementation_tpu.utils import integrity

    report: dict = {"schema": "ghs-tune-drill-v1", "record": args.record,
                    "checks": {}}

    # 1. Integrity: the sidecar must verify, not merely exist.
    state = integrity.check_file(args.record)
    report["checks"]["integrity"] = state
    if state != "ok":
        return _fail(report, f"record integrity is {state!r}, wanted 'ok'")

    record = load_record(args.record)
    if record is None:
        return _fail(report, "record failed to load (missing or stale)")

    # 2. CPU pin: off TPU, every winner must be exactly xla.
    winners = record_mod.winners(record)
    report["checks"]["buckets"] = len(winners)
    if jax.default_backend() != "tpu":
        bad = {record_mod.bucket_key_str(b): k
               for b, k in winners.items() if k != "xla"}
        report["checks"]["cpu_pin"] = "ok" if not bad else bad
        if bad:
            return _fail(report, f"non-xla winners on a CPU host: {bad}")

    # 3. Load-bearing: install, solve with kernel=None, demand the
    # measured tier counted. The graphs are seeded into a lane bucket the
    # record actually tuned (lanes-matching entry, else any lane entry).
    installed = record_mod.install_record(record, path=args.record)
    report["checks"]["installed"] = installed
    if installed < 1:
        return _fail(report, "install_record installed 0 buckets")

    lane_buckets = sorted(
        b for b in winners
        if b[2] >= 1 and b[3] in ("fused", "vmap")
    )
    if not lane_buckets:
        return _fail(report, "record has no lane-mode buckets to drill")
    bucket = next((b for b in lane_buckets if b[2] == args.lanes),
                  lane_buckets[0])
    n_pad, m_pad, lanes, mode = bucket
    graph = _bucket_graph(n_pad, m_pad, seed=7)
    if graph is None:
        return _fail(report, f"no seeded graph pads into bucket {bucket}")
    graphs = [graph] * max(2, min(lanes, 4))

    before = BUS.counters().get("kernel.selected.measured", 0)
    tuned = lanes_mod.solve_lanes(graphs, lanes=lanes, mode=mode, kernel=None)
    measured = BUS.counters().get("kernel.selected.measured", 0) - before
    report["checks"]["measured_selections"] = measured
    if measured < 1:
        return _fail(report, "kernel.selected.measured did not count — "
                             "the installed record was never consulted")

    # 4. Parity: tuned vs explicit xla vs interpret-mode pallas.
    xla = lanes_mod.solve_lanes(graphs, lanes=lanes, mode=mode, kernel="xla")
    pal = lanes_mod.solve_lanes(graphs, lanes=lanes, mode=mode,
                                kernel="pallas")
    resolved_pallas = pk.kernel_choice("pallas")
    report["checks"]["pallas_resolved"] = resolved_pallas
    for name, other in (("tuned_vs_xla", tuned), ("pallas_vs_xla", pal)):
        ok = all(
            np.array_equal(a[0], b[0]) for a, b in zip(other, xla)
        )
        report["checks"][name] = "ok" if ok else "MISMATCH"
        if not ok:
            return _fail(report, f"edge parity failed: {name}")

    report["tuning"] = pk.tuned_summary()
    report["failed"] = None
    out = json.dumps(report, indent=2, sort_keys=True)
    print(out)
    if args.output:
        with open(args.output, "w") as f:
            f.write(out + "\n")
    print("TUNE DRILL PASSED", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
