#!/bin/bash
# Attach a Cloud TPU pod slice to a serving fleet as one network worker.
#
# Starts `fleet.worker --listen` on the slice so an off-host router
# (`ghs serve --fleet-workers <slice-host>:<port>`) can dial it: the
# worker owns a mesh-sharded oversize lane over every chip it can see
# (`--sharded-lane`), and `--multihost` brings up the JAX distributed
# runtime from pod metadata first (parallel/multihost.py) so
# jax.devices() spans the slice before the service builds its mesh.
#
# Single-host slices (v5e-8, v4-8, ...) are fully supported: one process,
# all chips, one listening socket. Multi-host slices start the same
# command on every host; today only host 0's listener should be given to
# the router (the fleet protocol is served per-process — driving
# pod-spanning collectives from one worker's request loop is the
# follower-broadcast frontier ROADMAP item 1 names).
#
# Usage:
#   ./launcher/tpu_pod_worker.sh <tpu-name> <zone> <worker-id> <port> [extra flags]
#   # then, from the router host:
#   #   ghs serve --fleet-workers <slice-host>:<port> --backend device
set -euo pipefail

TPU_NAME="$1"; shift
ZONE="$1"; shift
WORKER_ID="$1"; shift
PORT="$1"; shift

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command "cd $(pwd) && python -m distributed_ghs_implementation_tpu.fleet.worker \
    --worker-id $WORKER_ID --listen 0.0.0.0:$PORT --multihost --sharded-lane $*"
