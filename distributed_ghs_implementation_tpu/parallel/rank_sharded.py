"""Sharded rank-space solver — the multi-chip fast path.

The single-chip rank solver (``models/rank_solver.py``) does ~94% of its
edge work in levels 1-2; this module shards exactly that work over the
mesh's edge axis and keeps everything else replicated:

  * **Layout**: the undirected rank space is block-sharded (shard ``k`` owns
    global ranks ``[k*mb, (k+1)*mb)``) — contiguous blocks keep the global
    rank order, which is the tie-break total order. ``vmin0`` (per-vertex
    min incident rank, host-precomputed) and all fragment state are
    replicated; MST marks live with the rank block that owns them.
  * **Level 1** arrives host-precomputed (``host_level1`` during staging —
    the hook edges are the host-known vertex minima, so the partition costs
    the solve nothing); each shard only marks the level-1 ranks it owns.
  * **Level 2** is one per-shard ``segment_min`` over the local rank block
    plus one n-sized ``lax.pmin`` — the ICI analog of the reference's
    REPORT convergecast (``/root/reference/ghs_implementation_mpi.py:493-580``).
  * **Finish**: survivors (a few % of edges on RMAT-like graphs) are
    compacted per shard and ``all_gather``-ed — shard-block concatenation
    preserves the global rank order, so the compact slot index stays a valid
    tie-break — then the remaining levels run replicated with no further
    host round trips.

Harvest is multi-process capable: the rank-block-sharded MST mask is
bit-packed per shard and replicated by one tiled ``all_gather`` (m/8 bytes
over ICI/DCN), so every process reads the full mask from its own addressable
devices — the reference's rank-0 result gather
(``/root/reference/ghs_implementation_mpi.py:760-779``) done as a collective.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.models.boruvka import (
    _bucket_size,
    _max_levels,
)
from distributed_ghs_implementation_tpu.models.rank_solver import (
    _CENSUS_MIN_SPACE,
    _compact_slots,
    _finish_to_fixpoint,
    _INT32_RANK_LIMIT,
    _level_core,
    _moe_over,
    _pad_l2_ranks,
    _pick_family,
    _PACKBITS_CHUNK,
    _prefix_size,
    _restore_state_host,
    check_rank_envelope,
    host_level1,
    host_level2,
    fetch_mst_edge_ids,
    packed_to_edge_ids,
    use_filtered_path,
)
from distributed_ghs_implementation_tpu.ops.segment_ops import INT32_MAX
from distributed_ghs_implementation_tpu.ops.union_find import hook_and_compress
from distributed_ghs_implementation_tpu.parallel.mesh import (
    EDGE_AXIS,
    edge_mesh,
    shard_map_compat,
)
from distributed_ghs_implementation_tpu.parallel.sharded import _stage


def _owner_lookup(table, ranks, has, k, mb, axis):
    """Cross-shard gather: the shard owning global rank ``ranks[i]`` proposes
    ``table[local]``; everyone else proposes the sentinel; pmin selects."""
    local = jnp.where(has, ranks, 0) - k * mb
    mine = has & (local >= 0) & (local < mb)
    li = jnp.where(mine, local, 0)
    return jax.lax.pmin(jnp.where(mine, table[li], INT32_MAX), axis), mine, li


def _sharded_l1_marks(vmin0, mb, k):
    """Level-1 MST marks for the local rank block: the chosen ranks are
    exactly the ``vmin0`` values (the level-1 partition itself arrives
    host-precomputed as ``parent1`` — no cross-shard lookups needed)."""
    has1 = vmin0 < INT32_MAX
    safe1 = jnp.where(has1, vmin0, 0)
    local = safe1 - k * mb
    mine1 = has1 & (local >= 0) & (local < mb)
    return jnp.zeros(mb, bool).at[jnp.where(mine1, local, mb)].max(
        mine1, mode="drop"
    )


# ---------------------------------------------------------------------------
# Split-key (shard, local) rank space — the 2^31+ global-rank regime.
#
# Global rank ids outgrow int32 one scale step past RMAT-26, but the block
# sharding already factors every global rank as k * mb + local with
# local < mb < 2^31 — so the TOTAL ORDER is the lexicographic order on the
# int32 pair (shard, local), and no int64 ever needs to touch the device.
# The one place global ranks are compared across shards (the MOE combine)
# becomes two sequential int32 pmins: the minimum rank lives in the
# SMALLEST shard id holding any candidate (blocks partition the order), so
#   kmin = pmin(k | shard has a candidate)
#   lmin = pmin(local_moe | k == kmin).
# Everything else (marks, owner lookups, survivor cranks) is local or
# derives the shard from position. Measured negative that forces this
# design: s64 cross-replica reductions do not lower on TPU at all
# ("Supported lowering only of Sum all reduce" — the int64-key variant
# fails to compile), and s64 would have doubled the n-sized residents.
# ---------------------------------------------------------------------------


def _sharded_l1_marks_kl(vk, vl, mb, k):
    """Split-key level-1 marks: vertex ``v``'s min incident rank lives at
    shard ``vk[v]``, local offset ``vl[v]`` (``vk == INT32_MAX`` when
    isolated — never equal to a real shard id)."""
    mine1 = vk == k
    return jnp.zeros(mb, bool).at[jnp.where(mine1, vl, mb)].max(
        mine1, mode="drop"
    )


def _combine_kl(local_moe, k, axis):
    """Lexicographic-min combine of per-shard local MOEs -> global
    ``(kmin, lmin)`` per fragment, as two int32 pmins."""
    has_local = local_moe < INT32_MAX
    kmin = jax.lax.pmin(jnp.where(has_local, k, INT32_MAX), axis)
    lmin = jax.lax.pmin(
        jnp.where(has_local & (kmin == k), local_moe, INT32_MAX), axis
    )
    return kmin, lmin


def _owner_lookup_kl(table, kmin, lmin, has, k, axis):
    """Split-key owner gather: the shard whose id matches ``kmin`` proposes
    ``table[lmin]``; pmin selects (table values are vertex ids, int32)."""
    mine = has & (kmin == k)
    li = jnp.where(mine, lmin, 0)
    return jax.lax.pmin(jnp.where(mine, table[li], INT32_MAX), axis), mine, li


def _moe_int32(fa, fb, k, mb, n):
    """MOE strategy, int32 global ranks: segment_min over global slot keys,
    one pmin combine, owner lookup by rank-block subtraction. Returns
    ``(has, mine, li, wa, wb)``."""
    gslot = k * mb + jnp.arange(mb, dtype=jnp.int32)
    key = jnp.where(fa != fb, gslot, INT32_MAX)
    moe = jax.lax.pmin(_moe_over(fa, fb, key, n), EDGE_AXIS)
    has = moe < INT32_MAX
    wa, mine, li = _owner_lookup(fa, moe, has, k, mb, EDGE_AXIS)
    wb, _, _ = _owner_lookup(fb, moe, has, k, mb, EDGE_AXIS)
    return has, mine, li, wa, wb


def _moe_kl(fa, fb, k, mb, n):
    """MOE strategy, split keys: segment_min over LOCAL slot keys, the
    two-pmin lexicographic combine, split-key owner lookup. Same contract
    as :func:`_moe_int32`."""
    lslot = jnp.arange(mb, dtype=jnp.int32)
    key = jnp.where(fa != fb, lslot, INT32_MAX)
    local_moe = _moe_over(fa, fb, key, n)
    kmin, lmin = _combine_kl(local_moe, k, EDGE_AXIS)
    has = kmin < INT32_MAX
    wa, mine, li = _owner_lookup_kl(fa, kmin, lmin, has, k, EDGE_AXIS)
    wb, _, _ = _owner_lookup_kl(fb, kmin, lmin, has, k, EDGE_AXIS)
    return has, mine, li, wa, wb


def _sharded_moe_level(fragment, mst, fa, fb, k, n, moe_fn, kernel="xla"):
    """One hook level over relabeled sharded endpoints — the shared body of
    the int32 and split-key programs; ``moe_fn`` is the only difference.
    ``kernel`` selects the fused Pallas hook+compress round (the n-sized
    replicated union-find — identical results either way). Returns
    ``(fragment, mst, fa, fb, has)``."""
    mb = fa.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)
    has, mine, li, wa, wb = moe_fn(fa, fb, k, mb, n)
    dst = jnp.where(has, jnp.where(wa == ids, wb, wa), ids)
    fragment, parent = hook_and_compress(has, dst, fragment, kernel=kernel)
    mst = mst.at[jnp.where(mine, li, mb)].max(mine, mode="drop")
    return fragment, mst, parent[fa], parent[fb], has


def _rank_sharded_head_kl(vk, vl, parent1, ra, rb, *, kernel="xla"):
    """Split-key per-shard head: levels 1-2 with all-int32 device state.
    Same contract as ``_rank_sharded_head``."""
    n = vk.shape[0]
    mb = ra.shape[0]
    k = jax.lax.axis_index(EDGE_AXIS).astype(jnp.int32)

    fragment = parent1
    has1 = vk < INT32_MAX
    mst = _sharded_l1_marks_kl(vk, vl, mb, k)
    fa = parent1[ra]
    fb = parent1[rb]
    fragment, mst, fa, fb, has2 = _sharded_moe_level(
        fragment, mst, fa, fb, k, n, _moe_kl, kernel
    )

    lv = jnp.any(has1).astype(jnp.int32) + jnp.any(has2).astype(jnp.int32)
    local_alive = jnp.sum((fa != fb).astype(jnp.int32))
    total = jax.lax.psum(local_alive, EDGE_AXIS)
    cmax = jax.lax.pmax(local_alive, EDGE_AXIS)
    return fragment, mst, fa, fb, jnp.stack([lv, total, cmax])


def _rank_sharded_finish_kl(
    fragment, mst, fa, fb, *, fs_local: int, max_levels: int, kernel="xla"
):
    """Split-key variant of ``_rank_sharded_finish``: survivor cranks carry
    LOCAL offsets only; the owning shard of a gathered slot is its block
    position (``slot // fs_local`` — tiled all_gather concatenates shard
    blocks in axis order), so global ranks never materialize."""
    n = fragment.shape[0]
    mb = fa.shape[0]
    k = jax.lax.axis_index(EDGE_AXIS).astype(jnp.int32)
    crank_local = jnp.arange(mb, dtype=jnp.int32)
    cfa, cfb, crank, _ = _compact_slots(fa, fb, crank_local, fs_local)
    gfa = jax.lax.all_gather(cfa, EDGE_AXIS, tiled=True)
    gfb = jax.lax.all_gather(cfb, EDGE_AXIS, tiled=True)
    gcrank = jax.lax.all_gather(crank, EDGE_AXIS, tiled=True)
    # Gathered-slot order = (shard block, local compact position) =
    # ascending global rank among valid entries: a valid tie-break.
    cslot = jnp.arange(gfa.shape[0], dtype=jnp.int32)

    def cond(s):
        return s[4] & (s[5] < max_levels)

    def body(s):
        fragment, mst, gfa, gfb, _, lv = s
        key = jnp.where(gfa != gfb, cslot, INT32_MAX)
        fragment, parent, has, safe = _level_core(
            fragment, gfa, gfb, key, n, kernel=kernel
        )
        owner = safe // fs_local
        winners = gcrank[safe]
        mine = has & (owner == k)
        mst = mst.at[jnp.where(mine, winners, mb)].max(mine, mode="drop")
        return (fragment, mst, parent[gfa], parent[gfb], jnp.any(has), lv + 1)

    alive = jnp.sum((gfa != gfb).astype(jnp.int32)) > 0
    state = (fragment, mst, gfa, gfb, alive, jnp.zeros((), jnp.int32))
    fragment, mst, _, _, _, lv = jax.lax.while_loop(cond, body, state)
    return fragment, mst, lv


def _rank_sharded_head(vmin0, parent1, ra, rb, *, kernel="xla"):
    """Per-shard body: levels 1-2 (level-1 partition host-precomputed).
    Returns ``(fragment, mst_local, fa, fb, stats)`` with ``stats =
    [levels, total_alive, max_local_alive]``."""
    n = vmin0.shape[0]
    mb = ra.shape[0]
    k = jax.lax.axis_index(EDGE_AXIS).astype(jnp.int32)

    fragment = parent1
    has1 = vmin0 < INT32_MAX
    mst = _sharded_l1_marks(vmin0, mb, k)

    # ---- Relabel the local rank block (the sharded edge-sized work),
    # then level 2: per-shard segment_min + one pmin combine.
    fa = parent1[ra]
    fb = parent1[rb]
    fragment, mst, fa, fb, has2 = _sharded_moe_level(
        fragment, mst, fa, fb, k, n, _moe_int32, kernel
    )

    lv = jnp.any(has1).astype(jnp.int32) + jnp.any(has2).astype(jnp.int32)
    local_alive = jnp.sum((fa != fb).astype(jnp.int32))
    total = jax.lax.psum(local_alive, EDGE_AXIS)
    cmax = jax.lax.pmax(local_alive, EDGE_AXIS)
    return fragment, mst, fa, fb, jnp.stack([lv, total, cmax])


def _finish_gathered_loop(fragment, mst, cfa, cfb, crank, k, mb, max_levels,
                          kernel="xla"):
    """All-gather per-shard compacted survivors and run the remaining levels
    replicated (each shard marks only its own rank block) — the shared tail
    of :func:`_rank_sharded_finish` and :func:`_rank_sharded_finish_pre`.
    Shard-block concatenation keeps ascending global-rank order among the
    valid entries, so the gathered slot index is a valid tie-break order."""
    n = fragment.shape[0]
    gfa = jax.lax.all_gather(cfa, EDGE_AXIS, tiled=True)
    gfb = jax.lax.all_gather(cfb, EDGE_AXIS, tiled=True)
    gcrank = jax.lax.all_gather(crank, EDGE_AXIS, tiled=True)
    cslot = jnp.arange(gfa.shape[0], dtype=jnp.int32)

    def cond(s):
        return s[4] & (s[5] < max_levels)

    def body(s):
        fragment, mst, gfa, gfb, _, lv = s
        key = jnp.where(gfa != gfb, cslot, INT32_MAX)
        fragment, parent, has, safe = _level_core(
            fragment, gfa, gfb, key, n, kernel=kernel
        )
        winners = gcrank[safe] - k * mb  # global rank -> local block offset
        mine = has & (winners >= 0) & (winners < mb)
        mst = mst.at[jnp.where(mine, winners, mb)].max(mine, mode="drop")
        return (fragment, mst, parent[gfa], parent[gfb], jnp.any(has), lv + 1)

    alive = jnp.sum((gfa != gfb).astype(jnp.int32)) > 0
    state = (fragment, mst, gfa, gfb, alive, jnp.zeros((), jnp.int32))
    fragment, mst, _, _, _, lv = jax.lax.while_loop(cond, body, state)
    return fragment, mst, lv


def _rank_sharded_finish(fragment, mst, fa, fb, *, fs_local: int,
                         max_levels: int, kernel="xla"):
    """Per-shard body: compact local survivors, all-gather, run the remaining
    levels replicated (each shard marks only its own rank block)."""
    mb = fa.shape[0]
    k = jax.lax.axis_index(EDGE_AXIS).astype(jnp.int32)
    crank_local = k * mb + jnp.arange(mb, dtype=jnp.int32)
    cfa, cfb, crank, _ = _compact_slots(fa, fb, crank_local, fs_local)
    return _finish_gathered_loop(
        fragment, mst, cfa, cfb, crank, k, mb, max_levels, kernel
    )


def _rank_sharded_finish_pre(fragment, mst, cfa, cfb, crank, *,
                             max_levels: int, kernel="xla"):
    """Per-shard body for ALREADY-COMPACTED survivors (the fused
    filter+compact path): all-gather + replicated levels only."""
    mb = mst.shape[0]
    k = jax.lax.axis_index(EDGE_AXIS).astype(jnp.int32)
    return _finish_gathered_loop(
        fragment, mst, cfa, cfb, crank, k, mb, max_levels, kernel
    )


# ---------------------------------------------------------------------------
# Filtered (filter-Kruskal) sharded path — see models/rank_solver.py for the
# exactness argument. The division of labor on the mesh:
#   * level 1 stays sharded (pmin owner lookups — n-sized traffic only);
#   * the prefix solve (levels 2+ over the lightest ranks) runs REPLICATED
#     on a replicated copy of the prefix block (2n ranks — small);
#   * the filter — the only edge-width work — is embarrassingly parallel:
#     each shard relabels its own rank block against the final prefix
#     partition with two local gathers, no collectives;
#   * the survivor finish reuses the existing compact/all-gather loop.
# Per-chip edge-width traffic drops from four gathers + a double-width
# segment_min to the two filter gathers.
# ---------------------------------------------------------------------------


def _rank_sharded_l1(vmin0, parent1, ra):
    """Per-shard body: level-1 marks only (the partition is ``parent1``).
    Returns ``(fragment, mst_local)``."""
    mb = ra.shape[0]
    k = jax.lax.axis_index(EDGE_AXIS).astype(jnp.int32)
    return parent1, _sharded_l1_marks(vmin0, mb, k)


def _rank_resume_relabel(fragment, ra, rb):
    """Per-shard body for checkpoint resume: rebuild the local rank block's
    endpoints from a restored vertex partition (exact from any saved
    partition — the remaining work is Borůvka from there). Two local
    gathers, no collectives beyond the survivor stats."""
    fa = fragment[ra]
    fb = fragment[rb]
    local_alive = jnp.sum((fa != fb).astype(jnp.int32))
    total = jax.lax.psum(local_alive, EDGE_AXIS)
    cmax = jax.lax.pmax(local_alive, EDGE_AXIS)
    return fa, fb, jnp.stack([total, cmax])


def _rank_sharded_level(fragment, mst, fa, fb, *, moe_fn=_moe_int32,
                        kernel="xla"):
    """Per-shard body: ONE Borůvka level over already-relabeled sharded
    endpoints, in place (per-shard ``segment_min`` + pmin combine,
    endpoints stay block-sharded — no survivor gather). Used when the alive
    set is still too wide for the compact/all-gather finish: each level
    at least halves the fragment count, so a few of these bring any state
    under the gather budget. ``moe_fn`` selects the int32 or split-key MOE
    strategy. Returns updated state + ``[total, cmax, progressed]``."""
    n = fragment.shape[0]
    k = jax.lax.axis_index(EDGE_AXIS).astype(jnp.int32)
    fragment, mst, fa, fb, has = _sharded_moe_level(
        fragment, mst, fa, fb, k, n, moe_fn, kernel
    )
    local_alive = jnp.sum((fa != fb).astype(jnp.int32))
    total = jax.lax.psum(local_alive, EDGE_AXIS)
    cmax = jax.lax.pmax(local_alive, EDGE_AXIS)
    return fragment, mst, fa, fb, jnp.stack(
        [total, cmax, jnp.any(has).astype(jnp.int32)]
    )


@jax.jit
def _prefix_relabel_l2(parent12, ra_p, rb_p, l2_ranks):
    """Replicated prefix phase entry with level 2 host-precomputed
    (``host_level2`` over the prefix ranks, staged replicated): one
    relabel plus the mark scatter — the replicated segment_min and hook
    never run on device. Returns ``(fragment, mst_p, fa, fb, stats)``
    with ``stats = [levels_past_1, prefix_alive]``."""
    prefix = ra_p.shape[0]
    fa = parent12[ra_p]
    fb = parent12[rb_p]
    has2 = l2_ranks < prefix  # pads carry m_pad and are dropped
    mst_p = jnp.zeros(prefix, dtype=bool).at[
        jnp.where(has2, l2_ranks, prefix)
    ].max(has2, mode="drop")
    count = jnp.sum((fa != fb).astype(jnp.int32))
    return parent12, mst_p, fa, fb, jnp.stack(
        [jnp.any(has2).astype(jnp.int32), count]
    )


def _filter_core(fragment, prefix_mask, mst, ra, rb, prefix, k):
    """The shared filter body of ``_rank_filter_relabel`` (two-step) and
    ``_rank_filter_compact`` (fused): relabel the local rank block against
    the final prefix partition (dropped slots are exactly the edges the
    cycle rule excludes; prefix slots are all intra-fragment by now and
    fall out of ``alive`` with no special-casing) and merge the replicated
    prefix MST marks into the shard that owns them. One body so the fused
    path and its overflow fallback cannot diverge semantically. Returns
    ``(mst, fa, fb, gi, total, cmax)``."""
    mb = ra.shape[0]
    gi = k * mb + jnp.arange(mb, dtype=jnp.int32)
    fa = fragment[ra]
    fb = fragment[rb]
    in_prefix = gi < prefix
    mst = mst | (in_prefix & prefix_mask[jnp.minimum(gi, prefix - 1)])
    local_alive = jnp.sum((fa != fb).astype(jnp.int32))
    total = jax.lax.psum(local_alive, EDGE_AXIS)
    cmax = jax.lax.pmax(local_alive, EDGE_AXIS)
    return mst, fa, fb, gi, total, cmax


def _rank_filter_relabel(fragment, prefix_mask, mst, ra, rb, *, prefix: int):
    """Per-shard body: the one edge-width pass (two-step form — the fused
    :func:`_rank_filter_compact` is the production path; this is its
    overflow fallback and the resume-adjacent entry)."""
    k = jax.lax.axis_index(EDGE_AXIS).astype(jnp.int32)
    mst, fa, fb, _gi, total, cmax = _filter_core(
        fragment, prefix_mask, mst, ra, rb, prefix, k
    )
    return mst, fa, fb, jnp.stack([total, cmax])


@functools.lru_cache(maxsize=64)
def make_prefix_slice(mesh: Mesh, prefix: int):
    """Replicate the prefix block from the already-staged sharded rank
    arrays on device (an ICI gather) — NOT a second host upload, which at
    v5e-8 RMAT-24 scale would re-send ~268 MB through the tunnel."""
    rep = NamedSharding(mesh, P())
    return jax.jit(lambda x: x[:prefix], out_shardings=rep)


@functools.lru_cache(maxsize=32)
def make_rank_sharded_l1(mesh: Mesh):
    mapped = shard_map_compat(
        _rank_sharded_l1,
        mesh,
        in_specs=(P(), P(), P(EDGE_AXIS)),
        out_specs=(P(), P(EDGE_AXIS)),
    )
    return jax.jit(mapped)


def _rank_filter_compact(
    fragment, prefix_mask, mst, ra, rb, *, prefix: int, fs_local: int
):
    """Fused per-shard filter + survivor compaction (r5): one dispatch, no
    mb-wide ``fa``/``fb`` HBM round trip between the filter and the finish
    (the sharded analog of the single-chip ``_filter_suffix_fused``;
    measured 0.98 + 0.55 s as two steps at RMAT-24/8 width).
    ``fs_local`` is speculative — callers read ``cmax`` from the stats and
    fall back to the two-step path on overflow. ``crank`` carries global
    ranks, so the output feeds ``_rank_sharded_finish_pre`` directly."""
    k = jax.lax.axis_index(EDGE_AXIS).astype(jnp.int32)
    mst, fa, fb, gi, total, cmax = _filter_core(
        fragment, prefix_mask, mst, ra, rb, prefix, k
    )
    cfa, cfb, crank, _ = _compact_slots(fa, fb, gi, fs_local)
    return mst, cfa, cfb, crank, jnp.stack([total, cmax])


@functools.lru_cache(maxsize=64)
def make_rank_filter_compact(mesh: Mesh, prefix: int, fs_local: int):
    fn = functools.partial(
        _rank_filter_compact, prefix=prefix, fs_local=fs_local
    )
    mapped = shard_map_compat(
        fn,
        mesh,
        in_specs=(P(), P(), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS)),
        out_specs=(
            P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS), P(),
        ),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=32)
def make_rank_sharded_finish_pre(mesh: Mesh, max_levels: int, kernel: str = "xla"):
    fn = functools.partial(
        _rank_sharded_finish_pre, max_levels=max_levels, kernel=kernel
    )
    mapped = shard_map_compat(
        fn,
        mesh,
        in_specs=(
            P(), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS),
        ),
        out_specs=(P(), P(EDGE_AXIS), P()),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=64)
def make_rank_filter_relabel(mesh: Mesh, prefix: int):
    fn = functools.partial(_rank_filter_relabel, prefix=prefix)
    mapped = shard_map_compat(
        fn,
        mesh,
        in_specs=(P(), P(), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS)),
        out_specs=(P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS), P()),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=32)
def make_mask_harvest(mesh: Mesh):
    """Pack each shard's MST mask to bits, then replicate the packed bytes
    with one tiled ``all_gather``. Shard widths are multiples of 8 (the
    staging pad guarantees it), so the concatenated per-shard bytes equal a
    global ``packbits`` of the full mask. The replicated result is fully
    addressable on every process — the multi-process harvest path."""

    def pack_gather(mst):
        w = mst.shape[0]
        if w > _PACKBITS_CHUNK:
            # A single full-width packbits fails to compile at 2^30 width
            # (rank_solver._PACKBITS_CHUNK's rationale); slice it. Widths
            # above the threshold are multiples of 8*n_dev, so every slice
            # stays byte-aligned.
            packed = jnp.concatenate([
                jnp.packbits(mst[s : min(s + _PACKBITS_CHUNK, w)])
                for s in range(0, w, _PACKBITS_CHUNK)
            ])
        else:
            packed = jnp.packbits(mst)
        return jax.lax.all_gather(packed, EDGE_AXIS, tiled=True)

    mapped = shard_map_compat(
        pack_gather, mesh, in_specs=(P(EDGE_AXIS),), out_specs=P()
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=32)
def make_rank_resume_relabel(mesh: Mesh):
    mapped = shard_map_compat(
        _rank_resume_relabel,
        mesh,
        in_specs=(P(), P(EDGE_AXIS), P(EDGE_AXIS)),
        out_specs=(P(EDGE_AXIS), P(EDGE_AXIS), P()),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=32)
def make_rank_sharded_level(mesh: Mesh, rank64: bool = False, kernel: str = "xla"):
    fn = functools.partial(
        _rank_sharded_level, moe_fn=_moe_kl if rank64 else _moe_int32,
        kernel=kernel,
    )
    mapped = shard_map_compat(
        fn,
        mesh,
        in_specs=(P(), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS)),
        out_specs=(P(), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS), P()),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=32)
def make_rank_sharded_head_kl(mesh: Mesh, kernel: str = "xla"):
    mapped = shard_map_compat(
        functools.partial(_rank_sharded_head_kl, kernel=kernel),
        mesh,
        in_specs=(P(), P(), P(), P(EDGE_AXIS), P(EDGE_AXIS)),
        out_specs=(P(), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS), P()),
    )
    return jax.jit(mapped)


# The all-gather finish replicates three n_dev * fs_local int32 arrays per
# chip; cap the gathered width at 2^25 slots (~400 MB total) and run
# in-place sharded levels until the alive set fits. Reachable from a resume
# off an early checkpoint (most ranks still alive) — the fresh paths arrive
# here already small.
_FINISH_GATHER_MAX_SLOTS = 1 << 25
# Checkpoint cadence inside the capacity-guard level loop (ADVICE r4): a
# high-diameter graph at capacity can run many in-place levels before the
# finish; save every K so a preemption there does not lose them all.
_GUARD_CHECKPOINT_EVERY = 4


def _full_mask_host(mesh, mst, m_pad: int, mst_p=None, prefix: int = 0):
    """Materialize the full-width rank mask on the host (checkpoint saves):
    harvest the block-sharded mask bit-packed, then overlay the replicated
    prefix-phase marks. Every process gets the full mask (the harvest is an
    all-gather), so checkpoint writes can be gated on the primary alone."""
    packed = np.asarray(make_mask_harvest(mesh)(mst))
    mask = np.unpackbits(packed, count=m_pad).astype(bool)
    if mst_p is not None:
        mask[:prefix] |= np.asarray(mst_p)[:prefix]
    return mask


@functools.lru_cache(maxsize=32)
def make_rank_sharded_head(mesh: Mesh, kernel: str = "xla"):
    mapped = shard_map_compat(
        functools.partial(_rank_sharded_head, kernel=kernel),
        mesh,
        in_specs=(P(), P(), P(EDGE_AXIS), P(EDGE_AXIS)),
        out_specs=(P(), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS), P()),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=64)
def make_rank_sharded_finish(
    mesh: Mesh, fs_local: int, max_levels: int, rank64: bool = False,
    kernel: str = "xla",
):
    fn = functools.partial(
        _rank_sharded_finish_kl if rank64 else _rank_sharded_finish,
        fs_local=fs_local, max_levels=max_levels, kernel=kernel,
    )
    mapped = shard_map_compat(
        fn,
        mesh,
        in_specs=(P(), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS)),
        out_specs=(P(), P(EDGE_AXIS), P()),
    )
    return jax.jit(mapped)


def solve_graph_rank_sharded(
    graph: Graph,
    *,
    mesh: Mesh | None = None,
    filtered: bool | None = None,
    on_chunk=None,
    initial_state: tuple | None = None,
    rank64: bool | None = None,
    kernel: str | None = None,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host entry mirroring ``solve_graph_rank`` on a device mesh.

    ``kernel`` (``None`` = process default) selects the fused Pallas
    hook+compress round inside the head / in-place level / finish
    programs (docs/KERNELS.md); the prefix phase of the filtered path
    keeps its XLA form (it runs replicated through the single-chip
    helpers).

    Plain path (small/sparse graphs): two dispatches — the sharded head
    (levels 1-2), then the compact/all-gather finish sized from the head's
    survivor stats. Dense graphs at filter scale route through the sharded
    filter-Kruskal path instead. ``filtered`` overrides the size/density
    policy, except that a graph without enough suffix beyond the prefix
    (``2 * prefix > m_pad``) always takes the plain path — the split would
    be degenerate there.

    ``on_chunk(level, vertex_fragment, mask_fn, count)`` fires after the
    head, each prefix-phase chunk, the filter, every
    ``_GUARD_CHECKPOINT_EVERY`` in-place levels of the capacity-guard loop
    (high-diameter graphs at capacity can spend many levels there), and
    the finish. Unlike the
    single-chip contract, the third argument is a ZERO-ARG CALLABLE that
    materializes the full-width mask on the host when invoked — the
    materialization is a collective (packed all-gather) plus a sizeable
    host transfer, so receivers skip it on chunks they don't save; because
    it is a collective, the decision to invoke it must be identical on
    every process (derive it from the chunk counter, not from local
    state). Both ``mask_fn`` and the fragment must be consumed during the
    callback: prefix-phase ``mask_fn`` calls return one shared host array,
    overlaid in place per save (marks are monotone, so the latest view is
    always correct — but earlier snapshots are not preserved; copy if you
    need history). ``initial_state`` is ``(fragment, mask, level)`` from
    a checkpoint — exact from any saved partition: the local rank blocks are
    relabeled against the restored partition (two local gathers per shard)
    and the survivors run through the normal compact/all-gather finish.

    ``rank64`` lifts the int32 rank envelope on this path with SPLIT KEYS:
    every global rank is ``k * mb + local`` under the block sharding, so
    rank state ships as int32 ``(shard, local)`` pairs and the cross-shard
    MOE combine becomes two sequential int32 pmins (blocks partition the
    total order, so the min rank lives in the smallest shard id holding a
    candidate). No int64 touches the device — s64 cross-replica
    reductions do not lower on TPU at all (measured; see docs/SCALING.md
    "Past int32") — and the memory footprint is unchanged. Auto-enabled
    when the padded rank space reaches 2^31 (the regime the single-chip
    path refuses); force ``True`` to exercise the split-key program at
    test widths. Routes through the plain path (``filtered=False`` — the
    filter split brings no benefit once the suffix no longer fits a chip
    anyway; the chunked single-chip filter covers up to the envelope).
    """
    if mesh is None:
        mesh = edge_mesh()
    from distributed_ghs_implementation_tpu.ops.pallas_kernels import (
        kernel_choice,
    )

    kernel = kernel_choice(kernel)
    n_dev = int(mesh.devices.size)
    n = graph.num_nodes
    if n == 0 or graph.num_edges == 0:
        return np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0

    n_pad = _bucket_size(n)
    # Shard widths must divide by 8 so the bit-packed harvest's per-shard
    # byte blocks concatenate into a global packbits (pad slots are inert).
    unit = 8 * n_dev
    m_pad = int(math.ceil(_bucket_size(graph.num_edges) / unit) * unit)
    if rank64 is None:
        rank64 = m_pad >= _INT32_RANK_LIMIT
    mb = m_pad // n_dev
    if rank64:
        # Only the rank space is lifted: vertex ids must still index int32
        # (2^31 vertices is out of scope for any projected pod), and the
        # PER-SHARD block must stay under 2^31 (local slot iotas and
        # offsets are int32).
        if n_pad >= _INT32_RANK_LIMIT:
            raise ValueError(
                f"rank64 lifts only the RANK space: padded vertex count "
                f"{n_pad:,} must stay below 2^31 (vertex ids are int32 "
                f"everywhere; no projected pod needs more)."
            )
        if mb >= _INT32_RANK_LIMIT:
            raise ValueError(
                f"split-key rank64 needs the per-shard rank block below "
                f"2^31: {m_pad:,} ranks over {n_dev} device(s) gives "
                f"mb = {mb:,}. Use a mesh with more devices."
            )
        filtered = False
    else:
        check_rank_envelope(n_pad, m_pad)
    int32_max = np.iinfo(np.int32).max
    ra_np, rb_np = graph.rank_endpoints(pad_to=m_pad)

    rep = NamedSharding(mesh, P())
    blk = NamedSharding(mesh, P(EDGE_AXIS))
    ra = _stage(ra_np, blk)
    rb = _stage(rb_np, blk)
    if initial_state is None:
        # Fresh solve: build the level-1 inputs. A resume never reads them
        # (the restored partition replaces parent1 and the marks), and at
        # the rank64 regime first_ranks64 + host_level1 are two O(m) host
        # passes worth skipping.
        if rank64:
            # Host-side rank ids are int64; the device sees only the int32
            # split keys (shard, local) derived from them.
            int64_max = np.iinfo(np.int64).max
            vmin0_np = np.full(n_pad, int64_max, dtype=np.int64)
            if m_pad >= _INT32_RANK_LIMIT:
                fr64 = None
                try:
                    from distributed_ghs_implementation_tpu.graphs import (
                        native,
                    )

                    if native.native_available():
                        # Reuse the padded int32 endpoints just built —
                        # first_ranks64 would re-gather int64 endpoints
                        # from u/v (~34 GB of host temporaries at the
                        # RMAT-27 scale this branch targets).
                        m = graph.num_edges
                        fr64 = native.first_rank_i32_out64_native(
                            n, ra_np[:m], rb_np[:m]
                        )
                except Exception:  # noqa: BLE001 — fallback below
                    pass
                vmin0_np[:n] = (
                    fr64 if fr64 is not None else graph.first_ranks64
                )
            else:
                # Forced-small validation: widen the int32 first_ranks,
                # remapping the isolated-vertex sentinel.
                fr = graph.first_ranks.astype(np.int64)
                vmin0_np[:n] = np.where(fr == int32_max, int64_max, fr)
        else:
            vmin0_np = np.full(n_pad, int32_max, dtype=np.int32)
            vmin0_np[:n] = graph.first_ranks
        parent1_np = host_level1(vmin0_np, ra_np, rb_np)
        parent1 = _stage(parent1_np, rep)
        if rank64:
            isolated = vmin0_np == np.iinfo(np.int64).max
            vk = _stage(
                np.where(isolated, int32_max, vmin0_np // mb).astype(
                    np.int32
                ),
                rep,
            )
            vl = _stage(
                np.where(isolated, 0, vmin0_np % mb).astype(np.int32), rep
            )
        else:
            vmin0 = _stage(vmin0_np, rep)

    prefix = _prefix_size(n_pad, m_pad, mult=1)  # tuned staged default
    if filtered is None:
        filtered = (
            use_filtered_path(_pick_family(graph), m_pad) and 2 * prefix <= m_pad
        )
    fused = None  # set by the filtered branch when its fused compact fits
    if initial_state is not None:
        frag_np, mask_np, lv = _restore_state_host(initial_state, n_pad, m_pad)
        fragment = _stage(frag_np, rep)
        mst = _stage(mask_np, blk)
        fa, fb, stats = make_rank_resume_relabel(mesh)(fragment, ra, rb)
        total, cmax = (int(x) for x in jax.device_get(stats))
    elif filtered and 2 * prefix <= m_pad:
        slice_rep = make_prefix_slice(mesh, prefix)
        ra_p = slice_rep(ra)
        rb_p = slice_rep(rb)
        l1 = make_rank_sharded_l1(mesh)
        fragment, mst = l1(vmin0, parent1, ra)
        # Host prefix-L2 (r5): the replicated level 2 becomes one relabel
        # plus a mark scatter — the n-space segment_min/hook never run on
        # device. parent12/l2 ride replicated (n-sized + compacted marks).
        parent12_np, l2r = host_level2(parent1_np, ra_np, rb_np, prefix)
        parent12 = _stage(parent12_np, rep)
        l2_staged = _stage(_pad_l2_ranks(l2r, m_pad), rep)
        fragment, mst_p, fa_p, fb_p, stats = _prefix_relabel_l2(
            parent12, ra_p, rb_p, l2_staged
        )
        lv2, count = (int(x) for x in jax.device_get(stats))
        lv = 1 + lv2
        hook = None
        if on_chunk is not None:
            # The sharded mask holds only the level-1 marks during the
            # whole prefix phase — harvest it at most once (lazily; the
            # harvest is a collective + host transfer) and overlay the
            # prefix marks per save. Prefix marks are monotone, so the
            # in-place overlay stays correct across saves. The receiver's
            # decision to invoke mask_fn must be identical on every
            # process (see the docstring).
            l1_cache = []

            def hook(lv_, frag_, mstp_, count_):
                def mask_fn():
                    if not l1_cache:
                        l1_cache.append(_full_mask_host(mesh, mst, m_pad))
                    full = l1_cache[0]
                    full[:prefix] |= np.asarray(mstp_)[:prefix]
                    return full

                on_chunk(lv_, frag_, mask_fn, count_)

            hook(lv, fragment, mst_p, count)
        mst_p, fragment, lv = _finish_to_fixpoint(
            fragment, mst_p, fa_p, fb_p, jnp.arange(prefix, dtype=jnp.int32),
            lv=lv, count=count, space=n_pad, max_levels=lv + _max_levels(n_pad),
            chunk_levels=3, compact_space=n_pad >= _CENSUS_MIN_SPACE,
            on_chunk=hook,
        )
        # Fused filter + compaction (speculative survivor width; the
        # gathered width is clamped under the finish budget so the
        # capacity guard is never needed on this path). Overflow falls
        # back to the exact two-step filter (re-merging the prefix marks
        # is idempotent).
        fs_spec = min(
            max(_bucket_size(mb // 128), 1024),
            _FINISH_GATHER_MAX_SLOTS // n_dev,
        )
        fc = make_rank_filter_compact(mesh, prefix, fs_spec)
        mst, cfa, cfb, crank, fstats = fc(fragment, mst_p, mst, ra, rb)
        total, cmax = (int(x) for x in jax.device_get(fstats))
        if cmax <= fs_spec:
            fused = (cfa, cfb, crank)
            fa = fb = None
        else:
            fused = None
            del cfa, cfb, crank
            filt = make_rank_filter_relabel(mesh, prefix)
            mst, fa, fb, fstats = filt(fragment, mst_p, mst, ra, rb)
            total, cmax = (int(x) for x in jax.device_get(fstats))
    elif rank64:
        head = make_rank_sharded_head_kl(mesh, kernel)
        fragment, mst, fa, fb, stats = head(vk, vl, parent1, ra, rb)
        lv, total, cmax = (int(x) for x in jax.device_get(stats))
    else:
        head = make_rank_sharded_head(mesh, kernel)
        fragment, mst, fa, fb, stats = head(vmin0, parent1, ra, rb)
        lv, total, cmax = (int(x) for x in jax.device_get(stats))
    if on_chunk is not None and initial_state is None:
        # Bind the buffer per-site (default arg): the hook sites share this
        # function scope, and a late-binding closure over a rebound local
        # would silently hand a held mask_fn a LATER level's mask.
        on_chunk(
            lv, fragment,
            lambda mst_=mst: _full_mask_host(mesh, mst_, m_pad), total,
        )
    if fused is not None:
        # Fused filtered path: survivors arrive pre-compacted and the
        # gathered width is under the finish budget by construction — no
        # capacity guard.
        if total > 0:
            finish = make_rank_sharded_finish_pre(
                mesh, _max_levels(n_pad), kernel
            )
            fragment, mst, extra = finish(fragment, mst, *fused)
            lv += int(extra)
            if on_chunk is not None:
                on_chunk(
                    lv, fragment,
                    lambda mst_=mst: _full_mask_host(mesh, mst_, m_pad), 0,
                )
    else:
        # Capacity guard before the finish: shrink the alive set with
        # in-place sharded levels while the would-be gathered width exceeds
        # the budget. A high-diameter graph can spend many levels here, so
        # checkpoint every _GUARD_CHECKPOINT_EVERY iterations — the decision
        # is a pure function of the loop counter, hence SPMD-identical
        # across processes (the harvest inside mask_fn is a collective).
        guard_iters = 0
        while total > 0 and n_dev * _bucket_size(cmax) > _FINISH_GATHER_MAX_SLOTS:
            level_fn = make_rank_sharded_level(mesh, rank64, kernel)
            fragment, mst, fa, fb, lstats = level_fn(fragment, mst, fa, fb)
            total, cmax, progressed = (int(x) for x in jax.device_get(lstats))
            lv += 1
            guard_iters += 1
            if not progressed:
                break  # isolated remainder (disconnected pads)
            if on_chunk is not None and guard_iters % _GUARD_CHECKPOINT_EVERY == 0:
                on_chunk(
                    lv, fragment,
                    lambda mst_=mst: _full_mask_host(mesh, mst_, m_pad), total,
                )
        if total > 0:
            fs_local = max(_bucket_size(cmax), 1024)
            finish = make_rank_sharded_finish(
                mesh, fs_local, _max_levels(n_pad), rank64, kernel
            )
            fragment, mst, extra = finish(fragment, mst, fa, fb)
            lv += int(extra)
            if on_chunk is not None:
                on_chunk(
                    lv, fragment,
                    lambda mst_=mst: _full_mask_host(mesh, mst_, m_pad), 0,
                )
    if jax.process_count() > 1:
        # One packed all-gather makes the rank-block-sharded mask
        # addressable on every process.
        packed = np.asarray(make_mask_harvest(mesh)(mst))
        edge_ids = packed_to_edge_ids(graph, packed, m_pad)
    else:
        # Single process: every shard is addressable; the measured chunked
        # fetch (one dispatch per packbits slice) skips the all-gather.
        edge_ids = fetch_mst_edge_ids(graph, mst)
    return edge_ids, np.asarray(fragment)[:n], lv
