"""The stream/ subsystem: windowed batched maintenance (coalescing +
edge-for-edge parity vs fresh solves), the durable update log (torn tail,
``.bak`` fallback, snapshot/WAL disagreement, two-process flock hammer),
replay recovery that never touches the solver, subscription sessions over
the serve ops, and the stream.* SLO/warmup plumbing."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import (
    gnm_random_graph,
)
from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.serve.dynamic import DynamicMST, Update
from distributed_ghs_implementation_tpu.stream.log import ChainBreak, UpdateLog
from distributed_ghs_implementation_tpu.stream.session import (
    StaleDigest,
    StreamManager,
    poll_gap_check,
)
from distributed_ghs_implementation_tpu.stream.window import (
    WindowedMST,
    coalesce,
)


def _random_graph(rng, n, m, wmax=50):
    return Graph.from_arrays(
        n,
        rng.integers(0, n, m),
        rng.integers(0, n, m),
        rng.integers(1, wmax + 1, m),
    )


def _random_update(rng, dyn, n, wmax=50):
    kind = str(rng.choice(["insert", "delete", "reweight"]))
    if kind in ("delete", "reweight") and dyn._u.size and rng.random() < 0.7:
        i = int(rng.integers(0, dyn._u.size))
        a, b = int(dyn._u[i]), int(dyn._v[i])
        if kind == "delete":
            return Update("delete", a, b)
        return Update("reweight", a, b, int(rng.integers(1, wmax + 1)))
    a, b = (int(x) for x in rng.integers(0, n, 2))
    while a == b:
        a, b = (int(x) for x in rng.integers(0, n, 2))
    if kind == "delete":
        return Update("delete", min(a, b), max(a, b))
    return Update("insert", min(a, b), max(a, b), int(rng.integers(1, wmax + 1)))


def _check_exact(result, context=""):
    ids_ref, frag_ref, _ = solve_graph(result.graph)
    assert np.array_equal(np.sort(result.edge_ids), np.sort(ids_ref)), context
    assert result.num_components == int(np.unique(frag_ref).size), context


# ----------------------------------------------------------------------
# Coalescing (the dynamic.py same-edge-pair correctness fix)
# ----------------------------------------------------------------------
def test_coalesce_last_write_wins_per_edge():
    net = coalesce([
        Update("insert", 0, 1, 5),
        Update("reweight", 1, 0, 7),   # same edge, either orientation
        Update("delete", 2, 3),
        Update("insert", 2, 3, 9),     # delete -> insert nets to a set
    ])
    assert [(u.kind, u.u, u.v, u.w) for u in net] == [
        ("insert", 0, 1, 7),
        ("insert", 2, 3, 9),
    ]


def test_coalesce_self_cancelling_and_duplicates():
    # insert -> delete of a never-existing edge vanishes entirely as a
    # delete (a defined no-op); duplicate deletes collapse.
    net = coalesce([
        Update("insert", 4, 5, 3),
        Update("delete", 4, 5),
        Update("delete", 4, 5),
    ])
    assert [(u.kind, u.u, u.v) for u in net] == [("delete", 4, 5)]


def test_coalesce_order_independent():
    a = coalesce([Update("insert", 0, 1, 5), Update("delete", 2, 3),
                  Update("reweight", 0, 1, 9)])
    b = coalesce([Update("delete", 2, 3), Update("insert", 0, 1, 9)])
    assert [(u.kind, u.u, u.v, u.w) for u in a] == [
        (u.kind, u.u, u.v, u.w) for u in b
    ]


@pytest.mark.parametrize("seed", [0, 1])
def test_coalesced_window_matches_arrival_order_per_update(seed):
    """A window applied per-update in arrival order and the same window
    coalesced-then-windowed must land on the identical forest — including
    duplicate, reordered, and self-cancelling same-edge pairs."""
    rng = np.random.default_rng(300 + seed)
    n = 60
    g = _random_graph(rng, n, 180)
    result = minimum_spanning_forest(g)
    seq = DynamicMST(result, resolve_threshold=10**9)
    win = WindowedMST(result, resolve_threshold=10**9)
    for _ in range(4):
        raw = []
        for _ in range(10):
            upd = _random_update(rng, seq, n)
            raw.append(upd)
            if rng.random() < 0.4:  # same-edge churn: dup/reorder/cancel
                if rng.random() < 0.5:
                    raw.append(Update("delete", upd.u, upd.v))
                else:
                    raw.append(Update("insert", upd.u, upd.v,
                                      int(rng.integers(1, 51))))
        for upd in raw:
            seq.apply([upd])
        win_result, info = win.apply_window(raw)
        assert info.coalesced_from == len(raw)
        seq_result = seq.result()
        assert np.array_equal(seq_result.graph.u, win_result.graph.u)
        assert np.array_equal(seq_result.graph.w, win_result.graph.w)
        assert np.array_equal(
            np.sort(seq_result.edge_ids), np.sort(win_result.edge_ids)
        )
        _check_exact(win_result, seed)


# ----------------------------------------------------------------------
# Windowed batched apply: parity + escape hatches
# ----------------------------------------------------------------------
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_windowed_stream_parity_vs_fresh_solve(seed):
    rng = np.random.default_rng(100 + seed)
    n = 80
    g = _random_graph(rng, n, 240)
    dyn = WindowedMST(minimum_spanning_forest(g))
    for step in range(6):
        window = [
            _random_update(rng, dyn, n)
            for _ in range(int(rng.integers(1, 24)))
        ]
        result, info = dyn.apply_window(window)
        assert info.mode in ("batched", "noop"), (seed, step, info.mode)
        _check_exact(result, (seed, step))
    assert dyn.last_mode == "window"


def test_window_modes_agree_edge_for_edge():
    rng = np.random.default_rng(7)
    n = 70
    g = _random_graph(rng, n, 200)
    result = minimum_spanning_forest(g)
    sessions = {
        mode: WindowedMST(result, window_mode=mode, resolve_threshold=10**9)
        for mode in ("batched", "sequential", "resolve")
    }
    for _ in range(3):
        window = [
            _random_update(rng, sessions["batched"], n) for _ in range(8)
        ]
        outs = {m: s.apply_window(window) for m, s in sessions.items()}
        ids = {
            m: np.sort(r.edge_ids).tolist() for m, (r, _) in outs.items()
        }
        assert ids["batched"] == ids["sequential"] == ids["resolve"]
        assert outs["batched"][1].mode == "batched"
        assert outs["sequential"][1].mode == "sequential"
        assert outs["resolve"][1].mode == "resolve"


def test_oversized_window_degrades_to_resolve():
    BUS.enable()
    BUS.clear()
    rng = np.random.default_rng(11)
    g = _random_graph(rng, 50, 150)
    dyn = WindowedMST(
        minimum_spanning_forest(g), window_resolve_threshold=3
    )
    window = [_random_update(rng, dyn, 50) for _ in range(12)]
    result, info = dyn.apply_window(window)
    assert info.mode == "resolve"
    assert BUS.counters()["stream.window.over_threshold"] == 1
    _check_exact(result)
    BUS.clear()


def test_window_verify_failure_falls_back_to_resolve(monkeypatch):
    BUS.enable()
    BUS.clear()
    g = Graph.from_edges(4, [(0, 1, 1), (1, 2, 2), (2, 3, 3), (0, 3, 9)])
    dyn = WindowedMST(minimum_spanning_forest(g))
    monkeypatch.setattr(dyn, "_forest_ok", lambda: False)
    result, info = dyn.apply_window([Update("reweight", 0, 1, 2)])
    assert info.mode == "resolve"
    assert BUS.counters()["stream.window.verify_failed"] == 1
    assert result.total_weight == 2 + 2 + 3
    BUS.clear()


def test_noop_window_keeps_digest_and_reports_nothing():
    g = Graph.from_edges(3, [(0, 1, 5), (1, 2, 6)])
    dyn = WindowedMST(minimum_spanning_forest(g))
    before = dyn.result().graph.digest()
    result, info = dyn.apply_window([
        Update("insert", 0, 2, 4), Update("delete", 0, 2),  # self-cancel
        Update("delete", 0, 2),  # absent: no-op
    ])
    assert info.mode == "noop" or info.applied <= 1  # net delete is a no-op
    assert result.graph.digest() == before
    assert info.entered == [] and info.left == []
    assert info.weight_delta == 0


def test_window_notification_contents():
    g = Graph.from_edges(4, [(0, 1, 1), (1, 2, 2), (2, 3, 3), (0, 3, 9)])
    dyn = WindowedMST(minimum_spanning_forest(g))
    # (0,3,9) is the only non-tree edge; make it cheap and drop (1,2).
    result, info = dyn.apply_window([
        Update("reweight", 0, 3, 1), Update("delete", 1, 2),
    ])
    assert (0, 3, 1) in info.entered
    assert (1, 2, 2) in info.left
    expected_delta = result.graph.w[result.edge_ids].sum() - (1 + 2 + 3)
    assert info.weight_delta == expected_delta


def test_window_validation_rejects_bad_updates_before_mutation():
    g = Graph.from_edges(3, [(0, 1, 5), (1, 2, 6)])
    dyn = WindowedMST(minimum_spanning_forest(g))
    with pytest.raises(ValueError, match="out of range"):
        dyn.apply_window([Update("insert", 0, 99, 2)])
    assert not dyn.dirty
    result, info = dyn.apply_window([Update("insert", 0, 2, 4)])
    assert result.total_weight == 5 + 4 or result.total_weight == 5 + 6


def test_state_arrays_round_trip_without_solving(monkeypatch):
    rng = np.random.default_rng(5)
    g = _random_graph(rng, 40, 120)
    dyn = WindowedMST(minimum_spanning_forest(g))
    dyn.apply_window([_random_update(rng, dyn, 40) for _ in range(6)])
    state = dyn.state_arrays()
    import distributed_ghs_implementation_tpu.serve.dynamic as dyn_mod

    def bomb(*a, **k):
        raise AssertionError("from_state must not solve")

    monkeypatch.setattr(dyn_mod, "minimum_spanning_forest", bomb)
    rebuilt = WindowedMST.from_state(state)
    assert rebuilt.result().graph.digest() == dyn.result().graph.digest()
    assert np.array_equal(
        np.sort(rebuilt.result().edge_ids), np.sort(dyn.result().edge_ids)
    )


# ----------------------------------------------------------------------
# Durable log: torn tail, .bak fallback, chain breaks, compaction
# ----------------------------------------------------------------------
def _seed_log(tmp_path, windows=3):
    log = UpdateLog(str(tmp_path), "s1")
    log.snapshot(
        {"num_nodes": np.asarray(4), "u": np.arange(3), "v": np.arange(1, 4),
         "w": np.ones(3, dtype=np.int64), "in_tree": np.ones(3, dtype=bool)},
        seq=0, digest="d0",
    )
    for i in range(1, windows + 1):
        log.append(seq=i, prev_digest=f"d{i-1}", digest=f"d{i}",
                   updates=[{"kind": "insert", "u": 0, "v": i, "w": i}])
    return log


def test_log_round_trip_and_chaining(tmp_path):
    log = _seed_log(tmp_path, windows=3)
    state, entries, notes = log.load()
    assert state is not None and state["seq"] == 0 and state["digest"] == "d0"
    assert [e["seq"] for e in entries] == [1, 2, 3]
    assert entries[-1]["digest"] == "d3"


def test_log_torn_tail_is_skipped_not_fatal(tmp_path):
    BUS.enable()
    BUS.clear()
    log = _seed_log(tmp_path, windows=3)
    with open(log.wal_path, "rb+") as f:
        f.seek(-9, os.SEEK_END)
        f.truncate()  # tear mid-record, no trailing newline
    state, entries, _notes = log.load()
    assert [e["seq"] for e in entries] == [1, 2]  # the torn third is gone
    assert BUS.counters()["stream.log.torn_skipped"] >= 1
    BUS.clear()


def test_log_append_seals_torn_tail_keeping_both_parseable(tmp_path):
    """A retried append after a torn tail must not fuse the new record
    onto the partial line: the garbage is sealed onto its own line (and
    skipped on read) so the committed retry replays."""
    BUS.enable()
    BUS.clear()
    log = UpdateLog(str(tmp_path), "s")
    log.append(seq=1, prev_digest="a", digest="b", updates=[])
    with open(log.wal_path, "a") as f:
        f.write('{"schema": "ghs-stream-wal-v1", "seq": 2, "pre')  # torn
    log.append(seq=2, prev_digest="b", digest="c", updates=[])
    entries, _torn = log._read_wal()
    assert [e["seq"] for e in entries] == [1, 2]
    counters = BUS.counters()
    assert counters["stream.log.sealed_torn"] == 1
    assert counters["stream.log.corrupt_line"] == 1  # the sealed garbage
    BUS.clear()


def test_log_snapshot_bak_fallback(tmp_path):
    BUS.enable()
    BUS.clear()
    log = _seed_log(tmp_path, windows=1)
    # A second snapshot rotates the first to .bak; then tear the primary.
    log.snapshot(
        {"num_nodes": np.asarray(4), "u": np.arange(3), "v": np.arange(1, 4),
         "w": np.ones(3, dtype=np.int64), "in_tree": np.ones(3, dtype=bool)},
        seq=1, digest="d1",
    )
    with open(log.snap_path, "wb") as f:
        f.write(b"torn")
    state, notes = log.load_snapshot()
    assert state is not None and state["seq"] == 0  # the .bak generation
    assert BUS.counters()["stream.log.snap_fallback"] == 1
    assert any("torn" not in p and why != "missing" for p, why in notes)
    BUS.clear()


def test_log_chain_break_stops_replay_at_disagreement(tmp_path):
    """Snapshot/log disagreement: a WAL whose entries do not follow from
    the snapshot replays only the verifiable prefix."""
    BUS.enable()
    BUS.clear()
    log = _seed_log(tmp_path, windows=3)
    # Corrupt entry 2's chain: its prev no longer matches entry 1's digest.
    with open(log.wal_path) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    lines[1]["prev"] = "divergent"
    with open(log.wal_path, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    state, entries, notes = log.load()
    assert [e["seq"] for e in entries] == [1]
    assert BUS.counters()["stream.log.chain_broken"] == 1
    assert any("chain break" in why for _p, why in notes)
    BUS.clear()


def test_log_chain_break_repair_lets_append_extend_recovered_head(tmp_path):
    """load() truncates the WAL past a chain break: append validates
    against the LAST parsable line, so leaving the unreachable tail in
    place would refuse every publish from the recovered head forever
    (ChainBreak -> StaleDigest with the dead tail digest -> the client
    adopts it -> the session recovers back to the chained head: a re-sync
    livelock)."""
    BUS.enable()
    BUS.clear()
    log = _seed_log(tmp_path, windows=3)
    with open(log.wal_path) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    lines[1]["prev"] = "divergent"
    with open(log.wal_path, "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    state, entries, _notes = log.load()
    assert [e["seq"] for e in entries] == [1]
    assert BUS.counters()["stream.log.chain_truncated"] == 1
    # The durable tail now IS the recovered head, so extending it works.
    assert log._durable_head() == (1, "d1")
    log.append(seq=2, prev_digest="d1", digest="d2-repaired", updates=[])
    state, entries, _notes = log.load()
    assert [(e["seq"], e["digest"]) for e in entries] == [
        (1, "d1"), (2, "d2-repaired"),
    ]
    BUS.clear()


def test_log_compaction_drops_covered_entries(tmp_path):
    log = _seed_log(tmp_path, windows=4)
    log.snapshot(
        {"num_nodes": np.asarray(4), "u": np.arange(3), "v": np.arange(1, 4),
         "w": np.ones(3, dtype=np.int64), "in_tree": np.ones(3, dtype=bool)},
        seq=3, digest="d3",
    )
    entries, _ = log._read_wal()
    assert [e["seq"] for e in entries] == [4]  # 1..3 compacted away
    state, chained, _ = log.load()
    assert state["seq"] == 3 and [e["seq"] for e in chained] == [4]


def test_log_two_process_flock_hammer(tmp_path):
    """Two real processes appending to one stream WAL concurrently must
    interleave cleanly — every line whole, parseable, and accounted for
    (mirrors the round-12 store hammer) — AND come out as ONE chain: an
    append that lost the race gets ChainBreak (the fork guard) instead of
    forking the log, so each writer re-reads the durable tail and
    retries."""
    wal_dir = str(tmp_path / "shared")
    child = (
        "import sys\n"
        "from distributed_ghs_implementation_tpu.stream.log import (\n"
        "    ChainBreak, UpdateLog)\n"
        "log = UpdateLog(sys.argv[1], 'hammer')\n"
        "who = sys.argv[2]\n"
        "done = 0\n"
        "while done < 25:\n"
        "    tail = log._durable_head()  # racy peek; append re-validates\n"
        "    seq = (tail[0] if tail else 0) + 1\n"
        "    prev = tail[1] if tail else 'seed'\n"
        "    try:\n"
        "        log.append(seq=seq, prev_digest=prev,\n"
        "                   digest=f'{who}-{seq}',\n"
        "                   updates=[{'kind': 'insert', 'u': 0, 'v': 1,\n"
        "                             'w': seq}])\n"
        "    except ChainBreak:\n"
        "        continue  # the other writer committed first\n"
        "    done += 1\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}
    procs = [
        subprocess.Popen([sys.executable, "-c", child, wal_dir, who],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE)
        for who in ("a", "b")
    ]
    for p in procs:
        _, err = p.communicate(timeout=120)
        assert p.returncode == 0, err.decode()
    log = UpdateLog(wal_dir, "hammer")
    with open(log.wal_path) as f:
        lines = [line for line in f.read().split("\n") if line]
    records = [json.loads(line) for line in lines]  # every line parses
    assert len(records) == 50
    assert [rec["seq"] for rec in records] == list(range(1, 51))
    prev = "seed"
    for rec in records:  # one unforked chain across both writers
        assert rec["prev"] == prev
        prev = rec["digest"]
    writers = [rec["digest"].split("-")[0] for rec in records]
    assert writers.count("a") == 25 and writers.count("b") == 25


# ----------------------------------------------------------------------
# Replay: recovery without a single fresh solve
# ----------------------------------------------------------------------
def _drive_stream(root, *, windows=5, snapshot_every=2, seed=9):
    rng = np.random.default_rng(seed)
    g = gnm_random_graph(60, 180, seed=seed)
    result = minimum_spanning_forest(g)
    mgr = StreamManager(root=root, snapshot_every=snapshot_every)
    session = mgr.subscribe(digest=g.digest(), result=result)
    head = session.head
    for _ in range(windows):
        window = [
            upd.__dict__
            for upd in (
                _random_update(rng, session.mst, 60) for _ in range(4)
            )
        ]
        head = mgr.publish(session.id, head, window)["digest"]
    return mgr, session, head


def test_replay_recovers_head_and_notifications_without_solving(
    tmp_path, monkeypatch
):
    BUS.enable()
    root = str(tmp_path)
    _mgr, session, head = _drive_stream(root, windows=5, snapshot_every=2)
    import distributed_ghs_implementation_tpu.serve.dynamic as dyn_mod

    def bomb(*a, **k):
        raise AssertionError("replay must never solve")

    monkeypatch.setattr(dyn_mod, "minimum_spanning_forest", bomb)
    BUS.clear()
    fresh = StreamManager(root=root, snapshot_every=2)
    recovered = fresh.recover(session.id)
    assert recovered is not None
    assert recovered.head == head
    assert recovered.seq == 5
    # The full notification ring is available again: gap/dup-free 1..5.
    poll = fresh.poll(session.id, after_seq=0)
    seqs = [n["seq"] for n in poll["notifications"]]
    assert poll_gap_check(seqs, poll["seq"]) == {"gaps": 0, "dups": 0}
    counters = BUS.counters()
    assert counters["stream.replay.streams"] == 1
    assert counters["stream.replay.windows"] >= 1
    BUS.clear()


def test_replay_windows_carry_publish_trace_with_fresh_spans(tmp_path):
    """Trace continuity across the WAL: each committed window persists its
    publish-time trace context, so a crash-recovery replay re-applies it
    under the ORIGINAL trace id — with fresh span ids parented on the
    publish-time window span, so one trace shows both the live commit and
    its later replay."""
    from distributed_ghs_implementation_tpu.obs import tracing

    BUS.enable()
    root = str(tmp_path)
    ctx = tracing.mint("update")
    token = tracing.activate(ctx)
    try:
        # snapshot_every=10: only the seed snapshot lands, so recovery
        # must WAL-replay every one of the 3 published windows.
        _mgr, session, head = _drive_stream(
            root, windows=3, snapshot_every=10
        )
    finally:
        tracing.deactivate(token)
    publish_spans = {
        args["span"]
        for _ph, name, _c, _t, _d, _tid, args in BUS.events()
        if name == "stream.window" and args
        and args.get("trace") == ctx.trace_id
    }
    assert len(publish_spans) == 3
    BUS.clear()
    fresh = StreamManager(root=root, snapshot_every=10)
    recovered = fresh.recover(session.id)
    assert recovered is not None and recovered.head == head
    replays = [
        args for _ph, name, _c, _t, _d, _tid, args in BUS.events()
        if name == "stream.replay.window" and args
    ]
    assert len(replays) == 3
    for args in replays:
        assert args.get("trace") == ctx.trace_id  # the ORIGINAL trace
        assert args["span"] not in publish_spans  # ...as a fresh span
        assert args.get("parent") in publish_spans  # under its commit
    BUS.clear()


def test_subscribe_by_seed_digest_recovers_after_restart(tmp_path):
    """A restarted process that never solved the seed can still subscribe
    by the SEED digest: the stream id derives from it, so recovery finds
    the on-disk log even though the head has long moved on."""
    root = str(tmp_path)
    _mgr, session, head = _drive_stream(root, windows=3)
    seed_digest = gnm_random_graph(60, 180, seed=9).digest()
    fresh = StreamManager(root=root)
    recovered = fresh.subscribe(digest=seed_digest)
    assert recovered.id == session.id
    assert recovered.head == head


def test_publish_against_stale_head_raises_with_current_head(tmp_path):
    _mgr, session, head = _drive_stream(str(tmp_path), windows=2)
    with pytest.raises(StaleDigest) as exc:
        _mgr.publish(session.id, "not-the-head", [])
    assert exc.value.head == head
    assert exc.value.seq == 2


def test_poll_gap_check():
    assert poll_gap_check([1, 2, 3], 3) == {"gaps": 0, "dups": 0}
    assert poll_gap_check([1, 3], 3) == {"gaps": 1, "dups": 0}
    assert poll_gap_check([1, 2, 2, 3], 3) == {"gaps": 0, "dups": 1}
    # A mid-chain joiner (subscribe returned seq=40) only owes 41+.
    assert poll_gap_check([41, 42], 42, start_seq=40) == {"gaps": 0, "dups": 0}
    assert poll_gap_check([42], 42, start_seq=40) == {"gaps": 1, "dups": 0}


# ----------------------------------------------------------------------
# Service-level verbs + store chain eviction
# ----------------------------------------------------------------------
@pytest.fixture
def stream_service(tmp_path):
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    BUS.enable()
    BUS.clear()
    yield MSTService(
        stream_dir=str(tmp_path / "streams"), stream_snapshot_every=2
    )
    BUS.clear()


def _solve_request(g, **extra):
    return {
        "op": "solve",
        "num_nodes": g.num_nodes,
        "edges": [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)],
        **extra,
    }


def test_service_subscribe_publish_poll_flow(stream_service):
    g = gnm_random_graph(50, 150, seed=21)
    solved = stream_service.handle(_solve_request(g))
    assert solved["ok"]
    sub = stream_service.handle({"op": "subscribe", "digest": solved["digest"]})
    assert sub["ok"] and sub["seq"] == 0
    head = sub["digest"]
    for i in range(3):
        pub = stream_service.handle({
            "op": "publish", "stream": sub["stream"], "digest": head,
            "updates": [{"kind": "insert", "u": 0, "v": 10 + i, "w": 1}],
        })
        assert pub["ok"], pub
        assert pub["prev_digest"] == head
        assert pub["seq"] == i + 1
        head = pub["digest"]
    assert pub["notification"]["entered"]
    poll = stream_service.handle({
        "op": "poll", "stream": sub["stream"], "after_seq": 0,
    })
    assert [n["seq"] for n in poll["notifications"]] == [1, 2, 3]
    assert poll["digest"] == head
    # Stale publish: structured re-sync response, not a generic error.
    stale = stream_service.handle({
        "op": "publish", "stream": sub["stream"], "digest": sub["digest"],
        "updates": [],
    })
    assert stale["ok"] is False and stale["stale"] is True
    assert stale["digest"] == head and stale["seq"] == 3
    stats = stream_service.handle({"op": "stats"})
    assert stats["streams"] == 1
    # snapshot_every=2 and 3 commits → a durable snapshot exists, so the
    # stream also counts as recoverable-from-disk.
    assert stats["streams_recoverable"] == 1
    assert stats["counters"]["stream.window.committed"] == 3


def test_service_publish_evicts_chain_ancestor_from_lru(stream_service):
    from distributed_ghs_implementation_tpu.serve.store import (
        cache_key_for_digest,
    )

    g = gnm_random_graph(50, 150, seed=22)
    solved = stream_service.handle(_solve_request(g))
    sub = stream_service.handle({"op": "subscribe", "digest": solved["digest"]})
    pub = stream_service.handle({
        "op": "publish", "stream": sub["stream"], "digest": sub["digest"],
        "updates": [{"kind": "insert", "u": 1, "v": 7, "w": 2}],
    })
    assert pub["ok"]
    store = stream_service.store
    assert store.get(
        cache_key_for_digest(sub["digest"]), record_miss=False
    ) is None  # the superseded ancestor left the LRU
    assert store.get(
        cache_key_for_digest(pub["digest"]), record_miss=False
    ) is not None  # the new head is cached
    assert BUS.counters()["serve.store.chain_evicted"] >= 1


def test_service_noop_publish_keeps_head_cached(stream_service):
    """A window with no net effect (prev == new digest) must not evict
    the result it just cached — the chain did not move."""
    from distributed_ghs_implementation_tpu.serve.store import (
        cache_key_for_digest,
    )

    g = gnm_random_graph(50, 150, seed=23)
    solved = stream_service.handle(_solve_request(g))
    sub = stream_service.handle({"op": "subscribe", "digest": solved["digest"]})
    before = BUS.counters().get("serve.store.chain_evicted", 0)
    pub = stream_service.handle({
        "op": "publish", "stream": sub["stream"], "digest": sub["digest"],
        "updates": [],
    })
    assert pub["ok"] and pub["mode"] == "noop"
    assert pub["digest"] == pub["prev_digest"] == sub["digest"]
    assert stream_service.store.get(
        cache_key_for_digest(sub["digest"]), record_miss=False
    ) is not None  # the head survived its own commit
    assert BUS.counters().get("serve.store.chain_evicted", 0) == before


def test_service_subscribe_falls_back_to_store_after_session_eviction(
    tmp_path,
):
    """The parked update-session seed is LRU-bounded; subscribe-by-digest
    must fall back to the result store so the advertised
    recover-by-resubscribe path survives session churn without a solve."""
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    BUS.enable()
    BUS.clear()
    service = MSTService(
        stream_dir=str(tmp_path / "streams"),
        max_sessions=1,  # the next solve evicts the parked seed
    )
    g = gnm_random_graph(40, 120, seed=31)
    solved = service.handle(_solve_request(g))
    assert solved["ok"]
    other = gnm_random_graph(40, 120, seed=32)
    assert service.handle(_solve_request(other))["ok"]
    assert solved["digest"] not in service._sessions  # seed evicted
    sub = service.handle({"op": "subscribe", "digest": solved["digest"]})
    assert sub["ok"], sub  # seeded from the store's memory LRU
    assert sub["digest"] == solved["digest"] and sub["seq"] == 0
    BUS.clear()


def test_service_subscribe_unknown_digest_errors(stream_service):
    out = stream_service.handle({"op": "subscribe", "digest": "nope"})
    assert out["ok"] is False
    assert "solve the graph first" in out["error"]


def test_store_evict_chain_unit():
    from distributed_ghs_implementation_tpu.serve.store import ResultStore

    store = ResultStore(capacity=4)
    g = gnm_random_graph(20, 40, seed=1)
    res = minimum_spanning_forest(g)
    store.put("k1:device", res)
    assert store.evict_chain("k1:device") is True
    assert store.evict_chain("k1:device") is False  # already gone
    assert len(store) == 0


# ----------------------------------------------------------------------
# SLO taxonomy + warmup plumbing
# ----------------------------------------------------------------------
def test_slo_joins_stream_window_spans_per_class():
    from distributed_ghs_implementation_tpu.obs import slo

    bus_events = [
        ("X", "serve.request", "serve", 0, 2_000_000, 0,
         {"cls": "publish", "ok": True}),
        ("X", "stream.window", "stream", 0, 1_000_000, 0,
         {"cls": "publish", "mode": "batched"}),
    ]
    stats = slo.ClassStats()
    slo.ingest_bus_events(stats, bus_events)
    summary = slo.assemble(stats, wall_s=1.0)
    cls = summary["classes"]["publish"]
    assert cls["window_s"]["count"] == 1
    assert abs(cls["window_s"]["p50"] - 0.001) < 1e-9


def test_warmup_plan_carries_stream_buckets():
    from distributed_ghs_implementation_tpu.batch.warmup import (
        plan_from_flags,
        run_warmup,
    )

    plan = plan_from_flags(stream_buckets="64x128")
    assert plan.stream_buckets == ((64, 128),)
    report = run_warmup(plan)
    assert report["stream_warmed"] >= 1


# ----------------------------------------------------------------------
# Fleet failover (slow: spawns real jax workers; CI's stream kill drill
# covers the same path end-to-end)
# ----------------------------------------------------------------------
@pytest.mark.slow
def test_fleet_stream_failover_replays_on_survivor(tmp_path):
    from distributed_ghs_implementation_tpu.fleet.router import (
        FleetConfig,
        FleetRouter,
    )

    config = FleetConfig(
        workers=2,
        disk_dir=str(tmp_path / "store"),
        stream_dir=str(tmp_path / "streams"),
        stream_snapshot_every=2,
        ready_timeout_s=180.0,
    )
    g = gnm_random_graph(50, 150, seed=31)
    with FleetRouter(config) as router:
        solved = router.handle(_solve_request(g))
        assert solved["ok"]
        sub = router.handle({"op": "subscribe", "digest": solved["digest"]})
        assert sub["ok"]
        head = sub["digest"]
        for i in range(3):
            pub = router.handle({
                "op": "publish", "stream": sub["stream"], "digest": head,
                "updates": [{"kind": "insert", "u": 0, "v": 9 + i, "w": 1}],
            })
            assert pub["ok"], pub
            head = pub["digest"]
        owner = pub["worker"]
        router.kill_worker(owner)
        # The next publish lands on the survivor (or the restarted
        # incarnation), which must recover the stream from the shared
        # snapshot+WAL — same head, same sequence, no gap.
        pub = router.handle({
            "op": "publish", "stream": sub["stream"], "digest": head,
            "updates": [{"kind": "insert", "u": 1, "v": 20, "w": 1}],
        })
        assert pub["ok"], pub
        assert pub["seq"] == 4
        poll = router.handle({
            "op": "poll", "stream": sub["stream"], "digest": pub["digest"],
            "after_seq": 0,
        })
        assert poll["ok"]
        seqs = [n["seq"] for n in poll["notifications"]]
        assert poll_gap_check(seqs, poll["seq"]) == {"gaps": 0, "dups": 0}


# ----------------------------------------------------------------------
# Failure paths: poisoning, commit ordering, replay chaining, LRU bound
# ----------------------------------------------------------------------
def test_publish_poisoned_on_mid_window_failure(tmp_path):
    """An apply that dies mid-mutation leaves a forest no client has seen:
    the session must be dropped (stream.poisoned) and the next publish
    must recover the clean pre-window state from the durable log."""
    BUS.enable()
    BUS.clear()
    root = str(tmp_path)
    mgr, session, head = _drive_stream(root, windows=2, snapshot_every=10)

    real_apply = WindowedMST.apply_window

    def dies_dirty(self, updates):
        self._dirty = True
        raise RuntimeError("boom mid-window")

    session.mst.apply_window = dies_dirty.__get__(session.mst)
    with pytest.raises(RuntimeError, match="boom"):
        mgr.publish(session.id, head, [{"kind": "insert", "u": 0, "v": 1, "w": 1}])
    assert BUS.counters()["stream.poisoned"] == 1
    assert len(mgr) == 0  # dropped, not retained dirty
    # The retry recovers seq 2 from snapshot+WAL and commits seq 3 cleanly.
    out = mgr.publish(
        session.id, head, [{"kind": "insert", "u": 0, "v": 1, "w": 1}]
    )
    assert out["seq"] == 3
    poll = mgr.poll(session.id, after_seq=0)
    seqs = [n["seq"] for n in poll["notifications"]]
    assert poll_gap_check(seqs, poll["seq"]) == {"gaps": 0, "dups": 0}
    assert WindowedMST.apply_window is real_apply  # class left untouched
    BUS.clear()


def test_publish_wal_failure_yields_no_duplicate_notification(tmp_path):
    """The WAL append is the commit point: a failed append must not leave
    a notification in the ring, so the client's retry cannot produce two
    notifications for one sequence number."""
    BUS.enable()
    BUS.clear()
    root = str(tmp_path)
    mgr, session, head = _drive_stream(root, windows=2, snapshot_every=10)

    def refuses(**kwargs):
        raise OSError("disk full")

    session.log.append = refuses
    with pytest.raises(OSError):
        mgr.publish(session.id, head, [{"kind": "insert", "u": 0, "v": 1, "w": 1}])
    assert BUS.counters()["stream.poisoned"] == 1
    # Recovery rebuilt the pre-failure state; the retry commits ONE seq 3.
    out = mgr.publish(
        session.id, head, [{"kind": "insert", "u": 0, "v": 1, "w": 1}]
    )
    assert out["seq"] == 3
    poll = mgr.poll(session.id, after_seq=0)
    seqs = [n["seq"] for n in poll["notifications"]]
    assert seqs.count(3) == 1
    assert poll_gap_check(seqs, poll["seq"]) == {"gaps": 0, "dups": 0}
    BUS.clear()


def test_recover_chains_wal_on_stored_snapshot_digest(tmp_path):
    """When the snapshot's stored digest disagrees with the digest
    recomputed from its arrays (the digest_mismatch path), the WAL still
    chains from the STORED digest — replay must follow it rather than
    silently dropping every post-snapshot window."""
    BUS.enable()
    root = str(tmp_path)
    _mgr, session, head = _drive_stream(root, windows=1, snapshot_every=10)
    log = UpdateLog(root, session.id)
    # Rewrite the stored seed digest (snapshot + the entry chained from
    # it) to a value the arrays can no longer re-derive.
    with np.load(log.snap_path) as data:
        arrays = {k: np.asarray(data[k]) for k in data.files}
    arrays["digest"] = np.asarray("tampered-stored-digest")
    np.savez(log.snap_path, **arrays)
    # Drop the checksum sidecar: this test simulates a LEGACY stored-digest
    # mismatch (pre-integrity snapshot generations), not bit rot — with the
    # stale sidecar left in place the round-19 integrity layer would
    # (correctly) quarantine the rewritten file before replay ever saw it.
    os.unlink(log.snap_path + ".sha256")
    with open(log.wal_path) as f:
        entries = [json.loads(line) for line in f.read().splitlines() if line]
    entries[0]["prev"] = "tampered-stored-digest"
    for e in entries:
        # Strip the per-record crc too (legacy lines carry none): an edited
        # line under the ORIGINAL crc is exactly what the round-19 checksum
        # exists to reject.
        e.pop("crc", None)
    with open(log.wal_path, "w") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")
    BUS.clear()
    fresh = StreamManager(root=root, snapshot_every=10)
    recovered = fresh.recover(session.id)
    counters = BUS.counters()
    assert counters["stream.replay.digest_mismatch"] == 1
    assert counters.get("stream.replay.diverged", 0) == 0
    assert recovered.seq == 1  # the post-snapshot window was NOT dropped
    assert recovered.head == head
    BUS.clear()


def test_append_refuses_fork_from_stale_tail(tmp_path):
    """An append that does not extend the durable tail raises ChainBreak
    (carrying the durable head) instead of writing a forked entry — for
    both tail sources: the last WAL entry, and the snapshot head after
    compaction emptied the WAL."""
    BUS.enable()
    BUS.clear()
    log = _seed_log(tmp_path, windows=2)
    with pytest.raises(ChainBreak) as exc:
        log.append(seq=2, prev_digest="d1", digest="fork",
                   updates=[])  # duplicate seq: tail is (2, d2)
    assert (exc.value.seq, exc.value.digest) == (2, "d2")
    entries, _ = log._read_wal()
    assert [e["digest"] for e in entries] == ["d1", "d2"]  # no fork landed
    # Compact the WAL to empty: the snapshot head still guards the chain.
    log.snapshot(
        {"num_nodes": np.asarray(4), "u": np.arange(3), "v": np.arange(1, 4),
         "w": np.ones(3, dtype=np.int64), "in_tree": np.ones(3, dtype=bool)},
        seq=2, digest="d2",
    )
    assert log._read_wal()[0] == []
    with pytest.raises(ChainBreak):
        log.append(seq=2, prev_digest="d1", digest="fork", updates=[])
    log.append(seq=3, prev_digest="d2", digest="d3", updates=[])  # extends
    assert BUS.counters()["stream.log.fork_refused"] == 2
    BUS.clear()


def test_publish_fork_refused_across_sharing_processes(tmp_path):
    """Two managers sharing one stream root (fleet workers after a
    re-pin): the one holding a stale resident copy passes its in-memory
    staleness check, but the WAL fork guard bounces its publish as
    StaleDigest carrying the DURABLE head — and no second entry for the
    contested sequence number reaches the shared log."""
    BUS.enable()
    BUS.clear()
    root = str(tmp_path)
    mgr_a, session, head2 = _drive_stream(root, windows=2)
    mgr_b = StreamManager(root=root)
    stale = mgr_b.subscribe(stream=session.id)  # resident at seq 2
    assert stale.head == head2
    out = mgr_a.publish(
        session.id, head2, [{"kind": "insert", "u": 0, "v": 9, "w": 7}]
    )  # the pinned worker commits seq 3
    with pytest.raises(StaleDigest) as exc:
        mgr_b.publish(
            stale.id, head2, [{"kind": "insert", "u": 1, "v": 8, "w": 5}]
        )
    assert exc.value.head == out["digest"]
    assert exc.value.seq == 3
    counters = BUS.counters()
    assert counters["stream.log.fork_refused"] == 1
    assert counters["stream.publish.stale"] == 1
    assert counters.get("stream.poisoned", 0) == 0  # a re-sync, not poison
    # The shared WAL holds exactly one seq-3 entry: the pinned worker's.
    entries, _ = UpdateLog(root, session.id)._read_wal()
    assert [e["seq"] for e in entries].count(3) == 1
    assert entries[-1]["digest"] == out["digest"]
    # The stale manager recovers the durable head on its next verb (its
    # forked resident copy was dropped by the refusal).
    poll = mgr_b.poll(session.id, after_seq=0)
    assert poll["digest"] == out["digest"] and poll["seq"] == 3
    seqs = [n["seq"] for n in poll["notifications"]]
    assert poll_gap_check(seqs, poll["seq"]) == {"gaps": 0, "dups": 0}
    BUS.clear()


def test_move_head_never_maps_evicted_session(tmp_path):
    """A publish whose session lost the LRU race must not re-insert its
    new head into the digest index: every _by_head entry always points at
    a resident stream (the dangling-mapping leak)."""
    root = str(tmp_path)
    mgr = StreamManager(root=root, max_streams=1)
    g1 = gnm_random_graph(40, 120, seed=21)
    s1 = mgr.subscribe(digest=g1.digest(), result=minimum_spanning_forest(g1))
    g2 = gnm_random_graph(40, 120, seed=22)
    mgr.subscribe(digest=g2.digest(), result=minimum_spanning_forest(g2))
    assert s1.id not in mgr.heads()  # s1 was evicted by s2
    # Simulate s1's in-flight publish completing after the eviction.
    prev = s1.head
    s1.head = "post-eviction-head"
    mgr._move_head(s1, prev)
    with mgr._lock:
        assert "post-eviction-head" not in mgr._by_head
        assert all(sid in mgr._streams for sid in mgr._by_head.values())


def test_stream_manager_lru_bound_evicts_and_recovers(tmp_path):
    """Streams are bounded like update sessions: past max_streams the
    least-recently-used stream leaves memory (stream.evicted) but stays
    reachable — its next verb replays it from the durable log."""
    BUS.enable()
    BUS.clear()
    root = str(tmp_path)
    mgr = StreamManager(root=root, max_streams=2)
    sessions = []
    for seed in (1, 2, 3):
        g = gnm_random_graph(40, 120, seed=seed)
        result = minimum_spanning_forest(g)
        sessions.append(mgr.subscribe(digest=g.digest(), result=result))
    assert len(mgr) == 2
    counters = BUS.counters()
    assert counters["stream.evicted"] == 1
    first = sessions[0]
    assert first.id not in mgr.heads()
    # The evicted stream recovers transparently on its next verb.
    poll = mgr.poll(first.id, after_seq=0)
    assert poll["digest"] == first.head
    assert BUS.counters()["stream.replay.streams"] == 1
    assert len(mgr) == 2  # recovery itself respects the bound
    BUS.clear()


def test_subscribe_by_mid_chain_head_recovers_evicted_stream(tmp_path):
    """Log dirs are keyed by the SEED digest, so an evicted stream
    addressed by its current head must be found by scanning durable
    heads — silently creating a fresh seq-0 stream instead would leave
    re-subscribing pollers (cursors at the old sequence) waiting
    forever."""
    mgr = StreamManager(root=str(tmp_path), max_streams=1)
    g1 = gnm_random_graph(40, 120, seed=41)
    r1 = minimum_spanning_forest(g1)
    s1 = mgr.subscribe(digest=g1.digest(), result=r1)
    out = mgr.publish(
        s1.id, s1.head, [{"kind": "insert", "u": 0, "v": 1, "w": 1}]
    )
    head = out["digest"]
    g2 = gnm_random_graph(40, 120, seed=42)
    mgr.subscribe(digest=g2.digest(), result=minimum_spanning_forest(g2))
    assert s1.id not in mgr.heads()  # evicted
    # Re-subscribe by the CURRENT head (not the seed): even with a seed
    # result in hand, this must recover the existing stream, not fork.
    again = mgr.subscribe(digest=head, result=r1)
    assert again.id == s1.id
    assert again.seq == 1 and again.head == head


def test_publish_on_commit_runs_under_session_lock_with_chain_args(tmp_path):
    """The on_commit hook (the service's cache/residency maintenance)
    must run INSIDE the session lock so concurrent publishes keep per-head
    bookkeeping in seq order — after publish returns, a later window's
    chain eviction could land before an earlier window's insert."""
    mgr = StreamManager(root=str(tmp_path))
    g = gnm_random_graph(40, 120, seed=43)
    session = mgr.subscribe(digest=g.digest(), result=minimum_spanning_forest(g))
    seed_head = session.head
    calls = []

    def on_commit(result, prev_digest, new_digest):
        # Non-blocking acquire fails iff the session lock is held.
        assert not session.lock.acquire(blocking=False)
        calls.append((result, prev_digest, new_digest))

    out = mgr.publish(
        session.id, seed_head,
        [{"kind": "insert", "u": 0, "v": 1, "w": 1}],
        on_commit=on_commit,
    )
    assert len(calls) == 1
    result, prev_digest, new_digest = calls[0]
    assert prev_digest == seed_head
    assert new_digest == out["digest"]
    assert result.graph.digest() == new_digest
