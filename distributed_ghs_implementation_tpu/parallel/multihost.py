"""Multi-host execution: JAX distributed runtime over a TPU pod.

The reference scales out with ``mpiexec -n N`` / SLURM, one OS process per
graph vertex (``/root/reference/README_MPI.md:78-92,156-167``). The TPU-native
equivalent is one JAX process per host, all chips in one
``jax.sharding.Mesh``: after :func:`initialize`, ``jax.devices()`` spans the
pod, ``parallel.edge_mesh()`` covers every chip, and the same
``solve_graph_sharded`` code runs unchanged — XLA routes the per-level pmin
combines over ICI within a slice and DCN across hosts. Launch scripts live in
``launcher/`` (the reference's ``run_ghs.slurm`` is referenced but missing
from its repo — C17 in SURVEY.md §2; ours ships).
"""

from __future__ import annotations

import os
from typing import Optional


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the JAX distributed runtime (idempotent).

    With no arguments, reads the standard env (TPU pod metadata or
    ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``, the
    names our SLURM launcher exports). Call before any other JAX API on every
    host, then build meshes as usual.
    """
    import jax

    if getattr(initialize, "_done", False):
        return
    kwargs = {}
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if process_id is not None:
        kwargs["process_id"] = process_id
    from distributed_ghs_implementation_tpu.obs.events import BUS

    with BUS.span("parallel.multihost.initialize", cat="parallel") as span:
        jax.distributed.initialize(**kwargs)
        span.set(
            process_index=jax.process_index(),
            process_count=jax.process_count(),
        )
    initialize._done = True


def is_primary() -> bool:
    """True on the host that should write artifacts (rank 0's role in the
    reference's result aggregation, ``ghs_implementation_mpi.py:929-954``)."""
    import jax

    return jax.process_index() == 0


def broadcast_resume_state(state, error: bool = False):
    """Primary's checkpoint state -> every process (``None`` stays ``None``).

    Checkpoint saves are primary-only (the rank-0 artifact rule), so on a
    non-shared filesystem only the primary can see the file. A per-process
    ``os.path.exists`` decision would diverge the SPMD program — mismatched
    collectives hang the pod — so the primary's view is authoritative:
    broadcast a presence flag + shapes, then the arrays. Single-process
    runs return ``state`` unchanged.

    ``error=True`` (primary only, before re-raising a load failure)
    broadcasts an abort flag instead: every other process raises too, so a
    corrupt or mismatched checkpoint kills the whole pod cleanly rather
    than leaving n-1 processes blocked in this collective forever.
    """
    import jax

    if jax.process_count() == 1:
        return None if error else state

    import numpy as np
    from jax.experimental import multihost_utils as mu

    from distributed_ghs_implementation_tpu.obs.events import BUS

    BUS.instant(
        "parallel.multihost.broadcast_resume",
        cat="parallel",
        error=error,
        has_state=state is not None,
    )

    if jax.process_index() == 0 and (error or state is not None):
        if error:
            frag = np.zeros(0, dtype=np.int32)
            mask = np.zeros(0, dtype=bool)
            meta = np.asarray([2, 0, 0, 0], dtype=np.int64)
        else:
            frag = np.asarray(state[0], dtype=np.int32)
            mask = np.asarray(state[1], dtype=bool)
            meta = np.asarray(
                [1, frag.shape[0], mask.shape[0], int(state[2])], dtype=np.int64
            )
    else:
        frag = np.zeros(0, dtype=np.int32)
        mask = np.zeros(0, dtype=bool)
        meta = np.zeros(4, dtype=np.int64)
    meta = np.asarray(mu.broadcast_one_to_all(meta))
    if meta[0] == 2:
        if jax.process_index() == 0:
            return None  # primary re-raises the original load error
        raise RuntimeError(
            "checkpoint load failed on the primary process; aborting"
        )
    if meta[0] == 0:
        return None
    if jax.process_index() != 0:
        frag = np.zeros(int(meta[1]), dtype=np.int32)
        mask = np.zeros(int(meta[2]), dtype=bool)
    frag = np.asarray(mu.broadcast_one_to_all(frag))
    mask = np.asarray(mu.broadcast_one_to_all(mask))
    return frag, mask, int(meta[3])
