"""Distributed tracing + fleet telemetry plane (obs/tracing.py,
obs/pulse.py, the obs/export.py multi-process merge).

The cross-process trace-continuity legs live with their subsystems
(tests/test_fleet.py: failover re-queue and the caps.trace version gate;
tests/test_stream.py: WAL-replay continuity). This file owns the tracing
primitives, the merge/critical-path assembly, and the pulse plane.
"""

import json
import os

import pytest

from distributed_ghs_implementation_tpu.obs import tracing
from distributed_ghs_implementation_tpu.obs.events import (
    BUS,
    EventBus,
    merge_hists,
)
from distributed_ghs_implementation_tpu.obs.export import (
    merge_trace_files,
    render_stats,
    write_events_jsonl,
    write_merged_trace,
)
from distributed_ghs_implementation_tpu.obs.pulse import (
    FleetPulse,
    parse_budgets,
    pulse_report,
    write_prometheus,
)


@pytest.fixture(autouse=True)
def _clean_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.enable()
    BUS.clear()


# ----------------------------------------------------------------------
# Context primitives
# ----------------------------------------------------------------------
def test_mint_activate_and_child_context():
    assert tracing.current() is None
    ctx = tracing.mint("interactive")
    assert len(ctx.trace_id) == 32  # 128-bit hex
    assert ctx.span_id is None  # a root: its first span has no parent
    assert ctx.slo_class == "interactive"
    token = tracing.activate(ctx)
    try:
        assert tracing.current() is ctx
        child = ctx.child("abc123")
        assert child.trace_id == ctx.trace_id
        assert child.span_id == "abc123"
        assert child.slo_class == "interactive"
    finally:
        tracing.deactivate(token)
    assert tracing.current() is None


def test_front_door_mints_once_and_reuses_active_context():
    with tracing.front_door("bulk"):
        outer = tracing.current()
        assert outer is not None and outer.slo_class == "bulk"
        # A nested front door (router handle below serve_loop, say) must
        # JOIN the active trace, not start a second one.
        with tracing.front_door("other"):
            assert tracing.current().trace_id == outer.trace_id
    assert tracing.current() is None


def test_head_sampling_is_deterministic_and_seeded(monkeypatch):
    ids = [tracing.new_trace_id() for _ in range(200)]
    monkeypatch.setenv("GHS_TRACE_SAMPLE", "0.5")
    monkeypatch.setenv("GHS_TRACE_SEED", "7")
    first = [tracing.head_sampled(t) for t in ids]
    assert first == [tracing.head_sampled(t) for t in ids]  # deterministic
    assert 40 < sum(first) < 160  # actually samples, not all/none
    monkeypatch.setenv("GHS_TRACE_SEED", "8")
    assert first != [tracing.head_sampled(t) for t in ids]  # seed matters
    monkeypatch.setenv("GHS_TRACE_SAMPLE", "1.0")
    assert all(tracing.head_sampled(t) for t in ids)
    monkeypatch.setenv("GHS_TRACE_SAMPLE", "0")
    assert not any(tracing.head_sampled(t) for t in ids)


def test_wire_context_round_trip_and_garbage_tolerance():
    assert tracing.wire_context() is None  # no active context
    ctx = tracing.mint("interactive")
    token = tracing.activate(ctx)
    try:
        wire = tracing.wire_context()
    finally:
        tracing.deactivate(token)
    assert wire["trace"] == ctx.trace_id and wire["cls"] == "interactive"
    back = tracing.from_wire(wire)
    assert back.trace_id == ctx.trace_id and back.slo_class == "interactive"
    # from_wire is a trust boundary: garbage degrades to None, never
    # raises into the read loop that called it.
    for junk in (None, {}, [], "x", 7, {"trace": 9}, {"trace": ""},
                 {"sampled": True}):
        assert tracing.from_wire(junk) is None


# ----------------------------------------------------------------------
# Span stamping (EventBus integration)
# ----------------------------------------------------------------------
def test_spans_stamp_trace_and_nest_parents():
    bus = EventBus(enabled=True)
    ctx = tracing.mint("interactive")
    token = tracing.activate(ctx)
    try:
        with bus.span("a", cat="t"):
            with bus.span("b", cat="t"):
                pass
    finally:
        tracing.deactivate(token)
    by_name = {
        name: args for _ph, name, _c, _t, _d, _tid, args in bus.events()
    }
    assert by_name["a"]["trace"] == ctx.trace_id
    assert "parent" not in by_name["a"]  # the root span
    assert by_name["b"]["trace"] == ctx.trace_id
    assert by_name["b"]["parent"] == by_name["a"]["span"]
    assert by_name["a"]["span"] != by_name["b"]["span"]


def test_spans_untraced_without_context_and_when_unsampled(monkeypatch):
    bus = EventBus(enabled=True)
    with bus.span("plain", cat="t"):
        pass
    (args,) = [a or {} for _p, n, _c, _t, _d, _ti, a in bus.events()
               if n == "plain"]
    assert "trace" not in args and "span" not in args
    # An unsampled trace stays context-active (the class tag, the wire
    # decision) but stamps nothing.
    monkeypatch.setenv("GHS_TRACE_SAMPLE", "0")
    ctx = tracing.mint("bulk")
    assert ctx.sampled is False
    token = tracing.activate(ctx)
    try:
        with bus.span("dark", cat="t"):
            pass
        assert tracing.wire_context() is None
    finally:
        tracing.deactivate(token)
    (args,) = [a or {} for _p, n, _c, _t, _d, _ti, a in bus.events()
               if n == "dark"]
    assert "trace" not in args


# ----------------------------------------------------------------------
# Multi-process merge + critical path (obs/export.py)
# ----------------------------------------------------------------------
def _two_process_trace(tmp_path):
    """One request traced across a synthetic router + worker 'process'
    pair (two buses, two JSONL exports)."""
    import time

    router_bus = EventBus(enabled=True)
    worker_bus = EventBus(enabled=True)
    ctx = tracing.mint("interactive")
    token = tracing.activate(ctx)
    try:
        with router_bus.span("fleet.request", cat="fleet", op="solve"):
            with router_bus.span("fleet.attempt", cat="fleet", attempt=1):
                wire = tracing.wire_context()
                # "the worker": re-establish context from the wire
                wtoken = tracing.activate(tracing.from_wire(wire))
                try:
                    with worker_bus.span("fleet.serve", cat="fleet"):
                        with worker_bus.span("serve.solve", cat="serve"):
                            time.sleep(0.002)
                finally:
                    tracing.deactivate(wtoken)
                time.sleep(0.001)
    finally:
        tracing.deactivate(token)
    rp = str(tmp_path / "router.jsonl")
    wp = str(tmp_path / "worker0.jsonl")
    write_events_jsonl(router_bus, rp, label="router")
    write_events_jsonl(worker_bus, wp, label="worker0")
    return ctx, [rp, wp]


def test_merge_joins_processes_with_flow_arrows_and_no_orphans(tmp_path):
    _ctx, paths = _two_process_trace(tmp_path)
    trace, report = merge_trace_files(paths)
    assert report["schema"] == "ghs-trace-merge-v1"
    assert len(report["processes"]) == 2
    assert report["traces_total"] == 1
    assert report["traces_joined"] == 1  # spans from BOTH processes
    assert report["orphan_spans"] == 0
    assert report["flow_arrows"] >= 1
    # Distinct pids even though both buses ran in THIS process (the
    # dedup fallback), each with a process_name metadata event.
    pids = {e["pid"] for e in trace["traceEvents"] if "pid" in e}
    assert len(pids) == 2
    names = {
        e["args"]["name"] for e in trace["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert names == {"router", "worker0"}
    # Flow arrows pair: a start at the parent, a finish at the child.
    phases = [e["ph"] for e in trace["traceEvents"] if e.get("cat") == "trace"]
    assert phases.count("s") == phases.count("f") >= 1


def test_merge_critical_path_accounts_request_wall_time(tmp_path):
    _ctx, paths = _two_process_trace(tmp_path)
    _trace, report = merge_trace_files(paths)
    summary = report["critical_path"]["summary"]
    assert summary["traces"] == 1
    assert summary["accounted_frac_min"] >= 0.9  # the acceptance gate
    (per,) = report["critical_path"]["per_trace"]
    total = per["total_s"]
    parts = (per["queue_s"] + per["probe_s"] + per["transport_s"]
             + per["solve_s"] + per["verify_s"] + per["service_other_s"]
             + per["residual_s"])
    assert parts == pytest.approx(total)  # the decomposition telescopes
    assert per["solve_s"] > 0  # serve.solve classified as solve time


def test_merge_counts_worker_only_fragments_as_unrooted_not_orphans(
    tmp_path,
):
    """A worker fragment whose router spans were cleared (the drill's
    warm phase) must NOT read as a broken trace: it has no root, so it is
    unrooted — orphan_spans counts dangling parents inside ROOTED traces
    only."""
    ctx, paths = _two_process_trace(tmp_path)
    warm_bus = EventBus(enabled=True)
    # A wire context whose parent span lived in the since-cleared router
    # bus: the worker's span has a DANGLING parent and its trace no root.
    warm = tracing.from_wire({
        "trace": tracing.new_trace_id(), "sampled": True,
        "span": "deadbeef00000001", "cls": "warm",
    })
    token = tracing.activate(warm)
    try:
        with warm_bus.span("fleet.serve", cat="fleet"):
            pass
    finally:
        tracing.deactivate(token)
    frag = str(tmp_path / "worker1.jsonl")
    write_events_jsonl(warm_bus, frag, label="worker1")
    _trace, report = merge_trace_files(paths + [frag])
    assert report["traces_total"] == 2
    assert report["traces_rooted"] == 1
    assert report["traces_unrooted"] == 1
    assert report["orphan_spans"] == 0
    assert report["traces_joined"] == 1


def test_write_merged_trace_emits_both_artifacts(tmp_path):
    _ctx, paths = _two_process_trace(tmp_path)
    out = str(tmp_path / "merged.json")
    rep_path = str(tmp_path / "cp.json")
    report = write_merged_trace(paths, out, rep_path)
    assert json.load(open(out))["traceEvents"]
    assert json.load(open(rep_path))["orphan_spans"] == 0
    assert report["traces_joined"] == 1


# ----------------------------------------------------------------------
# Reservoir merge (obs/events.py)
# ----------------------------------------------------------------------
def test_merge_hists_exact_moments_and_determinism():
    a, b = EventBus(enabled=True), EventBus(enabled=True)
    for i in range(100):
        a.record("lat_s", i * 0.001)
    for i in range(50):
        b.record("lat_s", 1.0 + i * 0.001)
    raws = [a.histograms_export()["lat_s"], b.histograms_export()["lat_s"]]
    merged = merge_hists(raws)
    assert merged.count == 150
    assert merged.total == pytest.approx(
        sum(i * 0.001 for i in range(100))
        + sum(1.0 + i * 0.001 for i in range(50))
    )
    assert merged.vmin == 0.0 and merged.vmax == pytest.approx(1.049)
    # Deterministic: same inputs, byte-identical summary.
    assert merged.summary() == merge_hists(raws).summary()
    # Under the cap, the merge is exact concatenation.
    assert sorted(merged.samples) == sorted(
        raws[0]["samples"] + raws[1]["samples"]
    )


def test_merge_hists_over_cap_weights_by_count():
    big, small = EventBus(enabled=True), EventBus(enabled=True)
    for i in range(2000):
        big.record("x", 10.0)
    for i in range(100):
        small.record("x", 1.0)
    merged = merge_hists(
        [big.histograms_export()["x"], small.histograms_export()["x"]]
    )
    assert merged.count == 2100
    share = sum(1 for s in merged.samples if s == 10.0) / len(merged.samples)
    assert share > 0.8  # the big worker dominates the merged reservoir


# ----------------------------------------------------------------------
# Pulse (obs/pulse.py)
# ----------------------------------------------------------------------
def _canned_stats():
    wbus = EventBus(enabled=True)
    wbus.record("echo.latency_s", 0.001)
    wbus.record("echo.latency_s", 0.003)
    return {
        "ok": True,
        "fleet": {"fleet.requests": 9},
        "pool": {"workers": 3},
        "workers": {
            0: {"alive": True, "pending": 0, "stats": {
                "counters": {"echo.handled": 3},
                "events_dropped": 0,
                "histograms_raw": wbus.histograms_export()}},
            1: {"alive": True, "pending": 1, "stats": {
                "counters": {"echo.handled": 4, "other": 2},
                "events_dropped": 5,
                "histograms_raw": {}}},
            2: {"alive": True, "pending": 0, "stats": {
                "counters": {"echo.handled": 5},
                "events_dropped": 0,
                "histograms_raw": {}}},
        },
    }


def test_pulse_report_totals_are_exact_per_worker_sums():
    report = pulse_report(_canned_stats())
    assert report["schema"] == "ghs-fleet-pulse-v1"
    assert report["workers_scraped"] == 3
    # THE invariant: totals == the exact sum of the per-worker counters
    # the report itself carries (CI re-asserts this on a live fleet).
    for name, total in report["counters"].items():
        assert total == sum(
            (w.get("counters") or {}).get(name, 0)
            for w in report["workers"].values()
        )
    assert report["counters"]["echo.handled"] == 12
    assert report["workers"]["1"]["events_dropped"] == 5
    assert report["histograms"]["echo.latency_s"]["count"] == 2
    assert report["router"]["counters"]["fleet.requests"] == 9


def test_pulse_scrape_writes_artifacts_and_prometheus(tmp_path):
    stats = _canned_stats()

    class StubRouter:
        def handle(self, request):
            assert request == {"op": "stats"}
            return stats

    pulse = FleetPulse(
        StubRouter(), interval_s=999.0, out_dir=str(tmp_path),
        budgets={"default": 1.0},
    )
    report = pulse.scrape_once()
    assert pulse.scrapes == 1 and pulse.last_report is report
    on_disk = json.load(open(tmp_path / "pulse.json"))
    assert on_disk["counters"]["echo.handled"] == 12
    prom = open(tmp_path / "pulse.prom").read()
    assert "ghs_echo_handled 12.0" in prom  # the exact total line
    assert "ghs_other 2.0" in prom  # no cross-metric bleed into totals
    assert 'ghs_echo_handled{worker="1"} 4.0' in prom
    assert 'ghs_worker_events_dropped{worker="1"} 5' in prom
    assert 'ghs_echo_latency_s{quantile="0.99"}' in prom


def test_pulse_slow_request_exemplar_captures_full_span_tree(tmp_path):
    class StubRouter:
        def handle(self, request):
            return {"workers": {}}

    ctx = tracing.mint("interactive")
    token = tracing.activate(ctx)
    try:
        with BUS.span("fleet.request", cat="fleet", cls="interactive"):
            with BUS.span("fleet.attempt", cat="fleet", attempt=1):
                import time

                time.sleep(0.005)
    finally:
        tracing.deactivate(token)
    pulse = FleetPulse(
        StubRouter(), interval_s=999.0, out_dir=str(tmp_path),
        budgets={"interactive": 0.001},  # the 5ms sleep breaches it
    )
    pulse.scrape_once()
    lines = open(tmp_path / "exemplars.jsonl").read().splitlines()
    (exemplar,) = [json.loads(line) for line in lines]
    assert exemplar["schema"] == "ghs-slow-exemplar-v1"
    assert exemplar["trace"] == ctx.trace_id
    assert exemplar["cls"] == "interactive"
    assert exemplar["dur_s"] > exemplar["budget_s"]
    names = {s["name"] for s in exemplar["spans"]}
    assert names == {"fleet.request", "fleet.attempt"}  # the WHOLE tree


def test_parse_budgets_spec_and_errors():
    assert parse_budgets("interactive=0.05, bulk=2,default=1") == {
        "interactive": 0.05, "bulk": 2.0, "default": 1.0,
    }
    assert parse_budgets("") == {}
    with pytest.raises(ValueError, match="CLASS=SECONDS"):
        parse_budgets("interactive=fast")


def test_write_prometheus_zero_count_histograms_skipped(tmp_path):
    report = pulse_report({"workers": {}})
    report["histograms"] = {"empty": {"count": 0}}
    path = str(tmp_path / "p.prom")
    write_prometheus(report, path)
    assert "empty" not in open(path).read()


# ----------------------------------------------------------------------
# render_stats drop flag (satellite)
# ----------------------------------------------------------------------
def test_render_stats_flags_workers_with_dropped_events():
    with BUS.span("x", cat="t"):
        pass
    snapshot = BUS.snapshot()
    snapshot["workers"] = {
        "0": {"stats": {"events_dropped": 0}},
        "1": {"stats": {"events_dropped": 41}},
    }
    text = render_stats(snapshot)
    assert "worker 1 dropped 41 events" in text
    assert "worker 0 dropped" not in text


# ----------------------------------------------------------------------
# Live end-to-end: in-process echo fleet, traced request, pulse audit
# ----------------------------------------------------------------------
def test_echo_fleet_traced_request_joins_worker_process(tmp_path):
    from distributed_ghs_implementation_tpu.fleet.router import (
        FleetConfig,
        FleetRouter,
    )

    obs_dir = str(tmp_path / "obs")
    router = FleetRouter(FleetConfig(
        workers=2, test_echo=True, heartbeat_interval_s=0.1,
        ready_timeout_s=120.0, request_timeout_s=30.0, obs_dir=obs_dir,
    )).start()
    try:
        for i in range(6):
            assert router.handle({"op": "solve", "digest": f"t{i}"})["ok"]
        # Live pulse against the real fleet: totals must equal the
        # per-worker sums it reports.
        report = FleetPulse(router, interval_s=999.0).scrape_once()
        assert report["workers_scraped"] == 2
        handled = report["counters"]["echo.handled"]
        assert handled == sum(
            (w.get("counters") or {}).get("echo.handled", 0)
            for w in report["workers"].values()
        )
        assert handled >= 6
    finally:
        router.shutdown()
    # The drained workers exported JSONL; merged with the router's bus,
    # every request must join across processes with zero orphans.
    router_jsonl = str(tmp_path / "router.jsonl")
    write_events_jsonl(BUS, router_jsonl, label="router")
    paths = [router_jsonl] + sorted(
        os.path.join(obs_dir, f) for f in os.listdir(obs_dir)
        if f.endswith(".jsonl")
    )
    _trace, report = merge_trace_files(paths)
    assert len(report["processes"]) == 3
    assert report["orphan_spans"] == 0
    assert report["traces_joined"] >= 6
    assert report["critical_path"]["summary"]["accounted_frac_min"] >= 0.9
