"""Multi-chip parallelism: edge sharding over a ``jax.sharding.Mesh``.

The first-class parallelism component (SURVEY.md §2): where the reference puts
one MPI rank per graph vertex with pickled point-to-point messages
(``/root/reference/ghs_implementation_mpi.py:94-115``), this shards the
directed edge list by contiguous slot blocks over a 1-D device mesh and
combines per-fragment minima with ``lax.pmin`` over ICI. Vertex arrays stay
replicated (67 MB at RMAT-24 — cheap next to the 8.6 GB edge partition).
"""

from distributed_ghs_implementation_tpu.parallel.lane import ShardedLane
from distributed_ghs_implementation_tpu.parallel.mesh import (
    edge_mesh,
    shard_map_compat,
)
from distributed_ghs_implementation_tpu.parallel.sharded import (
    make_sharded_solver,
    solve_graph_sharded,
)

__all__ = [
    "ShardedLane",
    "edge_mesh",
    "make_sharded_solver",
    "shard_map_compat",
    "solve_graph_sharded",
]
