"""The ``ghs-tuning-v1`` TuningRecord: persisted measured winners.

A record is one machine's measured per-bucket kernel selections, keyed by
the same platform fingerprint the persistent XLA compile cache shards on
(``utils/compile_cache._platform_fingerprint``): backend + device kind on
accelerators, a CPU-feature digest on hosts. Persistence follows the
round-19 integrity pattern (``utils/integrity.py``): atomic tmp+rename
writes with an fsync, a ``.sha256`` sidecar written after the payload,
and verification on load — a torn or tampered record is quarantined,
never trusted.

Staleness guards make the record self-invalidating: it embeds the
fingerprint, backend, jax version, and capability-probe result it was
measured under, and :func:`load_record` refuses (``tune.record.stale``)
when any of them no longer match — a record measured on one machine, one
jax, or one probe outcome says nothing about another. Loads land on the
obs bus as ``tune.record.hit`` / ``miss`` / ``stale`` so a serving
process can *prove* whether its selections are measured.

Determinism contract: :func:`save_record` emits canonical JSON (sorted
keys, fixed indent, no timestamps), so two runs of the same deterministic
search produce byte-identical files — what CI's ``gate-tune-v1`` pins.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, Optional, Tuple

import jax

from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.ops import pallas_kernels as _pk
from distributed_ghs_implementation_tpu.utils.compile_cache import (
    _platform_fingerprint,
    default_cache_dir,
)
from distributed_ghs_implementation_tpu.utils import integrity

RECORD_SCHEMA = "ghs-tuning-v1"

Bucket = Tuple[int, int, int, str]  # (n_pad, m_pad, lanes, mode)

#: Matches ``tune.space.VALID_MODES`` (kept literal here: record parsing
#: must stay importable without the search machinery).
_VALID_MODES = ("fused", "vmap", "ell", "mesh")


class TuningRecordError(ValueError):
    """A record file that cannot be used (bad schema, bad entry) — raised
    only for *malformed* files; stale-but-well-formed records degrade to
    ``None`` (the probe heuristic), never an error."""


def bucket_key_str(bucket: Bucket) -> str:
    n, m, lanes, mode = bucket
    return f"{int(n)}x{int(m)}x{int(lanes)}x{mode}"


def parse_bucket_key(key: str) -> Bucket:
    parts = key.split("x")
    if len(parts) != 4:
        raise TuningRecordError(
            f"bad tuning bucket key {key!r}; expected NxMxLANESxMODE"
        )
    try:
        n, m, lanes = int(parts[0]), int(parts[1]), int(parts[2])
    except ValueError as ex:
        raise TuningRecordError(
            f"bad tuning bucket key {key!r}: {ex}"
        ) from None
    if n < 1 or m < 1 or lanes < 0:
        raise TuningRecordError(
            f"bad tuning bucket key {key!r}: sizes must be positive"
        )
    if parts[3] not in _VALID_MODES:
        raise TuningRecordError(
            f"bad tuning bucket key {key!r}: unknown mode {parts[3]!r} "
            f"(expected one of {_VALID_MODES})"
        )
    return (n, m, lanes, parts[3])


def fingerprint() -> str:
    """The machine identity records are keyed by (shared with the
    persistent XLA compile cache, so 'same cache, same record')."""
    return _platform_fingerprint()


def new_record(entries: Dict[Bucket, dict], *, pinned: bool) -> dict:
    """Assemble a record dict around measured ``entries`` (bucket ->
    ``{"kernel", "source", "geometry", ...}``) with the staleness-guard
    environment embedded."""
    return {
        "schema": RECORD_SCHEMA,
        "fingerprint": fingerprint(),
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "probe_ok": bool(_pk.pallas_supported()),
        "pinned": bool(pinned),
        "entries": {
            bucket_key_str(b): entries[b] for b in sorted(entries)
        },
    }


def default_record_path(directory: Optional[str] = None) -> str:
    """``<dir>/tuning-<fingerprint>.json``; ``dir`` defaults to
    ``$GHS_TUNE_DIR`` or a ``tune`` sibling of the compile-cache dir —
    fleet workers on one host share it exactly like the XLA cache."""
    d = directory or os.environ.get("GHS_TUNE_DIR")
    if not d:
        d = os.path.join(os.path.dirname(default_cache_dir()), "ghs-tune")
    return os.path.join(d, f"tuning-{fingerprint()}.json")


def save_record(record: dict, path: str) -> str:
    """Atomically persist a record + its sha256 sidecar; returns ``path``.

    Canonical serialization (sorted keys, fixed indent): a deterministic
    search yields a byte-deterministic file.
    """
    if record.get("schema") != RECORD_SCHEMA:
        raise TuningRecordError(
            f"refusing to save record with schema {record.get('schema')!r}"
        )
    payload = json.dumps(record, indent=2, sort_keys=True) + "\n"
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tuning-", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    integrity.write_sidecar(path)
    return path


def _stale(path: str, why: str) -> None:
    BUS.count("tune.record.stale")
    BUS.instant("tune.record.stale_detail", cat="tune", path=path, why=why)


def load_record(path: str) -> Optional[dict]:
    """Load + verify a record; ``None`` on miss or staleness (the caller
    falls back to the probe heuristic), raises :class:`TuningRecordError`
    only on a malformed file.

    Verification order: existence (``tune.record.miss``) → sidecar
    integrity (corrupt records are quarantined) → schema/entry shape →
    staleness guards (fingerprint, backend, jax version, probe result —
    any mismatch counts ``tune.record.stale``). A verified fresh record
    counts ``tune.record.hit``.
    """
    if not os.path.exists(path):
        BUS.count("tune.record.miss")
        return None
    try:
        integrity.check_file(path)
    except integrity.IntegrityError as ex:
        integrity.quarantine(
            path, reason=f"tuning record failed integrity: {ex}",
            counter="tune.record.quarantined",
        )
        _stale(path, "integrity")
        return None
    with open(path) as f:
        try:
            record = json.load(f)
        except json.JSONDecodeError as ex:
            raise TuningRecordError(f"{path}: not JSON: {ex}") from None
    if record.get("schema") != RECORD_SCHEMA:
        raise TuningRecordError(
            f"{path}: bad tuning record schema {record.get('schema')!r} "
            f"(expected {RECORD_SCHEMA})"
        )
    entries = record.get("entries")
    if not isinstance(entries, dict):
        raise TuningRecordError(f"{path}: record has no entries mapping")
    for key, entry in entries.items():
        parse_bucket_key(key)  # raises TuningRecordError, names the key
        if not isinstance(entry, dict) or entry.get("kernel") not in (
            "pallas", "xla",
        ):
            raise TuningRecordError(
                f"{path}: entry {key!r} has no pallas|xla winner "
                f"(got {entry!r})"
            )
    # Staleness: the measuring environment must match the consuming one.
    if record.get("fingerprint") != fingerprint():
        _stale(path, "fingerprint")
        return None
    if record.get("backend") != jax.default_backend():
        _stale(path, "backend")
        return None
    if record.get("jax_version") != jax.__version__:
        _stale(path, "jax_version")
        return None
    if bool(record.get("probe_ok")) != bool(_pk.pallas_supported()):
        _stale(path, "probe")
        return None
    BUS.count("tune.record.hit")
    return record


def winners(record: dict) -> Dict[Bucket, str]:
    """``bucket -> kernel`` mapping of a (validated) record."""
    return {
        parse_bucket_key(key): entry["kernel"]
        for key, entry in record.get("entries", {}).items()
    }


def install_record(record: dict, *, path: Optional[str] = None) -> int:
    """Make a loaded record load-bearing: install its winners into the
    selector's measured-auto tier (``pallas_kernels.set_tuned_kernels``)
    and, when every Pallas winner agrees on one geometry, apply that
    geometry process-wide (so warmed buckets compile the tuned variant).
    Returns the number of installed bucket winners."""
    mapping = winners(record)
    geoms = {
        json.dumps(entry.get("geometry"), sort_keys=True)
        for entry in record.get("entries", {}).values()
        if entry.get("kernel") == "pallas" and entry.get("geometry")
    }
    if len(geoms) == 1:
        _pk.set_geometry(
            _pk.KernelGeometry.from_json(json.loads(next(iter(geoms))))
        )
    _pk.set_tuned_kernels(
        mapping,
        source={
            "fingerprint": record.get("fingerprint"),
            "path": path,
            "entries": len(mapping),
            "pinned": bool(record.get("pinned")),
        },
    )
    return len(mapping)


def load_and_install(path: str) -> int:
    """Convenience: :func:`load_record` then :func:`install_record`;
    returns 0 (and installs nothing) on miss/stale."""
    record = load_record(path)
    if record is None:
        return 0
    return install_record(record, path=path)
