"""Failure diagnostics: the structured analog of the reference's debug dump.

The reference auto-prints per-node state, per-edge state, fragment membership,
and unreachable-node detection when a run produces the wrong edge count
(``/root/reference/ghs_implementation.py:554-641``, triggered at
``:735-737``). Here the same information is collected into one JSON artifact
whenever verification fails — machine-checkable, and it works at scales where
a per-node table could never be printed (histograms + capped samples instead).
"""

from __future__ import annotations

import json
from typing import Dict, Optional

import numpy as np

# Per-node tables are only useful (and affordable) for small graphs; above
# this the report keeps aggregates and capped samples only.
_NODE_TABLE_CAP = 512
_SAMPLE_CAP = 32


def _mst_components(num_nodes: int, u: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Component label per vertex under the harvested MST edges (the shared
    C-speed pass in ``graphs.edgelist.component_labels`` — a failed RMAT-20
    run must not spend minutes in a Python union-find)."""
    from distributed_ghs_implementation_tpu.graphs.edgelist import (
        component_labels,
    )

    return component_labels(num_nodes, u, v)


def failure_report(result, verification=None, *, nodes: Optional[Dict] = None) -> dict:
    """Build the diagnostic dict for a (suspected wrong) :class:`MSTResult`.

    ``nodes`` is the per-node map from ``protocol.runner.run_protocol`` — when
    given, per-node protocol state and edge-state tallies are included (the
    analog of the reference's node/edge tables at
    ``ghs_implementation.py:565-597``).
    """
    graph = result.graph
    n = graph.num_nodes
    mst_u = graph.u[result.edge_ids]
    mst_v = graph.v[result.edge_ids]
    comp = _mst_components(n, mst_u, mst_v)
    roots, sizes = np.unique(comp, return_counts=True)

    # Fragment-size histogram: size -> how many fragments have that size.
    hist_sizes, hist_counts = np.unique(sizes, return_counts=True)

    # Edge disposition under the final partition: an edge between two
    # components is still "alive" (a correct spanning forest leaves none).
    inter = comp[graph.u] != comp[graph.v]
    alive_edges = int(np.count_nonzero(inter))
    wcast = int if graph.is_integer_weighted else float
    alive_sample = [
        (int(graph.u[i]), int(graph.v[i]), wcast(graph.w[i]))
        for i in np.nonzero(inter)[0][:_SAMPLE_CAP]
    ]

    # Unreachable-node detection (reference: BFS from node 0 at
    # ghs_implementation.py:621-641): vertices outside node 0's component.
    unreachable = np.nonzero(comp != comp[0])[0] if n else np.zeros(0, np.int64)

    report = {
        "schema": "ghs-failure-report-v1",
        "graph": {
            "num_nodes": n,
            "num_edges": graph.num_edges,
            "total_weight": float(graph.total_weight),
        },
        "result": {
            "backend": result.backend,
            "num_levels": result.num_levels,
            "mst_edges": result.num_edges,
            "mst_weight": float(result.total_weight),
            "num_components": result.num_components,
        },
        "verification": None
        if verification is None
        else {
            "ok": bool(verification.ok),
            "oracle": verification.oracle,
            "expected_weight": verification.expected_weight,
            "actual_weight": verification.actual_weight,
            "expected_edges": verification.expected_edges,
            "actual_edges": verification.actual_edges,
        },
        "fragments": {
            "count": int(roots.size),
            "size_histogram": {int(s): int(c) for s, c in zip(hist_sizes, hist_counts)},
            "largest": sorted(
                ((int(r), int(s)) for r, s in zip(roots, sizes)),
                key=lambda x: -x[1],
            )[:_SAMPLE_CAP],
        },
        "edges": {
            "alive_inter_fragment": alive_edges,
            "alive_sample": alive_sample,
        },
        "unreachable_from_node0": {
            "count": int(unreachable.size),
            "sample": [int(x) for x in unreachable[:_SAMPLE_CAP]],
        },
    }

    if nodes is not None:
        from distributed_ghs_implementation_tpu.protocol.messages import EdgeState

        edge_state_totals = {s.name: 0 for s in EdgeState}
        node_rows = []
        for vid in sorted(nodes):
            node = nodes[vid]
            for e in node.edges.values():
                edge_state_totals[e.state.name] += 1
            if len(node_rows) < _NODE_TABLE_CAP:
                node_rows.append(
                    {
                        "id": node.id,
                        "state": node.state.name,
                        "level": node.level,
                        "fragment": node.fragment,
                        "find_count": node.find_count,
                        "best_edge": node.best_edge,
                        "in_branch": node.in_branch,
                        "halted": node.halted,
                        "messages_processed": node.messages_processed,
                        "edge_states": {
                            str(e.neighbor): e.state.name for e in node.edges.values()
                        },
                    }
                )
        report["protocol"] = {
            "edge_state_totals": edge_state_totals,
            "nodes_truncated": len(nodes) > _NODE_TABLE_CAP,
            "nodes": node_rows,
        }
    return report


def dump_failure_report(
    result, verification=None, *, nodes=None, path: str = "ghs_failure_report.json"
) -> str:
    """Write :func:`failure_report` to ``path`` (the auto-dump trigger analog
    of ``ghs_implementation.py:735-737``); returns the path."""
    report = failure_report(result, verification, nodes=nodes)
    with open(path, "w") as f:
        json.dump(report, f, indent=2)
    return path
