"""Fleet tier: framing, consistent-hash routing, failover/re-queue,
shedding, drain, and the shared-disk-store recovery path.

Most tests run against ``--test-echo`` workers (real subprocesses + real
pipes + real kills, canned answers — no kernel compiles), so the failover
machinery is exercised at full fidelity in seconds. One integration test
runs real ``MSTService`` workers end to end.
"""

import io
import os
import signal
import subprocess
import sys
import time

import pytest

from distributed_ghs_implementation_tpu.fleet.framing import (
    FrameError,
    read_frame,
    write_frame,
)
from distributed_ghs_implementation_tpu.fleet.hashing import HashRing
from distributed_ghs_implementation_tpu.fleet.router import (
    FleetConfig,
    FleetRouter,
)
from distributed_ghs_implementation_tpu.fleet.transport import (
    PROTO_VERSION,
    HelloError,
    build_hello,
    check_hello,
)
from distributed_ghs_implementation_tpu.obs.events import BUS


@pytest.fixture(autouse=True)
def _clean_global_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.enable()
    BUS.clear()


# ----------------------------------------------------------------------
# Framing
# ----------------------------------------------------------------------
def test_frame_round_trip_and_interleaved_stream():
    buf = io.BytesIO()
    frames = [{"id": 1, "req": {"op": "solve"}}, {"pong": 7}, {"drain": True}]
    for f in frames:
        write_frame(buf, f)
    buf.seek(0)
    assert [read_frame(buf) for _ in frames] == frames
    assert read_frame(buf) is None  # EOF


def test_frame_torn_and_garbage_raise_typed_frame_error():
    # Torn payload: header promises more bytes than the stream holds.
    with pytest.raises(FrameError, match="truncated"):
        read_frame(io.BytesIO(b"100\n{\"id\": 1}"))
    # Garbage header.
    with pytest.raises(FrameError, match="non-numeric"):
        read_frame(io.BytesIO(b"not-a-length\nxx\n"))
    # Valid length, invalid JSON.
    with pytest.raises(FrameError, match="not valid JSON"):
        read_frame(io.BytesIO(b"2\nxx\n"))
    # A frame that parses but is not an object.
    with pytest.raises(FrameError, match="not object"):
        read_frame(io.BytesIO(b"7\n[1,2,3]\n"))
    # FrameError IS a ValueError: peer-death handlers that catch
    # (OSError, ValueError) keep treating a garbled peer as dead.
    assert issubclass(FrameError, ValueError)


def test_frame_truncated_prefix_and_header_flood():
    # Truncated prefix: bytes end inside the header (no newline) — the
    # stream is garbage, not EOF.
    with pytest.raises(FrameError, match="header"):
        read_frame(io.BytesIO(b"123"))
    # A corrupt stream with no newline anywhere must NOT buffer
    # unboundedly hunting for one: the header read is capped.
    with pytest.raises(FrameError, match="header"):
        read_frame(io.BytesIO(b"9" * 10_000))


def test_frame_oversize_declaration_refused_before_allocating():
    # A corrupt length prefix may not size an allocation: past max_bytes
    # the frame is refused without reading the payload.
    big = b"999999999999\n" + b"x" * 64
    with pytest.raises(FrameError, match="outside"):
        read_frame(io.BytesIO(big))
    # Per-call ceilings tighten the default (the hello exchange).
    frame = io.BytesIO()
    write_frame(frame, {"pad": "y" * 2048})
    frame.seek(0)
    with pytest.raises(FrameError, match="outside"):
        read_frame(frame, max_bytes=128)
    # ...and a frame under the ceiling still round-trips.
    frame.seek(0)
    assert read_frame(frame, max_bytes=1 << 20)["pad"] == "y" * 2048


def test_frame_crc_round_trip_and_meta():
    from distributed_ghs_implementation_tpu.fleet.framing import encode_frame

    obj = {"id": 3, "resp": {"ok": True, "total_weight": 42}}
    buf = io.BytesIO(encode_frame(obj, crc=True))
    meta = {}
    assert read_frame(buf, meta=meta) == obj
    assert meta["crc"] is True
    # Legacy frames still read, and report crc=False.
    buf = io.BytesIO(encode_frame(obj))
    meta = {}
    assert read_frame(buf, meta=meta) == obj
    assert meta["crc"] is False


def test_frame_crc_rejects_every_bit_flipped_payload():
    """The gap CRC closes: without it, a flipped payload byte can survive
    as DIFFERENT valid JSON (e.g. a mutated digit in a weight). With the
    checksummed form, every single-bit payload mutation is a typed
    FrameError at the frame boundary — fuzzed across all payload bytes
    and several bit positions."""
    import random

    from distributed_ghs_implementation_tpu.fleet.framing import encode_frame

    obj = {"id": 9, "resp": {"ok": True, "total_weight": 1234,
                             "mst_edges": [[0, 1], [1, 2]]}}
    frame = encode_frame(obj, crc=True)
    header_len = frame.index(b"\n") + 1
    payload_len = len(frame) - header_len - 1
    rng = random.Random(7)
    for _ in range(64):
        i = header_len + rng.randrange(payload_len)
        flipped = bytearray(frame)
        flipped[i] ^= 1 << rng.randrange(8)
        with pytest.raises(FrameError):
            read_frame(io.BytesIO(bytes(flipped)))
    # The same flips on a LEGACY frame demonstrate the hole: at least one
    # mutation must survive parsing as a different object (that is why
    # the checksum exists). Flip each digit of the weight.
    legacy = encode_frame(obj)
    lh = legacy.index(b"\n") + 1
    survived = 0
    for i in range(lh, len(legacy) - 1):
        flipped = bytearray(legacy)
        flipped[i] ^= 1  # low-bit flip: digit -> adjacent digit
        try:
            out = read_frame(io.BytesIO(bytes(flipped)))
        except FrameError:
            continue
        if out is not None and out != obj:
            survived += 1
    assert survived > 0


def test_frame_crc_garbage_headers_refused():
    with pytest.raises(FrameError, match="non-hex"):
        read_frame(io.BytesIO(b"5 zzzz\nhello\n"))
    with pytest.raises(FrameError, match="malformed"):
        read_frame(io.BytesIO(b"5 1a2b 77\nhello\n"))
    # Declared crc that simply mismatches.
    with pytest.raises(FrameError, match="checksum mismatch"):
        read_frame(io.BytesIO(b'2 00000000\n{}\n'))


def test_transport_crc_echo_on_receipt():
    """A worker-side transport flips its outbound frames to the
    checksummed form after the first checksummed inbound frame — the
    negotiation that never sends CRC at a peer that might not parse it."""
    import os as _os

    from distributed_ghs_implementation_tpu.fleet.transport import (
        PipeTransport,
    )

    r1, w1 = _os.pipe()  # router -> worker
    r2, w2 = _os.pipe()  # worker -> router
    router_side = PipeTransport(_os.fdopen(w1, "wb"), _os.fdopen(r2, "rb"))
    worker_side = PipeTransport(_os.fdopen(w2, "wb"), _os.fdopen(r1, "rb"))
    try:
        assert not worker_side.crc_out
        worker_side.send({"ready": True})  # hello: always legacy form
        meta_frame = router_side.recv()
        assert meta_frame == {"ready": True} and not router_side.crc_out
        router_side.enable_crc()  # the hello advertised caps.crc
        router_side.send({"ping": 1})
        assert worker_side.recv() == {"ping": 1}
        assert worker_side.crc_out  # echo-on-receipt
        worker_side.send({"pong": 1})
        assert router_side.recv() == {"pong": 1}
    finally:
        router_side.close()
        worker_side.close()


def test_chaos_payload_corrupts_only_result_frames_exactly():
    """fleet.chaos.payload fires PAST framing, only on decoded solve
    responses that carry an edge set, one armed shot per corrupted
    frame — so drill counters map 1:1 onto corruptions."""
    from distributed_ghs_implementation_tpu.fleet.transport import (
        ChaosState,
        ChaosTransport,
        PipeTransport,
    )
    from distributed_ghs_implementation_tpu.utils.resilience import FAULTS

    r1, w1 = os.pipe()
    writer = PipeTransport(os.fdopen(w1, "wb"), io.BytesIO())
    reader = ChaosTransport(
        PipeTransport(io.BytesIO(), os.fdopen(r1, "rb")), ChaosState()
    )
    try:
        FAULTS.arm("fleet.chaos.payload", times=1)
        result = {"id": 1, "resp": {
            "ok": True, "total_weight": 10, "mst_edges": [[0, 1], [1, 2]]}}
        writer.send({"pong": 3})          # no edge set: never corrupted
        writer.send(dict(result))         # armed: corrupted
        writer.send({"id": 2, "resp": dict(result["resp"])})  # shot spent
        assert reader.recv() == {"pong": 3}
        corrupted = reader.recv()
        assert corrupted["resp"]["total_weight"] == 11
        assert corrupted["resp"]["mst_edges"][0] == [0, 0]
        clean = reader.recv()
        assert clean["resp"]["total_weight"] == 10
        assert BUS.counters().get("fleet.chaos.payload_corrupted") == 1
    finally:
        FAULTS.reset()
        writer.close()
        reader.close()


def test_router_certifies_solve_responses():
    """The router-side payload certificate: a good claim passes, a
    mutated edge set / weight fails, unverifiable pairs are skipped."""
    import numpy as np

    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.models.rank_solver import (
        solve_graph_kruskal_host,
    )

    g = gnm_random_graph(48, 120, seed=3)
    edge_ids, _frag, _lv = solve_graph_kruskal_host(g)
    mst_edges = [[int(a), int(b)]
                 for a, b in zip(g.u[edge_ids], g.v[edge_ids])]
    weight = int(np.sum(g.w[edge_ids]))
    request = {
        "op": "solve", "num_nodes": g.num_nodes,
        "edges": [[int(a), int(b), int(c)]
                  for a, b, c in zip(g.u, g.v, g.w)],
    }
    good = {"ok": True, "mst_edges": mst_edges, "total_weight": weight}
    cert = FleetRouter._certify_solve_response(request, good)
    assert cert is not None and cert.ok
    bad = dict(good, total_weight=weight + 1)
    cert = FleetRouter._certify_solve_response(request, bad)
    assert cert is not None and not cert.ok
    assert cert.reason == "weight_mismatch"
    mangled = dict(good, mst_edges=[[0, 0]] + mst_edges[1:])
    cert = FleetRouter._certify_solve_response(request, mangled)
    assert cert is not None and cert.reason == "unknown_edge"
    # Unverifiable pairs: digest-only requests, edge-less responses.
    assert FleetRouter._certify_solve_response(
        {"op": "solve", "digest": "d"}, good
    ) is None
    assert FleetRouter._certify_solve_response(
        request, {"ok": True, "total_weight": weight}
    ) is None
    # Structurally malformed claims from a buggy/lying peer must FAIL
    # certification, never crash the request that would have rejected
    # them (ragged rows, non-numeric entries).
    for junk in ([[0, 1], [2]], [["a", "b"]], [[0]], "nope and nope"):
        cert = FleetRouter._certify_solve_response(
            request, dict(good, mst_edges=junk if isinstance(junk, list)
                          else [junk])
        )
        assert cert is not None and not cert.ok
        assert cert.reason == "malformed_claim", (junk, cert.summary())


# ----------------------------------------------------------------------
# Hello / protocol version (fleet/transport.py)
# ----------------------------------------------------------------------
def test_hello_round_trip_carries_proto_and_caps():
    hello = build_hello(
        3, caps={"lane": True, "stream": False, "kernel": "xla"},
        token="tok-1",
    )
    checked = check_hello(dict(hello))
    assert checked["proto"] == PROTO_VERSION
    assert checked["worker"] == 3 and checked["token"] == "tok-1"
    # Round 19: every hello from this build additionally advertises the
    # frame-checksum capability (the router version-gates CRC on it), and
    # this round the trace capability (the router version-gates the
    # request frames' trace field on it the same way) — asserted as a
    # SUBSET, not an exact dict, so the next capability doesn't break
    # this test the way trace broke its exact-match ancestor.
    expected = {"lane": True, "stream": False, "kernel": "xla"}
    assert {k: checked["caps"][k] for k in expected} == expected
    assert checked["caps"]["crc"] is True
    assert checked["caps"]["trace"] is True


def test_hello_legacy_no_trace_cap_degrades_to_untraced_frames(monkeypatch):
    """A worker that doesn't advertise ``caps.trace`` (an older build, or
    this one with GHS_FLEET_TRACE=0) must degrade to untraced request
    frames — same version-gating contract as the round-19 CRC opt-in —
    never to a frame the legacy peer could reject."""
    from distributed_ghs_implementation_tpu.obs import tracing

    # The worker subprocesses inherit the router process environment, so
    # the env var IS the legacy-worker simulator.
    monkeypatch.setenv("GHS_FLEET_TRACE", "0")
    hello = build_hello(0)
    assert hello["caps"]["trace"] is False  # what a legacy peer "says"
    cfg = FleetConfig(
        workers=1, test_echo=True,
        heartbeat_interval_s=0.1, ready_timeout_s=120.0,
        request_timeout_s=30.0,
    )
    router = FleetRouter(cfg).start()
    try:
        assert router._workers[0].caps.get("trace") is False
        # A traced front door is ACTIVE on the router side; the gate must
        # still keep the wire clean and the request must still answer.
        ctx = tracing.mint("interactive")
        token = tracing.activate(ctx)
        try:
            resp = router.handle({"op": "solve", "digest": "legacy-probe"})
        finally:
            tracing.deactivate(token)
        assert resp["ok"]
        # The router-side request span is still traced (local telemetry
        # does not degrade — only the wire field does).
        spans = [
            args for ph, name, _c, _t, _d, _tid, args in BUS.events()
            if name == "fleet.request" and args
        ]
        assert any(a.get("trace") == ctx.trace_id for a in spans)
    finally:
        router.shutdown()


def test_hello_version_mismatch_rejected_with_clear_error():
    hello = build_hello(0)
    hello["proto"] = PROTO_VERSION + 7
    with pytest.raises(HelloError, match="protocol version mismatch"):
        check_hello(hello)
    with pytest.raises(HelloError, match="not a hello"):
        check_hello({"pong": 1})
    missing = build_hello(0)
    del missing["worker"]
    with pytest.raises(HelloError, match="worker id"):
        check_hello(missing)


# ----------------------------------------------------------------------
# Consistent hashing (satellite: stability + bounded movement)
# ----------------------------------------------------------------------
def test_ring_deterministic_across_instances():
    keys = [f"digest-{i}" for i in range(300)]
    a = HashRing([0, 1, 2])
    b = HashRing([2, 0, 1])  # insertion order must not matter
    assert [a.assign(k) for k in keys] == [b.assign(k) for k in keys]
    # ...and across "restarts": a freshly built ring maps identically.
    assert [HashRing([0, 1, 2]).assign(k) for k in keys] == [
        a.assign(k) for k in keys
    ]


def test_ring_remove_moves_only_the_dead_workers_keys():
    keys = [f"digest-{i}" for i in range(500)]
    ring = HashRing([0, 1, 2])
    before = {k: ring.assign(k) for k in keys}
    assert set(before.values()) == {0, 1, 2}  # every worker owns a share
    ring.remove(1)
    after = {k: ring.assign(k) for k in keys}
    for k in keys:
        if before[k] != 1:
            assert after[k] == before[k]  # survivors' keys never move
        else:
            assert after[k] in (0, 2)
    # Rejoin restores the original mapping exactly (cache affinity
    # survives a restart round-trip).
    ring.add(1)
    assert {k: ring.assign(k) for k in keys} == before


def test_ring_empty_raises_and_len_counts_members():
    ring = HashRing()
    assert len(ring) == 0
    with pytest.raises(LookupError):
        ring.assign("x")
    ring.add(3)
    assert len(ring) == 1 and ring.assign("anything") == 3


def test_ring_churn_moves_bounded_keys_and_stays_deterministic():
    # The elastic fleet's ring contract: adding K workers moves only the
    # keys the joiners take over (a bounded fraction), removing them
    # restores the original mapping exactly, and the digest->owner map is
    # identical across router restarts with the same member set.
    import random

    keys = [f"digest-{i}" for i in range(2000)]
    base = [0, 1, 2, 3, 4, 5]
    ring = HashRing(base)
    before = {k: ring.assign(k) for k in keys}
    for m in (6, 7):
        ring.add(m)
    after = {k: ring.assign(k) for k in keys}
    moved = [k for k in keys if after[k] != before[k]]
    # Every moved key went TO a joiner — survivors never reshuffle among
    # themselves — and the moved fraction is bounded (expected K/(N+K) =
    # 0.25 at 64 replicas; 0.45 leaves room for placement variance).
    assert moved and all(after[k] in (6, 7) for k in moved)
    assert len(moved) / len(keys) < 0.45
    for m in (6, 7):
        ring.remove(m)
    assert {k: ring.assign(k) for k in keys} == before
    # Restart determinism: a freshly built ring with the same member set
    # (any insertion order) maps identically.
    rng = random.Random(7)
    shuffled = list(base)
    rng.shuffle(shuffled)
    rebuilt = HashRing(shuffled)
    assert {k: rebuilt.assign(k) for k in keys} == before
    # Idempotent add: a join racing a rejoin must not duplicate a
    # member's ring points (which would silently double its keyspace).
    rebuilt.add(3)
    assert {k: rebuilt.assign(k) for k in keys} == before
    assert len(rebuilt._points) == len(base) * rebuilt.replicas
    # Sustained churn: after an arbitrary add/remove sequence, assignment
    # equals a fresh ring over the surviving member set.
    live = set(base)
    churn = HashRing(base)
    for _ in range(40):
        if rng.random() < 0.5 and len(live) > 1:
            m = rng.choice(sorted(live))
            churn.remove(m)
            live.discard(m)
        else:
            m = rng.randrange(0, 12)
            churn.add(m)
            live.add(m)
    fresh = HashRing(sorted(live))
    assert all(churn.assign(k) == fresh.assign(k) for k in keys[:500])


def test_restart_backoff_jitter_deterministic_under_seed():
    # Satellite: mass worker death must not thundering-herd the shared
    # disk store / compile cache — backoffs carry a per-(worker, attempt)
    # jitter that is reproducible under restart_jitter_seed.
    def seq(router):
        return [
            router._backoff_s(w, k) for w in range(4) for k in range(6)
        ]

    a = FleetRouter(FleetConfig(workers=1, test_echo=True,
                                restart_jitter_seed=42))
    b = FleetRouter(FleetConfig(workers=1, test_echo=True,
                                restart_jitter_seed=42))
    c = FleetRouter(FleetConfig(workers=1, test_echo=True,
                                restart_jitter_seed=43))
    assert seq(a) == seq(b)  # same seed, same schedule (tests reproduce)
    assert seq(a) != seq(c)  # the seed actually moves the schedule
    cap = a.config.restart_backoff_cap_s
    assert all(0 < x <= cap for x in seq(a))  # the cap stays a ceiling
    # Desync is the point: same attempt number, different workers, all
    # distinct sleep times — the restart wave fans out.
    same_attempt = [a._backoff_s(w, 8) for w in range(6)]
    assert len(set(same_attempt)) == len(same_attempt)
    # Jitter off: the documented plain capped exponential.
    plain = FleetRouter(FleetConfig(workers=1, test_echo=True,
                                    restart_jitter=0.0))
    assert plain._backoff_s(3, 2) == min(0.05 * 4, 2.0)


# ----------------------------------------------------------------------
# Echo fleet: routing, failover, re-queue idempotency
# ----------------------------------------------------------------------
@pytest.fixture(scope="module")
def echo_fleet():
    cfg = FleetConfig(
        workers=3, test_echo=True,
        heartbeat_interval_s=0.1, restart_backoff_base_s=0.02,
        restart_backoff_cap_s=0.2, ready_timeout_s=120.0,
        request_timeout_s=30.0,
    )
    router = FleetRouter(cfg).start()
    yield router
    router.shutdown()


def test_fleet_routes_deterministically_by_digest(echo_fleet):
    r = echo_fleet
    first = {
        d: r.handle({"op": "solve", "digest": d})["worker"]
        for d in (f"d{i}" for i in range(24))
    }
    assert set(first.values()) == {0, 1, 2}  # the deck spreads
    for d, w in first.items():
        assert r.handle({"op": "solve", "digest": d})["worker"] == w


def test_fleet_update_chain_sticks_to_the_session_worker(echo_fleet):
    r = echo_fleet
    solved = r.handle({"op": "solve", "digest": "chain-seed"})
    digest, workers = "chain-seed", set()
    for _ in range(5):
        resp = r.handle(
            {"op": "update", "digest": digest, "updates": [{"k": 1}]}
        )
        assert resp["ok"]
        digest = resp["digest"]
        workers.add(resp["worker"])
    # Re-keying renames the digest every hop; the session pin keeps every
    # hop on the worker that owns the materialized session.
    assert workers == {solved["worker"]}


def test_fleet_kill_mid_traffic_requeues_and_restarts(echo_fleet):
    r = echo_fleet
    victim = r.handle({"op": "solve", "digest": "kill-probe"})["worker"]
    restarts_before = r._workers[victim].restarts
    dead_before = BUS.counters().get("fleet.worker.dead", 0)
    # Arm the registry INSIDE the worker: it dies in place of its next
    # request (no response flushed) — the accepted query must still be
    # answered, by a survivor, via the digest re-queue.
    assert r.arm_worker_fault(victim, times=1)
    resp = r.handle({"op": "solve", "digest": "kill-probe", "slo_class": "x"})
    assert resp["ok"] and resp["worker"] != victim
    assert resp.get("requeued", 0) >= 1
    counters = BUS.counters()
    assert counters.get("fleet.worker.dead", 0) == dead_before + 1
    assert counters.get("fleet.requeue", 0) >= 1
    # The dead worker restarts with backoff and rejoins the ring...
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not r._workers[victim].alive:
        time.sleep(0.05)
    assert r._workers[victim].alive
    assert r._workers[victim].restarts == restarts_before + 1
    # ...and serves its keyspace again (deterministic mapping restored).
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        resp = r.handle({"op": "solve", "digest": "kill-probe"})
        assert resp["ok"]
        if resp["worker"] == victim:
            break
        time.sleep(0.05)
    assert resp["worker"] == victim


def test_fleet_kill_requeue_preserves_trace_with_new_child_span(echo_fleet):
    """Trace continuity across failover: when the owning worker dies
    mid-request and the router re-queues onto a survivor, the re-dispatch
    must stay in the ORIGINAL request's trace (same trace id) as a fresh
    child span — one trace tells the whole failover story."""
    from distributed_ghs_implementation_tpu.obs import tracing

    r = echo_fleet
    victim = r.handle({"op": "solve", "digest": "trace-kill"})["worker"]
    assert r.arm_worker_fault(victim, times=1)
    BUS.clear()
    ctx = tracing.mint("interactive")
    token = tracing.activate(ctx)
    try:
        resp = r.handle({"op": "solve", "digest": "trace-kill"})
    finally:
        tracing.deactivate(token)
    assert resp["ok"] and resp.get("requeued", 0) >= 1
    spans: dict = {}
    for _ph, name, _cat, _ts, _dur, _tid, args in BUS.events():
        if args and args.get("trace") == ctx.trace_id:
            spans.setdefault(name, []).append(args)
    (root,) = spans["fleet.request"]
    attempts = spans["fleet.attempt"]
    assert attempts and all(a["parent"] == root["span"] for a in attempts)
    redispatches = spans["fleet.requeue.dispatch"]
    assert redispatches, "failover re-dispatch must be a traced span"
    for red in redispatches:
        assert red["span"] != root["span"]  # a NEW span...
        # ...parented inside the attempt whose worker died, so the tree
        # reads request -> attempt -> requeue.dispatch.
        assert red["parent"] in {a["span"] for a in attempts}
    # Wait for the victim's restart so the module-scoped fleet is healthy
    # for whoever runs next.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not r._workers[victim].alive:
        time.sleep(0.05)
    assert r._workers[victim].alive


def test_fleet_same_digest_twice_lands_once_per_worker(echo_fleet):
    # Re-queue idempotency's foundation: duplicate digests route to the
    # same worker, whose scheduler single-flights them; a duplicated
    # *response* (late delivery from a "dead" worker) is discarded by the
    # pending-map pop, never delivered twice.
    r = echo_fleet
    a = r.handle({"op": "solve", "digest": "dup-digest"})
    b = r.handle({"op": "solve", "digest": "dup-digest"})
    assert a["ok"] and b["ok"] and a["worker"] == b["worker"]


def test_fleet_stats_aggregates_workers(echo_fleet):
    stats = echo_fleet.handle({"op": "stats"})
    assert stats["ok"] and stats["counters"].get("echo.handled", 0) >= 1
    assert sorted(stats["ring"]) == [0, 1, 2]
    assert set(stats["workers"]) == {"0", "1", "2"}


# ----------------------------------------------------------------------
# Admission control + drain (their own small fleets: they wedge queues)
# ----------------------------------------------------------------------
def test_fleet_sheds_configured_class_when_queue_full():
    cfg = FleetConfig(
        workers=1, test_echo=True, queue_depth=1,
        shed_classes=("droppable",), heartbeat_interval_s=0.2,
        ready_timeout_s=120.0, request_timeout_s=30.0,
    )
    with FleetRouter(cfg) as r:
        import threading

        slow = threading.Thread(
            target=r.handle,
            args=({"op": "solve", "digest": "slow", "sleep_s": 1.0},),
        )
        slow.start()
        time.sleep(0.3)  # the one slot is now held by the sleeper
        shed = r.handle(
            {"op": "solve", "digest": "x", "slo_class": "droppable"}
        )
        assert shed["shed"] and not shed["ok"]
        # A non-sheddable class backpressures instead and succeeds.
        kept = r.handle({"op": "solve", "digest": "y", "slo_class": "gold"})
        assert kept["ok"]
        slow.join()
        assert BUS.counters().get("fleet.shed", 0) == 1


def test_fleet_graceful_drain_answers_in_flight_and_exits_zero():
    cfg = FleetConfig(
        workers=1, test_echo=True, heartbeat_interval_s=0.2,
        ready_timeout_s=120.0,
    )
    r = FleetRouter(cfg).start()
    import threading

    results = []
    t = threading.Thread(
        target=lambda: results.append(
            r.handle({"op": "solve", "digest": "inflight", "sleep_s": 0.5})
        )
    )
    t.start()
    time.sleep(0.2)  # the request is in the worker when drain begins
    r.shutdown(drain=True)
    t.join(timeout=10)
    assert results and results[0]["ok"]  # drained, not dropped
    assert r._workers[0].proc.returncode == 0  # exit 0, not a kill


def test_retire_drain_outliving_lease_is_not_declared_dead():
    # Satellite regression: a worker in graceful drain stops reading its
    # channel on purpose — if the lease still applied, a drain slower than
    # lease_s would be declared dead mid-flush and its in-flight work
    # re-queued (duplicate solves + a spurious fleet.worker.dead in a
    # PLANNED scale-down). The lease is 0.3s here and the drain takes
    # ~0.6s; the response must still come back from the draining worker.
    import threading

    cfg = FleetConfig(
        workers=2, test_echo=True, heartbeat_interval_s=0.05,
        lease_s=0.3, ready_timeout_s=120.0, request_timeout_s=30.0,
    )
    r = FleetRouter(cfg).start()
    try:
        victim = r.handle({"op": "solve", "digest": "drain-probe"})["worker"]
        results = []
        t = threading.Thread(target=lambda: results.append(r.handle(
            {"op": "solve", "digest": "drain-probe", "sleep_s": 0.6}
        )))
        t.start()
        time.sleep(0.2)  # the slow request is inside the victim now
        # timeout_s below the in-flight sleep: the drain frame goes out
        # with work still in flight, so the flush phase outlives lease_s.
        out = r.retire_worker(victim, timeout_s=0.1)
        t.join(timeout=30)
        assert results, "in-flight request lost during retire"
        resp = results[0]
        assert resp["ok"] and resp["worker"] == victim  # flushed, not moved
        assert "requeued" not in resp
        counters = BUS.counters()
        assert counters.get("fleet.lease.expired", 0) == 0
        assert counters.get("fleet.heartbeat.miss", 0) == 0
        assert counters.get("fleet.worker.dead", 0) == 0
        assert counters.get("fleet.requeue", 0) == 0
        assert out["exit_code"] == 0  # drained, never killed
        assert counters.get("fleet.scale.down", 0) == 1
    finally:
        r.shutdown()


def test_worker_sigterm_drains_and_exits_zero(tmp_path):
    # SIGTERM straight at a worker process: drain semantics, exit 0.
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_ghs_implementation_tpu.fleet.worker",
         "--worker-id", "0", "--test-echo"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        env={**os.environ, "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + os.environ.get("PYTHONPATH", "").split(os.pathsep)
        )},
    )
    try:
        assert read_frame(proc.stdout).get("ready")
        write_frame(proc.stdin, {"id": 1, "req": {"op": "solve",
                                                  "digest": "d"}})
        assert read_frame(proc.stdout)["resp"]["ok"]
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()


# ----------------------------------------------------------------------
# Real-service fleet: cache affinity + shared-store failover
# ----------------------------------------------------------------------
def _solve_request(g, cls=None):
    req = {
        "op": "solve",
        "num_nodes": g.num_nodes,
        "edges": [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)],
    }
    if cls:
        req["slo_class"] = cls
    return req


def test_fleet_real_service_affinity_update_and_disk_failover(tmp_path):
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )

    cfg = FleetConfig(
        workers=2, disk_dir=str(tmp_path / "store"),
        heartbeat_interval_s=0.25, restart_backoff_base_s=0.05,
        ready_timeout_s=180.0, request_timeout_s=120.0,
    )
    with FleetRouter(cfg) as r:
        graphs = [gnm_random_graph(40, 90, seed=s) for s in range(3)]
        solved = [r.handle(_solve_request(g, "miss")) for g in graphs]
        assert all(s["ok"] for s in solved), solved
        # Affinity: a repeat is a cache hit on the SAME worker.
        again = r.handle(_solve_request(graphs[0], "hit"))
        assert again["ok"] and again["cached"]
        assert again["worker"] == solved[0]["worker"]
        # Updates flow through the session worker and re-key.
        upd = r.handle({
            "op": "update", "digest": solved[0]["digest"],
            "updates": [{"kind": "insert", "u": 0, "v": 7, "w": 1}],
        })
        assert upd["ok"] and upd["prev_digest"] == solved[0]["digest"]
        # Kill a worker; its digests must still be answerable by the
        # survivor THROUGH THE SHARED DISK STORE (no re-solve required,
        # though a re-solve would also be correct — same forest).
        victim = solved[1]["worker"]
        r.kill_worker(victim)
        time.sleep(0.5)
        after = r.handle(_solve_request(graphs[1], "hit"))
        assert after["ok"]
        assert after["total_weight"] == solved[1]["total_weight"]


def test_service_cached_only_probe_hits_after_solve(tmp_path):
    # The forwarding hop's worker-side half: a cached_only solve answers
    # from the store by digest alone (no edge list on the wire) and NEVER
    # solves on a miss.
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    service = MSTService()
    g = gnm_random_graph(30, 60, seed=5)
    digest = g.digest()
    miss = service.handle({"op": "solve", "cached_only": True,
                           "digest": digest})
    assert not miss["ok"] and miss["cache_miss"]
    assert BUS.counters().get("serve.errors", 0) == 0  # a miss is not an error
    solved = service.handle(_solve_request(g))
    hit = service.handle({"op": "solve", "cached_only": True,
                          "digest": digest})
    assert hit["ok"] and hit["cached"] and hit["source"] == "cache"
    assert hit["total_weight"] == solved["total_weight"]
    bad = service.handle({"op": "solve", "cached_only": True})
    assert not bad["ok"] and "digest" in bad["error"]


# ----------------------------------------------------------------------
# TCP transport: the same fleet over localhost sockets
# ----------------------------------------------------------------------
@pytest.fixture(scope="module")
def tcp_fleet():
    cfg = FleetConfig(
        workers=3, test_echo=True, transport="tcp",
        heartbeat_interval_s=0.1, restart_backoff_base_s=0.02,
        restart_backoff_cap_s=0.2, ready_timeout_s=120.0,
        request_timeout_s=30.0,
    )
    router = FleetRouter(cfg).start()
    yield router
    router.shutdown()


def test_tcp_fleet_routes_and_pins_sessions_like_pipes(tcp_fleet):
    r = tcp_fleet
    first = {
        d: r.handle({"op": "solve", "digest": d})["worker"]
        for d in (f"t{i}" for i in range(24))
    }
    assert set(first.values()) == {0, 1, 2}
    for d, w in first.items():
        assert r.handle({"op": "solve", "digest": d})["worker"] == w
    solved = r.handle({"op": "solve", "digest": "tcp-chain"})
    digest, workers = "tcp-chain", set()
    for _ in range(4):
        resp = r.handle({"op": "update", "digest": digest,
                         "updates": [{"k": 1}]})
        assert resp["ok"]
        digest = resp["digest"]
        workers.add(resp["worker"])
    assert workers == {solved["worker"]}
    stats = r.handle({"op": "stats"})
    assert stats["transport"] == "tcp"
    assert stats["workers"]["0"]["transport"] == "tcp"
    assert stats["workers"]["0"]["caps"].get("kernel") is not None


def test_tcp_fleet_kill_mid_traffic_requeues_and_restarts(tcp_fleet):
    r = tcp_fleet
    victim = r.handle({"op": "solve", "digest": "tcp-kill"})["worker"]
    dead_before = BUS.counters().get("fleet.worker.dead", 0)
    assert r.arm_worker_fault(victim, times=1)
    resp = r.handle({"op": "solve", "digest": "tcp-kill", "slo_class": "x"})
    assert resp["ok"] and resp["worker"] != victim
    assert resp.get("requeued", 0) >= 1
    assert BUS.counters().get("fleet.worker.dead", 0) == dead_before + 1
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not r._workers[victim].alive:
        time.sleep(0.05)
    assert r._workers[victim].alive  # re-dialed in and rejoined the ring


def test_tcp_hard_socket_close_requeues_in_flight_onto_survivors(tcp_fleet):
    # Satellite: connection loss WITHOUT process death. The victim's
    # socket is hard-closed while it is mid-solve; its accepted request
    # must re-queue onto a survivor by digest — and the limping victim's
    # late response hits a dead socket, never a client.
    import threading

    r = tcp_fleet
    victim = r.handle({"op": "solve", "digest": "conn-loss"})["worker"]
    results = []
    t = threading.Thread(target=lambda: results.append(r.handle(
        {"op": "solve", "digest": "conn-loss", "sleep_s": 1.0}
    )))
    requeue_before = BUS.counters().get("fleet.requeue", 0)
    t.start()
    time.sleep(0.4)  # the request is inside the victim worker now
    r.close_worker_connection(victim)
    t.join(timeout=30)
    assert results, "in-flight request lost on connection close"
    resp = results[0]
    assert resp["ok"] and resp["worker"] != victim
    assert resp.get("requeued", 0) >= 1
    assert BUS.counters().get("fleet.requeue", 0) >= requeue_before + 1
    # Idempotency: the same digest keeps answering consistently afterwards.
    again = r.handle({"op": "solve", "digest": "conn-loss"})
    assert again["ok"]
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not r._workers[victim].alive:
        time.sleep(0.05)
    assert r._workers[victim].alive


def test_tcp_graceful_drain_answers_in_flight_and_exits_zero():
    import threading

    cfg = FleetConfig(
        workers=1, test_echo=True, transport="tcp",
        heartbeat_interval_s=0.2, ready_timeout_s=120.0,
    )
    r = FleetRouter(cfg).start()
    results = []
    t = threading.Thread(
        target=lambda: results.append(
            r.handle({"op": "solve", "digest": "inflight", "sleep_s": 0.5})
        )
    )
    t.start()
    time.sleep(0.2)
    r.shutdown(drain=True)
    t.join(timeout=10)
    assert results and results[0]["ok"]  # drained, not dropped
    assert r._workers[0].proc.returncode == 0


def test_tcp_forwarding_probes_owner_before_local_solve():
    # Cross-host cache-miss forwarding: worker 0 owns the lane subring,
    # forwarding on (no shared disk). A digest solved at its full-ring
    # owner and re-requested oversize forwards (hit, answered by the
    # owner, no local solve); a fresh oversize digest probes the owner,
    # misses, and solves locally at the lane worker.
    cfg = FleetConfig(
        workers=3, test_echo=True, transport="tcp",
        sharded_lane_workers=1, forward_cache=True,
        heartbeat_interval_s=0.2, ready_timeout_s=120.0,
        request_timeout_s=30.0,
    )
    ring = HashRing(range(3), replicas=cfg.ring_replicas)
    d_hit = next(f"fh-{i}" for i in range(1000)
                 if ring.assign(f"fh-{i}") != 0)
    d_miss = next(f"fm-{i}" for i in range(1000)
                  if ring.assign(f"fm-{i}") != 0)
    oversize = {"num_nodes": 200_000, "edges": [[0, 1, 1]]}
    with FleetRouter(cfg) as r:
        owner = r.handle({"op": "solve", "digest": d_hit})
        assert owner["worker"] == ring.assign(d_hit)
        fwd = r.handle({"op": "solve", "digest": d_hit, **oversize})
        assert fwd["ok"] and fwd["cached"]
        assert fwd["forwarded_from"] == owner["worker"]
        local = r.handle({"op": "solve", "digest": d_miss, **oversize})
        assert local["ok"] and local["worker"] == 0  # lane worker solved
        assert "forwarded_from" not in local
        counters = BUS.counters()
        assert counters.get("fleet.forward.hit", 0) == 1
        assert counters.get("fleet.forward.miss", 0) == 1
        stats = r.handle({"op": "stats"})
        assert stats["forward_cache"] is True


def _spawn_listening_worker(extra_env=None, worker_id=0):
    env = {**os.environ, "PYTHONPATH": os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + os.environ.get("PYTHONPATH", "").split(os.pathsep)
    ), **(extra_env or {})}
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_ghs_implementation_tpu.fleet.worker",
         "--worker-id", str(worker_id), "--test-echo",
         "--listen", "127.0.0.1:0"],
        stderr=subprocess.PIPE, env=env,
    )
    line = proc.stderr.readline().decode()
    assert "listening on" in line, line
    addr = line.rsplit(" ", 1)[-1].strip()
    return proc, addr


def test_remote_listen_worker_survives_partition_with_warm_rejoin():
    # The remote topology: an externally started `--listen` worker the
    # router dials by host:port. A hard connection close (network
    # partition) re-queues + reconnects to the SAME process — state
    # (echo.handled) proves the rejoin was warm, not a cold restart.
    proc, addr = _spawn_listening_worker()
    try:
        cfg = FleetConfig(
            remote_workers=(addr,), transport="tcp", test_echo=True,
            heartbeat_interval_s=0.1, restart_backoff_base_s=0.02,
            ready_timeout_s=30.0, request_timeout_s=30.0,
        )
        with FleetRouter(cfg) as r:
            for i in range(5):
                assert r.handle({"op": "solve", "digest": f"r{i}"})["ok"]
            r.close_worker_connection(0)
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and not r._workers[0].alive:
                time.sleep(0.05)
            assert r._workers[0].alive, "router never re-dialed the worker"
            after = r.handle({"op": "solve", "digest": "post-partition"})
            assert after["ok"]
            stats = r.handle({"op": "stats"})
            handled = stats["counters"].get("echo.handled", 0)
            # > 2: the pre-partition requests still count — same process.
            assert handled >= 6, f"cold restart suspected: handled={handled}"
            assert stats["workers"]["0"]["addr"] == addr
        # shutdown() drained the remote worker: it exits 0.
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()


def test_router_rejects_wrong_protocol_version_with_clear_error():
    # A worker advertising the wrong fleet protocol version must be
    # rejected at hello with an actionable message — not a silent ready
    # timeout. GHS_FLEET_PROTO is the test hook that fakes an old build.
    cfg = FleetConfig(
        workers=1, test_echo=True, transport="tcp",
        ready_timeout_s=6.0, max_restarts=1,
        worker_env={0: {"GHS_FLEET_PROTO": "999"}},
    )
    router = FleetRouter(cfg)
    with pytest.raises(TimeoutError, match="protocol version mismatch"):
        router.start()
    router.shutdown(drain=False)


# ----------------------------------------------------------------------
# Router survivability (round 18): journal, crash, warm re-adoption
# ----------------------------------------------------------------------
def _listen_fleet_config(addrs, tmp_path=None, **overrides):
    kwargs = dict(
        remote_workers=tuple(addrs), transport="tcp", test_echo=True,
        heartbeat_interval_s=0.1, restart_backoff_base_s=0.02,
        restart_backoff_cap_s=0.2, ready_timeout_s=30.0,
        request_timeout_s=30.0,
    )
    kwargs.update(overrides)
    return FleetConfig(**kwargs)


def test_router_crash_restart_readopts_workers_and_replays_journal(tmp_path):
    # The round-18 contract end to end: a router crash with accepted work
    # outstanding loses NOTHING — the successor on the same journal
    # re-dials the still-live --listen workers (warm: handled counts
    # persist), rebuilds pins/affinity, and re-queues the orphaned accept.
    import threading

    procs, addrs = zip(*[
        _spawn_listening_worker(worker_id=i) for i in range(2)
    ])
    jdir = str(tmp_path / "journal")
    try:
        cfg = _listen_fleet_config(addrs, journal_dir=jdir)
        r1 = FleetRouter(cfg).start()
        for i in range(4):
            assert r1.handle({"op": "solve", "digest": f"j{i}"})["ok"]
        upd = r1.handle({"op": "update", "digest": "j0",
                         "updates": [{"k": 1}]})
        assert upd["ok"]
        pin_digest, pin_worker = upd["digest"], upd["worker"]
        pre_handled = r1.handle({"op": "stats"})["counters"]["echo.handled"]

        results = []
        t = threading.Thread(target=lambda: results.append(r1.handle(
            {"op": "solve", "digest": "orphan", "sleep_s": 0.8}
        )))
        t.start()
        time.sleep(0.25)  # the accept is journaled and in flight
        r1.crash()
        t.join(timeout=10)
        assert results and results[0].get("router_crashed")

        r2 = FleetRouter(cfg).start()
        try:
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                stats = r2.handle({"op": "stats"})
                if stats["journal"]["unanswered"] == 0:
                    break
                time.sleep(0.1)
            # Every journaled accept is answered after replay...
            assert stats["journal"]["unanswered"] == 0
            # ...the workers were re-adopted WARM (same processes: the
            # pre-crash handled counts persist and keep growing)...
            assert stats["counters"]["echo.handled"] > pre_handled
            counters = BUS.counters()
            assert counters.get("fleet.router.crash") == 1
            assert counters.get("fleet.router.restart.readopted") == 2
            assert counters.get("fleet.router.restart.requeued", 0) >= 1
            assert counters.get("fleet.router.restart.replayed", 0) >= 1
            # ...and the session pin survived: the chain continues on the
            # worker holding the materialized session.
            upd2 = r2.handle({"op": "update", "digest": pin_digest,
                              "updates": [{"k": 2}]})
            assert upd2["ok"] and upd2["worker"] == pin_worker
        finally:
            r2.shutdown()
        for p in procs:
            assert p.wait(timeout=20) == 0  # drained, exit 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_journal_restores_scale_cooldown_across_restart(tmp_path):
    # A restarted router must not double-scale: the journaled (wall-clock
    # stamped) scale decision restores the autoscaler's cooldown window.
    from distributed_ghs_implementation_tpu.fleet.autoscaler import (
        Autoscaler,
        ElasticPolicy,
    )

    jdir = str(tmp_path / "journal")
    cfg = FleetConfig(workers=1, test_echo=True, journal_dir=jdir,
                      ready_timeout_s=120.0)
    with FleetRouter(cfg) as r1:
        r1.note_scale_decision({"action": "up", "pool": 2, "reason": "x"})
    r2 = FleetRouter(cfg)
    try:
        assert r2.last_scale_decision["action"] == "up"
        scaler = Autoscaler(r2, ElasticPolicy(cooldown_s=3600.0))
        # The cooldown clock survived the crash: a fresh autoscaler is
        # already cooling, not free to immediately scale again.
        assert scaler._last_scale_done > float("-inf")
    finally:
        r2.shutdown(drain=False)


def test_busy_worker_answers_pongs_out_of_band_and_keeps_its_lease():
    # Satellite: a long solve must NEVER trip the lease — pings are
    # answered inline from the worker's read loop while the solve stalls
    # a pool thread (fleet.worker.slow, the deterministic slow-solve
    # hook). Lease 0.4s, solve 1.2s: three leases elapse while busy.
    cfg = FleetConfig(
        workers=2, test_echo=True, transport="tcp", worker_threads=1,
        heartbeat_interval_s=0.1, lease_s=0.4, ready_timeout_s=120.0,
        request_timeout_s=30.0,
    )
    with FleetRouter(cfg) as r:
        victim = r.handle({"op": "solve", "digest": "busy-probe"})["worker"]
        assert r.arm_worker_fault(
            victim, site="fleet.worker.slow", kind="slow", value=1.2
        )
        resp = r.handle({"op": "solve", "digest": "busy-probe",
                         "slo_class": "x"})
        # Answered by the SAME worker after the stall — never re-queued,
        # never declared dead mid-solve.
        assert resp["ok"] and resp["worker"] == victim
        assert "requeued" not in resp
        counters = BUS.counters()
        assert counters.get("fleet.lease.expired", 0) == 0
        assert counters.get("fleet.heartbeat.miss", 0) == 0
        assert counters.get("fleet.worker.dead", 0) == 0


# ----------------------------------------------------------------------
# Transport chaos layer (round 18)
# ----------------------------------------------------------------------
def test_oneway_partition_expires_lease_then_heals_with_warm_rejoin():
    import threading

    procs, addrs = zip(*[
        _spawn_listening_worker(worker_id=i) for i in range(2)
    ])
    try:
        cfg = _listen_fleet_config(addrs, chaos=True, lease_s=0.5)
        with FleetRouter(cfg) as r:
            for i in range(6):
                assert r.handle({"op": "solve", "digest": f"p{i}"})["ok"]
            pre = r.handle({"op": "stats"})["counters"]["echo.handled"]
            victim = 0
            results = []
            t = threading.Thread(target=lambda: results.append(r.handle(
                {"op": "solve", "digest": "pp", "sleep_s": 0.8,
                 "slo_class": "x"}
            )))
            t.start()
            time.sleep(0.2)
            r.partition_worker(victim, mode="oneway")
            t.join(timeout=30)
            # The in-flight query is answered exactly once — either its
            # response slipped out before the drop (one-way: worker->router
            # still flows) or the lease expired and it re-queued. Never
            # lost, never duplicated to the client.
            assert results and results[0]["ok"]
            # With nothing in flight the victim goes silent: the lease
            # expires (the one-way partition's signature — the socket
            # never EOFs) and the pool keeps serving on the survivor.
            deadline = time.monotonic() + 10
            while (time.monotonic() < deadline
                   and BUS.counters().get("fleet.lease.expired", 0) < 1):
                time.sleep(0.05)
            assert BUS.counters().get("fleet.lease.expired", 0) >= 1
            assert r.handle({"op": "solve", "digest": "during"})["ok"]
            r.heal_partition(victim)
            deadline = time.monotonic() + 20
            while (time.monotonic() < deadline
                   and not r._workers[victim].alive):
                time.sleep(0.05)
            assert r._workers[victim].alive, "no rejoin after heal"
            post = r.handle({"op": "stats"})
            # Warm rejoin: same process, pre-partition handled persists.
            assert post["counters"]["echo.handled"] >= pre
            # The healthy side never tripped: survivor neither died nor
            # restarted (its restarts counter stays 0).
            assert post["workers"]["1"]["restarts"] == 0
            counters = BUS.counters()
            assert counters.get("fleet.chaos.partition") == 1
            assert counters.get("fleet.chaos.heal") == 1
            assert counters.get("fleet.chaos.dropped", 0) >= 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_corrupt_frame_injection_drops_channel_and_requeues():
    # fleet.chaos.corrupt mangles the next outbound frame's bytes (length
    # prefix included): the worker's framing raises FrameError, the
    # channel drops, the accepted request re-queues, and the redial is a
    # warm rejoin — corruption is detected, never mis-parsed.
    from distributed_ghs_implementation_tpu.utils.resilience import FAULTS

    procs, addrs = zip(*[
        _spawn_listening_worker(worker_id=i) for i in range(2)
    ])
    try:
        # Heartbeat slowed way down: the armed corrupt shot must land on
        # the SOLVE frame, not race a ping to an arbitrary worker.
        cfg = _listen_fleet_config(addrs, chaos=True,
                                   heartbeat_interval_s=5.0)
        with FleetRouter(cfg) as r:
            assert r.handle({"op": "solve", "digest": "c0"})["ok"]
            pre = r.handle({"op": "stats"})["counters"]["echo.handled"]
            FAULTS.arm("fleet.chaos.corrupt", times=1)
            resp = r.handle({"op": "solve", "digest": "c1",
                             "slo_class": "x"})
            assert resp["ok"] and resp.get("requeued", 0) >= 1
            assert BUS.counters().get("fleet.chaos.corrupted") == 1
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and not all(
                w.alive for w in r._workers
            ):
                time.sleep(0.05)
            assert all(w.alive for w in r._workers)
            post = r.handle({"op": "stats"})["counters"]["echo.handled"]
            assert post >= pre  # warm rejoin, not a cold restart
    finally:
        FAULTS.reset()
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_chaos_latency_injection_is_seeded_and_bounded():
    from distributed_ghs_implementation_tpu.fleet.transport import ChaosState

    a = ChaosState(seed=7, name="0")
    b = ChaosState(seed=7, name="0")
    c = ChaosState(seed=8, name="0")
    for s in (a, b, c):
        s.latency_s, s.jitter_s = 0.01, 0.02
    seq_a = [a.delay() for _ in range(16)]
    seq_b = [b.delay() for _ in range(16)]
    seq_c = [c.delay() for _ in range(16)]
    assert seq_a == seq_b          # deterministic under the seed
    assert seq_a != seq_c          # the seed actually moves the schedule
    assert all(0.01 <= d <= 0.03 for d in seq_a)
    # Corruption is deterministic too (same seed, same mangled bytes).
    data = b"37\n" + b"x" * 37 + b"\n"
    assert ChaosState(seed=7, name="0").corrupt(data) == \
        ChaosState(seed=7, name="0").corrupt(data)
    assert ChaosState(seed=7, name="0").corrupt(data) != data


# ----------------------------------------------------------------------
# Satellite: framing + hello fuzz — typed rejection, never a hang or an
# oversize allocation or an uncaught exception
# ----------------------------------------------------------------------
def test_framing_fuzz_random_bytes_always_typed_outcome():
    import numpy as np

    rng = np.random.default_rng(1234)
    for trial in range(300):
        n = int(rng.integers(0, 200))
        blob = bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
        stream = io.BytesIO(blob)
        try:
            frame = read_frame(stream, max_bytes=1 << 16)
            assert frame is None or isinstance(frame, dict)
        except FrameError:
            pass  # the ONLY acceptable exception type
        # Bounded consumption: nothing read past the blob (no hang states
        # are representable on BytesIO, but a seek past EOF would show a
        # runaway header/payload hunt).
        assert stream.tell() <= len(blob)


def test_framing_fuzz_truncations_of_valid_frames():
    payload = {"id": 7, "req": {"op": "solve", "edges": [[0, 1, 2]] * 40}}
    buf = io.BytesIO()
    write_frame(buf, payload)
    wire = buf.getvalue()
    for cut in range(len(wire) - 1):
        stream = io.BytesIO(wire[:cut])
        try:
            frame = read_frame(stream)
            # A truncation can only "succeed" as clean EOF (cut == 0).
            assert frame is None and cut == 0
        except FrameError:
            pass
    # And the untouched frame still round-trips.
    assert read_frame(io.BytesIO(wire)) == payload


def test_framing_fuzz_never_allocates_from_corrupt_declarations():
    # Headers declaring absurd lengths must be refused before the read:
    # the reader may never size a buffer from garbage-controlled bytes.
    for declared in (10**9, 10**12, 10**17):
        stream = io.BytesIO(b"%d\n" % declared + b"x" * 64)
        with pytest.raises(FrameError, match="outside"):
            read_frame(stream, max_bytes=1 << 20)
        assert stream.tell() < 64  # the payload was never consumed


def test_hello_fuzz_random_dicts_always_hello_error_or_valid():
    import numpy as np

    rng = np.random.default_rng(99)
    keys = ["ready", "proto", "worker", "pid", "caps", "token", "lease_s"]
    values = [True, False, None, 0, 1, PROTO_VERSION, -3, "x", [], {},
              {"lane": True}, 2**63, "😈", b"bytes".decode("utf-8",
                                                           "ignore")]
    for trial in range(300):
        frame = {
            keys[int(rng.integers(0, len(keys)))]:
                values[int(rng.integers(0, len(values)))]
            for _ in range(int(rng.integers(0, 6)))
        }
        try:
            hello = check_hello(dict(frame))
            # Anything accepted really is a hello: right version, an
            # identity, caps normalized to a dict.
            assert hello["proto"] == PROTO_VERSION
            assert hello.get("worker") is not None
            assert isinstance(hello["caps"], dict)
        except HelloError:
            pass  # the ONLY acceptable exception type
