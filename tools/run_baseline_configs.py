"""Reproduce every BASELINE.json config in one run; emits one JSON line each.

Configs (BASELINE.json `configs`):
  1. the reference's 6-node README sample (thread-backend analog: device)
  2. gnm_random_graph(1024, 8192)
  3. RMAT scale-20 single-chip (the bench.py headline)
  4. RMAT scale-24 (16.7M nodes) — `--big` only; the 8-chip version of this
     config is validated functionally on a virtual mesh (dryrun_multichip)
  5. USA-road-scale high-diameter grid (23.9M nodes) — `--big` only

Default run (configs 1-3) takes ~1 minute warm on the chip; `--big` adds
the two multi-minute configs. Every solve is weight-verified against the
NetworkX/SciPy oracle before its line is printed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))



def run_config(name, graph, *, oracle="scipy", expect_weight=None):
    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.utils.verify import verify_result

    t0 = time.perf_counter()
    result = minimum_spanning_forest(graph)
    wall = time.perf_counter() - t0
    if expect_weight is not None:
        ok = result.total_weight == expect_weight
        expected = expect_weight
    else:
        v = verify_result(result, oracle=oracle)
        ok, expected = v.ok, v.expected_weight
    line = {
        "config": name,
        "nodes": graph.num_nodes,
        "edges": graph.num_edges,
        "levels": result.num_levels,
        "wall_s": round(wall, 3),
        "weight": result.total_weight,
        "expected": expected,
        "verified": bool(ok),
    }
    print(json.dumps(line), flush=True)
    if not ok:
        raise SystemExit(f"VERIFICATION FAILED for {name}: {line}")
    return line


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--big", action="store_true",
                   help="also run RMAT-24 and the USA-road-scale grid")
    p.add_argument("--rmat24-weight", type=int, default=None,
                   help="known MST weight for RMAT-24 seed 24 (skips the "
                        "~15-minute SciPy oracle); 518885017 for this repo's "
                        "generator")
    args = p.parse_args(argv)

    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
        readme_sample_graph,
        rmat_graph,
        road_grid_graph,
    )

    run_config("1: readme 6-node sample", readme_sample_graph(),
               oracle="networkx")
    run_config("2: gnm(1024, 8192)", gnm_random_graph(1024, 8192, seed=2),
               oracle="networkx")
    run_config("3: RMAT-20 single chip", rmat_graph(20, 16, seed=24))
    if args.big:
        run_config(
            "4: RMAT-24 single chip (8-chip layout validated on virtual mesh)",
            rmat_graph(24, 16, seed=24),
            expect_weight=args.rmat24_weight,
        )
        run_config("5: USA-road-scale grid (23.9M nodes, diameter ~10k)",
                   road_grid_graph(4864, 4912, seed=7))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
