"""Micro-batching solve scheduler: single-flight coalescing + admission bound.

Request handling for the serve path, in order:

1. **Cache probe** — ``ResultStore.get`` by content key; a hit never touches
   the solver (zero ``solver.*`` spans — the warm-path guarantee tests
   assert on bus events).
2. **Single-flight** — concurrent requests for the same key join the one
   in-flight solve instead of duplicating it (``serve.scheduler.coalesced``
   counts the joins). This is what keeps a thundering herd of identical
   queries at exactly one kernel dispatch.
3. **Admission bound** — distinct misses solve under a semaphore
   (``max_concurrent``); excess requests queue. ``serve.queue.depth`` is
   sampled on every transition so traces show pressure over time.
4. **Supervised solve** — every miss runs through the round-6 resilience
   supervisor (watchdog, bounded retry, the sharded->device->stepped->host
   degradation ladder), so one flaky device never fails a request that a
   degraded rung can still answer exactly.

``solve_batch`` is the micro-batching entry: it dedups a whole request list
by key first, solves each unique key once, and fans the results back out —
duplicates inside a batch cost a dict lookup, not a solve.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

from distributed_ghs_implementation_tpu.api import MSTResult, minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.serve.store import ResultStore, solve_cache_key


class _Flight:
    """One in-flight solve; joiners block on ``event`` and read the outcome."""

    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result: Optional[MSTResult] = None
        self.error: Optional[BaseException] = None


class SolveScheduler:
    """Cache-fronted, single-flight, capacity-bounded solve dispatch."""

    def __init__(
        self,
        store: Optional[ResultStore] = None,
        *,
        backend: str = "device",
        max_concurrent: int = 2,
        supervisor_config=None,
    ):
        if max_concurrent < 1:
            raise ValueError(f"max_concurrent must be >= 1, got {max_concurrent}")
        self.store = store if store is not None else ResultStore()
        self.backend = backend
        self._supervisor_config = supervisor_config
        self._sem = threading.BoundedSemaphore(max_concurrent)
        self._flights: dict = {}
        self._lock = threading.Lock()

    def solve(
        self, graph: Graph, *, backend: Optional[str] = None
    ) -> Tuple[MSTResult, str]:
        """Answer one solve request; returns ``(result, source)`` where
        ``source`` is ``"cache"`` / ``"coalesced"`` / ``"solved"``."""
        backend = backend or self.backend
        key = solve_cache_key(graph, backend=backend)
        cached = self.store.get(key, graph=graph)
        if cached is not None:
            return cached, "cache"

        with self._lock:
            flight = self._flights.get(key)
            leader = flight is None
            if leader:
                flight = self._flights[key] = _Flight()
                BUS.sample("serve.queue.depth", len(self._flights))
        if not leader:
            BUS.count("serve.scheduler.coalesced")
            flight.event.wait()
            if flight.error is not None:
                raise flight.error
            return flight.result, "coalesced"

        try:
            # Double-check after winning leadership: a previous leader may
            # have published between our cache probe and the flight insert —
            # without this, that race re-solves an already-cached graph.
            cached = self.store.get(key, graph=graph, record_miss=False)
            if cached is not None:
                flight.result = cached
                return cached, "cache"
            with self._sem:
                with BUS.span(
                    "serve.solve", cat="serve", backend=backend,
                    nodes=graph.num_nodes, edges=graph.num_edges,
                ):
                    flight.result = minimum_spanning_forest(
                        graph, backend=backend, supervised=True,
                        supervisor=self._make_supervisor(),
                    )
            self.store.put(key, flight.result)
        except BaseException as e:
            flight.error = e
            raise
        finally:
            with self._lock:
                del self._flights[key]
                BUS.sample("serve.queue.depth", len(self._flights))
            flight.event.set()
        return flight.result, "solved"

    def solve_batch(
        self, graphs: Sequence[Graph], *, backend: Optional[str] = None
    ) -> List[Tuple[MSTResult, str]]:
        """Solve a batch, deduplicating by content key first (micro-batching:
        duplicates inside the batch resolve against the leader's result)."""
        backend = backend or self.backend
        unique: dict = {}
        keys = []
        for g in graphs:
            key = solve_cache_key(g, backend=backend)
            keys.append(key)
            if key in unique:
                BUS.count("serve.scheduler.coalesced")
            else:
                unique[key] = g
        solved = {
            key: self.solve(g, backend=backend) for key, g in unique.items()
        }
        out: List[Tuple[MSTResult, str]] = []
        first = set()
        for key in keys:
            if key in first:
                out.append((solved[key][0], "coalesced"))
            else:
                first.add(key)
                out.append(solved[key])
        return out

    # ------------------------------------------------------------------
    def _make_supervisor(self):
        from distributed_ghs_implementation_tpu.utils.resilience import Supervisor

        return Supervisor(self._supervisor_config)
