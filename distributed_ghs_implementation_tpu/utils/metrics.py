"""Per-level metrics as a compatibility view over the event bus.

Historically this module kept its own private timing; it now routes every
observation through ``obs.events`` (the unified bus behind ``trace``/
``stats`` and the bench gate) and keeps :class:`SolveMetrics` /
:class:`LevelMetrics` only as a thin read-back view so existing callers and
tests are unaffected. Each instrumented level lands on the bus as a
``metrics.level`` span-event carrying the fragment census
(``fragments_before/after``, ``edges_alive``); the dataclasses below are
reconstructed from exactly those events after the solve.

When the global bus is disabled (``GHS_OBS=0``) a private single-use bus
collects the same events, so the compatibility API keeps working without
re-enabling process-wide telemetry.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import time
from typing import List

import numpy as np

from distributed_ghs_implementation_tpu.obs.events import BUS, EventBus


@dataclasses.dataclass
class LevelMetrics:
    level: int
    fragments_before: int
    fragments_after: int
    edges_alive_after: int
    wall_time_s: float


@dataclasses.dataclass
class SolveMetrics:
    num_nodes: int
    num_edges: int
    levels: List[LevelMetrics]
    total_wall_time_s: float

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)


def _metrics_bus() -> EventBus:
    """The global bus when it's on; otherwise a private single-solve bus
    (the compat API must work even with process telemetry disabled)."""
    return BUS if BUS.enabled else EventBus(capacity=8192)


def _levels_from_bus(bus: EventBus, mark: int) -> List[LevelMetrics]:
    """Reconstruct the compatibility records from ``metrics.level`` events."""
    records = []
    for rec in bus.events_since(mark):
        if rec[0] != "X" or rec[1] != "metrics.level":
            continue
        args = rec[6] or {}
        records.append(
            LevelMetrics(
                level=args["level"],
                fragments_before=args["fragments_before"],
                fragments_after=args["fragments_after"],
                edges_alive_after=args["edges_alive"],
                wall_time_s=rec[4] / 1e9,
            )
        )
    return records


def _level_emitter(bus: EventBus, num_nodes: int):
    """Build the shared per-level hook body: census the fragment array and
    emit one ``metrics.level`` event. Returns ``emit(level, fragment,
    edges_alive, dt)``."""
    frags_before = [num_nodes]

    def emit(level: int, fragment, edges_alive: int, dt: float) -> None:
        frags_after = int(np.unique(np.asarray(fragment)[:num_nodes]).size)
        bus.complete(
            "metrics.level",
            dt,
            cat="metrics",
            level=int(level),
            fragments_before=frags_before[0],
            fragments_after=frags_after,
            edges_alive=int(edges_alive),
        )
        frags_before[0] = frags_after

    return emit


def solve_graph_instrumented(
    graph, *, compact: bool = True, strategy: str = "stepped"
) -> tuple:
    """Like ``models.boruvka.solve_graph`` but returns ``(result_tuple,
    SolveMetrics)``.

    ``strategy="stepped"`` records one entry per level (host-stepped
    execution); ``strategy="rank"`` uses the fast rank solver and records one
    entry per chunk boundary (its hook granularity) — the practical choice at
    bench scale where the stepped kernel is not a usable host.
    """
    n = graph.num_nodes
    if n == 0 or graph.num_edges == 0:
        empty = (np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0)
        return empty, SolveMetrics(n, graph.num_edges, [], 0.0)

    if strategy == "rank":
        return _solve_rank_instrumented(graph)
    if strategy != "stepped":
        raise ValueError(f"unknown strategy {strategy!r}; expected stepped|rank")

    from distributed_ghs_implementation_tpu.models.boruvka import (
        prepare_device_arrays,
        solve_arrays_stepped,
    )

    bus = _metrics_bus()
    mark = bus.mark()
    args = prepare_device_arrays(graph)
    emit = _level_emitter(bus, n)

    def on_level(level, fragment, mst_ranks, has, count, dt):
        # The stepped kernel counts surviving *directed slots*; each
        # undirected edge occupies two, so halve for the edge count.
        emit(level, fragment, count // 2, dt)

    t_start = time.perf_counter()
    with bus.span("metrics.solve", cat="metrics", strategy="stepped", nodes=n):
        mst_ranks, fragment, levels = solve_arrays_stepped(
            *args, compact=compact, stepped_levels=None, on_level=on_level
        )
    total = time.perf_counter() - t_start

    ranks_chosen = np.nonzero(np.asarray(mst_ranks))[0]
    edge_ids = np.sort(graph.edge_id_of_rank(ranks_chosen))
    result = (edge_ids, np.asarray(fragment)[:n], levels)
    return result, SolveMetrics(
        n, graph.num_edges, _levels_from_bus(bus, mark), total
    )


def _solve_rank_instrumented(graph) -> tuple:
    """Rank-solver instrumentation via its ``on_chunk`` hook (chunk-boundary
    granularity; the alive count there is undirected already)."""
    from distributed_ghs_implementation_tpu.models.rank_solver import (
        make_production_solver,
    )

    n = graph.num_nodes
    bus = _metrics_bus()
    mark = bus.mark()
    emit = _level_emitter(bus, n)
    last = [time.perf_counter()]

    def on_chunk(level, fragment, mst_ranks, count):
        now = time.perf_counter()
        emit(level, fragment, count, now - last[0])
        last[0] = now

    # make_production_solver is the single routing source shared with
    # solve_graph_rank: the instrumented path measures the kernels
    # production runs (passing on_chunk selects the chunked forms — the
    # speculative single-dispatch variant has no boundaries to instrument).
    solve = make_production_solver(graph)
    with bus.span("metrics.solve", cat="metrics", strategy="rank", nodes=n):
        last[0] = time.perf_counter()
        t_start = last[0]
        mst_ranks, fragment, levels = solve(on_chunk=on_chunk)
        total = time.perf_counter() - t_start

    ranks_chosen = np.nonzero(np.asarray(mst_ranks))[0]
    edge_ids = np.sort(graph.edge_id_of_rank(ranks_chosen))
    result = (edge_ids, np.asarray(fragment)[:n], levels)
    return result, SolveMetrics(
        n, graph.num_edges, _levels_from_bus(bus, mark), total
    )


@contextlib.contextmanager
def profiler_trace(log_dir: str):
    """Wrap a solve in a JAX device profile (TensorBoard/Perfetto trace).

    >>> with profiler_trace("/tmp/ghs-trace"):
    ...     minimum_spanning_forest(graph)

    This is the *device-side* (XLA op) view; the host-side structured trace
    is ``python -m distributed_ghs_implementation_tpu trace``.
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
