"""The GHS node state machine — the protocol, implemented once.

Faithful to the classic Gallager–Humblet–Spira algorithm, which is what the
reference's two hand-rolled variants approximate
(``/root/reference/ghs_implementation.py:118-413``,
``ghs_implementation_mpi.py:117-757``). Differences that make this variant
exact and deterministic where the reference is neither:

* **Edges are identified by rank, not raw weight.** GHS requires distinct
  edge weights; the reference uses raw ``randint(1, 10)`` weights where ties
  break that assumption (one source of its wrong MSTs). Here every edge
  carries its global rank in the sort by ``(weight, edge id)`` — the same
  total order the batched kernel uses — so fragments are named by core-edge
  rank exactly as in the original paper.
* **Deferral is a transport concern.** Handlers return ``False`` when the
  protocol says "process this later" (CONNECT onto a BASIC edge at equal
  level, TEST from a higher level, REPORT racing the local find); the
  transport requeues. No requeue caps, no forced merges
  (contrast ``ghs_implementation.py:88-100,176-185``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

from distributed_ghs_implementation_tpu.protocol.messages import (
    EdgeState,
    Message,
    MessageType,
    NodeState,
)

INF = None  # REPORT weight for "no outgoing edge found"


def _lt(a: Optional[int], b: Optional[int]) -> bool:
    """Rank comparison where None is +infinity."""
    if a is None:
        return False
    if b is None:
        return True
    return a < b


@dataclasses.dataclass
class _Edge:
    neighbor: int
    rank: int  # global (weight, edge id) rank — the protocol's "weight"
    state: EdgeState = EdgeState.BASIC


class GHSNode:
    """One vertex's protocol endpoint.

    ``send(dest, message)`` is injected by the transport; ``on_halt`` fires
    when this node's fragment root detects completion (best weight = inf).
    """

    def __init__(
        self,
        node_id: int,
        neighbors: Dict[int, int],  # neighbor id -> edge rank
        send: Callable[[int, Message], None],
        on_halt: Callable[[int], None] = lambda _nid: None,
    ):
        self.id = node_id
        self.edges: Dict[int, _Edge] = {
            nbr: _Edge(neighbor=nbr, rank=rank) for nbr, rank in neighbors.items()
        }
        self._send = send
        self._on_halt = on_halt

        self.state = NodeState.SLEEPING
        self.level = 0
        self.fragment = 0  # rank of the fragment's core edge
        self.find_count = 0
        self.best_edge: Optional[int] = None  # neighbor id toward best MOE
        self.best_weight: Optional[int] = INF
        self.test_edge: Optional[int] = None
        self.in_branch: Optional[int] = None  # neighbor id toward fragment root
        self.halted = False
        self.messages_processed = 0

    # ------------------------------------------------------------------
    def branch_neighbors(self) -> List[int]:
        return [e.neighbor for e in self.edges.values() if e.state == EdgeState.BRANCH]

    def wakeup(self) -> None:
        """Spontaneous start (``ghs_implementation.py:118-137``): the minimum
        adjacent edge becomes BRANCH and CONNECT(0) crosses it."""
        if self.state != NodeState.SLEEPING:
            return
        if not self.edges:
            # Isolated vertex: a one-node fragment is already complete.
            self.state = NodeState.FOUND
            self.halted = True
            self._on_halt(self.id)
            return
        m = min(self.edges.values(), key=lambda e: e.rank)
        m.state = EdgeState.BRANCH
        self.level = 0
        self.state = NodeState.FOUND
        self.find_count = 0
        self._send(m.neighbor, Message(MessageType.CONNECT, self.id, level=0))

    # ------------------------------------------------------------------
    def handle(self, msg: Message) -> bool:
        """Process one message; returns False if it must be requeued."""
        if self.state == NodeState.SLEEPING:
            self.wakeup()
        handler = {
            MessageType.CONNECT: self._on_connect,
            MessageType.INITIATE: self._on_initiate,
            MessageType.TEST: self._on_test,
            MessageType.ACCEPT: self._on_accept,
            MessageType.REJECT: self._on_reject,
            MessageType.REPORT: self._on_report,
            MessageType.CHANGE_ROOT: self._on_change_root,
        }[msg.type]
        ok = handler(msg)
        if ok:
            self.messages_processed += 1
        return ok

    # ------------------------------------------------------------------
    def _on_connect(self, msg: Message) -> bool:
        """Absorb (lower level) or merge (equal level over the core edge) —
        ``ghs_implementation.py:155-199``, minus its forced-merge fallbacks."""
        edge = self.edges[msg.sender]
        if msg.level < self.level:
            # Absorb the lower-level fragment at our level.
            edge.state = EdgeState.BRANCH
            self._send(
                msg.sender,
                Message(
                    MessageType.INITIATE,
                    self.id,
                    level=self.level,
                    fragment=self.fragment,
                    weight=0 if self.state == NodeState.FIND else 1,
                ),
            )
            if self.state == NodeState.FIND:
                self.find_count += 1
            return True
        if edge.state == EdgeState.BASIC:
            return False  # equal level but our CONNECT hasn't crossed yet: defer
        # Merge: both fragments chose this edge; its rank names the new fragment.
        self._send(
            msg.sender,
            Message(
                MessageType.INITIATE,
                self.id,
                level=self.level + 1,
                fragment=edge.rank,
                weight=0,  # new root search starts in FIND
            ),
        )
        return True

    def _on_initiate(self, msg: Message) -> bool:
        """Adopt (level, fragment, state), broadcast down branches, start the
        MOE search — ``ghs_implementation.py:201-233``."""
        self.level = msg.level
        self.fragment = msg.fragment
        self.state = NodeState.FIND if msg.weight == 0 else NodeState.FOUND
        self.in_branch = msg.sender
        self.best_edge = None
        self.best_weight = INF
        self.test_edge = None
        for e in self.edges.values():
            if e.neighbor != msg.sender and e.state == EdgeState.BRANCH:
                self._send(
                    e.neighbor,
                    Message(
                        MessageType.INITIATE,
                        self.id,
                        level=msg.level,
                        fragment=msg.fragment,
                        weight=msg.weight,
                    ),
                )
                if self.state == NodeState.FIND:
                    self.find_count += 1
        if self.state == NodeState.FIND:
            self._test()
        return True

    def _test(self) -> None:
        """Probe the minimum BASIC edge — ``ghs_implementation.py:235-254``."""
        basic = [e for e in self.edges.values() if e.state == EdgeState.BASIC]
        if basic:
            e = min(basic, key=lambda e: e.rank)
            self.test_edge = e.neighbor
            self._send(
                e.neighbor,
                Message(
                    MessageType.TEST, self.id, level=self.level, fragment=self.fragment
                ),
            )
        else:
            self.test_edge = None
            self._report()

    def _on_test(self, msg: Message) -> bool:
        """ACCEPT (different fragment) / REJECT (same) —
        ``ghs_implementation.py:256-281``."""
        if msg.level > self.level:
            return False  # their level is ahead of ours: defer
        if msg.fragment != self.fragment:
            self._send(msg.sender, Message(MessageType.ACCEPT, self.id))
            return True
        edge = self.edges[msg.sender]
        if edge.state == EdgeState.BASIC:
            edge.state = EdgeState.REJECTED
        if self.test_edge != msg.sender:
            self._send(msg.sender, Message(MessageType.REJECT, self.id))
        else:
            self._test()  # we were testing the same edge: move on, no REJECT needed
        return True

    def _on_accept(self, msg: Message) -> bool:
        edge = self.edges[msg.sender]
        self.test_edge = None
        if _lt(edge.rank, self.best_weight):
            self.best_edge = msg.sender
            self.best_weight = edge.rank
        self._report()
        return True

    def _on_reject(self, msg: Message) -> bool:
        edge = self.edges[msg.sender]
        if edge.state == EdgeState.BASIC:
            edge.state = EdgeState.REJECTED
        self._test()
        return True

    def _report(self) -> None:
        """Convergecast the best weight up ``in_branch`` once all children
        reported and the local probe finished — ``ghs_implementation.py:303-320``."""
        if self.find_count == 0 and self.test_edge is None:
            self.state = NodeState.FOUND
            self._send(
                self.in_branch,
                Message(MessageType.REPORT, self.id, weight=self.best_weight),
            )

    def _on_report(self, msg: Message) -> bool:
        if msg.sender != self.in_branch:
            # A child's report.
            self.find_count -= 1
            if _lt(msg.weight, self.best_weight):
                self.best_weight = msg.weight
                self.best_edge = msg.sender
            self._report()
            return True
        # Report from the other core half (we are one of the two roots).
        if self.state == NodeState.FIND:
            return False  # our own find is still running: defer
        if _lt(self.best_weight, msg.weight):
            # Our half holds the better edge: the root moves to our side.
            self._change_root()
            return True
        if msg.weight is None and self.best_weight is None:
            # Both halves found nothing: the fragment spans its component.
            self.halted = True
            self._on_halt(self.id)
            return True
        return True

    def _change_root(self) -> None:
        """Walk toward the MOE; at its endpoint, CONNECT across —
        ``ghs_implementation.py:355-387``."""
        edge = self.edges[self.best_edge]
        if edge.state == EdgeState.BRANCH:
            self._send(self.best_edge, Message(MessageType.CHANGE_ROOT, self.id))
        else:
            self._send(
                self.best_edge, Message(MessageType.CONNECT, self.id, level=self.level)
            )
            edge.state = EdgeState.BRANCH

    def _on_change_root(self, msg: Message) -> bool:
        self._change_root()
        return True
