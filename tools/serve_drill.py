#!/usr/bin/env python
"""Serve drill: drive the MST query service and check every answer.

Three modes:

* ``--smoke`` — the CI gate: start ``ghs serve`` as a subprocess, drive the
  JSONL protocol over its pipes (solve -> update -> repeat the original
  solve), and assert the repeat is answered from cache — both via the
  response's ``cached`` flag and via the ``serve.store.hit`` counter in the
  ``stats`` op (the obs-bus proof that no solver ran).
* ``--warmup-smoke`` — the warm-path gate: start ``ghs serve`` with
  ``--batch-lanes`` and ``--warmup-buckets`` covering the drill's graph
  shape, drive two distinct solves on that bucket, and assert via the
  ``compile.*`` counters in ``stats`` that the warmup compiled
  (``compile.warmup >= 1``) and the query phase compiled NOTHING
  (no ``compile.miss``) — the "zero request-time XLA compiles" acceptance
  from docs/SERVING.md. The report carries the compile counters (CI
  uploads it as the compile-cache stats artifact).
* default — an in-process replay: a seeded random graph, then ``--updates``
  random insert/delete/reweight requests through :class:`MSTService`, every
  response's MST weight checked against the SciPy oracle on an
  independently-maintained mirror of the edge set. ``--chaos`` arms
  ``GHS_FAULT_*``-style faults first (supervisor retries on the miss path,
  torn cache writes when ``--disk-cache`` is set), so the drill doubles as
  the serving layer's game-day. Armed ``GHS_FAULT_*`` environment variables
  are honored in both modes.

Exit code 0 iff every check passed. ``--output`` writes a JSON report.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _seed_graph(nodes: int, edges: int, seed: int):
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )

    return gnm_random_graph(nodes, edges, seed=seed)


def _slo_section(stats, wall_s: float, stats_response: dict = None) -> dict:
    """The drill's per-class summary — the SAME ``ghs-slo-summary-v1``
    schema the load drill reports, so all drills compare field-for-field.
    Subprocess modes measure client-side (the server's bus lives across
    the pipes); ``events_dropped`` rides in from the ``stats`` op."""
    from distributed_ghs_implementation_tpu.obs import slo

    dropped = int((stats_response or {}).get("events_dropped", 0))
    return slo.assemble(stats, wall_s=wall_s, events_dropped=dropped)


def _graph_edges(g):
    return [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]


def run_smoke(args) -> dict:
    """solve -> update -> repeat-solve over the real CLI pipes."""
    from distributed_ghs_implementation_tpu.obs import slo

    g = _seed_graph(args.nodes, args.edges, args.seed)
    edges = _graph_edges(g)
    requests = [
        {"op": "solve", "num_nodes": g.num_nodes, "edges": edges,
         "slo_class": "miss"},
        {"op": "update", "digest": None, "updates": [],
         "slo_class": "update"},  # digest patched below
        {"op": "solve", "num_nodes": g.num_nodes, "edges": edges,
         "slo_class": "hit"},
        {"op": "stats"},
        {"op": "shutdown"},
    ]
    proc = subprocess.Popen(
        [sys.executable, "-m", "distributed_ghs_implementation_tpu", "serve"],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")},
    )

    acct = slo.ClassStats()

    def roundtrip(request):
        t0 = time.perf_counter()
        proc.stdin.write(json.dumps(request) + "\n")
        proc.stdin.flush()
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError("serve process closed its pipe early")
        response = json.loads(line)
        if request.get("slo_class"):
            acct.observe(
                request["slo_class"],
                time.perf_counter() - t0,
                ok=bool(response.get("ok")),
            )
        return response

    checks = []
    stats = {}
    t_run = time.perf_counter()
    try:
        first = roundtrip(requests[0])
        checks.append(("first solve ok", bool(first.get("ok"))))
        checks.append(("first solve is a miss", first.get("source") == "solved"))
        requests[1]["digest"] = first.get("digest")
        requests[1]["updates"] = [
            {"kind": "insert", "u": 0, "v": g.num_nodes - 1, "w": 1}
        ]
        update = roundtrip(requests[1])
        checks.append(("update ok", bool(update.get("ok"))))
        checks.append(("update incremental", update.get("mode") == "incremental"))
        repeat = roundtrip(requests[2])
        checks.append(("repeat solve ok", bool(repeat.get("ok"))))
        checks.append(("repeat is a cache hit", repeat.get("cached") is True))
        checks.append(
            ("repeat weight stable",
             repeat.get("total_weight") == first.get("total_weight"))
        )
        stats = roundtrip(requests[3])
        hits = stats.get("counters", {}).get("serve.store.hit", 0)
        checks.append(("obs counter saw the hit", hits >= 1))
        roundtrip(requests[4])
    finally:
        proc.stdin.close()
        proc.wait(timeout=60)
    slo_summary = _slo_section(acct, time.perf_counter() - t_run, stats)
    return {
        "mode": "smoke",
        "checks": [{"name": n, "ok": bool(ok)} for n, ok in checks],
        "slo": slo_summary,
        "events_dropped": slo_summary["events_dropped"],
        "dropped_warning": slo_summary["dropped_warning"],
        "ok": all(ok for _, ok in checks),
    }


def run_warmup_smoke(args) -> dict:
    """Warmup serve, query the pre-declared bucket, assert zero
    request-time compiles (``compile.miss``) via the stats op."""
    from distributed_ghs_implementation_tpu.obs import slo

    g1 = _seed_graph(args.nodes, args.edges, args.seed)
    g2 = _seed_graph(args.nodes, args.edges, args.seed + 1)
    cache_dir = args.compile_cache_dir or "serve_compile_cache"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "distributed_ghs_implementation_tpu",
            "serve",
            "--batch-lanes", "4",
            "--warmup-buckets", f"{args.nodes}x{args.edges}",
            "--compile-cache-dir", cache_dir,
        ],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")},
    )

    def roundtrip(request):
        proc.stdin.write(json.dumps(request) + "\n")
        proc.stdin.flush()
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError("serve process closed its pipe early")
        return json.loads(line)

    checks = []
    counters = {}
    warmup_report = None
    stats = {}
    acct = slo.ClassStats()
    t_run = time.perf_counter()
    try:
        # A throwaway stats roundtrip absorbs subprocess boot + the warmup
        # phase, so the timed solves below measure warm QUERY latency, not
        # interpreter startup.
        boot = roundtrip({"op": "stats"})
        checks.append(("serve booted", bool(boot.get("ok"))))
        t_run = time.perf_counter()
        for i, g in enumerate((g1, g2), 1):
            t0 = time.perf_counter()
            response = roundtrip(
                {"op": "solve", "num_nodes": g.num_nodes,
                 "edges": _graph_edges(g), "slo_class": "miss"}
            )
            acct.observe(
                "miss", time.perf_counter() - t0, ok=bool(response.get("ok"))
            )
            checks.append((f"solve {i} ok", bool(response.get("ok"))))
            checks.append((f"solve {i} is a miss", response.get("source") == "solved"))
            checks.append(
                (f"solve {i} rode the lane engine",
                 str(response.get("backend", "")).startswith("batch/"))
            )
        stats = roundtrip({"op": "stats"})
        counters = stats.get("counters", {})
        warmup_report = stats.get("warmup")
        wall_s = time.perf_counter() - t_run
        checks.append(("warmup ran", bool(warmup_report)))
        checks.append(
            ("warmup compiled the bucket",
             counters.get("compile.warmup", 0) >= 1)
        )
        checks.append(
            ("zero request-time compiles (compile.miss)",
             counters.get("compile.miss", 0) == 0)
        )
        checks.append(
            ("queries hit the precompiled solver",
             counters.get("batch.compile.hit", 0) >= 2)
        )
        roundtrip({"op": "shutdown"})
    finally:
        proc.stdin.close()
        proc.wait(timeout=120)
    slo_summary = _slo_section(acct, wall_s, stats)
    return {
        "mode": "warmup-smoke",
        "checks": [{"name": n, "ok": bool(ok)} for n, ok in checks],
        "slo": slo_summary,
        "events_dropped": slo_summary["events_dropped"],
        "dropped_warning": slo_summary["dropped_warning"],
        "warmup": warmup_report,
        "compile_counters": {
            k: v for k, v in counters.items() if k.startswith("compile.")
        },
        "compile_cache_dir": cache_dir,
        "ok": all(ok for _, ok in checks),
    }


def run_replay(args) -> dict:
    """In-process update-stream replay, every step checked vs the oracle."""
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
    from distributed_ghs_implementation_tpu.obs import slo
    from distributed_ghs_implementation_tpu.obs.events import BUS
    from distributed_ghs_implementation_tpu.serve.service import MSTService
    from distributed_ghs_implementation_tpu.utils.resilience import FAULTS
    from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight

    BUS.enable()
    BUS.clear()
    if args.chaos:
        # The miss path must survive transient device failures (supervisor
        # retry), and the persistent cache a torn write mid-save.
        FAULTS.arm("resilience.attempt.device", times=1)
        if args.disk_cache:
            FAULTS.arm("serve.store.save", times=1, kind="torn")

    service = MSTService(disk_dir=args.disk_cache)
    g = _seed_graph(args.nodes, args.edges, args.seed)
    mirror = {
        (int(a), int(b)): int(c) for a, b, c in zip(g.u, g.v, g.w)
    }
    t_run = time.perf_counter()
    response = service.handle(
        {"op": "solve", "num_nodes": g.num_nodes, "edges": _graph_edges(g),
         "slo_class": "miss"}
    )
    if not response.get("ok"):
        return {"mode": "replay", "ok": False, "error": response.get("error")}
    digest = response["digest"]

    rng = np.random.default_rng(args.seed + 1)
    steps = []
    ok = True
    for step in range(args.updates):
        kind = str(rng.choice(["insert", "delete", "reweight"]))
        if kind == "delete" and mirror:
            a, b = list(mirror)[int(rng.integers(0, len(mirror)))]
            upd = {"kind": "delete", "u": a, "v": b}
            del mirror[(a, b)]
        elif kind == "reweight" and mirror:
            a, b = list(mirror)[int(rng.integers(0, len(mirror)))]
            w = int(rng.integers(1, 100))
            upd = {"kind": "reweight", "u": a, "v": b, "w": w}
            mirror[(a, b)] = w
        else:
            a, b = sorted(int(x) for x in rng.integers(0, g.num_nodes, 2))
            if a == b:
                continue
            w = int(rng.integers(1, 100))
            upd = {"kind": "insert", "u": a, "v": b, "w": w}
            mirror[(a, b)] = w  # insert of an existing edge is a reweight
        response = service.handle(
            {"op": "update", "digest": digest, "updates": [upd],
             "slo_class": "update"}
        )
        if not response.get("ok"):
            steps.append({"step": step, "update": upd,
                          "error": response.get("error")})
            ok = False
            break
        digest = response["digest"]
        pairs = np.asarray(list(mirror), dtype=np.int64).reshape(-1, 2)
        oracle_graph = Graph.from_arrays(
            g.num_nodes, pairs[:, 0], pairs[:, 1],
            np.asarray(list(mirror.values()), dtype=np.int64),
        )
        expect = scipy_mst_weight(oracle_graph) if mirror else 0.0
        good = abs(float(response["total_weight"]) - float(expect)) < 1e-6
        ok = ok and good
        steps.append(
            {"step": step, "update": upd, "mode": response.get("mode"),
             "weight": response["total_weight"], "oracle": expect, "ok": good}
        )
    stats = service.handle({"op": "stats"})
    # In-process: per-class accounting joins the REAL bus events (the same
    # obs.slo join the load drill gates on), not client stopwatches.
    slo_summary = slo.summarize_bus(BUS, wall_s=time.perf_counter() - t_run)
    return {
        "mode": "replay",
        "chaos": bool(args.chaos),
        "ok": ok,
        "steps_run": len(steps),
        "slo": slo_summary,
        "events_dropped": slo_summary["events_dropped"],
        "dropped_warning": slo_summary["dropped_warning"],
        "counters": stats.get("counters", {}),
        "failures": [s for s in steps if not s.get("ok", True)],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="serve_drill", description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="CI smoke: subprocess + JSONL pipes + cache-hit assert")
    p.add_argument("--warmup-smoke", action="store_true",
                   help="CI warm-path smoke: serve --warmup-buckets, assert "
                   "zero request-time compiles via compile.* counters")
    p.add_argument("--compile-cache-dir",
                   help="persistent compile-cache dir for --warmup-smoke")
    p.add_argument("--chaos", action="store_true",
                   help="arm fault sites before the replay")
    p.add_argument("--nodes", type=int, default=300)
    p.add_argument("--edges", type=int, default=1200)
    p.add_argument("--seed", type=int, default=17)
    p.add_argument("--updates", type=int, default=25)
    p.add_argument("--disk-cache", help="persistent cache dir for the replay")
    p.add_argument("--output", help="write the JSON report here")
    args = p.parse_args(argv)

    if args.smoke:
        report = run_smoke(args)
    elif args.warmup_smoke:
        report = run_warmup_smoke(args)
    else:
        report = run_replay(args)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    print(json.dumps({
        k: v for k, v in report.items() if k != "counters"
    } if report["mode"] == "replay" else report, indent=2))
    print(f"serve drill: {'PASS' if report['ok'] else 'FAIL'}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
