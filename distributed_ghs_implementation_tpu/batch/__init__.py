"""Bucketed, vmapped multi-graph batch execution (docs/BATCHING.md).

Independent small-graph solve requests waste the chip one dispatch at a
time: the padded kernel shapes are identical across same-bucket graphs, so
K of them can ride one compiled program. ``lanes`` stacks same-bucket
graphs into lanes and solves them in a single dispatch, ``policy`` decides
what batches with what (and what bypasses), and ``engine`` owns the queue
behind the serving scheduler's miss path.
"""

from distributed_ghs_implementation_tpu.batch.engine import BatchEngine
from distributed_ghs_implementation_tpu.batch.lanes import (
    bucket_key,
    bucket_of,
    compiled_bucket_keys,
    lane_compile_stats,
    precompile_bucket,
    solve_lanes,
)
from distributed_ghs_implementation_tpu.batch.policy import BatchPolicy, FormedBatch
from distributed_ghs_implementation_tpu.batch.warmup import (
    WarmupPlan,
    load_bucket_record,
    run_warmup,
    save_bucket_record,
)

__all__ = [
    "BatchEngine",
    "BatchPolicy",
    "FormedBatch",
    "WarmupPlan",
    "bucket_key",
    "bucket_of",
    "compiled_bucket_keys",
    "lane_compile_stats",
    "load_bucket_record",
    "precompile_bucket",
    "run_warmup",
    "save_bucket_record",
    "solve_lanes",
]
