"""Feasibility probe: vectorized dynamic gather from a VMEM-resident table in
a Pallas TPU kernel. If this compiles + runs fast, the ELL scan's dominant
cost (fragment[dstb] random gather, ~480 ms at RMAT-20) drops ~7x.

Promoted to production in round 15: the measured win lives in
``ops/pallas_kernels.py`` (fused MOE + hook/compress kernels behind the
``kernel="pallas"`` selector), and the CPU-runnable parity suite is
``tests/test_pallas_kernels.py`` (interpret mode). This probe stays as
the raw on-hardware microbenchmark for re-validating gather throughput
on a new chip generation.
"""

import _bootstrap  # noqa: F401 — repo-root sys.path setup

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def _sync(out):
    np.asarray(out.ravel()[0])


def timeit(fn, *args, repeats=5):
    out = fn(*args)
    _sync(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args)
        _sync(out)
        best = min(best, time.perf_counter() - t0)
    return best, out


def gather_kernel(table_ref, idx_ref, out_ref):
    idx = idx_ref[...]
    out_ref[...] = jnp.take(table_ref[...], idx, axis=0)


@functools.partial(jax.jit, static_argnames=("block",))
def pallas_gather(table, idx, *, block=512):
    n_idx = idx.shape[0]
    lanes = 128
    rows = n_idx // lanes
    idx2 = idx.reshape(rows, lanes)
    grid = (rows // block,)
    return pl.pallas_call(
        gather_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(table.shape, lambda i: (0,)),  # whole table each step
            pl.BlockSpec((block, lanes), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block, lanes), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, lanes), table.dtype),
    )(table, idx2).reshape(-1)


def main():
    rng = np.random.default_rng(0)
    n = 1 << 20
    table = jnp.asarray(rng.integers(0, 1 << 30, n, dtype=np.int32))
    for e in (24, 26):
        m = 1 << e
        idx = jnp.asarray(rng.integers(0, n, m, dtype=np.int32))
        xla = jax.jit(lambda t, i: t[i])
        t_x, out_x = timeit(xla, table, idx)
        try:
            t_p, out_p = timeit(pallas_gather, table, idx)
            ok = bool(jnp.array_equal(out_x, out_p))
        except Exception as ex:  # noqa: BLE001
            print(f"pallas gather failed at m=2^{e}: {type(ex).__name__}: {ex}")
            continue
        print(
            f"m=2^{e}: xla {t_x * 1e3:8.2f} ms   pallas {t_p * 1e3:8.2f} ms   "
            f"match={ok}"
        )


if __name__ == "__main__":
    main()
