"""AOT bucket warmup: compile the serving hot path before traffic arrives.

The first query landing on a new ``(n_pad, m_pad, lanes, mode)`` bucket
used to pay full XLA tracing+compilation *inside* the request — a
multi-hundred-ms p99 spike that repeats on every process restart. This
module removes it by precompiling a declared set of buckets ahead of
serving:

* **Ladder** — :func:`default_ladder` enumerates power-of-two shape
  buckets up to a ceiling (every graph shape maps into one of them), and
  :func:`parse_bucket_list` turns an operator-declared ``"128x512,..."``
  spec (raw node/edge counts; they bucket the same way requests do) into
  the exact buckets those workloads hit.
* **Replay** — :func:`save_bucket_record` persists the solver keys a live
  process actually compiled (``lanes.compiled_bucket_keys``), and
  :func:`load_bucket_record` turns the file back into a plan, so a restart
  precompiles precisely yesterday's traffic.
* **Run** — :func:`run_warmup` AOT-compiles each bucket's lane solver
  (``lanes.precompile_bucket`` → ``jax.jit(...).lower().compile()``) and,
  unless disabled, also warms the single-graph fused kernel for the same
  shape bucket (the bypass/fallback/non-batched path) by executing it once
  on an inert all-pad stack — that run exits after one level, so the cost
  is the compile, not a solve.

Warmup compiles land on the obs bus as ``compile.warmup`` (request-time
compiles are ``compile.miss``), so "zero request-time compiles" is an
assertable property: after a warmup covering the traffic's buckets, the
query phase must add no ``compile.miss`` counts (``tools/serve_drill.py
--warmup-smoke`` gates exactly this in CI). Pair with the persistent XLA
compile cache (``utils/compile_cache.py``) and even the warmup compiles
are disk reads after the first boot.

The elastic fleet (``fleet/autoscaler.py``) leans on exactly this: a
*joining* worker runs the same ladder (the router's ``_worker_argv``
forwards the fleet's warmup flags to every spawn, scale-ups included) and
only advertises ``warmed`` in its hello afterwards — so scale-up is warm
handoff by construction, and :func:`summarize_report` is the compact
what-did-the-joiner-warm record the hello carries for the stats op.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ghs_implementation_tpu.batch.lanes import (
    SolverKey,
    bucket_of,
    compiled_bucket_keys,
    precompile_bucket,
)
from distributed_ghs_implementation_tpu.models.boruvka import (
    ELL_AUTO_EDGE_THRESHOLD,
    _next_pow2,
    _solve_from_iota,
)
from distributed_ghs_implementation_tpu.obs.events import BUS

RECORD_SCHEMA = "ghs-warmup-buckets-v1"

#: Lane modes a warmup record may carry. ``lanes == 0`` entries use a
#: placeholder mode (they never reach the lane compiler), but it still
#: must be one of these — an unknown string is a corrupt record.
VALID_RECORD_MODES = ("fused", "vmap")

_INT32_MAX = np.iinfo(np.int32).max


class WarmupRecordError(ValueError):
    """A malformed warmup record entry, named precisely — a bad record
    must fail boot with *which entry* is bad, not a bare unpack error."""

#: Single-graph warm ceiling: buckets past these never run the fused iota
#: kernel (``solve_graph`` routes them to the rank solver), so warming
#: them would pay a huge boot-time compile no request ever hits. Matches
#: ``BatchPolicy``'s default admission ceiling.
MAX_SINGLE_WARM_EDGES = ELL_AUTO_EDGE_THRESHOLD
MAX_SINGLE_WARM_NODES = 1 << 16


def warmable_single(n_pad: int, m_pad: int) -> bool:
    """Would a graph in this bucket actually hit the fused single-graph
    kernel (vs routing to the rank solver at scale)?"""
    return n_pad <= MAX_SINGLE_WARM_NODES and m_pad <= MAX_SINGLE_WARM_EDGES


@dataclasses.dataclass(frozen=True)
class WarmupPlan:
    """What to precompile before serving.

    ``buckets`` are padded shape buckets ``(n_pad, m_pad)``; each is
    compiled at ``lanes`` lanes in ``mode`` (``lanes == 0`` skips the lane
    solver — a service running without the batch engine only needs the
    single-graph kernel). ``keys`` are exact replayed solver keys (each
    carries its own lane count/mode). ``warm_single`` additionally warms
    the single-graph fused kernel per distinct shape bucket.

    ``mesh_buckets`` are RAW ``(nodes, edges)`` workload sizes for the
    OVERSIZE path: each warms the sharded lane's mesh programs
    (``parallel/lane.py`` — head/finish at that bucket's padded shapes)
    when :func:`run_warmup` is handed a lane, so the first oversize query
    pays zero request-time compiles too. Raw sizes, not padded shapes:
    the lane derives its own mesh-aligned padding.

    ``stream_buckets`` are RAW ``(nodes, edges)`` sizes of subscribed
    graphs: each warms the windowed-maintenance Borůvka round
    (``stream/window.py``) at the padded edge buckets a stream of that
    size dispatches, so the first committed window — and a failover
    replay — pays no jit tracing either.

    ``kernel`` picks the level-kernel variant to warm (``"pallas"`` /
    ``"xla"``; ``None`` = the process's resolved choice,
    ``pallas_kernels.kernel_choice``). Warmup and request-time solving
    resolve through the same function, so a warmed bucket stays a
    request-time ``compile.hit`` whichever variant the process serves
    with — the zero-request-time-compiles property covers kernel
    variants (docs/KERNELS.md).

    ``tuning`` is the path of a ``ghs-tuning-v1`` TuningRecord
    (``tune/record.py``) to install *before* any bucket resolves: warmup
    then precompiles each bucket's *measured* winner (per-bucket
    ``kernel_choice`` with the bucket key), so the warmed variant is the
    one a tuned request-time resolution will hit. A missing or stale
    record installs nothing and warmup proceeds on the probe heuristic —
    degrade, never error.
    """

    buckets: Tuple[Tuple[int, int], ...] = ()
    lanes: int = 0
    mode: str = "fused"
    keys: Tuple[SolverKey, ...] = ()
    warm_single: bool = True
    mesh_buckets: Tuple[Tuple[int, int], ...] = ()
    stream_buckets: Tuple[Tuple[int, int], ...] = ()
    kernel: Optional[str] = None
    tuning: Optional[str] = None

    def is_empty(self) -> bool:
        return (
            not self.buckets
            and not self.keys
            and not self.mesh_buckets
            and not self.stream_buckets
        )


def parse_bucket_list(spec: str) -> List[Tuple[int, int]]:
    """Parse ``"128x512,300x1200"`` into padded shape buckets.

    Entries are RAW workload sizes (nodes x edges), bucketed exactly like
    requests are, so operators declare traffic shapes, not XLA shapes.
    Duplicate buckets collapse. ``"auto"`` yields :func:`default_ladder`.
    """
    spec = spec.strip()
    if not spec:
        return []
    if spec.lower() in ("auto", "ladder"):
        return default_ladder()
    out: List[Tuple[int, int]] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.lower().split("x")
        if len(parts) != 2:
            raise ValueError(
                f"bad bucket spec {entry!r}; expected NODESxEDGES, e.g. 128x512"
            )
        n, m = int(parts[0]), int(parts[1])
        if n < 1 or m < 1:
            raise ValueError(f"bad bucket spec {entry!r}: sizes must be positive")
        b = bucket_of(n, m)
        if b not in out:
            out.append(b)
    return out


def default_ladder(
    *,
    min_nodes: int = 64,
    max_nodes: int = 4096,
    edge_factors: Sequence[int] = (2, 4),
) -> List[Tuple[int, int]]:
    """A generic small-graph bucket ladder: power-of-two node counts from
    ``min_nodes`` to ``max_nodes``, each at the given edge/node factors.

    This is the no-information default for ``--warmup-buckets auto``; a
    deployment that knows its traffic should declare exact sizes or replay
    a recorded bucket file instead.
    """
    ladder: List[Tuple[int, int]] = []
    n = _next_pow2(max(2, min_nodes))
    while n <= max_nodes:
        for f in edge_factors:
            b = bucket_of(n, f * n)
            if b not in ladder:
                ladder.append(b)
        n *= 2
    return ladder


# ----------------------------------------------------------------------
# Record / replay
# ----------------------------------------------------------------------
def save_bucket_record(
    path: str,
    shape_buckets: Sequence[Tuple[int, int]] = (),
    *,
    include_compiled: bool = True,
) -> int:
    """Persist warmable buckets for replay; returns the entry count.

    ``shape_buckets`` are traffic-observed ``(n_pad, m_pad)`` buckets
    (recorded with ``lanes=0``; the replaying service normalizes them to
    its own lane geometry). With ``include_compiled`` the record also
    snapshots the lane-solver keys this process compiled —
    ``include_compiled=False`` is what ``serve --warmup-record`` uses, so
    a record driven purely by ``seen_buckets`` converges to actual
    traffic instead of accumulating every bucket a prior warmup ladder
    ever compiled.
    """
    keys = compiled_bucket_keys() if include_compiled else []
    covered = {(n, m) for n, m, _, _ in keys}
    for n_pad, m_pad in shape_buckets:
        if (n_pad, m_pad) not in covered:
            keys.append((n_pad, m_pad, 0, "fused"))
            covered.add((n_pad, m_pad))
    with open(path, "w") as f:
        json.dump(
            {
                "schema": RECORD_SCHEMA,
                "buckets": [list(k) for k in keys],
            },
            f,
            indent=2,
        )
        f.write("\n")
    return len(keys)


def _validate_record_entry(path: str, i: int, entry) -> SolverKey:
    """One record entry -> a SolverKey, or :class:`WarmupRecordError`
    naming the offending entry (index + repr)."""

    def bad(why: str) -> WarmupRecordError:
        return WarmupRecordError(
            f"{path}: bucket entry #{i} {entry!r}: {why}"
        )

    if not isinstance(entry, (list, tuple)) or len(entry) != 4:
        raise bad("expected [n_pad, m_pad, lanes, mode]")
    n, m, lanes, mode = entry
    for name, v in (("n_pad", n), ("m_pad", m), ("lanes", lanes)):
        if isinstance(v, bool) or not isinstance(v, int):
            raise bad(f"{name} must be an int, got {type(v).__name__}")
    if n < 1 or m < 1:
        raise bad(f"shape ({n}, {m}) must be positive")
    if lanes < 0:
        raise bad(f"lanes {lanes} must be >= 0")
    if not isinstance(mode, str) or mode not in VALID_RECORD_MODES:
        raise bad(
            f"unknown mode {mode!r} (expected one of {VALID_RECORD_MODES})"
        )
    return (n, m, lanes, mode)


def load_bucket_record(path: str) -> WarmupPlan:
    """Load a recorded bucket file into a replayable :class:`WarmupPlan`.

    Every entry is validated before any is used — a malformed entry
    (wrong arity, non-int sizes, negative sizes, unknown mode) raises a
    typed :class:`WarmupRecordError` naming it, so an operator fixing a
    hand-edited record sees *which* line is bad instead of a bare
    unpacking traceback mid-boot.
    """
    with open(path) as f:
        record = json.load(f)
    if record.get("schema") != RECORD_SCHEMA:
        raise ValueError(
            f"{path}: bad warmup record schema {record.get('schema')!r} "
            f"(expected {RECORD_SCHEMA})"
        )
    buckets = record.get("buckets", [])
    if not isinstance(buckets, list):
        raise WarmupRecordError(
            f"{path}: 'buckets' must be a list, got "
            f"{type(buckets).__name__}"
        )
    keys = tuple(
        _validate_record_entry(path, i, entry)
        for i, entry in enumerate(buckets)
    )
    return WarmupPlan(keys=keys)


def parse_mesh_bucket_list(spec: str) -> List[Tuple[int, int]]:
    """Parse ``"70000x140000,..."`` into raw mesh-bucket workload sizes.

    Same NODESxEDGES surface as :func:`parse_bucket_list`, but entries stay
    RAW — the sharded lane pads to its own mesh-aligned shapes, so padding
    here would double-bucket. Duplicates collapse.
    """
    spec = spec.strip()
    if not spec:
        return []
    out: List[Tuple[int, int]] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.lower().split("x")
        if len(parts) != 2:
            raise ValueError(
                f"bad mesh bucket spec {entry!r}; expected NODESxEDGES"
            )
        n, m = int(parts[0]), int(parts[1])
        if n < 1 or m < 1:
            raise ValueError(
                f"bad mesh bucket spec {entry!r}: sizes must be positive"
            )
        if (n, m) not in out:
            out.append((n, m))
    return out


def plan_from_flags(
    buckets: Optional[str] = None,
    replay: Optional[str] = None,
    lanes: int = 0,
    mesh_buckets: Optional[str] = None,
    stream_buckets: Optional[str] = None,
    kernel: Optional[str] = None,
    tuning: Optional[str] = None,
) -> Optional[WarmupPlan]:
    """A :class:`WarmupPlan` from the serve-CLI flag surface, or ``None``.

    The ONE mapping from ``--warmup-buckets`` / ``--warmup-replay`` /
    ``--warmup-mesh-buckets`` / ``--warmup-stream-buckets`` strings to a
    plan — shared by ``ghs serve`` and every fleet worker
    (``fleet/worker.py``), so a bucket ladder declared on the router warms
    identically in all N worker processes.
    """
    plans: List[WarmupPlan] = []
    if buckets:
        plans.append(
            WarmupPlan(buckets=tuple(parse_bucket_list(buckets)), lanes=lanes)
        )
    if replay:
        plans.append(load_bucket_record(replay))
    if mesh_buckets:
        plans.append(
            WarmupPlan(
                mesh_buckets=tuple(parse_mesh_bucket_list(mesh_buckets))
            )
        )
    if stream_buckets:
        # Same RAW NODESxEDGES surface as mesh buckets: the window kernels
        # derive their own power-of-two padding.
        plans.append(
            WarmupPlan(
                stream_buckets=tuple(parse_mesh_bucket_list(stream_buckets))
            )
        )
    if not plans:
        return None
    merged = merge_plans(*plans)
    if kernel and kernel != "auto":
        merged = dataclasses.replace(merged, kernel=kernel)
    if tuning:
        merged = dataclasses.replace(merged, tuning=tuning)
    return merged


def merge_plans(*plans: WarmupPlan) -> WarmupPlan:
    """Union of several plans (CLI: ``--warmup-buckets`` + ``--warmup-replay``)."""
    buckets: List[Tuple[int, int]] = []
    mesh_buckets: List[Tuple[int, int]] = []
    stream_buckets: List[Tuple[int, int]] = []
    keys: List[SolverKey] = []
    lanes, mode, warm_single, kernel = 0, "fused", True, None
    tuning = None
    for p in plans:
        for b in p.buckets:
            if b not in buckets:
                buckets.append(b)
        for b in p.mesh_buckets:
            if b not in mesh_buckets:
                mesh_buckets.append(b)
        for b in p.stream_buckets:
            if b not in stream_buckets:
                stream_buckets.append(b)
        for k in p.keys:
            if k not in keys:
                keys.append(k)
        lanes = max(lanes, p.lanes)
        if p.lanes:
            mode = p.mode
        warm_single = warm_single and p.warm_single
        kernel = kernel or p.kernel
        tuning = tuning or p.tuning
    return WarmupPlan(
        buckets=tuple(buckets), lanes=lanes, mode=mode,
        keys=tuple(keys), warm_single=warm_single,
        mesh_buckets=tuple(mesh_buckets),
        stream_buckets=tuple(stream_buckets),
        kernel=kernel,
        tuning=tuning,
    )


def summarize_report(report: Optional[dict]) -> Optional[dict]:
    """Compact warmup facts for the fleet hello (``caps["warmup"]``).

    A joining worker's hello should say *what* it warmed (so the stats op
    can show an operator why the join took ``fleet.join.warm_s``) without
    shipping the whole report over the wire on every connection.
    ``None`` in, ``None`` out — a service booted without a plan has
    nothing to summarize.
    """
    if not report:
        return None
    return {
        "buckets": report.get("buckets", 0),
        "single_warmed": report.get("single_warmed", 0),
        "mesh_warmed": report.get("mesh_warmed", 0),
        "stream_warmed": report.get("stream_warmed", 0),
        "stream_sharded_warmed": report.get("stream_sharded_warmed", 0),
        "kernel": report.get("kernel"),
        "tuned_entries": report.get("tuned_entries", 0),
        "wall_s": round(float(report.get("wall_s", 0.0)), 3),
    }


# ----------------------------------------------------------------------
# Execution
# ----------------------------------------------------------------------
def _warm_single_graph_kernel(n_pad: int, m_pad: int, kernel: str) -> None:
    """Warm the single-graph fused kernel for one shape bucket by solving
    an inert all-pad stack: self-edge slots, sentinel ranks. The level
    loop exits after one no-progress level, so the call costs one compile
    (or nothing when the jit cache / persistent cache already has it) —
    this is the path bypass, fallback, and non-batched serving hit.
    ``kernel`` is the static level-kernel variant requests will resolve.
    """
    e_pad = 2 * m_pad
    src = jnp.zeros(e_pad, jnp.int32)
    rank = jnp.full(e_pad, _INT32_MAX, jnp.int32)
    ra = jnp.zeros(m_pad, jnp.int32)
    out = _solve_from_iota(src, src, rank, ra, ra, num_nodes=n_pad, kernel=kernel)
    # Scalar fetch = real sync (block_until_ready does not block on the
    # axon remote backend): an execution fault must surface HERE, where
    # run_warmup's kernel fallback can catch it, not at a later request.
    _ = int(jax.device_get(out[2]))


def run_warmup(plan: WarmupPlan, *, lane=None) -> dict:
    """Execute a warmup plan; returns a report dict.

    Idempotent: already-compiled buckets are skipped (and reported as
    ``cached``). The whole phase is one ``compile.warmup_phase`` span so a
    trace shows exactly what boot paid for. ``lane`` (a
    ``parallel.lane.ShardedLane``) receives the plan's ``mesh_buckets`` —
    each warms the oversize path's mesh programs; without a lane they are
    counted ``mesh_skipped`` (declared but unreachable, like oversize
    shape buckets on the fused kernel).
    """
    from distributed_ghs_implementation_tpu.ops.pallas_kernels import (
        disable_pallas,
        kernel_choice,
    )

    tuned_entries = 0
    if plan.tuning:
        # Install the tuning record FIRST: every bucket below resolves
        # through the measured-auto tier, so the warmed variant is the
        # tuned one requests will hit. Miss/stale installs nothing
        # (tune.record.miss/stale on the bus) and the probe heuristic
        # carries the warmup — boot never dies on a bad record.
        from distributed_ghs_implementation_tpu.tune.record import (
            load_and_install,
        )

        tuned_entries = load_and_install(plan.tuning)
    kernel = kernel_choice(plan.kernel)
    report = {
        "buckets": 0,
        "compiled": 0,
        "cached": 0,
        "skipped": 0,
        "single_warmed": 0,
        "mesh_warmed": 0,
        "mesh_skipped": 0,
        "stream_warmed": 0,
        "stream_sharded_warmed": 0,
        "kernel": kernel,
        "tuned_entries": tuned_entries,
        "wall_s": 0.0,
    }
    if plan.is_empty():
        return report

    # The raw request threads into per-bucket resolution below, so an
    # installed TuningRecord's measured winner applies bucket by bucket;
    # after a fallback the sticky disable_pallas makes every later
    # resolution land on "xla" regardless.
    request = plan.kernel

    def _warm_fallback(site: str, ex: Exception) -> None:
        # The same degrade-never-error contract the request path has
        # (docs/KERNELS.md): a Pallas compile failure during warmup trips
        # the sticky process fallback and the rest of the phase — and the
        # retried site — warms the XLA variant serving will now resolve.
        # Boot must not die on the kernel the process won't even run.
        nonlocal kernel, request
        disable_pallas(f"warmup[{site}]: {type(ex).__name__}: {ex}")
        kernel = "xla"
        request = "xla"
        report["kernel"] = "xla"

    t0 = time.perf_counter()
    keys: List[SolverKey] = list(plan.keys)
    if plan.lanes > 0:
        for n_pad, m_pad in plan.buckets:
            k = (n_pad, m_pad, plan.lanes, plan.mode)
            if k not in keys:
                keys.append(k)
    with BUS.span(
        "compile.warmup_phase", cat="compile",
        lane_buckets=len(keys), shape_buckets=len(plan.buckets),
        mesh_buckets=len(plan.mesh_buckets), kernel=kernel,
    ) as span:
        for n_pad, m_pad, lanes, mode in keys:
            if lanes < 1:
                continue  # shape-only record entry: single-graph warm below
            if not warmable_single(n_pad, m_pad):
                # Past the admission ceiling the request path bypasses the
                # lane engine entirely — a typo'd spec must not stall boot
                # on a giant compile no request can reach.
                report["skipped"] += 1
                continue
            report["buckets"] += 1
            bkern = kernel_choice(
                request, bucket=(n_pad, m_pad, lanes, mode)
            )
            try:
                fresh = precompile_bucket(
                    n_pad, m_pad, lanes, mode, kernel=bkern
                )
            except ValueError:
                raise  # geometry rejections are never kernel faults
            except Exception as ex:  # noqa: BLE001 — kernel fallback
                if bkern != "pallas":
                    raise
                _warm_fallback(f"bucket {n_pad}x{m_pad}", ex)
                fresh = precompile_bucket(
                    n_pad, m_pad, lanes, mode, kernel="xla"
                )
            if fresh:
                report["compiled"] += 1
            else:
                report["cached"] += 1
        if plan.warm_single:
            shapes = {(n, m) for n, m in plan.buckets}
            shapes.update((n, m) for n, m, _, _ in keys)
            for n_pad, m_pad in sorted(shapes):
                if not warmable_single(n_pad, m_pad):
                    continue  # routed to the rank solver, never this kernel
                # The single-graph path resolves at its shape-only bucket
                # (lanes=0), the key single buckets tune under.
                skern = kernel_choice(
                    request, bucket=(n_pad, m_pad, 0, "fused")
                )
                try:
                    _warm_single_graph_kernel(n_pad, m_pad, skern)
                except ValueError:
                    raise  # geometry rejections are never kernel faults
                except Exception as ex:  # noqa: BLE001 — kernel fallback
                    if skern != "pallas":
                        raise
                    _warm_fallback(f"single {n_pad}x{m_pad}", ex)
                    _warm_single_graph_kernel(n_pad, m_pad, "xla")
                report["single_warmed"] += 1
        for nodes, edges in plan.mesh_buckets:
            if lane is None:
                report["mesh_skipped"] += 1
                continue
            lane.precompile(nodes, edges)
            report["mesh_warmed"] += 1
        if plan.stream_buckets:
            from distributed_ghs_implementation_tpu.stream.window import (
                warm_window_kernels,
            )

            for nodes, edges in plan.stream_buckets:
                report["stream_warmed"] += warm_window_kernels(nodes, edges)
        if lane is not None and plan.mesh_buckets:
            # The fused path: a lane worker's declared oversize workloads
            # are also the sizes its SHARDED STREAMS publish windows at
            # (stream/session.py) — warm the windowed-maintenance round
            # for them too, so the first committed window on a mesh-
            # resident stream pays no jit tracing even when the operator
            # only declared --warmup-mesh-buckets. Cheap when
            # --warmup-stream-buckets already covered the size (jit-cache
            # hit), and warm_window_kernels caps the cycle-pass bucket at
            # the tree size, so n >> m oversize shapes stay small.
            from distributed_ghs_implementation_tpu.stream.window import (
                warm_window_kernels,
            )

            for nodes, edges in plan.mesh_buckets:
                report["stream_sharded_warmed"] += warm_window_kernels(
                    nodes, edges
                )
        span.set(compiled=report["compiled"], cached=report["cached"])
    report["wall_s"] = time.perf_counter() - t0
    return report
