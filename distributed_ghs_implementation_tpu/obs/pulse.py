"""Fleet pulse: a router-side scrape loop merging N workers' telemetry.

Fleet-wide health used to be reconstructable only by hand: run a drill,
collect each worker's JSONL dump, eyeball per-process counters. This
module is the continuously-scraped answer — a :class:`FleetPulse` thread
polls the router's ``stats`` fan-out on an interval and folds the
per-worker payloads into one ``ghs-fleet-pulse-v1`` report:

* **Counters merge exactly**: the report's fleet totals are the literal
  sum of the per-worker counters it also carries, so a reader can always
  audit the aggregation (and the CI gate does).
* **Histograms merge statistically**: workers ship RAW reservoirs
  (``EventBus.histograms_export``), merged by the deterministic seeded
  reservoir merge (``obs.events.merge_hists``) — a fleet p99 computed
  from the pooled samples, not an average of per-worker p99s.
* **Dropped telemetry is surfaced, not swallowed**: every worker's
  ``events_dropped`` rides the report per worker, and
  ``obs.export.render_stats`` flags any nonzero-drop worker by name.
* **Slow-request exemplars**: any ``fleet.request`` span breaching its
  SLO-class budget gets its FULL span tree (every retained span sharing
  its trace id) appended to ``exemplars.jsonl`` — the "why was this one
  slow" artifact, captured at breach time instead of reconstructed later.

The report also renders as a Prometheus text-exposition file
(:func:`write_prometheus`) so a scraper can lift the fleet's counters and
latency summaries without speaking anything ghs-specific.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from distributed_ghs_implementation_tpu.obs.events import (
    BUS,
    PH_COMPLETE,
    merge_hists,
)

PULSE_SCHEMA = "ghs-fleet-pulse-v1"
EXEMPLAR_SCHEMA = "ghs-slow-exemplar-v1"

#: Default per-class latency budgets (seconds) for exemplar capture when
#: neither the constructor nor ``GHS_PULSE_BUDGETS`` provides one.
DEFAULT_BUDGETS = {"default": 1.0}


def parse_budgets(spec: str) -> Dict[str, float]:
    """``"interactive=0.05,bulk=2,default=1"`` -> class->seconds."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        cls, _, value = part.partition("=")
        try:
            out[cls.strip()] = float(value)
        except ValueError:
            raise ValueError(
                f"bad budget {part!r}; expected CLASS=SECONDS"
            ) from None
    return out


def pulse_report(stats: dict) -> dict:
    """Fold one router ``stats`` fan-out into a ``ghs-fleet-pulse-v1``
    report. Pure function of the stats payload — the scrape loop calls it,
    and tests feed it canned fan-outs."""
    workers_in = stats.get("workers") or {}
    workers_out: Dict[str, dict] = {}
    totals: Dict[str, float] = {}
    hist_raws: Dict[str, List[dict]] = {}
    scraped = 0
    for wid in sorted(workers_in, key=str):
        info = workers_in[wid]
        if not isinstance(info, dict):
            continue
        entry: Dict[str, Any] = {
            "alive": bool(info.get("alive")),
            "pending": info.get("pending", 0),
        }
        wstats = info.get("stats")
        if isinstance(wstats, dict):
            scraped += 1
            counters = {
                str(k): float(v)
                for k, v in (wstats.get("counters") or {}).items()
            }
            entry["counters"] = counters
            entry["events_dropped"] = int(wstats.get("events_dropped", 0))
            for name, value in counters.items():
                totals[name] = totals.get(name, 0.0) + value
            raws = wstats.get("histograms_raw")
            if isinstance(raws, dict):
                # Sorted-wid iteration order makes the reservoir merge
                # deterministic across scrapes of the same exports.
                for name, raw in raws.items():
                    hist_raws.setdefault(str(name), []).append(raw)
        workers_out[str(wid)] = entry
    histograms = {
        name: merge_hists(raws).summary()
        for name, raws in sorted(hist_raws.items())
    }
    return {
        "schema": PULSE_SCHEMA,
        "ts_unix": time.time(),
        "workers_scraped": scraped,
        "workers": workers_out,
        # The audit invariant: these totals are the exact sum of the
        # per-worker counters above (CI asserts it).
        "counters": totals,
        "histograms": histograms,
        "router": {
            "counters": stats.get("fleet") or {},
            "pool": stats.get("pool") or {},
            "events_dropped": BUS.dropped,
        },
    }


def _prom_name(name: str) -> str:
    san = "".join(
        c if c.isalnum() or c == "_" else "_" for c in name
    )
    if san and san[0].isdigit():
        san = "_" + san
    return f"ghs_{san}"


def write_prometheus(report: dict, path: str) -> str:
    """Render a pulse report as Prometheus text exposition (one file a
    node_exporter textfile collector or a curl-based scraper can lift)."""
    lines: List[str] = []
    lines.append("# ghs fleet pulse (ghs-fleet-pulse-v1)")
    lines.append("# TYPE ghs_pulse_workers_scraped gauge")
    lines.append(
        f"ghs_pulse_workers_scraped {int(report.get('workers_scraped', 0))}"
    )
    workers = report.get("workers") or {}
    lines.append("# TYPE ghs_worker_events_dropped gauge")
    for wid in sorted(workers, key=str):
        dropped = int(workers[wid].get("events_dropped", 0) or 0)
        lines.append(
            f'ghs_worker_events_dropped{{worker="{wid}"}} {dropped}'
        )
    for name in sorted(report.get("counters") or {}):
        metric = _prom_name(name)
        lines.append(f"# TYPE {metric} counter")
        total = report["counters"][name]
        lines.append(f"{metric} {total}")
        for wid in sorted(workers, key=str):
            value = (workers[wid].get("counters") or {}).get(name)
            if value is not None:
                lines.append(f'{metric}{{worker="{wid}"}} {value}')
    for name, h in sorted((report.get("histograms") or {}).items()):
        if not h.get("count"):
            continue
        metric = _prom_name(name)
        lines.append(f"# TYPE {metric} summary")
        for q, label in (
            ("p50", "0.5"), ("p90", "0.9"), ("p95", "0.95"), ("p99", "0.99")
        ):
            lines.append(
                f'{metric}{{quantile="{label}"}} {h[q]}'
            )
        lines.append(f"{metric}_sum {h.get('sum', 0.0)}")
        lines.append(f"{metric}_count {h['count']}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


class FleetPulse:
    """The scrape loop. ``router`` is anything whose ``handle`` answers
    ``{"op": "stats"}`` the fleet way (``FleetRouter`` — or a canned stub
    in tests). Artifacts land in ``out_dir`` each scrape: ``pulse.json``
    (the report), ``pulse.prom`` (Prometheus exposition), and
    ``exemplars.jsonl`` (appended breach span-trees)."""

    def __init__(
        self,
        router,
        *,
        interval_s: float = 5.0,
        out_dir: Optional[str] = None,
        budgets: Optional[Dict[str, float]] = None,
    ):
        self.router = router
        self.interval_s = float(interval_s)
        self.out_dir = out_dir
        if budgets is None:
            env = os.environ.get("GHS_PULSE_BUDGETS", "")
            budgets = parse_budgets(env) if env else dict(DEFAULT_BUDGETS)
        self.budgets = dict(budgets)
        self.last_report: Optional[dict] = None
        self.scrapes = 0
        # Mark 0: the FIRST scrape scans the whole retained ring (a pulse
        # attached after traffic still captures its breaches); later
        # scrapes are incremental from the previous one.
        self._mark = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "FleetPulse":
        self._thread = threading.Thread(
            target=self._loop, name="fleet-pulse", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, self.interval_s + 1.0))
            self._thread = None

    def __enter__(self) -> "FleetPulse":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — a scrape must never kill
                BUS.count("pulse.scrape_failed")  # the loop (or the fleet)

    # -- one scrape ----------------------------------------------------
    def scrape_once(self) -> dict:
        stats = self.router.handle({"op": "stats"})
        report = pulse_report(stats)
        self.last_report = report
        self.scrapes += 1
        BUS.count("pulse.scrapes")
        if self.out_dir:
            self._write_artifacts(report)
        self._capture_exemplars()
        return report

    def _write_artifacts(self, report: dict) -> None:
        json_path = os.path.join(self.out_dir, "pulse.json")
        tmp = json_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        os.replace(tmp, json_path)  # a reader never sees a torn report
        write_prometheus(report, os.path.join(self.out_dir, "pulse.prom"))

    def _capture_exemplars(self) -> None:
        """Append the full span tree of every SLO-budget-breaching
        ``fleet.request`` completed since the last scrape."""
        events = BUS.events_since(self._mark)
        self._mark = BUS.mark()
        breaches = []
        for ph, name, _cat, _ts, dur_ns, _tid, args in events:
            if ph != PH_COMPLETE or name != "fleet.request" or not args:
                continue
            trace_id = args.get("trace")
            if not trace_id:
                continue  # unsampled: nothing to assemble a tree from
            cls = args.get("cls") or "default"
            budget = self.budgets.get(cls, self.budgets.get("default"))
            if budget is None or dur_ns / 1e9 <= budget:
                continue
            breaches.append((trace_id, cls, dur_ns))
        if not breaches or not self.out_dir:
            if breaches:
                BUS.count("pulse.exemplars", len(breaches))
            return
        retained = BUS.events()
        path = os.path.join(self.out_dir, "exemplars.jsonl")
        with open(path, "a") as f:
            for trace_id, cls, dur_ns in breaches:
                spans = [
                    {
                        "name": name,
                        "cat": cat,
                        "ts_us": ts_ns / 1000.0,
                        "dur_us": dur_ns2 / 1000.0,
                        "args": args,
                    }
                    for ph, name, cat, ts_ns, dur_ns2, _tid, args
                    in retained
                    if ph == PH_COMPLETE and args
                    and args.get("trace") == trace_id
                ]
                f.write(json.dumps({
                    "schema": EXEMPLAR_SCHEMA,
                    "ts_unix": time.time(),
                    "trace": trace_id,
                    "cls": cls,
                    "dur_s": dur_ns / 1e9,
                    "budget_s": self.budgets.get(
                        cls, self.budgets.get("default")
                    ),
                    "spans": spans,
                }, separators=(",", ":")) + "\n")
                BUS.count("pulse.exemplars")
