"""Per-level checkpoint/resume for long solves.

The reference has no checkpointing (SURVEY.md §5 — durable state is input
files and result JSONs only). Here the whole solver state is three arrays —
``fragment[n]``, ``mst_ranks[m]``, ``level`` — so a checkpoint is one npz and
resume is ``boruvka_solve`` from an arbitrary starting partition (explicitly
supported; see its docstring). Worth having for the RMAT-24/USA-road configs
where a preempted multi-minute run would otherwise restart from scratch.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional, Tuple

import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph


def save_checkpoint(path: str, fragment, mst_ranks, level: int) -> str:
    """Atomic npz write of the solver state (tmp file + rename)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    os.close(fd)
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f,
                fragment=np.asarray(fragment),
                mst_ranks=np.asarray(mst_ranks),
                level=np.asarray(level),
            )
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_checkpoint(path: str) -> Tuple[np.ndarray, np.ndarray, int]:
    data = np.load(path)
    return data["fragment"], data["mst_ranks"], int(data["level"])


def solve_graph_checkpointed(
    graph: Graph,
    checkpoint_path: str,
    *,
    every: int = 1,
    resume: bool = True,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host-stepped solve writing a checkpoint every ``every`` levels; resumes
    from ``checkpoint_path`` when present. Same return contract as
    ``models.boruvka.solve_graph``."""
    from distributed_ghs_implementation_tpu.models.boruvka import (
        prepare_device_arrays,
        solve_arrays_stepped,
    )

    n = graph.num_nodes
    if n == 0 or graph.num_edges == 0:
        return np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0

    args = prepare_device_arrays(graph)
    initial_state = None
    if resume and os.path.exists(checkpoint_path):
        initial_state = load_checkpoint(checkpoint_path)

    def on_level(level, fragment, mst_ranks, has, count, dt):
        if level % every == 0 or not has:
            save_checkpoint(checkpoint_path, fragment, mst_ranks, level)

    mst_ranks, fragment, levels = solve_arrays_stepped(
        *args, stepped_levels=None, initial_state=initial_state, on_level=on_level
    )
    save_checkpoint(checkpoint_path, fragment, mst_ranks, levels)

    ranks_chosen = np.nonzero(np.asarray(mst_ranks))[0]
    edge_ids = np.sort(graph.edge_id_of_rank(ranks_chosen))
    return edge_ids, np.asarray(fragment)[:n], levels
