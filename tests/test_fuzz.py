"""Differential fuzz: every solver strategy against the oracle on adversarial
shapes — sizes straddling padding-bucket boundaries, duplicate weights, stars,
near-empty and dense graphs. The reference has nothing comparable (its only
randomized coverage is six fixed seeds); this is the regression net for the
padding/bucketing/compaction edge cases the batched formulation introduces.
"""

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
    solve_graph_rank_sharded,
)
from distributed_ghs_implementation_tpu.parallel.sharded import solve_graph_sharded
from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight


def _random_graph(rng, n, m, wmax):
    u = rng.integers(0, n, size=m)
    v = rng.integers(0, n, size=m)
    w = rng.integers(1, wmax + 1, size=m)
    return Graph.from_arrays(n, u, v, w)


# Sizes straddle pow2/bucket boundaries (16, 17, 20, 31, 33...) on purpose.
CASES = [
    (16, 15, 3),     # tree-ish, heavy ties
    (17, 40, 2),     # n just past a pow2, almost all duplicate weights
    (33, 33, 1),     # ALL weights equal: pure tie-break territory
    (100, 99, 10**9),  # huge weight range
    (257, 2048, 5),  # dense multigraph with dups and self-loops dropped
    (64, 1, 7),      # single edge
    (40, 4000, 4),   # very dense, few distinct weights
]


@pytest.mark.parametrize("n,m,wmax", CASES)
@pytest.mark.parametrize("seed", [0, 1])
def test_all_strategies_agree_with_oracle(n, m, wmax, seed):
    rng = np.random.default_rng(seed * 1000 + n)
    g = _random_graph(rng, n, m, wmax)
    expect = scipy_mst_weight(g) if g.num_edges else 0.0

    results = {}
    for strat in ("rank", "fused", "ell", "stepped"):
        ids, frag, _ = solve_graph(g, strategy=strat)
        assert abs(float(g.w[ids].sum()) - expect) < 1e-6, strat
        results[strat] = ids
    ids_sh, _, _ = solve_graph_sharded(g, strategy="flat")
    assert abs(float(g.w[ids_sh].sum()) - expect) < 1e-6, "sharded-flat"
    ids_rs, _, _ = solve_graph_rank_sharded(g)
    assert abs(float(g.w[ids_rs].sum()) - expect) < 1e-6, "rank-sharded"

    # Filter-Kruskal variants (single-chip and sharded), forced on even
    # below their size thresholds.
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    vmin0, ra, rb = rs.prepare_rank_arrays(g)
    mst_f, _, _ = rs.solve_rank_filtered(vmin0, ra, rb)
    ranks = np.nonzero(np.asarray(mst_f))[0]
    ids_f = np.sort(g.edge_id_of_rank(ranks))
    assert abs(float(g.w[ids_f].sum()) - expect) < 1e-6, "filtered"
    ids_fs, _, _ = solve_graph_rank_sharded(g, filtered=True)
    assert abs(float(g.w[ids_fs].sum()) - expect) < 1e-6, "filtered-sharded"

    # The shared (weight, edge id) tie-break makes every strategy pick the
    # same edge set, not just the same weight.
    base = results["rank"]
    for strat, ids in results.items():
        assert np.array_equal(ids, base), strat
    assert np.array_equal(ids_sh, base)
    assert np.array_equal(ids_rs, base)
    assert np.array_equal(ids_f, base)
    assert np.array_equal(ids_fs, base)


def test_star_graph_all_strategies():
    """Star hub: the degree-skew extreme (one vertex on every edge)."""
    n = 130
    g = Graph.from_edges(n, [(0, i, (i * 7) % 11 + 1) for i in range(1, n)])
    expect = scipy_mst_weight(g)
    for strat in ("rank", "fused", "ell"):
        ids, _, _ = solve_graph(g, strategy=strat)
        assert float(g.w[ids].sum()) == expect, strat
    ids, _, _ = solve_graph_rank_sharded(g)
    assert float(g.w[ids].sum()) == expect


def test_float_weights_all_strategies():
    rng = np.random.default_rng(3)
    u = rng.integers(0, 50, size=300)
    v = rng.integers(0, 50, size=300)
    w = rng.random(300)
    g = Graph.from_arrays(50, u, v, w)
    expect = scipy_mst_weight(g)
    for strat in ("rank", "fused"):
        ids, _, _ = solve_graph(g, strategy=strat)
        assert abs(float(g.w[ids].sum()) - expect) < 1e-9, strat


@pytest.mark.slow
def test_determinism_across_processes(tmp_path):
    """Same graph, two fresh interpreter processes, byte-identical MST edge
    ids — the guarantee the reference fundamentally lacks (its 20-node config
    differs run to run)."""
    import subprocess
    import sys

    code = r"""
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
g = rmat_graph(13, 8, seed=77)
ids, frag, lv = solve_graph(g, strategy="rank")
np.save(sys.argv[1], ids)
"""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = []
    for i in range(2):
        out = str(tmp_path / f"ids{i}.npy")
        subprocess.run(
            [sys.executable, "-c", code.format(repo=repo), out],
            check=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        outs.append(np.load(out))
    assert np.array_equal(outs[0], outs[1])


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_road_network_fuzz(seed):
    """Randomized road-network params (holes, link probs, shape): device and
    sharded solves agree with the oracle and each other."""
    from distributed_ghs_implementation_tpu.graphs.generators import (
        random_road_network,
    )

    rng = np.random.default_rng(seed)
    g = random_road_network(
        int(rng.integers(20, 70)),
        int(rng.integers(20, 70)),
        seed=seed,
        hole_prob=float(rng.uniform(0.0, 0.25)),
        axis_prob=float(rng.uniform(0.3, 0.9)),
        diag_prob=float(rng.uniform(0.0, 0.3)),
    )
    expect = scipy_mst_weight(g) if g.num_edges else 0.0
    ids, _, _ = solve_graph(g, strategy="rank")
    assert abs(float(g.w[ids].sum()) - expect) < 1e-6
    ids_sh, _, _ = solve_graph_rank_sharded(g)
    assert np.array_equal(ids, ids_sh)


@pytest.mark.parametrize("stop_at", [1, 2, 3])
def test_filtered_resume_from_every_boundary(stop_at):
    """Interrupt the filtered solve at each successive chunk boundary and
    resume: byte-identical MST from every save point (the resume contract
    is 'exact from ANY saved partition', so test them all, not just one)."""
    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    g = rmat_graph(11, 16, seed=9)
    ref_ids, _, _ = solve_graph(g, strategy="rank")
    vmin0, ra, rb = rs.prepare_rank_arrays(g)

    class Stop(Exception):
        pass

    state = {}

    def hook(level, fragment, mst, count):
        state["saved"] = (
            np.asarray(fragment).copy(), np.asarray(mst).copy(), level
        )
        state["n"] = state.get("n", 0) + 1
        if state["n"] == stop_at:
            raise Stop()

    try:
        rs.solve_rank_filtered(vmin0, ra, rb, on_chunk=hook)
    except Stop:
        pass
    # The interrupt must have fired at the requested boundary — otherwise a
    # solver retune that changes the boundary count would leave this test
    # passing vacuously on the final state.
    assert state["n"] == stop_at, f"only {state['n']} boundaries reached"
    mst_r, frag_r, _ = rs.solve_rank_resume(vmin0, ra, rb, state["saved"])
    ranks = np.nonzero(np.asarray(mst_r))[0]
    ids_r = np.sort(g.edge_id_of_rank(ranks))
    assert np.array_equal(ids_r, ref_ids), f"resume from boundary {stop_at}"


@pytest.mark.parametrize("seed", range(8))
def test_production_routing_fuzz(seed):
    """solve_graph_rank's production routing (host L1/L2 per family, the
    r5 paths) vs the plain Borůvka reference, across random densities that
    straddle every family-policy boundary (sparse <=3 < grid <=8 < dense)
    plus disconnection and isolated vertices."""
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
    from distributed_ghs_implementation_tpu.models.rank_solver import (
        _pick_family,
        solve_graph_rank,
    )

    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(30, 400))
    # Density sweeps the family policy: avg degree in [1, 12].
    m = int(n * rng.uniform(0.5, 6.0))
    g = Graph.from_arrays(
        n + int(rng.integers(0, 5)),  # a few isolated vertices
        rng.integers(0, n, m),
        rng.integers(0, n, m),
        rng.integers(1, int(rng.choice([5, 1000])), m),  # tie-heavy or wide
    )
    fam = _pick_family(g)
    ids, frag, _ = solve_graph_rank(g)
    ref_ids, ref_frag, _ = solve_graph(g)
    assert np.array_equal(ids, ref_ids), fam
    assert np.unique(frag).size == np.unique(ref_frag).size
