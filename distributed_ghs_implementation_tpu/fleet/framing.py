"""Length-prefixed JSON framing for the router <-> worker pipes.

The single-process service speaks newline-delimited JSON (one request per
line, ``serve/service.py``); the fleet cannot: a worker's stdout carries
*interleaved* responses written by concurrent request threads, and a torn
line would silently merge two frames. Each frame is therefore::

    <payload-byte-length>\\n<payload>\\n

— the reader knows exactly how many bytes belong to the frame before it
parses a single one, a short read is detected (not mis-parsed), and the
trailing newline keeps frames greppable in a captured pipe dump.

Framing errors are indistinguishable from a dead peer by design:
:func:`read_frame` returns ``None`` on EOF *and* on a torn frame, because
both mean the same thing to the router — this worker's pipe can no longer
be trusted, fail over. Writes must be serialized by the caller (the router
holds a per-worker lock; the worker holds one stdout lock across its
request threads).
"""

from __future__ import annotations

import json
from typing import IO, Optional

#: A frame larger than this is a protocol violation (a runaway edges_out
#: response, or garbage on the pipe) — refuse to buffer it.
MAX_FRAME_BYTES = 256 * 1024 * 1024


def write_frame(stream: IO[bytes], obj: dict) -> None:
    """Serialize ``obj`` as one length-prefixed frame and flush."""
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    stream.write(b"%d\n" % len(payload) + payload + b"\n")
    stream.flush()


def read_frame(stream: IO[bytes]) -> Optional[dict]:
    """Read one frame; ``None`` on EOF or any torn/garbled frame."""
    header = stream.readline()
    if not header:
        return None
    try:
        n = int(header)
    except ValueError:
        return None
    if n < 0 or n > MAX_FRAME_BYTES:
        return None
    payload = stream.read(n)
    if payload is None or len(payload) != n:
        return None
    stream.read(1)  # the trailing newline (EOF here still parsed a frame)
    try:
        obj = json.loads(payload)
    except ValueError:
        return None
    return obj if isinstance(obj, dict) else None
