"""``python -m distributed_ghs_implementation_tpu`` — see cli.py."""

import sys

from distributed_ghs_implementation_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
