"""ctypes bindings for the native ingestion library, with NumPy fallback.

Compiles ``native/graph_native.cpp`` on first use (g++ -O3 -fopenmp) into the
repo-local ``native/`` dir and caches the handle. Every entry point has a pure
NumPy fallback, so the framework works without a toolchain — native just makes
RMAT-24-scale ingestion fast enough that data prep doesn't dwarf the solve
(SURVEY.md §7 hard part 6).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native")
_SRC = os.path.join(_NATIVE_DIR, "graph_native.cpp")
_SO = os.path.join(_NATIVE_DIR, "libgraph_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False

_I64 = ctypes.POINTER(ctypes.c_int64)


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
        _SRC, "-o", _SO,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        print(f"native build failed ({e}); using NumPy fallback", file=sys.stderr)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        if not os.path.exists(_SO) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        ):
            if not os.path.exists(_SRC) or not _build():
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            print(f"native load failed ({e}); using NumPy fallback", file=sys.stderr)
            _lib_failed = True
            return None
        lib.rmat_generate.argtypes = [
            ctypes.c_int, ctypes.c_int64, ctypes.c_uint64,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_int64, ctypes.c_int64, _I64, _I64, _I64,
        ]
        lib.rmat_generate.restype = None
        lib.dedup_edges.argtypes = [ctypes.c_int64, ctypes.c_int64, _I64, _I64, _I64]
        lib.dedup_edges.restype = ctypes.c_int64
        lib.dimacs_parse.argtypes = [
            ctypes.c_char_p, _I64, _I64, _I64, _I64, ctypes.c_int64,
        ]
        lib.dimacs_parse.restype = ctypes.c_int64
        lib.build_csr.argtypes = [
            ctypes.c_int64, ctypes.c_int64, _I64, _I64, _I64, _I64, _I64, _I64,
        ]
        lib.build_csr.restype = None
        lib.build_rank_csr.argtypes = [
            ctypes.c_int64, ctypes.c_int64, _I64, _I64, _I64, _I64, _I64, _I64,
        ]
        lib.build_rank_csr.restype = None
        lib.first_rank.argtypes = [
            ctypes.c_int64, ctypes.c_int64, _I64, _I64,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.first_rank.restype = None
        _I32 = ctypes.POINTER(ctypes.c_int32)
        lib.first_rank_i32.argtypes = [
            ctypes.c_int64, ctypes.c_int64, _I32, _I32, _I32,
        ]
        lib.first_rank_i32.restype = None
        lib.first_rank64.argtypes = [
            ctypes.c_int64, ctypes.c_int64, _I64, _I64, _I64,
        ]
        lib.first_rank64.restype = None
        lib.first_cross_rank.argtypes = [
            ctypes.c_int64, ctypes.c_int64, _I32, _I32, _I32, _I32,
        ]
        lib.first_cross_rank.restype = None
        lib.first_rank_i32e64.argtypes = [
            ctypes.c_int64, ctypes.c_int64, _I32, _I32, _I64,
        ]
        lib.first_rank_i32e64.restype = None
        lib.kruskal_msf.argtypes = [
            ctypes.c_int64, ctypes.c_int64, _I64, _I64, _I64, _I64, _I64,
        ]
        lib.kruskal_msf.restype = None
        lib.kruskal_msf_solve.argtypes = [
            ctypes.c_int64, ctypes.c_int64, _I64, _I64, _I64, _I64, _I64,
            _I64,
        ]
        lib.kruskal_msf_solve.restype = ctypes.c_int64
        lib.rank_endpoints_i32.argtypes = [
            ctypes.c_int64, ctypes.c_int64, _I64, _I64, _I64, _I32, _I32,
        ]
        lib.rank_endpoints_i32.restype = None
        lib.rank_endpoints_i32_planes.argtypes = [
            ctypes.c_int64, ctypes.c_int64, _I64, _I64, _I64, _I32, _I32,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.rank_endpoints_i32_planes.restype = None
        lib.rank_order_counting.argtypes = [
            ctypes.c_int64, _I64, ctypes.c_int64, ctypes.c_int64, _I64,
        ]
        lib.rank_order_counting.restype = ctypes.c_int
        _lib = lib
        return _lib


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(_I64)


def native_available() -> bool:
    return get_lib() is not None


def rmat_edges(
    scale: int,
    edge_factor: int,
    *,
    seed: int = 1,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    weight_low: int = 1,
    weight_high: int = 255,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Raw RMAT samples + canonical dedup, natively; ``(u, v, w, n)``."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = 1 << scale
    m = int(edge_factor) << scale
    u = np.empty(m, dtype=np.int64)
    v = np.empty(m, dtype=np.int64)
    w = np.empty(m, dtype=np.int64)
    lib.rmat_generate(
        scale, m, seed, a, b, c, weight_low, weight_high, _ptr(u), _ptr(v), _ptr(w)
    )
    kept = int(lib.dedup_edges(m, n, _ptr(u), _ptr(v), _ptr(w)))
    return u[:kept].copy(), v[:kept].copy(), w[:kept].copy(), n


def read_dimacs_native(path: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """DIMACS .gr arcs via the native parser; ``(u, v, w, n)`` (raw arcs)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n_out = np.zeros(1, dtype=np.int64)
    count = int(lib.dimacs_parse(path.encode(), _ptr(n_out), None, None, None, 0))
    if count < 0:
        raise FileNotFoundError(path)
    u = np.empty(count, dtype=np.int64)
    v = np.empty(count, dtype=np.int64)
    w = np.empty(count, dtype=np.int64)
    wrote = int(
        lib.dimacs_parse(path.encode(), _ptr(n_out), _ptr(u), _ptr(v), _ptr(w), count)
    )
    return u[:wrote], v[:wrote], w[:wrote], int(n_out[0])


def build_rank_csr_native(
    num_nodes: int, u: np.ndarray, v: np.ndarray, rank: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Rank-sorted CSR over directed slots; ``(indptr, adj_dst, adj_rank)``."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    m = u.shape[0]
    u = np.ascontiguousarray(u, dtype=np.int64)
    v = np.ascontiguousarray(v, dtype=np.int64)
    rank = np.ascontiguousarray(rank, dtype=np.int64)
    indptr = np.empty(num_nodes + 1, dtype=np.int64)
    adj_dst = np.empty(2 * m, dtype=np.int64)
    adj_rank = np.empty(2 * m, dtype=np.int64)
    lib.build_rank_csr(num_nodes, m, _ptr(u), _ptr(v), _ptr(rank),
                       _ptr(indptr), _ptr(adj_dst), _ptr(adj_rank))
    return indptr, adj_dst, adj_rank


def kruskal_msf_native(
    num_nodes: int, order: np.ndarray, u: np.ndarray, v: np.ndarray,
    w: np.ndarray
) -> Tuple[int, int]:
    """Kruskal over the precomputed (weight, edge id) order: one union-find
    pass returning ``(total_msf_weight, msf_edge_count)`` — the C-speed
    verification oracle (measured 6.6 s at 64M edges; SciPy csgraph needs
    ~80 s there).
    The pass VALIDATES the order (non-decreasing permutation) rather than
    trusting it — the solver under test consumes the same order — and
    raises ``ValueError`` on corruption (callers fall back to SciPy, which
    sorts independently)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    order = np.ascontiguousarray(order, dtype=np.int64)
    u = np.ascontiguousarray(u, dtype=np.int64)
    v = np.ascontiguousarray(v, dtype=np.int64)
    w = np.ascontiguousarray(w, dtype=np.int64)
    out = np.zeros(2, dtype=np.int64)
    lib.kruskal_msf(
        num_nodes, order.shape[0], _ptr(order), _ptr(u), _ptr(v), _ptr(w),
        _ptr(out),
    )
    if out[1] < 0:
        raise ValueError(
            "rank order is not a non-decreasing permutation of the edges"
        )
    return int(out[0]), int(out[1])


def kruskal_msf_solve_native(
    num_nodes: int, order: np.ndarray, u: np.ndarray, v: np.ndarray,
    w: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Full Kruskal solve over the precomputed rank order: ``(edge_ids,
    labels)`` — the chosen MSF edges (ascending rank order) and the final
    per-vertex component label. Same order validation as
    :func:`kruskal_msf_native` (raises ``ValueError`` on corruption).
    Because ranks make the weight order total, the edge set is THE unique
    MSF — byte-identical to every device backend."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    order = np.ascontiguousarray(order, dtype=np.int64)
    u = np.ascontiguousarray(u, dtype=np.int64)
    v = np.ascontiguousarray(v, dtype=np.int64)
    w = np.ascontiguousarray(w, dtype=np.int64)
    out_edges = np.empty(max(num_nodes, 1), dtype=np.int64)
    labels = np.empty(max(num_nodes, 1), dtype=np.int64)
    count = int(
        lib.kruskal_msf_solve(
            num_nodes, order.shape[0], _ptr(order), _ptr(u), _ptr(v),
            _ptr(w), _ptr(out_edges), _ptr(labels),
        )
    )
    if count < 0:
        raise ValueError(
            "rank order is not a non-decreasing permutation of the edges"
        )
    return out_edges[:count], labels[:num_nodes]


def first_rank64_native(
    num_nodes: int, ra: np.ndarray, rb: np.ndarray
) -> np.ndarray:
    """:func:`first_rank_native` with int64 rank output (INT64_MAX when
    isolated) — the rank64 regime, where rank ids exceed int32."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    ra = np.ascontiguousarray(ra, dtype=np.int64)
    rb = np.ascontiguousarray(rb, dtype=np.int64)
    out = np.empty(num_nodes, dtype=np.int64)
    lib.first_rank64(num_nodes, ra.shape[0], _ptr(ra), _ptr(rb), _ptr(out))
    return out


def first_cross_rank_native(
    num_nodes: int, ra: np.ndarray, rb: np.ndarray, parent1: np.ndarray
) -> np.ndarray:
    """Per-fragment first CROSS rank (level-2 MOE) fused with the fragment
    relabel — host analog of the device head's full-width level 2. Pass
    unpadded ``ra[:m]``/``rb[:m]`` views."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    assert ra.dtype == np.int32 and ra.flags.c_contiguous
    assert rb.dtype == np.int32 and rb.flags.c_contiguous
    parent1 = np.ascontiguousarray(parent1, dtype=np.int32)
    _i32p = ctypes.POINTER(ctypes.c_int32)
    out = np.empty(num_nodes, dtype=np.int32)
    lib.first_cross_rank(
        num_nodes, ra.shape[0],
        ra.ctypes.data_as(_i32p), rb.ctypes.data_as(_i32p),
        parent1.ctypes.data_as(_i32p), out.ctypes.data_as(_i32p),
    )
    return out


def first_rank_i32_out64_native(
    num_nodes: int, ra: np.ndarray, rb: np.ndarray
) -> np.ndarray:
    """Per-vertex min incident rank with int64 output over int32 endpoint
    views — the rank64 staging reuses its padded ra/rb (pass unpadded
    ``ra[:m]`` views) instead of re-gathering int64 endpoints from u/v
    (two O(m) int64 fancy-gathers, ~34 GB of host temporaries at the
    RMAT-27 scale the path targets)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    assert ra.dtype == np.int32 and ra.flags.c_contiguous
    assert rb.dtype == np.int32 and rb.flags.c_contiguous
    _i32p = ctypes.POINTER(ctypes.c_int32)
    out = np.empty(num_nodes, dtype=np.int64)
    lib.first_rank_i32e64(
        num_nodes, ra.shape[0],
        ra.ctypes.data_as(_i32p), rb.ctypes.data_as(_i32p), _ptr(out),
    )
    return out


def first_rank_i32_native(
    num_nodes: int, ra: np.ndarray, rb: np.ndarray
) -> np.ndarray:
    """:func:`first_rank_native` over int32 endpoint arrays (the prep fast
    path reuses its freshly built padded ``ra``/``rb`` — pass unpadded
    ``ra[:m]`` views, pads would alias vertex 0)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    assert ra.dtype == np.int32 and ra.flags.c_contiguous
    assert rb.dtype == np.int32 and rb.flags.c_contiguous
    _i32p = ctypes.POINTER(ctypes.c_int32)
    out = np.empty(num_nodes, dtype=np.int32)
    lib.first_rank_i32(
        num_nodes, ra.shape[0],
        ra.ctypes.data_as(_i32p), rb.ctypes.data_as(_i32p),
        out.ctypes.data_as(_i32p),
    )
    return out


def rank_endpoints_i32_native(
    order: np.ndarray, u: np.ndarray, v: np.ndarray, size_pad: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Fused ``(u[order].astype(i32), v[order].astype(i32))`` with zero pad to
    ``size_pad`` — one native pass in place of two int64 fancy-gathers plus
    casts (the pre-transfer critical path of prep)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    m = order.shape[0]
    order = np.ascontiguousarray(order, dtype=np.int64)
    u = np.ascontiguousarray(u, dtype=np.int64)
    v = np.ascontiguousarray(v, dtype=np.int64)
    _i32p = ctypes.POINTER(ctypes.c_int32)
    ra = np.empty(size_pad, dtype=np.int32)
    rb = np.empty(size_pad, dtype=np.int32)
    lib.rank_endpoints_i32(
        m, size_pad, _ptr(order), _ptr(u), _ptr(v),
        ra.ctypes.data_as(_i32p), rb.ctypes.data_as(_i32p),
    )
    return ra, rb


def rank_endpoints_i32_planes_native(
    order: np.ndarray, u: np.ndarray, v: np.ndarray, size_pad: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """:func:`rank_endpoints_i32_native` fused with the 24-bit planar wire
    packing: returns ``(ra, rb, planes)`` where ``planes`` is the
    six-byte-plane uint8 buffer the packed transfer ships (see
    ``models.rank_solver._stage_pair_packed24``). One pass instead of
    gather-then-repack — this sits on prep's pre-transfer critical path.
    Caller guarantees endpoint ids < 2^24."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    m = order.shape[0]
    order = np.ascontiguousarray(order, dtype=np.int64)
    u = np.ascontiguousarray(u, dtype=np.int64)
    v = np.ascontiguousarray(v, dtype=np.int64)
    _i32p = ctypes.POINTER(ctypes.c_int32)
    ra = np.empty(size_pad, dtype=np.int32)
    rb = np.empty(size_pad, dtype=np.int32)
    planes = np.empty(6 * size_pad, dtype=np.uint8)
    lib.rank_endpoints_i32_planes(
        m, size_pad, _ptr(order), _ptr(u), _ptr(v),
        ra.ctypes.data_as(_i32p), rb.ctypes.data_as(_i32p),
        planes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return ra, rb, planes


def first_rank_native(num_nodes: int, ra: np.ndarray, rb: np.ndarray) -> np.ndarray:
    """Per-vertex min incident rank over rank-ordered endpoints (INT32_MAX if
    isolated) — Boruvka level 1, computed host-side in one O(m) pass."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    ra = np.ascontiguousarray(ra, dtype=np.int64)
    rb = np.ascontiguousarray(rb, dtype=np.int64)
    out = np.empty(num_nodes, dtype=np.int32)
    lib.first_rank(
        num_nodes, ra.shape[0], _ptr(ra), _ptr(rb),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out


def rank_order_counting_native(w: np.ndarray) -> Optional[np.ndarray]:
    """Stable counting-sort rank order by (weight, edge id); None when weights
    are non-integer / too wide (caller falls back to lexsort)."""
    lib = get_lib()
    if lib is None or w.dtype.kind not in "iu" or w.size == 0:
        return None
    w = np.ascontiguousarray(w, dtype=np.int64)
    wlow, whigh = int(w.min()), int(w.max())
    order = np.empty(w.shape[0], dtype=np.int64)
    ok = lib.rank_order_counting(w.shape[0], _ptr(w), wlow, whigh, _ptr(order))
    return order if ok else None


def build_csr_native(
    num_nodes: int, u: np.ndarray, v: np.ndarray, w: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """CSR over directed slots, natively; ``(indptr, adj_dst, adj_w)``."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    m = u.shape[0]
    u = np.ascontiguousarray(u, dtype=np.int64)
    v = np.ascontiguousarray(v, dtype=np.int64)
    w = np.ascontiguousarray(w, dtype=np.int64)
    indptr = np.empty(num_nodes + 1, dtype=np.int64)
    adj_dst = np.empty(2 * m, dtype=np.int64)
    adj_w = np.empty(2 * m, dtype=np.int64)
    lib.build_csr(num_nodes, m, _ptr(u), _ptr(v), _ptr(w),
                  _ptr(indptr), _ptr(adj_dst), _ptr(adj_w))
    return indptr, adj_dst, adj_w
