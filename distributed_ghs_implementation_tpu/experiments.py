"""Experiment harness: the reference's suite, oracle-gated and extensible.

Parity with ``/root/reference/ghs_implementation.py:724-835``: the same six
graph configurations (``:787-794``), generated with the same sampling
(``reference_random_graph``), each solved, verified against NetworkX, rendered
(small graphs), and dumped to ``ghs_experiments.json`` with a PASS/FAIL
console table. Unlike the reference — which fails its own 20-node config 2/3
of the time (SURVEY.md §0) — every config passes deterministically.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional, Sequence

from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import (
    reference_random_graph,
)
from distributed_ghs_implementation_tpu.utils.reporting import (
    experiment_record,
    print_summary_table,
    write_experiments_json,
)
from distributed_ghs_implementation_tpu.utils.verify import (
    networkx_mst_weight,
    scipy_mst_weight,
)

# The reference's six experiment configurations (ghs_implementation.py:787-794).
REFERENCE_CONFIGS = [
    {"num_nodes": 5, "edge_probability": 0.5, "seed": 42},
    {"num_nodes": 6, "edge_probability": 0.4, "seed": 100},
    {"num_nodes": 7, "edge_probability": 0.6, "seed": 200},
    {"num_nodes": 6, "edge_probability": 0.7, "seed": 300},
    {"num_nodes": 10, "edge_probability": 0.8, "seed": 400},
    {"num_nodes": 20, "edge_probability": 0.3, "seed": 500},
]

# Where the reference's envelope ends (~10 vertices reliably), ours continues
# (these use the vectorized generator; "generator": "native").
EXTENDED_CONFIGS = [
    {"num_nodes": 100, "edge_probability": 0.1, "seed": 600, "generator": "native"},
    {"num_nodes": 1000, "edge_probability": 0.01, "seed": 700, "generator": "native"},
    {"num_nodes": 5000, "edge_probability": 0.002, "seed": 800, "generator": "native"},
]


def run_experiment(
    graph: Graph,
    index: int,
    *,
    backend: str = "device",
    visualize_dir: Optional[str] = None,
) -> dict:
    """Solve + verify one graph (``ghs_implementation.py:724-776`` parity)."""
    result = minimum_spanning_forest(graph, backend=backend)
    oracle = (
        networkx_mst_weight(graph)
        if graph.num_edges <= 200_000
        else scipy_mst_weight(graph)
    )
    record = experiment_record(result, oracle, index)
    if not record["is_correct"]:
        from distributed_ghs_implementation_tpu.utils.diagnostics import (
            dump_failure_report,
        )
        from distributed_ghs_implementation_tpu.utils.verify import Verification

        # Reuse the oracle weight computed above (recomputing it on a failed
        # RMAT-scale run would cost minutes on the fail-fast path).
        v = Verification(
            ok=False,
            expected_weight=float(oracle),
            actual_weight=float(result.total_weight),
            expected_edges=graph.num_nodes - result.num_components,
            actual_edges=result.num_edges,
            oracle="networkx" if graph.num_edges <= 200_000 else "scipy",
        )
        record["failure_report"] = dump_failure_report(
            result, v, path=f"experiment_{index}_failure_report.json"
        )
    if visualize_dir is not None:
        from distributed_ghs_implementation_tpu.utils.viz import visualize_mst

        os.makedirs(visualize_dir, exist_ok=True)
        visualize_mst(
            result, os.path.join(visualize_dir, f"experiment_{index}.png")
        )
    return record


def run_suite(
    *,
    backend: str = "device",
    extended: bool = False,
    output_json: str = "ghs_experiments.json",
    visualize_dir: Optional[str] = None,
    configs: Optional[Sequence[dict]] = None,
) -> List[dict]:
    """Run the full suite; writes JSON, prints the summary table."""
    if configs is None:
        configs = list(REFERENCE_CONFIGS) + (EXTENDED_CONFIGS if extended else [])
    records = []
    for i, cfg in enumerate(configs, 1):
        print(
            f"experiment {i}: n={cfg['num_nodes']} p={cfg['edge_probability']} "
            f"seed={cfg['seed']}",
            file=sys.stderr,
        )
        if cfg.get("generator") == "native":
            from distributed_ghs_implementation_tpu.graphs.generators import (
                erdos_renyi_graph,
            )

            g = erdos_renyi_graph(
                cfg["num_nodes"], cfg["edge_probability"], seed=cfg["seed"]
            )
        else:
            g = reference_random_graph(
                cfg["num_nodes"], cfg["edge_probability"], cfg["seed"]
            )
        records.append(
            run_experiment(g, i, backend=backend, visualize_dir=visualize_dir)
        )
    if output_json:
        write_experiments_json(records, output_json)
    print_summary_table(records)
    return records
