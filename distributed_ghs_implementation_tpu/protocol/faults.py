"""Lossy channels and reliable delivery for the protocol transport.

The reference is only *probabilistically* live: its requeue caps, forced
merges, and idle-based termination exist to escape hangs that appear under
nondeterministic timing (SURVEY.md preamble), and they are exactly what makes
it wrong under adversity. This module attacks the problem from the other
side: make the channel *adversarial on purpose* and make correctness a
theorem again.

Two layers:

* :class:`FaultyTransport` — a :class:`SimTransport` whose channel drops,
  duplicates, and reorders transmissions, driven by a seeded RNG
  (:class:`FaultSpec`), so every failure scenario replays bit-identically.
  Under the raw GHS protocol a single dropped CONNECT either truncates the
  MST or livelocks a deferral cycle (caught by the ``max_events`` guard) —
  which is the demonstration that the reference's heuristics cannot be
  patched into safety.
* :class:`ReliableTransport` — the same lossy channel with a reliable
  in-order delivery sublayer on top: per-directed-link sequence numbers,
  positive acks, retransmit timers with capped exponential backoff, and
  duplicate suppression. GHS assumes reliable FIFO links; this layer restores
  that assumption over any loss rate < 1, so ``run_protocol`` reaches exact
  quiescence with the oracle MST no matter what the fault spec does.
  ``tools/chaos_drill.py`` sweeps the matrix.

Everything is deterministic: the event loop is a single priority queue and
fault draws happen in event order, so (graph, spec, latency) fully determine
the run.
"""

from __future__ import annotations

import dataclasses
import heapq
import random
from typing import Dict, Tuple

from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.protocol.messages import Message
from distributed_ghs_implementation_tpu.protocol.transport import SimTransport


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """Seeded channel misbehavior, applied independently per transmission.

    ``drop``/``duplicate``/``reorder`` are probabilities; a reordered
    (or duplicated) transmission is delayed by 1..``max_jitter`` extra ticks,
    which lets later sends overtake it — genuine reordering, not just
    latency. ``seed`` makes the whole fault schedule replayable.
    """

    drop: float = 0.0
    duplicate: float = 0.0
    reorder: float = 0.0
    max_jitter: int = 16
    seed: int = 0

    def __post_init__(self):
        for name in ("drop", "duplicate", "reorder"):
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name} must be a probability, got {p}")
        if self.max_jitter < 1:
            raise ValueError(f"max_jitter must be >= 1, got {self.max_jitter}")

    @property
    def is_clean(self) -> bool:
        return self.drop == 0.0 and self.duplicate == 0.0 and self.reorder == 0.0


class FaultyTransport(SimTransport):
    """Event-queue transport whose channel misbehaves per a :class:`FaultSpec`.

    Counters (``dropped``/``duplicated``/``jittered``) record what the
    channel actually did, so tests can assert a scenario genuinely exercised
    the fault path rather than passing vacuously.
    """

    def __init__(self, spec: FaultSpec = FaultSpec(), latency=1, **kwargs):
        super().__init__(latency, **kwargs)
        self.spec = spec
        self._rng = random.Random(spec.seed)
        self.dropped = 0
        self.duplicated = 0
        self.jittered = 0

    def _delivery_times(self, base: int) -> list:
        """Fault-adjusted arrival times for one transmission (empty = lost)."""
        rng, spec = self._rng, self.spec
        if spec.drop and rng.random() < spec.drop:
            self.dropped += 1
            return []
        when = base
        if spec.reorder and rng.random() < spec.reorder:
            self.jittered += 1
            when = base + rng.randint(1, spec.max_jitter)
        times = [when]
        if spec.duplicate and rng.random() < spec.duplicate:
            self.duplicated += 1
            times.append(base + rng.randint(1, spec.max_jitter))
        return times

    def send(self, src: int, dst: int, msg: Message) -> None:
        self.messages_sent += 1
        base = self.now + max(1, self._latency(src, dst))
        for when in self._delivery_times(base):
            heapq.heappush(self._queue, (when, next(self._seq), dst, msg))

    def _bus_counters(self) -> Dict[str, int]:
        counters = super()._bus_counters()
        counters.update(
            {
                "protocol.drops_injected": self.dropped,
                "protocol.duplicates_injected": self.duplicated,
                "protocol.reorders_injected": self.jittered,
            }
        )
        return counters


# Wire/loop items for ReliableTransport. DATA and ACK cross the lossy
# channel; TIMER and LOCAL are node-local bookkeeping events and bypass it.
@dataclasses.dataclass(frozen=True)
class _Data:
    src: int
    seq_no: int
    payload: Message


@dataclasses.dataclass(frozen=True)
class _Ack:
    src: int  # the data *receiver* acking back
    seq_no: int


@dataclasses.dataclass(frozen=True)
class _Timer:
    dst: int  # peer the unacked data was sent to (event target = the sender)
    seq_no: int
    attempt: int


@dataclasses.dataclass(frozen=True)
class _Local:
    payload: Message  # protocol-deferred message awaiting redelivery


class ReliableTransport(FaultyTransport):
    """Reliable in-order delivery over the lossy channel.

    Per directed link ``(src, dst)``: the sender stamps consecutive sequence
    numbers and keeps every message until acked, retransmitting on a timer
    whose period doubles from ``rto`` up to ``rto_cap``; the receiver acks
    every receipt (so a lost ack is healed by the next retransmit),
    suppresses duplicates by sequence number, and releases messages to the
    node strictly in order through a reorder buffer.

    ``max_retries=None`` retries forever — delivery is then guaranteed for
    any ``drop < 1`` and quiescence stays exact (all timers die once acked).
    A finite ``max_retries`` models a link declared dead: the run raises
    ``RuntimeError`` instead of silently computing a wrong forest.

    Protocol-level deferral (``handle`` returning ``False``) is unchanged:
    the payload is redelivered locally at ``defer_delay`` later, exactly as
    ``SimTransport`` does — reliability is a sublayer below the protocol's
    own semantics, not a change to them.
    """

    def __init__(
        self,
        spec: FaultSpec = FaultSpec(),
        latency=1,
        *,
        defer_delay: int = 1,
        max_events: int = 50_000_000,
        rto: int = 8,
        rto_cap: int = 256,
        max_retries: int | None = None,
    ):
        if spec.drop >= 1.0:
            raise ValueError("drop=1.0 severs every link; no reliable layer helps")
        super().__init__(
            spec, latency, defer_delay=defer_delay, max_events=max_events
        )
        self._rto = rto
        self._rto_cap = rto_cap
        self._max_retries = max_retries
        # Sender state, keyed by directed link (src, dst).
        self._next_seq: Dict[Tuple[int, int], int] = {}
        self._unacked: Dict[Tuple[int, int], Dict[int, Message]] = {}
        # Receiver state, keyed by directed link (src, dst).
        self._expected: Dict[Tuple[int, int], int] = {}
        self._rx_buffer: Dict[Tuple[int, int], Dict[int, Message]] = {}
        self.retransmits = 0
        self.acks_sent = 0
        self.dup_suppressed = 0
        # Ack latency (sim ticks, first send -> first ack per sequence).
        self._sent_at: Dict[Tuple[Tuple[int, int], int], int] = {}
        self.ack_latency_count = 0
        self.ack_latency_sum = 0
        self.ack_latency_max = 0

    # ------------------------------------------------------------------
    def _push(self, when: int, target: int, item) -> None:
        heapq.heappush(self._queue, (when, next(self._seq), target, item))

    def _transmit(self, src: int, dst: int, item) -> None:
        """One trip across the lossy channel (DATA and ACK both ride it)."""
        base = self.now + max(1, self._latency(src, dst))
        for when in self._delivery_times(base):
            self._push(when, dst, item)

    def send(self, src: int, dst: int, msg: Message) -> None:
        self.messages_sent += 1
        link = (src, dst)
        seq_no = self._next_seq.get(link, 0)
        self._next_seq[link] = seq_no + 1
        self._unacked.setdefault(link, {})[seq_no] = msg
        self._sent_at[(link, seq_no)] = self.now
        self._transmit(src, dst, _Data(src, seq_no, msg))
        self._push(self.now + self._rto, src, _Timer(dst, seq_no, 1))

    # ------------------------------------------------------------------
    def _dispatch(self, nodes, target: int, item) -> int:
        """The reliable layer's event vocabulary, under the shared run loop."""
        if isinstance(item, _Data):
            return self._on_data(nodes, target, item)
        if isinstance(item, _Ack):
            self._on_ack(target, item)
            return 0
        if isinstance(item, _Timer):
            self._on_timer(target, item)
            return 0
        if isinstance(item, _Local):
            return self._deliver(nodes, target, item.payload)
        # A raw Message cannot appear: send() always wraps.
        raise AssertionError(f"unexpected event item {item!r}")

    def _on_ack(self, owner: int, ack: "_Ack") -> None:
        link = (owner, ack.src)
        if self._unacked.get(link, {}).pop(ack.seq_no, None) is None:
            return  # duplicate ack: already settled
        sent = self._sent_at.pop((link, ack.seq_no), None)
        if sent is not None:
            latency = self.now - sent
            self.ack_latency_count += 1
            self.ack_latency_sum += latency
            if latency > self.ack_latency_max:
                self.ack_latency_max = latency
            BUS.record("protocol.ack_latency_ticks", latency)

    def _on_data(self, nodes, dst: int, data: _Data) -> int:
        link = (data.src, dst)
        # Ack unconditionally — duplicates re-ack so a lost ack cannot wedge
        # the sender into retransmitting forever.
        self.acks_sent += 1
        self._transmit(dst, data.src, _Ack(dst, data.seq_no))
        expected = self._expected.get(link, 0)
        buf = self._rx_buffer.setdefault(link, {})
        if data.seq_no < expected or data.seq_no in buf:
            self.dup_suppressed += 1
            return 0
        buf[data.seq_no] = data.payload
        handled = 0
        while expected in buf:
            handled += self._deliver(nodes, dst, buf.pop(expected))
            expected += 1
        self._expected[link] = expected
        return handled

    def _deliver(self, nodes, dst: int, payload: Message) -> int:
        if nodes[dst].handle(payload):
            return 1
        self.messages_deferred += 1
        self._push(self.now + self._defer_delay, dst, _Local(payload))
        return 0

    def _on_timer(self, owner: int, timer: _Timer) -> None:
        link = (owner, timer.dst)
        msg = self._unacked.get(link, {}).get(timer.seq_no)
        if msg is None:
            return  # acked in the meantime; the timer chain dies here
        if self._max_retries is not None and timer.attempt > self._max_retries:
            raise RuntimeError(
                f"link {link} seq {timer.seq_no}: gave up after "
                f"{self._max_retries} retransmits (drop={self.spec.drop})"
            )
        self.retransmits += 1
        self._transmit(owner, timer.dst, _Data(owner, timer.seq_no, msg))
        backoff = min(self._rto << timer.attempt, self._rto_cap)
        self._push(
            self.now + backoff, owner, _Timer(timer.dst, timer.seq_no, timer.attempt + 1)
        )

    def _bus_counters(self) -> Dict[str, int]:
        counters = super()._bus_counters()
        counters.update(
            {
                "protocol.retransmits": self.retransmits,
                "protocol.acks_sent": self.acks_sent,
                "protocol.dup_suppressed": self.dup_suppressed,
            }
        )
        return counters

    @property
    def stats(self) -> dict:
        """Channel + reliability counters, for reports and assertions."""
        return {
            "messages_sent": self.messages_sent,
            "messages_deferred": self.messages_deferred,
            "dropped": self.dropped,
            "duplicated": self.duplicated,
            "jittered": self.jittered,
            "retransmits": self.retransmits,
            "acks_sent": self.acks_sent,
            "dup_suppressed": self.dup_suppressed,
            "ack_latency_ticks": {
                "count": self.ack_latency_count,
                "mean": (
                    self.ack_latency_sum / self.ack_latency_count
                    if self.ack_latency_count
                    else 0.0
                ),
                "max": self.ack_latency_max,
            },
        }
