"""Unified observability layer: event bus, exporters, bench gate, CLI.

The coverage contract from the issue: span nesting, counter/histogram
aggregation, ring-buffer overflow, the disabled-mode zero-allocation path,
and a Chrome-trace export round-trip (valid JSON loadable as a trace) —
plus the bench gate's pass/fail behavior against a committed baseline and
the ``trace``/``stats`` CLI surface.
"""

import json
import os
import sys

import pytest

from distributed_ghs_implementation_tpu.obs.events import (
    BUS,
    NULL_SPAN,
    EventBus,
)
from distributed_ghs_implementation_tpu.obs.export import (
    read_events_jsonl,
    render_stats,
    snapshot_from_jsonl,
    write_chrome_trace,
    write_events_jsonl,
)


@pytest.fixture(autouse=True)
def _clean_global_bus():
    """Every test sees an enabled, empty global bus and leaves it that way
    (the default state: production telemetry is on)."""
    BUS.enable()
    BUS.clear()
    yield
    BUS.enable()
    BUS.clear()


# ----------------------------------------------------------------------
# Event bus core
# ----------------------------------------------------------------------
def test_span_records_complete_event_with_args():
    bus = EventBus(capacity=64)
    with bus.span("outer", cat="test", a=1) as span:
        span.set(b=2)
    (rec,) = bus.events()
    ph, name, cat, ts_ns, dur_ns, _tid, args = rec
    assert (ph, name, cat) == ("X", "outer", "test")
    assert dur_ns >= 0 and ts_ns >= 0
    assert args == {"a": 1, "b": 2}


def test_span_nesting_timestamps_contain_inner():
    bus = EventBus(capacity=64)
    with bus.span("outer"):
        with bus.span("inner"):
            pass
    events = {rec[1]: rec for rec in bus.events()}
    assert set(events) == {"outer", "inner"}
    # Exit order: inner lands first.
    assert [rec[1] for rec in bus.events()] == ["inner", "outer"]
    o, i = events["outer"], events["inner"]
    assert o[3] <= i[3]  # inner starts within outer
    assert i[3] + i[4] <= o[3] + o[4]  # and ends within it


def test_counter_and_histogram_aggregation():
    bus = EventBus(capacity=64)
    bus.count("msgs", 3)
    bus.count("msgs", 4)
    bus.count("other")
    for v in [1.0, 2.0, 3.0, 10.0]:
        bus.record("latency", v)
    assert bus.counters() == {"msgs": 7, "other": 1}
    h = bus.histograms()["latency"]
    assert h["count"] == 4
    assert h["sum"] == 16.0
    assert h["min"] == 1.0 and h["max"] == 10.0
    assert h["p50"] in (2.0, 3.0)


def test_ring_buffer_overflow_drops_oldest_keeps_totals():
    bus = EventBus(capacity=8)
    for i in range(20):
        bus.instant(f"e{i}")
        bus.count("total")
    events = bus.events()
    assert len(events) == 8
    assert [rec[1] for rec in events] == [f"e{i}" for i in range(12, 20)]
    assert bus.dropped == 12
    assert bus.counters()["total"] == 20  # aggregates survive overflow
    snap = bus.snapshot()
    assert snap["events_dropped"] == 12 and snap["events_retained"] == 8


def test_events_since_mark():
    bus = EventBus(capacity=64)
    bus.instant("before")
    mark = bus.mark()
    bus.instant("after")
    assert [rec[1] for rec in bus.events_since(mark)] == ["after"]


def test_disabled_mode_is_allocation_free_noop():
    bus = EventBus(capacity=64, enabled=False)
    # The span handle is the shared module-level singleton: nothing is
    # allocated per call on the disabled path.
    assert bus.span("a") is NULL_SPAN
    assert bus.span("b", x=1) is NULL_SPAN
    with bus.span("c") as s:
        s.set(y=2)  # no-op, chainable
    bus.instant("i")
    bus.count("c", 5)
    bus.record("h", 1.0)
    bus.complete("x", 0.5)
    bus.sample("s", 3)
    assert bus.events() == []
    assert bus.counters() == {}
    assert bus.histograms() == {}
    # Re-enabling starts recording without any reconstruction.
    bus.enable()
    bus.instant("live")
    assert [rec[1] for rec in bus.events()] == ["live"]


def test_complete_event_explicit_duration():
    bus = EventBus(capacity=64)
    bus.complete("k", 0.25, cat="solver", level=3)
    (rec,) = bus.events()
    assert rec[1] == "k" and abs(rec[4] - 0.25e9) < 1e6
    assert rec[6] == {"level": 3}


def test_bad_capacity_rejected():
    with pytest.raises(ValueError, match="capacity"):
        EventBus(capacity=0)


def test_histogram_reservoir_quantiles_unbiased_over_long_runs():
    """The bounded sample set must stay a UNIFORM sample of the whole run,
    not a sliding window of recent values (the bug this guards: a long
    drill's p99 forgetting everything but its final seconds)."""
    from distributed_ghs_implementation_tpu.obs.events import _Hist

    n = 50_000
    h = _Hist()
    for i in range(n):  # monotone ramp: recency bias is maximally visible
        h.add(float(i))
    s = h.summary()
    assert s["count"] == n and s["min"] == 0.0 and s["max"] == float(n - 1)
    # A recent-window implementation would report p50 ~= 49750 here.
    assert abs(s["p50"] - 0.50 * n) < 0.10 * n
    assert abs(s["p95"] - 0.95 * n) < 0.03 * n
    assert abs(s["p99"] - 0.99 * n) < 0.03 * n
    # ... and would have discarded every early observation.
    assert min(h.samples) < 0.10 * n


def test_histogram_reservoir_is_deterministic():
    """Seeded reservoir: identical observation sequences summarize
    identically (drill reports are reproducible run-to-run)."""
    from distributed_ghs_implementation_tpu.obs.events import _Hist

    h1, h2 = _Hist(), _Hist()
    for i in range(10_000):
        h1.add(float(i % 997))
        h2.add(float(i % 997))
    assert h1.summary() == h2.summary()
    assert h1.samples == h2.samples


def test_quantile_nearest_rank():
    from distributed_ghs_implementation_tpu.obs.events import quantile

    assert quantile([], 0.99) == 0.0
    assert quantile([7.0], 0.5) == 7.0
    xs = list(range(101))
    assert quantile(xs, 0.0) == 0
    assert quantile(xs, 0.50) == 50
    assert quantile(xs, 0.99) == 99
    assert quantile(xs, 1.0) == 100
    assert quantile([3.0, 1.0, 2.0], 1.0) == 3.0  # unsorted input


# ----------------------------------------------------------------------
# Exporters
# ----------------------------------------------------------------------
def _populate(bus):
    with bus.span("solve", cat="solver", nodes=10):
        with bus.span("level", cat="solver"):
            pass
    bus.instant("degrade", cat="resilience", from_rung="device")
    bus.count("protocol.messages_sent", 42)
    bus.sample("protocol.messages_sent", 17)
    bus.record("ack_latency", 3.0)
    bus.record("ack_latency", 5.0)


def test_chrome_trace_round_trip(tmp_path):
    bus = EventBus(capacity=64)
    _populate(bus)
    path = str(tmp_path / "trace.json")
    write_chrome_trace(bus, path)
    with open(path) as f:
        trace = json.load(f)  # valid JSON — loadable as a trace
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    for ev in events:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(ev)
        assert ev["ph"] in ("X", "I", "C")
    by_name = {}
    for ev in events:
        by_name.setdefault(ev["name"], []).append(ev)
    assert by_name["solve"][0]["dur"] >= by_name["level"][0]["dur"]
    assert by_name["solve"][0]["args"] == {"nodes": 10}
    # Counters appear as "C" track events with the final total.
    counter_values = [
        ev["args"]["value"]
        for ev in by_name["protocol.messages_sent"]
        if ev["ph"] == "C"
    ]
    assert 42 in counter_values  # final total sample
    assert trace["otherData"]["events_dropped"] == 0


def test_jsonl_round_trip_and_stats(tmp_path):
    bus = EventBus(capacity=64)
    _populate(bus)
    path = str(tmp_path / "events.jsonl")
    write_events_jsonl(bus, path)
    events, meta = read_events_jsonl(path)
    assert {e["name"] for e in events} >= {"solve", "level", "degrade"}
    assert meta["counters"]["protocol.messages_sent"] == 42
    assert meta["histograms"]["ack_latency"]["count"] == 2

    snap = snapshot_from_jsonl(path)
    assert snap["spans"]["solve"]["count"] == 1
    assert snap["instants"]["degrade"] == 1
    text = render_stats(snap)
    assert "solve" in text and "protocol.messages_sent" in text
    assert "ack_latency" in text

    # The live-bus snapshot renders the same names.
    live = render_stats(bus.snapshot())
    assert "solve" in live and "degrade" in live


def test_jsonl_header_carries_capacity_and_dropped(tmp_path):
    """The LEADING metadata line: a log truncated before its trailing
    totals line must still tell the reader whether the ring overflowed."""
    bus = EventBus(capacity=8)
    for i in range(20):
        bus.instant(f"e{i}")
    path = str(tmp_path / "events.jsonl")
    write_events_jsonl(bus, path)
    with open(path) as f:
        first = json.loads(f.readline())
    assert first["ph"] == "M" and first["kind"] == "header"
    assert first["capacity"] == 8 and first["events_dropped"] == 12

    # Drop the trailing totals line (simulates a crash mid-export):
    # the header still reports the overflow.
    lines = open(path).read().splitlines()
    with open(path, "w") as f:
        f.write("\n".join(lines[:-1]) + "\n")
    snap = snapshot_from_jsonl(path)
    assert snap["events_dropped"] == 12


def test_jsonl_reader_skips_torn_final_line(tmp_path):
    """A concurrently-written log's torn last line is skipped and counted,
    never a crash — the load drill reads logs other threads still write."""
    bus = EventBus(capacity=64)
    _populate(bus)
    path = str(tmp_path / "events.jsonl")
    write_events_jsonl(bus, path)
    full_events, full_meta = read_events_jsonl(path)

    # Torn mid-record write: truncate the file inside the LAST event line.
    raw = open(path).read()
    cut = raw.rindex('{"ph"')
    torn = str(tmp_path / "torn.jsonl")
    with open(torn, "w") as f:
        f.write(raw[: cut + 25])  # half a JSON object
    events, meta = read_events_jsonl(torn)
    assert meta["lines_skipped"] == 1
    assert len(events) == len(full_events)  # only the torn META line lost
    snap = snapshot_from_jsonl(torn)
    assert snap["lines_skipped"] == 1
    assert snap["spans"] == snapshot_from_jsonl(path)["spans"]
    text = render_stats(snap)
    assert "WARNING" in text and "skipped" in text

    # Mid-file corruption (a partially flushed then continued write) is
    # skipped too; intact lines before AND after still parse.
    lines = raw.splitlines()
    garbled = str(tmp_path / "garbled.jsonl")
    with open(garbled, "w") as f:
        f.write("\n".join(lines[:2] + ['{"ph": "X", "na'] + lines[2:]) + "\n")
    events_g, meta_g = read_events_jsonl(garbled)
    assert meta_g["lines_skipped"] == 1
    assert len(events_g) == len(full_events)
    assert meta_g["counters"] == full_meta["counters"]


def test_jsonl_reader_tolerates_concurrent_writer(tmp_path):
    """Reading WHILE a writer appends: every fully-written line parses,
    the in-flight line is skipped, nothing raises."""
    import threading

    bus = EventBus(capacity=256)
    for i in range(50):
        bus.instant(f"e{i}")
    path = str(tmp_path / "live.jsonl")
    write_events_jsonl(bus, path)  # the file exists before the reader starts
    stop = threading.Event()

    def writer():
        # Rewrite the log repeatedly with an unterminated tail record, the
        # steady state a tailing reader actually observes.
        while not stop.is_set():
            write_events_jsonl(bus, path)
            with open(path, "a") as f:
                f.write('{"ph": "I", "name": "partial')

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(25):
            events, meta = read_events_jsonl(path)  # must never raise
            for rec in events:
                assert isinstance(rec, dict)
    finally:
        stop.set()
        t.join()


# ----------------------------------------------------------------------
# Layer instrumentation lands on the global bus
# ----------------------------------------------------------------------
def test_solver_emits_solve_span_and_level_events():
    from distributed_ghs_implementation_tpu.graphs.generators import (
        erdos_renyi_graph,
    )
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph

    g = erdos_renyi_graph(60, 0.1, seed=3)
    solve_graph(g, strategy="stepped")
    names = [rec[1] for rec in BUS.events()]
    assert "solver.solve" in names
    assert names.count("solver.level") >= 1
    level_args = [
        rec[6] for rec in BUS.events() if rec[1] == "solver.level"
    ]
    assert all("edges_alive" in a and "level" in a for a in level_args)


def test_protocol_transport_publishes_counters():
    from distributed_ghs_implementation_tpu.graphs.generators import line_graph
    from distributed_ghs_implementation_tpu.protocol.runner import (
        solve_graph_protocol,
    )

    solve_graph_protocol(line_graph(12))
    counters = BUS.counters()
    assert counters["protocol.messages_sent"] > 0
    names = [rec[1] for rec in BUS.events()]
    assert "protocol.run" in names


def test_repeated_runs_publish_counter_deltas_once():
    """Driving run() twice on one transport publishes each message to the
    bus exactly once (delta-based publishing, not lifetime totals)."""
    from distributed_ghs_implementation_tpu.protocol.messages import (
        Message,
        MessageType,
    )
    from distributed_ghs_implementation_tpu.protocol.transport import SimTransport

    class _Sink:
        def handle(self, msg):
            return True

    t = SimTransport()
    nodes = {0: _Sink(), 1: _Sink()}
    for i in range(5):
        t.send(0, 1, Message(MessageType.TEST, sender=0, fragment=i))
    t.run(nodes)
    for i in range(3):
        t.send(1, 0, Message(MessageType.TEST, sender=1, fragment=i))
    t.run(nodes)
    assert t.messages_sent == 8
    assert BUS.counters()["protocol.messages_sent"] == 8


def test_reliable_transport_counters_and_ack_latency():
    from distributed_ghs_implementation_tpu.graphs.generators import (
        erdos_renyi_graph,
    )
    from distributed_ghs_implementation_tpu.protocol.faults import (
        FaultSpec,
        ReliableTransport,
    )
    from distributed_ghs_implementation_tpu.protocol.runner import (
        solve_graph_protocol,
    )

    t = ReliableTransport(FaultSpec(drop=0.2, duplicate=0.1, reorder=0.3, seed=7))
    solve_graph_protocol(erdos_renyi_graph(30, 0.15, seed=2), transport=t)
    counters = BUS.counters()
    assert counters["protocol.drops_injected"] == t.dropped > 0
    assert counters["protocol.retransmits"] == t.retransmits > 0
    assert counters["protocol.dup_suppressed"] == t.dup_suppressed
    lat = BUS.histograms()["protocol.ack_latency_ticks"]
    assert lat["count"] == t.ack_latency_count > 0
    assert lat["max"] == t.stats["ack_latency_ticks"]["max"]


def test_metrics_compat_view_reads_back_from_bus():
    from distributed_ghs_implementation_tpu.graphs.generators import (
        erdos_renyi_graph,
    )
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
    from distributed_ghs_implementation_tpu.utils.metrics import (
        solve_graph_instrumented,
    )

    g = erdos_renyi_graph(60, 0.1, seed=4)
    (ids, frag, lv), metrics = solve_graph_instrumented(g)
    assert list(ids) == list(solve_graph(g)[0])
    assert metrics.num_nodes == 60
    assert len(metrics.levels) == lv
    assert metrics.levels[0].fragments_before == 60
    for a, b in zip(metrics.levels, metrics.levels[1:]):
        assert b.fragments_before == a.fragments_after
    # The same observations exist as metrics.level events on the bus.
    bus_levels = [rec for rec in BUS.events() if rec[1] == "metrics.level"]
    assert len(bus_levels) == len(metrics.levels)
    assert bus_levels[0][6]["fragments_after"] == metrics.levels[0].fragments_after


def test_metrics_compat_works_with_global_bus_disabled():
    from distributed_ghs_implementation_tpu.graphs.generators import (
        erdos_renyi_graph,
    )
    from distributed_ghs_implementation_tpu.utils.metrics import (
        solve_graph_instrumented,
    )

    BUS.disable()
    g = erdos_renyi_graph(40, 0.15, seed=5)
    (_ids, _frag, lv), metrics = solve_graph_instrumented(g)
    assert len(metrics.levels) == lv >= 1
    assert BUS.events() == []  # nothing leaked onto the disabled global bus


# ----------------------------------------------------------------------
# Bench gate
# ----------------------------------------------------------------------
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def _gate():
    import bench_gate

    return bench_gate


def _metrics_doc(**overrides):
    metrics = {
        "device_solve_s": 1.0,
        "device_levels": 6,
        "mst_weight": 8291,
        "protocol_messages_sent": 1000,
        "edges_per_sec": 500.0,
    }
    metrics.update(overrides)
    return {"schema": "ghs-bench-metrics-v1", "config": {"workload": "t"},
            "metrics": metrics}


def test_gate_passes_identical_and_improved():
    gate = _gate()
    base = _metrics_doc()
    ok, _ = gate.compare(base, _metrics_doc())
    assert ok
    better = _metrics_doc(
        device_solve_s=0.5, protocol_messages_sent=900, edges_per_sec=800.0
    )
    ok, lines = gate.compare(base, better)
    assert ok, lines


def test_gate_fails_each_regression_class():
    gate = _gate()
    base = _metrics_doc()
    # Wall-time past tolerance.
    ok, lines = gate.compare(base, _metrics_doc(device_solve_s=1.6))
    assert not ok and any("device_solve_s" in ln and "FAIL" in ln for ln in lines)
    # Message-count regression past the tight count tolerance.
    ok, lines = gate.compare(base, _metrics_doc(protocol_messages_sent=1100))
    assert not ok and any("protocol_messages_sent" in ln for ln in lines if "FAIL" in ln)
    # Throughput collapse.
    ok, _ = gate.compare(base, _metrics_doc(edges_per_sec=100.0))
    assert not ok
    # Weight change: exact metric, any delta fails.
    ok, lines = gate.compare(base, _metrics_doc(mst_weight=8292))
    assert not ok and any("exact" in ln for ln in lines if "FAIL" in ln)
    # Missing metric fails rather than silently ungating.
    broken = _metrics_doc()
    del broken["metrics"]["device_levels"]
    ok, lines = gate.compare(base, broken)
    assert not ok and any("missing" in ln for ln in lines)


def test_gate_config_mismatch_fails():
    gate = _gate()
    base = _metrics_doc()
    fresh = _metrics_doc()
    fresh["config"] = {"workload": "other"}
    ok, lines = gate.compare(base, fresh)
    assert not ok and "config mismatch" in lines[0]


def test_gate_cli_against_committed_baseline(tmp_path):
    """The acceptance scenario: the committed baseline passes a synthetic
    identical run and fails a synthetically-regressed metrics file."""
    gate = _gate()
    baseline_path = os.path.join(
        os.path.dirname(__file__), "..", "docs", "BENCH_BASELINE.json"
    )
    with open(baseline_path) as f:
        baseline = json.load(f)
    same = str(tmp_path / "same.json")
    with open(same, "w") as f:
        json.dump(baseline, f)
    assert gate.main(["--baseline", baseline_path, "--metrics", same]) == 0

    regressed = dict(baseline)
    regressed["metrics"] = dict(baseline["metrics"])
    regressed["metrics"]["protocol_messages_sent"] = int(
        baseline["metrics"]["protocol_messages_sent"] * 1.5
    )
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        json.dump(regressed, f)
    assert gate.main(["--baseline", baseline_path, "--metrics", bad]) == 1


def test_gate_rejects_bad_schema(tmp_path):
    gate = _gate()
    path = str(tmp_path / "junk.json")
    with open(path, "w") as f:
        json.dump({"schema": "nope", "metrics": {}}, f)
    assert gate.main(["--metrics", path]) == 2


def test_gate_live_run_matches_committed_counts():
    """The gate's own seeded workload reproduces the committed deterministic
    counters exactly (this is what makes the CI gate meaningful)."""
    gate = _gate()
    fresh = gate.run_gate_bench()
    baseline_path = os.path.join(
        os.path.dirname(__file__), "..", "docs", "BENCH_BASELINE.json"
    )
    with open(baseline_path) as f:
        baseline = json.load(f)
    for name, value in baseline["metrics"].items():
        if gate.metric_kind(name) in ("count", "exact"):
            assert fresh["metrics"][name] == value, name


# ----------------------------------------------------------------------
# CLI: trace + stats
# ----------------------------------------------------------------------
def test_cli_trace_writes_valid_chrome_trace(tmp_path):
    from distributed_ghs_implementation_tpu.cli import main

    out = str(tmp_path / "trace.json")
    jsonl = str(tmp_path / "events.jsonl")
    assert main([
        "trace", "--nodes", "64", "--edges", "160", "--seed", "9",
        "--out", out, "--jsonl", jsonl,
    ]) == 0
    with open(out) as f:
        trace = json.load(f)
    names = {ev["name"] for ev in trace["traceEvents"]}
    assert "trace.session" in names
    assert "solver.level" in names  # per-level solver spans
    assert "protocol.messages_sent" in names  # protocol counter track
    assert os.path.exists(jsonl)


def test_cli_trace_captures_resilience_retries(tmp_path, monkeypatch):
    from distributed_ghs_implementation_tpu.cli import main
    from distributed_ghs_implementation_tpu.utils.resilience import FAULTS

    monkeypatch.setenv("GHS_FAULT_RESILIENCE_ATTEMPT_STEPPED", "1")
    out = str(tmp_path / "trace.json")
    try:
        assert main([
            "trace", "--nodes", "48", "--edges", "120",
            "--no-protocol-sample", "--out", out,
        ]) == 0
    finally:
        FAULTS.reset()
    with open(out) as f:
        trace = json.load(f)
    attempts = [
        ev["args"] for ev in trace["traceEvents"]
        if ev["name"] == "resilience.attempt"
    ]
    assert [a["outcome"] for a in attempts] == ["transient", "ok"]
    assert attempts[0]["site"] == "resilience.attempt.stepped"


def test_cli_stats_from_jsonl(tmp_path, capsys):
    from distributed_ghs_implementation_tpu.cli import main

    out = str(tmp_path / "trace.json")
    jsonl = str(tmp_path / "events.jsonl")
    assert main([
        "trace", "--nodes", "48", "--edges", "120", "--out", out,
        "--jsonl", jsonl,
    ]) == 0
    capsys.readouterr()
    assert main(["stats", "--input", jsonl]) == 0
    text = capsys.readouterr().out
    assert "solver.level" in text
    assert "protocol.messages_sent" in text
