"""MST solver models.

``boruvka`` is the flagship: the GHS protocol recast as batched Borůvka
graph contraction, fully on-device. ``ghs_protocol`` (see
``distributed_ghs_implementation_tpu/protocol``) is the message-level state
machine for protocol-parity testing against the reference.
"""

from distributed_ghs_implementation_tpu.models.boruvka import (
    BoruvkaState,
    boruvka_level,
    boruvka_solve,
    solve_graph,
)

__all__ = ["BoruvkaState", "boruvka_level", "boruvka_solve", "solve_graph"]
