"""Per-class SLO accounting: join the obs event stream into a latency report.

Every request the serving stack answers is tagged with a **query class** —
``hit`` / ``miss`` / ``batch`` / ``dup`` / ``update`` / ``oversize`` / ...
are the load-drill deck's classes, but the label is an open string. The tag
travels two ways at once:

* as the ``cls`` argument on the ``serve.request`` (and nested
  ``serve.solve``) spans — this module *joins* those events back into
  per-class counts and latency reservoirs, so a report is derivable from a
  live bus **or** an exported JSONL log, and
* as a thread-scoped context tag (:func:`tagged_class` /
  :func:`current_class`) that layers below the service — the scheduler,
  the batch engine's forming queue — read to attribute their own telemetry
  (e.g. ``batch.queue.wait_s.<cls>``) without any API threading.

The output schema (``ghs-slo-summary-v1``) is shared by ALL drills
(``tools/load_drill.py``, ``tools/serve_drill.py``, ``tools/batch_drill.py``)
so their reports compare field-for-field: per class ``sent`` / ``ok`` /
``errors`` / ``shed`` counts, ``goodput_per_sec`` (ok-responses per wall
second), and ``latency_s`` / ``solve_s`` / ``queue_wait_s`` reservoirs
(p50/p95/p99 via the repo-wide nearest-rank :func:`obs.events.quantile`).
``latency_s`` minus ``solve_s`` is the scheduling/queueing overhead a
closed-loop micro-bench never sees; ``queue_wait_s`` narrows it to the
batch engine's forming queue when lanes are on. The ``stream.*`` taxonomy
joins the same way: a ``publish`` request's ``stream.window`` span (the
window apply + durable-log append + notification) surfaces as
``window_s`` under its class, so a report decomposes notification latency
into commit cost vs routing/queueing.

A summary computed while the ring overflowed is *flagged*
(``dropped_warning``) — span-derived per-class counts under-count once
events fall off the ring, and a drill must surface that, not report a
silently rosier p99. Counter/histogram-derived fields survive overflow.

:func:`gate_metrics` flattens a summary into the ``ghs-bench-metrics-v1``
shape ``tools/bench_gate.py`` already understands (``*_s`` wall-times,
``*_per_sec`` throughput floors, bare-name counts), which is how the
``gate-load-v1`` baseline (``docs/BENCH_BASELINE_LOAD.json``) gates p99 and
goodput regressions in CI. See ``docs/LOAD_TESTING.md``.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Dict, Iterable, Optional

from distributed_ghs_implementation_tpu.obs.events import (
    PH_COMPLETE,
    EventBus,
    _Hist,
)

SCHEMA = "ghs-slo-summary-v1"

#: Histogram-name prefix the batch engine uses for per-class forming-queue
#: wait (``batch.queue.wait_s.<cls>``); summaries attach these per class.
QUEUE_WAIT_PREFIX = "batch.queue.wait_s."

_current_class: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "ghs_slo_class", default=None
)


def current_class() -> Optional[str]:
    """The query class tag of the current request context (or ``None``)."""
    return _current_class.get()


def sanitize_class(cls) -> Optional[str]:
    """Normalize an untrusted ``slo_class`` label to a short, dotted-name-
    safe token (it is interpolated into bus histogram names downstream).
    The ONE sanitizer both the single-process service and the fleet router
    apply, so a class gated in one mode reports identically in the other."""
    if cls is None:
        return None
    return "".join(
        ch if ch.isalnum() or ch in "_-" else "_" for ch in str(cls)
    )[:32] or "untagged"


@contextlib.contextmanager
def tagged_class(cls: Optional[str]):
    """Scope the current thread of work to query class ``cls``.

    ``None`` is a no-op (untagged traffic stays untagged). Context-local,
    so concurrent request threads never see each other's tags.
    """
    if cls is None:
        yield
        return
    token = _current_class.set(str(cls))
    try:
        yield
    finally:
        _current_class.reset(token)


# -- query kinds -------------------------------------------------------------
#
# The analytics front door (``analytics/``) serves five query *kinds* over
# the same stack: ``mst`` (the default), ``components``, ``k_msf``,
# ``bottleneck``, ``path_max``. Each kind gets a default SLO class so a
# request that names a kind but no ``slo_class`` still lands in a stable,
# per-kind latency bucket (and picks up any per-class verify policy the
# operator configured). ``mst`` maps to ``None`` on purpose: pre-analytics
# traffic must keep its historical untagged telemetry shape.

KIND_CLASS_DEFAULTS: Dict[str, Optional[str]] = {
    "mst": None,
    "components": "components",
    "k_msf": "k_msf",
    "bottleneck": "bottleneck",
    "path_max": "path_max",
}


def default_class_for_kind(kind) -> Optional[str]:
    """Default SLO class for a query ``kind`` (``None`` for ``mst``/unknown)."""
    return KIND_CLASS_DEFAULTS.get(str(kind)) if kind is not None else None


_current_kind: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "ghs_query_kind", default=None
)


def current_kind() -> Optional[str]:
    """The query kind of the current request context (``None`` == ``mst``).

    Like :func:`current_class` this is a thread/context-scoped side channel:
    the batch engine snapshots it at submit time so forming lanes stay
    kind-homogeneous without threading a ``kind`` argument through the
    scheduler API.
    """
    return _current_kind.get()


@contextlib.contextmanager
def tagged_kind(kind: Optional[str]):
    """Scope the current thread of work to query kind ``kind`` (``None`` no-op)."""
    if kind is None:
        yield
        return
    token = _current_kind.set(str(kind))
    try:
        yield
    finally:
        _current_kind.reset(token)


class ClassStats:
    """Per-class accumulator: outcome counts + latency/solve reservoirs.

    Feed it either from the event stream (:func:`ingest_bus_events` /
    :func:`ingest_jsonl_events`) or directly from client-side measurements
    (:meth:`observe`, the subprocess drills' path — they cannot see the
    server's bus, only their own stopwatches). Both roads end in the same
    :func:`assemble` summary schema.
    """

    def __init__(self):
        self._classes: Dict[str, dict] = {}
        self._total_latency = _Hist()
        # worker id -> per-class accumulator (fleet mode: the router stamps
        # ``worker`` on fleet.request spans; empty in single-process runs).
        self._workers: Dict[str, "ClassStats"] = {}

    # -- recording -----------------------------------------------------
    def _entry(self, cls: str) -> dict:
        entry = self._classes.get(cls)
        if entry is None:
            entry = self._classes[cls] = {
                "sent": 0,
                "ok": 0,
                "errors": 0,
                "shed": 0,
                "latency": _Hist(),
                "solve": _Hist(),
                "queue_wait": _Hist(),
                "window": _Hist(),
            }
        return entry

    def observe(
        self,
        cls: str,
        latency_s: Optional[float] = None,
        *,
        ok: bool = True,
        shed: bool = False,
    ) -> None:
        """One finished (or shed) request of class ``cls``."""
        entry = self._entry(cls)
        entry["sent"] += 1
        if shed:
            entry["shed"] += 1
        elif ok:
            entry["ok"] += 1
        else:
            entry["errors"] += 1
        if latency_s is not None:
            entry["latency"].add(float(latency_s))
            self._total_latency.add(float(latency_s))

    def observe_solve(self, cls: str, dur_s: float) -> None:
        """Solver/scheduler time attributed to class ``cls`` (the
        ``serve.solve`` span — cache hits never record one)."""
        self._entry(cls)["solve"].add(float(dur_s))

    def observe_queue_wait(self, cls: str, dur_s: float) -> None:
        self._entry(cls)["queue_wait"].add(float(dur_s))

    def observe_window(self, cls: str, dur_s: float) -> None:
        """Stream window-commit time attributed to class ``cls`` (the
        ``stream.window`` span — the apply+log+notify cost of one window,
        nested inside its publish request's end-to-end latency)."""
        self._entry(cls)["window"].add(float(dur_s))

    def observe_worker(
        self,
        worker: str,
        cls: str,
        latency_s: Optional[float] = None,
        *,
        ok: bool = True,
        shed: bool = False,
    ) -> None:
        """The same observation, attributed to one fleet worker — the
        per-worker SLO breakdown a kill drill reads to show the degraded
        worker's latency apart from its healthy siblings'."""
        sub = self._workers.get(worker)
        if sub is None:
            sub = self._workers[worker] = ClassStats()
        sub.observe(cls, latency_s, ok=ok, shed=shed)

    # -- reading -------------------------------------------------------
    def classes(self):
        return sorted(self._classes)

    def class_summary(self, cls: str, wall_s: Optional[float]) -> dict:
        entry = self._classes[cls]
        out = {
            "sent": entry["sent"],
            "ok": entry["ok"],
            "errors": entry["errors"],
            "shed": entry["shed"],
            "goodput_per_sec": (
                entry["ok"] / wall_s if wall_s else None
            ),
            "latency_s": entry["latency"].summary(),
        }
        for field, key in (
            ("solve", "solve_s"),
            ("queue_wait", "queue_wait_s"),
            ("window", "window_s"),
        ):
            if entry[field].count:
                out[key] = entry[field].summary()
        return out

    def workers_summary(self, wall_s: Optional[float]) -> Dict[str, dict]:
        """Per-worker per-class summaries (empty unless fleet spans fed in)."""
        return {
            worker: {
                "classes": {
                    cls: sub.class_summary(cls, wall_s)
                    for cls in sub.classes()
                },
                "totals": sub.totals(wall_s),
            }
            for worker, sub in sorted(self._workers.items())
        }

    def totals(self, wall_s: Optional[float]) -> dict:
        sent = sum(e["sent"] for e in self._classes.values())
        ok = sum(e["ok"] for e in self._classes.values())
        return {
            "sent": sent,
            "ok": ok,
            "errors": sum(e["errors"] for e in self._classes.values()),
            "shed": sum(e["shed"] for e in self._classes.values()),
            "goodput_per_sec": ok / wall_s if wall_s else None,
            "latency_s": self._total_latency.summary(),
        }


# ----------------------------------------------------------------------
# Joining the event stream
# ----------------------------------------------------------------------
def _ingest(
    stats: ClassStats, ph: str, name: str, dur_s: float, args: Optional[dict]
) -> None:
    """One event into the accumulator. The join key is the ``cls`` span
    argument the service stamps on ``serve.request`` (outcome + end-to-end
    latency) and the scheduler propagates onto ``serve.solve`` (the
    miss-path solve/queue time nested inside that request). In fleet mode
    the router's ``fleet.request`` span plays the serve.request role — its
    latency additionally includes routing, queueing, pipe transport, and
    any failover re-queue — and its ``worker`` argument feeds the
    per-worker breakdown."""
    if ph != PH_COMPLETE or not args:
        return
    cls = args.get("cls")
    if cls is None:
        return
    if name in ("serve.request", "fleet.request"):
        ok = bool(args.get("ok", True))
        shed = bool(args.get("shed", False))
        stats.observe(str(cls), dur_s, ok=ok, shed=shed)
        worker = args.get("worker")
        if name == "fleet.request" and worker is not None:
            stats.observe_worker(str(worker), str(cls), dur_s, ok=ok, shed=shed)
    elif name == "serve.solve":
        stats.observe_solve(str(cls), dur_s)
    elif name == "stream.window":
        # The stream taxonomy's class-attributed span: publish requests
        # tag their class, the session layer stamps it on the window
        # commit, and the join exposes it as ``window_s`` — per-class
        # commit cost next to end-to-end publish latency.
        stats.observe_window(str(cls), dur_s)


def ingest_bus_events(stats: ClassStats, events: Iterable[tuple]) -> None:
    """Live-bus record tuples (``obs.events.EventTuple`` layout)."""
    for ph, name, _cat, _ts_ns, dur_ns, _tid, args in events:
        _ingest(stats, ph, name, dur_ns / 1e9, args)


def window_class_waits(events: Iterable[tuple]) -> Dict[str, list]:
    """Per-class request durations from one SLICE of live-bus events.

    The elastic autoscaler's breach signal (``fleet/autoscaler.py``): each
    control tick it reads the events appended since its last mark
    (``BUS.events_since``) and joins the same spans the SLO report joins —
    ``fleet.request`` in fleet mode, ``serve.request`` single-process — by
    their ``cls`` argument. Returning the raw duration lists (seconds, not
    a reservoir) keeps the tick-window p99 exact: a reservoir over the
    whole run would remember breaches long after they healed, and
    hysteresis needs a *recent* signal. Untagged requests don't feed the
    breach check — the budgets are per-class by design (an operator who
    wants a fleet-wide budget tags a fleet-wide class).
    """
    out: Dict[str, list] = {}
    for ph, name, _cat, _ts_ns, dur_ns, _tid, args in events:
        if ph != PH_COMPLETE or not args:
            continue
        if name not in ("fleet.request", "serve.request"):
            continue
        cls = args.get("cls")
        if cls is None:
            continue
        out.setdefault(str(cls), []).append(dur_ns / 1e9)
    return out


def ingest_jsonl_events(stats: ClassStats, events: Iterable[dict]) -> None:
    """Event dicts as parsed by ``obs.export.read_events_jsonl``."""
    for rec in events:
        _ingest(
            stats,
            rec.get("ph"),
            rec.get("name"),
            rec.get("dur_us", 0.0) / 1e6,
            rec.get("args"),
        )


def assemble(
    stats: ClassStats,
    *,
    wall_s: Optional[float] = None,
    histograms: Optional[dict] = None,
    events_dropped: int = 0,
    lines_skipped: int = 0,
) -> dict:
    """A ``ghs-slo-summary-v1`` dict from an accumulator (+ the bus's
    aggregate histograms, which survive ring overflow — per-class queue
    wait rides in as ``batch.queue.wait_s.<cls>``)."""
    histograms = histograms or {}
    classes = {}
    for cls in stats.classes():
        summary = stats.class_summary(cls, wall_s)
        queue_hist = histograms.get(QUEUE_WAIT_PREFIX + cls)
        if queue_hist and queue_hist.get("count"):
            summary["queue_wait_s"] = queue_hist
        classes[cls] = summary
    out = {
        "schema": SCHEMA,
        "wall_s": wall_s,
        "events_dropped": events_dropped,
        "dropped_warning": events_dropped > 0,
        "classes": classes,
        "totals": stats.totals(wall_s),
    }
    workers = stats.workers_summary(wall_s)
    if workers:
        out["workers"] = workers
    if lines_skipped:
        out["lines_skipped"] = lines_skipped
    return out


def summarize_bus(bus: EventBus, *, wall_s: Optional[float] = None) -> dict:
    """Join a live bus's retained events into the per-class summary."""
    stats = ClassStats()
    ingest_bus_events(stats, bus.events())
    return assemble(
        stats,
        wall_s=wall_s,
        histograms=bus.histograms(),
        events_dropped=bus.dropped,
    )


def summarize_jsonl(path: str, *, wall_s: Optional[float] = None) -> dict:
    """Same summary, rebuilt offline from an exported JSONL event log."""
    from distributed_ghs_implementation_tpu.obs.export import read_events_jsonl

    events, meta = read_events_jsonl(path)
    stats = ClassStats()
    ingest_jsonl_events(stats, events)
    return assemble(
        stats,
        wall_s=wall_s,
        histograms=meta.get("histograms", {}),
        events_dropped=meta.get("events_dropped", 0),
        lines_skipped=meta.get("lines_skipped", 0),
    )


# ----------------------------------------------------------------------
# Bench-gate bridge
# ----------------------------------------------------------------------
def gate_metrics(
    summary: dict,
    *,
    workload: str,
    config: Optional[dict] = None,
    extra_metrics: Optional[dict] = None,
) -> dict:
    """Flatten an SLO summary into ``ghs-bench-metrics-v1`` for the gate.

    Per class: ``<cls>_p99_s`` (wall-time ceiling), ``<cls>_goodput_per_sec``
    (throughput floor), ``<cls>_errors`` / ``<cls>_shed`` (count ceilings —
    a zero baseline means ANY error fails). p50/p95 stay report-only: on
    shared CI runners sub-millisecond medians are nearly all scheduler
    noise, while the p99 tail and goodput are the SLO. ``extra_metrics``
    lets the drill add scenario-level facts (``lost_accepted`` gates
    exactly via ``bench_gate.KINDS``).
    """
    metrics: Dict[str, float] = {}
    for cls, c in summary.get("classes", {}).items():
        lat = c.get("latency_s") or {}
        if lat.get("count"):
            metrics[f"{cls}_p99_s"] = lat["p99"]
        if c.get("goodput_per_sec") is not None:
            metrics[f"{cls}_goodput_per_sec"] = c["goodput_per_sec"]
        metrics[f"{cls}_errors"] = c.get("errors", 0)
        metrics[f"{cls}_shed"] = c.get("shed", 0)
    totals = summary.get("totals", {})
    metrics["queries_sent"] = totals.get("sent", 0)
    if totals.get("goodput_per_sec") is not None:
        metrics["total_goodput_per_sec"] = totals["goodput_per_sec"]
    if extra_metrics:
        metrics.update(extra_metrics)
    return {
        "schema": "ghs-bench-metrics-v1",
        "config": {"workload": workload, **(config or {})},
        "metrics": metrics,
    }
