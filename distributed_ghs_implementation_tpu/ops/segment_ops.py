"""Segment reductions: the minimum-outgoing-edge (MOE) search as dense array ops.

One GHS level's TEST/ACCEPT/REJECT probing plus the REPORT convergecast
(``/root/reference/ghs_implementation.py:235-353``) is, in batched form, a
single question per fragment: *what is the minimum-weight edge leaving me?*
That is ONE ``segment_min`` over the directed edge list keyed by the source
endpoint's fragment id, comparing edges by a precomputed global *rank* — the
position in the host-side sort by ``(weight, edge id)`` (``Graph.rank_arrays``).
Rank is a total order on undirected edges, which makes the per-fragment choice
globally consistent — the property that confines union-find hook cycles to
mutual pairs — and it collapses weight comparison, tie-breaking, and edge
identification into a single int32 reduction.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def segment_min(data: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    """Per-segment minimum; empty segments get the dtype's identity (max/+inf)."""
    return jax.ops.segment_min(data, segment_ids, num_segments=num_segments)


def weight_sentinel(dtype) -> jax.Array:
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.integer):
        return jnp.asarray(jnp.iinfo(dtype).max, dtype)
    return jnp.asarray(jnp.inf, dtype)


INT32_MAX = jnp.iinfo(jnp.int32).max


def fragment_moe(
    fragment: jax.Array,
    src: jax.Array,
    dst: jax.Array,
    rank: jax.Array,
    ra: jax.Array,
    rb: jax.Array,
    *,
    axis_name: str | None = None,
    identity_fragment: bool = False,
    kernel: str = "xla",
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-fragment minimum outgoing edge over (optionally sharded) edge slots.

    Edges are compared by their precomputed global rank (total order on
    ``(weight, edge id)``, built host-side in ``Graph.rank_arrays``), so the
    whole MOE search is ONE ``segment_min`` plus cheap n-sized lookups —
    weights never reach the device.

    Args:
      fragment: ``[n]`` int32, fragment id per vertex (always a root id).
      src, dst: ``[e]`` int32 directed slot endpoints (the local shard when
        ``axis_name`` is set).
      rank: ``[e]`` int32 global rank of each slot's undirected edge
        (INT32_MAX on padding slots).
      ra, rb: endpoints of the rank-``r`` undirected edge, indexed by rank
        (sharded by contiguous rank blocks when ``axis_name`` is set).
      axis_name: if set, combine per-fragment minima across this mesh axis
        with ``lax.pmin`` — the ICI replacement for the reference's MPI
        point-to-point REPORT convergecast.
      kernel: ``"pallas"`` fuses the two fragment gathers + the alive-mask
        rank select into one VMEM pass (``ops.pallas_kernels.
        fused_gather_key``) on non-identity partitions; guarded geometries
        and ``"xla"`` take the plain gather/select form. Identical results
        either way.

    Returns:
      ``(has_moe[n], moe_rank[n], moe_dst_frag[n])`` — whether each fragment
      has an outgoing edge, the winning edge's rank (INT32_MAX when none), and
      the fragment on the far side.
    """
    n = fragment.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)

    if identity_fragment:
        # Level 0: fragment == iota, so the relabel gathers are identity.
        f_src, f_dst = src, dst
        key = jnp.where(f_src != f_dst, rank, INT32_MAX)
    else:
        from distributed_ghs_implementation_tpu.ops import pallas_kernels as pk

        if kernel == "pallas" and pk.flat_shape_ok(n, src.shape[0]):
            f_src, key = pk.fused_gather_key(fragment, src, dst, rank)
        else:
            f_src = fragment[src]
            f_dst = fragment[dst]
            key = jnp.where(f_src != f_dst, rank, INT32_MAX)
    moe_rank = segment_min(key, f_src, n)
    if axis_name is not None:
        moe_rank = jax.lax.pmin(moe_rank, axis_name)
    has_moe = moe_rank < INT32_MAX

    # Far-side fragment of the winning edge via its endpoints. Single device:
    # direct n-sized gathers through (ra, rb). Sharded: the shard owning the
    # winning rank block proposes both endpoint fragments; pmin selects them.
    if axis_name is None:
        safe = jnp.where(has_moe, moe_rank, 0)
        fa = fragment[ra[safe]]
        fb = fragment[rb[safe]]
    else:
        m_local = ra.shape[0]
        shard = jax.lax.axis_index(axis_name).astype(jnp.int32)
        local = moe_rank - shard * m_local
        mine = has_moe & (local >= 0) & (local < m_local)
        safe = jnp.where(mine, local, 0)
        fa = jax.lax.pmin(jnp.where(mine, fragment[ra[safe]], INT32_MAX), axis_name)
        fb = jax.lax.pmin(jnp.where(mine, fragment[rb[safe]], INT32_MAX), axis_name)
    moe_dst_frag = jnp.where(has_moe, jnp.where(fa == ids, fb, fa), ids)
    return has_moe, moe_rank, moe_dst_frag
