"""Horizontal serving fleet: digest-routed worker processes with failover.

The single-process serving stack (``serve/`` + ``batch/``) caps out at one
Python process and loses every in-flight query when it crashes. ``fleet/``
lifts it horizontal: N worker subprocesses (``fleet/worker.py``), each a
full :class:`serve.service.MSTService`, behind a consistent-hash router
(``fleet/router.py``) with health-checked failover, re-queue of accepted
requests, restart-with-backoff, admission control, and graceful drain.
``docs/FLEET.md`` covers topology, failure modes, and drill recipes.
"""

from distributed_ghs_implementation_tpu.fleet.hashing import HashRing
from distributed_ghs_implementation_tpu.fleet.router import (
    FleetConfig,
    FleetRouter,
)

__all__ = ["FleetConfig", "FleetRouter", "HashRing"]
