"""Runtime verification: MST certificates, serving policy, async audit.

The survey's critical finding about the reference implementation is that it
was only *probabilistically* correct — at 20 nodes its deadlock-escape
heuristics silently produced a wrong MST (weight 57 vs 53) in 2 of 3 runs,
and nothing in its serving path could have noticed. This package is the
missing trust layer: every served result can be *certified* against the
input graph in O(m α + m log n) — orders of magnitude cheaper than
re-solving and, crucially, through an independent code path (union-find +
binary-lifting path-max, never the Borůvka kernels), so a miscompiled
kernel, a bit-rotted cache entry, or a corrupted forwarded payload cannot
co-sign its own wrong answer.

* :mod:`verify.certify` — the certificate checker itself (``docs/
  VERIFICATION.md`` has the semantics).
* :mod:`verify.policy` — the ``off|sample|full`` per-SLO-class serving
  policy, the background audit thread, and the serve-side glue that
  corrects a failed certificate transparently (evict + re-solve).

Import discipline: this package must stay importable without jax — the
fleet router (jax-free in echo drills) certifies forwarded payloads with
the numpy engine; the XLA engine loads lazily on first use.
"""

from distributed_ghs_implementation_tpu.verify.certify import (  # noqa: F401
    Certificate,
    certify_claim,
    certify_edge_ids,
    certify_result,
)
from distributed_ghs_implementation_tpu.verify.policy import (  # noqa: F401
    AsyncAuditor,
    ResultVerifier,
    VerifyPolicy,
)
