"""The graph query service: a JSONL request/response loop over the serve stack.

One JSON object per input line, one JSON response line per request — a
protocol a test, the chaos drill, or a thin network front-end can all drive
(``ghs serve`` wires it to stdin/stdout). The full protocol, op by op:

* ``{"op": "solve", "num_nodes": N, "edges": [[u, v, w], ...]}`` — or
  ``{"op": "solve", "graph_path": "graph.npz"}`` — optional ``"backend"``,
  ``"edges_out": true`` to include the answer's edge list in the response,
  ``"cached_only": true`` to probe this host's cache by ``"digest"`` alone
  (the fleet router's forwarding probe — a miss answers ``{"ok": false,
  "cache_miss": true}`` without solving). Response carries the graph
  ``digest`` (the handle updates key on) and ``source``: ``"cache"`` /
  ``"coalesced"`` / ``"solved"``.

  An optional ``"kind"`` field selects the analytics query kind
  (``analytics/kinds.py``, docs/ANALYTICS.md) — every kind runs the same
  GHS level loop and caches under a per-kind digest key:

  - ``{"op": "solve", "kind": "mst", ...}`` — the default; the minimum
    spanning forest.
  - ``{"op": "solve", "kind": "components", ...}`` — connected components
    via the weight-free solve; response adds exact ``num_components`` and,
    with ``"labels_out": true``, the per-node ``labels`` array.
  - ``{"op": "solve", "kind": "k_msf", "k": 3, ...}`` — the optimal
    ``k``-forest (lightest ``n - max(k, c)`` MSF edges); response echoes
    ``k``.
  - ``{"op": "solve", "kind": "bottleneck", ...}`` — minimum bottleneck
    spanning value; response adds ``bottleneck_weight`` +
    ``bottleneck_edge``.
  - ``{"op": "solve", "kind": "path_max", "u": 0, "v": 7, ...}`` — the
    minimax (bottleneck-optimal) edge between two nodes; response adds
    ``connected``, ``path_max_weight``, ``path_max_edge``.

* ``{"op": "update", "digest": "...", "updates": [{"kind": "insert",
  "u": 1, "v": 2, "w": 5}, {"kind": "delete", "u": 3, "v": 4}, ...]}`` —
  incremental maintenance against the session for ``digest``; the response
  carries the *new* digest (sessions re-key content-addressed) and ``mode``
  (``"incremental"`` or ``"resolve"``).
* ``{"op": "subscribe", "digest": "..."}`` (or ``"stream": id`` to resume)
  — pin a long-lived stream to a solved graph; the response carries the
  ``stream`` id, current head ``digest``, and head ``seq``
  (``stream/session.py``, docs/STREAMING.md).
* ``{"op": "publish", "stream": id, "digest": head, "updates": [...]}`` —
  commit one update window against the stream head: coalesced, applied in
  one batched pass, appended to the durable log, and notified. The
  response carries the new head ``digest`` + ``prev_digest`` (the fleet
  router follows the chain) and the window's MST-change ``notification``.
  A stale head fails with ``"stale": true`` plus the current head/seq.
* ``{"op": "poll", "stream": id, "after_seq": N}`` — drain MST-change
  notifications with ``seq > N`` (edges entered/left the forest, weight
  delta — gapless, duplicate-free, failover-surviving sequence numbers).
* ``{"op": "stats"}`` — serve counters from the ``obs`` bus + store stats.
* ``{"op": "shutdown"}`` — acknowledge and end the loop (EOF also ends it).

Every request may carry ``"slo_class"`` (per-class latency accounting and
verify policy, ``obs/slo.py``); a ``kind`` query without one lands in its
kind's default class. Errors never kill the loop: a malformed line or a
failed request produces ``{"ok": false, "error": ...}`` and the loop reads
on.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import threading
from typing import IO, Optional

from distributed_ghs_implementation_tpu.api import MSTResult
from distributed_ghs_implementation_tpu.batch.warmup import (
    bucket_of,
    warmable_single,
)
from distributed_ghs_implementation_tpu.fleet.framing import (
    SECTIONS_KEY,
    FrameError,
    WireSections,
    encode_bframe,
    encode_frame,
    fold_sections,
    frame_sections,
    read_frame,
)
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.obs import tracing
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.obs.slo import (
    default_class_for_kind,
    sanitize_class,
    tagged_class,
    tagged_kind,
)
from distributed_ghs_implementation_tpu.serve.dynamic import DynamicMST
from distributed_ghs_implementation_tpu.serve.scheduler import SolveScheduler
from distributed_ghs_implementation_tpu.serve.store import (
    ResultStore,
    cache_key_for_digest,
    solve_cache_key,
)

_MAX_SESSIONS = 32  # update handles retained (LRU); results outlive them

#: The protocol's op set — the dispatch table and the unknown-op error both
#: derive from this one tuple so the message can never drift out of date
#: again (it once predated several ops).
_OPS = ("solve", "update", "subscribe", "publish", "poll", "stats",
        "shutdown")


class MSTService:
    """Request handler: solve through the scheduler, update through
    per-digest :class:`DynamicMST` sessions, everything cached in the store."""

    def __init__(
        self,
        *,
        backend: str = "device",
        store: Optional[ResultStore] = None,
        store_capacity: int = 128,
        disk_dir: Optional[str] = None,
        max_concurrent: int = 2,
        resolve_threshold: Optional[int] = None,
        max_sessions: int = _MAX_SESSIONS,
        batch_lanes: int = 0,
        batch_wait_s: Optional[float] = None,
        warmup=None,
        sharded_lane=False,
        stream_dir: Optional[str] = None,
        stream_snapshot_every: int = 8,
        stream_window_mode: str = "batched",
        max_streams: Optional[int] = None,
        verify=None,
    ):
        self.store = store if store is not None else ResultStore(
            capacity=store_capacity, disk_dir=disk_dir
        )
        # batch_lanes > 0 attaches the lane engine: device-backend cache
        # misses coalesce into multi-graph batches (batch/engine.py).
        engine = None
        if batch_lanes > 0:
            from distributed_ghs_implementation_tpu.batch.engine import BatchEngine
            from distributed_ghs_implementation_tpu.batch.policy import BatchPolicy

            # batch_wait_s widens the forming window for lane-mates (the
            # load drill uses a wider window than the 2 ms production
            # default so open-loop burst arrivals actually share lanes).
            policy_kwargs = {"max_lanes": batch_lanes}
            if batch_wait_s is not None:
                policy_kwargs["max_wait_s"] = batch_wait_s
            engine = BatchEngine(policy=BatchPolicy(**policy_kwargs))
        # sharded_lane opens the oversize route: device-backend misses past
        # the batch admission ceiling run on a mesh (parallel/lane.py —
        # device-resident LRU, donated updates) instead of bypassing to
        # the single-device path. True = all devices; an int = that many.
        lane = None
        if sharded_lane:
            from distributed_ghs_implementation_tpu.parallel.lane import (
                ShardedLane,
            )
            from distributed_ghs_implementation_tpu.parallel.mesh import (
                edge_mesh,
            )

            num = None if sharded_lane is True else int(sharded_lane)
            lane = ShardedLane(edge_mesh(num_devices=num))
        self.sharded_lane = lane
        self.scheduler = SolveScheduler(
            self.store, backend=backend, max_concurrent=max_concurrent,
            batch_engine=engine, sharded_lane=lane,
        )
        self.backend = backend
        self.resolve_threshold = resolve_threshold
        self.max_sessions = max_sessions
        # Subscription streams (stream/): long-lived windowed sessions with
        # a durable log under stream_dir (shared across fleet workers, so a
        # restarted worker replays instead of re-solving). The full-resolve
        # escape hatch routes through the scheduler — cached, supervised,
        # single-flighted — and window commits register with the priority
        # gate so bulk mesh solves yield to them. Deferred import: the
        # stream package reaches serve/__init__ (window -> serve.dynamic),
        # which imports this module — a top-level import here deadlocks
        # that chain when stream loads first.
        from distributed_ghs_implementation_tpu.stream.session import (
            StreamManager,
        )

        # Result verification (round 19, docs/VERIFICATION.md): an
        # off|sample|full policy per SLO class. ``full`` classes certify
        # inline with transparent correction (the poisoned entry leaves
        # store + sessions + residency, the graph re-solves fresh, the
        # corrected answer is the one served); ``sample`` classes ride
        # the async audit thread. ``verify`` accepts a spec string or a
        # prebuilt verify.policy.VerifyPolicy. Built BEFORE the stream
        # manager so sharded stream commits can ride the same auditor.
        self.verifier = None
        if verify:
            from distributed_ghs_implementation_tpu.verify.policy import (
                ResultVerifier,
                VerifyPolicy,
            )

            policy = VerifyPolicy.parse(verify)
            if policy.enabled:
                self.verifier = ResultVerifier(
                    policy,
                    invalidate=self._invalidate_entry,
                    resolve=self._fresh_resolve,
                )
        stream_kwargs = {}
        if max_streams is not None:
            stream_kwargs["max_streams"] = max_streams
        self.streams = StreamManager(
            root=stream_dir,
            snapshot_every=stream_snapshot_every,
            backend=backend,
            resolve_threshold=resolve_threshold,
            window_mode=stream_window_mode,
            solver=lambda g: self.scheduler.solve(g, backend=backend)[0],
            interactive_gate=self.scheduler.interactive,
            # The sharded-stream fusion: oversize streams keep their heads
            # mesh-resident (pinned, donated window scatters, replay
            # re-staging) and their post-window heads audited
            # (stream/session.py module docstring).
            lane=lane,
            verifier=self.verifier,
            **stream_kwargs,
        )
        # digest -> DynamicMST (materialized by an update) or a lightweight
        # (result, backend) seed (parked by a solve).
        self._sessions: "collections.OrderedDict[str, object]" = (
            collections.OrderedDict()
        )
        # Shape buckets traffic actually hit (insertion-ordered) — the
        # warmup record's input, so even a no-batch-engine serve records
        # what a restart should warm (single-graph kernels).
        self.seen_buckets: "collections.OrderedDict[tuple, None]" = (
            collections.OrderedDict()
        )
        # Warmup phase: precompile the declared buckets BEFORE the first
        # request, so a pre-declared bucket's first query runs against an
        # already-compiled executable (compile.warmup vs compile.miss on
        # the bus tells warm from cold — docs/SERVING.md "Warmup").
        self.warmup_report = None
        if warmup is not None:
            from distributed_ghs_implementation_tpu.batch.warmup import (
                WarmupPlan,
                run_warmup,
            )

            if not isinstance(warmup, WarmupPlan):
                raise TypeError(
                    f"warmup must be a batch.warmup.WarmupPlan, got "
                    f"{type(warmup).__name__}"
                )
            # Normalize the plan to THIS service's lane geometry: replayed
            # keys recorded at a different --batch-lanes (or declared bare
            # shape buckets) must warm the solvers this process actually
            # dispatches — otherwise the first query pays a request-time
            # compile despite warmup "succeeding".
            shapes = tuple(dict.fromkeys(
                tuple(warmup.buckets)
                + tuple((n, m) for n, m, _, _ in warmup.keys)
            ))
            warmup = dataclasses.replace(
                warmup, buckets=shapes, keys=(), lanes=batch_lanes,
                mode=engine.policy.mode if engine else "fused",
            )
            # Mesh-shaped buckets warm on the sharded lane (the oversize
            # path's AOT coverage); without a lane they are skipped, the
            # same way oversize shape buckets skip the fused kernel warm.
            self.warmup_report = run_warmup(warmup, lane=lane)

    # ------------------------------------------------------------------
    def handle(self, request: dict) -> dict:
        # Deferred for the same serve <-> stream import cycle as the
        # StreamManager import in __init__ — by the first request both
        # packages are fully loaded, so this is a sys.modules lookup.
        from distributed_ghs_implementation_tpu.stream.session import (
            StaleDigest,
        )

        op = request.get("op")
        # Analytics query kind: "mst" (the historical default) unless the
        # solve names another registered kind. Counted per kind
        # (serve.kind.<kind>) and propagated context-scoped (tagged_kind)
        # so the batch engine keeps forming lanes kind-homogeneous.
        kind = str(request.get("kind", "mst")) if op == "solve" else None
        # SLO class tag: clients label each query ("hit"/"miss"/"update"/
        # ...); the label rides the serve.request span args (what
        # obs.slo joins per-class reports from) AND the thread-scoped
        # tagged_class context, so nested layers (scheduler serve.solve
        # spans, the batch engine's queue-wait histograms) attribute their
        # telemetry to the same class without any API threading. A kind
        # query without an explicit class falls into its kind's default
        # class (obs.slo.KIND_CLASS_DEFAULTS) — mst stays untagged.
        cls = sanitize_class(request.get("slo_class"))
        if cls is None and kind is not None:
            cls = default_class_for_kind(kind)
        span_args = {"op": str(op)}
        if kind is not None and kind != "mst":
            span_args["kind"] = kind
        if cls is not None:
            span_args["cls"] = cls
        with tagged_class(cls), tagged_kind(kind), tracing.front_door(
            cls
        ), BUS.span("serve.request", cat="serve", **span_args) as span:
            BUS.count("serve.requests")
            try:
                if op == "solve":
                    from distributed_ghs_implementation_tpu import (
                        analytics,
                    )

                    # Unknown kinds raise the registry's ValueError before
                    # any solving; known kinds count per kind.
                    analytics.get(kind)
                    BUS.count(f"serve.kind.{kind}")
                    response = self._handle_solve(request)
                elif op == "update":
                    response = self._handle_update(request)
                elif op == "subscribe":
                    response = self._handle_subscribe(request)
                elif op == "publish":
                    response = self._handle_publish(request)
                elif op == "poll":
                    response = self._handle_poll(request)
                elif op == "stats":
                    response = self._handle_stats()
                elif op == "shutdown":
                    response = {"ok": True, "op": "shutdown"}
                else:
                    raise ValueError(
                        f"unknown op {op!r}; expected {'|'.join(_OPS)}"
                    )
            except StaleDigest as e:
                # Not an error so much as a re-sync point: the client's
                # head lost a race (or a failover replayed past it); the
                # response carries the current head so it can catch up
                # without re-solving.
                response = {
                    "ok": False, "op": op, "stale": True,
                    "error": f"StaleDigest: {e}",
                    "stream": e.stream_id, "digest": e.head, "seq": e.seq,
                }
            except Exception as e:  # noqa: BLE001 — the loop must survive
                BUS.count("serve.errors")
                response = {
                    "ok": False, "op": op, "error": f"{type(e).__name__}: {e}",
                }
            span.set(ok=bool(response.get("ok")))
            source = response.get("source") or response.get("mode")
            if source:
                span.set(source=source)
            if cls is not None:
                response.setdefault("slo_class", cls)
            return response

    # -- verification hooks (round 19) ---------------------------------
    def _invalidate_entry(self, key: Optional[str], digest: str) -> None:
        """Purge a certificate-failing result EVERYWHERE it could be
        served from again: store memory + disk (quarantined), the parked
        update-session seed (it aliases the same arrays), and any mesh
        residency for the digest."""
        if key is not None:
            self.store.invalidate(key, reason="certificate failed")
        entry = self._sessions.get(digest)
        if entry is not None and not isinstance(entry, DynamicMST):
            del self._sessions[digest]
        if self.sharded_lane is not None:
            evict = getattr(self.sharded_lane, "evict", None)
            if evict is not None:
                evict(digest)

    def _fresh_resolve(self, graph: Graph, backend: str) -> MSTResult:
        """The correction re-solve: by the time this runs the poisoned
        entry is invalidated, so the scheduler misses and solves fresh
        (supervised, single-flighted — the normal miss machinery)."""
        result, _source = self.scheduler.solve(graph, backend=backend)
        return result

    # ------------------------------------------------------------------
    def _handle_solve(self, request: dict) -> dict:
        if request.get("cached_only"):
            return self._handle_cached_probe(request)
        kind = str(request.get("kind", "mst"))
        if kind != "mst":
            return self._handle_analytics(request, kind)
        graph = self._load_graph(request)
        backend = request.get("backend", self.backend)
        bucket = bucket_of(graph.num_nodes, graph.num_edges)
        if warmable_single(*bucket):
            # Oversize buckets route to the rank solver, not the fused
            # kernel warmup compiles — recording them would make replay
            # pay boot-time compiles no request ever hits.
            self.seen_buckets[bucket] = None
        result, source = self.scheduler.solve(graph, backend=backend)
        digest = graph.digest()
        verified = None
        if self.verifier is not None:
            # Per-policy certification of EVERY solve answer — cache hits
            # included (a bit-rotted or memory-corrupted cached result is
            # precisely what nothing upstream can notice). A failed
            # inline certificate is corrected transparently; the client
            # sees only the corrected result (+ the verify.* counters).
            result, verified = self.verifier.check(
                result,
                cls=sanitize_class(request.get("slo_class")),
                key=solve_cache_key(graph, backend=backend),
                backend=backend,
            )
        self._remember(digest, result, backend)
        out = {
            "ok": True,
            "op": "solve",
            "digest": digest,
            "source": source,
            "cached": source != "solved",
        }
        if verified is not None:
            out["verified"] = verified
        out.update(self._result_fields(result, request))
        return out

    def _handle_analytics(self, request: dict, kind: str) -> dict:
        """A non-``mst`` solve: dispatch through the analytics registry.

        Every kind rides the normal scheduler path (single-flight dedup,
        admission, batch lanes, the sharded oversize lane, supervision) —
        ``components`` by solving the graph's index-weighted twin, the
        rest by deriving from the graph's own MSF (which therefore shares
        the ``mst`` cache entry; cross-kind affinity is deliberate).
        Cacheable kinds store under their per-kind digest key, and — like
        the mst path — every *served* answer is certified per policy with
        the kind's own adapter, corrected transparently on failure.
        """
        from distributed_ghs_implementation_tpu import analytics
        from distributed_ghs_implementation_tpu.analytics import (
            solvers as asolvers,
        )
        from distributed_ghs_implementation_tpu.verify.certify import (
            certify_components,
            certify_k_forest,
        )

        params = analytics.parse_params(kind, request)
        graph = self._load_graph(request)
        backend = request.get("backend", self.backend)
        digest = graph.digest()
        cls = sanitize_class(request.get("slo_class"))
        if cls is None:
            cls = analytics.get(kind).slo_class
        bucket = bucket_of(graph.num_nodes, graph.num_edges)
        if warmable_single(*bucket):
            self.seen_buckets[bucket] = None

        def solve(g):
            return self.scheduler.solve(g, backend=backend)

        token = analytics.cache_token(kind, k=params.get("k"))
        kind_key = (
            cache_key_for_digest(digest, backend=backend, kind=token)
            if token is not None else None
        )
        mst_key = cache_key_for_digest(digest, backend=backend)
        verified = None
        extra: dict = {}

        if kind == "components":
            result = self.store.get(kind_key, graph)
            source = "cache"
            if result is None:
                result, source = asolvers.solve_components(graph, solve)
                self.store.put(kind_key, result)
            if self.verifier is not None:
                def _rederive_components() -> MSTResult:
                    # The poison may live in the connectivity twin's own
                    # cache entry — purge it so the re-solve is honest.
                    twin = asolvers.connectivity_graph(graph)
                    self.store.invalidate(
                        solve_cache_key(twin, backend=backend),
                        reason="kind rederive",
                    )
                    fresh, _src = asolvers.solve_components(graph, solve)
                    self.store.put(kind_key, fresh)
                    return fresh

                result, verified = self.verifier.check(
                    result, cls=cls, key=kind_key, backend=backend,
                    certify=lambda r, engine: certify_components(
                        r.graph, r.edge_ids, engine=engine,
                        expect_components=r.num_components,
                    ),
                    rederive=_rederive_components,
                )
            if request.get("labels_out"):
                labels = asolvers.labels_for_forest(result)
                if SECTIONS_KEY in request:
                    extra[SECTIONS_KEY] = WireSections().add(
                        "labels", labels
                    )
                else:
                    extra["labels"] = labels.tolist()
        elif kind == "k_msf":
            k = params["k"]
            result = self.store.get(kind_key, graph)
            source = "cache"
            if result is None:
                result, source, full = asolvers.solve_k_msf(graph, solve, k)
                self._remember(digest, full, backend)
                self.store.put(kind_key, result)
            if self.verifier is not None:
                def _rederive_k_msf() -> MSTResult:
                    # Trimming is local; a bad k-forest implicates the
                    # underlying MSF entry, so purge that too.
                    self.store.invalidate(mst_key, reason="kind rederive")
                    fresh, _src, full = asolvers.solve_k_msf(
                        graph, solve, k
                    )
                    self._remember(digest, full, backend)
                    self.store.put(kind_key, fresh)
                    return fresh

                result, verified = self.verifier.check(
                    result, cls=cls, key=kind_key, backend=backend,
                    certify=lambda r, engine: certify_k_forest(
                        r.graph, r.edge_ids, k, engine=engine,
                    ),
                    rederive=_rederive_k_msf,
                )
            extra["k"] = k
        else:
            # bottleneck / path_max: scalar reductions over the graph's
            # own (certified) MSF — never separately store-cached; the
            # shared mst entry is the cache.
            result, source = solve(graph)
            if self.verifier is not None:
                result, verified = self.verifier.check(
                    result, cls=cls, key=mst_key, backend=backend,
                )
            self._remember(digest, result, backend)
            if kind == "bottleneck":
                bn = asolvers.bottleneck_of(result)
                extra["bottleneck_weight"] = None if bn is None else bn[0]
                extra["bottleneck_edge"] = (
                    None if bn is None else [bn[1], bn[2]]
                )
            else:  # path_max
                ans = asolvers.path_max_of(result, params["u"], params["v"])
                extra.update({
                    "u": params["u"], "v": params["v"],
                    "connected": ans["connected"],
                    "path_max_weight": ans["weight"],
                    "path_max_edge": (
                        None if ans["edge"] is None else list(ans["edge"])
                    ),
                })

        out = {
            "ok": True,
            "op": "solve",
            "kind": kind,
            "digest": digest,
            "source": source,
            "cached": source != "solved",
        }
        if verified is not None:
            out["verified"] = verified
        out.update(self._result_fields(result, request))
        self._merge_fields(out, extra)
        return out

    def _handle_cached_probe(self, request: dict) -> dict:
        """A ``cached_only`` solve: answer from the store (memory LRU, or
        this host's disk layer) by digest alone — never solve. This is the
        fleet router's cross-host forwarding probe: the frame carries only
        the digest (no edge list), so a hit ships one cached result over
        the wire and a miss costs a single tiny round trip before the
        dispatch target solves locally (``docs/FLEET.md``).

        Probes are kind-aware: a ``kind`` probe answers from its own
        per-kind key (never the mst entry — kind-correctness is the whole
        point of the per-kind keys), and the derived kinds (``k_msf``,
        ``bottleneck``, ``path_max``) additionally fall back to *deriving*
        from the cached mst entry — O(tree) host work, honoring the
        never-solve contract."""
        digest = request.get("digest")
        if not digest:
            raise ValueError("cached_only solve needs a digest")
        kind = str(request.get("kind", "mst"))
        backend = request.get("backend", self.backend)
        if kind != "mst":
            return self._kind_probe(request, kind, str(digest), backend)
        result = self.store.get(
            cache_key_for_digest(str(digest), backend=backend),
            record_miss=False,
        )
        BUS.count("serve.probe.hit" if result is not None
                  else "serve.probe.miss")
        if result is None:
            # Not an error: the probing router falls back to a local
            # solve, so this must not land in serve.errors.
            return {"ok": False, "op": "solve", "digest": digest,
                    "cache_miss": True,
                    "error": f"cache_miss: {digest} not cached here"}
        out = {
            "ok": True,
            "op": "solve",
            "digest": digest,
            "source": "cache",
            "cached": True,
        }
        out.update(self._result_fields(result, request))
        return out

    def _kind_probe(
        self, request: dict, kind: str, digest: str, backend: str
    ) -> dict:
        """The non-``mst`` arm of :meth:`_handle_cached_probe`."""
        from distributed_ghs_implementation_tpu import analytics
        from distributed_ghs_implementation_tpu.analytics import (
            solvers as asolvers,
        )

        params = analytics.parse_params(kind, request)
        token = analytics.cache_token(kind, k=params.get("k"))
        extra: dict = {}
        result = None
        if token is not None:
            result = self.store.get(
                cache_key_for_digest(digest, backend=backend, kind=token),
                record_miss=False,
            )
        if result is None and kind in ("k_msf", "bottleneck", "path_max"):
            # Derivable kinds: a cached mst entry answers without solving.
            # components is NOT derived here — its canonical cache entry is
            # the connectivity forest, and a probe must never plant a
            # different edge set under the kind key.
            mst_cached = self.store.get(
                cache_key_for_digest(digest, backend=backend),
                record_miss=False,
            )
            if mst_cached is not None:
                if kind == "k_msf":
                    result = asolvers.trim_to_k_forest(
                        mst_cached, params["k"]
                    )
                    self.store.put(
                        cache_key_for_digest(
                            digest, backend=backend, kind=token
                        ),
                        result,
                        memory_only=True,
                    )
                else:
                    result = mst_cached
        BUS.count("serve.probe.hit" if result is not None
                  else "serve.probe.miss")
        if result is None:
            return {"ok": False, "op": "solve", "kind": kind,
                    "digest": digest, "cache_miss": True,
                    "error": f"cache_miss: {digest} ({kind}) "
                             f"not cached here"}
        if kind == "k_msf":
            extra["k"] = params["k"]
        elif kind == "bottleneck":
            bn = asolvers.bottleneck_of(result)
            extra["bottleneck_weight"] = None if bn is None else bn[0]
            extra["bottleneck_edge"] = None if bn is None else [bn[1], bn[2]]
        elif kind == "path_max":
            ans = asolvers.path_max_of(result, params["u"], params["v"])
            extra.update({
                "u": params["u"], "v": params["v"],
                "connected": ans["connected"],
                "path_max_weight": ans["weight"],
                "path_max_edge": (
                    None if ans["edge"] is None else list(ans["edge"])
                ),
            })
        elif kind == "components" and request.get("labels_out"):
            labels = asolvers.labels_for_forest(result)
            if SECTIONS_KEY in request:
                extra[SECTIONS_KEY] = WireSections().add("labels", labels)
            else:
                extra["labels"] = labels.tolist()
        out = {
            "ok": True,
            "op": "solve",
            "kind": kind,
            "digest": digest,
            "source": "cache",
            "cached": True,
        }
        out.update(self._result_fields(result, request))
        self._merge_fields(out, extra)
        return out

    def _handle_update(self, request: dict) -> dict:
        digest = request.get("digest")
        entry = self._sessions.get(digest) if digest else None
        if entry is None:
            raise KeyError(
                f"no session for digest {digest!r} (solve the graph first; "
                f"{len(self._sessions)} sessions live)"
            )
        if not isinstance(entry, DynamicMST):
            # Lazy materialization: solves park a (result, backend) seed —
            # the O(m) session arrays are only built for graphs that
            # actually receive updates, never on the query-only warm path.
            seed_result, seed_backend = entry
            entry = DynamicMST(
                seed_result,
                resolve_threshold=self.resolve_threshold,
                backend=seed_backend,
            )
            self._sessions[digest] = entry
        session = entry
        self._sessions.move_to_end(digest)
        try:
            result = session.apply(request.get("updates", []))
        except Exception:
            if session.dirty:
                # The apply failed mid-batch — a state no client has seen.
                # Drop the session; the next update for this digest needs a
                # fresh solve first (usually a cache hit). Pre-mutation
                # failures (validation) leave the session usable.
                del self._sessions[digest]
                BUS.count("serve.sessions.poisoned")
            raise
        new_digest = result.graph.digest()
        # Re-key content-addressed: the session now answers for the updated
        # graph, and the updated result is cached for future solve requests.
        del self._sessions[digest]
        self._sessions[new_digest] = session
        if self.sharded_lane is not None:
            # Migrate any device residency along the digest chain: the
            # changed rank slots scatter into the resident (donated)
            # buffers, so a later re-solve of the updated oversize graph
            # stays dispatch-only. A no-op unless the old digest was
            # actually resident on the mesh.
            self.sharded_lane.refresh_resident(digest, result.graph)
        # Cache under the backend the session's solves used (a client pinned
        # to a non-default backend must hit this entry on its next solve).
        self.store.put(
            solve_cache_key(result.graph, backend=session.backend), result
        )
        if self.verifier is not None:
            # Update results ride the ASYNC audit regardless of class
            # mode: the incremental cut/cycle maintenance is exactly the
            # machinery a certificate should cross-check, but inline
            # correction has no safe shape here (the session already
            # re-keyed) — a failed audit evicts the cached entry so the
            # next solve re-derives it fresh.
            self.verifier.audit(
                result,
                cls=sanitize_class(request.get("slo_class")),
                key=solve_cache_key(result.graph, backend=session.backend),
            )
        out = {
            "ok": True,
            "op": "update",
            "digest": new_digest,
            "prev_digest": digest,
            "mode": session.last_mode,
            "applied": len(request.get("updates", [])),
        }
        out.update(self._result_fields(result, request))
        return out

    # -- streams (stream/session.py, docs/STREAMING.md) ------------------
    def _seed_result(self, digest: str, backend: str):
        """The solved seed a new stream pins to: the parked update-session
        entry for this digest (a solve always parks one), falling back to
        the store's memory LRU — the parked seed is bounded by
        ``max_sessions``, but the cached result outlives it, and an
        evicted stream's re-subscribe-by-digest must keep working without
        a fresh solve. (The disk layer needs the graph to rebuild a
        result, which a digest-only subscribe doesn't carry.) Store keys
        carry the backend the solve ran on, so the probe honors the
        request's backend — a seed solved with an explicit
        ``backend=host`` is cached under the host key, not the service
        default. ``None`` when neither layer knows the graph."""
        entry = self._sessions.get(digest)
        if entry is not None:
            if isinstance(entry, DynamicMST):
                return entry.result()
            return entry[0]
        return self.store.get(
            cache_key_for_digest(digest, backend=backend),
            record_miss=False,
        )

    def _handle_subscribe(self, request: dict) -> dict:
        digest = request.get("digest")
        stream = request.get("stream")
        backend = request.get("backend", self.backend)
        session = self.streams.subscribe(
            digest=digest,
            stream=stream,
            result=self._seed_result(digest, backend) if digest else None,
        )
        return {
            "ok": True,
            "op": "subscribe",
            "stream": session.id,
            "digest": session.head,
            "seq": session.seq,
            "num_nodes": session.mst.num_nodes,
            "num_components": session.mst.num_components,
        }

    def _handle_publish(self, request: dict) -> dict:
        stream = request.get("stream")
        if not stream:
            raise ValueError("publish needs a stream id (from subscribe)")
        # The chain moved: cache the new head for future solve requests and
        # evict the superseded ancestor from the memory LRU — a long-lived
        # stream must not fill the cache with dead chain links. A noop
        # window (prev == new digest) moves nothing: evicting there would
        # drop the result we just cached. Memory-only: the stream
        # snapshot+WAL is the durable layer for every head on the chain.
        # Runs as the commit hook (inside the session lock) so concurrent
        # publishes on one stream maintain the cache in seq order — done
        # after publish returns, a later window's eviction could land
        # before an earlier window's insert and re-plant a dead ancestor.
        def _cache_head(result, prev_digest, digest):
            self.store.put(
                solve_cache_key(result.graph, backend=self.backend),
                result,
                memory_only=True,
            )
            if prev_digest != digest:
                self.store.evict_chain(
                    cache_key_for_digest(prev_digest, backend=self.backend)
                )
                # Mesh residency migration moved INTO the stream manager's
                # commit path (stream/session.py _maintain_residency):
                # it re-keys the session's eviction pin along with the
                # buffers, which a hook out here cannot do.

        out = self.streams.publish(
            stream, request.get("digest"), request.get("updates", []),
            on_commit=_cache_head,
        )
        result = out.pop("result")
        if self.verifier is not None:
            # Stream commits audit async like updates (same reasoning:
            # the WAL append is already the commit point).
            self.verifier.audit(
                result,
                cls=sanitize_class(request.get("slo_class")),
                key=solve_cache_key(result.graph, backend=self.backend),
            )
        response = {"ok": True, "op": "publish", **out}
        response.update(self._result_fields(result, request))
        return response

    def _handle_poll(self, request: dict) -> dict:
        stream = request.get("stream")
        if not stream:
            raise ValueError("poll needs a stream id (from subscribe)")
        out = self.streams.poll(stream, int(request.get("after_seq", 0)))
        return {"ok": True, "op": "poll", **out}

    def _handle_stats(self) -> dict:
        counters = {
            name: value
            for name, value in BUS.counters().items()
            if name.startswith(
                ("serve.", "batch.", "compile.", "lane.", "stream.",
                 "verify.")
            )
        }
        out = {
            "ok": True,
            "op": "stats",
            "counters": counters,
            "store": self.store.stats(),
            "sessions": len(self._sessions),
            "streams": len(self.streams),
            # Ring-overflow visibility: a drill reading stats over the
            # pipes must know when span-derived numbers under-count.
            "events_dropped": BUS.dropped,
            # Raw reservoirs (not summaries): the router-side pulse merges
            # these across workers with obs.events.merge_hists — fleet
            # percentiles need the samples, not per-worker p99s.
            "histograms_raw": BUS.histograms_export(),
        }
        if self.verifier is not None:
            out["verify"] = self.verifier.policy.describe()
        stream_stats = self.streams.stats()
        # Durable streams outnumber resident ones after an LRU eviction
        # or a restart; an operator needs the on-disk count to know a
        # quiet worker still owns replayable state.
        out["streams_recoverable"] = len(stream_stats.get("recoverable", ()))
        if self.warmup_report is not None:
            out["warmup"] = self.warmup_report
        return out

    # ------------------------------------------------------------------
    def _load_graph(self, request: dict) -> Graph:
        if "graph_path" in request:
            from distributed_ghs_implementation_tpu.graphs import io

            path = request["graph_path"]
            if path.endswith(".npz"):
                return io.read_npz(path)
            return io.read_partition_dir(path)
        if "edges" in request:
            return Graph.from_edges(
                int(request["num_nodes"]), request["edges"]
            )
        if SECTIONS_KEY in request:
            # Binary ingest (docs/FLEET.md "Binary wire plane"): u/v/w
            # arrive as raw little-endian sections; frombuffer views, no
            # JSON list ever existed. Digest/cache keys are byte-identical
            # to the edges path by the codec's canonical-form contract.
            return Graph.from_wire(request)
        raise ValueError("solve needs either graph_path or num_nodes+edges")

    def _remember(self, digest: str, result: MSTResult, backend: str) -> None:
        if digest not in self._sessions:
            # A lightweight seed, not a DynamicMST: the result is shared
            # with the store entry (no array copies) until an update op
            # materializes the session.
            self._sessions[digest] = (result, backend)
        self._sessions.move_to_end(digest)
        while len(self._sessions) > self.max_sessions:
            self._sessions.popitem(last=False)
            BUS.count("serve.sessions.evicted")

    @staticmethod
    def _result_fields(result: MSTResult, request: dict) -> dict:
        out = {
            "total_weight": result.total_weight,
            "num_nodes": result.graph.num_nodes,
            "num_edges": result.graph.num_edges,
            "num_edges_in_mst": result.num_edges,
            "num_components": result.num_components,
            "backend": result.backend,
            "wall_time_s": result.wall_time_s,
        }
        if result.incidents is not None and len(result.incidents):
            out["incident_summary"] = result.incidents.summary()
        if request.get("edges_out"):
            # Vectorized either way: one fancy-index per endpoint column,
            # never a per-edge Python loop. Binary clients (the request
            # arrived with sections) get the answer back as sections.
            import numpy as np

            ids = np.asarray(result.edge_ids)
            mst_u = result.graph.u[ids]
            mst_v = result.graph.v[ids]
            if SECTIONS_KEY in request:
                out[SECTIONS_KEY] = (
                    WireSections().add("mst_u", mst_u).add("mst_v", mst_v)
                )
            else:
                out["mst_edges"] = np.stack(
                    [mst_u, mst_v], axis=1
                ).tolist()
        return out

    @staticmethod
    def _merge_fields(out: dict, extra: dict) -> None:
        """``out.update(extra)`` that unions binary egress sections
        instead of letting one response field family clobber the other
        (``edges_out`` + ``labels_out`` on one binary request)."""
        have = out.get(SECTIONS_KEY)
        more = extra.get(SECTIONS_KEY)
        if isinstance(have, WireSections) and isinstance(more, WireSections):
            for name in more.names:
                have.add(name, more.array(name))
            extra = {k: v for k, v in extra.items() if k != SECTIONS_KEY}
        out.update(extra)


class _DrainSignal(Exception):
    """Raised by the SIGTERM/SIGINT handlers while the loop is idle."""


def serve_loop(
    in_stream: IO[str], out_stream: IO[str], service=None
) -> int:
    """Drain JSONL requests from ``in_stream`` until EOF or ``shutdown``;
    one flushed JSON response line each. Returns a process exit code.

    ``service`` is anything with an ``MSTService``-shaped ``handle`` (the
    fleet router qualifies); ``None`` builds a default :class:`MSTService`.

    **Graceful shutdown**: SIGTERM/SIGINT drain instead of killing the
    process mid-line. A signal arriving while a request is being handled
    lets the solve finish and its response flush, THEN ends the loop; a
    signal arriving while blocked on input ends the loop immediately. An
    accepted request therefore always gets its response — previously a
    mid-solve SIGINT tore the loop between accept and respond, which is
    exactly the lost-query shape the fleet drills hunt. Handlers install
    only on the main thread (threaded callers keep their own handling) and
    the previous handlers are restored on exit.
    """
    import signal

    service = service or MSTService()
    draining = threading.Event()
    in_request = [False]

    def _drain_handler(signum, frame):
        draining.set()
        if not in_request[0]:
            # Idle (blocked reading): nothing in flight to protect.
            raise _DrainSignal()

    previous = {}
    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            previous[sig] = signal.signal(sig, _drain_handler)
    except ValueError:
        previous = {}  # not the main thread: run without drain handlers
    try:
        with BUS.span("serve.session", cat="serve"):
            try:
                for line in in_stream:
                    # A line read off the stream IS an accepted request:
                    # flip the flag before touching it, so a signal landing
                    # anywhere past the read drains-after-response instead
                    # of dropping it.
                    in_request[0] = True
                    line = line.strip()
                    if line:
                        try:
                            request = json.loads(line)
                        except json.JSONDecodeError as e:
                            BUS.count("serve.errors")
                            response = {"ok": False, "error": f"bad JSON: {e}"}
                        else:
                            response = service.handle(request)
                        # Compact separators, same as every framed payload
                        # (fleet/framing.py): egress bytes are protocol,
                        # not pretty-printing. Any binary egress sections
                        # fold to their JSON forms — the text protocol
                        # cannot carry raw buffers.
                        out_stream.write(
                            json.dumps(
                                fold_sections(response),
                                separators=(",", ":"),
                            )
                            + "\n"
                        )
                        out_stream.flush()
                    else:
                        response = {}
                    in_request[0] = False
                    if draining.is_set():
                        break
                    if response.get("op") == "shutdown" and response.get("ok"):
                        break
            except _DrainSignal:
                pass  # caught while idle: responses are already flushed
    finally:
        for sig, handler in previous.items():
            signal.signal(sig, handler)
    return 0


def serve_frames(
    in_stream: IO[bytes], out_stream: IO[bytes], service=None
) -> int:
    """The binary front door (``ghs serve --wire binary``): length-prefixed
    frames (``fleet/framing.py``) over binary stdio instead of text JSONL.

    Same ops, same service — only the carrier changes. Requests arrive as
    classic JSON frames or B-frames (raw ``u``/``v``/``w`` array sections
    behind a compact header, crc32 over both); the first inbound B-frame
    flips binary egress on, after which section-bearing responses
    (``edges_out`` / ``labels_out``) go back as B-frames too — the same
    echo-on-receipt negotiation the fleet transports use. JSON frames in,
    JSON (checksummed) frames out: a legacy framed client never sees a
    byte it cannot parse.

    A garbled frame is terminal: past a :class:`FrameError` the stream is
    no longer frame-aligned, so the loop reports it (one best-effort error
    frame) and exits nonzero — the supervisor restarts the process, which
    is the same contract the fleet's channel reader applies. Clean EOF or
    an acknowledged ``shutdown`` exits zero.
    """
    service = service or MSTService()
    wire_out = False
    with BUS.span("serve.session", cat="serve"):
        while True:
            meta: dict = {}
            try:
                request = read_frame(in_stream, meta=meta)
            except FrameError as e:
                BUS.count("serve.errors")
                try:
                    out_stream.write(
                        encode_frame(
                            {"ok": False, "error": f"bad frame: {e}"},
                            crc=True,
                        )
                    )
                    out_stream.flush()
                except OSError:
                    pass
                return 1
            if request is None:
                return 0
            if meta.get("wire"):
                wire_out = True
            response = service.handle(request)
            if wire_out and frame_sections(response) is not None:
                data = encode_bframe(response)
            else:
                data = encode_frame(fold_sections(response), crc=True)
            out_stream.write(data)
            out_stream.flush()
            if response.get("op") == "shutdown" and response.get("ok"):
                return 0
