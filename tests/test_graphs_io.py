"""Data layer: generators, partition-dir interop with the reference format."""

import json
import os

import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import (
    erdos_renyi_graph,
    gnm_random_graph,
    line_graph,
    rmat_graph,
    simple_test_graph,
)
from distributed_ghs_implementation_tpu.graphs.io import (
    read_dimacs,
    read_npz,
    read_partition_dir,
    write_npz,
    write_partition_dir,
)


def test_er_connected_deterministic():
    g1 = erdos_renyi_graph(100, 0.05, seed=4)
    g2 = erdos_renyi_graph(100, 0.05, seed=4)
    assert np.array_equal(g1.u, g2.u) and np.array_equal(g1.w, g2.w)
    import networkx as nx

    assert nx.is_connected(g1.to_networkx())


def test_gnm_edge_count():
    g = gnm_random_graph(256, 1024, seed=1, ensure_connected=False)
    assert g.num_edges == 1024
    assert g.num_nodes == 256


def test_rmat_shapes():
    g = rmat_graph(8, 4, seed=3, dedup=False)
    assert g.num_nodes == 256
    # Dedup and loop-dropping shrink the raw 1024 samples.
    assert 0 < g.num_edges <= 1024


def test_partition_roundtrip(tmp_path):
    g = erdos_renyi_graph(12, 0.4, seed=8)
    d = write_partition_dir(g, str(tmp_path / "gdir"))
    g2 = read_partition_dir(d)
    assert g2.num_nodes == g.num_nodes
    assert g2.edge_triples() == g.edge_triples()


def test_partition_file_format_matches_reference(tmp_path):
    """Field-for-field compatibility with create_graph_files.py:57-88."""
    g = simple_test_graph()
    d = write_partition_dir(g, str(tmp_path / "gdir"))
    with open(os.path.join(d, "node_1.json")) as f:
        node1 = json.load(f)
    assert node1 == {
        "node_id": 1,
        "neighbors": {"0": 1, "2": 2},
        "num_neighbors": 2,
    }
    with open(os.path.join(d, "graph_metadata.json")) as f:
        meta = json.load(f)
    assert meta["num_nodes"] == 3
    assert meta["num_edges"] == 3
    assert [0, 1, 1] in meta["edges"]


def test_read_partition_from_node_files_only(tmp_path):
    """MPINode-style reconstruction when metadata is absent
    (ghs_implementation_mpi.py:74-92 reads only node files)."""
    g = erdos_renyi_graph(8, 0.5, seed=2)
    d = write_partition_dir(g, str(tmp_path / "gdir"))
    os.remove(os.path.join(d, "graph_metadata.json"))
    g2 = read_partition_dir(d)
    assert g2.edge_triples() == g.edge_triples()


def test_dimacs_reader(tmp_path):
    p = tmp_path / "toy.gr"
    p.write_text(
        "c toy\np sp 4 10\n"
        "a 1 2 5\na 2 1 5\na 2 3 2\na 3 2 2\na 3 4 7\na 4 3 7\na 1 4 1\na 4 1 1\n"
        "a 1 3 9\na 3 1 9\n"
    )
    g = read_dimacs(str(p))
    assert g.num_nodes == 4
    assert g.num_edges == 5  # both-direction arcs collapsed
    assert g.total_weight == 5 + 2 + 7 + 1 + 9


def test_npz_roundtrip(tmp_path):
    g = rmat_graph(6, 8, seed=5)
    p = write_npz(g, str(tmp_path / "g.npz"))
    g2 = read_npz(p)
    assert g2.num_nodes == g.num_nodes
    assert np.array_equal(g2.w, g.w)


def test_directed_arrays_interleaving():
    g = simple_test_graph()
    src, dst, w = g.directed_arrays()
    assert src.shape[0] == 2 * g.num_edges
    # Slot 2e is u->v, slot 2e+1 is v->u.
    assert src[0] == g.u[0] and dst[0] == g.v[0]
    assert src[1] == g.v[0] and dst[1] == g.u[0]
    assert w[0] == w[1] == g.w[0]


def test_directed_arrays_padding():
    g = simple_test_graph()
    src, dst, w = g.directed_arrays(pad_to=16)
    assert src.shape[0] == 16
    # Pads are inert self-edges with sentinel weight.
    assert np.all(src[6:] == dst[6:])


def test_csr():
    g = simple_test_graph()
    indptr, dst, w = g.csr()
    assert indptr.tolist() == [0, 2, 4, 6]
    assert sorted(dst[0:2].tolist()) == [1, 2]


def test_degree_and_weight_helpers():
    g = line_graph(5, weight=3)
    assert g.degrees().tolist() == [1, 2, 2, 2, 1]
    assert g.total_weight == 12
    assert g.is_integer_weighted


def test_dimacs_write_read_roundtrip(tmp_path):
    """write_dimacs -> read_dimacs / read_dimacs_native round-trip exactly."""
    import numpy as np

    from distributed_ghs_implementation_tpu.graphs import native
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
    from distributed_ghs_implementation_tpu.graphs.generators import road_grid_graph
    from distributed_ghs_implementation_tpu.graphs.io import read_dimacs, write_dimacs

    g = road_grid_graph(20, 30, seed=2)
    p = str(tmp_path / "grid.gr")
    write_dimacs(g, p, comment="roundtrip fixture")
    g2 = read_dimacs(p)
    assert g2.num_nodes == g.num_nodes
    assert np.array_equal(g2.u, g.u)
    assert np.array_equal(g2.v, g.v)
    assert np.array_equal(g2.w, g.w)
    if native.native_available():
        u, v, w, n = native.read_dimacs_native(p)
        g3 = Graph.from_arrays(n, u, v, w)
        assert np.array_equal(g3.u, g.u) and np.array_equal(g3.w, g.w)


def test_road_grid_solve_matches_oracle():
    import numpy as np

    from distributed_ghs_implementation_tpu.graphs.generators import road_grid_graph
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
    from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight

    g = road_grid_graph(50, 40, seed=3)
    ids, frag, lv = solve_graph(g, strategy="rank")
    assert abs(float(g.w[ids].sum()) - scipy_mst_weight(g)) < 1e-6
    assert np.unique(frag).size == 1  # grid is connected
    ids_f, _, _ = solve_graph(g, strategy="fused")
    assert np.array_equal(ids, ids_f)
