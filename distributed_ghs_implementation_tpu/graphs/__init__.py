"""Graph data layer: in-memory edge lists, generators, and on-disk formats.

Covers the reference's L0 data layer (``create_graph_files.py``,
``create_simple_test.py``) — generation, vertex partitioning, persistence —
rebuilt around dense NumPy arrays that feed the TPU kernel directly.
"""

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import (
    erdos_renyi_graph,
    line_graph,
    reference_random_graph,
    rmat_graph,
    simple_test_graph,
)
from distributed_ghs_implementation_tpu.graphs.io import (
    read_dimacs,
    read_partition_dir,
    write_partition_dir,
)

__all__ = [
    "Graph",
    "erdos_renyi_graph",
    "line_graph",
    "read_dimacs",
    "read_partition_dir",
    "reference_random_graph",
    "rmat_graph",
    "simple_test_graph",
    "write_partition_dir",
]
