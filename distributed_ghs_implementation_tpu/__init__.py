"""TPU-native distributed minimum-spanning-tree framework.

A brand-new framework with the capabilities of the reference GHS implementation
(``Trisanu-007/Distributed_GHS_Implementation``): exact MSTs of weighted graphs,
NetworkX weight parity, graph generation/partitioning tooling, experiment
harness, and visualization — redesigned TPU-first.

Instead of the reference's per-vertex message passing (one thread or MPI rank
per graph vertex, ``/root/reference/ghs_implementation.py:46-116`` and
``ghs_implementation_mpi.py:40-115``), the GHS protocol is recast as a batched
Borůvka-style graph-contraction kernel: the TEST/ACCEPT/REJECT minimum-outgoing-
edge search becomes a ``segment_min`` over an edge list, the CONNECT/INITIATE/
CHANGEROOT fragment merge becomes pointer-jumping union-find, and levels run in
an on-device ``lax.while_loop``, with edges shardable over a TPU mesh and
per-level minima combined over ICI.

Public API (mirrors the reference surface, ``ghs_implementation.py:416-442``):

    >>> from distributed_ghs_implementation_tpu import GHSAlgorithm
    >>> mst = GHSAlgorithm(num_nodes, edges).run()

or the functional form:

    >>> from distributed_ghs_implementation_tpu import minimum_spanning_tree
"""

import os as _os

# Persistent XLA compilation cache. Kernel shapes here are data-dependent
# (finish chunks compile per survivor-count bucket), and a cold compile costs
# ~10 s per shape on a remote-tunnel TPU — across processes that dominated
# end-to-end road-graph solves. Opt out / relocate with GHS_TPU_COMPILE_CACHE
# (empty string disables). Must run before any JAX backend initialization.
_cache_dir = _os.environ.get(
    "GHS_TPU_COMPILE_CACHE",
    _os.path.join(_os.path.expanduser("~"), ".cache", "ghs_tpu_xla"),
)
if _cache_dir:
    try:
        import jax as _jax

        # CPU-only sessions (the test suite) skip the cache: CPU compiles
        # are cheap, and reloading CPU AOT results across processes can hit
        # machine-feature-detection mismatches (observed
        # "+prefer-no-scatter ... could lead to SIGILL" loader warnings).
        _platforms = _jax.config.jax_platforms or _os.environ.get(
            "JAX_PLATFORMS", ""
        )
        # Explicit cpu selection, or no accelerator platform mentioned at
        # all: skip the cache (only accelerator compiles are worth it).
        _cpu_only = _platforms == "cpu" or (
            _platforms == "" and not _os.environ.get("PJRT_DEVICE")
            and not _os.path.exists("/root/.axon_site")
        )
        if not _cpu_only and _jax.config.jax_compilation_cache_dir is None:
            _jax.config.update("jax_compilation_cache_dir", _cache_dir)
            _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
        pass

from distributed_ghs_implementation_tpu.api import (
    GHSAlgorithm,
    MSTResult,
    minimum_spanning_forest,
    minimum_spanning_forest_batch,
    minimum_spanning_tree,
)
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph

__version__ = "0.1.0"

__all__ = [
    "GHSAlgorithm",
    "Graph",
    "MSTResult",
    "minimum_spanning_forest",
    "minimum_spanning_forest_batch",
    "minimum_spanning_tree",
    "__version__",
]
