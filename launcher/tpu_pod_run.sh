#!/bin/bash
# Launch a multi-host solve across a Cloud TPU pod slice.
#
# Runs the same command on every host of the slice; JAX's TPU runtime
# auto-discovers coordinator/process topology from pod metadata, so
# `multihost.initialize()` needs no explicit addresses here.
#
# Usage:
#   ./launcher/tpu_pod_run.sh <tpu-name> <zone> --graph-dir /shared/graph_data
set -euo pipefail

TPU_NAME="$1"; shift
ZONE="$1"; shift

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command "cd $(pwd) && python -m distributed_ghs_implementation_tpu run --multihost --backend sharded $*"
