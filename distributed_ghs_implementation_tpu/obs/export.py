"""Exporters for the event bus: Chrome-trace/Perfetto JSON, JSONL, stats.

Three views of the same ring buffer:

* :func:`write_chrome_trace` — the Chrome ``traceEvents`` JSON format, which
  Perfetto (https://ui.perfetto.dev) and ``chrome://tracing`` both load
  directly: spans become ``"X"`` slices that nest by time per thread track,
  counters become ``"C"`` timeline tracks.
* :func:`write_events_jsonl` — one JSON object per line (stream-appendable,
  grep-able), with a trailing ``"M"`` metadata line carrying the counter
  totals and histogram summaries so a log file is self-contained.
* :func:`render_stats` — the plain-text summary behind the ``stats``
  subcommand, computed from a live bus or a parsed JSONL file.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from distributed_ghs_implementation_tpu.obs.events import (
    PH_COMPLETE,
    PH_COUNTER,
    PH_INSTANT,
    EventBus,
    aggregate_span_stats,
)


def _jsonable(value: Any) -> Any:
    """Lazy serialization boundary: coerce arbitrary arg values to JSON."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    try:  # numpy scalars expose item()
        return value.item()
    except AttributeError:
        return repr(value)


def _tid_map(events) -> Dict[int, int]:
    """Stable small-int thread ids (raw idents are unreadable in a trace)."""
    mapping: Dict[int, int] = {}
    for rec in events:
        mapping.setdefault(rec[5], len(mapping))
    return mapping


def chrome_trace_events(bus: EventBus) -> List[dict]:
    """Bus records as Chrome ``traceEvents`` dicts (timestamps in µs)."""
    events = bus.events()
    tids = _tid_map(events)
    pid = os.getpid()
    out: List[dict] = []
    for ph, name, cat, ts_ns, dur_ns, tid, args in events:
        ev: Dict[str, Any] = {
            "name": name,
            "cat": cat,
            "ph": ph,
            "ts": ts_ns / 1000.0,
            "pid": pid,
            "tid": tids[tid],
        }
        if ph == PH_COMPLETE:
            ev["dur"] = dur_ns / 1000.0
        if ph == PH_COUNTER:
            ev["args"] = {"value": _jsonable((args or {}).get("value", 0))}
        elif args:
            ev["args"] = _jsonable(args)
        if ph == PH_INSTANT:
            ev["s"] = "t"  # thread-scoped instant marker
        out.append(ev)
    # Counter totals as a final sample each, so every counter has a track
    # even if no timeline samples were taken during the run.
    end_ts = max((e["ts"] + e.get("dur", 0.0) for e in out), default=0.0)
    for name, value in sorted(bus.counters().items()):
        out.append(
            {
                "name": name,
                "cat": "counter",
                "ph": PH_COUNTER,
                "ts": end_ts,
                "pid": pid,
                "tid": 0,
                "args": {"value": _jsonable(value)},
            }
        )
    return out


def to_chrome_trace(bus: EventBus) -> dict:
    return {
        "traceEvents": chrome_trace_events(bus),
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "distributed_ghs_implementation_tpu.obs",
            "events_dropped": bus.dropped,
        },
    }


def write_chrome_trace(bus: EventBus, path: str) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(bus), f)
        f.write("\n")
    return path


def write_events_jsonl(
    bus: EventBus, path: str, *, label: Optional[str] = None
) -> str:
    """Events one-per-line, bracketed by metadata: a LEADING header line
    (ring capacity + dropped count at export time) and a TRAILING line with
    the counter/histogram totals. The header exists so a log truncated
    mid-write — the normal state of a file another process is tailing —
    still tells the reader whether the ring overflowed; a measurement that
    dropped events must be flagged, never silently under-counted.

    The header also carries merge provenance — ``pid``, a wall-clock
    ``epoch_unix_ns`` anchor for the bus's monotonic timeline, and an
    optional ``process`` label — which is what lets
    :func:`merge_trace_files` align N per-process logs onto one axis."""
    tids = _tid_map(bus.events())
    with open(path, "w") as f:
        header = {
            "ph": "M",
            "kind": "header",
            "schema": "ghs-obs-jsonl-v1",
            "capacity": bus.capacity,
            "events_dropped": bus.dropped,
            "pid": os.getpid(),
            "epoch_unix_ns": bus.epoch_unix_ns(),
        }
        if label:
            header["process"] = str(label)
        f.write(json.dumps(header, separators=(",", ":")) + "\n")
        for ph, name, cat, ts_ns, dur_ns, tid, args in bus.events():
            rec = {
                "ph": ph,
                "name": name,
                "cat": cat,
                "ts_us": ts_ns / 1000.0,
                "tid": tids[tid],
            }
            if ph == PH_COMPLETE:
                rec["dur_us"] = dur_ns / 1000.0
            if args:
                rec["args"] = _jsonable(args)
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        f.write(
            json.dumps(
                {
                    "ph": "M",
                    "counters": _jsonable(bus.counters()),
                    "histograms": _jsonable(bus.histograms()),
                    "events_dropped": bus.dropped,
                },
                separators=(",", ":"),
            )
            + "\n"
        )
    return path


def read_events_jsonl(path: str) -> Tuple[List[dict], dict]:
    """Parse a JSONL event log; returns ``(event_dicts, metadata)``.

    Tolerant of files still being written (or truncated by a crash): a
    line that fails to parse — typically the torn final line of a
    concurrent writer — is *skipped and counted* (``lines_skipped`` in the
    metadata), never raised. Metadata merges the leading header under the
    trailing totals line, so a log cut off before its trailing ``"M"``
    line still reports the header's ``events_dropped``.
    """
    events: List[dict] = []
    header: dict = {}
    meta: dict = {}
    skipped = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if not isinstance(rec, dict):
                skipped += 1
                continue
            if rec.get("ph") == "M":
                if rec.get("kind") == "header":
                    header = rec
                else:
                    meta = rec
            else:
                events.append(rec)
    merged = {**header, **meta}
    merged.pop("kind", None)
    if skipped:
        merged["lines_skipped"] = skipped
    return events, merged


def snapshot_from_jsonl(path: str) -> dict:
    """Rebuild a :meth:`EventBus.snapshot`-shaped dict from a JSONL log."""
    events, meta = read_events_jsonl(path)
    spans, instants = aggregate_span_stats(
        (rec.get("ph"), rec.get("name"), rec.get("dur_us", 0.0) / 1e6)
        for rec in events
    )
    snap = {
        "schema": "ghs-obs-snapshot-v1",
        "spans": spans,
        "instants": instants,
        "counters": meta.get("counters", {}),
        "histograms": meta.get("histograms", {}),
        "events_retained": len(events),
        "events_dropped": meta.get("events_dropped", 0),
    }
    if meta.get("lines_skipped"):
        snap["lines_skipped"] = meta["lines_skipped"]
    return snap


# -- multi-process trace assembly ------------------------------------------

MERGE_SCHEMA = "ghs-trace-merge-v1"

#: Span names whose duration counts as "solve" in the critical path.
_SOLVE_SPAN_NAMES = (
    "serve.solve", "stream.window", "stream.replay.window",
)
_SOLVE_SPAN_PREFIXES = ("solver.", "batch.flush", "lane.solve")


def _read_merge_inputs(paths) -> List[dict]:
    """Per-file read + provenance: pid (deduplicated), display label, and
    the wall-clock offset that maps its monotonic timeline onto the
    earliest file's axis. Files without an ``epoch_unix_ns`` header
    (pre-merge exports) align at offset 0 — still loadable, just not
    cross-process-accurate."""
    files: List[dict] = []
    for path in sorted(paths):
        events, meta = read_events_jsonl(path)
        label = meta.get("process") or os.path.splitext(
            os.path.basename(path)
        )[0]
        files.append({
            "path": path,
            "events": events,
            "meta": meta,
            "label": str(label),
            "pid": meta.get("pid"),
            "epoch": meta.get("epoch_unix_ns"),
        })
    seen_pids = set()
    for i, fi in enumerate(files):
        pid = fi["pid"]
        if not isinstance(pid, int) or pid in seen_pids:
            pid = 1_000_000 + i  # synthetic, collision-free
        fi["pid"] = pid
        seen_pids.add(pid)
    epochs = [
        fi["epoch"] for fi in files
        if isinstance(fi["epoch"], (int, float))
    ]
    base = min(epochs) if epochs else 0
    for fi in files:
        epoch = fi["epoch"]
        fi["offset_us"] = (
            (epoch - base) / 1000.0
            if isinstance(epoch, (int, float)) else 0.0
        )
    return files


def merge_trace_files(paths) -> Tuple[dict, dict]:
    """Join N per-process JSONL event logs into ONE Perfetto trace.

    Returns ``(trace, report)``:

    * ``trace`` — a Chrome-trace object with one process track per input
      file (named by the header's ``process`` label), every process's
      spans aligned onto a shared wall-clock axis, and flow ("s"/"f")
      arrows stitching each cross-process parent→child span edge — the
      router's ``fleet.attempt`` visually connects to the worker's
      ``fleet.serve`` it dispatched.
    * ``report`` — ``ghs-trace-merge-v1``: per-process inventory, trace
      join accounting (``traces_joined``, ``orphan_spans``), and the
      per-trace critical-path decomposition (queue vs transport vs solve
      vs verify vs residual) for every rooted ``fleet.request``.

    **Rooted-traces rule**: orphan/join accounting only covers traces
    whose ROOT span (one with no ``parent``) is present in the merged
    set. A worker-side fragment whose router log was cleared or rotated
    away (warm-phase traffic before a drill's measured window) is
    reported in ``traces_unrooted`` — excluding it is what makes
    ``orphan_spans == 0`` a real integrity invariant instead of an
    artifact of log retention.
    """
    files = _read_merge_inputs(paths)
    out_events: List[dict] = []
    spans: Dict[str, dict] = {}
    traces: Dict[str, List[dict]] = {}
    processes = []
    for fi in files:
        pid = fi["pid"]
        processes.append({
            "label": fi["label"],
            "pid": pid,
            "path": fi["path"],
            "events": len(fi["events"]),
            "events_dropped": fi["meta"].get("events_dropped", 0),
        })
        out_events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": fi["label"]},
        })
        for rec in fi["events"]:
            ph = rec.get("ph")
            ts = float(rec.get("ts_us", 0.0)) + fi["offset_us"]
            ev: Dict[str, Any] = {
                "name": rec.get("name"),
                "cat": rec.get("cat", "app"),
                "ph": ph,
                "ts": ts,
                "pid": pid,
                "tid": int(rec.get("tid", 0)),
            }
            if ph == PH_COMPLETE:
                ev["dur"] = float(rec.get("dur_us", 0.0))
            if ph == PH_INSTANT:
                ev["s"] = "t"
            args = rec.get("args")
            if args:
                ev["args"] = args
            out_events.append(ev)
            if (
                ph == PH_COMPLETE
                and isinstance(args, dict)
                and args.get("trace")
                and args.get("span")
            ):
                info = {
                    "span": args["span"],
                    "parent": args.get("parent"),
                    "trace": args["trace"],
                    "name": rec.get("name"),
                    "pid": pid,
                    "tid": ev["tid"],
                    "ts_us": ts,
                    "dur_us": ev["dur"],
                }
                spans[args["span"]] = info
                traces.setdefault(args["trace"], []).append(info)
    # Flow arrows: one s->f pair per cross-process parent->child edge.
    flow_id = 0
    for info in spans.values():
        parent = info.get("parent")
        pi = spans.get(parent) if parent else None
        if pi is not None and pi["pid"] != info["pid"]:
            flow_id += 1
            out_events.append({
                "ph": "s", "id": flow_id, "name": "trace.hop",
                "cat": "trace", "pid": pi["pid"], "tid": pi["tid"],
                "ts": pi["ts_us"],
            })
            out_events.append({
                "ph": "f", "bp": "e", "id": flow_id, "name": "trace.hop",
                "cat": "trace", "pid": info["pid"], "tid": info["tid"],
                "ts": info["ts_us"],
            })
    rooted: Dict[str, List[dict]] = {}
    unrooted = 0
    orphan_spans = 0
    traces_joined = 0
    for trace_id, infos in traces.items():
        if not any(s["parent"] is None for s in infos):
            unrooted += 1
            continue
        rooted[trace_id] = infos
        orphan_spans += sum(
            1 for s in infos
            if s["parent"] is not None and s["parent"] not in spans
        )
        if len({s["pid"] for s in infos}) >= 2:
            traces_joined += 1
    report = {
        "schema": MERGE_SCHEMA,
        "processes": processes,
        "spans_indexed": len(spans),
        "flow_arrows": flow_id,
        "traces_total": len(traces),
        "traces_rooted": len(rooted),
        "traces_unrooted": unrooted,
        "traces_joined": traces_joined,
        "orphan_spans": orphan_spans,
        "critical_path": _critical_path_report(rooted),
    }
    trace = {
        "traceEvents": out_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "distributed_ghs_implementation_tpu.obs.merge",
            "schema": MERGE_SCHEMA,
            "processes": [p["label"] for p in processes],
        },
    }
    return trace, report


def _is_solve_span(name: str) -> bool:
    return name in _SOLVE_SPAN_NAMES or name.startswith(_SOLVE_SPAN_PREFIXES)


def _critical_path_report(rooted: Dict[str, List[dict]]) -> dict:
    """Decompose every rooted ``fleet.request`` into where its wall time
    went. The buckets telescope by construction —

    ``total = queue + probe + transport + (solve + verify + service_other)
    + residual``

    — where ``queue`` is router-side time outside any attempt (routing,
    journal fsync, admission), ``transport`` is attempt time not covered
    by the worker's in-process ``fleet.serve`` span (the wire hop plus
    worker queueing), and ``residual`` is whatever clock skew or clamping
    left unaccounted; ``accounted_frac`` is the share the named buckets
    explain, which the CI gate holds at >= 0.9."""
    per_trace: List[dict] = []
    totals = {
        "queue_s": 0.0, "probe_s": 0.0, "transport_s": 0.0,
        "solve_s": 0.0, "verify_s": 0.0, "service_other_s": 0.0,
        "residual_s": 0.0, "total_s": 0.0,
    }
    fracs: List[float] = []
    for trace_id, infos in sorted(rooted.items()):
        root = next(
            (s for s in infos
             if s["name"] == "fleet.request" and s["parent"] is None),
            None,
        )
        if root is None:
            continue  # rooted at serve.request / stream.window: no fleet hop
        total = root["dur_us"]
        attempt = sum(
            s["dur_us"] for s in infos if s["name"] == "fleet.attempt"
        )
        probe = sum(
            s["dur_us"] for s in infos
            if s["name"] == "fleet.forward.probe"
        )
        serve = sum(
            s["dur_us"] for s in infos if s["name"] == "fleet.serve"
        )
        solve = sum(
            s["dur_us"] for s in infos if _is_solve_span(s["name"])
        )
        verify = sum(
            s["dur_us"] for s in infos
            if s["name"].startswith("verify")
        )
        queue = max(0.0, total - attempt - probe)
        transport = max(0.0, attempt - serve)
        service_other = max(0.0, serve - solve - verify)
        accounted = min(
            total,
            queue + probe + transport + solve + verify + service_other,
        )
        residual = max(0.0, total - accounted)
        entry = {
            "trace": trace_id,
            "total_s": total / 1e6,
            "queue_s": queue / 1e6,
            "probe_s": probe / 1e6,
            "transport_s": transport / 1e6,
            "solve_s": solve / 1e6,
            "verify_s": verify / 1e6,
            "service_other_s": service_other / 1e6,
            "residual_s": residual / 1e6,
            "accounted_frac": (accounted / total) if total > 0 else 1.0,
            "attempts": sum(
                1 for s in infos if s["name"] == "fleet.attempt"
            ),
            "processes": len({s["pid"] for s in infos}),
        }
        per_trace.append(entry)
        fracs.append(entry["accounted_frac"])
        for key in totals:
            if key in entry:
                totals[key] += entry[key]
    summary = dict(totals)
    summary["traces"] = len(per_trace)
    summary["accounted_frac_min"] = min(fracs) if fracs else 1.0
    summary["accounted_frac_mean"] = (
        sum(fracs) / len(fracs) if fracs else 1.0
    )
    return {"per_trace": per_trace, "summary": summary}


def write_merged_trace(
    paths, trace_path: str, report_path: Optional[str] = None
) -> dict:
    """Merge ``paths`` (see :func:`merge_trace_files`), write the Perfetto
    trace to ``trace_path`` (and the report beside it when asked); returns
    the report."""
    trace, report = merge_trace_files(paths)
    with open(trace_path, "w") as f:
        json.dump(trace, f)
        f.write("\n")
    if report_path is not None:
        with open(report_path, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    return report


def _fmt_s(seconds: float) -> str:
    if seconds >= 1.0:
        return f"{seconds:8.3f}s "
    if seconds >= 1e-3:
        return f"{seconds * 1e3:8.3f}ms"
    return f"{seconds * 1e6:8.1f}µs"


def render_stats(snapshot: dict) -> str:
    """Human-readable summary of a snapshot (live bus or JSONL-derived)."""
    lines: List[str] = []
    spans = snapshot.get("spans", {})
    if spans:
        lines.append("spans (by total time):")
        lines.append(
            f"  {'name':<32} {'count':>7} {'total':>10} {'mean':>10} {'max':>10}"
        )
        for name, agg in sorted(
            spans.items(), key=lambda kv: -kv[1]["total_s"]
        ):
            lines.append(
                f"  {name:<32} {agg['count']:>7} {_fmt_s(agg['total_s']):>10}"
                f" {_fmt_s(agg['mean_s']):>10} {_fmt_s(agg['max_s']):>10}"
            )
    instants = snapshot.get("instants", {})
    if instants:
        lines.append("instants:")
        for name, count in sorted(instants.items()):
            lines.append(f"  {name:<32} {count:>7}")
    counters = snapshot.get("counters", {})
    if counters:
        lines.append("counters:")
        for name, value in sorted(counters.items()):
            value = int(value) if float(value).is_integer() else value
            lines.append(f"  {name:<40} {value:>12}")
    hists = snapshot.get("histograms", {})
    if hists:
        lines.append("histograms:")
        for name, h in sorted(hists.items()):
            if not h.get("count"):
                continue
            lines.append(
                f"  {name:<32} count={h['count']} mean={h['mean']:.2f} "
                f"p50={h['p50']:.2f} p90={h['p90']:.2f} p99={h['p99']:.2f} "
                f"max={h['max']:.2f}"
            )
    dropped = snapshot.get("events_dropped", 0)
    lines.append(
        f"events: {snapshot.get('events_retained', 0)} retained, "
        f"{dropped} dropped (ring overflow)"
    )
    if dropped:
        lines.append(
            f"WARNING: ring overflow dropped {dropped} events — span tables "
            "above under-count; counters/histograms are still complete"
        )
    if snapshot.get("lines_skipped"):
        lines.append(
            f"WARNING: {snapshot['lines_skipped']} unparseable JSONL "
            "line(s) skipped (torn write?)"
        )
    # Fleet-shaped snapshots (router stats / pulse reports) carry a
    # per-worker map; a worker whose ring overflowed silently under-counts
    # every span-derived number it reported — flag each one by name.
    workers = snapshot.get("workers")
    if isinstance(workers, dict):
        for wid in sorted(workers, key=str):
            info = workers[wid]
            if not isinstance(info, dict):
                continue
            stats = info.get("stats")
            source = stats if isinstance(stats, dict) else info
            worker_dropped = source.get("events_dropped", 0)
            if worker_dropped:
                lines.append(
                    f"WARNING: worker {wid} dropped {worker_dropped} "
                    "events (ring overflow) — its span-derived telemetry "
                    "under-counts"
                )
    return "\n".join(lines)


def save_snapshot(bus: EventBus, path: str) -> str:
    with open(path, "w") as f:
        json.dump(bus.snapshot(), f, indent=2)
        f.write("\n")
    return path


def load_snapshot(path: str) -> Optional[dict]:
    with open(path) as f:
        return json.load(f)
