"""Process-local structured event bus: spans, counters, histograms.

The reference's only observability was print-narration (per-message logs at
``ghs_implementation_mpi.py:100-113``) — which is exactly what let its
silent-wrong-MST failures go unnoticed. This bus is the opposite design
point: one process-wide sink of *typed* telemetry cheap enough to stay on in
production, drained by exporters (``obs.export``) into Chrome-trace JSON,
JSONL event logs, and plain-text stats.

Cost model:

* **Disabled** (``GHS_OBS=0`` or :meth:`EventBus.disable`): every emission
  is one attribute check; :meth:`EventBus.span` returns a module-level
  singleton, so the hot path allocates nothing.
* **Enabled**: events land in a fixed-capacity ring buffer as plain tuples
  (no dict/object per event); serialization happens only at export time.
  Overflow overwrites the oldest events and counts them in
  :attr:`EventBus.dropped` — memory is bounded no matter how long the
  process runs. Counters and histograms are O(1) aggregates outside the
  ring, so totals survive overflow.

Event taxonomy (names are dotted, ``docs/OBSERVABILITY.md`` has the full
registry): ``solver.*`` (level/chunk kernels), ``protocol.*`` (message
transport + reliable sublayer), ``resilience.*`` (supervisor attempts,
degradations), ``parallel.*`` (sharded staging/collectives), ``trace.*``
(CLI session phases), ``metrics.*`` (per-level fragment census),
``serve.*`` (query service: cache hits/misses, single-flight coalescing,
queue-depth samples, incremental-vs-resolve update routing).
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from distributed_ghs_implementation_tpu.obs import tracing

# Chrome-trace phase codes carried on every record (export stays a rename).
PH_COMPLETE = "X"  # span with a duration
PH_INSTANT = "I"  # point event
PH_COUNTER = "C"  # counter sample on a timeline track

# Record layout (plain tuple — cheap to emit, lazy to serialize):
#   (ph, name, cat, ts_ns, dur_ns, tid, args_dict_or_None)
EventTuple = Tuple[str, str, str, int, int, int, Optional[Dict[str, Any]]]

_HIST_SAMPLE_CAP = 512  # bounded per-histogram reservoir for percentiles
_HIST_SEED = 0x5EED  # fixed reservoir seed: summaries are run-reproducible


def quantile(
    samples: Sequence[float], p: float, *, presorted: bool = False
) -> float:
    """Nearest-rank quantile of a sample sequence.

    The ONE quantile rule every percentile in the repo uses — histogram
    summaries, the SLO accounting layer (``obs.slo``), and ``bench.py``'s
    warm-latency metrics — so a p99 in one report is comparable to a p99
    in another. Empty input returns 0.0 (a report field, not an error).
    ``presorted=True`` skips the sort for callers taking several quantiles
    of one sample set.
    """
    if not samples:
        return 0.0
    xs = samples if presorted else sorted(samples)
    return xs[min(len(xs) - 1, int(round(p * (len(xs) - 1))))]


class _NullSpan:
    """The disabled-mode span: a reusable, allocation-free no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **args) -> "_NullSpan":
        return self


NULL_SPAN = _NullSpan()


class _Span:
    """Live span handle; records one ``PH_COMPLETE`` event on exit.

    When a :mod:`obs.tracing` context is active (and sampled), entering
    the span stamps ``trace``/``span``/``parent`` ids into its args and
    pushes itself as the context's parent, so spans opened inside —
    including on other processes that re-establish the context from the
    wire — chain to it. Untraced code pays one contextvar read.
    """

    __slots__ = ("_bus", "name", "cat", "args", "_t0", "_trace_token")

    def __init__(self, bus: "EventBus", name: str, cat: str, args: dict):
        self._bus = bus
        self.name = name
        self.cat = cat
        self.args = args or None
        self._t0 = bus.now_ns()
        self._trace_token = None

    def set(self, **args) -> "_Span":
        """Attach arguments discovered mid-span (e.g. a resolved strategy)."""
        if self.args is None:
            self.args = {}
        self.args.update(args)
        return self

    def __enter__(self) -> "_Span":
        ctx = tracing.current()
        if ctx is not None and ctx.sampled:
            span_id = tracing.new_span_id()
            if self.args is None:
                self.args = {}
            self.args["trace"] = ctx.trace_id
            self.args["span"] = span_id
            if ctx.span_id is not None:
                self.args["parent"] = ctx.span_id
            self._trace_token = tracing.push_child(ctx, span_id)
        return self

    def __exit__(self, *exc) -> bool:
        if self._trace_token is not None:
            tracing.pop(self._trace_token)
            self._trace_token = None
        bus = self._bus
        bus._append(
            (
                PH_COMPLETE,
                self.name,
                self.cat,
                self._t0,
                bus.now_ns() - self._t0,
                threading.get_ident(),
                self.args,
            )
        )
        return False


class _Hist:
    """Running aggregate + bounded uniform reservoir (percentiles stay
    O(cap) in memory and unbiased over arbitrarily long runs).

    The previous implementation overwrote the 512-sample buffer
    round-robin — a sliding window of *recent* values, which skews long-run
    tail quantiles toward whatever the process did last (a load drill's
    p99 would forget its own warm phase). Algorithm R reservoir sampling
    keeps each of the ``count`` observations in the sample set with equal
    probability ``cap/count``; the RNG is seeded per histogram, so two runs
    over the same observation sequence summarize identically.
    """

    __slots__ = ("count", "total", "vmin", "vmax", "samples", "_rng")

    def __init__(self, seed: int = _HIST_SEED):
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.samples: List[float] = []
        self._rng = random.Random(seed)

    def add(self, value: float) -> None:
        self.count += 1
        self.total += value
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value
        if len(self.samples) < _HIST_SAMPLE_CAP:
            self.samples.append(value)
        else:  # Algorithm R: keep with probability cap/count, evict uniform
            j = self._rng.randrange(self.count)
            if j < _HIST_SAMPLE_CAP:
                self.samples[j] = value

    def summary(self) -> dict:
        if not self.count:
            return {"count": 0}
        s = sorted(self.samples)
        return {
            "count": self.count,
            "sum": self.total,
            "mean": self.total / self.count,
            "min": self.vmin,
            "max": self.vmax,
            "p50": quantile(s, 0.50, presorted=True),
            "p90": quantile(s, 0.90, presorted=True),
            "p95": quantile(s, 0.95, presorted=True),
            "p99": quantile(s, 0.99, presorted=True),
        }


def merge_hists(raws: Sequence[dict]) -> _Hist:
    """Deterministic merge of raw reservoir exports into one :class:`_Hist`.

    ``raws`` are :meth:`EventBus.histograms_export` values for ONE metric
    across N processes, in a caller-stabilized order (the pulse sorts by
    worker id). Count/sum/min/max merge exactly; the merged reservoir is a
    count-weighted with-replacement draw from the per-process reservoirs
    under the same fixed seed every histogram uses — so two pulses over
    identical worker exports summarize byte-for-byte identically, and a
    big worker's tail outweighs a small one's in the merged p99.
    """
    merged = _Hist()
    pools = [
        r for r in raws
        if r and int(r.get("count", 0)) > 0 and r.get("samples")
    ]
    if not pools:
        return merged
    merged.count = sum(int(r["count"]) for r in pools)
    merged.total = float(sum(float(r.get("sum", 0.0)) for r in pools))
    merged.vmin = min(float(r["min"]) for r in pools)
    merged.vmax = max(float(r["max"]) for r in pools)
    concat = [float(s) for r in pools for s in r["samples"]]
    if len(concat) <= _HIST_SAMPLE_CAP:
        merged.samples = concat
        return merged
    rng = random.Random(_HIST_SEED)
    weights = [int(r["count"]) for r in pools]
    total_weight = sum(weights)
    for _ in range(_HIST_SAMPLE_CAP):
        x = rng.randrange(total_weight)
        for r, w in zip(pools, weights):
            if x < w:
                samples = r["samples"]
                merged.samples.append(
                    float(samples[rng.randrange(len(samples))])
                )
                break
            x -= w
    return merged


class EventBus:
    """Fixed-memory structured telemetry sink (see module docstring).

    All mutators are safe under CPython's GIL for the access patterns here
    (single-writer per thread; the ring index is guarded by a lock because
    two threads CAN interleave an append).
    """

    def __init__(self, capacity: int = 65536, enabled: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.enabled = enabled
        self._buf: List[Optional[EventTuple]] = [None] * capacity
        self._write = 0  # monotone count of events ever appended
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._hists: Dict[str, _Hist] = {}
        self._epoch_ns = time.perf_counter_ns()

    # -- lifecycle -----------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        """Drop all events, counters, and histograms; restart the clock."""
        with self._lock:
            self._buf = [None] * self.capacity
            self._write = 0
            self._counters = {}
            self._hists = {}
            self._epoch_ns = time.perf_counter_ns()

    def now_ns(self) -> int:
        """Nanoseconds since this bus's epoch (clear() resets it)."""
        return time.perf_counter_ns() - self._epoch_ns

    def epoch_unix_ns(self) -> int:
        """The bus epoch as a wall-clock unix timestamp (ns) — the anchor
        the multi-file trace merge uses to align per-process monotonic
        timelines onto one axis. Derived at call time (wall clock minus
        elapsed monotonic), so it is stable to ~scheduler noise, which is
        plenty for cross-process flow arrows."""
        return time.time_ns() - (time.perf_counter_ns() - self._epoch_ns)

    # -- emission ------------------------------------------------------
    def _append(self, rec: EventTuple) -> None:
        with self._lock:
            self._buf[self._write % self.capacity] = rec
            self._write += 1

    def span(self, name: str, cat: str = "app", **args):
        """Context manager timing a region; no-op singleton when disabled."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, cat, args)

    def complete(
        self,
        name: str,
        dur_s: float,
        cat: str = "app",
        ts_ns: Optional[int] = None,
        **args,
    ) -> None:
        """Record an already-measured span (duration in seconds)."""
        if not self.enabled:
            return
        dur_ns = int(dur_s * 1e9)
        if ts_ns is None:
            ts_ns = self.now_ns() - dur_ns
        ctx = tracing.current()
        if ctx is not None and ctx.sampled:
            args = dict(args)
            args["trace"] = ctx.trace_id
            args["span"] = tracing.new_span_id()
            if ctx.span_id is not None:
                args["parent"] = ctx.span_id
        self._append(
            (PH_COMPLETE, name, cat, ts_ns, dur_ns,
             threading.get_ident(), args or None)
        )

    def instant(self, name: str, cat: str = "app", **args) -> None:
        if not self.enabled:
            return
        self._append(
            (PH_INSTANT, name, cat, self.now_ns(), 0,
             threading.get_ident(), args or None)
        )

    def count(self, name: str, value: float = 1) -> None:
        """Accumulate a counter total (O(1); survives ring overflow)."""
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def counter_sample(self, name: str, cat: str = "counter") -> None:
        """Drop a timeline sample of ``name``'s current total into the ring."""
        if not self.enabled:
            return
        self.sample(name, self._counters.get(name, 0), cat=cat)

    def sample(self, name: str, value: float, cat: str = "counter") -> None:
        """Drop an explicit-value sample onto counter track ``name``
        (used for run-local live values, e.g. a transport mid-drain)."""
        if not self.enabled:
            return
        self._append(
            (PH_COUNTER, name, cat, self.now_ns(), 0,
             threading.get_ident(), {"value": value})
        )

    def record(self, name: str, value: float) -> None:
        """Add one observation to histogram ``name``."""
        if not self.enabled:
            return
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = _Hist()
            hist.add(value)

    # -- reading -------------------------------------------------------
    @property
    def dropped(self) -> int:
        """Events overwritten by ring overflow (totals are unaffected)."""
        return max(0, self._write - self.capacity)

    def mark(self) -> int:
        """Position token for :meth:`events_since` (monotone event count)."""
        return self._write

    def events(self) -> List[EventTuple]:
        """Retained events, oldest first."""
        return self.events_since(0)

    def events_since(self, mark: int) -> List[EventTuple]:
        """Events appended at/after ``mark`` that are still retained."""
        with self._lock:
            write = self._write
            start = max(mark, write - self.capacity, 0)
            return [
                self._buf[i % self.capacity]  # type: ignore[misc]
                for i in range(start, write)
            ]

    def counters(self) -> Dict[str, float]:
        return dict(self._counters)

    def histograms(self) -> Dict[str, dict]:
        return {name: h.summary() for name, h in self._hists.items()}

    def histograms_export(self) -> Dict[str, dict]:
        """Raw reservoir export (count/sum/min/max/samples) — the shape a
        worker ships in its ``stats`` reply so the router-side pulse can
        re-merge fleet-wide percentiles via :func:`merge_hists` instead of
        averaging per-worker p99s (which is statistically meaningless)."""
        with self._lock:
            return {
                name: {
                    "count": h.count,
                    "sum": h.total,
                    "min": h.vmin,
                    "max": h.vmax,
                    "samples": list(h.samples),
                }
                for name, h in self._hists.items()
                if h.count
            }

    def snapshot(self) -> dict:
        """Aggregated view: span stats by name, counter totals, histograms.

        This is the machine-readable summary behind ``stats`` and the bench
        gate — everything in it is derivable offline from the JSONL export
        (``obs.export.snapshot_from_jsonl`` rebuilds the same shape through
        the shared :func:`aggregate_span_stats`).
        """
        events = self.events()
        spans, instants = aggregate_span_stats(
            (rec[0], rec[1], rec[4] / 1e9) for rec in events
        )
        return {
            "schema": "ghs-obs-snapshot-v1",
            "spans": spans,
            "instants": instants,
            "counters": self.counters(),
            "histograms": self.histograms(),
            "events_retained": len(events),
            "events_dropped": self.dropped,
        }


def aggregate_span_stats(triples) -> Tuple[Dict[str, dict], Dict[str, int]]:
    """Fold ``(ph, name, dur_s)`` triples into the snapshot's span/instant
    tables — the ONE aggregation both the live bus and the JSONL reader use,
    so ``stats`` renders identically from either source."""
    spans: Dict[str, dict] = {}
    instants: Dict[str, int] = {}
    for ph, name, dur_s in triples:
        if ph == PH_COMPLETE:
            agg = spans.setdefault(name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += dur_s
            if dur_s > agg["max_s"]:
                agg["max_s"] = dur_s
        elif ph == PH_INSTANT:
            instants[name] = instants.get(name, 0) + 1
    for agg in spans.values():
        agg["mean_s"] = agg["total_s"] / agg["count"]
    return spans, instants


def _default_bus() -> EventBus:
    capacity = int(os.environ.get("GHS_OBS_CAPACITY", "65536"))
    enabled = os.environ.get("GHS_OBS", "1") != "0"
    return EventBus(capacity=capacity, enabled=enabled)


#: The process-global bus every instrumented layer emits to. Import the
#: MODULE-level accessor (``get_bus()``) or this name directly; tests swap
#: state via ``BUS.clear()`` / ``BUS.disable()`` rather than rebinding.
BUS = _default_bus()


def get_bus() -> EventBus:
    return BUS
