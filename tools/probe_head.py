"""Probe the filtered head's internals at RMAT-24 (r4 bisection follow-up).

Questions, each answered by a direct on-chip timing:
  1. How much of ``_filtered_head``'s ~4.6 s is the full-width MST mask
     (zeros(m_pad) + two scatters + copy)? -> time a mask-free variant
     that returns the n-sized L1 winners instead (the L1 marks are exactly
     ``unique(vmin0)`` — no scatter needed).
  2. Is the fused filter's ~6.2 s gather-bound? -> time the bare alive
     pass (two gathers + count) alone.
  3. Would sorting the gather indices help? -> time a 252M-element gather
     into the 16.8M-entry table with ascending vs random indices.

Usage: python tools/probe_head.py [scale]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def t3(fn, *args):
    """Min-of-3 timing with a FORCED host round trip per call:
    ``block_until_ready`` alone returns immediately on the axon tunnel
    backend (observed: every phase measures 0.00 s), so fetch one element
    of the last output leaf — that cannot complete before the whole output
    buffer exists on device."""
    import jax

    best = None
    out = None
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(*args)
        leaf = jax.tree_util.tree_leaves(out)[-1]
        np.asarray(leaf if getattr(leaf, "ndim", 0) == 0 else leaf[:1])
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best, out


def main():
    import functools

    import jax
    import jax.numpy as jnp

    from distributed_ghs_implementation_tpu.graphs.io import read_npz
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    scale = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    g = read_npz(f"/tmp/rmat{scale}_s24.npz")
    vmin0, ra, rb, parent1 = rs.prepare_rank_arrays_full(g)
    jax.block_until_ready((vmin0, ra, rb, parent1))
    n_pad = vmin0.shape[0]
    m_pad = ra.shape[0]
    prefix = rs._prefix_size(n_pad, m_pad, 1)
    log(f"n_pad={n_pad:,} m_pad={m_pad:,} prefix={prefix:,}")

    # 1a. The shipped head.
    head = functools.partial(rs._filtered_head, prefix=prefix)
    dt, (fragment, mst, fa, fb, stats) = t3(head, vmin0, ra, rb, parent1)
    log(f"head (with full-width mask): {dt:.2f}s")

    # 1b. Mask-free variant: identical work minus the m_pad-wide mask.
    @functools.partial(jax.jit, static_argnames=("prefix",))
    def head_nomask(vmin0, ra, rb, *, prefix):
        fragment, parent1, has1, safe1 = rs._level1_hook(vmin0, ra, rb)
        fa = parent1[ra[:prefix]]
        fb = parent1[rb[:prefix]]
        fragment, fa, fb, has2, safe2, count = rs._prefix_level2_core(
            fragment, fa, fb
        )
        mst_p = jnp.zeros(prefix, dtype=bool).at[safe2].max(has2)
        lv = jnp.asarray(1, jnp.int32) + jnp.any(has2).astype(jnp.int32)
        return fragment, mst_p, fa, fb, jnp.stack([lv, count])

    dt_nm, (fragment2, mst_p, fa2, fb2, stats2) = t3(
        functools.partial(head_nomask, prefix=prefix), vmin0, ra, rb
    )
    log(f"head (mask-free, prefix-width marks): {dt_nm:.2f}s")

    # 1c. L1 hook alone (the shared prologue).
    l1 = jax.jit(rs._level1_hook)
    dt_l1, _ = t3(l1, vmin0, ra, rb)
    log(f"  level1_hook alone: {dt_l1:.2f}s")

    # 2. Bare filter alive pass on the final prefix partition stand-in
    # (use the head's fragment — same access pattern and table size).
    @functools.partial(jax.jit, static_argnames=("prefix",))
    def alive_only(fragment, ra, rb, *, prefix):
        return jnp.sum(
            (fragment[ra[prefix:]] != fragment[rb[prefix:]]).astype(jnp.int32)
        )

    dt_alive, _ = t3(
        functools.partial(alive_only, prefix=prefix), fragment, ra, rb
    )
    log(f"filter alive pass alone (2 suffix gathers + count): {dt_alive:.2f}s")

    # 3. Sorted vs random gather, suffix-sized indices into an n-sized table.
    suffix = m_pad - prefix
    table = fragment[:n_pad]
    rng = np.random.default_rng(0)
    idx_rand = jnp.asarray(
        rng.integers(0, n_pad, size=suffix, dtype=np.int32)
    )
    idx_sort = jnp.sort(idx_rand)
    jax.block_until_ready((idx_rand, idx_sort))

    @jax.jit
    def gsum(table, idx):
        return jnp.sum(table[idx])

    dt_r, _ = t3(gsum, table, idx_rand)
    dt_s, _ = t3(gsum, table, idx_sort)
    log(f"gather {suffix/1e6:.0f}M from {n_pad/1e6:.1f}M-entry table: "
        f"random {dt_r:.2f}s vs sorted {dt_s:.2f}s")


if __name__ == "__main__":
    main()
