"""Fleet tier: framing, consistent-hash routing, failover/re-queue,
shedding, drain, and the shared-disk-store recovery path.

Most tests run against ``--test-echo`` workers (real subprocesses + real
pipes + real kills, canned answers — no kernel compiles), so the failover
machinery is exercised at full fidelity in seconds. One integration test
runs real ``MSTService`` workers end to end.
"""

import io
import os
import signal
import subprocess
import sys
import time

import pytest

from distributed_ghs_implementation_tpu.fleet.framing import (
    read_frame,
    write_frame,
)
from distributed_ghs_implementation_tpu.fleet.hashing import HashRing
from distributed_ghs_implementation_tpu.fleet.router import (
    FleetConfig,
    FleetRouter,
)
from distributed_ghs_implementation_tpu.obs.events import BUS


@pytest.fixture(autouse=True)
def _clean_global_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.enable()
    BUS.clear()


# ----------------------------------------------------------------------
# Framing
# ----------------------------------------------------------------------
def test_frame_round_trip_and_interleaved_stream():
    buf = io.BytesIO()
    frames = [{"id": 1, "req": {"op": "solve"}}, {"pong": 7}, {"drain": True}]
    for f in frames:
        write_frame(buf, f)
    buf.seek(0)
    assert [read_frame(buf) for _ in frames] == frames
    assert read_frame(buf) is None  # EOF


def test_frame_torn_and_garbage_reads_as_eof():
    # Torn payload: header promises more bytes than the stream holds.
    buf = io.BytesIO(b"100\n{\"id\": 1}")
    assert read_frame(buf) is None
    # Garbage header.
    assert read_frame(io.BytesIO(b"not-a-length\nxx\n")) is None
    # Valid length, invalid JSON.
    assert read_frame(io.BytesIO(b"2\nxx\n")) is None


# ----------------------------------------------------------------------
# Consistent hashing (satellite: stability + bounded movement)
# ----------------------------------------------------------------------
def test_ring_deterministic_across_instances():
    keys = [f"digest-{i}" for i in range(300)]
    a = HashRing([0, 1, 2])
    b = HashRing([2, 0, 1])  # insertion order must not matter
    assert [a.assign(k) for k in keys] == [b.assign(k) for k in keys]
    # ...and across "restarts": a freshly built ring maps identically.
    assert [HashRing([0, 1, 2]).assign(k) for k in keys] == [
        a.assign(k) for k in keys
    ]


def test_ring_remove_moves_only_the_dead_workers_keys():
    keys = [f"digest-{i}" for i in range(500)]
    ring = HashRing([0, 1, 2])
    before = {k: ring.assign(k) for k in keys}
    assert set(before.values()) == {0, 1, 2}  # every worker owns a share
    ring.remove(1)
    after = {k: ring.assign(k) for k in keys}
    for k in keys:
        if before[k] != 1:
            assert after[k] == before[k]  # survivors' keys never move
        else:
            assert after[k] in (0, 2)
    # Rejoin restores the original mapping exactly (cache affinity
    # survives a restart round-trip).
    ring.add(1)
    assert {k: ring.assign(k) for k in keys} == before


def test_ring_empty_raises_and_len_counts_members():
    ring = HashRing()
    assert len(ring) == 0
    with pytest.raises(LookupError):
        ring.assign("x")
    ring.add(3)
    assert len(ring) == 1 and ring.assign("anything") == 3


# ----------------------------------------------------------------------
# Echo fleet: routing, failover, re-queue idempotency
# ----------------------------------------------------------------------
@pytest.fixture(scope="module")
def echo_fleet():
    cfg = FleetConfig(
        workers=3, test_echo=True,
        heartbeat_interval_s=0.1, restart_backoff_base_s=0.02,
        restart_backoff_cap_s=0.2, ready_timeout_s=120.0,
        request_timeout_s=30.0,
    )
    router = FleetRouter(cfg).start()
    yield router
    router.shutdown()


def test_fleet_routes_deterministically_by_digest(echo_fleet):
    r = echo_fleet
    first = {
        d: r.handle({"op": "solve", "digest": d})["worker"]
        for d in (f"d{i}" for i in range(24))
    }
    assert set(first.values()) == {0, 1, 2}  # the deck spreads
    for d, w in first.items():
        assert r.handle({"op": "solve", "digest": d})["worker"] == w


def test_fleet_update_chain_sticks_to_the_session_worker(echo_fleet):
    r = echo_fleet
    solved = r.handle({"op": "solve", "digest": "chain-seed"})
    digest, workers = "chain-seed", set()
    for _ in range(5):
        resp = r.handle(
            {"op": "update", "digest": digest, "updates": [{"k": 1}]}
        )
        assert resp["ok"]
        digest = resp["digest"]
        workers.add(resp["worker"])
    # Re-keying renames the digest every hop; the session pin keeps every
    # hop on the worker that owns the materialized session.
    assert workers == {solved["worker"]}


def test_fleet_kill_mid_traffic_requeues_and_restarts(echo_fleet):
    r = echo_fleet
    victim = r.handle({"op": "solve", "digest": "kill-probe"})["worker"]
    restarts_before = r._workers[victim].restarts
    dead_before = BUS.counters().get("fleet.worker.dead", 0)
    # Arm the registry INSIDE the worker: it dies in place of its next
    # request (no response flushed) — the accepted query must still be
    # answered, by a survivor, via the digest re-queue.
    assert r.arm_worker_fault(victim, times=1)
    resp = r.handle({"op": "solve", "digest": "kill-probe", "slo_class": "x"})
    assert resp["ok"] and resp["worker"] != victim
    assert resp.get("requeued", 0) >= 1
    counters = BUS.counters()
    assert counters.get("fleet.worker.dead", 0) == dead_before + 1
    assert counters.get("fleet.requeue", 0) >= 1
    # The dead worker restarts with backoff and rejoins the ring...
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not r._workers[victim].alive:
        time.sleep(0.05)
    assert r._workers[victim].alive
    assert r._workers[victim].restarts == restarts_before + 1
    # ...and serves its keyspace again (deterministic mapping restored).
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        resp = r.handle({"op": "solve", "digest": "kill-probe"})
        assert resp["ok"]
        if resp["worker"] == victim:
            break
        time.sleep(0.05)
    assert resp["worker"] == victim


def test_fleet_same_digest_twice_lands_once_per_worker(echo_fleet):
    # Re-queue idempotency's foundation: duplicate digests route to the
    # same worker, whose scheduler single-flights them; a duplicated
    # *response* (late delivery from a "dead" worker) is discarded by the
    # pending-map pop, never delivered twice.
    r = echo_fleet
    a = r.handle({"op": "solve", "digest": "dup-digest"})
    b = r.handle({"op": "solve", "digest": "dup-digest"})
    assert a["ok"] and b["ok"] and a["worker"] == b["worker"]


def test_fleet_stats_aggregates_workers(echo_fleet):
    stats = echo_fleet.handle({"op": "stats"})
    assert stats["ok"] and stats["counters"].get("echo.handled", 0) >= 1
    assert sorted(stats["ring"]) == [0, 1, 2]
    assert set(stats["workers"]) == {"0", "1", "2"}


# ----------------------------------------------------------------------
# Admission control + drain (their own small fleets: they wedge queues)
# ----------------------------------------------------------------------
def test_fleet_sheds_configured_class_when_queue_full():
    cfg = FleetConfig(
        workers=1, test_echo=True, queue_depth=1,
        shed_classes=("droppable",), heartbeat_interval_s=0.2,
        ready_timeout_s=120.0, request_timeout_s=30.0,
    )
    with FleetRouter(cfg) as r:
        import threading

        slow = threading.Thread(
            target=r.handle,
            args=({"op": "solve", "digest": "slow", "sleep_s": 1.0},),
        )
        slow.start()
        time.sleep(0.3)  # the one slot is now held by the sleeper
        shed = r.handle(
            {"op": "solve", "digest": "x", "slo_class": "droppable"}
        )
        assert shed["shed"] and not shed["ok"]
        # A non-sheddable class backpressures instead and succeeds.
        kept = r.handle({"op": "solve", "digest": "y", "slo_class": "gold"})
        assert kept["ok"]
        slow.join()
        assert BUS.counters().get("fleet.shed", 0) == 1


def test_fleet_graceful_drain_answers_in_flight_and_exits_zero():
    cfg = FleetConfig(
        workers=1, test_echo=True, heartbeat_interval_s=0.2,
        ready_timeout_s=120.0,
    )
    r = FleetRouter(cfg).start()
    import threading

    results = []
    t = threading.Thread(
        target=lambda: results.append(
            r.handle({"op": "solve", "digest": "inflight", "sleep_s": 0.5})
        )
    )
    t.start()
    time.sleep(0.2)  # the request is in the worker when drain begins
    r.shutdown(drain=True)
    t.join(timeout=10)
    assert results and results[0]["ok"]  # drained, not dropped
    assert r._workers[0].proc.returncode == 0  # exit 0, not a kill


def test_worker_sigterm_drains_and_exits_zero(tmp_path):
    # SIGTERM straight at a worker process: drain semantics, exit 0.
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_ghs_implementation_tpu.fleet.worker",
         "--worker-id", "0", "--test-echo"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        env={**os.environ, "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + os.environ.get("PYTHONPATH", "").split(os.pathsep)
        )},
    )
    try:
        assert read_frame(proc.stdout).get("ready")
        write_frame(proc.stdin, {"id": 1, "req": {"op": "solve",
                                                  "digest": "d"}})
        assert read_frame(proc.stdout)["resp"]["ok"]
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()


# ----------------------------------------------------------------------
# Real-service fleet: cache affinity + shared-store failover
# ----------------------------------------------------------------------
def _solve_request(g, cls=None):
    req = {
        "op": "solve",
        "num_nodes": g.num_nodes,
        "edges": [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)],
    }
    if cls:
        req["slo_class"] = cls
    return req


def test_fleet_real_service_affinity_update_and_disk_failover(tmp_path):
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )

    cfg = FleetConfig(
        workers=2, disk_dir=str(tmp_path / "store"),
        heartbeat_interval_s=0.25, restart_backoff_base_s=0.05,
        ready_timeout_s=180.0, request_timeout_s=120.0,
    )
    with FleetRouter(cfg) as r:
        graphs = [gnm_random_graph(40, 90, seed=s) for s in range(3)]
        solved = [r.handle(_solve_request(g, "miss")) for g in graphs]
        assert all(s["ok"] for s in solved), solved
        # Affinity: a repeat is a cache hit on the SAME worker.
        again = r.handle(_solve_request(graphs[0], "hit"))
        assert again["ok"] and again["cached"]
        assert again["worker"] == solved[0]["worker"]
        # Updates flow through the session worker and re-key.
        upd = r.handle({
            "op": "update", "digest": solved[0]["digest"],
            "updates": [{"kind": "insert", "u": 0, "v": 7, "w": 1}],
        })
        assert upd["ok"] and upd["prev_digest"] == solved[0]["digest"]
        # Kill a worker; its digests must still be answerable by the
        # survivor THROUGH THE SHARED DISK STORE (no re-solve required,
        # though a re-solve would also be correct — same forest).
        victim = solved[1]["worker"]
        r.kill_worker(victim)
        time.sleep(0.5)
        after = r.handle(_solve_request(graphs[1], "hit"))
        assert after["ok"]
        assert after["total_weight"] == solved[1]["total_weight"]
