"""Deterministic discrete-event transport for the GHS protocol.

The reference's transports — per-thread ``queue.Queue`` with requeue caps
(``/root/reference/ghs_implementation.py:82-116``) and MPI ``iprobe``/``recv``
with deferred lists (``ghs_implementation_mpi.py:94-115,696-701``) — are both
sources of nondeterminism and the reason its liveness heuristics exist. This
transport is a single priority queue keyed ``(deliver_time, sequence)``:
identical runs deliver identical orders, deferred messages are redelivered at
a strictly later time, and quiescence (empty queue) is *exact* termination
detection — no idle counters, no polling (contrast
``ghs_implementation.py:442-526``).
"""

from __future__ import annotations

import heapq
import itertools
from typing import Dict

from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.protocol.messages import Message

# Counter-track sampling period for the event bus (in drained events): dense
# enough for a timeline shape, cheap enough to leave on.
_SAMPLE_EVERY = 8192


class SimTransport:
    """Event-queue message delivery with per-hop latency.

    ``latency`` may be a constant or a ``(src, dst) -> int`` callable, letting
    tests model asymmetric links and delivery races deterministically.
    """

    def __init__(self, latency=1, *, defer_delay: int = 1, max_events: int = 50_000_000):
        self._queue: list = []
        self._seq = itertools.count()
        self._latency = latency if callable(latency) else (lambda s, d: latency)
        self._defer_delay = defer_delay
        self._max_events = max_events
        self.now = 0
        self.messages_sent = 0
        self.messages_deferred = 0
        # Bus totals already published for this transport, per counter name
        # (publishing folds in only the delta, so driving run() repeatedly
        # on one transport never double-counts).
        self._published: Dict[str, int] = {}

    def send(self, src: int, dst: int, msg: Message) -> None:
        self.messages_sent += 1
        when = self.now + max(1, self._latency(src, dst))
        heapq.heappush(self._queue, (when, next(self._seq), dst, msg))

    def run(self, nodes: Dict[int, "GHSNode"]) -> int:
        """Drain the queue to quiescence; returns events processed.

        The loop is shared with every transport subclass; the per-item
        semantics live in :meth:`_dispatch` (``ReliableTransport`` overrides
        it for its DATA/ACK/TIMER/LOCAL vocabulary).
        """
        processed = 0
        iterations = 0
        with BUS.span("protocol.run", cat="protocol", nodes=len(nodes)) as span:
            while self._queue:
                iterations += 1  # counts deferrals too: livelock trips the guard
                if iterations >= self._max_events:
                    raise RuntimeError(
                        f"protocol did not quiesce within {self._max_events} events"
                    )
                if iterations % _SAMPLE_EVERY == 0:
                    self._sample_counters()
                when, _, target, item = heapq.heappop(self._queue)
                self.now = max(self.now, when)
                processed += self._dispatch(nodes, target, item)
            span.set(events=iterations, sim_ticks=self.now)
            self._publish_counters()
        return processed

    def _dispatch(self, nodes, dst: int, msg: Message) -> int:
        """Handle one popped queue item; returns messages processed (0/1)."""
        if nodes[dst].handle(msg):
            return 1
        # Protocol-mandated deferral: redeliver strictly later.
        self.messages_deferred += 1
        heapq.heappush(
            self._queue,
            (self.now + self._defer_delay, next(self._seq), dst, msg),
        )
        return 0

    # -- observability -------------------------------------------------
    def _bus_counters(self) -> Dict[str, int]:
        """Channel totals this transport contributes to the event bus."""
        return {
            "protocol.messages_sent": self.messages_sent,
            "protocol.messages_deferred": self.messages_deferred,
        }

    def _sample_counters(self) -> None:
        """Timeline samples of this run's live totals (periodic, from run()).

        Samples carry the run-local value; the bus counter *totals* are only
        folded in once, at quiescence, by :meth:`_publish_counters`.
        """
        if not BUS.enabled:
            return
        for name, value in self._bus_counters().items():
            BUS.sample(name, value)

    def _publish_counters(self) -> None:
        """Fold this transport's totals into the bus counters at quiescence —
        delta-based, so repeated run() calls on one transport publish each
        message exactly once."""
        if not BUS.enabled:
            return
        for name, value in self._bus_counters().items():
            delta = value - self._published.get(name, 0)
            if delta:
                BUS.count(name, delta)
            self._published[name] = value
            BUS.sample(name, value)
