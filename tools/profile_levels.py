"""Per-level timing breakdown of the ELL kernel on a real chip.

Answers VERDICT weak #1: where does the RMAT-20 solve time go? Times each
level individually (jitted single-level call + device sync), reports alive
fragment counts so the shrink profile is visible, then prints the fused
while_loop time for comparison (per-level sync overhead is the difference).

Usage: python tools/profile_levels.py [--scale 20] [--edge-factor 16]
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse
import functools
import time

import jax
import jax.numpy as jnp

from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
from distributed_ghs_implementation_tpu.models.boruvka import (
    _ell_level,
    _solve_ell,
    prepare_ell_arrays,
)


@functools.partial(jax.jit, static_argnames=("nbuckets",))
def _one_level(fragment, mst_ranks, *flat, nbuckets: int):
    buckets = tuple(
        (flat[3 * i], flat[3 * i + 1], flat[3 * i + 2]) for i in range(nbuckets)
    )
    ra, rb = flat[3 * nbuckets], flat[3 * nbuckets + 1]
    f2, m2, has = _ell_level(fragment, mst_ranks, buckets, ra, rb)
    # fragment entries are root ids and roots map to themselves, so the
    # distinct count is the number of self-mapped vertices (no sort needed).
    ids = jnp.arange(f2.shape[0], dtype=f2.dtype)
    return f2, m2, has, jnp.sum(f2 == ids)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--scale", type=int, default=20)
    p.add_argument("--edge-factor", type=int, default=16)
    p.add_argument("--trace-dir", default=None, help="write a jax profiler trace here")
    args = p.parse_args()

    t0 = time.perf_counter()
    g = rmat_graph(args.scale, args.edge_factor, seed=24)
    t_gen = time.perf_counter() - t0
    t0 = time.perf_counter()
    buckets, ra, rb, n_pad = prepare_ell_arrays(g)
    t_prep = time.perf_counter() - t0
    slot_total = sum(int(b[1].size) for b in buckets)
    print(
        f"RMAT-{args.scale}: n={g.num_nodes:,} m={g.num_edges:,} "
        f"gen={t_gen:.1f}s prep={t_prep:.1f}s "
        f"buckets={len(buckets)} padded_slots={slot_total:,} "
        f"(directed={2 * g.num_edges:,})"
    )
    for verts, dstb, rankb in buckets:
        print(f"  bucket W={dstb.shape[1]:>6}  rows={dstb.shape[0]:>9,}  slots={dstb.size:>11,}")

    flat = []
    for b in buckets:
        flat.extend(b)
    flat.extend([ra, rb])
    nb = len(buckets)

    fragment = jnp.arange(n_pad, dtype=jnp.int32)
    mst_ranks = jnp.zeros(ra.shape[0], dtype=bool)
    # warm compile (int() forces a real sync; block_until_ready does not
    # block on the axon remote backend)
    f2, m2, has, nf = _one_level(fragment, mst_ranks, *flat, nbuckets=nb)
    _ = int(nf)

    fragment = jnp.arange(n_pad, dtype=jnp.int32)
    mst_ranks = jnp.zeros(ra.shape[0], dtype=bool)
    level = 0
    total = 0.0
    while True:
        t0 = time.perf_counter()
        fragment, mst_ranks, has, nfrag = _one_level(
            fragment, mst_ranks, *flat, nbuckets=nb
        )
        nfrag_i = int(nfrag)  # syncs the whole level
        dt = time.perf_counter() - t0
        total += dt
        level += 1
        print(f"level {level:2d}: {dt * 1e3:8.2f} ms  fragments={nfrag_i:,}")
        if not bool(has) or level > 40:
            break
    print(f"stepped total: {total:.3f} s")

    out = _solve_ell(buckets_j := tuple(buckets), ra, rb, num_nodes=n_pad)
    _ = int(out[2])
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = _solve_ell(buckets_j, ra, rb, num_nodes=n_pad)
        _ = int(out[2])
        times.append(time.perf_counter() - t0)
    print(f"fused while_loop: best {min(times):.3f} s, levels={int(out[2])}")

    if args.trace_dir:
        with jax.profiler.trace(args.trace_dir):
            out = _solve_ell(buckets_j, ra, rb, num_nodes=n_pad)
            jax.block_until_ready(out[0])
        print(f"trace written to {args.trace_dir}")


if __name__ == "__main__":
    main()
