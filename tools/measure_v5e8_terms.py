"""Measure every term of the v5e-8 RMAT-24 projection on the real chip.

VERDICT r4 item 1: the 2.5-4 s 8-chip claim in docs/SCALING.md was
arithmetic. Every term of the sharded filtered program is single-chip
measurable at its actual per-shard width (mb = m_pad/8 = 2^25 for
RMAT-24/8), because the per-chip work contains no edge-width collectives:

  T_l1       level-1 marks on one rank block        (make_rank_sharded_l1, mb)
  T_prefix   the REPLICATED prefix solve            (_prefix_relabel_l2 +
             _finish_to_fixpoint at prefix = 2^24, exactly as
             solve_graph_rank_sharded runs it)
  T_filter   the per-shard filter relabel           (make_rank_filter_relabel,
             two gathers over the mb block)
  T_compact  per-shard survivor compaction          (_compact_slots at mb)
  T_finish   the post-gather survivor finish        (real survivors at the
             real gathered width, space = n_pad)
  T_pack     per-shard packbits for the harvest     (mb bits)

plus dispatch round trips (measured per-trip cost x trip count) and the
ICI transfers, which CANNOT be measured on one chip and stay arithmetic
(they are listed separately with their byte volumes).

All kernels run through the real mesh machinery on a 1-device mesh (the
collectives degenerate; the per-shard bodies are byte-identical). Timing
uses a tiny host fetch per measurement (block_until_ready is a no-op on
the tunneled backend). Emits one JSON blob; paste the table into
docs/SCALING.md.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def t(fn, *args, reps=3, fetch=None, **kwargs):
    """Best-of-reps wall time of a dispatched computation, forced by a tiny
    host fetch of (by default) every output leaf."""
    out = fn(*args, **kwargs)  # warm/compile
    _force(out if fetch is None else fetch(out))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        _force(out if fetch is None else fetch(out))
        best = min(best, time.perf_counter() - t0)
    return best, out


def _force(out):
    leaves = out if isinstance(out, (tuple, list)) else (out,)
    for leaf in leaves:
        if hasattr(leaf, "ravel"):
            _ = np.asarray(leaf.ravel()[:1])


def main() -> int:
    import jax
    import jax.numpy as jnp

    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.models import rank_solver as rs
    from distributed_ghs_implementation_tpu.parallel import rank_sharded as rsh
    from distributed_ghs_implementation_tpu.parallel.mesh import edge_mesh

    n_dev_target = 8
    scale = 24

    t0 = time.perf_counter()
    g = rmat_graph(scale, 16, seed=24)
    print(f"gen: {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    t0 = time.perf_counter()
    vmin0, ra, rb, parent1 = rs.prepare_rank_arrays_full(g)
    print(f"prep: {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    n_pad = vmin0.shape[0]
    m_pad = ra.shape[0]
    mb = m_pad // n_dev_target
    prefix = rs._prefix_size(n_pad, m_pad, mult=1)
    assert mb * n_dev_target == m_pad
    mesh1 = edge_mesh()
    res = {
        "config": f"RMAT-{scale}/{n_dev_target} term measurement",
        "n_pad": n_pad, "m_pad": m_pad, "mb": mb, "prefix": prefix,
        "round": 5,
    }

    slice_blk = jax.jit(
        lambda x, k: jax.lax.dynamic_slice(x, (k * mb,), (mb,)),
        static_argnums=1,
    )
    # A representative suffix block (block 5 of 8) — the filter term's cost
    # is gather-bound and block-independent (r4: sorted == random gather).
    ra_blk = slice_blk(ra, 5)
    rb_blk = slice_blk(rb, 5)

    # --- T_l1: level-1 marks over one rank block ---------------------------
    l1 = rsh.make_rank_sharded_l1(mesh1)
    res["t_l1_s"], (frag1, mst_blk) = t(l1, vmin0, parent1, ra_blk)

    # --- T_prefix: the replicated prefix solve, exactly as the sharded path
    # runs it (r5: host prefix-L2 + relabel + finish chunks; host trips
    # included). The host_level2 pass is prep-time work — timed separately
    # below as t_prefix_host_s (in production it overlaps staging) --------
    ra_p = jax.jit(lambda x: x[:prefix])(ra)
    rb_p = jax.jit(lambda x: x[:prefix])(rb)
    _force((ra_p, rb_p))
    ra_h, rb_h = g.rank_endpoints(pad_to=m_pad)
    parent1_np = np.asarray(parent1)
    t0 = time.perf_counter()
    parent12_np, l2r = rs.host_level2(parent1_np, ra_h, rb_h, prefix)
    res["t_prefix_host_s"] = time.perf_counter() - t0
    parent12 = jax.device_put(parent12_np)
    l2_staged = jax.device_put(rs._pad_l2_ranks(l2r, m_pad))
    _force((parent12, l2_staged))

    def prefix_phase():
        fragment, mst_p, fa_p, fb_p, stats = rsh._prefix_relabel_l2(
            parent12, ra_p, rb_p, l2_staged
        )
        lv2, count = (int(x) for x in jax.device_get(stats))
        mst_p, fragment, lv = rs._finish_to_fixpoint(
            fragment, mst_p, fa_p, fb_p,
            jnp.arange(prefix, dtype=jnp.int32),
            lv=1 + lv2, count=count, space=n_pad,
            max_levels=1 + lv2 + rs._max_levels(n_pad),
            chunk_levels=3, compact_space=n_pad >= rs._CENSUS_MIN_SPACE,
        )
        return fragment, mst_p, lv

    # warm (compiles); then time twice (the mask buffer is freshly built
    # each call, so repeats are true re-runs)
    fragment_f, mst_p, lv = prefix_phase()
    _force((fragment_f, mst_p))
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        fragment_f, mst_p, lv = prefix_phase()
        _force((fragment_f, mst_p))
        best = min(best, time.perf_counter() - t0)
    res["t_prefix_s"] = best
    res["prefix_levels"] = int(lv)

    # --- T_filter: per-shard filter relabel at mb (suffix shard: the
    # prefix-mark merge indexes an 8-wide stub mask) ------------------------
    filt = rsh.make_rank_filter_relabel(mesh1, 8)
    stub_mask = jnp.zeros(8, dtype=bool)
    res["t_filter_s"], (mst_f, fa_blk, fb_blk, fstats) = t(
        filt, fragment_f, stub_mask, mst_blk, ra_blk, rb_blk
    )
    total_blk, cmax_blk = (int(x) for x in jax.device_get(fstats))
    res["block_survivors"] = total_blk

    # --- T_compact: per-shard survivor compaction at mb --------------------
    fs_local = max(rs._bucket_size(cmax_blk), 1024)
    res["fs_local"] = fs_local
    crank_blk = jnp.arange(5 * mb, 6 * mb, dtype=jnp.int32)
    compact = jax.jit(rs._compact_slots, static_argnames=("out_size",))
    res["t_compact_s"], (cfa, cfb, crank, _) = t(
        compact, fa_blk, fb_blk, crank_blk, out_size=fs_local
    )

    # --- T_filter_compact: the FUSED per-shard filter+compaction (the
    # production path; the two separate terms above are its fallback) ------
    fc = rsh.make_rank_filter_compact(mesh1, 8, fs_local)
    res["t_filter_compact_fused_s"], _out = t(
        fc, fragment_f, stub_mask, mst_blk, ra_blk, rb_blk
    )

    # --- T_finish: survivor finish at the gathered width. Emulate the
    # all-gather output: per-shard compactions concatenated in block order
    # (that IS what all_gather produces), then finish replicated ------------
    blocks = []
    for k in range(n_dev_target):
        rab = slice_blk(ra, k)
        rbb = slice_blk(rb, k)
        mstb = l1(vmin0, parent1, rab)[1]
        mb_mask, fab, fbb, _ = filt(fragment_f, stub_mask, mstb, rab, rbb)
        ck = jnp.arange(k * mb, (k + 1) * mb, dtype=jnp.int32)
        blocks.append(compact(fab, fbb, ck, out_size=fs_local)[:3])
    gfa = jnp.concatenate([b[0] for b in blocks])
    gfb = jnp.concatenate([b[1] for b in blocks])
    gcrank = jnp.concatenate([b[2] for b in blocks])
    _force((gfa, gfb, gcrank))
    res["gathered_width"] = int(gfa.shape[0])
    total = int(jnp.sum((gfa != gfb).astype(jnp.int32)))

    def finish_phase():
        mst_fin, frag_fin, lvf = rs._finish_to_fixpoint(
            fragment_f, jnp.zeros(m_pad, dtype=bool), gfa, gfb, gcrank,
            lv=lv, count=total, space=n_pad,
            max_levels=lv + rs._max_levels(n_pad),
            chunk_levels=3, compact_space=n_pad >= rs._CENSUS_MIN_SPACE,
        )
        return mst_fin, frag_fin, lvf

    mst_fin, frag_fin, lvf = finish_phase()
    _force((mst_fin, frag_fin))
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        mst_fin, frag_fin, lvf = finish_phase()
        _force((mst_fin, frag_fin))
        best = min(best, time.perf_counter() - t0)
    res["t_finish_s"] = best
    res["total_levels"] = int(lvf)

    # --- T_pack: per-shard packbits --------------------------------------
    pack = jax.jit(lambda x: jnp.packbits(x))
    res["t_pack_s"], _ = t(pack, mst_blk)

    # --- dispatch round-trip cost ----------------------------------------
    tiny = jax.jit(lambda x: x + 1)
    res["t_dispatch_s"], _ = t(tiny, jnp.zeros(8, jnp.int32), reps=5)

    # --- correctness cross-check: the emulated 8-shard program must land
    # on the oracle weight (l1 marks across all blocks + prefix marks +
    # finish marks over global cranks) -------------------------------------
    # Reuse the production sharded entry on the 1-device mesh for the weight
    # check instead of re-assembling marks by hand.
    edge_ids, _, _ = rsh.solve_graph_rank_sharded(g, mesh=mesh1, filtered=True)
    w = int(g.w[edge_ids].sum())
    res["sharded_weight"] = w
    res["weight_ok"] = bool(w == 518_885_017)

    # ICI terms (NOT measurable single-chip): byte volumes for the table.
    res["ici_bytes"] = {
        "prefix_replicate": 2 * prefix * 4,
        "survivor_all_gather": 3 * fs_local * 4 * (n_dev_target - 1),
        "packed_mask_all_gather": m_pad // 8,
        "n_sized_pmin_equivalents": 0,
    }
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
