"""Command-line interface: generate / run / verify / experiments / bench.

Flag-compatible supersets of the reference's two CLIs:

* ``generate`` mirrors ``create_graph_files.py``'s argparse surface
  (``--nodes --edge-prob --seed --output-dir``,
  ``/root/reference/create_graph_files.py:151-170``) and adds G(n,m)/RMAT
  generators and npz output for large graphs.
* ``run --graph-dir`` mirrors the MPI runner's flag
  (``ghs_implementation_mpi.py:894-901``); instead of ``mpiexec -n N`` the
  backend flag picks device/sharded/protocol execution.
* ``verify`` is ``check_mst.py`` as a real subcommand (the reference's has a
  hard-coded directory, ``check_mst.py:4``).
* ``experiments`` is the suite of ``ghs_implementation.py:779-835``.

Usage: ``python -m distributed_ghs_implementation_tpu <subcommand> ...``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _cmd_generate(args) -> int:
    from distributed_ghs_implementation_tpu.graphs import generators, io

    t0 = time.perf_counter()
    if args.kind == "er":
        g = generators.erdos_renyi_graph(
            args.nodes, args.edge_prob, seed=args.seed
        )
    elif args.kind == "reference":
        g = generators.reference_random_graph(args.nodes, args.edge_prob, args.seed)
    elif args.kind == "gnm":
        g = generators.gnm_random_graph(args.nodes, args.edges, seed=args.seed)
    elif args.kind == "rmat":
        g = generators.rmat_graph(args.rmat_scale, args.rmat_edge_factor, seed=args.seed)
    elif args.kind == "simple-test":
        g = generators.simple_test_graph()
    else:
        raise ValueError(args.kind)
    print(
        f"generated {args.kind}: {g.num_nodes:,} nodes, {g.num_edges:,} edges "
        f"in {time.perf_counter() - t0:.2f}s",
        file=sys.stderr,
    )
    if args.npz:
        os.makedirs(args.output_dir, exist_ok=True)
        path = io.write_npz(g, os.path.join(args.output_dir, "graph.npz"))
        print(path)
    else:
        io.write_partition_dir(g, args.output_dir)
        print(args.output_dir)
    if args.visualize:
        from distributed_ghs_implementation_tpu.utils.viz import visualize_graph

        visualize_graph(g, os.path.join(args.output_dir, "input_graph.png"))
    return 0


def _load_graph(args):
    from distributed_ghs_implementation_tpu.graphs import io

    if args.graph_dir.endswith(".npz"):
        return io.read_npz(args.graph_dir)
    return io.read_partition_dir(args.graph_dir)


def _cmd_run(args) -> int:
    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.utils.reporting import (
        result_to_dict,
        write_result_json,
    )

    primary = True
    if args.multihost:
        from distributed_ghs_implementation_tpu.parallel import multihost

        multihost.initialize()
        primary = multihost.is_primary()

    g = _load_graph(args)
    if args.checkpoint:
        if args.backend not in ("device", "sharded"):
            raise SystemExit("--checkpoint requires --backend device or sharded")
        if args.supervised:
            raise SystemExit(
                "--supervised and --checkpoint are separate recovery paths; "
                "pick one (checkpointed solves already self-heal via the "
                ".bak generation fallback)"
            )
        import numpy as np

        from distributed_ghs_implementation_tpu.api import MSTResult
        from distributed_ghs_implementation_tpu.utils.checkpoint import (
            solve_graph_checkpointed,
            solve_graph_checkpointed_sharded,
        )

        t0 = time.perf_counter()
        if args.backend == "sharded":
            edge_ids, fragment, levels = solve_graph_checkpointed_sharded(
                g, args.checkpoint, every=args.checkpoint_every
            )
        else:
            edge_ids, fragment, levels = solve_graph_checkpointed(
                g, args.checkpoint, every=args.checkpoint_every
            )
        result = MSTResult(
            graph=g,
            edge_ids=edge_ids,
            num_levels=levels,
            wall_time_s=time.perf_counter() - t0,
            backend=f"{args.backend}/checkpointed",
            num_components=int(np.unique(fragment).size),
        )
    else:
        supervisor = None
        if args.supervised and args.deadline_s is not None:
            from distributed_ghs_implementation_tpu.utils.resilience import (
                Supervisor,
                SupervisorConfig,
            )

            supervisor = Supervisor(SupervisorConfig(deadline_s=args.deadline_s))
        result = minimum_spanning_forest(
            g, backend=args.backend, supervised=args.supervised,
            supervisor=supervisor,
        )
    if not primary:
        return 0  # artifacts are written by process 0 only
    if result.incidents is not None and len(result.incidents):
        print(f"supervisor: {result.incidents.summary()}", file=sys.stderr)
    print(json.dumps(result_to_dict(result), indent=2))
    if args.output:
        write_result_json(result, args.output)
    if args.metrics_out:
        # The bench-gate schema (tools/bench_gate.py), so ad-hoc runs gate
        # against saved baselines exactly like `ghs bench` runs do.
        with open(args.metrics_out, "w") as f:
            json.dump(
                {
                    "schema": "ghs-bench-metrics-v1",
                    "config": {
                        "workload": f"run-{os.path.basename(args.graph_dir)}"
                        f"-{result.backend}",
                    },
                    "metrics": {
                        "solve_s": result.wall_time_s,
                        "levels": int(result.num_levels),
                        "mst_weight": result.total_weight,
                        "mst_edges": int(result.num_edges),
                    },
                },
                f,
                indent=2,
            )
            f.write("\n")
    if args.visualize:
        from distributed_ghs_implementation_tpu.utils.viz import visualize_mst

        out = args.output or "mst_result.json"
        visualize_mst(result, os.path.splitext(out)[0] + ".png")
    if args.verify:
        from distributed_ghs_implementation_tpu.utils.verify import verify_result

        v = verify_result(result)
        print(
            f"verify[{v.oracle}]: {'OK' if v.ok else 'FAIL'} "
            f"(weight {v.actual_weight} vs {v.expected_weight}, "
            f"edges {v.actual_edges} vs {v.expected_edges})",
            file=sys.stderr,
        )
        if not v.ok:
            # Auto-dump diagnostics on failure, like the reference's debug
            # dump trigger (ghs_implementation.py:735-737).
            from distributed_ghs_implementation_tpu.utils.diagnostics import (
                dump_failure_report,
            )

            path = dump_failure_report(
                result, v,
                path=os.path.splitext(args.output or "ghs_result")[0]
                + "_failure_report.json",
            )
            print(f"diagnostics written to {path}", file=sys.stderr)
        return 0 if v.ok else 1
    return 0


def _cmd_verify(args) -> int:
    """check_mst.py parity: print the oracle MST for a graph dir."""
    from distributed_ghs_implementation_tpu.utils.verify import (
        networkx_mst_edges,
        networkx_mst_weight,
        scipy_mst_weight,
    )

    g = _load_graph(args)
    if g.num_edges <= 200_000:
        weight = networkx_mst_weight(g)
        edges = sorted(networkx_mst_edges(g))
        print(f"expected MST weight: {weight}")
        for a, b in edges:
            print(f"  ({a}, {b})")
    else:
        weight = scipy_mst_weight(g)
        print(f"expected MSF weight: {weight}")
    if args.result:
        with open(args.result) as f:
            res = json.load(f)
        ok = abs(float(res["total_weight"]) - float(weight)) < 1e-6
        print(f"result file {args.result}: {'OK' if ok else 'MISMATCH'}")
        return 0 if ok else 1
    return 0


def _cmd_experiments(args) -> int:
    from distributed_ghs_implementation_tpu.experiments import run_suite

    records = run_suite(
        backend=args.backend,
        extended=args.extended,
        output_json=args.output,
        visualize_dir=args.visualize_dir,
    )
    return 0 if all(r["is_correct"] for r in records) else 1


def _cmd_chaos(args) -> int:
    from distributed_ghs_implementation_tpu.utils import chaos

    report = chaos.run_chaos_drill(
        fast=not args.full, include_solver=not args.no_solver
    )
    return chaos.emit_report(report, args.output)


def _trace_graph(args):
    """The graph a trace/stats session runs on: ``--graph-dir`` when given,
    else a seeded G(n,m) (default 1k nodes — small enough to trace every
    backend, big enough for multi-level solver activity)."""
    if args.graph_dir:
        return _load_graph(args)
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )

    edges = args.edges or 4 * args.nodes
    return gnm_random_graph(args.nodes, edges, seed=args.seed)


def _traced_session(args):
    """Run one fully-instrumented solve session; returns the event bus.

    The solve goes through the self-healing supervisor (entry = the chosen
    backend rung), so armed ``GHS_FAULT_*`` sites surface as structured
    ``resilience.attempt`` retry events in the trace. Default entry is the
    ``stepped`` rung — the host-stepped kernel emits one ``solver.level``
    span per level, which is the timeline a trace is for. Unless disabled,
    a protocol pass over the same graph rides along and contributes message
    counters (``protocol.*``) to the same trace.
    """
    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.obs.events import BUS
    from distributed_ghs_implementation_tpu.utils.resilience import FAULTS

    BUS.enable()
    BUS.clear()
    FAULTS.reload_env()
    g = _trace_graph(args)
    with BUS.span(
        "trace.session", cat="trace", backend=args.backend,
        nodes=g.num_nodes, edges=g.num_edges,
    ):
        if args.backend == "protocol":
            from distributed_ghs_implementation_tpu.protocol.runner import (
                solve_graph_protocol,
            )

            solve_graph_protocol(g)
        else:
            result = minimum_spanning_forest(
                g, backend=args.backend, supervised=True
            )
            if result.incidents is not None and len(result.incidents):
                print(
                    f"supervisor: {result.incidents.summary()}", file=sys.stderr
                )
            if (
                not args.no_protocol_sample
                and g.num_nodes <= args.protocol_sample_max
            ):
                from distributed_ghs_implementation_tpu.protocol.runner import (
                    solve_graph_protocol,
                )

                with BUS.span(
                    "trace.protocol_sample", cat="trace", nodes=g.num_nodes
                ):
                    solve_graph_protocol(g)
    return BUS


def _cmd_trace(args) -> int:
    from distributed_ghs_implementation_tpu.obs.export import (
        render_stats,
        write_chrome_trace,
        write_events_jsonl,
    )

    bus = _traced_session(args)
    write_chrome_trace(bus, args.out)
    if args.jsonl:
        write_events_jsonl(bus, args.jsonl)
    print(render_stats(bus.snapshot()), file=sys.stderr)
    print("open in https://ui.perfetto.dev or chrome://tracing", file=sys.stderr)
    print(args.out)
    return 0


def _cmd_stats(args) -> int:
    from distributed_ghs_implementation_tpu.obs.export import (
        render_stats,
        snapshot_from_jsonl,
    )

    if args.input:
        snapshot = snapshot_from_jsonl(args.input)
    else:
        snapshot = _traced_session(args).snapshot()
    print(render_stats(snapshot))
    return 0


def _cmd_merge_trace(args) -> int:
    """Join per-process span JSONL logs (router + workers, or any set of
    ``write_events_jsonl`` exports) into one Perfetto trace with flow
    arrows across process hops, plus the per-trace critical-path report
    (docs/OBSERVABILITY.md has the walkthrough)."""
    from distributed_ghs_implementation_tpu.obs.export import (
        write_merged_trace,
    )

    report = write_merged_trace(args.inputs, args.out, args.report)
    cp = report["critical_path"]["summary"]
    print(
        f"merged {len(report['processes'])} processes, "
        f"{report['spans_indexed']} spans, "
        f"{report['flow_arrows']} flow arrows",
        file=sys.stderr,
    )
    print(
        f"traces: {report['traces_total']} total, "
        f"{report['traces_joined']} joined across processes, "
        f"{report['orphan_spans']} orphan spans",
        file=sys.stderr,
    )
    if cp.get("traces"):
        print(
            f"critical path over {cp['traces']} rooted traces: "
            f"queue {cp['queue_s']:.3f}s, transport {cp['transport_s']:.3f}s, "
            f"solve {cp['solve_s']:.3f}s, verify {cp['verify_s']:.3f}s "
            f"(accounted >= {cp['accounted_frac_min']:.3f})",
            file=sys.stderr,
        )
    print("open in https://ui.perfetto.dev or chrome://tracing",
          file=sys.stderr)
    print(args.out)
    return 0


def _cmd_serve(args) -> int:
    """The MST query service: JSONL requests on stdin (or --input), JSON
    responses on stdout (serve/service.py has the protocol). ``--fleet N``
    serves the same protocol through N digest-routed worker processes with
    health-checked failover (fleet/router.py, docs/FLEET.md)."""
    from distributed_ghs_implementation_tpu.serve.service import (
        MSTService,
        serve_frames,
        serve_loop,
    )

    def _serve_stdio(handler) -> int:
        # One switch for both fleet and single-process serving: the binary
        # wire swaps the carrier (framed binary stdio, B-frame ingest/
        # egress), never the handler.
        if args.wire == "binary":
            if args.input:
                with open(args.input, "rb") as f:
                    return serve_frames(f, sys.stdout.buffer, handler)
            return serve_frames(sys.stdin.buffer, sys.stdout.buffer, handler)
        if args.input:
            with open(args.input) as f:
                return serve_loop(f, sys.stdout, handler)
        return serve_loop(sys.stdin, sys.stdout, handler)

    if args.kernel:
        # Process default for every solve layer (kernel_choice), exported
        # through the environment so fleet worker subprocesses resolve the
        # same variant their warmup precompiles.
        from distributed_ghs_implementation_tpu.ops.pallas_kernels import (
            set_default_kernel,
        )

        set_default_kernel(args.kernel)
        os.environ["GHS_KERNEL"] = args.kernel

    if args.fleet_elastic and not (args.fleet or args.fleet_workers):
        raise SystemExit("--fleet-elastic needs --fleet N")
    if args.fleet or args.fleet_workers:
        from distributed_ghs_implementation_tpu.fleet.router import (
            FleetConfig,
            FleetRouter,
        )

        if args.warmup_record:
            raise SystemExit(
                "--warmup-record is per-worker state the router cannot "
                "see; record from a single-process serve, then replay "
                "with --fleet --warmup-replay"
            )
        remote = tuple(
            a for a in (args.fleet_workers or "").split(",") if a
        )
        if remote and args.fleet and args.fleet != len(remote):
            raise SystemExit(
                f"--fleet {args.fleet} contradicts --fleet-workers "
                f"({len(remote)} endpoints); drop --fleet or make them match"
            )
        config = FleetConfig(
            workers=len(remote) or args.fleet,
            transport="tcp" if remote else args.fleet_transport,
            remote_workers=remote,
            forward_cache={"auto": None, "on": True, "off": False}[
                args.fleet_forward_cache
            ],
            lease_s=args.fleet_lease,
            journal_dir=args.fleet_journal,
            backend=args.backend,
            batch_lanes=args.batch_lanes,
            store_capacity=args.cache_entries,
            disk_dir=args.disk_cache,
            max_concurrent=args.max_concurrent,
            resolve_threshold=args.resolve_threshold,
            queue_depth=args.fleet_queue_depth,
            shed_classes=tuple(
                c for c in (args.fleet_shed or "").split(",") if c
            ),
            warmup_buckets=args.warmup_buckets,
            warmup_replay=args.warmup_replay,
            warmup_mesh_buckets=args.warmup_mesh_buckets,
            warmup_stream_buckets=args.warmup_stream_buckets,
            compile_cache_dir=args.compile_cache_dir,
            no_compile_cache=args.no_compile_cache,
            tune_record=args.tune_record,
            obs_dir=args.fleet_obs_dir,
            sharded_lane_workers=args.sharded_lane,
            stream_dir=args.stream_dir,
            stream_snapshot_every=args.stream_snapshot_every,
            verify=args.verify_policy,
        )
        autoscaler = None
        if args.fleet_elastic:
            from distributed_ghs_implementation_tpu.fleet.autoscaler import (
                Autoscaler,
                ElasticPolicy,
                parse_class_budgets,
            )

            mn, _, mx = args.fleet_elastic.partition(":")
            try:
                policy = ElasticPolicy(
                    min_workers=int(mn),
                    max_workers=int(mx),
                    wait_budget_s=args.fleet_scale_budget,
                    class_budgets_s=parse_class_budgets(
                        args.fleet_scale_budgets or ""
                    ),
                    cooldown_s=args.fleet_scale_cooldown,
                )
            except ValueError as e:
                raise SystemExit(f"--fleet-elastic: {e}")
            if remote:
                raise SystemExit(
                    "--fleet-elastic needs spawnable workers; a "
                    "--fleet-workers remote topology is fixed by its "
                    "endpoint list"
                )
            if not policy.min_workers <= config.workers <= policy.max_workers:
                raise SystemExit(
                    f"--fleet {config.workers} must sit inside "
                    f"--fleet-elastic {policy.min_workers}:"
                    f"{policy.max_workers}"
                )
        # Workers enable the (shared, machine-fingerprinted) persistent
        # compile cache and run warmup themselves; the router never
        # compiles, so none of that happens in this process.
        with FleetRouter(config) as router:
            print(
                f"fleet: {config.workers} workers ready over "
                f"{config.transport} (queue_depth={config.queue_depth}"
                + (", forward_cache on" if config.forward_enabled else "")
                + (f", elastic {args.fleet_elastic}"
                   if args.fleet_elastic else "")
                + ")",
                file=sys.stderr,
            )
            if args.fleet_elastic:
                autoscaler = Autoscaler(router, policy).start()
            try:
                return _serve_stdio(router)
            finally:
                if autoscaler is not None:
                    autoscaler.close()

    # Persistent compile cache first (default ON for serve): config must
    # land before the first compile — warmup's included.
    if not args.no_compile_cache:
        from distributed_ghs_implementation_tpu.utils.compile_cache import (
            enable_persistent_cache,
        )

        cache_dir = enable_persistent_cache(args.compile_cache_dir)
        if cache_dir:
            print(f"compile cache: {cache_dir}", file=sys.stderr)

    if args.tune_record:
        # Measured kernel winners land before the first kernel_choice —
        # warmup's precompiles included (stale/missing degrades to the
        # probe heuristic, never an error).
        from distributed_ghs_implementation_tpu.tune import load_and_install

        installed = load_and_install(args.tune_record)
        print(
            f"tune record: {installed} bucket(s) from {args.tune_record}",
            file=sys.stderr,
        )

    from distributed_ghs_implementation_tpu.batch.warmup import plan_from_flags

    warmup_plan = plan_from_flags(
        buckets=args.warmup_buckets,
        replay=args.warmup_replay,
        lanes=args.batch_lanes,
        mesh_buckets=args.warmup_mesh_buckets,
        stream_buckets=args.warmup_stream_buckets,
        kernel=args.kernel,
        tuning=args.tune_record,
    )

    service = MSTService(
        backend=args.backend,
        store_capacity=args.cache_entries,
        disk_dir=args.disk_cache,
        max_concurrent=args.max_concurrent,
        resolve_threshold=args.resolve_threshold,
        batch_lanes=args.batch_lanes,
        warmup=warmup_plan,
        # -1 = the bare flag: all devices; N > 0 = a submesh of N.
        sharded_lane=(True if args.sharded_lane == -1
                      else max(0, args.sharded_lane)),
        stream_dir=args.stream_dir,
        stream_snapshot_every=args.stream_snapshot_every,
        verify=args.verify_policy,
    )
    if service.warmup_report is not None:
        print(f"warmup: {json.dumps(service.warmup_report)}", file=sys.stderr)
    try:
        return _serve_stdio(service)
    finally:
        if args.warmup_record:
            from distributed_ghs_implementation_tpu.batch import warmup as warmup_mod

            # Traffic-only record: the shapes requests actually hit, not
            # whatever a warmup ladder happened to compile — replayed
            # records converge to real traffic across restarts.
            count = warmup_mod.save_bucket_record(
                args.warmup_record,
                shape_buckets=list(service.seen_buckets),
                include_compiled=False,
            )
            print(
                f"warmup record: {count} bucket(s) -> {args.warmup_record}",
                file=sys.stderr,
            )


def _cmd_tune(args) -> int:
    """Offline kernel autotuner: enumerate the valid kernel x geometry
    candidates per bucket, score them (seeded, warm-then-median, parity-
    gated), and persist a machine-fingerprinted ``ghs-tuning-v1`` record
    that ``kernel_choice``'s auto tier consults per bucket
    (docs/KERNELS.md "Autotuning"). Off TPU — and always with ``--dry``
    — winners deterministically pin ``xla``, so two runs yield
    byte-identical records (CI's gate-tune-v1 asserts exactly that)."""
    from distributed_ghs_implementation_tpu.batch import warmup as warmup_mod
    from distributed_ghs_implementation_tpu.tune import (
        default_record_path,
        save_record,
        search,
    )
    from distributed_ghs_implementation_tpu.tune.measure import mesh_bucket

    lanes = max(0, args.lanes)
    buckets = []
    if args.buckets:
        for n, m in warmup_mod.parse_bucket_list(args.buckets):
            if lanes >= 1:
                buckets.append((n, m, lanes, args.mode))
            # The single-graph (miss-path) variant serves the same shapes.
            buckets.append((n, m, 0, "fused"))
    if args.warmup_record:
        # A --warmup-record file from a serving run: tune exactly the
        # buckets real traffic compiled.
        plan = warmup_mod.load_bucket_record(args.warmup_record)
        buckets.extend(tuple(k) for k in plan.keys)
    if args.mesh_buckets:
        import jax

        n_dev = jax.device_count()
        for n, m in warmup_mod.parse_mesh_bucket_list(args.mesh_buckets):
            buckets.append(mesh_bucket(n, m, n_dev))
    if not buckets:
        raise SystemExit(
            "tune: nothing to tune; pass --buckets, --warmup-record, "
            "and/or --mesh-buckets"
        )
    record = search(buckets, repeats=args.repeats, dry=args.dry)
    out = args.out or default_record_path()
    save_record(record, out)
    print(json.dumps({
        "path": out,
        "fingerprint": record["fingerprint"],
        "backend": record["backend"],
        "pinned": record["pinned"],
        "buckets": len(record["entries"]),
        "winners": {
            k: e["kernel"] for k, e in sorted(record["entries"].items())
        },
    }, indent=2, sort_keys=True))
    return 0


def _cmd_bench(args) -> int:
    import bench as bench_mod  # repo-root bench.py

    argv = ["--scale", str(args.scale),
            "--edge-factor", str(args.edge_factor),
            "--repeats", str(args.repeats), "--backend", args.backend]
    if args.no_verify:
        argv.append("--no-verify")
    if args.metrics_out:
        argv += ["--metrics-out", args.metrics_out]
    if args.batch_lanes:
        argv += ["--batch-lanes", str(args.batch_lanes)]
    if args.warmup:
        argv.append("--warmup")
    if args.update_stream:
        argv.append("--update-stream")
    return bench_mod.main(argv)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="distributed_ghs_implementation_tpu", description=__doc__
    )
    sub = p.add_subparsers(dest="command", required=True)

    g = sub.add_parser("generate", help="generate a graph + partition files")
    g.add_argument("--nodes", type=int, default=6)  # create_graph_files.py default
    g.add_argument("--edge-prob", type=float, default=0.5)
    g.add_argument("--seed", type=int, default=42)
    g.add_argument("--output-dir", default="graph_data")
    g.add_argument(
        "--kind",
        default="reference",
        choices=["reference", "er", "gnm", "rmat", "simple-test"],
    )
    g.add_argument("--edges", type=int, default=8192, help="for --kind gnm")
    g.add_argument("--rmat-scale", type=int, default=16)
    g.add_argument("--rmat-edge-factor", type=int, default=16)
    g.add_argument("--npz", action="store_true", help="write graph.npz instead of JSON")
    g.add_argument("--visualize", action="store_true")
    g.set_defaults(fn=_cmd_generate)

    r = sub.add_parser("run", help="compute the MST of a graph dir / npz")
    r.add_argument("--graph-dir", default="graph_data")
    r.add_argument(
        "--backend", default="device", choices=["device", "sharded", "protocol"]
    )
    r.add_argument("--output", help="write mst_result.json here")
    r.add_argument("--visualize", action="store_true")
    r.add_argument("--verify", action="store_true")
    r.add_argument(
        "--multihost",
        action="store_true",
        help="initialize jax.distributed first (see launcher/run_ghs.slurm)",
    )
    r.add_argument(
        "--checkpoint",
        help="write per-level solver state here and resume from it if present",
    )
    r.add_argument(
        "--checkpoint-every", type=int, default=1, help="levels between checkpoints"
    )
    r.add_argument(
        "--supervised",
        action="store_true",
        help="self-healing solve: watchdog + retry/backoff + the "
        "sharded->device->stepped->host degradation ladder "
        "(utils/resilience.py)",
    )
    r.add_argument(
        "--deadline-s",
        type=float,
        help="with --supervised: watchdog deadline per attempt, checked at "
        "chunk/level boundaries",
    )
    r.add_argument(
        "--metrics-out",
        help="write bench-gate metrics JSON here (tools/bench_gate.py; "
        "same schema as `bench --metrics-out`)",
    )
    r.set_defaults(fn=_cmd_run)

    v = sub.add_parser("verify", help="print the oracle MST for a graph dir")
    v.add_argument("--graph-dir", default="graph_data")
    v.add_argument("--result", help="optionally check a result JSON against it")
    v.set_defaults(fn=_cmd_verify)

    e = sub.add_parser("experiments", help="run the reference experiment suite")
    e.add_argument(
        "--backend", default="device", choices=["device", "sharded", "protocol"]
    )
    e.add_argument("--extended", action="store_true")
    e.add_argument("--output", default="ghs_experiments.json")
    e.add_argument("--visualize-dir")
    e.set_defaults(fn=_cmd_experiments)

    c = sub.add_parser(
        "chaos",
        help="fault-injection drill: lossy transport + induced solver faults "
        "+ torn checkpoint writes, all checked against the MST oracle",
    )
    c.add_argument("--full", action="store_true", help="full fault matrix")
    c.add_argument("--no-solver", action="store_true")
    c.add_argument("--output", help="write the JSON report here")
    c.set_defaults(fn=_cmd_chaos)

    def _obs_graph_args(sp):
        sp.add_argument("--graph-dir", default=None,
                        help="trace this graph dir / npz instead of generating")
        sp.add_argument("--nodes", type=int, default=1000)
        sp.add_argument("--edges", type=int, default=0,
                        help="G(n,m) edges (default 4x nodes)")
        sp.add_argument("--seed", type=int, default=42)
        sp.add_argument(
            "--backend",
            default="stepped",
            choices=["stepped", "device", "sharded", "protocol"],
            help="supervisor entry rung (stepped emits per-level spans) or "
            "the message-level protocol backend",
        )
        sp.add_argument(
            "--no-protocol-sample",
            action="store_true",
            help="skip the protocol pass that adds message counters",
        )
        sp.add_argument("--protocol-sample-max", type=int, default=2000,
                        help="largest node count the protocol sample runs at")

    t = sub.add_parser(
        "trace",
        help="run an instrumented solve and export a Chrome-trace/Perfetto "
        "timeline (solver levels, protocol counters, resilience retries)",
    )
    _obs_graph_args(t)
    t.add_argument("--out", default="trace.json",
                   help="Chrome-trace JSON output path")
    t.add_argument("--jsonl", help="also write the raw event log here")
    t.set_defaults(fn=_cmd_trace)

    s = sub.add_parser(
        "stats",
        help="plain-text telemetry summary (span/counter/histogram tables) "
        "from a fresh instrumented solve or an existing event JSONL",
    )
    _obs_graph_args(s)
    s.add_argument("--input", help="summarize this event JSONL instead of running")
    s.set_defaults(fn=_cmd_stats)

    mt = sub.add_parser(
        "merge-trace",
        help="join per-process span JSONL logs (fleet router + workers) "
        "into one Perfetto trace with cross-process flow arrows and a "
        "per-request critical-path report (docs/OBSERVABILITY.md)",
    )
    mt.add_argument("inputs", nargs="+",
                    help="event JSONL files exported by each process "
                    "(e.g. a --trace-dir's router.jsonl + worker*.jsonl)")
    mt.add_argument("--out", default="merged_trace.json",
                    help="merged Chrome-trace JSON output path")
    mt.add_argument("--report",
                    help="also write the merge + critical-path report here")
    mt.set_defaults(fn=_cmd_merge_trace)

    srv = sub.add_parser(
        "serve",
        help="MST query service: JSONL solve/update/stats requests on stdin, "
        "content-addressed result cache + incremental edge updates "
        "(docs/SERVING.md)",
    )
    srv.add_argument(
        "--backend", default="device", choices=["device", "sharded"]
    )
    srv.add_argument("--cache-entries", type=int, default=128,
                     help="in-memory LRU capacity (results)")
    srv.add_argument("--disk-cache",
                     help="directory for the persistent cache layer")
    srv.add_argument("--max-concurrent", type=int, default=2,
                     help="solve admission bound (cache misses in flight)")
    srv.add_argument(
        "--resolve-threshold", type=int,
        help="update batches larger than this re-solve instead of applying "
        "incrementally (default: max(64, edges/10))",
    )
    srv.add_argument(
        "--batch-lanes", type=int, default=0,
        help="coalesce device-backend cache misses into multi-graph device "
        "batches of up to this many lanes (0 = off; docs/BATCHING.md)",
    )
    srv.add_argument(
        "--warmup-buckets",
        help="AOT-precompile these workload shapes before serving: "
        "comma-separated NODESxEDGES (e.g. 128x512,300x1200; shapes bucket "
        "exactly like requests do) or 'auto' for the default ladder",
    )
    srv.add_argument(
        "--warmup-replay",
        help="AOT-precompile the buckets recorded in this file (written by "
        "--warmup-record on a prior run)",
    )
    srv.add_argument(
        "--sharded-lane", type=int, nargs="?", const=-1, default=0,
        metavar="N",
        help="route oversize solves to a mesh-sharded solve lane over N "
        "devices (bare flag = all devices; 0 = off) with device-resident "
        "graph residency and donated incremental updates; with --fleet, "
        "N is instead the number of worker slots that own a lane (bare "
        "flag = every worker) and the router steers oversize digests at "
        "them (docs/SHARDED_LANE.md)",
    )
    srv.add_argument(
        "--warmup-mesh-buckets",
        help="AOT-warm the sharded lane's mesh programs for these RAW "
        "NODESxEDGES oversize workloads before serving (needs "
        "--sharded-lane)",
    )
    srv.add_argument(
        "--stream-dir",
        help="durable stream layer: subscription streams persist a "
        "snapshot + update WAL per stream here (shared across fleet "
        "workers; a restart replays instead of re-solving — "
        "docs/STREAMING.md)",
    )
    srv.add_argument(
        "--stream-snapshot-every", type=int, default=8,
        help="committed windows between stream snapshots (the WAL holds "
        "the deltas in between)",
    )
    srv.add_argument(
        "--warmup-stream-buckets",
        help="AOT-warm the windowed-maintenance kernels for subscribed "
        "graphs of these RAW NODESxEDGES sizes before serving",
    )
    srv.add_argument(
        "--verify", dest="verify_policy", default=None, metavar="SPEC",
        help="result verification policy (docs/VERIFICATION.md): 'off', "
        "'sample[:N]', 'full', or per-class "
        "'bulk=full,interactive=sample,default=off'. 'full' classes "
        "certify every answer inline (O(m log n) MST certificate, "
        "independent code path) with transparent correction on failure; "
        "'sample' classes audit on a background thread. Fleet mode "
        "passes the spec to every worker",
    )
    srv.add_argument(
        "--kernel", choices=["auto", "pallas", "xla"], default=None,
        help="per-level solver kernel: 'pallas' = fused Pallas TPU kernels "
        "(MOE gather+reduce, hook+compress), 'xla' = the plain two-step "
        "path, 'auto' (default) = Pallas on TPU where the capability probe "
        "passes, XLA elsewhere; warmup precompiles the selected variant "
        "and fleet workers inherit the choice (docs/KERNELS.md)",
    )
    srv.add_argument(
        "--warmup-record",
        help="on exit, record the buckets this process compiled to this "
        "file (feed it to --warmup-replay after a restart)",
    )
    srv.add_argument(
        "--compile-cache-dir",
        help="persistent XLA compile-cache directory (default "
        "$GHS_COMPILE_CACHE_DIR or ~/.cache/ghs-xla, under a per-machine "
        "subdirectory so heterogeneous hosts never share AOT executables)",
    )
    srv.add_argument(
        "--no-compile-cache", action="store_true",
        help="disable the persistent XLA compile cache (on by default for "
        "serve: restarts reuse compiled executables)",
    )
    srv.add_argument("--input",
                     help="read JSONL requests from this file instead of stdin")
    srv.add_argument(
        "--wire", choices=("json", "binary"), default="json",
        help="front-door carrier: 'json' = text JSONL (default); 'binary' "
        "= length-prefixed frames over binary stdio, accepting B-frames "
        "(raw little-endian edge-array sections behind a compact header, "
        "zero-copy ingest) and answering in kind per connection "
        "(docs/SERVING.md \"Binary wire\")",
    )
    srv.add_argument(
        "--fleet", type=int, default=0, metavar="N",
        help="serve through N digest-routed worker processes with "
        "health-checked failover and graceful drain (0 = single-process; "
        "docs/FLEET.md)",
    )
    srv.add_argument(
        "--fleet-queue-depth", type=int, default=64,
        help="with --fleet: per-worker in-flight bound (full queues shed "
        "--fleet-shed classes, backpressure everything else)",
    )
    srv.add_argument(
        "--fleet-shed",
        help="with --fleet: comma-separated slo_class labels that may be "
        "shed when a worker queue is full (default: none — block instead)",
    )
    srv.add_argument(
        "--fleet-obs-dir",
        help="with --fleet: each worker exports its obs event JSONL here "
        "on drain (worker<K>.<incarnation>.jsonl)",
    )
    srv.add_argument(
        "--fleet-transport", choices=("pipe", "tcp"), default="pipe",
        help="with --fleet: the router<->worker channel — subprocess "
        "pipes (single host) or TCP sockets with coalesced pipelined "
        "frame writes (fleet/transport.py; spawned workers dial into the "
        "router's listener with a tokened hello; docs/FLEET.md "
        "\"Network transport\")",
    )
    srv.add_argument(
        "--fleet-workers", metavar="HOST:PORT,...",
        help="serve through externally started workers (`python -m "
        "distributed_ghs_implementation_tpu.fleet.worker --listen PORT` — "
        "on other machines or pod slices, launcher/tpu_pod_worker.sh) "
        "instead of spawning local processes; implies --fleet-transport "
        "tcp, worker count = the list length",
    )
    srv.add_argument(
        "--fleet-forward-cache", choices=("auto", "on", "off"),
        default="auto",
        help="cross-host cache-miss forwarding: probe the digest-owner "
        "worker with a cached_only frame before solving locally "
        "(fleet.forward.hit/miss). auto = on for TCP fleets without a "
        "shared --disk-cache, off elsewhere",
    )
    srv.add_argument(
        "--fleet-elastic", metavar="MIN:MAX",
        help="with --fleet: drive the worker pool between MIN and MAX via "
        "the obs-driven autoscaler (fleet/autoscaler.py) — scale-up on a "
        "per-class wait-budget breach or queue-depth watermark, joins "
        "warm-gated on the worker's 'warmed' hello; scale-down on "
        "sustained idle by draining the lowest-affinity worker "
        "(docs/FLEET.md \"Elasticity\")",
    )
    srv.add_argument(
        "--fleet-scale-budget", type=float, default=0.25, metavar="SECONDS",
        help="with --fleet-elastic: default per-class request-latency "
        "budget whose tick-window p99 breach triggers scale-up",
    )
    srv.add_argument(
        "--fleet-scale-budgets", metavar="CLS=S,...",
        help="with --fleet-elastic: per-class budget overrides, e.g. "
        "interactive=0.05,bulk=2",
    )
    srv.add_argument(
        "--fleet-scale-cooldown", type=float, default=2.0, metavar="SECONDS",
        help="with --fleet-elastic: minimum seconds between scale events "
        "(hysteresis; scale steps are always by one worker)",
    )
    srv.add_argument(
        "--fleet-journal", default=None, metavar="DIR",
        help="with --fleet: durable accepted-work journal directory "
        "(fleet/journal.py) — every accept is fsynced before dispatch, "
        "and a restarted router on the same DIR re-adopts live --listen "
        "workers warm, rebuilds pins/affinity, and re-queues orphaned "
        "accepts (docs/FLEET.md 'Router survivability')",
    )
    srv.add_argument(
        "--fleet-lease", type=float, default=None, metavar="SECONDS",
        help="with --fleet: worker silence window before a connected but "
        "unresponsive worker is declared dead (default: heartbeat "
        "interval x miss threshold = 5s); tune UP on congested WANs, "
        "DOWN for faster failover on a quiet LAN",
    )
    srv.add_argument(
        "--tune-record", default=None, metavar="PATH",
        help="install this ghs-tuning-v1 record (written by `ghs tune`) "
        "so the auto kernel tier uses measured per-bucket winners; "
        "stale or missing records degrade to the probe heuristic. "
        "Fleet mode shares the path with every worker, like the "
        "persistent compile cache (docs/KERNELS.md \"Autotuning\")",
    )
    srv.set_defaults(fn=_cmd_serve)

    tn = sub.add_parser(
        "tune",
        help="offline kernel autotuner: measure per-bucket kernel/geometry "
        "winners into a machine-fingerprinted record for `serve "
        "--tune-record` (docs/KERNELS.md \"Autotuning\")",
    )
    tn.add_argument(
        "--buckets",
        help="tune these workload shapes: comma-separated NODESxEDGES "
        "(bucketed exactly like requests) or 'auto' for the default "
        "warmup ladder",
    )
    tn.add_argument(
        "--lanes", type=int, default=0,
        help="also tune the batched lane solver at this lane count "
        "(matches serve --batch-lanes; 0 = single-graph buckets only)",
    )
    tn.add_argument(
        "--mode", choices=("fused", "vmap"), default="fused",
        help="lane execution mode the lane buckets tune (with --lanes)",
    )
    tn.add_argument(
        "--warmup-record", metavar="PATH",
        help="seed the bucket list from a serve --warmup-record file: "
        "tune exactly the buckets real traffic compiled",
    )
    tn.add_argument(
        "--mesh-buckets",
        help="also tune the sharded lane's kernels for these RAW "
        "NODESxEDGES oversize workloads (per-device proxy measurement)",
    )
    tn.add_argument(
        "--repeats", type=int, default=5,
        help="timed calls per candidate after the warm call (median wins)",
    )
    tn.add_argument(
        "--dry", action="store_true",
        help="skip all timing and pin xla winners on any backend — the "
        "deterministic CI mode (two runs are byte-identical)",
    )
    tn.add_argument(
        "--out", metavar="PATH",
        help="record path (default: the fingerprinted path under "
        "$GHS_TUNE_DIR or ~/.cache/ghs-tune)",
    )
    tn.set_defaults(fn=_cmd_tune)

    b = sub.add_parser("bench", help="run the benchmark (see bench.py)")
    b.add_argument("--scale", type=int, default=22)
    b.add_argument("--edge-factor", type=int, default=16)
    b.add_argument("--repeats", type=int, default=3)
    b.add_argument("--backend", default="device", choices=["device", "sharded"])
    b.add_argument("--no-verify", action="store_true")
    b.add_argument("--metrics-out",
                   help="write bench-gate metrics JSON here (tools/bench_gate.py)")
    b.add_argument(
        "--batch-lanes", type=int, default=0,
        help="instead of the RMAT bench, measure batched small-graph "
        "throughput (graphs/sec) at this lane count vs the sequential "
        "miss path (bench.py --batch-lanes)",
    )
    b.add_argument(
        "--warmup", action="store_true",
        help="with --batch-lanes: AOT-precompile the bucket before the "
        "cold-first-query clock (bench.py --warmup)",
    )
    b.add_argument(
        "--update-stream", action="store_true",
        help="measure streaming MSF maintenance: windowed batched apply "
        "vs the sequential per-update path (bench.py --update-stream, "
        "docs/STREAMING.md)",
    )
    b.set_defaults(fn=_cmd_bench)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
