"""Micro-decomposition of one ELL level's cost on the real chip.

Times, with full output sync and repeats: (a) a whole ``_ell_level`` at a
realistic mid-solve fragment state, (b) the bucket scan alone, (c) the
per-fragment scatter-min alone, (d) ``hook_and_compress`` alone, (e) the
rank-endpoint lookups. Answers: where do the ~780 ms/level go?
"""

from __future__ import annotations

import _bootstrap  # noqa: F401 — repo-root sys.path setup

import argparse
import functools
import time

import jax
import jax.numpy as jnp

from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
from distributed_ghs_implementation_tpu.models.boruvka import (
    _ell_level,
    prepare_ell_arrays,
)
from distributed_ghs_implementation_tpu.ops.segment_ops import INT32_MAX
from distributed_ghs_implementation_tpu.ops.union_find import hook_and_compress


def _sync(out):
    """Force completion: fetch one element of every output buffer.

    ``block_until_ready`` does not actually block on the axon remote backend,
    so timings must be closed with a real device->host transfer.
    """
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "ravel") and getattr(leaf, "size", 0):
            np.asarray(leaf.ravel()[0])


def timeit(fn, *args, repeats=5, **kw):
    out = fn(*args, **kw)
    _sync(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        _sync(out)
        best = min(best, time.perf_counter() - t0)
    return best, out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--scale", type=int, default=20)
    args = p.parse_args()

    g = rmat_graph(args.scale, 16, seed=24)
    buckets, ra, rb, n_pad = prepare_ell_arrays(g)
    nb = len(buckets)

    def flatten(bs):
        flat = []
        for b in bs:
            flat.extend(b)
        return flat

    flat = flatten(buckets) + [ra, rb]

    @functools.partial(jax.jit, static_argnames=("nbuckets",))
    def level(fragment, mst_ranks, *f, nbuckets):
        bs = tuple((f[3 * i], f[3 * i + 1], f[3 * i + 2]) for i in range(nbuckets))
        return _ell_level(fragment, mst_ranks, bs, f[3 * nbuckets], f[3 * nbuckets + 1])

    @functools.partial(jax.jit, static_argnames=("nbuckets",))
    def scan_only(fragment, *f, nbuckets):
        n = fragment.shape[0]
        vmin = jnp.full(n, INT32_MAX, jnp.int32)
        for i in range(nbuckets):
            verts, dstb, rankb = f[3 * i], f[3 * i + 1], f[3 * i + 2]
            fv = fragment[verts]
            fd = fragment[dstb]
            key = jnp.where(fd != fv[:, None], rankb, INT32_MAX)
            vmin = vmin.at[verts].min(jnp.min(key, axis=1))
        return vmin

    @functools.partial(jax.jit, static_argnames=("nbuckets",))
    def scan_gathers_only(fragment, *f, nbuckets):
        acc = jnp.zeros((), jnp.int32)
        for i in range(nbuckets):
            dstb = f[3 * i + 1]
            fd = fragment[dstb]
            acc += jnp.min(fd)
        return acc

    @jax.jit
    def scatter_min(fragment, vmin):
        n = fragment.shape[0]
        return jnp.full(n, INT32_MAX, jnp.int32).at[fragment].min(vmin)

    @jax.jit
    def hook(has, dst_frag, fragment):
        return hook_and_compress(has, dst_frag, fragment)

    # Produce a realistic post-level-1 fragment state.
    fragment0 = jnp.arange(n_pad, dtype=jnp.int32)
    mst0 = jnp.zeros(ra.shape[0], dtype=bool)
    f1, m1, _ = level(fragment0, mst0, *flat, nbuckets=nb)
    jax.block_until_ready(f1)

    t, _ = timeit(level, fragment0, mst0, *flat, nbuckets=nb)
    print(f"full level @identity fragment : {t * 1e3:8.2f} ms")
    t, _ = timeit(level, f1, m1, *flat, nbuckets=nb)
    print(f"full level @post-L1 fragment  : {t * 1e3:8.2f} ms")
    t, vmin = timeit(scan_only, f1, *flat, nbuckets=nb)
    print(f"bucket scan only              : {t * 1e3:8.2f} ms")
    t, _ = timeit(scan_gathers_only, f1, *flat, nbuckets=nb)
    print(f"bucket fd-gathers only        : {t * 1e3:8.2f} ms")
    t, moe = timeit(scatter_min, f1, vmin)
    print(f"fragment scatter-min          : {t * 1e3:8.2f} ms")
    has = moe < INT32_MAX
    ids = jnp.arange(n_pad, dtype=jnp.int32)
    safe = jnp.where(has, moe, 0)
    fa = f1[ra[safe]]
    fb = f1[rb[safe]]
    dst_frag = jnp.where(has, jnp.where(fa == ids, fb, fa), ids)
    t, _ = timeit(hook, has, dst_frag, f1)
    print(f"hook_and_compress             : {t * 1e3:8.2f} ms")


if __name__ == "__main__":
    main()
