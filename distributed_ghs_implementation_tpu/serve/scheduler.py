"""Micro-batching solve scheduler: single-flight coalescing + admission bound.

Request handling for the serve path, in order:

1. **Cache probe** — ``ResultStore.get`` by content key; a hit never touches
   the solver (zero ``solver.*`` spans — the warm-path guarantee tests
   assert on bus events).
2. **Single-flight** — concurrent requests for the same key join the one
   in-flight solve instead of duplicating it (``serve.scheduler.coalesced``
   counts the joins). This is what keeps a thundering herd of identical
   queries at exactly one kernel dispatch.
3. **Admission bound** — distinct misses solve under a semaphore
   (``max_concurrent``); excess requests queue. ``serve.queue.depth`` is
   sampled on every transition so traces show pressure over time. With a
   batch engine attached the engine's own forming queue + serialized
   dispatch is the capacity bound instead (holding the semaphore while
   waiting for lane-mates would forbid the very coalescing the engine is
   for).
4. **Supervised solve** — every miss runs through the round-6 resilience
   supervisor (watchdog, bounded retry, the sharded->device->stepped->host
   degradation ladder), so one flaky device never fails a request that a
   degraded rung can still answer exactly. With a batch engine, device
   misses instead run the engine's batch-shaped supervision (batch retry,
   then per-lane ladder fallback — ``batch/engine.py``).

``solve_batch`` is the micro-batching entry: it dedups a whole request list
by key, registers ONE flight per distinct missed digest *before any solving
starts* (duplicates inside the batch — and concurrent ``solve`` callers —
join that flight instead of racing it), then solves the distinct misses as
a group: through the batch engine when attached (same-bucket misses share
device dispatches), else sequentially.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import List, Optional, Sequence, Tuple

from distributed_ghs_implementation_tpu.api import MSTResult, minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.obs.slo import current_class
from distributed_ghs_implementation_tpu.serve.store import ResultStore, solve_cache_key


def _cls_args() -> dict:
    """The SLO class tag of the current request context, as span args —
    stamping it on ``serve.solve`` lets ``obs.slo`` decompose each class's
    end-to-end latency into solve time vs everything else."""
    cls = current_class()
    return {"cls": cls} if cls is not None else {}


class _Flight:
    """One in-flight solve; joiners block on ``event`` and read the outcome."""

    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result: Optional[MSTResult] = None
        self.error: Optional[BaseException] = None


class PriorityGate:
    """Two-class priority over the shared device: bulk yields to interactive.

    A bulk mesh solve (oversize → the sharded lane) is seconds of work; an
    interactive small-graph miss is milliseconds. Without priority, one
    RMAT-24 in flight starves every small query behind it. The gate is the
    minimal mechanism that prevents that:

    * interactive misses run inside :meth:`interactive` — a pending-count
      context the solve holds for its duration;
    * bulk solves call :meth:`checkpoint` between device dispatches (the
      stepped-solve boundaries ``parallel/lane.py`` exposes): while
      interactive work is pending, the bulk solve PAUSES — bounded by
      ``max_pause_s`` per checkpoint, so a steady interactive stream delays
      bulk work rather than deadlocking it.

    Telemetry: ``serve.gate.yields`` counts checkpoints that actually
    paused; ``serve.gate.bulk_pause_s`` records how long — the receipts
    behind "interactive p99 protected under concurrent bulk load"
    (``tools/load_drill.py --oversize-heavy``).
    """

    def __init__(self, max_pause_s: float = 5.0):
        self.max_pause_s = max_pause_s
        self._cv = threading.Condition()
        self._pending = 0
        self._local = threading.local()

    @contextlib.contextmanager
    def interactive(self):
        with self._cv:
            self._pending += 1
        self._local.pending = getattr(self._local, "pending", 0) + 1
        try:
            yield
        finally:
            self._local.pending -= 1
            with self._cv:
                self._pending -= 1
                if self._pending <= 0:
                    self._cv.notify_all()

    def checkpoint(self) -> None:
        """Bulk-side yield point: wait out pending interactive work.

        Pending registrations held by THIS thread don't count: a solve
        that degrades to bulk from inside an interactive context (a
        stream window's resolve escape hatch routing to the sharded
        lane) must not wait out its own registration at every stepped
        boundary — it still yields to everyone else's.
        """
        own = getattr(self._local, "pending", 0)
        t0 = time.monotonic()
        with self._cv:
            while (
                self._pending > own
                and time.monotonic() - t0 < self.max_pause_s
            ):
                self._cv.wait(timeout=0.05)
        paused = time.monotonic() - t0
        if paused >= 0.002:
            BUS.count("serve.gate.yields")
            BUS.record("serve.gate.bulk_pause_s", paused)


class SolveScheduler:
    """Cache-fronted, single-flight, capacity-bounded solve dispatch."""

    def __init__(
        self,
        store: Optional[ResultStore] = None,
        *,
        backend: str = "device",
        max_concurrent: int = 2,
        supervisor_config=None,
        batch_engine=None,
        sharded_lane=None,
    ):
        if max_concurrent < 1:
            raise ValueError(f"max_concurrent must be >= 1, got {max_concurrent}")
        self.store = store if store is not None else ResultStore()
        self.backend = backend
        self.batch_engine = batch_engine
        # sharded_lane (a parallel.lane.ShardedLane) opens the oversize
        # route: device-backend misses past the batch policy's admission
        # ceiling run on the mesh instead of bypassing to the semaphore
        # path — where one such solve used to hold a max_concurrent slot
        # for seconds, starving interactive misses behind it.
        self.sharded_lane = sharded_lane
        self.gate = PriorityGate()
        self._supervisor_config = supervisor_config
        self._sem = threading.BoundedSemaphore(max_concurrent)
        self._flights: dict = {}
        self._lock = threading.Lock()
        # The oversize decision is the batch policy's admission rule even
        # when no engine is attached (one rule set, batch/policy.py).
        if batch_engine is not None:
            self._route_policy = batch_engine.policy
        else:
            from distributed_ghs_implementation_tpu.batch.policy import (
                BatchPolicy,
            )

            self._route_policy = BatchPolicy()

    def solve(
        self, graph: Graph, *, backend: Optional[str] = None
    ) -> Tuple[MSTResult, str]:
        """Answer one solve request; returns ``(result, source)`` where
        ``source`` is ``"cache"`` / ``"coalesced"`` / ``"solved"``."""
        backend = backend or self.backend
        key = solve_cache_key(graph, backend=backend)
        cached = self.store.get(key, graph=graph)
        if cached is not None:
            return cached, "cache"

        flight, leader = self._join_or_lead(key)
        if not leader:
            BUS.count("serve.scheduler.coalesced")
            flight.event.wait()
            if flight.error is not None:
                raise flight.error
            return flight.result, "coalesced"

        try:
            # Double-check after winning leadership: a previous leader may
            # have published between our cache probe and the flight insert —
            # without this, that race re-solves an already-cached graph.
            cached = self.store.get(key, graph=graph, record_miss=False)
            if cached is not None:
                flight.result = cached
                return cached, "cache"
            flight.result = self._solve_miss(graph, backend)
            self.store.put(key, flight.result)
        except BaseException as e:
            flight.error = e
            raise
        finally:
            self._land(key, flight)
        return flight.result, "solved"

    def solve_batch(
        self, graphs: Sequence[Graph], *, backend: Optional[str] = None
    ) -> List[Tuple[MSTResult, str]]:
        """Solve a batch, deduplicating by content key first: duplicates
        inside the batch resolve against one flight (never race), and the
        distinct misses solve as a group (coalescing into device batches
        when the batch engine is attached)."""
        backend = backend or self.backend
        keys: List[str] = []
        unique: dict = {}
        for g in graphs:
            key = solve_cache_key(g, backend=backend)
            keys.append(key)
            if key in unique:
                BUS.count("serve.scheduler.coalesced")
            else:
                unique[key] = g

        outcome: dict = {}
        leaders: list = []  # (key, graph, flight)
        joiners: list = []  # (key, flight)
        for key, g in unique.items():
            cached = self.store.get(key, graph=g)
            if cached is not None:
                outcome[key] = (cached, "cache")
                continue
            flight, leader = self._join_or_lead(key)
            if leader:
                # Leadership double-check, as in solve().
                cached = self.store.get(key, graph=g, record_miss=False)
                if cached is not None:
                    flight.result = cached
                    self._land(key, flight)
                    outcome[key] = (cached, "cache")
                else:
                    leaders.append((key, g, flight))
            else:
                joiners.append((key, flight))

        if leaders:
            try:
                results = self._solve_misses(
                    [g for _, g, _ in leaders], backend
                )
            except BaseException as e:
                for key, _, flight in leaders:
                    flight.error = e
                    self._land(key, flight)
                raise
            try:
                for (key, _, flight), result in zip(leaders, results):
                    flight.result = result
                    self.store.put(key, result)
                    self._land(key, flight)
                    outcome[key] = (result, "solved")
            except BaseException as e:
                # A raise mid-publish (e.g. KeyboardInterrupt) must not
                # leak the remaining flights — a leaked flight blocks its
                # joiners forever. Land every unlanded leader (with its
                # result when the solve already succeeded).
                for key, _, flight in leaders:
                    if not flight.event.is_set():
                        if flight.result is None:
                            flight.error = e
                        self._land(key, flight)
                raise

        for key, flight in joiners:
            BUS.count("serve.scheduler.coalesced")
            flight.event.wait()
            if flight.error is not None:
                raise flight.error
            outcome[key] = (flight.result, "coalesced")

        out: List[Tuple[MSTResult, str]] = []
        first = set()
        for key in keys:
            result, source = outcome[key]
            out.append((result, source) if key not in first else (result, "coalesced"))
            first.add(key)
        return out

    # ------------------------------------------------------------------
    def _join_or_lead(self, key: str) -> Tuple[_Flight, bool]:
        """Atomically join the in-flight solve for ``key`` or become its
        leader; returns ``(flight, is_leader)``."""
        with self._lock:
            flight = self._flights.get(key)
            if flight is not None:
                return flight, False
            flight = self._flights[key] = _Flight()
            BUS.sample("serve.queue.depth", len(self._flights))
            return flight, True

    def _land(self, key: str, flight: _Flight) -> None:
        """Retire a flight and wake its joiners."""
        with self._lock:
            del self._flights[key]
            BUS.sample("serve.queue.depth", len(self._flights))
        flight.event.set()

    def _route(self, graph: Graph, backend: str) -> str:
        """One solve's route: ``"batch"`` (engine-admitted), ``"direct"``
        (small graph on the semaphore path), ``"sharded_lane"``, or
        ``"bypass"`` (oversize without a usable mesh lane)."""
        if backend != "device":
            return "direct"
        route = self._route_policy.route(
            graph,
            sharded_available=(
                self.sharded_lane is not None
                and self.sharded_lane.admits(graph)
            ),
        )
        if route == "lane":
            return "batch" if self.batch_engine is not None else "direct"
        return route

    def interactive(self):
        """Register non-solve request work with the priority gate.

        The stream layer wraps each window commit in this context so a
        bulk mesh solve yields to window applies at its stepped-solve
        checkpoints, the same way it yields to interactive misses.
        """
        return self.gate.interactive()

    def _solve_miss(self, graph: Graph, backend: str) -> MSTResult:
        """One cache miss, routed: batch-engine submission (admitted,
        device backend), the mesh-sharded lane (oversize with a lane
        attached — ``parallel/lane.py``), or a semaphore-bounded
        supervised solve (small graphs without an engine, non-device
        backends, and the oversize BYPASS when no lane is attached).
        Oversize spans carry ``route`` (``sharded_lane`` vs ``bypass``) so
        SLO summaries can tell the two oversize paths apart; interactive
        (non-oversize) solves register with the priority gate the bulk
        lane yields to."""
        # Every path below runs the solver on a graph nothing had cached —
        # the one counter "zero fresh solves on recovery" drills assert
        # stays flat while a restarted worker replays its streams.
        BUS.count("serve.scheduler.fresh_solve")
        route = self._route(graph, backend)
        if route == "batch":
            with self.gate.interactive(), BUS.span(
                "serve.solve", cat="serve", backend="batch",
                nodes=graph.num_nodes, edges=graph.num_edges, **_cls_args(),
            ):
                return self.batch_engine.submit(graph).wait()
        if route == "sharded_lane":
            BUS.count("serve.route.sharded_lane")
            with BUS.span(
                "serve.solve", cat="serve", backend="sharded_lane",
                route="sharded_lane", nodes=graph.num_nodes,
                edges=graph.num_edges, **_cls_args(),
            ):
                # Bulk class: no semaphore slot held (interactive misses
                # must not queue behind a bulk solve), one mesh solve in
                # flight at a time inside the lane, yielding to pending
                # interactive work at every stepped-solve boundary.
                return self.sharded_lane.solve_result(
                    graph, yield_fn=self.gate.checkpoint
                )
        span_args = dict(
            backend=backend, nodes=graph.num_nodes, edges=graph.num_edges,
            **_cls_args(),
        )
        if route == "bypass":
            BUS.count("serve.route.bypass")
            span_args["route"] = "bypass"
        gate = (
            self.gate.interactive() if route == "direct"
            else contextlib.nullcontext()
        )
        with gate, self._sem:
            with BUS.span("serve.solve", cat="serve", **span_args):
                return minimum_spanning_forest(
                    graph, backend=backend, supervised=True,
                    supervisor=self._make_supervisor(),
                )

    def _solve_misses(
        self, graphs: List[Graph], backend: str
    ) -> List[MSTResult]:
        """The distinct misses of one batch, as a group: engine-admitted
        misses coalesce into device batches; sharded-lane-routed oversize
        misses peel off to the mesh (the engine would bypass them to the
        slow single-graph path otherwise)."""
        if self.batch_engine is not None and backend == "device":
            lane_set = {
                i for i, g in enumerate(graphs)
                if self._route(g, backend) == "sharded_lane"
            }
            results: List[Optional[MSTResult]] = [None] * len(graphs)
            rest = [i for i in range(len(graphs)) if i not in lane_set]
            if rest:
                with BUS.span(
                    "serve.solve", cat="serve", backend="batch",
                    misses=len(rest), **_cls_args(),
                ):
                    solved = self.batch_engine.solve_many(
                        [graphs[i] for i in rest]
                    )
                for i, result in zip(rest, solved):
                    results[i] = result
            for i in sorted(lane_set):
                results[i] = self._solve_miss(graphs[i], backend)
            return results  # type: ignore[return-value]
        return [self._solve_miss(g, backend) for g in graphs]

    # ------------------------------------------------------------------
    def _make_supervisor(self):
        from distributed_ghs_implementation_tpu.utils.resilience import Supervisor

        return Supervisor(self._supervisor_config)
