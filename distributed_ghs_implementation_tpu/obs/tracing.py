"""Distributed trace context: one id that follows a request everywhere.

The obs bus (``obs/events.py``) is process-local by design — cheap tuples
in a ring, no cross-process anything. That was the right primitive, but the
system it instruments stopped being one process: a query enters the fleet
router, hops a TCP frame to a worker, may fail over to a second worker, may
commit a stream window whose WAL replay re-runs it on a *third* process
days later. This module is the missing join key: a context-local
:class:`TraceContext` minted at every front door (``serve_loop``,
``FleetRouter.handle``, stream publish) and re-established on the far side
of every hop, so every span the bus records — on any process — carries the
same 128-bit ``trace`` id plus a ``span``/``parent`` edge that the
multi-file merge (``obs.export.merge_trace_files``) can stitch back into
one tree.

Design rules:

* **Stdlib only, imports nothing from obs.** ``events.py`` imports this
  module (to stamp spans); the reverse edge would be a cycle.
* **Context-local, not thread-local.** ``contextvars`` propagates through
  the worker thread-pools the same way ``obs.slo.tagged_class`` does; a
  token-based activate/deactivate keeps nesting exception-safe.
* **Deterministic head sampling.** The keep/drop decision hashes the
  trace id against a seed (``GHS_TRACE_SEED``) and a rate
  (``GHS_TRACE_SAMPLE``, default 1.0) — every process computes the same
  answer for the same trace, and the decision ALSO rides the wire so a
  worker with a different env cannot half-sample a trace.
* **Wire shape is a plain dict** (``{"trace","span","sampled","cls"}``)
  carried as an optional ``trace`` field on fleet frames, journal accept
  records, and stream WAL entries — gated by hello ``caps.trace`` exactly
  like the round-19 CRC opt-in, so a legacy peer simply never sees it.

Span ids are 16 hex chars: a per-process random prefix (8 hex, fresh at
import) + a monotone counter — collision-safe across the fleet without
coordination, and cheap (no per-span ``urandom``).
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import itertools
import os
import uuid
from typing import Any, Dict, Optional

__all__ = [
    "TraceContext",
    "current",
    "mint",
    "activate",
    "deactivate",
    "activated",
    "front_door",
    "push_child",
    "pop",
    "new_trace_id",
    "new_span_id",
    "head_sampled",
    "wire_context",
    "from_wire",
]


class TraceContext:
    """One request's identity at a point in the call tree.

    ``span_id`` is the id the *next* span should name as its parent —
    ``None`` at a fresh root, so the first span under a minted context
    records no ``parent`` and the merge sees a true root (never an
    orphan). ``sampled=False`` contexts still propagate (the decision is
    sticky) but stamp nothing.
    """

    __slots__ = ("trace_id", "span_id", "slo_class", "sampled")

    def __init__(
        self,
        trace_id: str,
        span_id: Optional[str] = None,
        slo_class: Optional[str] = None,
        sampled: bool = True,
    ):
        self.trace_id = trace_id
        self.span_id = span_id
        self.slo_class = slo_class
        self.sampled = sampled

    def child(self, span_id: str) -> "TraceContext":
        """The context spans nested under ``span_id`` should see."""
        return TraceContext(
            self.trace_id, span_id, self.slo_class, self.sampled
        )

    def __repr__(self) -> str:  # debugging aid only
        return (
            f"TraceContext(trace={self.trace_id[:8]}..., "
            f"span={self.span_id}, cls={self.slo_class}, "
            f"sampled={self.sampled})"
        )


_current: contextvars.ContextVar[Optional[TraceContext]] = (
    contextvars.ContextVar("ghs_trace_context", default=None)
)

# Per-process span-id prefix: 8 random hex chars fixed at import + an
# 8-hex monotone counter. Two processes share a prefix with p ~ 2^-32
# per pair — and even then ids only collide if the counters align.
_SPAN_PREFIX = os.urandom(4).hex()
_span_counter = itertools.count(1)


def new_trace_id() -> str:
    """A fresh 128-bit trace id (32 hex chars)."""
    return uuid.uuid4().hex


def new_span_id() -> str:
    return f"{_SPAN_PREFIX}{next(_span_counter) & 0xFFFFFFFF:08x}"


def head_sampled(trace_id: str) -> bool:
    """Deterministic head-sampling decision for ``trace_id``.

    ``GHS_TRACE_SAMPLE`` (default 1.0) is the keep rate;
    ``GHS_TRACE_SEED`` (default 0) salts the hash so operators can rotate
    which traces a low rate keeps without changing the rate. Every process
    with the same env computes the same answer — and the decision rides
    the wire anyway, so mixed-env fleets still agree per trace.
    """
    try:
        rate = float(os.environ.get("GHS_TRACE_SAMPLE", "1"))
    except ValueError:
        rate = 1.0
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    seed = os.environ.get("GHS_TRACE_SEED", "0")
    digest = hashlib.sha256(f"{seed}:{trace_id}".encode()).digest()
    u = int.from_bytes(digest[:8], "big") / 2.0**64
    return u < rate


def current() -> Optional[TraceContext]:
    return _current.get()


def mint(slo_class: Optional[str] = None) -> TraceContext:
    """A fresh root context (front doors only; hops use :func:`from_wire`)."""
    tid = new_trace_id()
    return TraceContext(tid, None, slo_class, head_sampled(tid))


def activate(ctx: Optional[TraceContext]) -> "contextvars.Token":
    return _current.set(ctx)


def deactivate(token: "contextvars.Token") -> None:
    _current.reset(token)


def push_child(ctx: TraceContext, span_id: str) -> "contextvars.Token":
    """Enter ``span_id``'s scope: spans opened until :func:`pop` parent it."""
    return _current.set(ctx.child(span_id))


def pop(token: "contextvars.Token") -> None:
    _current.reset(token)


@contextlib.contextmanager
def activated(ctx: Optional[TraceContext]):
    """Run a block under ``ctx``; a no-op when ``ctx`` is None (so callers
    can pass ``from_wire(frame.get("trace"))`` unconditionally)."""
    if ctx is None:
        yield None
        return
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


@contextlib.contextmanager
def front_door(slo_class: Optional[str] = None):
    """A request entry point: reuse the active context when one exists
    (a fleet worker re-established the router's), else mint a root.

    The reuse rule is what makes nesting front doors safe — the stream
    ``publish`` door inside a traced ``serve.request`` joins that trace
    instead of forking a new one.
    """
    ctx = _current.get()
    if ctx is not None:
        yield ctx
        return
    ctx = mint(slo_class)
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def wire_context() -> Optional[Dict[str, Any]]:
    """The active context as a frame/journal/WAL field, or None when
    there is nothing worth carrying (no context, or head-sampled out)."""
    ctx = _current.get()
    if ctx is None or not ctx.sampled:
        return None
    wire: Dict[str, Any] = {"trace": ctx.trace_id, "sampled": True}
    if ctx.span_id is not None:
        wire["span"] = ctx.span_id
    if ctx.slo_class is not None:
        wire["cls"] = ctx.slo_class
    return wire


def from_wire(wire: Any) -> Optional[TraceContext]:
    """Rebuild a context from a wire dict; tolerant of absence/garbage
    (returns None, the untraced path) so legacy peers cost nothing."""
    if not isinstance(wire, dict):
        return None
    tid = wire.get("trace")
    if not isinstance(tid, str) or not tid:
        return None
    span = wire.get("span")
    return TraceContext(
        tid,
        span if isinstance(span, str) else None,
        wire.get("cls"),
        bool(wire.get("sampled", True)),
    )
