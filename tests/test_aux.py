"""Aux subsystems: metrics, checkpoint/resume, multihost helpers."""

import os

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.graphs.generators import (
    erdos_renyi_graph,
    line_graph,
)
from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
from distributed_ghs_implementation_tpu.utils.checkpoint import (
    load_checkpoint,
    save_checkpoint,
    solve_graph_checkpointed,
)
from distributed_ghs_implementation_tpu.utils.metrics import (
    solve_graph_instrumented,
)


def test_instrumented_matches_plain():
    g = erdos_renyi_graph(200, 0.05, seed=13)
    (edge_ids, fragment, levels), metrics = solve_graph_instrumented(g)
    ref_ids, ref_frag, _ = solve_graph(g)
    assert np.array_equal(edge_ids, ref_ids)
    assert metrics.num_nodes == 200
    assert len(metrics.levels) == levels
    # Fragment counts must be monotonically non-increasing and end at 1.
    counts = [r.fragments_after for r in metrics.levels]
    assert all(a >= b for a, b in zip(counts, counts[1:]))
    assert counts[-1] == 1
    assert metrics.to_json()  # serializes


def test_checkpoint_roundtrip(tmp_path):
    p = str(tmp_path / "ckpt.npz")
    frag = np.arange(10, dtype=np.int32)
    mst = np.zeros(20, dtype=bool)
    mst[3] = True
    save_checkpoint(p, frag, mst, 2)
    f2, m2, lv = load_checkpoint(p)
    assert np.array_equal(f2, frag) and np.array_equal(m2, mst) and lv == 2


def test_checkpointed_solve_and_resume(tmp_path):
    g = erdos_renyi_graph(150, 0.06, seed=14)
    p = str(tmp_path / "solve.npz")
    edge_ids, fragment, levels = solve_graph_checkpointed(g, p, every=1)
    ref_ids, _, _ = solve_graph(g)
    assert np.array_equal(edge_ids, ref_ids)
    assert os.path.exists(p)

    # Tamper: rewind to the level-1 state by re-solving with a fresh path,
    # stopping early via a partial checkpoint, then resuming.
    frag, mst, lv = load_checkpoint(p)
    assert lv == levels
    # Resume from the final checkpoint: must immediately converge to the same MST.
    edge_ids2, _, _ = solve_graph_checkpointed(g, p, every=1, resume=True)
    assert np.array_equal(edge_ids2, ref_ids)


def test_checkpoint_resume_midway(tmp_path):
    """Simulate preemption: checkpoint after level 1, resume, identical MST."""
    import jax.numpy as jnp

    from distributed_ghs_implementation_tpu.models.boruvka import (
        _level_kernel,
        prepare_device_arrays,
    )

    g = line_graph(130)  # high diameter -> several levels
    frag0, src, dst, rank, ra, rb = prepare_device_arrays(g)
    mst = jnp.zeros(ra.shape[0], dtype=bool)
    frag, mst, src_f, dst_f, has, count = _level_kernel(
        frag0, mst, src, dst, rank, ra, rb
    )
    p = str(tmp_path / "mid.npz")
    save_checkpoint(p, frag, mst, 1)

    edge_ids, _, _ = solve_graph_checkpointed(g, p, resume=True)
    ref_ids, _, _ = solve_graph(g)
    assert np.array_equal(edge_ids, ref_ids)


def test_multihost_helpers_single_process():
    from distributed_ghs_implementation_tpu.parallel import multihost

    assert multihost.is_primary()  # single-process run is its own primary
