"""Fused Pallas TPU kernels for the per-level inner loop.

The per-level hot path is a chain of XLA-scheduled gather / select /
``segment_min`` / pointer-jump ops with every intermediate materialized in
HBM. ``tools/test_pallas_gather.py`` measured the dominant cost — the
fragment-id random gather (~480 ms at RMAT-20) — dropping ~7x when the
fragment table is VMEM-resident inside a Pallas kernel. This module turns
that probe into production kernels:

* :func:`fused_ell_row_min` — the ELL kernel's per-bucket MOE search
  (``models.boruvka._ell_level``): the two fragment gathers
  (``fragment[verts]``, ``fragment[dstb]``), the outgoing-edge mask, and
  the rank-keyed row minimum run in ONE pass over VMEM-blocked edge
  buckets, with the fragment table resident in VMEM across the whole
  grid. Subsumes the reduction half of ``ops.segment_ops.fragment_moe``
  in the degree-bucketed layout.
* :func:`fused_gather_key` — the flat kernels' MOE front half
  (``fragment_moe`` with a non-identity partition): fragment gathers for
  both endpoints plus the alive-mask rank select in one VMEM pass; the
  n-segment ``segment_min`` scatter stays in XLA (a dense-reduction
  segment scatter has no efficient Pallas form — the ELL layout is the
  fused answer to that op).
* :func:`fused_hook_compress` — ``ops.union_find.break_symmetric_hooks``
  + bounded ``pointer_jump`` + the final relabel gather fused into one
  kernel: the parent array stays in VMEM across every jump, so no
  intermediate parent array ever round-trips HBM. ``ceil(log2 n)`` jumps
  reach the fixpoint of any hook forest (each jump doubles pointer
  reach), so the bounded loop is exact, not approximate.

Selection (the speculative/fallback discipline of the round-5 fused
filter+compaction work):

* ``kernel="pallas" | "xla"`` threads through ``models/boruvka.py``,
  ``batch/lanes.py``, and ``parallel/rank_sharded.py`` /
  ``parallel/lane.py`` as a STATIC trace-time argument — both variants
  compile side by side and cache independently.
* :func:`kernel_choice` resolves a per-solve override, then the process
  default (:func:`set_default_kernel`, the ``serve --kernel`` flag), then
  the ``GHS_KERNEL`` env var, then ``auto``: Pallas on TPU backends where
  the import-time capability probe passes, XLA everywhere else. On
  non-TPU backends Pallas kernels run in interpret mode (lowered to
  plain XLA ops) — bit-exact, so CPU CI asserts kernel parity without
  hardware; ``auto`` never picks the interpreted path for throughput.
* A runtime Pallas failure trips :func:`disable_pallas` — a sticky
  process-wide fallback to XLA (``kernel.fallback`` on the obs bus) so
  one Mosaic regression degrades throughput, never availability.

Every wrapper also has a shape guard (``*_shape_ok``): geometries past
the VMEM budget (fragment table > ``_TABLE_MAX_ELEMS``, hook arrays >
``_HOOK_MAX_NODES``) or off the tiling grid route back to the XLA form
at trace time, so ``kernel="pallas"`` is always safe to request.
"""

from __future__ import annotations

import functools
import math
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ghs_implementation_tpu.obs.events import BUS

INT32_MAX = np.iinfo(np.int32).max

#: VPU lane width — flat e-sized arrays reshape to ``(rows, 128)``.
_LANES = 128

#: Fragment-table ceiling for table-resident kernels: the whole table must
#: sit in VMEM beside the streamed blocks (1M int32 = 4 MB of ~16 MB).
_TABLE_MAX_ELEMS = 1 << 20

#: Hook+compress ceiling: the kernel holds the parent array plus take
#: temporaries in VMEM for every jump (2^19 int32 = 2 MB per buffer).
_HOOK_MAX_NODES = 1 << 19

#: Elements per streamed ELL block (rows x width).
_ELL_BLOCK_ELEMS = 1 << 15

#: Row cap per streamed flat block (rows of 128 lanes).
_FLAT_BLOCK_ROWS = 256

VALID_KERNELS = ("auto", "pallas", "xla")

_LOCK = threading.Lock()
_DEFAULT_KERNEL: str | None = None  # set_default_kernel (serve --kernel)
_DISABLED_REASON: str | None = None  # sticky runtime fallback
_PROBE_RESULT: bool | None = None
_PROBE_ERROR: str | None = None


def _interpret() -> bool:
    """Interpret mode off-TPU: kernels lower to plain XLA ops — bit-exact
    and compilable anywhere, which is what lets CPU CI assert parity."""
    return jax.default_backend() != "tpu"


def _probe() -> bool:
    """One-shot capability probe: build and run the probe gather kernel on
    the current backend (compiled on TPU, interpreted elsewhere)."""
    global _PROBE_RESULT, _PROBE_ERROR
    with _LOCK:
        if _PROBE_RESULT is not None:
            return _PROBE_RESULT
    try:
        from jax.experimental import pallas as pl

        def gather_kernel(table_ref, idx_ref, out_ref):
            out_ref[...] = jnp.take(table_ref[...], idx_ref[...], axis=0)

        table = jnp.arange(256, dtype=jnp.int32)
        idx = jnp.full((2, _LANES), 3, jnp.int32)
        out = pl.pallas_call(
            gather_kernel,
            grid=(1,),
            in_specs=[
                pl.BlockSpec(table.shape, lambda i: (0,)),
                pl.BlockSpec(idx.shape, lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec(idx.shape, lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct(idx.shape, table.dtype),
            interpret=_interpret(),
        )(table, idx)
        ok = bool(jax.device_get(out)[0, 0] == 3)
        err = None if ok else "probe kernel returned wrong values"
    except Exception as ex:  # noqa: BLE001 — any failure means unavailable
        ok, err = False, f"{type(ex).__name__}: {ex}"
    with _LOCK:
        _PROBE_RESULT, _PROBE_ERROR = ok, err
    return ok


def pallas_supported() -> bool:
    """Can ``kernel="pallas"`` run at all on this process's backend?
    (Compiled on TPU; interpret-mode — exact but slow — elsewhere.)"""
    return _DISABLED_REASON is None and _probe()


def set_default_kernel(choice: str | None) -> None:
    """Set the process-default kernel (the ``serve --kernel`` flag); wins
    over ``GHS_KERNEL``, loses to a per-solve override."""
    global _DEFAULT_KERNEL
    if choice is not None and choice not in VALID_KERNELS:
        raise ValueError(
            f"unknown kernel {choice!r}; expected one of {VALID_KERNELS}"
        )
    _DEFAULT_KERNEL = None if choice in (None, "auto") else choice


def disable_pallas(reason: str) -> None:
    """Sticky process-wide fallback: every later :func:`kernel_choice`
    resolves ``xla`` (``kernel.fallback`` counts the trip)."""
    global _DISABLED_REASON
    with _LOCK:
        already = _DISABLED_REASON is not None
        _DISABLED_REASON = _DISABLED_REASON or reason
    if not already:
        BUS.count("kernel.fallback")


def kernel_choice(override: str | None = None) -> str:
    """Resolve the effective kernel: per-solve override > process default
    (``set_default_kernel``) > ``GHS_KERNEL`` env > auto (Pallas on TPU
    when the probe passes, XLA everywhere else). Requests for an
    unavailable Pallas degrade to ``"xla"`` — never an error."""
    request = override or _DEFAULT_KERNEL or os.environ.get("GHS_KERNEL") or "auto"
    if request not in VALID_KERNELS:
        raise ValueError(
            f"unknown kernel {request!r}; expected one of {VALID_KERNELS}"
        )
    if request == "xla":
        return "xla"
    if _DISABLED_REASON is not None:
        return "xla"
    if request == "pallas":
        return "pallas" if pallas_supported() else "xla"
    # auto: only pick Pallas where it runs compiled — interpret mode is a
    # parity tool, not a throughput path.
    if jax.default_backend() == "tpu" and pallas_supported():
        return "pallas"
    return "xla"


def kernel_report() -> dict:
    """Selection state for drills/stats: what auto resolves to and why."""
    return {
        "backend": jax.default_backend(),
        "supported": pallas_supported(),
        "interpret": _interpret(),
        "default": _DEFAULT_KERNEL or os.environ.get("GHS_KERNEL") or "auto",
        "resolved": kernel_choice(),
        "disabled_reason": _DISABLED_REASON,
        "probe_error": _PROBE_ERROR,
    }


def _reset_for_tests() -> None:
    """Clear sticky selection state (tests simulate a process restart)."""
    global _DEFAULT_KERNEL, _DISABLED_REASON, _PROBE_RESULT, _PROBE_ERROR
    with _LOCK:
        _DEFAULT_KERNEL = None
        _DISABLED_REASON = None
        _PROBE_RESULT = None
        _PROBE_ERROR = None


# ---------------------------------------------------------------------------
# Shape guards — resolved at trace time (shapes are static), so a guarded
# geometry silently takes the XLA form instead of failing.
# ---------------------------------------------------------------------------
def _pow2_factor(x: int, cap: int) -> int:
    """Largest power of two dividing ``x``, capped (block sizes must divide
    the padded row count exactly — Pallas grids have no remainder step).
    The cap is rounded DOWN to a power of two first: a non-pow2 cap would
    otherwise win the ``min`` with a non-divisor and leave the grid's tail
    rows unwritten."""
    if x <= 0:
        return 1
    cap_pow2 = 1 << (max(1, cap).bit_length() - 1)
    return min(cap_pow2, x & (-x))


def ell_shape_ok(num_nodes: int, rows: int, width: int) -> bool:
    return 0 < num_nodes <= _TABLE_MAX_ELEMS and rows > 0 and width > 0


def flat_shape_ok(num_nodes: int, num_slots: int) -> bool:
    return (
        0 < num_nodes <= _TABLE_MAX_ELEMS
        and num_slots >= _LANES
        and num_slots % _LANES == 0
    )


def hook_shape_ok(num_nodes: int) -> bool:
    return 0 < num_nodes <= _HOOK_MAX_NODES


# ---------------------------------------------------------------------------
# Kernel bodies
# ---------------------------------------------------------------------------
def _ell_row_min_kernel(frag_ref, verts_ref, dst_ref, rank_ref, out_ref):
    """One ELL block: fragment gathers + alive mask + rank-keyed row min,
    fragment table VMEM-resident."""
    frag = frag_ref[...]
    fv = jnp.take(frag, verts_ref[...], axis=0)
    fd = jnp.take(frag, dst_ref[...], axis=0)
    key = jnp.where(fd != fv[:, None], rank_ref[...], INT32_MAX)
    out_ref[...] = jnp.min(key, axis=1)


def _gather_key_kernel(frag_ref, src_ref, dst_ref, rank_ref, fsrc_ref, key_ref):
    """One flat block: both endpoint fragment gathers + the alive-mask rank
    select, one pass (the MOE front half; segment_min stays in XLA)."""
    frag = frag_ref[...]
    fs = jnp.take(frag, src_ref[...], axis=0)
    fd = jnp.take(frag, dst_ref[...], axis=0)
    fsrc_ref[...] = fs
    key_ref[...] = jnp.where(fs != fd, rank_ref[...], INT32_MAX)


def _hook_compress_kernel(parent0_ref, frag_ref, newf_ref, parent_ref, *, num_iters):
    """Symmetric-hook break + ``num_iters`` pointer jumps + the final
    vertex relabel, parent resident in VMEM across every jump."""
    p = parent0_ref[...]
    rows, lanes = p.shape
    row = jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 1)
    ids = row * lanes + col
    # break_symmetric_hooks: mutual pair f <-> g, smaller id self-roots.
    pp = jnp.take(p.reshape(-1), p, axis=0)
    p = jnp.where((pp == ids) & (ids < p), ids, p)

    def jump(_, q):
        return jnp.take(q.reshape(-1), q, axis=0)

    p = jax.lax.fori_loop(0, num_iters, jump, p)
    parent_ref[...] = p
    newf_ref[...] = jnp.take(p.reshape(-1), frag_ref[...], axis=0)


# ---------------------------------------------------------------------------
# Wrappers (trace-time entry points; callers guard with *_shape_ok)
# ---------------------------------------------------------------------------
def fused_ell_row_min(fragment, verts, dstb, rankb):
    """Per-row masked rank minimum over one ELL bucket — the fused form of
    ``fragment[verts]`` / ``fragment[dstb]`` / mask / ``min(axis=1)``.
    Pad rows (vertex 0, all-sentinel ranks) come out as INT32_MAX, inert
    under the caller's scatter-min, exactly like the XLA form."""
    from jax.experimental import pallas as pl

    rows, width = dstb.shape
    block = _pow2_factor(rows, max(1, _ELL_BLOCK_ELEMS // max(1, width)))
    grid = (rows // block,)
    return pl.pallas_call(
        _ell_row_min_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(fragment.shape, lambda i: (0,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block, width), lambda i: (i, 0)),
            pl.BlockSpec((block, width), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((rows,), jnp.int32),
        interpret=_interpret(),
    )(fragment, verts, dstb, rankb)


def fused_gather_key(fragment, src, dst, rank):
    """``(fragment[src], masked rank key)`` in one VMEM pass over the flat
    slot arrays (the non-identity ``fragment_moe`` front half)."""
    from jax.experimental import pallas as pl

    e = src.shape[0]
    rows = e // _LANES
    block = _pow2_factor(rows, _FLAT_BLOCK_ROWS)
    shape2 = (rows, _LANES)
    blk = pl.BlockSpec((block, _LANES), lambda i: (i, 0))
    fsrc, key = pl.pallas_call(
        _gather_key_kernel,
        grid=(rows // block,),
        in_specs=[pl.BlockSpec(fragment.shape, lambda i: (0,)), blk, blk, blk],
        out_specs=(blk, blk),
        out_shape=(
            jax.ShapeDtypeStruct(shape2, jnp.int32),
            jax.ShapeDtypeStruct(shape2, jnp.int32),
        ),
        interpret=_interpret(),
    )(fragment, src.reshape(shape2), dst.reshape(shape2), rank.reshape(shape2))
    return fsrc.reshape(-1), key.reshape(-1)


def fused_hook_compress(has_moe, moe_dst_frag, fragment):
    """One merge round fused: hook, symmetric break, bounded pointer jump,
    vertex relabel — same contract as ``union_find.hook_and_compress``
    (``(new_fragment, parent_star)``), intermediates VMEM-only.

    Exactness: ``ceil(log2 n)`` jumps double pointer reach past any chain
    a forest of n nodes can hold, so the bounded loop lands on the same
    fixpoint the XLA ``while_loop`` early-exits at.
    """
    from jax.experimental import pallas as pl

    n = fragment.shape[0]
    pad = (-n) % _LANES
    total = n + pad
    ids = jnp.arange(total, dtype=jnp.int32)
    if pad:
        # Pad entries are isolated self-roots: no real entry can point at
        # them (parent values are node ids < n), so they perturb nothing.
        has_moe = jnp.concatenate([has_moe, jnp.zeros(pad, bool)])
        moe_dst_frag = jnp.concatenate([moe_dst_frag, ids[n:]])
        fragment = jnp.concatenate([fragment, ids[n:]])
    parent0 = jnp.where(has_moe, moe_dst_frag, ids)
    rows = total // _LANES
    shape2 = (rows, _LANES)
    num_iters = max(1, math.ceil(math.log2(max(2, total))))
    spec = pl.BlockSpec(shape2, lambda: (0, 0))
    newf, parent = pl.pallas_call(
        functools.partial(_hook_compress_kernel, num_iters=num_iters),
        in_specs=[spec, spec],
        out_specs=(spec, spec),
        out_shape=(
            jax.ShapeDtypeStruct(shape2, jnp.int32),
            jax.ShapeDtypeStruct(shape2, jnp.int32),
        ),
        interpret=_interpret(),
    )(parent0.reshape(shape2), fragment.reshape(shape2))
    return newf.reshape(-1)[:n], parent.reshape(-1)[:n]
