"""The query-kind registry: one row per thing the stack can answer.

Each :class:`KindSpec` binds a kind name to

* a **solver entry** (``analytics/solvers.py`` wrapper over the scheduler's
  MSF solve — every kind reuses the same GHS/Borůvka level loop),
* a **result schema** (the kind-specific response fields the serve protocol
  adds on top of the shared solve fields),
* a **NetworkX oracle** (the exactness contract ``gate-analytics-v1``
  enforces: label partition for ``components``, total weight for
  ``mst``/``k_msf``, max-MST-edge weight for ``bottleneck``, the minimax
  path value for ``path_max``),
* a **verify adapter** (the :mod:`verify.certify` entry that certifies a
  served answer of this kind), and
* a **default SLO class** (from :data:`obs.slo.KIND_CLASS_DEFAULTS`, applied
  only when the request names no ``slo_class`` of its own; ``mst`` stays
  untagged for telemetry back-compat).

Callable references are stored as ``"module:attr"`` strings and resolved
lazily so importing the registry never pulls jax/scipy — the fleet router
reads it on its jax-free path.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Optional, Tuple

from distributed_ghs_implementation_tpu.obs.slo import KIND_CLASS_DEFAULTS

_PKG = "distributed_ghs_implementation_tpu"


def _resolve(ref: Optional[str]):
    if ref is None:
        return None
    mod, _, attr = ref.partition(":")
    return getattr(importlib.import_module(mod), attr)


@dataclasses.dataclass(frozen=True)
class KindSpec:
    """One registry row; see the module docstring for field contracts."""

    name: str
    #: ``module:attr`` of the solver entry (None for ``mst`` — the service's
    #: native solve path IS the mst solver).
    solver_ref: Optional[str]
    #: ``module:attr`` of the NetworkX oracle used by drills/tests.
    oracle_ref: Optional[str]
    #: ``module:attr`` of the verify adapter (``verify/certify.py``).
    certify_ref: Optional[str]
    #: Kind-specific response fields beyond the shared solve fields.
    schema: Tuple[str, ...]
    #: Request parameters the kind consumes (validated by
    #: :func:`parse_params`).
    params: Tuple[str, ...] = ()
    #: Whether answers are store-cached under a per-kind digest key.
    cached: bool = True

    @property
    def slo_class(self) -> Optional[str]:
        return KIND_CLASS_DEFAULTS.get(self.name)

    @property
    def solver(self):
        return _resolve(self.solver_ref)

    @property
    def oracle(self):
        return _resolve(self.oracle_ref)

    @property
    def certify(self):
        return _resolve(self.certify_ref)


KINDS = {
    spec.name: spec
    for spec in (
        KindSpec(
            name="mst",
            solver_ref=None,
            oracle_ref=f"{_PKG}.utils.verify:networkx_mst_weight",
            certify_ref=f"{_PKG}.verify.certify:certify_result",
            schema=(),
        ),
        KindSpec(
            name="components",
            solver_ref=f"{_PKG}.analytics.solvers:solve_components",
            oracle_ref=f"{_PKG}.analytics.solvers:oracle_components",
            certify_ref=f"{_PKG}.verify.certify:certify_components",
            schema=("num_components", "labels"),
        ),
        KindSpec(
            name="k_msf",
            solver_ref=f"{_PKG}.analytics.solvers:solve_k_msf",
            oracle_ref=f"{_PKG}.analytics.solvers:oracle_k_msf_weight",
            certify_ref=f"{_PKG}.verify.certify:certify_k_forest",
            schema=("k",),
            params=("k",),
        ),
        KindSpec(
            name="bottleneck",
            solver_ref=f"{_PKG}.analytics.solvers:solve_bottleneck",
            oracle_ref=f"{_PKG}.analytics.solvers:oracle_bottleneck",
            certify_ref=f"{_PKG}.verify.certify:certify_bottleneck",
            schema=("bottleneck_weight", "bottleneck_edge"),
        ),
        KindSpec(
            name="path_max",
            solver_ref=f"{_PKG}.analytics.solvers:solve_path_max",
            oracle_ref=f"{_PKG}.analytics.solvers:oracle_path_max",
            certify_ref=None,  # derived per-query from a certified MST
            schema=("u", "v", "connected", "path_max_weight", "path_max_edge"),
            params=("u", "v"),
            cached=False,  # per-(u, v) answers; the underlying MST is cached
        ),
    )
}


def known() -> Tuple[str, ...]:
    return tuple(KINDS)


def get(kind) -> KindSpec:
    """The spec for ``kind`` (default ``mst``); ``ValueError`` on unknown."""
    name = "mst" if kind is None else str(kind)
    spec = KINDS.get(name)
    if spec is None:
        raise ValueError(
            f"unknown kind {name!r}; expected {'|'.join(KINDS)}"
        )
    return spec


def cache_token(kind, *, k: Optional[int] = None) -> Optional[str]:
    """The per-kind cache-key token (third ``:`` segment in the store key),
    or ``None`` when the kind is not store-cached (``path_max``). ``mst``
    returns ``"mst"`` — the store maps it back to the historical
    two-segment key."""
    spec = get(kind)
    if not spec.cached:
        return None
    if spec.name == "k_msf":
        return f"k_msf{int(k)}"
    return spec.name


def parse_params(kind, request: dict) -> dict:
    """Validate and extract the kind's request parameters.

    ``k_msf`` requires integer ``k >= 1``; ``path_max`` requires integer
    node ids ``u``/``v``. Raises ``ValueError`` with a client-facing
    message on anything malformed.
    """
    spec = get(kind)
    out: dict = {}
    if "k" in spec.params:
        try:
            k = int(request["k"])
        except (KeyError, TypeError, ValueError):
            raise ValueError("kind 'k_msf' requires an integer 'k' field")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        out["k"] = k
    if "u" in spec.params:
        try:
            out["u"] = int(request["u"])
            out["v"] = int(request["v"])
        except (KeyError, TypeError, ValueError):
            raise ValueError(
                "kind 'path_max' requires integer 'u' and 'v' fields"
            )
    return out
