"""Bench-scale coverage: the gap between unit graphs (≤10^4 edges) and bench
graphs (10^7+) is where padding/bucketing/compaction bugs live (VERDICT r1
weak #8). These run ≥10^6-edge graphs through both the device and sharded
backends on the virtual 8-device CPU mesh, oracle-verified. Marked slow;
run explicitly with `pytest -m slow` (they are in the default run too — the
whole suite stays under the driver's budget)."""

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.graphs.generators import (
    gnm_random_graph,
    rmat_graph,
    road_grid_graph,
)
from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight


@pytest.mark.slow
@pytest.mark.parametrize("scale", [16, 17])
def test_rmat_bench_scale_device(scale):
    """RMAT at 10^6-edge scale: rank strategy vs oracle + fused parity."""
    g = rmat_graph(scale, 24, seed=scale)
    assert g.num_edges > 10**6
    ids, frag, _ = solve_graph(g, strategy="rank")
    assert abs(float(g.w[ids].sum()) - scipy_mst_weight(g)) < 1e-6
    assert len(ids) == g.num_nodes - np.unique(frag).size
    ids_f, _, _ = solve_graph(g, strategy="fused")
    assert np.array_equal(ids, ids_f)


@pytest.mark.slow
def test_gnm_bench_scale_device():
    """G(n, m) with 10^6 edges (BASELINE config 2 scaled up)."""
    g = gnm_random_graph(1 << 18, 1 << 20, seed=44)
    ids, frag, _ = solve_graph(g, strategy="rank")
    assert abs(float(g.w[ids].sum()) - scipy_mst_weight(g)) < 1e-6
    assert np.unique(frag).size == 1


@pytest.mark.slow
def test_road_grid_bench_scale_device():
    """High-diameter grid at 10^6 nodes: the compact_after=1 path at scale."""
    g = road_grid_graph(1024, 1024, seed=45)
    ids, frag, lv = solve_graph(g, strategy="rank")
    assert abs(float(g.w[ids].sum()) - scipy_mst_weight(g)) < 1e-6
    assert np.unique(frag).size == 1
    assert lv > 6  # diameter >> log n regime actually exercised


@pytest.mark.slow
def test_rmat_bench_scale_sharded():
    """RMAT-16 (10^6 edges) on the virtual 8-device mesh, byte-identical to
    the single-device solve."""
    from distributed_ghs_implementation_tpu.parallel.sharded import (
        solve_graph_sharded,
    )

    g = rmat_graph(16, 24, seed=16)
    assert g.num_edges > 10**6
    ids_s, frag_s, _ = solve_graph_sharded(g)
    ids_d, frag_d, _ = solve_graph(g, strategy="rank")
    assert np.array_equal(ids_s, ids_d)
    assert np.array_equal(frag_s, frag_d)
    assert abs(float(g.w[ids_s].sum()) - scipy_mst_weight(g)) < 1e-6


@pytest.mark.slow
def test_compact_space_shrink_fires_and_is_exact():
    """The high-diameter compact-fragment-space path: assert the shrink
    actually fires (not just that some path solved the graph) and that MST
    weight, fragment labels, and label fixpoints survive the replay."""
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    g = road_grid_graph(512, 512, seed=3)
    orig = rs._shrink_and_run
    orig_oneshot = rs._ONE_SHOT_MAX_SLOTS
    f_sizes = []

    def spy(*a, **k):
        f_sizes.append(k.get("f_size"))
        return orig(*a, **k)

    rs._shrink_and_run = spy
    # Disable adaptive one-shot chunking: at this test's size it finishes
    # the solve in the first shrink's dispatch, leaving the multi-stage
    # chain (the thing under test) unexercised.
    rs._ONE_SHOT_MAX_SLOTS = 0
    try:
        # Force the sparse head (level 1 only): the grid family's full-width
        # level 2 would leave just one shrink; this path exercises the
        # multi-stage chain + replay.
        vmin0, ra, rb = rs.prepare_rank_arrays(g)
        mst, fragment, lv = rs.solve_rank_staged(
            vmin0, ra, rb, compact_after=1, chunk_levels=2, compact_space=True
        )
    finally:
        rs._shrink_and_run = orig
        rs._ONE_SHOT_MAX_SLOTS = orig_oneshot
    ranks = np.nonzero(np.asarray(mst))[0]
    ids = np.sort(g.edge_id_of_rank(ranks))
    frag = np.asarray(fragment)[: g.num_nodes]
    assert len(f_sizes) >= 2, f_sizes  # multi-stage shrink chain + replay
    assert abs(float(g.w[ids].sum()) - scipy_mst_weight(g)) < 1e-6
    assert np.unique(frag).size == 1
    # Labels are fixpoints (fragment[label] == label), the kernel contract.
    labels = np.unique(frag)
    assert np.array_equal(frag[labels], labels)


@pytest.mark.slow
def test_compact_space_shrink_disconnected_with_isolated():
    """Replay must keep dead-fragment labels distinct across shrink stages."""
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
    from distributed_ghs_implementation_tpu.models import rank_solver as rs

    g1 = road_grid_graph(300, 300, seed=5)
    off = g1.num_nodes
    g2 = road_grid_graph(120, 120, seed=6)
    u = np.concatenate([g1.u, g2.u + off])
    v = np.concatenate([g1.v, g2.v + off])
    w = np.concatenate([g1.w, g2.w])
    g = Graph.from_arrays(off + g2.num_nodes + 7, u, v, w)  # +7 isolated
    ids, frag, lv = rs.solve_graph_rank(g)
    assert abs(float(g.w[ids].sum()) - scipy_mst_weight(g)) < 1e-6
    assert np.unique(frag).size == 2 + 7
    # Component membership must match a union-find over the MST edges.
    import scipy.sparse as sp
    import scipy.sparse.csgraph as csg

    m = sp.coo_matrix(
        (np.ones(len(ids)), (g.u[ids], g.v[ids])),
        shape=(g.num_nodes, g.num_nodes),
    )
    ncomp, ref_labels = csg.connected_components(m, directed=False)
    assert ncomp == 2 + 7
    # Same partition: each reference component maps to exactly one label.
    for c in range(ncomp):
        assert np.unique(frag[ref_labels == c]).size == 1


@pytest.mark.slow
def test_rank_sharded_bench_scale():
    """The multi-chip fast path at 10^6-edge scale on the virtual 8-device
    mesh (the other sharded tests stop at 10^4 edges)."""
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )

    g = rmat_graph(16, 24, seed=3)
    assert g.num_edges > 10**6
    ids, frag, lv = solve_graph_rank_sharded(g)
    assert abs(float(g.w[ids].sum()) - scipy_mst_weight(g)) < 1e-6
    assert np.unique(frag).size == g.num_nodes - len(ids)


@pytest.mark.slow
def test_rank_sharded_high_diameter_scale():
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )

    g = road_grid_graph(600, 600, seed=5)
    ids, frag, lv = solve_graph_rank_sharded(g)
    assert abs(float(g.w[ids].sum()) - scipy_mst_weight(g)) < 1e-6
    assert lv >= 8  # genuinely multi-level


def test_int32_rank_envelope_guard():
    """A graph whose padded rank space leaves the int32 envelope fails at
    staging with the measured ceiling in the message, not deep in the level
    loop (VERDICT r3 weak #6)."""
    from distributed_ghs_implementation_tpu.models.rank_solver import (
        check_rank_envelope,
        prepare_rank_arrays,
    )

    check_rank_envelope(1 << 27, 1 << 30)  # RMAT-26 class: inside
    with pytest.raises(ValueError, match="int32 rank envelope"):
        check_rank_envelope(1 << 27, 1 << 31)
    with pytest.raises(ValueError, match="int32 rank envelope"):
        check_rank_envelope(1 << 31, 1 << 30)

    class ScaleTooBig:
        """Duck-typed stand-in: 2^31-edge arrays are not allocatable here;
        the guard must fire before any allocation happens."""

        num_nodes = 1 << 28
        num_edges = (1 << 31) - 100

    with pytest.raises(ValueError, match="2\\^31"):
        prepare_rank_arrays(ScaleTooBig())


@pytest.mark.slow
def test_rank_sharded_filtered_realistic_width():
    """The sharded filter-Kruskal path at RMAT-19 width (VERDICT r3 item 2):
    ~7.7M edges, 1M-slot shards on the 8-device mesh — the auto policy
    engages the filter for real (m_pad >= _FILTER_MIN_RANKS), and per-shard
    compaction / fs_local sizing / the packed harvest all run at a width
    where overflow bugs would show. Byte-identical to the single-device
    solve and oracle-verified."""
    from distributed_ghs_implementation_tpu.models.rank_solver import (
        _pick_family,
        use_filtered_path,
    )
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )

    from distributed_ghs_implementation_tpu.models.boruvka import _bucket_size

    g = rmat_graph(19, 16, seed=24)
    m_pad = _bucket_size(g.num_edges)  # the entry's policy tests padded width
    assert use_filtered_path(_pick_family(g), m_pad)  # auto = filtered
    ids, frag, lv = solve_graph_rank_sharded(g)
    ids_d, frag_d, _ = solve_graph(g, strategy="rank")
    assert np.array_equal(ids, ids_d)
    assert np.array_equal(frag, frag_d)
    assert abs(float(g.w[ids].sum()) - scipy_mst_weight(g)) < 1e-6
