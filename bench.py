"""Benchmark: MST throughput on RMAT graphs (BASELINE.json metric).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "edges/s", "vs_baseline": N}

Baseline: the reference's best measured *correct* run — the 10-node/28-edge
thread-backend experiment at 0.41 s (BASELINE.md) ≈ 68 edges/s. Its 20-node
config is already wrong 2/3 of the time, so this is the fastest throughput the
reference demonstrably sustains.

Default config: RMAT scale-22 (4.2M vertices, ~64M undirected edges after
dedup), solved on the real TPU chip, verified for weight parity against the
SciPy MSF oracle — the largest size whose full gen+verify cycle stays in
single-digit minutes (scale 24's oracle alone is ~15 min; its measured
numbers live in docs/BASELINE_RUNS.jsonl). Throughput rises with scale
(the filter-Kruskal path amortizes fixed costs), so this is also a more
faithful picture of the solver than scale 20 (~17.8M vs ~11.8M edges/s).
``--scale`` adjusts size; ``--backend sharded`` exercises the mesh path.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

BASELINE_EDGES_PER_SEC = 68.0  # reference: 28 edges / 0.41 s (BASELINE.md)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scale", type=int, default=22, help="RMAT scale (2^scale vertices)")
    p.add_argument("--edge-factor", type=int, default=16)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--backend", default="device", choices=["device", "sharded"])
    p.add_argument("--no-verify", action="store_true")
    args = p.parse_args(argv)

    from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
    from distributed_ghs_implementation_tpu.graphs.generators import rmat_graph
    from distributed_ghs_implementation_tpu.utils.verify import verify_result

    t0 = time.perf_counter()
    g = rmat_graph(args.scale, args.edge_factor, seed=24)
    print(
        f"generated RMAT-{args.scale}: {g.num_nodes:,} nodes, {g.num_edges:,} edges "
        f"in {time.perf_counter() - t0:.1f}s",
        file=sys.stderr,
    )

    # Device-resident timing of the kernel that is also the one verified:
    # arrays staged once, each repeat is solve + scalar sync.
    times = []
    if args.backend == "device":
        import numpy as np

        from distributed_ghs_implementation_tpu.api import MSTResult
        from distributed_ghs_implementation_tpu.models.rank_solver import (
            _pick_family,
            prepare_rank_arrays_full,
            solve_rank_auto,
        )

        t0 = time.perf_counter()
        vmin0, ra, rb, parent1 = prepare_rank_arrays_full(g)
        print(f"host prep (ranks + first_ranks + L1 + staging): "
              f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
        fam = _pick_family(g)  # same path production takes
        mst, fragment, levels = solve_rank_auto(
            vmin0, ra, rb, family=fam, parent1=parent1
        )
        _ = np.asarray(mst.ravel()[0])  # warm + sync
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            mst, fragment, levels = solve_rank_auto(
                vmin0, ra, rb, family=fam, parent1=parent1
            )
            _ = np.asarray(mst.ravel()[0])
            times.append(time.perf_counter() - t0)
        # Wrap the timed kernel's own output for verification below.
        ranks = np.nonzero(np.asarray(mst))[0]
        edge_ids = np.sort(g.edge_id_of_rank(ranks))
        fragment = np.asarray(fragment)[: g.num_nodes]
        result = MSTResult(
            graph=g,
            edge_ids=edge_ids,
            num_levels=int(levels),
            wall_time_s=min(times),
            backend="device/rank",
            num_components=int(np.unique(fragment).size),
        )
    else:
        result = minimum_spanning_forest(g, backend=args.backend)
        for _ in range(args.repeats):
            r = minimum_spanning_forest(g, backend=args.backend)
            times.append(r.wall_time_s)
    best = min(times)
    print(f"solve times: {[f'{t:.3f}' for t in times]}", file=sys.stderr)

    if not args.no_verify:
        v = verify_result(result, oracle="scipy")
        if not v.ok:
            print(f"VERIFICATION FAILED: {v}", file=sys.stderr)
            print(
                json.dumps(
                    {
                        "metric": f"MST edges/sec on RMAT-{args.scale} (VERIFY FAILED)",
                        "value": 0.0,
                        "unit": "edges/s",
                        "vs_baseline": 0.0,
                    }
                )
            )
            return 1
        print(f"verified: weight {v.actual_weight} = scipy oracle", file=sys.stderr)

    edges_per_sec = g.num_edges / best
    verified = "weight-verified" if not args.no_verify else "unverified"
    print(
        json.dumps(
            {
                "metric": f"MST edges/sec on RMAT-{args.scale} ({g.num_nodes} nodes, {g.num_edges} edges, {verified})",
                "value": round(edges_per_sec, 1),
                "unit": "edges/s",
                "vs_baseline": round(edges_per_sec / BASELINE_EDGES_PER_SEC, 1),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
