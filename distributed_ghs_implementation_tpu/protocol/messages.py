"""Protocol vocabulary: message kinds, node states, edge states.

Mirrors the reference's enums (``/root/reference/ghs_implementation.py:17-43``;
MPI variant adds TERMINATE at ``ghs_implementation_mpi.py:14-22``, which a
deterministic simulator does not need — quiescence is detectable exactly).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional


class MessageType(enum.Enum):
    CONNECT = "connect"
    INITIATE = "initiate"
    TEST = "test"
    ACCEPT = "accept"
    REJECT = "reject"
    REPORT = "report"
    CHANGE_ROOT = "change_root"


class NodeState(enum.Enum):
    """``SLEEPING/FIND/FOUND`` per the protocol (``ghs_implementation.py:33-37``)."""

    SLEEPING = "sleeping"
    FIND = "find"
    FOUND = "found"


class EdgeState(enum.Enum):
    """``BASIC/BRANCH/REJECTED`` per the protocol (``ghs_implementation.py:27-31``)."""

    BASIC = "basic"
    BRANCH = "branch"
    REJECTED = "rejected"


@dataclasses.dataclass(frozen=True)
class Message:
    """A protocol message on the wire.

    ``level``/``fragment``/``weight`` cover every payload the seven message
    kinds need (the reference ships ad-hoc dicts,
    ``ghs_implementation_mpi.py:99``). ``fragment`` and ``weight`` carry edge
    *ranks* (see ``protocol/node.py`` on why ranks, not raw weights).
    """

    type: MessageType
    sender: int
    level: int = 0
    fragment: int = 0
    weight: Optional[int] = None  # None encodes "infinity" in REPORT
