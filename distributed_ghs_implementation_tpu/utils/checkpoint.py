"""Per-level checkpoint/resume for long solves, with crash-consistent saves.

The reference has no checkpointing (SURVEY.md §5 — durable state is input
files and result JSONs only). Here the whole solver state is three arrays —
``fragment[n]``, ``mst_ranks[m]``, ``level`` — so a checkpoint is one npz and
resume is ``boruvka_solve`` from an arbitrary starting partition (explicitly
supported; see its docstring). Worth having for the RMAT-24/USA-road configs
where a preempted multi-minute run would otherwise restart from scratch.

Durability discipline: every save is tmp-file + rename, and the previous
checkpoint survives as ``<path>.bak`` (one retained generation). Resume goes
through :func:`load_checkpoint_resilient` — primary, then ``.bak``, then a
fresh solve — so a file torn by a crash mid-write (simulated via the
``checkpoint.save`` fault site, ``utils.resilience.FAULTS``) costs at most
one checkpoint interval, never the run. A checkpoint from a *different*
graph still refuses loudly (:class:`CheckpointMismatch`): silently solving
from a stranger's partition is the one failure recovery must not paper over.
"""

from __future__ import annotations

import contextlib
import os
import tempfile
from typing import List, Optional, Tuple

import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.utils.locking import fsync_dir
from distributed_ghs_implementation_tpu.utils.resilience import FAULTS, InjectedFault


class CheckpointMismatch(ValueError):
    """The checkpoint was written for a different graph (fingerprint guard)."""


def graph_fingerprint(graph: Graph) -> np.ndarray:
    """Identity of a graph as int64 words: ``[n, m, sha256/4...]``.

    Derived from :meth:`Graph.digest` (the content hash the serve result
    cache keys on, so checkpoints and cache entries agree on what "the same
    graph" means); ``n``/``m`` lead so a mismatch error stays readable.
    Guards resume against a stale checkpoint from a *different* graph, which
    would otherwise silently yield a wrong MST whenever the padded shapes
    happen to collide (likely, since shapes are pow2-bucketed).
    """
    return np.concatenate(
        [
            np.asarray([graph.num_nodes, graph.num_edges], dtype=np.int64),
            graph.digest_words(),
        ]
    )


def atomic_write_npz(
    path: str,
    arrays: dict,
    *,
    retain_previous: bool = True,
    fault_site: str = "checkpoint.save",
) -> str:
    """Crash-consistent npz write: tmp file + rename, one ``.bak`` generation.

    ``retain_previous`` rotates an existing ``path`` to ``path + ".bak"``
    first, so the last known-good generation survives a write that a crash
    (or the armed ``fault_site``) leaves torn. Shared by solver checkpoints
    and the serve result store (``serve/store.py``, fault site
    ``serve.store.save``).

    Durability regression note (round 18): the tmp file is fsynced before
    the rename and the PARENT DIRECTORY is fsynced after it. The original
    "atomic dance" stopped at ``os.replace``, which only orders the
    rename against other metadata ops — on a journaling filesystem a host
    crash (power loss, not process death) shortly after the rename could
    replay the directory without the new entry, or land the entry while
    the file's blocks were still unwritten, losing the checkpoint despite
    the atomic rename. rename-without-dirfsync is durable *eventually*,
    not at return — and every caller here (serve store publishes, stream
    snapshots, checkpoint saves) treats return as the commit point.

    Integrity (round 19): every write records a ``<path>.sha256`` sidecar
    (``utils/integrity.py``) so loads can refuse bit-rotted or torn bytes
    before deserializing them. Ordering closes the false-quarantine hole:
    the OLD sidecar rotates to ``.bak`` (or is unlinked) before the data
    rename, and the NEW sidecar lands after it — a crash in the window
    leaves the fresh data file *without* a sidecar, which loads treat as
    "unverified" (accepted, counted), never as a mismatch against a stale
    hash.
    """
    from distributed_ghs_implementation_tpu.utils.integrity import (
        sha256_file,
        sidecar_path,
        write_sidecar,
    )

    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    os.close(fd)
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **{k: np.asarray(v) for k, v in arrays.items()})
            f.flush()
            os.fsync(f.fileno())
        digest = sha256_file(tmp)
        if retain_previous and os.path.exists(path):
            import zipfile

            if zipfile.is_zipfile(path):
                os.replace(path, path + ".bak")
                try:
                    os.replace(
                        sidecar_path(path), sidecar_path(path + ".bak")
                    )
                except OSError:
                    # The rotated primary had NO sidecar (a crash landed
                    # between its data rename and sidecar write): any
                    # older .bak sidecar now describes bytes that are
                    # gone — leaving it behind would false-quarantine
                    # the good .bak generation on its next read.
                    with contextlib.suppress(OSError):
                        os.unlink(sidecar_path(path + ".bak"))
            else:
                # The primary is torn (e.g. the save this one follows
                # crashed mid-write): rotating it would clobber the last
                # good generation. Drop it and keep the loadable .bak.
                os.unlink(path)
                with contextlib.suppress(OSError):
                    os.unlink(sidecar_path(path))
        else:
            # The stale sidecar must never outlive the data file it
            # described (a crash after the data rename would otherwise
            # read as corruption of the NEW file).
            with contextlib.suppress(OSError):
                os.unlink(sidecar_path(path))
        armed = FAULTS.pop(fault_site)
        if armed is not None:
            if armed.kind == "torn":
                # Simulate a crash on a non-atomic filesystem: the
                # destination ends up holding a truncated npz.
                with open(tmp, "rb") as f:
                    blob = f.read()
                with open(path, "wb") as f:
                    f.write(blob[: max(1, len(blob) // 2)])
            raise InjectedFault(f"injected fault at {fault_site} ({armed.kind})")
        os.replace(tmp, path)
        write_sidecar(path, digest)
        fsync_dir(d)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def save_checkpoint(
    path: str,
    fragment,
    mst_ranks,
    level: int,
    *,
    fingerprint=None,
    retain_previous: bool = True,
) -> str:
    """Atomic npz write of the solver state (see :func:`atomic_write_npz`)."""
    arrays = dict(
        fragment=np.asarray(fragment),
        mst_ranks=np.asarray(mst_ranks),
        level=np.asarray(level),
    )
    if fingerprint is not None:
        arrays["fingerprint"] = np.asarray(fingerprint)
    return atomic_write_npz(path, arrays, retain_previous=retain_previous)


def load_checkpoint(
    path: str, *, expect_fingerprint=None
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Load solver state; refuses a checkpoint whose fingerprint mismatches."""
    with np.load(path) as data:
        if expect_fingerprint is not None:
            stored = data.get("fingerprint")
            if stored is None or not np.array_equal(stored, expect_fingerprint):
                raise CheckpointMismatch(
                    f"checkpoint {path} was written for a different graph "
                    f"(fingerprint {None if stored is None else stored.tolist()} "
                    f"!= expected {np.asarray(expect_fingerprint).tolist()})"
                )
        # Materialize before the NpzFile closes (arrays decompress on access).
        return (
            np.asarray(data["fragment"]),
            np.asarray(data["mst_ranks"]),
            int(data["level"]),
        )


def load_checkpoint_resilient(
    path: str, *, expect_fingerprint=None
) -> Tuple[Optional[Tuple[np.ndarray, np.ndarray, int]], Optional[str], List[Tuple[str, str]]]:
    """Load ``path``, falling back to ``path + ".bak"``, then to ``None``.

    Returns ``(state_or_None, source_path_or_None, notes)`` where ``notes``
    records why each skipped candidate was rejected — the incident trail for
    logs and the chaos report. Corruption (truncated zip, missing keys, IO
    errors) falls through; :class:`CheckpointMismatch` re-raises, because a
    wrong-graph resume is a caller bug, not a recoverable fault.
    """
    from distributed_ghs_implementation_tpu.utils.integrity import (
        IntegrityError,
        check_file,
    )

    notes: List[Tuple[str, str]] = []
    for candidate in (path, path + ".bak"):
        if not os.path.exists(candidate):
            notes.append((candidate, "missing"))
            continue
        try:
            # Checksum first: bit-rotted bytes must be rejected before
            # np.load parses them (a corrupt zip can fail DEEP inside
            # decompression — or worse, parse into wrong arrays).
            check_file(candidate)
            state = load_checkpoint(candidate, expect_fingerprint=expect_fingerprint)
        except CheckpointMismatch:
            raise
        except IntegrityError as e:
            notes.append((candidate, f"IntegrityError: {e}"))
            continue
        except Exception as e:  # torn/corrupt/unreadable: try the next generation
            notes.append((candidate, f"{type(e).__name__}: {e}"))
            continue
        return state, candidate, notes
    return None, None, notes


def _warn_skipped_generations(state, notes) -> None:
    """Surface a degraded resume: corrupt generations must not be silent."""
    skipped = [(p, why) for p, why in notes if why != "missing"]
    if not skipped:
        return
    import warnings

    trail = "; ".join(f"{p}: {why}" for p, why in skipped)
    tail = "resuming from the previous generation" if state is not None else (
        "no loadable generation — solving from scratch"
    )
    warnings.warn(f"checkpoint recovery: {trail} — {tail}", RuntimeWarning)


def solve_graph_checkpointed(
    graph: Graph,
    checkpoint_path: str,
    *,
    every: int = 1,
    resume: bool = True,
    strategy: str = "auto",
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Checkpointing solve; resumes from ``checkpoint_path`` when present.
    Same return contract as ``models.boruvka.solve_graph``.

    ``strategy``: ``"stepped"`` checkpoints after every ``every`` levels;
    ``"rank"`` uses the fast rank-space solver and checkpoints every
    ``every``-th chunk boundary (the per-chunk vertex partition is
    reconstructed through any fragment-space shrinks by the replay pass — at
    RMAT-24 scale the stepped kernel is not a practical host). ``"auto"``
    picks rank at bench scale.
    """
    n = graph.num_nodes
    if n == 0 or graph.num_edges == 0:
        return np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0

    fp = graph_fingerprint(graph)
    initial_state = None
    if resume:
        initial_state, _source, notes = load_checkpoint_resilient(
            checkpoint_path, expect_fingerprint=fp
        )
        _warn_skipped_generations(initial_state, notes)

    if strategy == "auto":
        from distributed_ghs_implementation_tpu.models.boruvka import (
            ELL_AUTO_EDGE_THRESHOLD,
        )

        strategy = (
            "rank" if graph.num_edges >= ELL_AUTO_EDGE_THRESHOLD else "stepped"
        )

    if strategy == "rank":
        from distributed_ghs_implementation_tpu.models.rank_solver import (
            _pick_family,
            make_production_solver,
            prepare_rank_arrays_full,
            solve_rank_resume,
        )

        chunks_seen = [0]

        def on_chunk(level, fragment, mst_ranks, count):
            # `every` counts chunk boundaries here (levels on the stepped
            # path); the final state is always saved below either way.
            chunks_seen[0] += 1
            if chunks_seen[0] % every == 0 or count == 0:
                save_checkpoint(
                    checkpoint_path, fragment, mst_ranks, level, fingerprint=fp
                )

        if initial_state is not None:
            # Resume is exact from any saved partition; solve_rank_resume
            # picks the chunked endpoint rebuild at widths where a
            # full-width relabel would not fit (the capacity regime the
            # chunked filter exists for).
            vmin0, ra, rb, _parent1 = prepare_rank_arrays_full(graph)
            mst_ranks, fragment, levels = solve_rank_resume(
                vmin0, ra, rb, initial_state, family=_pick_family(graph),
                on_chunk=on_chunk,
            )
        else:
            # Fresh solve: the production routing, with the checkpoint
            # hook (make_production_solver is the single routing source).
            mst_ranks, fragment, levels = make_production_solver(graph)(
                on_chunk=on_chunk
            )
    elif strategy == "stepped":
        from distributed_ghs_implementation_tpu.models.boruvka import (
            prepare_device_arrays,
            solve_arrays_stepped,
        )

        args = prepare_device_arrays(graph)

        def on_level(level, fragment, mst_ranks, has, count, dt):
            if level % every == 0 or not has:
                save_checkpoint(
                    checkpoint_path, fragment, mst_ranks, level, fingerprint=fp
                )

        mst_ranks, fragment, levels = solve_arrays_stepped(
            *args, stepped_levels=None, initial_state=initial_state,
            on_level=on_level,
        )
    else:
        raise ValueError(f"unknown strategy {strategy!r}; expected auto|rank|stepped")
    save_checkpoint(checkpoint_path, fragment, mst_ranks, levels, fingerprint=fp)

    ranks_chosen = np.nonzero(np.asarray(mst_ranks))[0]
    edge_ids = np.sort(graph.edge_id_of_rank(ranks_chosen))
    return edge_ids, np.asarray(fragment)[:n], levels


def solve_graph_checkpointed_sharded(
    graph: Graph,
    checkpoint_path: str,
    *,
    mesh=None,
    every: int = 1,
    resume: bool = True,
    filtered: bool | None = None,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Checkpointing solve on a device mesh (``parallel/rank_sharded.py``).

    Same contract as :func:`solve_graph_checkpointed`. Saves fire at the
    sharded solver's chunk boundaries; the full-width mask is materialized
    (a collective harvest + host transfer) only on boundaries that will be
    saved — the decision derives from the chunk counter, identical on every
    process, so the collective stays SPMD — and only the primary writes
    (the reference's rank-0 artifact rule,
    ``ghs_implementation_mpi.py:929-954``). The resume decision and state
    are broadcast from the primary, so a non-shared filesystem cannot
    diverge the program. Resume is exact from any saved partition and works
    across backends — a checkpoint written by the single-chip solver
    restores into the sharded solve and vice versa (both save the vertex
    partition + the full-width rank mask). The solver's last chunk hook
    (``count == 0``) persists the converged state, so no separate final
    save is needed.
    """
    from distributed_ghs_implementation_tpu.parallel import multihost
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )

    n = graph.num_nodes
    if n == 0 or graph.num_edges == 0:
        return np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0

    fp = graph_fingerprint(graph)
    primary = multihost.is_primary()
    initial_state = None
    if resume and primary:
        try:
            # Corrupt/torn generations fall back (.bak, then fresh) on the
            # primary alone; only a wrong-graph checkpoint still raises.
            initial_state, _source, notes = load_checkpoint_resilient(
                checkpoint_path, expect_fingerprint=fp
            )
            _warn_skipped_generations(initial_state, notes)
        except Exception:
            # Tell every process to abort before re-raising: a primary-only
            # failure would leave the others blocked in the broadcast.
            multihost.broadcast_resume_state(None, error=True)
            raise
    initial_state = multihost.broadcast_resume_state(initial_state)

    chunks_seen = [0]

    def on_chunk(level, fragment, mask_fn, count):
        chunks_seen[0] += 1
        if chunks_seen[0] % every == 0 or count == 0:
            full_mask = mask_fn()  # collective: every process participates
            if primary:
                save_checkpoint(
                    checkpoint_path, fragment, full_mask, level, fingerprint=fp
                )

    return solve_graph_rank_sharded(
        graph, mesh=mesh, filtered=filtered,
        on_chunk=on_chunk, initial_state=initial_state,
    )
