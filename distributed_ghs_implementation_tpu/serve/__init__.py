"""MST query service: cache, scheduler, incremental maintenance, JSONL loop.

The serving layer over the solver stack (``docs/SERVING.md``):

* ``store``     — content-addressed result cache (graph digest + solver
  config -> ``MSTResult``), in-memory LRU front + optional crash-consistent
  on-disk layer.
* ``scheduler`` — single-flight request coalescing and capacity-bounded
  admission; every cache miss solves under the ``utils.resilience``
  supervisor.
* ``dynamic``   — incremental MST maintenance for edge insert/delete/
  reweight against a cached result (cycle rule / replacement-edge search on
  the ``ops`` primitives), with a supervised full re-solve fallback.
* ``service``   — the JSONL request/response loop behind ``ghs serve``.
"""

from distributed_ghs_implementation_tpu.serve.dynamic import (
    DynamicMST,
    Update,
    components_via_unionfind,
    tree_path_max,
)
from distributed_ghs_implementation_tpu.serve.scheduler import SolveScheduler
from distributed_ghs_implementation_tpu.serve.service import MSTService, serve_loop
from distributed_ghs_implementation_tpu.serve.store import ResultStore, solve_cache_key

__all__ = [
    "DynamicMST",
    "MSTService",
    "ResultStore",
    "SolveScheduler",
    "Update",
    "components_via_unionfind",
    "serve_loop",
    "solve_cache_key",
    "tree_path_max",
]
