"""Integrity-checked persistence: sha256 sidecars from atomic_write_npz,
quarantine-instead-of-deserialize on mismatch, the store's corrupt-vs-
ENOENT distinction (a truncated npz NEVER raises out of ``get()``), the
WAL's per-record crc, and checkpoint-recovery integration."""

import json
import os

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.utils.checkpoint import (
    atomic_write_npz,
)
from distributed_ghs_implementation_tpu.utils.integrity import (
    IntegrityError,
    check_file,
    list_quarantined,
    quarantine,
    read_sidecar,
    sidecar_path,
)


@pytest.fixture(autouse=True)
def _clean_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.clear()


def _flip_one_byte(path: str, offset: int = -20) -> None:
    with open(path, "r+b") as f:
        data = bytearray(f.read())
        data[offset] ^= 0xFF
        f.seek(0)
        f.write(data)


# ----------------------------------------------------------------------
# Sidecars from atomic_write_npz
# ----------------------------------------------------------------------
def test_atomic_write_records_matching_sidecar(tmp_path):
    path = str(tmp_path / "x.npz")
    atomic_write_npz(path, {"a": np.arange(5)})
    assert os.path.exists(sidecar_path(path))
    assert check_file(path) == "ok"


def test_rotation_keeps_bak_sidecar_consistent(tmp_path):
    path = str(tmp_path / "x.npz")
    atomic_write_npz(path, {"a": np.arange(5)})
    atomic_write_npz(path, {"a": np.arange(9)})
    assert check_file(path) == "ok"
    assert check_file(path + ".bak") == "ok"
    # The generations really differ (the .bak sidecar is the OLD hash).
    assert read_sidecar(path) != read_sidecar(path + ".bak")


def test_rotation_after_sidecarless_primary_drops_stale_bak_sidecar(
    tmp_path,
):
    """Crash-window regression: a primary that lost its sidecar (crash
    between data rename and sidecar write) must not leave an OLDER
    generation's .bak sidecar behind on the next rotation — that stale
    hash would false-quarantine a perfectly good .bak fallback."""
    path = str(tmp_path / "x.npz")
    atomic_write_npz(path, {"a": np.arange(3)})   # gen 1
    atomic_write_npz(path, {"a": np.arange(5)})   # gen 2 (+ gen-1 .bak)
    os.unlink(sidecar_path(path))  # simulate the crash window
    atomic_write_npz(path, {"a": np.arange(7)})   # gen 3: rotates gen 2
    # The .bak holds gen-2 bytes; a surviving gen-1 sidecar would flag it.
    assert check_file(path) == "ok"
    assert check_file(path + ".bak") == "unverified"
    with np.load(path + ".bak") as data:
        assert data["a"].size == 5


def test_bit_flip_raises_integrity_error_then_quarantines(tmp_path):
    path = str(tmp_path / "x.npz")
    atomic_write_npz(path, {"a": np.arange(64)})
    _flip_one_byte(path)
    with pytest.raises(IntegrityError):
        check_file(path)
    dest = quarantine(path, reason="test", counter="test.quarantined")
    assert dest and os.path.exists(dest)
    assert not os.path.exists(path)
    assert os.path.exists(sidecar_path(dest))  # evidence travels together
    assert list_quarantined(str(tmp_path)) == ["x.npz"]
    assert BUS.counters().get("test.quarantined") == 1
    # A second quarantine of the now-missing path is a no-op, not an error.
    assert quarantine(path) is None


def test_missing_sidecar_is_unverified_not_error(tmp_path):
    path = str(tmp_path / "legacy.npz")
    np.savez(path, a=np.arange(3))  # a pre-integrity file: no sidecar
    assert check_file(path) == "unverified"
    with pytest.raises(FileNotFoundError):
        check_file(str(tmp_path / "nope.npz"))


# ----------------------------------------------------------------------
# Store: quarantine + corrupt-vs-ENOENT (satellite regression)
# ----------------------------------------------------------------------
def _store_with_one_entry(tmp_path):
    from distributed_ghs_implementation_tpu.api import (
        minimum_spanning_forest,
    )
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.serve.store import (
        ResultStore,
        solve_cache_key,
    )

    g = gnm_random_graph(48, 120, seed=5)
    result = minimum_spanning_forest(g, backend="host")
    store = ResultStore(capacity=4, disk_dir=str(tmp_path))
    key = solve_cache_key(g, backend="host")
    store.put(key, result)
    return store, key, g, result


def _disk_file(tmp_path):
    return [str(p) for p in tmp_path.iterdir()
            if p.name.endswith(".npz")][0]


def test_store_quarantines_rotted_file_and_degrades_to_miss(tmp_path):
    store, key, g, result = _store_with_one_entry(tmp_path)
    store._mem.clear()  # force the disk path
    _flip_one_byte(_disk_file(tmp_path))
    assert store.get(key, g) is None  # a miss, never an exception
    counters = BUS.counters()
    assert counters.get("serve.store.quarantined") == 1
    assert list_quarantined(str(tmp_path))
    # The rotted file is GONE from the serving directory: the next put
    # starts clean, the next get is a plain miss.
    BUS.clear()
    assert store.get(key, g) is None
    assert "serve.store.quarantined" not in BUS.counters()


def test_store_truncated_npz_never_raises_from_get(tmp_path):
    """The satellite regression: a legacy torn npz (no sidecar to catch
    it) must come back as a quarantined miss, not an exception."""
    store, key, g, result = _store_with_one_entry(tmp_path)
    store._mem.clear()
    path = _disk_file(tmp_path)
    os.unlink(sidecar_path(path))  # legacy file: integrity can't see it
    with open(path, "r+b") as f:
        blob = f.read()
        f.seek(0)
        f.truncate(len(blob) // 3)
    assert store.get(key, g) is None  # torn zip: miss, not a raise
    assert BUS.counters().get("serve.store.quarantined") == 1


def test_store_enoent_is_a_plain_miss_not_corruption(tmp_path):
    store, key, g, result = _store_with_one_entry(tmp_path)
    store._mem.clear()
    path = _disk_file(tmp_path)
    os.unlink(path)
    os.unlink(sidecar_path(path))
    assert store.get(key, g) is None
    counters = BUS.counters()
    assert "serve.store.quarantined" not in counters
    assert counters.get("serve.store.miss") == 1


def test_store_invalidate_purges_memory_and_quarantines_disk(tmp_path):
    store, key, g, result = _store_with_one_entry(tmp_path)
    assert store.invalidate(key)
    assert len(store) == 0
    assert list_quarantined(str(tmp_path))
    assert BUS.counters().get("serve.store.invalidated") == 1
    # Nothing left to serve from either layer.
    assert store.get(key, g) is None
    # Idempotent: a second invalidate finds nothing.
    assert not store.invalidate(key)


def test_store_bak_generation_survives_primary_rot(tmp_path):
    from distributed_ghs_implementation_tpu.serve.store import ResultStore

    store, key, g, result = _store_with_one_entry(tmp_path)
    store.put(key, result)  # second put: rotates a .bak generation
    store._mem.clear()
    _flip_one_byte(_disk_file(tmp_path))
    got = store.get(key, g)  # primary quarantined, .bak answers
    assert got is not None
    assert got.total_weight == result.total_weight
    assert BUS.counters().get("serve.store.quarantined") == 1


# ----------------------------------------------------------------------
# WAL per-record crc (utils/wal.py)
# ----------------------------------------------------------------------
def test_wal_records_carry_and_validate_crc(tmp_path):
    from distributed_ghs_implementation_tpu.utils.wal import JsonlWal

    wal = JsonlWal(str(tmp_path / "log.jsonl"), schema="test-v1",
                   counter_prefix="test.wal")
    wal.append({"seq": 1, "value": 10})
    wal.append({"seq": 2, "value": 20})
    entries, torn = wal.read()
    assert [e["seq"] for e in entries] == [1, 2] and torn == 0
    with open(wal.path) as f:
        assert all("crc" in json.loads(ln) for ln in f.read().splitlines())


def test_wal_value_mutation_caught_by_crc(tmp_path):
    """A bit flip that keeps the line VALID JSON — the corruption the
    schema check cannot see — must be skipped and counted."""
    from distributed_ghs_implementation_tpu.utils.wal import JsonlWal

    wal = JsonlWal(str(tmp_path / "log.jsonl"), schema="test-v1",
                   counter_prefix="test.wal")
    wal.append({"seq": 1, "value": 10})
    wal.append({"seq": 2, "value": 20})
    wal.append({"seq": 3, "value": 30})
    with open(wal.path) as f:
        lines = f.read().splitlines()
    assert '"value":20' in lines[1]
    lines[1] = lines[1].replace('"value":20', '"value":21')
    assert json.loads(lines[1])  # still parses: only crc can object
    with open(wal.path, "w") as f:
        f.write("\n".join(lines) + "\n")
    entries, _ = wal.read()
    assert [e["seq"] for e in entries] == [1, 3]
    counters = BUS.counters()
    assert counters.get("test.wal.crc_mismatch") == 1
    assert counters.get("test.wal.corrupt_line") == 1
    # The tail scan skips the mutated record the same way.
    lines[2] = lines[2].replace('"value":30', '"value":31')
    with open(wal.path, "w") as f:
        f.write("\n".join(lines) + "\n")
    assert wal.tail()["seq"] == 1


def test_wal_legacy_lines_without_crc_still_accepted(tmp_path):
    from distributed_ghs_implementation_tpu.utils.wal import JsonlWal

    wal = JsonlWal(str(tmp_path / "log.jsonl"), schema="test-v1",
                   counter_prefix="test.wal")
    with open(wal.path, "w") as f:
        f.write(json.dumps({"schema": "test-v1", "seq": 1}) + "\n")
    wal.append({"seq": 2})
    entries, _ = wal.read()
    assert [e["seq"] for e in entries] == [1, 2]
    assert wal.tail()["seq"] == 2


def test_wal_crc_canonical_roundtrip_floats_and_unicode(tmp_path):
    from distributed_ghs_implementation_tpu.utils.wal import JsonlWal

    wal = JsonlWal(str(tmp_path / "log.jsonl"), schema="test-v1",
                   counter_prefix="test.wal")
    record = {"seq": 1, "f": 0.1 + 0.2, "s": "naïve ☃",
              "nested": {"z": [1.5, None, True]}}
    wal.append(record)
    entries, _ = wal.read()
    assert entries[0]["f"] == record["f"]
    assert entries[0]["s"] == record["s"]
    assert BUS.counters().get("test.wal.crc_mismatch") is None


# ----------------------------------------------------------------------
# Checkpoint recovery integration
# ----------------------------------------------------------------------
def test_checkpoint_resilient_load_skips_rotted_primary(tmp_path):
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        load_checkpoint_resilient,
    )

    path = str(tmp_path / "ck.npz")
    atomic_write_npz(path, {
        "fragment": np.arange(4), "mst_ranks": np.arange(6),
        "level": np.asarray(2),
    })
    atomic_write_npz(path, {
        "fragment": np.arange(4), "mst_ranks": np.arange(6),
        "level": np.asarray(3),
    })
    _flip_one_byte(path)
    state, source, notes = load_checkpoint_resilient(path)
    assert state is not None and source == path + ".bak"
    assert state[2] == 2  # the .bak generation's level
    assert any("IntegrityError" in why for _, why in notes)


def test_stream_snapshot_rot_quarantined_falls_to_bak(tmp_path):
    from distributed_ghs_implementation_tpu.stream.log import UpdateLog

    log = UpdateLog(str(tmp_path), "s1")
    state = {"num_nodes": 4, "u": np.asarray([0, 1]),
             "v": np.asarray([1, 2]), "w": np.asarray([5, 6]),
             "in_tree": np.asarray([True, True])}
    log.snapshot(dict(state), seq=1, digest="d1")
    log.snapshot(dict(state), seq=2, digest="d2")
    _flip_one_byte(log.snap_path)
    loaded, notes = log.load_snapshot()
    assert loaded is not None and loaded["seq"] == 1  # the .bak generation
    assert BUS.counters().get("stream.log.quarantined") == 1
    assert any("quarantined" in why for _, why in notes)
