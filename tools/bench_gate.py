#!/usr/bin/env python
"""Bench regression gate: compare fresh metrics against a committed baseline.

    python tools/bench_gate.py                       # run + compare
    python tools/bench_gate.py --update              # (re)write the baseline
    python tools/bench_gate.py --metrics fresh.json  # compare a saved run

    # gate-load-v1: a load-drill report (tools/load_drill.py) gates its
    # embedded per-class SLO metrics against the committed load baseline
    python tools/bench_gate.py --metrics load_report.json \
        --baseline docs/BENCH_BASELINE_LOAD.json

    # The chaos scenario gates the same way against its own baseline
    # (docs/BENCH_BASELINE_LOAD_CHAOS.json): lost_accepted stays exact-zero
    # while the per-class p99 ceilings encode the degraded-but-bounded
    # envelope. Fleet drill reports (--fleet/--kill-worker) share the
    # report schema and unwrap identically; the kill drill is gated by its
    # own internal checks (restart counts are timing-dependent), not a
    # baseline.

Exit code 0 iff no metric regresses beyond its tolerance. Two metric
classes, told apart by key suffix (plus the KINDS overrides):

* **counts** (``levels``, ``*_messages_sent``, ...): deterministic — the
  solver, the rank order, and the event-queue protocol are all seeded — so
  the tolerance is tight (default 2%) and catches *algorithmic* regressions
  (an extra Borůvka level, a protocol chattiness bug) that wall-clock noise
  would hide. ``mst_weight`` is exact: any change is a correctness failure,
  never a tolerance question (the silent-wrong-MST failure mode from
  PAPER.md is precisely what this line guards).
* **times** (``*_s``) / **throughputs** (``*_per_sec``): machine-dependent —
  gate loosely by default (50%) and loosen further on shared CI
  (``--time-tolerance 5.0`` catches order-of-magnitude cliffs only).

The default run is small and CPU-safe (the gate must run in CI on every
push); ``bench.py --metrics-out`` emits the same schema at TPU bench scale
for gating real hardware runs against ``docs/BASELINE_RUNS.jsonl``-era
numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Tuple

try:
    import _bootstrap  # noqa: F401 — repo-root sys.path setup
except ImportError:  # loaded by file path (importlib in tests): tools/ is
    # not sys.path[0] then, so inline the bootstrap's one job.
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCHEMA = "ghs-bench-metrics-v1"
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs",
    "BENCH_BASELINE.json",
)

#: Metric-kind overrides; everything else is classified by suffix
#: (``*_s`` time, ``*_per_sec`` throughput, default count).
#: ``lost_accepted`` (the ``gate-load-v1`` workload, tools/load_drill.py)
#: is exact: the serving stack losing an accepted query is a correctness
#: failure exactly like a changed MST weight, never a tolerance question.
#: The load workload's per-class ``<cls>_p99_s`` / ``<cls>_goodput_per_sec``
#: keys need no override — the suffixes already gate them as wall-time
#: ceilings and throughput floors; ``<cls>_errors`` / ``<cls>_shed`` gate
#: as counts against a zero baseline, so ANY error or shed fails.
#: ``batch_speedup`` / ``pipeline_speedup`` are wall-clock ratios, so they
#: gate like throughputs (floor), never like deterministic counts. The
#: round-10 latency keys (``cold_first_solve_s``, ``warm_solve_p50_s`` /
#: ``warm_solve_p95_s``, ``solve_p50_s`` / ``solve_p95_s``, ``warmup_s``)
#: need no override — the ``*_s`` suffix already gates them as wall-times
#: (ceiling at ``1 + time_tolerance``); they are listed in the baseline
#: files so a latency regression fails the gate like any other slowdown.
#: Caveat: ``cold_first_solve_s`` is dominated by XLA compile wall time
#: and the sync/pipeline pair by scheduler jitter (docs/BENCH_NOTES.md
#: measures a 5x spread on a 2-core box), so gate those only at CI's
#: loose ``--time-tolerance 5.0``, never at the tight local default.
KINDS = {
    "mst_weight": "exact",
    "protocol_mst_weight": "exact",
    "batch_mst_weight": "exact",
    "batch_speedup": "throughput",
    "pipeline_speedup": "throughput",
    "lost_accepted": "exact",
    # gate-sharded-v1 (bench.py --sharded-lane): residency bookkeeping is
    # deterministic — a warm re-solve that re-staged (or an update that
    # fell off the donated path) is a regression of the resharding-free
    # contract, not jitter.
    "reshard_skipped": "exact",
    "update_donated": "exact",
    # Fleet drill extras: in a NO-kill fleet baseline these are exact
    # zeros (an unplanned failover is a regression, not jitter); kill-drill
    # reports are never baseline-gated, so nonzero values stay ungated.
    "session_resets": "exact",
    "worker_restarts": "exact",
    "requeued": "exact",
    # gate-stream-v1 (tools/load_drill.py --update-heavy): the
    # subscription contract is exact — a notification gap or duplicate, a
    # stream forced to re-sync, or ANY fresh solve while streams are live
    # is a correctness failure, never a tolerance question.
    # gate-tune-v1 (bench.py --tuned): how many buckets the installed
    # TuningRecord resolved is deterministic — a drop means the record
    # went stale or the measured tier stopped being consulted.
    "tune_record_hits": "exact",
    "notify_gaps": "exact",
    "notify_dups": "exact",
    "drain_errors": "exact",
    "stream_resets": "exact",
    "fresh_solves": "exact",
    # gate-fleet-tcp-v1 (bench.py --fleet-tcp): the forwarding scenario is
    # fully deterministic (pre-screened digests, echo workers) — a changed
    # hit/miss count means the router's forwarding decision logic changed,
    # never jitter. router_hop_*_s keys need no override: the _s suffix
    # already gates them as wall-time ceilings.
    "forward_hit": "exact",
    "forward_miss": "exact",
    # Elastic fleet (bench.py --fleet-tcp churn segment and
    # gate-fleet-elastic-v1, tools/load_drill.py --elastic): scale events
    # are policy-determined — cooldown serializes them, the min/max bounds
    # terminate them — so a changed count means the autoscaler's decision
    # logic (or the warm-join/retire machinery) changed, never jitter. A
    # planned retire reading as a death is likewise a logic regression.
    # elastic_join_warm_s / fleet_join_warm_p95_s need no override: the
    # _s suffix gates them as wall-time ceilings.
    "scale_up_events": "exact",
    "scale_down_events": "exact",
    "elastic_scale_up": "exact",
    "elastic_scale_down": "exact",
    "elastic_unplanned_deaths": "exact",
    # gate-fleet-router-v1 (tools/load_drill.py --kill-router): the router
    # survivability contract is exact — ONE deliberate mid-flight router
    # crash, a journal replay that must drain to zero unanswered accepts,
    # every --listen worker re-adopted warm, and zero fresh solves on the
    # re-adopted sessions. A changed count means the journal/replay/
    # re-adoption logic changed, never jitter (router_restart_s gates
    # loosely via its _s suffix; the downtime-window retry counts are
    # deliberately report-only — see the drill).
    "router_crashes": "exact",
    "journal_unanswered": "exact",
    "workers_readopted": "exact",
    # gate-stream-bench-v1 (bench.py --update-stream): the windowed-vs-
    # sequential ratio is a wall-clock pair — gate as a throughput floor.
    "window_speedup": "throughput",
    # gate-stream-sharded-v1 (bench.py --stream-sharded): the fused
    # stream/lane residency bookkeeping is deterministic — every window
    # must migrate device residency (donated scatter or bounded restage),
    # the crash rebuild must re-stage exactly once from the snapshot and
    # replay every WAL window with ZERO fresh solves, and the warm head
    # solves must stay dispatch-only. A changed count means the
    # fused-path logic changed, never jitter.
    "residency_restored": "exact",
    "residency_migrated": "exact",
    "replay_windows": "exact",
    "replay_fresh_solves": "exact",
    # gate-verify-v1 (tools/load_drill.py --corrupt-store) and
    # gate-verify-bench-v1 (bench.py --verify): the corruption drill is
    # fully seeded — K store files rot, M cached results are mutated, N
    # response payloads are corrupted in flight — so every defense
    # counter is exact. wrong_results is THE number this round exists
    # for: a single wrong served answer is the reference's silent-wrong-
    # MST failure reborn, never a tolerance question. quarantined /
    # verify_corrected / payload_rejected exact: a changed count means
    # corruption was missed (or phantom-detected), not jitter.
    # mutation_rejected exact: the certificate's statistical power is a
    # contract. verify_overhead_p50_s needs no override (the _s suffix
    # gates it as a wall-time ceiling).
    "wrong_results": "exact",
    "quarantined": "exact",
    "verify_failed": "exact",
    "verify_corrected": "exact",
    "payload_rejected": "exact",
    "audit_failed": "exact",
    "mutation_rejected": "exact",
    "verify_failed_clean": "exact",
    # gate-analytics-v1 (tools/load_drill.py --kinds-mixed): the analytics
    # front door gates PER KIND — wrong_<kind> is the silent-wrong-answer
    # failure mode reborn in that query class (a wrong components
    # partition or minimax value is exactly as disqualifying as a wrong
    # MST weight), so every one is an exact zero. The served/probe/store
    # counts are deterministic for the seeded deck: a changed count means
    # the per-kind cache keys, the probe derivation rules, or the
    # update-path cache sharing changed — never jitter. <kind>_p50_s
    # latencies need no override (the _s suffix gates them as wall-time
    # ceilings); wrong_results / verify_failed / verify_corrected are
    # already exact above.
    "wrong_mst": "exact",
    "wrong_components": "exact",
    "wrong_k_msf": "exact",
    "wrong_bottleneck": "exact",
    "wrong_path_max": "exact",
    "served_mst": "exact",
    "served_components": "exact",
    "served_k_msf": "exact",
    "served_bottleneck": "exact",
    "served_path_max": "exact",
    "hit_leg_fresh_solves": "exact",
    "probe_hits": "exact",
    "probe_misses": "exact",
    "store_files": "exact",
    "update_streams": "exact",
    "update_mst_hits": "exact",
    "fleet_served": "exact",
    "fleet_wrong_results": "exact",
    # gate-trace-v1 (tools/load_drill.py --trace-dir): the trace-join
    # contract is exact — every rooted trace in the merged multi-process
    # trace must resolve each of its spans to a parent (orphan_spans is a
    # zero-baseline exact), and the number of requests whose trace joins
    # spans from >= 2 processes is deterministic for the seeded echo deck
    # (every accepted request dispatches or probes to a worker). A changed
    # count means context propagation broke on some path — a dropped wire
    # field, a worker not re-establishing context — never jitter.
    "orphan_spans": "exact",
    "traces_joined": "exact",
    # gate-wire-v1 (bench.py --wire): the passthrough split is fully
    # deterministic — seeded deck digests, a deterministic ring, echo
    # workers — so a changed count means the router started (or stopped)
    # decoding edge sections on a dispatch path, or the per-connection
    # capability negotiation changed. Never jitter. wire_speedup is a
    # wall-clock ratio (floor, like batch_speedup); the *_per_sec ingest
    # throughputs need no override — the suffix already floors them.
    "wire_passthrough": "exact",
    "wire_fallback_json": "exact",
    "wire_mixed_passthrough": "exact",
    "wire_mixed_fallback_json": "exact",
    "wire_graphs": "exact",
    "wire_speedup": "throughput",
    # gate-kernel-v1 (tools/profile_levels.py --compare-kernels and
    # bench.py --kernel): the fused-Pallas vs XLA level-kernel ratio is a
    # wall-clock pair — gate as a throughput floor. On hosts where Pallas
    # auto-falls-back (no TPU) the profiler pins it at exactly 1.0, so the
    # gate passes on the XLA path — the fallback-routing contract
    # (docs/KERNELS.md).
    "level_kernel_speedup": "throughput",
}


def metric_kind(name: str) -> str:
    if name in KINDS:
        return KINDS[name]
    if name.endswith("_s"):
        return "time"
    if name.endswith("_per_sec"):
        return "throughput"
    return "count"


def run_gate_bench() -> dict:
    """The gate's own measurement: one small fixed workload per layer.

    Everything here is seeded; only the ``*_s`` entries vary run to run.
    """
    from distributed_ghs_implementation_tpu.graphs.generators import (
        erdos_renyi_graph,
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
    from distributed_ghs_implementation_tpu.protocol.faults import (
        FaultSpec,
        ReliableTransport,
    )
    from distributed_ghs_implementation_tpu.protocol.runner import (
        solve_graph_protocol,
    )
    from distributed_ghs_implementation_tpu.protocol.transport import SimTransport

    metrics: Dict[str, float] = {}

    # Device path: seeded G(n,m) at a size that exercises multiple levels.
    g = gnm_random_graph(4096, 16384, seed=11)
    solve_graph(g)  # warm: compile outside the clock
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        edge_ids, fragment, levels = solve_graph(g)
        times.append(time.perf_counter() - t0)
    metrics["device_solve_s"] = min(times)
    metrics["device_levels"] = int(levels)
    metrics["device_mst_edges"] = int(edge_ids.shape[0])
    metrics["mst_weight"] = int(g.w[edge_ids].sum())

    # Protocol path: the event-queue transport is deterministic, so message
    # counts are exact fingerprints of protocol behavior.
    gp = erdos_renyi_graph(96, 0.08, seed=12)
    transport = SimTransport()
    t0 = time.perf_counter()
    ids_p, _, _ = solve_graph_protocol(gp, transport=transport)
    metrics["protocol_solve_s"] = time.perf_counter() - t0
    metrics["protocol_messages_sent"] = transport.messages_sent
    metrics["protocol_messages_deferred"] = transport.messages_deferred
    metrics["protocol_mst_weight"] = int(gp.w[ids_p].sum())

    # Reliable sublayer under a fixed lossy spec: retransmit/suppression
    # counts are seeded-deterministic too.
    gr = erdos_renyi_graph(40, 0.12, seed=13)
    reliable = ReliableTransport(
        FaultSpec(drop=0.15, duplicate=0.1, reorder=0.2, seed=14)
    )
    solve_graph_protocol(gr, transport=reliable)
    metrics["reliable_messages_sent"] = reliable.messages_sent
    metrics["reliable_retransmits"] = reliable.retransmits
    metrics["reliable_dup_suppressed"] = reliable.dup_suppressed

    # Batch path: K same-bucket small graphs through the lane engine
    # (batch/) vs the sequential dispatch loop — the serving scheduler's
    # miss-coalescing fast path. Weight sum and compile count are
    # deterministic; the graphs/sec pair gates loosely like other
    # wall-clock metrics.
    from distributed_ghs_implementation_tpu.batch.engine import BatchEngine
    from distributed_ghs_implementation_tpu.batch.policy import BatchPolicy

    bgraphs = [gnm_random_graph(128, 480, seed=40 + i) for i in range(16)]
    engine = BatchEngine(policy=BatchPolicy(max_lanes=16))
    for g in bgraphs:
        solve_graph(g)  # warm the sequential path (compile + rank cache)
    batch_results = engine.solve_many(bgraphs)  # warm the lane solver
    seq_times, batch_times = [], []
    for _ in range(3):
        t0 = time.perf_counter()
        for g in bgraphs:
            solve_graph(g)
        seq_times.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        batch_results = engine.solve_many(bgraphs)
        batch_times.append(time.perf_counter() - t0)
    metrics["batch_graphs_per_sec"] = len(bgraphs) / min(batch_times)
    metrics["seq_graphs_per_sec"] = len(bgraphs) / min(seq_times)
    metrics["batch_speedup"] = min(seq_times) / min(batch_times)
    metrics["batch_mst_weight"] = int(
        sum(r.total_weight for r in batch_results)
    )

    return {
        "schema": SCHEMA,
        "config": {
            "workload": "gate-small-v2",
            "device_graph": "gnm(4096,16384,seed=11)",
            "protocol_graph": "er(96,0.08,seed=12)",
            "reliable_graph": "er(40,0.12,seed=13)+drop0.15dup0.1re0.2seed14",
            "batch_graphs": "gnm(128,480,seeds 40..55)x16lanes",
        },
        "metrics": metrics,
    }


def compare(
    baseline: dict,
    fresh: dict,
    *,
    time_tolerance: float = 0.5,
    count_tolerance: float = 0.02,
) -> Tuple[bool, List[str]]:
    """Per-metric verdicts; returns ``(ok, report_lines)``.

    A *regression* is: slower than ``(1 + time_tolerance) x`` baseline,
    throughput below ``1 / (1 + time_tolerance) x`` (the multiplicative
    mirror of the time ceiling — an additive ``1 - tolerance`` floor goes
    negative past tolerance 1.0 and gates nothing, exactly at the loose
    settings CI uses), a count above ``(1 + count_tolerance) x``, or any
    change at all to an exact metric. Improvements never fail the gate
    (they're reported, so a suspicious 10x "improvement" is still
    visible).
    """
    lines: List[str] = []
    ok = True
    base_cfg = baseline.get("config", {})
    fresh_cfg = fresh.get("config", {})
    if base_cfg and fresh_cfg and base_cfg != fresh_cfg:
        lines.append(
            f"FAIL config mismatch: baseline {base_cfg} vs fresh {fresh_cfg}"
        )
        return False, lines
    base_metrics = baseline.get("metrics", {})
    fresh_metrics = fresh.get("metrics", {})
    for name in sorted(base_metrics):
        base = base_metrics[name]
        if name not in fresh_metrics:
            lines.append(f"FAIL {name}: missing from fresh metrics")
            ok = False
            continue
        value = fresh_metrics[name]
        kind = metric_kind(name)
        ratio = value / base if base else float("inf" if value else 1)
        if kind == "exact":
            good = value == base
            verdict = "ok" if good else "FAIL"
            lines.append(f"{verdict} {name}: {value} vs {base} (exact)")
        elif kind == "time":
            good = ratio <= 1 + time_tolerance
            verdict = "ok" if good else "FAIL"
            lines.append(
                f"{verdict} {name}: {value:.4f}s vs {base:.4f}s "
                f"({ratio:.2f}x, limit {1 + time_tolerance:.2f}x)"
            )
        elif kind == "throughput":
            floor = 1 / (1 + time_tolerance)
            good = ratio >= floor
            verdict = "ok" if good else "FAIL"
            lines.append(
                f"{verdict} {name}: {value:.1f} vs {base:.1f} "
                f"({ratio:.2f}x, floor {floor:.2f}x)"
            )
        else:  # count
            good = ratio <= 1 + count_tolerance
            verdict = "ok" if good else "FAIL"
            lines.append(
                f"{verdict} {name}: {value} vs {base} "
                f"({ratio:.3f}x, limit {1 + count_tolerance:.3f}x)"
            )
        ok = ok and good
    for name in sorted(set(fresh_metrics) - set(base_metrics)):
        lines.append(f"note {name}: new metric (not in baseline), ungated")
    return ok, lines


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="bench_gate", description=__doc__)
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument(
        "--metrics",
        help="compare this saved metrics JSON instead of running the bench",
    )
    p.add_argument("--out", help="write the fresh metrics JSON here")
    p.add_argument(
        "--update",
        action="store_true",
        help="run the bench and (re)write the baseline instead of comparing",
    )
    p.add_argument(
        "--update-baseline",
        metavar="PATH",
        help="(re)write PATH from this run's metrics and exit — the "
        "one-flag form of '--update --baseline PATH', for refreshing a "
        "workload-specific baseline (e.g. docs/BENCH_BASELINE_VERIFY.json "
        "from a --metrics report) without touching the default",
    )
    p.add_argument("--time-tolerance", type=float, default=0.5,
                   help="allowed fractional wall-time regression (0.5 = +50%%)")
    p.add_argument("--count-tolerance", type=float, default=0.02,
                   help="allowed fractional count regression (0.02 = +2%%)")
    args = p.parse_args(argv)
    if args.update_baseline:
        args.baseline = args.update_baseline
        args.update = True

    if args.metrics:
        with open(args.metrics) as f:
            fresh = json.load(f)
        if fresh.get("schema") == "ghs-load-report-v1":
            # A load-drill report embeds its gate metrics (the
            # ``gate-load-v1`` workload, obs.slo.gate_metrics): per-class
            # p99 ceilings, goodput floors, error/shed counts,
            # lost_accepted. Gate on those directly.
            fresh = fresh.get("gate_metrics", {})
        elif fresh.get("schema") == "ghs-level-profile-v1":
            # A level-profile receipt (tools/profile_levels.py --json, the
            # gate-kernel-v1 workload) embeds its gate metrics the same
            # way: throughput + level_kernel_speedup + exact mst_weight.
            fresh = fresh.get("gate_metrics", {})
    else:
        fresh = run_gate_bench()
    if fresh.get("schema") != SCHEMA:
        print(f"bench_gate: bad metrics schema {fresh.get('schema')!r}",
              file=sys.stderr)
        return 2

    if args.out:
        with open(args.out, "w") as f:
            json.dump(fresh, f, indent=2)
            f.write("\n")

    if args.update:
        with open(args.baseline, "w") as f:
            json.dump(fresh, f, indent=2)
            f.write("\n")
        print(f"baseline written: {args.baseline}")
        return 0

    if not os.path.exists(args.baseline):
        print(
            f"bench_gate: no baseline at {args.baseline} "
            "(run with --update to create one)",
            file=sys.stderr,
        )
        return 2
    with open(args.baseline) as f:
        baseline = json.load(f)

    ok, lines = compare(
        baseline,
        fresh,
        time_tolerance=args.time_tolerance,
        count_tolerance=args.count_tolerance,
    )
    for line in lines:
        print(line)
    print(f"bench gate: {'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
