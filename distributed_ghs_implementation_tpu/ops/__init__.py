"""Device kernels: segment reductions and union-find primitives.

These are the TPU-native replacements for the reference's per-message handlers
(``/root/reference/ghs_implementation.py:118-413``): the TEST/ACCEPT/REJECT +
REPORT minimum-outgoing-edge search collapses into segment minima
(``segment_ops``), and CONNECT/INITIATE/CHANGEROOT fragment merging collapses
into hook-and-compress union-find (``union_find``).
"""

from distributed_ghs_implementation_tpu.ops.segment_ops import (
    fragment_moe,
    segment_min,
)
from distributed_ghs_implementation_tpu.ops.union_find import (
    break_symmetric_hooks,
    hook_and_compress,
    pointer_jump,
)

__all__ = [
    "break_symmetric_hooks",
    "fragment_moe",
    "hook_and_compress",
    "pointer_jump",
    "segment_min",
]
