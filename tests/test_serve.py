"""Serving layer: content-addressed store, single-flight scheduler, JSONL
service, digest identity, and the warm-path acceptance guarantees (a repeat
solve touches no solver span; a single-edge insert on a cached 10k-node
graph never re-solves)."""

import json
import os
import threading

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import gnm_random_graph
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.serve.scheduler import SolveScheduler
from distributed_ghs_implementation_tpu.serve.service import MSTService, serve_loop
from distributed_ghs_implementation_tpu.serve.store import (
    ResultStore,
    solve_cache_key,
)


@pytest.fixture(autouse=True)
def _clean_global_bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.enable()
    BUS.clear()


def _edges(g):
    return [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]


# ----------------------------------------------------------------------
# Digest (satellite: the ONE identity for cache + checkpoints)
# ----------------------------------------------------------------------
def test_digest_is_content_addressed_and_order_invariant():
    e = [(0, 1, 3), (1, 2, 5), (0, 2, 4)]
    a = Graph.from_edges(3, e)
    b = Graph.from_edges(3, list(reversed(e)))  # same set, different order
    c = Graph.from_edges(3, [(1, 0, 3), (2, 1, 5), (2, 0, 4)])  # flipped ends
    assert a.digest() == b.digest() == c.digest()
    assert a.digest() != Graph.from_edges(3, [(0, 1, 3), (1, 2, 5)]).digest()
    assert a.digest() != Graph.from_edges(4, e).digest()  # num_nodes counts
    # int 5 and float 5.0 weights are different graphs.
    f = Graph.from_edges(3, [(0, 1, 3.5), (1, 2, 5.0), (0, 2, 4.0)])
    assert a.digest() != f.digest()


def test_checkpoint_fingerprint_derives_from_digest():
    from distributed_ghs_implementation_tpu.utils.checkpoint import (
        graph_fingerprint,
    )

    g = gnm_random_graph(32, 64, seed=3)
    fp = graph_fingerprint(g)
    assert fp.dtype == np.int64 and fp.shape == (6,)
    assert fp[0] == g.num_nodes and fp[1] == g.num_edges
    expect = np.frombuffer(bytes.fromhex(g.digest()), dtype=np.int64)
    assert np.array_equal(fp[2:], expect)


# ----------------------------------------------------------------------
# Store
# ----------------------------------------------------------------------
def test_store_lru_eviction_and_counters():
    store = ResultStore(capacity=2)
    graphs = [gnm_random_graph(24, 48, seed=s) for s in range(3)]
    results = [minimum_spanning_forest(g) for g in graphs]
    keys = [solve_cache_key(g) for g in graphs]
    for key, result in zip(keys, results):
        store.put(key, result)
    assert len(store) == 2
    assert store.get(keys[0]) is None  # oldest evicted
    assert store.get(keys[2]) is results[2]
    counters = BUS.counters()
    assert counters["serve.store.evict"] == 1
    assert counters["serve.store.miss"] == 1
    assert counters["serve.store.hit"] == 1


def test_store_disk_layer_round_trip_and_digest_guard(tmp_path):
    g = gnm_random_graph(40, 120, seed=5)
    result = minimum_spanning_forest(g)
    key = solve_cache_key(g)
    ResultStore(capacity=4, disk_dir=str(tmp_path)).put(key, result)
    # A cold process (fresh store, same dir) serves from disk.
    cold = ResultStore(capacity=4, disk_dir=str(tmp_path))
    got = cold.get(key, graph=g)
    assert got is not None
    assert got.total_weight == result.total_weight
    assert np.array_equal(got.edge_ids, result.edge_ids)
    assert BUS.counters()["serve.store.disk_hit"] == 1
    # A different graph presented under the same key is refused.
    other = gnm_random_graph(40, 120, seed=6)
    assert ResultStore(capacity=4, disk_dir=str(tmp_path)).get(
        key, graph=other
    ) is None


def test_store_disk_write_is_crash_consistent(tmp_path):
    """A torn write (serve.store.save fault) must not poison the entry: the
    .bak generation still serves."""
    from distributed_ghs_implementation_tpu.utils.resilience import (
        FAULTS,
        InjectedFault,
    )

    g = gnm_random_graph(30, 90, seed=7)
    result = minimum_spanning_forest(g)
    key = solve_cache_key(g)
    store = ResultStore(capacity=4, disk_dir=str(tmp_path))
    store.put(key, result)
    with FAULTS.inject("serve.store.save", kind="torn"):
        with pytest.raises(InjectedFault):
            store._disk_put(key, result)  # the raw writer does raise...
    cold = ResultStore(capacity=4, disk_dir=str(tmp_path))
    got = cold.get(key, graph=g)
    assert got is not None and got.total_weight == result.total_weight
    # ...but put() is write-behind: a torn write never fails the caller.
    with FAULTS.inject("serve.store.save", kind="torn"):
        store.put(key, result)
    assert BUS.counters()["serve.store.disk_write_failed"] == 1


# ----------------------------------------------------------------------
# Scheduler
# ----------------------------------------------------------------------
def test_scheduler_single_flight_coalesces_duplicates(monkeypatch):
    """Deterministic single-flight: the leader's solve blocks on an event
    until every duplicate request has joined the flight, so all of them MUST
    coalesce (no timing luck involved)."""
    import time as _time

    from distributed_ghs_implementation_tpu.serve import scheduler as sched_mod

    g = gnm_random_graph(60, 180, seed=9)
    gate = threading.Event()
    real = sched_mod.minimum_spanning_forest

    def blocking_solve(graph, **kwargs):
        assert gate.wait(timeout=30)
        return real(graph, **kwargs)

    monkeypatch.setattr(sched_mod, "minimum_spanning_forest", blocking_solve)
    sched = SolveScheduler(max_concurrent=2)
    outcomes = []

    def worker():
        outcomes.append(sched.solve(g))

    threads = [threading.Thread(target=worker) for _ in range(5)]
    for t in threads:
        t.start()
    deadline = _time.monotonic() + 30
    while (
        BUS.counters().get("serve.scheduler.coalesced", 0) < 4
        and _time.monotonic() < deadline
    ):
        _time.sleep(0.01)
    gate.set()
    for t in threads:
        t.join()
    sources = [s for _, s in outcomes]
    assert sources.count("solved") == 1  # exactly one kernel dispatch
    assert sources.count("coalesced") == 4
    weights = {r.total_weight for r, _ in outcomes}
    assert len(weights) == 1
    assert BUS.counters()["serve.scheduler.coalesced"] == 4
    # And afterwards it's a plain cache hit.
    assert sched.solve(g)[1] == "cache"


def test_scheduler_batch_dedups_by_content():
    sched = SolveScheduler()
    g1 = gnm_random_graph(40, 100, seed=1)
    g1_again = Graph.from_edges(40, list(reversed(g1.edge_triples())))
    g2 = gnm_random_graph(40, 100, seed=2)
    out = sched.solve_batch([g1, g1_again, g2, g1])
    assert [s for _, s in out] == ["solved", "coalesced", "solved", "coalesced"]
    assert out[0][0].total_weight == out[1][0].total_weight


def test_scheduler_miss_runs_supervised():
    """Cache misses route through the resilience supervisor: a transient
    injected fault retries instead of failing the request."""
    from distributed_ghs_implementation_tpu.utils.resilience import FAULTS

    g = gnm_random_graph(60, 180, seed=11)
    sched = SolveScheduler()
    with FAULTS.inject("resilience.attempt.device", times=1):
        result, source = sched.solve(g)
    assert source == "solved"
    assert result.backend.startswith("supervised/")
    attempts = [
        rec[6]["outcome"] for rec in BUS.events()
        if rec[1] == "resilience.attempt"
    ]
    assert attempts == ["transient", "ok"]


# ----------------------------------------------------------------------
# Service + JSONL protocol
# ----------------------------------------------------------------------
def test_service_solve_update_stats_round_trip():
    svc = MSTService()
    g = gnm_random_graph(80, 240, seed=13)
    first = svc.handle({"op": "solve", "num_nodes": 80, "edges": _edges(g),
                        "edges_out": True})
    assert first["ok"] and first["source"] == "solved"
    assert len(first["mst_edges"]) == first["num_edges_in_mst"]
    repeat = svc.handle({"op": "solve", "num_nodes": 80, "edges": _edges(g)})
    assert repeat["cached"] and repeat["source"] == "cache"
    assert repeat["total_weight"] == first["total_weight"]

    update = svc.handle({
        "op": "update", "digest": first["digest"],
        "updates": [{"kind": "insert", "u": 0, "v": 79, "w": 1}],
    })
    assert update["ok"] and update["mode"] == "incremental"
    assert update["digest"] != first["digest"]
    # The updated graph is itself cached now.
    again = svc.handle({
        "op": "update", "digest": update["digest"],
        "updates": [{"kind": "delete", "u": 0, "v": 79}],
    })
    assert again["ok"] and again["total_weight"] == first["total_weight"]

    stats = svc.handle({"op": "stats"})
    assert stats["ok"]
    assert stats["counters"]["serve.store.hit"] >= 1
    assert stats["sessions"] >= 1


def test_service_error_responses_keep_loop_alive():
    svc = MSTService()
    bad = svc.handle({"op": "nope"})
    assert not bad["ok"] and "unknown op" in bad["error"]
    missing = svc.handle({"op": "update", "digest": "beef", "updates": []})
    assert not missing["ok"] and "no session" in missing["error"]
    no_graph = svc.handle({"op": "solve"})
    assert not no_graph["ok"]
    assert BUS.counters()["serve.errors"] == 3


def test_update_midbatch_failure_evicts_session(monkeypatch):
    """An apply that dies after mutation began leaves state no client saw:
    the session must be dropped. A pre-mutation validation error must NOT
    drop it."""
    from distributed_ghs_implementation_tpu.serve.dynamic import DynamicMST

    svc = MSTService()
    g = gnm_random_graph(20, 60, seed=33)
    first = svc.handle({"op": "solve", "num_nodes": 20, "edges": _edges(g)})
    digest = first["digest"]
    # Solves park a lightweight seed; the first update materializes it.
    assert not isinstance(svc._sessions[digest], DynamicMST)

    # Validation error: session survives (and is now materialized).
    bad = svc.handle({"op": "update", "digest": digest,
                      "updates": [{"kind": "frobnicate", "u": 0, "v": 1}]})
    assert not bad["ok"]
    assert digest in svc._sessions
    session = svc._sessions[digest]
    assert isinstance(session, DynamicMST)

    calls = []
    orig = session._apply_one

    def boom(upd):
        if calls:
            raise RuntimeError("boom mid-batch")
        calls.append(1)
        orig(upd)

    monkeypatch.setattr(session, "_apply_one", boom)
    failed = svc.handle({"op": "update", "digest": digest, "updates": [
        {"kind": "insert", "u": 0, "v": 10, "w": 1},
        {"kind": "insert", "u": 1, "v": 11, "w": 1},
    ]})
    assert not failed["ok"]
    assert digest not in svc._sessions  # poisoned mid-batch: evicted
    assert BUS.counters()["serve.sessions.poisoned"] == 1


def test_update_result_cached_under_session_backend():
    """A client pinned to a non-default backend must hit the cache for the
    graph an update produced (the entry is keyed by the SESSION's backend,
    not the service default)."""
    svc = MSTService(backend="device")
    edges = [[0, 1, 5], [1, 2, 6], [2, 3, 7]]
    first = svc.handle({"op": "solve", "num_nodes": 4, "edges": edges,
                        "backend": "sharded"})
    assert first["ok"]
    update = svc.handle({"op": "update", "digest": first["digest"],
                         "updates": [{"kind": "insert", "u": 0, "v": 3, "w": 1}]})
    assert update["ok"]
    follow = svc.handle({"op": "solve", "num_nodes": 4,
                         "edges": edges + [[0, 3, 1]], "backend": "sharded"})
    assert follow["source"] == "cache"
    assert follow["total_weight"] == update["total_weight"]


def test_serve_loop_jsonl_protocol(tmp_path):
    import io as _io

    g = gnm_random_graph(30, 90, seed=15)
    lines = [
        json.dumps({"op": "solve", "num_nodes": 30, "edges": _edges(g)}),
        "this is not json",
        json.dumps({"op": "solve", "num_nodes": 30, "edges": _edges(g)}),
        json.dumps({"op": "stats"}),
        json.dumps({"op": "shutdown"}),
        json.dumps({"op": "solve", "num_nodes": 30, "edges": _edges(g)}),
    ]
    out = _io.StringIO()
    rc = serve_loop(_io.StringIO("\n".join(lines) + "\n"), out)
    assert rc == 0
    responses = [json.loads(ln) for ln in out.getvalue().splitlines()]
    # The post-shutdown line was never processed.
    assert len(responses) == 5
    assert responses[0]["ok"] and responses[0]["source"] == "solved"
    assert not responses[1]["ok"] and "bad JSON" in responses[1]["error"]
    assert responses[2]["source"] == "cache"
    assert responses[3]["op"] == "stats"
    assert responses[4] == {"ok": True, "op": "shutdown"}


def test_service_graph_path_solve(tmp_path):
    from distributed_ghs_implementation_tpu.graphs import io as gio

    g = gnm_random_graph(50, 150, seed=21)
    path = gio.write_npz(g, str(tmp_path / "g.npz"))
    svc = MSTService()
    first = svc.handle({"op": "solve", "graph_path": path})
    assert first["ok"]
    inline = svc.handle({"op": "solve", "num_nodes": 50, "edges": _edges(g)})
    assert inline["source"] == "cache"  # same content, same key


# ----------------------------------------------------------------------
# Acceptance: the warm-path proof
# ----------------------------------------------------------------------
def test_warm_path_repeat_solve_records_zero_solver_spans():
    svc = MSTService()
    g = gnm_random_graph(500, 2000, seed=23)
    first = svc.handle({"op": "solve", "num_nodes": 500, "edges": _edges(g)})
    assert first["ok"]
    mark = BUS.mark()
    repeat = svc.handle({"op": "solve", "num_nodes": 500, "edges": _edges(g)})
    assert repeat["cached"]
    warm_names = [rec[1] for rec in BUS.events_since(mark)]
    assert not [n for n in warm_names if n.startswith("solver.")]
    assert not [n for n in warm_names if n.startswith("resilience.")]
    assert "serve.request" in warm_names


def test_single_edge_insert_on_cached_10k_graph_is_incremental():
    """The acceptance scenario: one insert on a cached 10k-node graph goes
    through serve/dynamic.py — no full re-solve (bus counters + zero solver
    spans) — and the weight matches networkx exactly."""
    import networkx as nx

    n = 10_000
    g = gnm_random_graph(n, 30_000, seed=24)
    svc = MSTService()
    first = svc.handle({"op": "solve", "num_nodes": n, "edges": _edges(g)})
    assert first["ok"]

    mark = BUS.mark()
    update = svc.handle({
        "op": "update", "digest": first["digest"],
        "updates": [{"kind": "insert", "u": 17, "v": 4242, "w": 1}],
    })
    assert update["ok"] and update["mode"] == "incremental"
    counters = BUS.counters()
    assert counters["serve.dynamic.incremental"] == 1
    assert counters.get("serve.dynamic.resolve", 0) == 0
    update_names = [rec[1] for rec in BUS.events_since(mark)]
    assert not [x for x in update_names if x.startswith("solver.")]

    nxg = nx.Graph()
    nxg.add_nodes_from(range(n))
    for a, b, c in zip(g.u, g.v, g.w):
        nxg.add_edge(int(a), int(b), weight=int(c))
    w17 = nxg[17][4242]["weight"] if nxg.has_edge(17, 4242) else None
    nxg.add_edge(17, 4242, weight=1 if w17 is None else min(1, w17))
    expect = nx.minimum_spanning_tree(nxg).size(weight="weight")
    assert float(update["total_weight"]) == float(expect)


# ----------------------------------------------------------------------
# Satellites: run --metrics-out, serve CLI file input
# ----------------------------------------------------------------------
def test_run_metrics_out_emits_bench_gate_schema(tmp_path):
    import sys as _sys

    _sys.path.insert(0, "tools")
    import bench_gate

    from distributed_ghs_implementation_tpu.cli import main

    gdir = str(tmp_path / "g")
    assert main(["generate", "--kind", "gnm", "--nodes", "64", "--edges",
                 "256", "--seed", "2", "--output-dir", gdir, "--npz"]) == 0
    metrics = str(tmp_path / "metrics.json")
    npz = f"{gdir}/graph.npz"
    assert main(["run", "--graph-dir", npz, "--metrics-out", metrics]) == 0
    with open(metrics) as f:
        doc = json.load(f)
    assert doc["schema"] == "ghs-bench-metrics-v1"
    assert {"solve_s", "levels", "mst_weight", "mst_edges"} <= set(doc["metrics"])
    # The file is self-comparable through the gate (identical run passes).
    assert bench_gate.main(["--baseline", metrics, "--metrics", metrics]) == 0


def test_serve_cli_input_file(tmp_path, capsys):
    from distributed_ghs_implementation_tpu.cli import main

    g = gnm_random_graph(20, 60, seed=31)
    req = str(tmp_path / "req.jsonl")
    with open(req, "w") as f:
        f.write(json.dumps(
            {"op": "solve", "num_nodes": 20, "edges": _edges(g)}) + "\n")
        f.write(json.dumps({"op": "shutdown"}) + "\n")
    assert main(["serve", "--input", req]) == 0
    out = capsys.readouterr().out
    responses = [json.loads(ln) for ln in out.splitlines()]
    assert responses[0]["ok"] and responses[-1]["op"] == "shutdown"


# ----------------------------------------------------------------------
# Satellite: advisory flock on the shared disk store's write path
# ----------------------------------------------------------------------
def test_store_flock_timeout_is_best_effort(tmp_path):
    import fcntl

    from distributed_ghs_implementation_tpu.serve.store import (
        _disk_path,
        _flocked,
    )

    disk = str(tmp_path / "store")
    store = ResultStore(capacity=4, disk_dir=disk)
    g = gnm_random_graph(24, 48, seed=7)
    result = minimum_spanning_forest(g)
    key = solve_cache_key(g)
    store.put(key, result)  # creates the entry + its .lock file
    path = _disk_path(disk, key)

    # Hold the lock as "another worker"; a writer must time out...
    fd = os.open(path + ".lock", os.O_CREAT | os.O_RDWR)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        with pytest.raises(TimeoutError):
            with _flocked(path, timeout_s=0.05):
                pass
        assert BUS.counters()["serve.store.lock_timeout"] >= 1
        # ...and put() treats that as a best-effort miss, never a failure.
        store.put(key, result)
        assert BUS.counters()["serve.store.disk_write_failed"] == 1
    finally:
        os.close(fd)
    # Lock released: writes flow again and the entry stays readable.
    store.put(key, result)
    fresh = ResultStore(capacity=4, disk_dir=disk)
    assert fresh.get(key, graph=g) is not None


def test_store_concurrent_processes_hammer_same_digest(tmp_path):
    """Two real processes publishing the same digest to one disk_dir must
    interleave cleanly: no torn primary, no lost .bak generation, entry
    always readable afterward."""
    import subprocess
    import sys as _sys
    import zipfile

    disk = str(tmp_path / "shared")
    child = (
        "import sys\n"
        "from distributed_ghs_implementation_tpu.api import "
        "minimum_spanning_forest\n"
        "from distributed_ghs_implementation_tpu.graphs.generators import "
        "gnm_random_graph\n"
        "from distributed_ghs_implementation_tpu.serve.store import "
        "ResultStore, solve_cache_key\n"
        "g = gnm_random_graph(24, 48, seed=11)\n"
        "res = minimum_spanning_forest(g)\n"
        "store = ResultStore(capacity=4, disk_dir=sys.argv[1])\n"
        "key = solve_cache_key(g)\n"
        "for _ in range(40):\n"
        "    store.put(key, res)\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}
    procs = [
        subprocess.Popen([_sys.executable, "-c", child, disk], env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for _ in range(2)
    ]
    for p in procs:
        _, err = p.communicate(timeout=180)
        assert p.returncode == 0, err.decode()
    g = gnm_random_graph(24, 48, seed=11)
    key = solve_cache_key(g)
    from distributed_ghs_implementation_tpu.serve.store import _disk_path

    path = _disk_path(disk, key)
    assert zipfile.is_zipfile(path)  # the published generation is whole
    if os.path.exists(path + ".bak"):
        assert zipfile.is_zipfile(path + ".bak")
    store = ResultStore(capacity=4, disk_dir=disk)
    got = store.get(key, graph=g)
    assert got is not None
    expect = minimum_spanning_forest(g)
    assert got.total_weight == expect.total_weight


# ----------------------------------------------------------------------
# Satellite: graceful drain of the single-process serve loop
# ----------------------------------------------------------------------
def test_serve_loop_sigterm_idle_exits_clean(tmp_path):
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}
    proc = subprocess.Popen(
        [_sys.executable, "-m", "distributed_ghs_implementation_tpu",
         "serve", "--no-compile-cache"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, env=env, text=True,
    )
    try:
        g = gnm_random_graph(20, 60, seed=31)
        proc.stdin.write(json.dumps(
            {"op": "solve", "num_nodes": 20, "edges": _edges(g)}) + "\n")
        proc.stdin.flush()
        assert json.loads(proc.stdout.readline())["ok"]  # loop is live
        import signal as _signal

        proc.send_signal(_signal.SIGTERM)  # idle: drains immediately
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()


def test_serve_loop_sigterm_mid_solve_flushes_response(tmp_path):
    """A SIGTERM landing while a request is being solved must let the
    solve finish and flush its response before exiting 0 — previously the
    default handler killed the process mid-line and the accepted request
    was lost."""
    import subprocess
    import sys as _sys
    import time as _time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}
    proc = subprocess.Popen(
        [_sys.executable, "-m", "distributed_ghs_implementation_tpu",
         "serve", "--no-compile-cache"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, env=env, text=True,
    )
    try:
        small = gnm_random_graph(20, 60, seed=31)
        proc.stdin.write(json.dumps(
            {"op": "solve", "num_nodes": 20, "edges": _edges(small)}) + "\n")
        proc.stdin.flush()
        assert json.loads(proc.stdout.readline())["ok"]  # loop is live
        # An uncached shape: the solve pays a compile, giving the signal a
        # wide window to land mid-request.
        big = gnm_random_graph(3000, 12000, seed=5)
        proc.stdin.write(json.dumps(
            {"op": "solve", "num_nodes": 3000, "edges": _edges(big)}) + "\n")
        proc.stdin.flush()
        _time.sleep(0.5)
        import signal as _signal

        proc.send_signal(_signal.SIGTERM)
        line = proc.stdout.readline()  # the accepted request's response
        assert line, "accepted request lost on SIGTERM"
        assert json.loads(line)["ok"]
        assert proc.wait(timeout=120) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
