"""BASELINE config 5 stand-in: high-diameter road-network solve, end-to-end.

Stage A (1M nodes): synthesize a 1024x1024 road grid, write it as a DIMACS
.gr file, read it back through the native parser, solve on the chip, verify
against the SciPy oracle — the full file-to-verified-MST path a USA-road user
would run. Stage B (USA-road scale): 4096x4096 grid (16.8M nodes, diameter
~8k >> log n = 24) solved from arrays and verified. Prints a JSON summary.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import road_grid_graph
from distributed_ghs_implementation_tpu.graphs.io import write_dimacs
from distributed_ghs_implementation_tpu.graphs.native import read_dimacs_native
from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight

out = {}

# ---- Stage A: 1M-node grid through the DIMACS file path.
t0 = time.perf_counter()
g = road_grid_graph(1024, 1024, seed=5)
t_gen = time.perf_counter() - t0
path = "/tmp/road_1m.gr"
t0 = time.perf_counter()
write_dimacs(g, path, comment="synthetic 1024x1024 road grid")
t_write = time.perf_counter() - t0
t0 = time.perf_counter()
u, v, w, n = read_dimacs_native(path)
g2 = Graph.from_arrays(n, u, v, w)
t_read = time.perf_counter() - t0
assert np.array_equal(g2.u, g.u) and np.array_equal(g2.w, g.w)
t0 = time.perf_counter()
ids, frag, lv = solve_graph(g2, strategy="rank")
t_solve1 = time.perf_counter() - t0  # includes compile
t0 = time.perf_counter()
ids, frag, lv = solve_graph(g2, strategy="rank")
t_solve = time.perf_counter() - t0
weight = float(g2.w[ids].sum())
t0 = time.perf_counter()
expect = scipy_mst_weight(g2)
t_oracle = time.perf_counter() - t0
ok = abs(weight - expect) < 1e-6
out["dimacs_1m"] = dict(
    nodes=g2.num_nodes, edges=g2.num_edges, levels=int(lv),
    file_mb=round(os.path.getsize(path) / 1e6, 1),
    gen_s=round(t_gen, 2), write_s=round(t_write, 2), read_s=round(t_read, 2),
    solve_first_s=round(t_solve1, 2), solve_s=round(t_solve, 3),
    oracle_s=round(t_oracle, 1), weight=weight, verified=ok,
)
print(json.dumps(out["dimacs_1m"]), file=sys.stderr, flush=True)
assert ok

# ---- Stage B: USA-road scale (16.8M nodes, diameter ~8k).
t0 = time.perf_counter()
g = road_grid_graph(4096, 4096, seed=6)
t_gen = time.perf_counter() - t0
t0 = time.perf_counter()
ids, frag, lv = solve_graph(g, strategy="rank")
t_solve1 = time.perf_counter() - t0
t0 = time.perf_counter()
ids, frag, lv = solve_graph(g, strategy="rank")
t_solve = time.perf_counter() - t0
weight = float(g.w[ids].sum())
t0 = time.perf_counter()
expect = scipy_mst_weight(g)
t_oracle = time.perf_counter() - t0
ok = abs(weight - expect) < 1e-6
out["grid_16m"] = dict(
    nodes=g.num_nodes, edges=g.num_edges, levels=int(lv),
    gen_s=round(t_gen, 2), solve_first_s=round(t_solve1, 2),
    solve_s=round(t_solve, 3), edges_per_s=round(g.num_edges / t_solve / 1e6, 2),
    oracle_s=round(t_oracle, 1), weight=weight, verified=ok,
)
print(json.dumps(out["grid_16m"]), file=sys.stderr, flush=True)
assert ok
print(json.dumps(out))
