"""Durable sharded streaming (the stream/ <-> parallel/lane.py fusion).

Four contracts in code:

* **Pinning** — a resident graph pinned by an open stream session is not
  LRU-evictable, even when eviction pressure lands DURING a window's
  apply; pins re-key along the digest chain with ``refresh_resident``.
* **Mesh maintenance** — a committed window on an oversize stream
  migrates device residency through the donated padded-slot scatter, and
  a window that degrades to a full re-solve migrates FIRST
  (``pre_resolve``) so the mesh solve is dispatch-only.
* **Crash-safe residency** — a restarted process rebuilds both the
  forest AND the device-resident state from snapshot + WAL replay with
  zero fresh solves (the round-14 replay-without-solving test, now on
  the mesh), edge-exact against a fresh oracle solve.
* **Verification** — post-window sharded heads ride the async NumPy
  certify engine under the standard off|sample|full policy.
"""

import numpy as np
import pytest

from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import (
    gnm_random_graph,
)
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.parallel.lane import ShardedLane
from distributed_ghs_implementation_tpu.stream.session import StreamManager
from distributed_ghs_implementation_tpu.stream.window import (
    random_update_stream,
)

# Oversize by NODE bucket (matches tests/test_lane.py): routes like a
# billion-edge graph — past the lane-engine admission ceiling, onto the
# mesh — while solving in test time.
OVERSIZE_NODES = 70_000
OVERSIZE_EDGES = 3_000


def _oversize_graph(seed):
    return gnm_random_graph(OVERSIZE_NODES, OVERSIZE_EDGES, seed=seed)


def _edges(g):
    return [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]


def _window(rng, seed_graph, size=4):
    return [
        u.__dict__
        for u in random_update_stream(
            rng, seed_graph, size,
            kinds=("insert", "insert", "delete", "reweight"), max_w=200,
        )
    ]


@pytest.fixture(autouse=True)
def _bus():
    BUS.enable()
    BUS.clear()
    yield
    BUS.clear()


def _stage_spans():
    return sum(1 for e in BUS.events() if e[1] == "lane.stage")


# ----------------------------------------------------------------------
# Satellite: pin/unpin on the lane LRU
# ----------------------------------------------------------------------
def test_pin_blocks_eviction_until_unpin():
    lane = ShardedLane(capacity=2)
    graphs = [gnm_random_graph(200, 600, seed=s) for s in range(4)]
    lane.solve(graphs[0])
    pinned = graphs[0].digest()
    assert lane.pin(pinned)  # resident at pin time
    lane.solve(graphs[1])
    lane.solve(graphs[2])  # pressure: would evict graphs[0] unpinned
    assert pinned in lane.resident_digests()
    assert graphs[1].digest() not in lane.resident_digests()
    lane.unpin(pinned)
    lane.solve(graphs[3])  # now the oldest unpinned entry IS graphs[0]
    assert pinned not in lane.resident_digests()


def test_all_pinned_runs_over_capacity():
    lane = ShardedLane(capacity=1)
    g1, g2 = (gnm_random_graph(200, 600, seed=s) for s in (10, 11))
    lane.solve(g1)
    lane.solve(g2)
    lane.pin(g1.digest())  # g1 was evicted: pin survives non-residency
    lane.pin(g2.digest())
    lane.solve(g1)  # restages; now both entries resident and pinned
    assert set(lane.resident_digests()) >= {g1.digest(), g2.digest()}
    assert BUS.counters().get("lane.resident.pin_overflow", 0) >= 1
    # Explicit eviction (the certificate-failure purge) overrides pins.
    assert lane.evict(g1.digest())
    assert g1.digest() not in lane.resident_digests()


def test_pins_rekey_along_chain_with_refresh():
    lane = ShardedLane()
    g = _oversize_graph(3)
    lane.solve(g)
    lane.pin(g.digest())
    edges = _edges(g)
    edges[10][2] += 1  # small rank shift: the donated-scatter regime
    g2 = Graph.from_edges(g.num_nodes, edges)
    assert lane.refresh_resident(g.digest(), g2)
    assert lane.pin_count(g.digest()) == 0
    assert lane.pin_count(g2.digest()) == 1
    assert g2.digest() in lane.resident_digests()


def test_ensure_resident_stages_without_solving():
    lane = ShardedLane()
    g = _oversize_graph(4)
    assert lane.ensure_resident(g, pin=True)
    c = BUS.counters()
    assert c.get("lane.resident.restored") == 1
    assert lane.pin_count(g.digest()) == 1
    assert not BUS.counters().get("lane.resident.miss")
    # No solve ran; the staged entry makes the NEXT solve dispatch-only
    # (resident.hit + reshard.skipped, no second lane.stage span).
    spans = _stage_spans()
    ids, _, _ = lane.solve(g)
    assert _stage_spans() == spans
    assert BUS.counters().get("lane.reshard.skipped") == 1
    ref = minimum_spanning_forest(g, backend="device")
    assert np.array_equal(ids, ref.edge_ids)
    # Idempotent: a second ensure is pin-only.
    assert lane.ensure_resident(g)
    assert BUS.counters().get("lane.resident.restored") == 1


# ----------------------------------------------------------------------
# Satellite regression: eviction pressure DURING apply_window
# ----------------------------------------------------------------------
def test_stream_head_survives_eviction_pressure_mid_window(
    tmp_path, monkeypatch
):
    lane = ShardedLane(capacity=1)
    g = _oversize_graph(5)
    result = lane.solve_result(g)
    mgr = StreamManager(root=str(tmp_path), lane=lane)
    session = mgr.subscribe(digest=g.digest(), result=result)
    assert session.sharded
    assert lane.pin_count(session.head) == 1

    rng = np.random.default_rng(0)
    real_apply = session.mst.apply_window

    def pressured_apply(updates):
        # Unrelated oversize traffic lands while the window is mid-apply:
        # at capacity 1 this is maximal eviction pressure on the pinned
        # head — the race the pin exists to close.
        lane.solve(_oversize_graph(50))
        lane.solve(_oversize_graph(51))
        return real_apply(updates)

    monkeypatch.setattr(session.mst, "apply_window", pressured_apply)
    out = mgr.publish(session.id, session.head, _window(rng, g))
    # The commit migrated the still-resident pinned entry to the new
    # head: residency and pin survived the pressure.
    assert out["digest"] in lane.resident_digests()
    assert lane.pin_count(out["digest"]) == 1
    assert lane.pin_count(out["prev_digest"]) == 0
    assert BUS.counters().get("stream.lane.migrated") == 1


# ----------------------------------------------------------------------
# Mesh maintenance on the publish path
# ----------------------------------------------------------------------
def test_sharded_publish_scatters_into_resident_slots(tmp_path):
    lane = ShardedLane()
    g = _oversize_graph(6)
    result = lane.solve_result(g)
    mgr = StreamManager(root=str(tmp_path), snapshot_every=2, lane=lane)
    session = mgr.subscribe(digest=g.digest(), result=result)
    rng = np.random.default_rng(1)
    head = session.head
    for _ in range(3):
        head = mgr.publish(session.id, head, _window(rng, g))["digest"]
    c = BUS.counters()
    # Every window migrated residency without a solve — by donated
    # scatter when the rank delta is narrow, full restage past
    # max_update_frac (a wide-shifting insert); never dropped.
    assert c.get("stream.lane.migrated") == 3
    assert (
        c.get("lane.update.donated", 0) + c.get("lane.restage", 0) == 3
    )
    assert c.get("lane.update.donated", 0) >= 1
    assert not c.get("lane.update.dropped")
    assert head in lane.resident_digests()
    assert lane.pin_count(head) == 1
    # A solve of the head is dispatch-only on the maintained residency,
    # and edge-exact against a fresh oracle solve.
    spans = _stage_spans()
    ids, _, _ = lane.solve(session.mst.result().graph)
    assert _stage_spans() == spans
    oracle = minimum_spanning_forest(
        session.mst.result().graph, backend="device"
    )
    assert np.array_equal(ids, oracle.edge_ids)


def test_resolve_escape_hatch_migrates_residency_first(tmp_path):
    lane = ShardedLane()
    g = _oversize_graph(7)
    result = lane.solve_result(g)
    mgr = StreamManager(
        root=str(tmp_path), lane=lane,
        solver=lambda graph: lane.solve_result(graph),
    )
    session = mgr.subscribe(digest=g.digest(), result=result)
    rng = np.random.default_rng(2)
    # Past the window threshold the window degrades to a full re-solve —
    # the escape hatch under test (lowered so a small window trips it).
    session.mst._window_threshold = 4
    out = mgr.publish(session.id, session.head, _window(rng, g, size=12))
    assert out["mode"] == "resolve"
    # pre_resolve migrated the head's residency onto the resolve graph
    # BEFORE the solver ran: the mesh solve found it resident (no cold
    # miss) and the pin followed the chain.
    assert BUS.counters().get("lane.reshard.skipped") == 1
    # Only the seed solve missed; the mid-publish resolve did not.
    assert BUS.counters().get("lane.resident.miss", 0) == 1
    assert out["digest"] in lane.resident_digests()
    assert lane.pin_count(out["digest"]) == 1


def test_small_stream_stays_unsharded_with_lane_attached(tmp_path):
    lane = ShardedLane()
    g = gnm_random_graph(60, 180, seed=8)
    result = minimum_spanning_forest(g)
    mgr = StreamManager(root=str(tmp_path), lane=lane)
    session = mgr.subscribe(digest=g.digest(), result=result)
    assert not session.sharded
    assert lane.pin_count(session.head) == 0
    rng = np.random.default_rng(3)
    out = mgr.publish(session.id, session.head, _window(rng, g))
    # No residency was created for a lane-engine-sized stream.
    assert out["digest"] not in lane.resident_digests()
    assert not BUS.counters().get("stream.lane.migrated")


def test_drop_and_manager_eviction_release_pins(tmp_path):
    lane = ShardedLane()
    graphs = [_oversize_graph(s) for s in (20, 21)]
    mgr = StreamManager(root=str(tmp_path), lane=lane, max_streams=1)
    s0 = mgr.subscribe(
        digest=graphs[0].digest(), result=lane.solve_result(graphs[0])
    )
    assert lane.pin_count(s0.head) == 1
    # Registering a second stream LRU-evicts the first -> its pin drops.
    mgr.subscribe(
        digest=graphs[1].digest(), result=lane.solve_result(graphs[1])
    )
    assert lane.pin_count(graphs[0].digest()) == 0
    assert lane.pin_count(graphs[1].digest()) == 1


# ----------------------------------------------------------------------
# Crash-safe residency: replay re-stages + re-scatters, never solves
# ----------------------------------------------------------------------
def test_sharded_replay_rebuilds_residency_without_solving(
    tmp_path, monkeypatch
):
    root = str(tmp_path)
    lane = ShardedLane()
    g = _oversize_graph(9)
    result = lane.solve_result(g)

    def solver_bomb(graph):
        raise AssertionError("sharded replay must never fresh-solve")

    mgr = StreamManager(
        root=root, snapshot_every=2, lane=lane, solver=solver_bomb
    )
    session = mgr.subscribe(digest=g.digest(), result=result)
    rng = np.random.default_rng(4)
    head = session.head
    seen = []
    for _ in range(5):
        out = mgr.publish(session.id, head, _window(rng, g))
        head = out["digest"]
        seen.append(out["seq"])
    stream_id = session.id

    # --- the worker dies; an inheritor process starts fresh -----------
    import distributed_ghs_implementation_tpu.serve.dynamic as dyn_mod

    def bomb(*a, **k):
        raise AssertionError("replay must never solve")

    monkeypatch.setattr(dyn_mod, "minimum_spanning_forest", bomb)
    BUS.clear()
    lane2 = ShardedLane()
    fresh = StreamManager(
        root=root, snapshot_every=2, lane=lane2, solver=solver_bomb
    )
    recovered = fresh.recover(stream_id)
    assert recovered is not None
    assert recovered.head == head
    assert recovered.seq == 5
    assert recovered.sharded
    c = BUS.counters()
    # Residency rebuilt: snapshot state re-staged once (a device_put),
    # each replayed window re-scattered through the donated path, the
    # digest re-keyed along the chain — and nothing solved.
    assert c.get("stream.replay.residency_restored") == 1
    assert c.get("lane.resident.restored") == 1
    assert not c.get("stream.replay.fresh_solve")
    assert not c.get("stream.replay.diverged")
    assert head in lane2.resident_digests()
    assert lane2.pin_count(head) == 1
    # Notification ring regenerated gap/dup-free.
    from distributed_ghs_implementation_tpu.stream.session import (
        poll_gap_check,
    )

    poll = fresh.poll(stream_id, after_seq=0)
    seqs = [n["seq"] for n in poll["notifications"]]
    assert poll_gap_check(seqs, poll["seq"]) == {"gaps": 0, "dups": 0}
    # The rebuilt head is edge-exact against a fresh oracle solve (the
    # API entry point is not the bombed reference).
    rebuilt = recovered.mst.result()
    oracle = minimum_spanning_forest(rebuilt.graph, backend="device")
    assert np.array_equal(np.sort(rebuilt.edge_ids), np.sort(oracle.edge_ids))
    # And serving the head from the rebuilt residency is dispatch-only.
    spans = _stage_spans()
    ids, _, _ = lane2.solve(rebuilt.graph)
    assert _stage_spans() == spans
    assert np.array_equal(ids, oracle.edge_ids)


def test_snapshot_carries_sharded_marker(tmp_path):
    from distributed_ghs_implementation_tpu.stream.log import UpdateLog

    root = str(tmp_path)
    lane = ShardedLane()
    g = _oversize_graph(12)
    mgr = StreamManager(root=root, lane=lane)
    session = mgr.subscribe(digest=g.digest(), result=lane.solve_result(g))
    state, _notes = UpdateLog(root, session.id).load_snapshot()
    assert state is not None and state["sharded"] is True

    small = gnm_random_graph(60, 180, seed=13)
    s2 = mgr.subscribe(
        digest=small.digest(), result=minimum_spanning_forest(small)
    )
    state2, _ = UpdateLog(root, s2.id).load_snapshot()
    assert state2 is not None and state2["sharded"] is False


# ----------------------------------------------------------------------
# Satellite: sharded commits ride the verify policy
# ----------------------------------------------------------------------
def test_sharded_commits_audited_under_policy(tmp_path):
    from distributed_ghs_implementation_tpu.verify.policy import (
        ResultVerifier,
        VerifyPolicy,
    )

    lane = ShardedLane()
    verifier = ResultVerifier(VerifyPolicy.parse("full"))
    g = _oversize_graph(14)
    mgr = StreamManager(
        root=str(tmp_path), snapshot_every=2, lane=lane, verifier=verifier
    )
    session = mgr.subscribe(digest=g.digest(), result=lane.solve_result(g))
    rng = np.random.default_rng(5)
    head = session.head
    for _ in range(2):
        head = mgr.publish(session.id, head, _window(rng, g))["digest"]
    assert verifier.auditor.flush(timeout_s=30.0)
    c = BUS.counters()
    assert c.get("verify.audit.queued", 0) >= 2
    assert c.get("verify.audit.ok", 0) >= 2
    assert not c.get("verify.audit.failed")

    # The replay-rebuilt head audits too — heads that never pass through
    # the one-shot publish/solve response path are still verified.
    BUS.clear()
    lane2 = ShardedLane()
    fresh = StreamManager(
        root=str(tmp_path), snapshot_every=2, lane=lane2, verifier=verifier
    )
    assert fresh.recover(session.id) is not None
    assert verifier.auditor.flush(timeout_s=30.0)
    c = BUS.counters()
    assert c.get("verify.audit.queued", 0) >= 1
    assert c.get("verify.audit.ok", 0) >= 1


def test_off_policy_skips_sharded_audit(tmp_path):
    from distributed_ghs_implementation_tpu.verify.policy import (
        ResultVerifier,
        VerifyPolicy,
    )

    lane = ShardedLane()
    verifier = ResultVerifier(VerifyPolicy.parse("off"))
    g = _oversize_graph(15)
    mgr = StreamManager(root=str(tmp_path), lane=lane, verifier=verifier)
    session = mgr.subscribe(digest=g.digest(), result=lane.solve_result(g))
    rng = np.random.default_rng(6)
    mgr.publish(session.id, session.head, _window(rng, g))
    assert not BUS.counters().get("verify.audit.queued")


# ----------------------------------------------------------------------
# Service-level: the fused path through the serve ops
# ----------------------------------------------------------------------
def test_service_sharded_stream_flow(tmp_path):
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    svc = MSTService(
        sharded_lane=True,
        stream_dir=str(tmp_path / "streams"),
        stream_snapshot_every=2,
        verify="sample",
    )
    g = _oversize_graph(16)
    edges = [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)]
    solved = svc.handle(
        {"op": "solve", "num_nodes": g.num_nodes, "edges": edges}
    )
    assert solved["ok"]
    sub = svc.handle({"op": "subscribe", "digest": solved["digest"]})
    assert sub["ok"]
    assert BUS.counters().get("serve.route.sharded_lane", 0) >= 1
    session = svc.streams._streams[sub["stream"]]
    assert session.sharded
    assert svc.sharded_lane.pin_count(sub["digest"]) == 1
    rng = np.random.default_rng(7)
    pub = svc.handle({
        "op": "publish", "stream": sub["stream"], "digest": sub["digest"],
        "updates": _window(rng, g),
    })
    assert pub["ok"]
    assert pub["digest"] in svc.sharded_lane.resident_digests()
    assert svc.sharded_lane.pin_count(pub["digest"]) == 1
    assert svc.streams.stats()["sharded"] == 1
