"""Elastic fleet control loop: obs-driven autoscaling with warm handoff.

``FleetConfig.workers`` fixes pool size at boot — the paper's own shape
(one MPI rank per graph node, forever) inherited by every fleet round so
far — while real traffic is diurnal and bursty. This module closes the
loop: an :class:`Autoscaler` thread watches the SAME obs bus the SLO gate
reads and drives the worker pool between ``min_workers`` and
``max_workers`` through the router's elastic primitives
(:meth:`fleet.router.FleetRouter.add_worker` /
:meth:`~fleet.router.FleetRouter.retire_worker`).

**Signals** (read per control tick, never sampled across the whole run —
hysteresis needs recency):

* *Queue-wait breach* — the per-class request durations appended to the
  bus since the last tick (``obs.slo.window_class_waits`` joins the
  ``fleet.request`` spans exactly like the SLO report does); a class
  whose tick-window p99 exceeds its budget
  (:meth:`ElasticPolicy.budget_for`) is a breach.
* *Queue depth* — ``router.queue_depths()``; any worker at or past
  ``queue_high`` in-flight requests is a breach even when latency has not
  yet degraded (depth leads latency).
* *Sustained idle* — zero new requests AND zero queued work for
  ``idle_ticks`` consecutive ticks.

**Decisions** are deterministic given the signals: scale **by one**, with
a ``cooldown_s`` window between any two scale operations — the hysteresis
that makes the elastic drill's scale-event counts exactly reproducible.
Scale-up is warm handoff by construction (``add_worker`` refuses ring
entry until the joiner's ``warmed`` hello is confirmed — the joiner
pre-seeded from the shared disk store, attached the persistent XLA
compile cache, and ran its warmup ladder first); scale-down picks the
lowest-affinity victim and drains it (``retire_worker``: off the ring
first, in-flight work flushes, pinned sessions migrate by disk-store
reads / stream-WAL replay on the inheritors, exit 0).

Telemetry: the router primitives count ``fleet.scale.up`` /
``fleet.scale.down`` and record ``fleet.join.warm_s``; this loop adds
``fleet.scale.decision`` instants (action + reason) and pushes its latest
decision to the router so the ``stats`` op can answer "why is the fleet
this size". ``docs/FLEET.md`` "Elasticity" covers the knobs;
``tools/load_drill.py --ramp --elastic`` is the drill and
``gate-fleet-elastic-v1`` the CI gate.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Mapping, Optional

from distributed_ghs_implementation_tpu.obs.events import BUS, quantile
from distributed_ghs_implementation_tpu.obs.slo import window_class_waits

#: Default per-class wait budget when :attr:`ElasticPolicy.class_budgets_s`
#: has no entry for a class (seconds of end-to-end request latency).
DEFAULT_WAIT_BUDGET_S = 0.25


def parse_class_budgets(spec: str) -> Dict[str, float]:
    """``"interactive=0.05,bulk=2"`` -> ``{"interactive": 0.05, ...}``
    (the ``--fleet-elastic-budgets`` CLI surface)."""
    out: Dict[str, float] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        cls, _, value = entry.partition("=")
        if not value:
            raise ValueError(
                f"bad class budget {entry!r}; expected CLASS=SECONDS"
            )
        out[cls.strip()] = float(value)
    return out


@dataclasses.dataclass(frozen=True)
class ElasticPolicy:
    """The autoscaler's knobs. Everything is deterministic: same signal
    sequence, same decisions (the reproducibility the drill gates on).

    ``wait_budget_s`` is the default per-class latency budget;
    ``class_budgets_s`` overrides it per class (the load drill sets an
    aggressive budget so a ramp deterministically provokes scale-up).
    ``cooldown_s`` runs from the *completion* of a scale operation — a
    warm join that takes 20s does not bank 20s of cooldown credit.
    """

    min_workers: int = 1
    max_workers: int = 4
    tick_s: float = 0.25
    cooldown_s: float = 2.0
    wait_budget_s: float = DEFAULT_WAIT_BUDGET_S
    class_budgets_s: Mapping[str, float] = dataclasses.field(
        default_factory=dict
    )
    queue_high: int = 8
    idle_ticks: int = 10
    join_timeout_s: Optional[float] = None  # None -> router ready timeout

    def __post_init__(self):
        if self.min_workers < 1:
            raise ValueError(f"min_workers must be >= 1, got {self.min_workers}")
        if self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers ({self.max_workers}) < min_workers "
                f"({self.min_workers})"
            )
        if self.tick_s <= 0:
            raise ValueError(f"tick_s must be > 0, got {self.tick_s}")
        if self.idle_ticks < 1:
            raise ValueError(f"idle_ticks must be >= 1, got {self.idle_ticks}")

    def budget_for(self, cls: str) -> float:
        return float(self.class_budgets_s.get(cls, self.wait_budget_s))


class Autoscaler:
    """The control loop. Own thread; :meth:`step` is also callable
    directly (tests drive ticks without wall-clock waits).

    Scale operations run INSIDE the loop thread and block it — a warm
    join is seconds-to-tens-of-seconds of spawn + warmup, and blocking is
    exactly the scale-by-one serialization the hysteresis wants: there is
    never more than one join or retire in flight.
    """

    def __init__(self, router, policy: Optional[ElasticPolicy] = None):
        self.router = router
        self.policy = policy or ElasticPolicy()
        if getattr(router.config, "remote_workers", ()):
            raise ValueError(
                "autoscaling needs spawnable workers; a --fleet-workers "
                "remote topology is fixed by its endpoint list"
            )
        self._mark = BUS.mark()
        self._requests_seen = float(
            BUS.counters().get("fleet.requests", 0)
        )
        self._idle_streak = 0
        self._last_scale_done = float("-inf")
        # A journal-restored router hands back its last (wall-clock
        # stamped) scale decision: the cooldown spans the crash, so a
        # restarting router cannot double-scale a fleet that had just
        # scaled (docs/FLEET.md "Router survivability").
        last = getattr(router, "last_scale_decision", None)
        if last and last.get("at") and last.get("action") in ("up", "down"):
            age = max(0.0, time.time() - float(last["at"]))
            if age < self.policy.cooldown_s:
                self._last_scale_done = time.monotonic() - age
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        #: Bounded decision log (newest last) — drills read it for the
        #: pool-size trajectory; the router keeps only the latest.
        self.decisions: List[dict] = []

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def _loop(self) -> None:
        while not self._closed:
            time.sleep(self.policy.tick_s)
            if self._closed:
                return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                # A failed join/retire is an incident, not a crash: note
                # it, keep watching (the next breach retries). The
                # fleet.scale.failed counter is owned by the router's
                # failure sites — counting here too would double one
                # timed-out join.
                self._note({
                    "action": "failed",
                    "reason": f"{type(e).__name__}: {e}",
                    "pool": self._pool(),
                })

    # -- signals -------------------------------------------------------
    def _pool(self) -> int:
        return self.router.pool_size()

    def _signals(self) -> dict:
        """One tick's worth of evidence, read then consumed (the mark and
        counter baselines advance so the next tick sees only new events —
        a BUS.clear() between ticks just re-bases both)."""
        events = BUS.events_since(self._mark)
        self._mark = BUS.mark()
        waits = window_class_waits(events)
        total = float(BUS.counters().get("fleet.requests", 0))
        if total < self._requests_seen:  # the bus was cleared
            self._requests_seen = total
        new_requests = total - self._requests_seen
        self._requests_seen = total
        depths = self.router.queue_depths()
        breach = None
        for cls in sorted(waits):
            p99 = quantile(waits[cls], 0.99)
            budget = self.policy.budget_for(cls)
            if p99 > budget:
                breach = (
                    f"class '{cls}' wait p99 {p99:.3f}s over its "
                    f"{budget:.3f}s budget"
                )
                break
        if breach is None and depths:
            worst = max(depths, key=lambda wid: depths[wid])
            if depths[worst] >= self.policy.queue_high:
                breach = (
                    f"worker {worst} queue depth {depths[worst]} at the "
                    f"{self.policy.queue_high} watermark"
                )
        idle = new_requests == 0 and sum(depths.values()) == 0
        return {"breach": breach, "idle": idle,
                "new_requests": new_requests}

    # -- the decision --------------------------------------------------
    def step(self, now: Optional[float] = None) -> dict:
        """One control tick; returns the decision record."""
        now = time.monotonic() if now is None else now
        sig = self._signals()
        if sig["idle"]:
            self._idle_streak += 1
        else:
            self._idle_streak = 0
        pool = self._pool()
        policy = self.policy
        cooling = (now - self._last_scale_done) < policy.cooldown_s
        decision = {"action": "hold", "pool": pool, "reason": "steady"}
        if sig["breach"] is not None:
            if pool >= policy.max_workers:
                decision["reason"] = (
                    f"{sig['breach']} — already at max_workers "
                    f"({policy.max_workers})"
                )
                decision["constrained"] = "at_max"
            elif cooling:
                decision["reason"] = f"{sig['breach']} — in cooldown"
            else:
                joined = self.router.add_worker(
                    timeout_s=policy.join_timeout_s
                )
                self._last_scale_done = time.monotonic()
                decision = {
                    "action": "up",
                    "pool": self._pool(),
                    "worker": joined["worker"],
                    "warm_s": round(joined["warm_s"], 3),
                    "reason": sig["breach"],
                }
        elif (
            self._idle_streak >= policy.idle_ticks
            and pool > policy.min_workers
            and not cooling
        ):
            retired = self.router.retire_worker()
            self._last_scale_done = time.monotonic()
            self._idle_streak = 0
            decision = {
                "action": "down",
                "pool": self._pool(),
                "worker": retired["worker"],
                "sessions_moved": retired["sessions_moved"],
                "reason": (
                    f"idle for {policy.idle_ticks} ticks "
                    f"({policy.idle_ticks * policy.tick_s:.1f}s) above "
                    f"min_workers ({policy.min_workers})"
                ),
            }
        if decision["action"] != "hold":
            self._note(decision)
        elif decision.get("constrained"):
            # Breach with no legal move (at max_workers): the one hold an
            # operator must SEE — it answers "why won't the fleet grow" in
            # stats.pool.last_scale (docs/FLEET.md failure row). Note the
            # first of each streak, not every tick: a persistent breach
            # would otherwise flood the decision log. Cooldown holds stay
            # un-noted — they resolve themselves within cooldown_s.
            last = self.decisions[-1] if self.decisions else None
            if last is None or not last.get("constrained"):
                self._note(decision)
        return decision

    def _note(self, decision: dict) -> None:
        decision = dict(decision)
        self.decisions.append(decision)
        del self.decisions[:-64]
        self.router.note_scale_decision(decision)
        BUS.instant("fleet.scale.decision", cat="fleet", **decision)
