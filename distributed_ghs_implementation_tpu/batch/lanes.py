"""Lane stacking: K same-bucket graphs through one compiled solve.

``models/boruvka.py`` already pads every graph to power-of-two ``(n_pad,
m_pad)`` buckets so same-bucket graphs share a compiled kernel — but the
sharing is only ever *serial*: one dispatch per graph, and on small graphs
the chip idles between dispatches. This module stacks K same-bucket graphs
into lanes and solves all of them in ONE dispatch, two ways:

* ``"fused"`` (default) — block-diagonal: lane ``i``'s vertices shift by
  ``i * n_pad`` and its ranks by ``i * m_pad``, turning the batch into one
  disjoint-union graph the existing flat kernel (``_solve_from_iota``)
  solves unchanged. Fragments never cross lanes, and the rank shift is
  order-preserving within a lane, so the MSF of the union is exactly the
  per-lane MSFs. Measured ~4x graphs/sec over serial dispatch on
  128-vertex graphs (CPU; the win is amortized per-op/dispatch overhead).
* ``"vmap"`` — ``jax.vmap`` of the same iota solve over a leading lane
  axis. The batched ``while_loop`` runs every lane to the slowest lane's
  level count with per-carry selects, which on small graphs eats the
  dispatch savings — kept as the straightforward formulation and for
  accelerators where the selects are free, not as the default.

Compiles are bounded by construction: the solver cache keys on
``(n_pad, m_pad, lanes, mode)``, so traffic drawn from B shape buckets
costs at most B compilations no matter how many batches run
(``batch.compile.hit`` / ``batch.compile.miss`` count the cache traffic).

Every cache entry is an **ahead-of-time compiled executable**
(``jax.jit(...).lower().compile()`` against the bucket's exact input
shapes), so a bucket can be compiled before any request needs it —
``batch/warmup.py`` drives exactly that, and :func:`precompile_bucket`
counts its compiles as ``compile.warmup`` instead of ``compile.miss`` so
cold vs warm traffic is distinguishable in traces (docs/OBSERVABILITY.md,
``compile.*`` taxonomy). On accelerators the fused path donates its input
buffers (they are consumed by the solve); on CPU donation is unsupported
and skipped.

Stacking and execution are separable: :func:`stack_lanes` does the pure
host work (padding, shifting, array assembly) and returns a
:class:`StackedBatch`; :func:`execute_stacked` runs the device dispatch
and unpacks per-lane results. ``batch/engine.py`` uses the split to form
batch *k+1* on a background thread while batch *k* executes.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.models.boruvka import (
    _next_pow2,
    _solve_from_iota,
)
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.ops import pallas_kernels as _pk

_INT32_MAX = np.iinfo(np.int32).max

BucketKey = Tuple[int, int]  # (n_pad, m_pad)
SolverKey = Tuple[int, int, int, str]  # (n_pad, m_pad, lanes, mode)
# The cache key internally carries a fifth dimension — the level-kernel
# variant ("xla" | "pallas", docs/KERNELS.md) — so both variants of a
# bucket can be warm at once. The public SolverKey surface (records,
# replay files, compiled_bucket_keys) stays 4-wide: which kernel a process
# runs is a property of the process (backend probe, GHS_KERNEL, serve
# --kernel), not of the recorded traffic.
_CacheKey = Tuple[int, int, int, str, str]


def bucket_of(num_nodes: int, num_edges: int) -> BucketKey:
    """The compiled-shape bucket a ``(nodes, edges)`` workload pads into.

    THE one encoding of the bucketing rule (``prepare_device_arrays``'s
    padding: vertices to the next power of two, undirected ranks to the
    next power of two — edge slots are always ``2 * m_pad``); warmup specs
    and request-time keys both route through it, so a declared bucket is a
    hit bucket by construction. Empty dimensions bucket at 1.
    """
    return (_next_pow2(max(1, num_nodes)), _next_pow2(max(1, num_edges)))


def bucket_key(graph: Graph) -> BucketKey:
    """:func:`bucket_of` for a built ``Graph`` — two graphs with equal
    keys stack into interchangeable lanes."""
    return bucket_of(graph.num_nodes, graph.num_edges)


# ----------------------------------------------------------------------
# Compile cache: (n_pad, m_pad, lanes, mode) -> AOT-compiled executable
#
# The lock guards only the dict lookups/inserts; compiles run OUTSIDE it
# (one to two seconds each) with per-key pending events, so a warm
# bucket's cache hit never stalls behind an unrelated bucket's cold
# compile — and two threads racing the same cold bucket still compile it
# exactly once.
# ----------------------------------------------------------------------
_SOLVER_CACHE: Dict[_CacheKey, object] = {}
_PENDING_COMPILES: Dict[_CacheKey, threading.Event] = {}
_CACHE_LOCK = threading.Lock()


def lane_compile_stats() -> dict:
    """Counters mirror onto the bus; this is the direct view for drills."""
    return {
        "entries": len(_SOLVER_CACHE),
        "keys": sorted(_SOLVER_CACHE),
    }


def compiled_bucket_keys() -> List[SolverKey]:
    """The solver keys compiled so far — the record warmup replay persists.

    Kernel variants collapse: a record replayed on a different backend (or
    under a different ``GHS_KERNEL``) warms the variant THAT process will
    actually serve with, which is the point of replay.
    """
    with _CACHE_LOCK:
        return sorted({k[:4] for k in _SOLVER_CACHE})


def clear_solver_cache() -> None:
    """Drop every compiled lane solver (tests simulate a process restart)."""
    with _CACHE_LOCK:
        _SOLVER_CACHE.clear()


def _lane_input_shapes(n_pad: int, m_pad: int, lanes: int, mode: str):
    """The exact input avals a bucket's solver compiles against."""
    e_pad = 2 * m_pad
    if mode == "fused":
        edge = jax.ShapeDtypeStruct((lanes * e_pad,), jnp.int32)
        rank = jax.ShapeDtypeStruct((lanes * m_pad,), jnp.int32)
    else:
        edge = jax.ShapeDtypeStruct((lanes, e_pad), jnp.int32)
        rank = jax.ShapeDtypeStruct((lanes, m_pad), jnp.int32)
    return edge, edge, edge, rank, rank


def _donate_inputs() -> bool:
    """Donate fused-path input buffers only where donation is implemented
    (accelerators); on CPU XLA ignores it with a warning per compile."""
    return jax.default_backend() in ("tpu", "gpu")


def _compile_bucket(n_pad: int, m_pad: int, lanes: int, mode: str, kernel: str):
    """AOT-compile one bucket's solver: trace+lower+compile now, so the
    executable is ready before (or instead of) the first request.
    ``kernel`` is the static level-kernel variant (docs/KERNELS.md)."""
    shapes = _lane_input_shapes(n_pad, m_pad, lanes, mode)
    if mode == "fused":
        fn = functools.partial(
            _solve_from_iota, num_nodes=lanes * n_pad, kernel=kernel
        )
        if _donate_inputs():
            fn = jax.jit(fn, donate_argnums=(0, 1, 2, 3, 4))
        else:
            fn = jax.jit(fn)
    elif mode == "vmap":
        fn = jax.jit(
            jax.vmap(
                functools.partial(
                    _solve_from_iota, num_nodes=n_pad, kernel=kernel
                )
            )
        )
    else:
        raise ValueError(f"unknown lane mode {mode!r}; expected fused|vmap")
    return fn.lower(*shapes).compile()


def _get_solver(
    n_pad: int, m_pad: int, lanes: int, mode: str, *,
    phase: str = "request", kernel: str | None = None,
):
    """The bucket's compiled executable, building it on first need.

    ``phase`` labels who paid for a compile: ``"request"`` (a live solve
    stalled on it — the cold-start spike warmup exists to remove) or
    ``"warmup"`` (precompiled ahead of traffic). Cache hits always count
    as ``compile.hit`` — a warmup-precompiled bucket is a *hit* at request
    time, never a fresh compile. ``kernel`` (resolved via
    ``pallas_kernels.kernel_choice`` when ``None``) is part of the cache
    key, and every compile event carries it — the ``compile.*`` taxonomy
    distinguishes kernel variants (``compile.kernel.pallas`` /
    ``compile.kernel.xla``). Resolution passes the solver bucket, so an
    installed TuningRecord's measured winner applies per bucket
    (docs/KERNELS.md "Autotuning").
    """
    kernel = _pk.kernel_choice(kernel, bucket=(n_pad, m_pad, lanes, mode))
    key = (n_pad, m_pad, lanes, mode, kernel)
    while True:
        with _CACHE_LOCK:
            fn = _SOLVER_CACHE.get(key)
            if fn is not None:
                BUS.count("batch.compile.hit")
                BUS.count("compile.hit")
                return fn
            pending = _PENDING_COMPILES.get(key)
            if pending is None:
                pending = _PENDING_COMPILES[key] = threading.Event()
                BUS.count("batch.compile.miss")
                BUS.count(f"compile.{'warmup' if phase == 'warmup' else 'miss'}")
                BUS.count(f"compile.kernel.{kernel}")
                break  # this thread leads the compile, outside the lock
        # Another thread is compiling this key: wait, then re-read the
        # cache (on the leader's failure the loop elects a new leader).
        pending.wait()
    try:
        t0 = time.perf_counter()
        with BUS.span(
            "compile.bucket", cat="compile",
            n_pad=n_pad, m_pad=m_pad, lanes=lanes, mode=mode, phase=phase,
            kernel=kernel,
        ):
            fn = _compile_bucket(n_pad, m_pad, lanes, mode, kernel)
        BUS.record("compile.time_s", time.perf_counter() - t0)
        with _CACHE_LOCK:
            _SOLVER_CACHE[key] = fn
        return fn
    finally:
        with _CACHE_LOCK:
            del _PENDING_COMPILES[key]
        pending.set()


def precompile_bucket(
    n_pad: int, m_pad: int, lanes: int, mode: str = "fused",
    kernel: str | None = None,
) -> bool:
    """Compile a bucket's lane solver ahead of serving (idempotent).

    Returns ``True`` if this call compiled, ``False`` if the bucket was
    already cached. The compile lands on the bus as ``compile.warmup``
    (plus ``batch.compile.miss`` — it *is* a lane-solver compilation, just
    not one a request waited on). Rejects geometries the request path
    itself rejects (int32 id-space overflow in ``stack_lanes``) — a
    warmup must never compile a solver no request can reach. ``kernel``
    (default: the process's resolved choice) picks the level-kernel
    variant to warm — warming and serving resolve identically, so a
    warmed bucket is a request-time hit under either variant.
    """
    if lanes < 1:
        raise ValueError(f"lanes must be >= 1, got {lanes}")
    if lanes * n_pad >= _INT32_MAX or lanes * m_pad >= _INT32_MAX:
        raise ValueError(
            f"bucket ({n_pad}, {m_pad}) x {lanes} lanes exceeds int32 id "
            "space; no request-path stack can ever use this solver"
        )
    kernel = _pk.kernel_choice(kernel, bucket=(n_pad, m_pad, lanes, mode))
    with _CACHE_LOCK:
        cached = (n_pad, m_pad, lanes, mode, kernel) in _SOLVER_CACHE
    if cached:
        return False
    _get_solver(n_pad, m_pad, lanes, mode, phase="warmup", kernel=kernel)
    return True


# ----------------------------------------------------------------------
# Stacking
# ----------------------------------------------------------------------
def _stack_fused(graphs: Sequence[Graph], n_pad: int, m_pad: int, lanes: int):
    """Block-diagonal layout: one flat disjoint-union graph.

    Pads are kept inert exactly as in the single-graph layout, just shifted
    into their lane's block: slot pads are lane-local self-edges, rank pads
    stay at the INT32_MAX sentinel (NOT shifted — shifting would overflow
    and, worse, make a pad comparable), endpoint pads are the lane's vertex
    0 (never chosen). Unfilled lanes are all-pad: zero real edges, n_pad
    isolated vertices that cost one union-find no-op per level.
    """
    e_pad = 2 * m_pad
    src = np.empty(lanes * e_pad, np.int32)
    dst = np.empty(lanes * e_pad, np.int32)
    rank = np.full(lanes * e_pad, _INT32_MAX, np.int32)
    ra = np.empty(lanes * m_pad, np.int32)
    rb = np.empty(lanes * m_pad, np.int32)
    for i in range(lanes):
        voff = i * n_pad
        es, ee = i * e_pad, (i + 1) * e_pad
        rs, re = i * m_pad, (i + 1) * m_pad
        if i < len(graphs):
            s, d, r, a, b = graphs[i].rank_arrays(
                pad_edges_to=e_pad, pad_ranks_to=m_pad
            )
            src[es:ee] = s + voff
            dst[es:ee] = d + voff
            rank[es:ee] = np.where(r == _INT32_MAX, _INT32_MAX, r + i * m_pad)
            ra[rs:re] = a + voff
            rb[rs:re] = b + voff
        else:
            src[es:ee] = voff
            dst[es:ee] = voff
            ra[rs:re] = voff
            rb[rs:re] = voff
    return src, dst, rank, ra, rb


def _stack_vmap(graphs: Sequence[Graph], n_pad: int, m_pad: int, lanes: int):
    """Leading-lane-axis layout ``(lanes, ...)`` for the vmapped solver."""
    e_pad = 2 * m_pad
    src = np.zeros((lanes, e_pad), np.int32)
    dst = np.zeros((lanes, e_pad), np.int32)
    rank = np.full((lanes, e_pad), _INT32_MAX, np.int32)
    ra = np.zeros((lanes, m_pad), np.int32)
    rb = np.zeros((lanes, m_pad), np.int32)
    for i, g in enumerate(graphs):
        s, d, r, a, b = g.rank_arrays(pad_edges_to=e_pad, pad_ranks_to=m_pad)
        src[i], dst[i], rank[i], ra[i], rb[i] = s, d, r, a, b
    return src, dst, rank, ra, rb


@dataclasses.dataclass(frozen=True)
class StackedBatch:
    """One formed batch's host-side arrays, ready to dispatch.

    The stack is immutable and re-dispatchable: the engine's retry loop
    re-executes the same :class:`StackedBatch` without re-stacking (the
    arrays are host copies — donation only consumes the per-call device
    buffers).
    """

    graphs: Tuple[Graph, ...]
    n_pad: int
    m_pad: int
    lanes: int
    mode: str
    arrays: tuple


def stack_lanes(
    graphs: Sequence[Graph],
    *,
    lanes: int | None = None,
    mode: str = "fused",
) -> StackedBatch:
    """The pure host half of a lane solve: validate and stack the arrays.

    Safe to run on a background thread while another batch executes — it
    touches no device state and no shared caches.
    """
    if not graphs:
        raise ValueError("cannot stack an empty batch")
    lanes = len(graphs) if lanes is None else int(lanes)
    if lanes < len(graphs):
        raise ValueError(f"lanes={lanes} < {len(graphs)} graphs")
    n_pad, m_pad = bucket_key(graphs[0])
    for g in graphs[1:]:
        if bucket_key(g) != (n_pad, m_pad):
            raise ValueError(
                f"mixed buckets in one lane stack: {bucket_key(g)} vs "
                f"{(n_pad, m_pad)} (the policy must group by bucket)"
            )
    if lanes * n_pad >= _INT32_MAX or lanes * m_pad >= _INT32_MAX:
        raise ValueError(
            f"bucket ({n_pad}, {m_pad}) x {lanes} lanes exceeds int32 id "
            "space; the policy should bypass graphs this large"
        )
    if mode == "fused":
        arrays = _stack_fused(graphs, n_pad, m_pad, lanes)
    elif mode == "vmap":
        arrays = _stack_vmap(graphs, n_pad, m_pad, lanes)
    else:
        raise ValueError(f"unknown lane mode {mode!r}; expected fused|vmap")
    return StackedBatch(
        graphs=tuple(graphs), n_pad=n_pad, m_pad=m_pad,
        lanes=lanes, mode=mode, arrays=arrays,
    )


def execute_stacked(
    stacked: StackedBatch, *, kernel: str | None = None
) -> List[Tuple[np.ndarray, np.ndarray, int]]:
    """The device half: one dispatch of a stacked batch + per-lane unpack.

    ``kernel`` picks the level-kernel variant (``None`` = process default).
    A Pallas solver failing at compile (a Mosaic lowering regression) or
    dispatch trips the sticky process-wide fallback
    (``pallas_kernels.disable_pallas``) and the SAME stack re-dispatches
    on the XLA variant — the stack's host arrays are intact (donation
    only consumes per-call device buffers), so the retry is exact and
    the request never sees the failure.
    """
    kernel = _pk.kernel_choice(
        kernel,
        bucket=(stacked.n_pad, stacked.m_pad, stacked.lanes, stacked.mode),
    )
    try:
        solver = _get_solver(
            stacked.n_pad, stacked.m_pad, stacked.lanes, stacked.mode,
            kernel=kernel,
        )
        # The device_get stays INSIDE the try: dispatch is async, so a
        # compiled Pallas program that faults at execution raises at the
        # first host sync, not at the call above.
        mst_ranks, fragment, levels = jax.device_get(solver(*stacked.arrays))
    except ValueError:
        raise  # caller/geometry errors are never kernel faults
    except Exception as ex:  # noqa: BLE001 — speculative-kernel fallback
        if kernel != "pallas":
            raise
        _pk.disable_pallas(f"lane dispatch: {type(ex).__name__}: {ex}")
        solver = _get_solver(
            stacked.n_pad, stacked.m_pad, stacked.lanes, stacked.mode,
            kernel="xla",
        )
        mst_ranks, fragment, levels = jax.device_get(solver(*stacked.arrays))

    graphs, lanes, n_pad, m_pad = (
        stacked.graphs, stacked.lanes, stacked.n_pad, stacked.m_pad
    )
    out: List[Tuple[np.ndarray, np.ndarray, int]] = []
    if stacked.mode == "fused":
        lane_ranks = np.asarray(mst_ranks).reshape(lanes, m_pad)
        lane_frag = np.asarray(fragment).reshape(lanes, n_pad)
        for i, g in enumerate(graphs):
            ranks = np.nonzero(lane_ranks[i])[0]
            edge_ids = np.sort(g.edge_id_of_rank(ranks))
            frag = lane_frag[i, : g.num_nodes] - i * n_pad
            out.append((edge_ids, frag.astype(np.int32), int(levels)))
    else:
        for i, g in enumerate(graphs):
            ranks = np.nonzero(np.asarray(mst_ranks[i]))[0]
            edge_ids = np.sort(g.edge_id_of_rank(ranks))
            frag = np.asarray(fragment[i])[: g.num_nodes]
            out.append((edge_ids, frag, int(np.asarray(levels)[i])))
    return out


# ----------------------------------------------------------------------
# The batch solve
# ----------------------------------------------------------------------
def solve_lanes(
    graphs: Sequence[Graph],
    *,
    lanes: int | None = None,
    mode: str = "fused",
    kernel: str | None = None,
) -> List[Tuple[np.ndarray, np.ndarray, int]]:
    """Solve K same-bucket graphs in one dispatch.

    Returns one ``(edge_ids, fragment, levels)`` per input graph, in order
    — the exact contract of ``models.boruvka.solve_graph`` (edge ids index
    ``graph.u/v/w``, sorted; fragment trimmed to ``num_nodes``). ``lanes``
    (default ``len(graphs)``) fixes the stacked lane count; extra lanes are
    inert padding, so a policy can pin ``lanes = max_lanes`` and keep ONE
    compiled shape per bucket regardless of fill. In ``"fused"`` mode
    ``levels`` is the shared batch level count (the slowest lane's); in
    ``"vmap"`` mode it is per-lane. ``kernel`` picks the level-kernel
    variant (``None`` = process default; docs/KERNELS.md).
    """
    if not graphs:
        return []
    return execute_stacked(
        stack_lanes(graphs, lanes=lanes, mode=mode), kernel=kernel
    )
